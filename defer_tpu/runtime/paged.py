"""Paged KV cache: a shared block pool instead of per-slot max_len
lanes (the vLLM idea, TPU-shaped).

A contiguous continuous-batching cache (runtime/decode_server.py)
reserves `max_batch x max_len` K/V rows even when every request is
short — decode HBM is cache-bound, so reserved-but-unused rows are the
serving memory ceiling. Here the cache is a pool of fixed-size BLOCKS
([L, num_blocks, H_kv, block_size, Dh]); each slot holds a BLOCK TABLE
of pool indices, and memory scales with the sum of actual request
budgets, not slots x max_len.

Static-shape design (everything jits once):

  * the decode step runs one of THREE attention paths, selected by
    `attention=` (default "gathered"):

      - "gathered": gather each slot's blocks into the standard
        contiguous [B, H_kv, S, Dh] view (one gather per layer) and
        run the EXACT SAME block math as the flat decoder
        (GptDecoder._block) — numerical parity is inherited, not
        re-proven (bit-exact vs the flat server at tested scales) —
        then scatter the single new K/V row back to its block. Per
        tick it reads O(B * max_blocks * block_size) rows regardless
        of request depth: the reference path, and the baseline the
        others are measured against.
      - "blockwise": attend THROUGH the block table — scatter the new
        K/V row into the pool first, then fold pool blocks into an
        online-softmax carry (running max / denominator,
        flash-attention recurrence) one table column at a time,
        stopping at the deepest LIVE block across the batch
        (`lax.fori_loop` with a traced bound). Pure XLA, runs
        everywhere CPU tier-1 runs. Reads O(B * live_blocks *
        block_size) rows per tick. Parity contract: TIE-TOLERANT —
        the projections/FFN are `_block`'s own code (bit-identical),
        but the softmax reduction order differs, so logits agree only
        to float tolerance; at tested scales the emitted tokens are
        identical (tests pin that), while near-ties could in
        principle resolve differently.
      - "pallas": the block-table-indexed flash-decode kernel
        (ops/pallas_attention.py::paged_flash_decode) — the table
        indirection happens in the kernel's index maps, dead columns
        are clamped so each slot DMAs only ITS OWN live blocks:
        per-slot bandwidth O(own live blocks), the full
        paged-attention win. Runs natively on TPU (Mosaic), and
        through the pallas interpreter anywhere else (slow; CI
        exercises it under the `slow` marker). Same tie-tolerant
        contract as "blockwise".

    The win is observable: `defer_kv_rows_read_total` vs
    `defer_kv_rows_gathered_baseline_total` (obs/serving.py) count
    per-tick rows read vs the gathered baseline, and
    scripts/bench_paged.py benches all modes side by side;
  * block tables are a fixed [B, max_blocks] shape; unallocated
    entries point at the reserved TRASH block 0 (never allocated to a
    request), so out-of-budget writes land in scrap instead of another
    request's memory and garbage reads sit beyond the position mask —
    every attention path keeps this invariant and the
    scatter-new-row write unchanged;
  * allocation is host-side and exact: a request's block need is known
    at submit time (prompt + step budget, eos can only shorten it), so
    admission takes ceil(total/block_size) blocks from the free list
    and finishing returns them — when the pool is exhausted, requests
    simply wait (the pool, not the slot count, is the admission
    limit).

Prefill reuses the flat decoder's admission path (single-request
contiguous prefill), and the resulting rows are scattered into the
allocated blocks in one jitted op.
"""

from __future__ import annotations

import collections
import hashlib
import queue
import threading
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from defer_tpu.constrain import runtime as crt
from defer_tpu.models.gpt import (
    sample_token_batched,
    sample_token_batched_nosort,
)
from defer_tpu.models.quant import (
    dequantize_symmetric,
    quantize_symmetric,
)
from defer_tpu.obs.serving import ServerStats, ServingMetrics
from defer_tpu.ops.pallas_attention import _MASK_VALUE
from defer_tpu.runtime.batching import (
    accept_lengths,
    microbatch_groups,
    pp_schedule_occupancy,
    window_drain_order,
)
from defer_tpu.runtime.decode_server import DraftLanes, SlotSampler
from defer_tpu.runtime.schedule import PrefillSeat, plan_mixed_tick
from defer_tpu.runtime.stopping import matcher_or_none, normalize_stops


def _pool_arr(pool):
    """The array leaf carrying the pool geometry ([.., NB, Hkv, bs,
    Dh]): the int8 payload of a quantized {"q","s"} pool, or the fp
    pool itself."""
    return pool["q"] if isinstance(pool, dict) else pool


def _pool_gather(pool_l, idx, dtype):
    """Gather per-layer pool blocks at `idx` and widen to `dtype`.
    `pool_l` is [NB, Hkv, bs, Dh] — a plain fp array, or an int8
    {"q","s"} pair with [NB, Hkv] per-(block, head) scales
    (models/quant.py convention). The scale folds in AT THE GATHER,
    so every attend path downstream sees ordinary fp blocks and the
    attention math stays exactly the fp path's. idx may be [B] (one
    block per slot) or [B, MB] (a whole table): s broadcasts as
    s[..., None, None] against q's trailing (bs, Dh) in either case."""
    if isinstance(pool_l, dict):
        return dequantize_symmetric(
            pool_l["q"][idx], pool_l["s"][idx][..., None, None], dtype
        )
    return pool_l[idx].astype(dtype)


def _pool_write_rows(pool_l, dest, rowi, val):
    """Scatter one fresh K/V row per batch entry into a per-layer
    pool slice: dest [N] block ids, rowi [N] rows-in-block, val
    [N, Hkv, Dh]. For an fp pool this is exactly the historical
    `.at[dest, :, rowi, :].set(val)` single-row scatter.

    An int8 pool can't write a row in place — symmetric int8 keeps
    ONE scale per (block, head), so landing a row means re-deriving
    the block scale: gather the touched blocks, dequantize, insert
    the new row, ZERO the stale rows past it (rows > rowi are a
    previous tenant's garbage; folding them into amax would blow up
    the scale and crush the live rows' precision — in fp they hide
    behind the position mask, here they'd poison the whole block),
    re-quantize over (bs, Dh), scatter payload + scale back.
    Duplicate dest entries (trash block 0) race over garbage, the
    module invariant; radix-shared blocks are never a live dest, so
    no other request's scale is ever perturbed."""
    if not isinstance(pool_l, dict):
        return pool_l.at[dest, :, rowi, :].set(val)
    n = dest.shape[0]
    bs = pool_l["q"].shape[2]
    blk = dequantize_symmetric(
        pool_l["q"][dest],
        pool_l["s"][dest][..., None, None],
        jnp.float32,
    )  # [N, Hkv, bs, Dh]
    blk = blk.at[jnp.arange(n), :, rowi, :].set(val.astype(jnp.float32))
    live = jnp.arange(bs)[None, :] <= rowi[:, None]  # [N, bs]
    blk = blk * live[:, None, :, None]
    q, s = quantize_symmetric(blk, axis=(-2, -1))  # s [N, Hkv]
    return {
        "q": pool_l["q"].at[dest].set(q),
        "s": pool_l["s"].at[dest].set(s),
    }


def _pool_write_rows_mt(pool_l, dest, rowi, val):
    """Multi-token sibling of _pool_write_rows: dest/rowi [B, T], val
    [B, T, Hkv, Dh] (T fresh rows per slot — a verify span or a
    prefill chunk). The fp path keeps the one-shot multi-row scatter.
    The int8 path loops the T columns SEQUENTIALLY through the
    single-row write: consecutive rows of one slot land in the same
    block, so each write must see the previous one's payload and
    scale — a parallel gather/requant would drop its siblings' rows.
    T is a small static bound (spec_k + 1, or a prefill chunk), and
    positions ascend with t, so the stale-row zeroing stays exact."""
    if not isinstance(pool_l, dict):
        return pool_l.at[dest, :, rowi, :].set(val)
    t = dest.shape[1]

    def body(j, pool):
        return _pool_write_rows(
            pool, dest[:, j], rowi[:, j], val[:, j]
        )

    return lax.fori_loop(0, t, body, pool_l)


def _quantize_blocks(blocks):
    """[L, n, Hkv, bs, Dh] fp block stack -> ({"q","s"}) int8 payload
    + [L, n, Hkv] scales, the pool's storage convention."""
    q, s = quantize_symmetric(
        blocks.astype(jnp.float32), axis=(-2, -1)
    )
    return q, s


def _blockwise_attend(q, pk_l, pv_l, tables, pos, bs, nb_live, window):
    """Single-token attention THROUGH a block table: fold pool blocks
    into the online-softmax carry (running max m, denominator l,
    accumulator — the flash recurrence, in fp32) one table column at a
    time, `lax.fori_loop`ed to `nb_live` = the deepest live block
    across the batch, so reads stop at actual depth instead of pool
    width. Per column the gather touches B blocks (one per slot); a
    slot shallower than the column has its whole block masked (its
    table entry points at live-or-trash rows the position mask
    excludes), which is what keeps the trash-block-0 invariant safe
    here. GQA folds grouped, [B, Hkv, G, *] against the [B, Hkv, bs,
    Dh] block — same head-major grouping as GptDecoder._block.

    q [B, Hq, 1, Dh]; pk_l/pv_l [NB, Hkv, bs, Dh]; tables [B, MB];
    pos [B] inclusive last valid key. Returns [B, 1, Hq*Dh] in
    q.dtype. Numerics: the recurrence computes the same softmax as
    the gathered path's one-pass einsum up to reduction order —
    tie-tolerant, not bit-exact (module docstring)."""
    b, hq, _, dh = q.shape
    hkv = _pool_arr(pk_l).shape[1]
    g = hq // hkv
    qg = q[:, :, 0, :].reshape(b, hkv, g, dh).astype(jnp.float32)
    qg = qg * (dh**-0.5)
    span = jnp.arange(bs)

    def body(j, carry):
        m, l, acc = carry
        blk = tables[:, j]  # [B]
        k = _pool_gather(pk_l, blk, jnp.float32)  # [B, Hkv, bs, Dh]
        v = _pool_gather(pv_l, blk, jnp.float32)
        s = jnp.einsum("bkgd,bksd->bkgs", qg, k)
        cols = j * bs + span  # [bs]
        mask = cols[None, :] <= pos[:, None]  # [B, bs]
        if window is not None:
            mask &= cols[None, :] > pos[:, None] - window
        s = jnp.where(mask[:, None, None, :], s, _MASK_VALUE)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bkgs,bksd->bkgd", p, v
        )
        return m_new, l_new, acc_new

    init = (
        jnp.full((b, hkv, g), _MASK_VALUE, jnp.float32),
        jnp.zeros((b, hkv, g), jnp.float32),
        jnp.zeros((b, hkv, g, dh), jnp.float32),
    )
    _, l, acc = lax.fori_loop(0, nb_live, body, init)
    out = acc / l[..., None]  # [B, Hkv, G, Dh]
    return out.astype(q.dtype).reshape(b, 1, hq * dh)


def _blockwise_attend_mt(q, pk_l, pv_l, tables, pos, bs, nb_live, window):
    """Multi-token sibling of _blockwise_attend: T query rows per slot
    (a speculative verify window or a prefill chunk), each causally
    masked at its OWN position pos[b] + t, folded through the block
    table with the same per-column online-softmax recurrence. Rows a
    slot is not using (pad rows of a prefill tail, the k speculative
    rows of a sampled slot) produce garbage the caller ignores — the
    mask keeps them from reading past their qpos, nothing more.

    q [B, Hq, T, Dh]; pos [B] = the FIRST query row's position (row t
    attends through pos + t inclusive). Returns [B, T, Hq*Dh] in
    q.dtype, the layout _attn_out takes. Same tie-tolerant contract as
    the single-token fold."""
    b, hq, t, dh = q.shape
    hkv = _pool_arr(pk_l).shape[1]
    g = hq // hkv
    qg = q.reshape(b, hkv, g, t, dh).astype(jnp.float32)
    qg = qg * (dh**-0.5)
    qpos = pos[:, None] + jnp.arange(t)[None, :]  # [B, T]
    span = jnp.arange(bs)

    def body(j, carry):
        m, l, acc = carry
        blk = tables[:, j]  # [B]
        k = _pool_gather(pk_l, blk, jnp.float32)  # [B, Hkv, bs, Dh]
        v = _pool_gather(pv_l, blk, jnp.float32)
        s = jnp.einsum("bkgtd,bksd->bkgts", qg, k)
        cols = j * bs + span  # [bs]
        mask = cols[None, None, :] <= qpos[:, :, None]  # [B, T, bs]
        if window is not None:
            mask &= cols[None, None, :] > qpos[:, :, None] - window
        s = jnp.where(mask[:, None, None, :, :], s, _MASK_VALUE)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bkgts,bksd->bkgtd", p, v
        )
        return m_new, l_new, acc_new

    init = (
        jnp.full((b, hkv, g, t), _MASK_VALUE, jnp.float32),
        jnp.zeros((b, hkv, g, t), jnp.float32),
        jnp.zeros((b, hkv, g, t, dh), jnp.float32),
    )
    _, l, acc = lax.fori_loop(0, nb_live, body, init)
    out = acc / l[..., None]  # [B, Hkv, G, T, Dh]
    return (
        out.transpose(0, 3, 1, 2, 4)
        .reshape(b, t, hq * dh)
        .astype(q.dtype)
    )


class HostKVSpill:
    """Bounded host-RAM spill tier for evicted prefix blocks: under
    pool pressure `PrefixBlockCache.evict` forgets warm blocks, and a
    later radix hit becomes a full re-prefill. This store keeps the
    evicted payload (already-quantized int8 + scale, or the fp bytes
    on an fp pool) keyed by the block's chained digest, so a *spill
    hit* revives the block into the pool with its EXACT stored bytes
    — token-identical to a resident hit — instead of recomputing it.

    Mutation domains (the disagg/ingest.py split, applied to spill):

      * the SERVING thread only enqueues device-array slices
        (`offer`, async dispatch — no blocking copy on the tick path)
        and reads/touches the store under `_lock` (`get`);
      * the DRAIN thread owns every blocking device->host copy and
        all insert/trim mutation of the store (under the same lock).

    The store is byte-bounded: inserts trim oldest-first (dict order
    is insertion order; `get` re-inserts on hit, so it is LRU). The
    offer queue is bounded too — under a burst of evictions spill is
    best-effort and sheds, never backpressuring admission. The race
    where a revival looks up a block that was evicted but not yet
    drained simply misses (a normal re-prefill), never corrupts."""

    def __init__(self, cap_bytes: int, obs: Any = None):
        self.cap = int(cap_bytes)
        self._q: queue.Queue = queue.Queue(maxsize=256)
        # key -> (own-block token bytes, host payload tuple, nbytes)
        self._store: dict[bytes, tuple] = {}
        self._bytes = 0
        self._lock = threading.Lock()
        self._obs = obs
        self._thread = threading.Thread(
            target=self._drain_loop, name="kv-spill-drain", daemon=True
        )
        self._thread.start()

    def offer(self, key: bytes, tok: bytes, arrays: tuple) -> None:
        """Serving thread: hand over async device slices of an
        evicted block. Never blocks — a full queue sheds the spill
        (the block is simply lost to the tier, as before this tier
        existed)."""
        try:
            self._q.put_nowait((key, tok, arrays))
        except queue.Full:
            pass

    # analysis: domain(drain) owns every blocking device->host copy and all store mutation (under _lock); serving only offers/gets
    def _drain_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            key, tok, arrays = item
            # The blocking device->host copies, off the tick path.
            host = tuple(np.asarray(a) for a in arrays)
            nbytes = sum(a.nbytes for a in host)
            with self._lock:
                old = self._store.pop(key, None)
                if old is not None:
                    self._bytes -= old[2]
                self._store[key] = (tok, host, nbytes)
                self._bytes += nbytes
                while self._bytes > self.cap and self._store:
                    k0 = next(iter(self._store))
                    _, _, nb0 = self._store.pop(k0)
                    self._bytes -= nb0
                stored_bytes = self._bytes
            if self._obs is not None:
                self._obs.prefix_spilled.inc()
                self._obs.spill_bytes.set(stored_bytes)
            self._q.task_done()

    def get(self, key: bytes, tok: bytes) -> tuple | None:
        """Serving thread: the spill lookup on a radix walk miss.
        Token-byte guarded like every radix hit (collision
        discipline); a hit is LRU-touched and its host payload
        returned for re-upload. The entry stays resident — the block
        may be evicted again later."""
        with self._lock:
            ent = self._store.get(key)
            if ent is None or ent[0] != tok:
                return None
            self._store[key] = self._store.pop(key)  # LRU touch
            return ent[1]

    def flush(self) -> None:
        """Block until every offered payload has drained into the
        store (tests / bench determinism; never on the tick path)."""
        self._q.join()

    @property
    def stored_blocks(self) -> int:
        with self._lock:
            return len(self._store)

    @property
    def stored_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def close(self) -> None:
        self._q.put(None)
        self._thread.join(timeout=5)


class PrefixBlockCache:
    """Host-side EXACT radix cache over pool blocks (the vLLM/SGLang
    automatic-prefix-caching idea, block-granular).

    A K/V block's content is a pure function of the token ANCESTRY it
    covers — every token from position 0 through its last row — so the
    cache keys each block by the bytes of that ancestry: lookups walk
    a request's leading full prompt blocks and stop at the first miss
    (exactly the radix-tree path walk, flattened into one dict).
    Blocks referenced by active requests carry a refcount; at
    refcount 0 a block is RETAINED in LRU order and revived on a
    later hit, evicted (key dropped, block returned to the caller's
    free list) only under allocation pressure. Only full blocks whose
    rows are all prompt content are ever registered — any block a
    request will write generated tokens into stays private.

    Keys are CHAINED digests, not raw ancestry bytes: block j's key is
    blake2b(key_{j-1} || block_j's own bs tokens), so a walk over n
    full blocks hashes O(n * bs) bytes total instead of the
    O(n^2 * bs) a per-block full-ancestry key costs on long prompts.
    Because a digest could in principle collide, every hit is guarded
    by an EXACT comparison of the candidate block's own token bytes
    (`tok_of`): along a sequential walk the ancestor blocks were
    already byte-verified, so by induction a guarded hit matches the
    full ancestry — a false hit would need a genuine blake2b-128
    collision AND identical own-block tokens.

    `obs` — optional obs.serving.ServingMetrics whose prefix-cache
    counters (parks / revivals / evictions) this cache drives; hit and
    miss counts are the admitting server's job (it knows whether an
    admission sticks)."""

    def __init__(self, obs: Any = None, on_evict: Any = None):
        # `on_evict(key, tok, blk)` — optional spill hook, called on
        # the evicting (serving) thread BEFORE the block is forgotten,
        # while its pool payload is still addressable: the server's
        # spill path snapshots the block for HostKVSpill there.
        self._on_evict = on_evict
        self.by_key: dict[bytes, int] = {}
        self.ref: dict[int, int] = {}
        self.key_of: dict[int, bytes] = {}
        self.tok_of: dict[int, bytes] = {}  # own-block tokens (guard)
        self.lru: dict[int, None] = {}  # refcount-0 blocks, dict=LRU
        self._obs = obs
        # Advertisement seam (fleet routing): `generation` bumps on
        # every change to the RESIDENT KEY SET (register / evict /
        # displacement), never on refcount churn, so a router can
        # compare one int to skip unchanged snapshots. The lock covers
        # only key-set mutation and snapshotting — the owning serving
        # thread is the sole mutator, the router's snapshot reader the
        # sole other party — so hot-path walk()/release() stay
        # lock-free.
        self.generation = 0
        self._lock = threading.Lock()

    @staticmethod
    def _hash(prev_key: bytes, block_bytes: bytes) -> bytes:
        """One chain link: key_j = H(key_{j-1} || block_j bytes)."""
        return hashlib.blake2b(
            prev_key + block_bytes, digest_size=16
        ).digest()

    def walk(
        self, tokens: np.ndarray, n_full: int, bs: int
    ) -> tuple[list[int], list[bytes], list[bytes]]:
        """Leading-hit walk over the n_full full prompt blocks:
        returns (hit pool blocks for blocks 0..k-1 where k is the
        first miss, the chained key of EVERY full block, each block's
        own token bytes). Keys/bytes for the miss tail feed
        `register` after the owner prefills — computed here in the
        same single O(n * bs) pass. Bumps refcounts on hits (reviving
        LRU entries); a digest hit whose own-block tokens mismatch is
        a collision, treated as a miss."""
        flat = tokens[: n_full * bs].astype(np.int64)
        keys: list[bytes] = []
        toks: list[bytes] = []
        prev = b""
        for j in range(n_full):
            bb = flat[j * bs : (j + 1) * bs].tobytes()
            prev = self._hash(prev, bb)
            keys.append(prev)
            toks.append(bb)
        hits: list[int] = []
        for j in range(n_full):
            blk = self.by_key.get(keys[j])
            if blk is None or self.tok_of[blk] != toks[j]:
                break
            if self.ref[blk] == 0:
                self.lru.pop(blk, None)
                if self._obs is not None:
                    self._obs.prefix_revivals.inc()
            self.ref[blk] += 1
            hits.append(blk)
        return hits, keys, toks

    def register(
        self, key: bytes, block_bytes: bytes, blk: int
    ) -> int | None:
        """Publish a freshly prefilled full prompt block under its
        chained `key` (from the same walk that missed it), with
        refcount 1 held by the registrant. Returns a DISPLACED block
        to free, if this key was still cached from an earlier,
        partially-evicted chain: the walk stops at the first miss, so
        a deeper same-key survivor is unreachable and must be
        forgotten here — silently overwriting the maps would leave its
        key_of entry aliasing the new block and corrupt a later
        eviction. A displaced block is always refcount 0: any ACTIVE
        holder of a deeper block also holds (and refcounts) the whole
        chain above it, which would have made this key a hit.
        (Deepest-first parking in _finish makes shallow keys outlive
        deep ones, so this path should be unreachable — it stays as
        defense for the invariant, raising so the check survives
        `python -O`.)"""
        displaced = self.by_key.get(key)
        if displaced is not None:
            if self.ref[displaced] != 0:
                raise RuntimeError(
                    f"prefix-cache invariant violated: key "
                    f"{key.hex()} would displace block {displaced} "
                    f"which still has {self.ref[displaced]} live "
                    f"reference(s) — an active chain holder should "
                    f"have made this key a hit"
                )
            del self.lru[displaced]
            del self.ref[displaced]
            del self.key_of[displaced]
            del self.tok_of[displaced]
        with self._lock:
            self.by_key[key] = blk
            self.generation += 1
        self.ref[blk] = 1
        self.key_of[blk] = key
        self.tok_of[blk] = block_bytes
        return displaced

    def release(self, blk: int) -> None:
        """Drop one reference; at 0 the block parks in LRU (still
        cached) rather than returning to the free list."""
        self.ref[blk] -= 1
        if self.ref[blk] == 0:
            self.lru[blk] = None
            if self._obs is not None:
                self._obs.prefix_parks.inc()

    def evict(self, n: int) -> list[int]:
        """Forget up to n least-recently-parked blocks; returns them
        for the free list."""
        out = []
        while self.lru and len(out) < n:
            blk = next(iter(self.lru))
            if self._on_evict is not None:
                self._on_evict(self.key_of[blk], self.tok_of[blk], blk)
            del self.lru[blk]
            with self._lock:
                del self.by_key[self.key_of.pop(blk)]
                self.generation += 1
            del self.ref[blk]
            del self.tok_of[blk]
            out.append(blk)
        if out and self._obs is not None:
            self._obs.prefix_evictions.inc(len(out))
        return out

    def resident_digests(self) -> tuple[int, frozenset[bytes]]:
        """(generation, resident chained digests) — the routing
        advertisement. A SHALLOW snapshot: the frozenset copies only
        key references (16-byte digests already interned in by_key),
        never block payloads or token bytes, so a router can poll this
        from another thread at advertisement frequency without taxing
        admission. The generation lets callers drop unchanged
        snapshots with one int compare before building anything."""
        with self._lock:
            return self.generation, frozenset(self.by_key)

    @property
    def cached_blocks(self) -> int:
        return len(self.by_key)


# -- pipeline-parallel stages (PagedDecodeServer pp_stages=) ---------------


def _pp_stage_step(dec, bs, attention, first, last, tp_axis):
    """RAW per-stage multi-token paged step for pipeline-parallel
    serving: `_mt_body`'s computation restricted to the contiguous
    layer range [first, last). The first stage embeds token ids, every
    other stage takes the previous stage's [B, T, D] activations; the
    last stage ends in the final norm + head (vocab slices all_gather
    to replicated logits under tp, exactly like _replicate_logits).
    Every stage recomputes the same write destinations from the
    replicated tables/pos operands, so each one scatters its layers'
    K/V rows into ITS OWN pool slice — the pool never crosses a stage
    boundary, only the [B, T, D] activation does.

    step(params_stage, pk, pv, tables, pos, xin, n_keep, keep_from,
    adapter_ids) -> (x_or_logits, pk, pv); decode rounds ride it at
    T=1 / n_keep=1 / keep_from=0, chunked pool-native prefill at
    T=chunk — one compiled program per (stage, shape), exactly the
    jit-cache behaviour the monolithic _mt has."""
    window = dec.cfg.window
    L = dec.cfg.num_layers
    tp = tp_axis
    if attention == "pallas":
        from defer_tpu.models.gpt import _flash_decode_mode
        from defer_tpu.ops.pallas_attention import paged_flash_prefill

        interpret = _flash_decode_mode() != "tpu"

    def step(
        params, pk, pv, tables, pos, xin, n_keep, keep_from,
        adapter_ids,
    ):
        b, t = xin.shape[0], xin.shape[1]
        mb = tables.shape[1]
        rows = jnp.arange(b)
        steps_t = jnp.arange(t)
        pvec = pos[:, None] + steps_t[None, :]  # [B, T]
        # Write destinations: identical math to _mt_body — dropped
        # rows (pad tails, radix-hit positions, frozen slots' zeroed
        # tables) redirect to trash block 0.
        blk = tables[
            rows[:, None], jnp.minimum(pvec // bs, mb - 1)
        ]
        keep = (steps_t[None, :] < n_keep[:, None]) & (
            pvec >= keep_from[:, None]
        )
        dest = jnp.where(keep, blk, 0)
        rowi = pvec % bs
        x = (
            dec._embed_tokens(params, xin, pos, tp)
            if first == 0
            else xin
        )

        if attention == "gathered":

            def body(carry, layer):
                x = carry
                p, pk_l, pv_l = layer
                kc = _pool_gather(pk_l, tables, dec.compute_dtype)
                vc = _pool_gather(pv_l, tables, dec.compute_dtype)
                b_, mb_, hkv, _, dh = kc.shape
                kc = kc.transpose(0, 2, 1, 3, 4).reshape(
                    b_, hkv, mb_ * bs, dh
                )
                vc = vc.transpose(0, 2, 1, 3, 4).reshape(
                    b_, hkv, mb_ * bs, dh
                )
                out, kc, vc = dec._block(
                    p, x, kc, vc, pos, tp_axis=tp,
                    adapter_ids=adapter_ids,
                )
                new_k = kc[rows[:, None], :, pvec, :]
                new_v = vc[rows[:, None], :, pvec, :]
                pk_l = _pool_write_rows_mt(pk_l, dest, rowi, new_k)
                pv_l = _pool_write_rows_mt(pv_l, dest, rowi, new_v)
                return out, (pk_l, pv_l)

        elif attention == "blockwise":

            def body(carry, layer):
                x = carry
                p, pk_l, pv_l = layer
                q, k_new, v_new = dec._attn_qkv(
                    p, x, pos, adapter_ids=adapter_ids
                )
                pk_l = _pool_write_rows_mt(
                    pk_l, dest, rowi, k_new.transpose(0, 2, 1, 3)
                )
                pv_l = _pool_write_rows_mt(
                    pv_l, dest, rowi, v_new.transpose(0, 2, 1, 3)
                )
                nb_live = jnp.minimum(
                    (jnp.max(pos) + t - 1) // bs + 1, mb
                )
                attn = _blockwise_attend_mt(
                    q, pk_l, pv_l, tables, pos, bs, nb_live,
                    window,
                )
                out = dec._attn_out(
                    p, x, attn, tp, adapter_ids=adapter_ids
                )
                return out, (pk_l, pv_l)

        else:  # pallas

            def body(carry, layer):
                x = carry
                p, pk_l, pv_l = layer
                q, k_new, v_new = dec._attn_qkv(
                    p, x, pos, adapter_ids=adapter_ids
                )
                pk_l = _pool_write_rows_mt(
                    pk_l, dest, rowi, k_new.transpose(0, 2, 1, 3)
                )
                pv_l = _pool_write_rows_mt(
                    pv_l, dest, rowi, v_new.transpose(0, 2, 1, 3)
                )
                b_, hq, t_, dh = q.shape
                attn = paged_flash_prefill(
                    q,
                    _pool_arr(pk_l),
                    _pool_arr(pv_l),
                    tables,
                    pos,
                    window=window,
                    interpret=interpret,
                )
                attn = (
                    attn.transpose(0, 2, 1, 3)
                    .reshape(b_, t_, hq * dh)
                    .astype(x.dtype)
                )
                out = dec._attn_out(
                    p, x, attn, tp, adapter_ids=adapter_ids
                )
                return out, (pk_l, pv_l)

        x, (pk, pv) = lax.scan(body, x, (params["stack"], pk, pv))
        if last == L:
            logits = dec._final_logits(params, x)
            if tp is not None:
                logits = lax.all_gather(
                    logits, tp, axis=-1, tiled=True
                )[..., : dec.cfg.vocab_size]
            return logits, pk, pv
        return x, pk, pv

    return step


def _pp_stage_specs(full_specs: dict, first: int, last: int, cfg) -> dict:
    """The shard_map in_specs subtree matching
    GptDecoder.stage_params(params, first, last): stack leaf specs are
    layer-leading (slicing the layer axis never changes them), the
    boundary stages add the embedding / final-norm / tied-head specs
    their extra params carry."""
    out = {"stack": full_specs["stack"]}
    if first == 0:
        out["token_embedding"] = full_specs["token_embedding"]
        if "pos_embedding" in full_specs:
            out["pos_embedding"] = full_specs["pos_embedding"]
    if last == cfg.num_layers:
        out["final_ln_scale"] = full_specs["final_ln_scale"]
        if "final_ln_bias" in full_specs:
            out["final_ln_bias"] = full_specs["final_ln_bias"]
        if "token_embedding" not in out:
            out["token_embedding"] = full_specs["token_embedding"]
    return out


class _PPLocalStage:
    """One pipeline stage resident in this process: the stage's param
    slice (GptDecoder.stage_params) and its [last-first, num_blocks,
    kv_heads, block_size, Dh] slice of the paged KV pool, placed
    together on one device (the in-process device-to-device tier) or
    one tensor-parallel submesh (pp x tp: the submesh is one slice of
    the joint {stage, model} mesh, so the stage's psums stay on its
    own ICI ring). `pp_dispatch` is the stage-boundary interface both
    placements share with _PPTransportStage: feed the six replicated
    operands, get the boundary activation (or final logits) back — an
    ASYNC device future here, which is what lets the server's
    round-major loop keep M microbatches in flight."""

    def __init__(
        self, dec, params, first, last, *, num_blocks, block_size,
        attention, device=None, submesh=None, model_axis="model",
    ):
        from defer_tpu.utils.memo import cached_step

        self.first = first
        self.last = last
        self.device = device
        self.submesh = submesh
        self.model_axis = model_axis if submesh is not None else None
        cfg = dec.cfg
        dh = cfg.dim // cfg.num_heads
        pool_shape = (
            last - first, num_blocks, cfg.kv_heads, block_size, dh,
        )
        if submesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as PSpec

            from defer_tpu.models.gpt import SpmdGptDecoder

            sdec = cached_step(
                dec,
                ("pp_spmd_view", submesh, model_axis),
                lambda: SpmdGptDecoder(
                    cfg,
                    compute_dtype=dec.compute_dtype,
                    mesh=submesh,
                    tp_axis=model_axis,
                ),
            )
            # Full params placed on THIS submesh (vocab pad + int8
            # bookkeeping), then sliced: the stack slices are fresh
            # per-stage buffers, the boundary tables alias the
            # placement.
            self.params = dec.stage_params(
                sdec.shard_params(params), first, last
            )
            self._param_specs = _pp_stage_specs(
                sdec._specs(), first, last, cfg
            )
            self._pool_spec = PSpec(None, None, model_axis, None, None)
            pool_sh = NamedSharding(submesh, self._pool_spec)
            self.pk = jnp.zeros(
                pool_shape, dec.compute_dtype, device=pool_sh
            )
            self.pv = jnp.zeros(
                pool_shape, dec.compute_dtype, device=pool_sh
            )
            self._sink = NamedSharding(submesh, PSpec())
        else:
            sp = dec.stage_params(params, first, last)
            if device is not None:
                sp = jax.device_put(sp, device)
            self.params = sp
            self._param_specs = None
            self._pool_spec = None
            self.pk = jnp.zeros(pool_shape, dec.compute_dtype)
            self.pv = jnp.zeros(pool_shape, dec.compute_dtype)
            if device is not None:
                self.pk = jax.device_put(self.pk, device)
                self.pv = jax.device_put(self.pv, device)
            self._sink = device
        self.pool_bytes = self.pk.nbytes + self.pv.nbytes
        self._fn = cached_step(
            dec,
            (
                "paged_pp_stage", block_size, attention, first, last,
                device, submesh, self.model_axis,
            ),
            lambda: self._build_fn(dec, block_size, attention),
        )

    def _build_fn(self, dec, bs, attention):
        body = _pp_stage_step(
            dec, bs, attention, self.first, self.last, self.model_axis
        )
        if self.submesh is None:
            return jax.jit(body, donate_argnums=(1, 2))
        from jax.sharding import PartitionSpec as PSpec

        from defer_tpu.utils.compat import shard_map

        pool, r = self._pool_spec, PSpec()
        sm = shard_map(
            body,
            self.submesh,
            in_specs=(self._param_specs, pool, pool) + (r,) * 6,
            out_specs=(r, pool, pool),
            # analysis: ignore[shard-spec] same waiver as _jit_tick: the body ends in slot scatters (and, on the last stage, a tiled all_gather) whose replication the checker cannot infer; psum placement is pinned by the defer_tp_psum_total mirror
            check_rep=False,
        )
        return jax.jit(sm, donate_argnums=(1, 2))

    def _put(self, a):
        """Commit an operand to this stage's placement — the
        in-process activation handoff (device-to-device copy; async,
        so chained stage dispatches overlap)."""
        if self._sink is None:
            return jnp.asarray(a)
        return jax.device_put(a, self._sink)

    def pp_dispatch(self, tables, pos, xin, n_keep, keep_from,
                    adapter_ids):
        out, self.pk, self.pv = self._fn(
            self.params,
            self.pk,
            self.pv,
            self._put(tables),
            self._put(pos),
            self._put(xin),
            self._put(n_keep),
            self._put(keep_from),
            self._put(adapter_ids),
        )
        return out

    def close(self):  # interface symmetry with _PPTransportStage
        pass


class _PPTransportStage:
    """A pipeline stage served by ANOTHER process over the framed
    activation transport (runtime/transport.py): `pp_dispatch` ships
    the six operands through an ArraySender to the stage worker
    (runtime/remote_stage.py::serve_pp_stage, which wraps a
    _PPLocalStage) and blocks on its one result array from the paired
    ArrayReceiver. The round trip is SYNCHRONOUS per dispatch — this
    placement is the cross-host parity/placement tier (same
    serve_stage session shape remote_stage.py uses), not an overlap
    win; in-process stages keep pipelining around it.

    `spec` is (host, port, result_receiver): the worker's listen
    address plus the caller-owned ArrayReceiver its results arrive
    on."""

    def __init__(self, spec, *, first, last, pool_bytes=0):
        from defer_tpu.runtime.transport import ArraySender

        host, port, receiver = spec
        self.first = first
        self.last = last
        self.pool_bytes = pool_bytes
        self._send = ArraySender(host, port)
        self._recv = receiver
        self._it = iter(receiver)

    def pp_dispatch(self, tables, pos, xin, n_keep, keep_from,
                    adapter_ids):
        for a in (tables, pos, xin, n_keep, keep_from, adapter_ids):
            # analysis: ignore[host-sync-in-hot-loop] the stage boundary IS a host transport here — framing the operand synchronizes it by design (documented parity tier)
            self._send.send(np.asarray(a))
        return next(self._it)

    def close(self):
        """Send the transport STOP so the worker's serve loop exits."""
        self._send.close()


class PagedDecodeServer:
    """Continuous batching over a paged KV pool; greedy by default,
    per-request sampling via `submit(..., sampling=)`.

    Protocol-compatible with runtime/decode_server.DecodeServer
    (submit -> run -> {rid: ids}), with the pool replacing per-slot
    max_len lanes. `num_blocks` INCLUDES the reserved trash block 0.

    `prefix_cache=True` turns on PER-REQUEST shared-prefix paging
    (PrefixBlockCache): any subset of requests sharing any leading
    prompt content automatically shares those full blocks — admission
    gathers the hit blocks into a flat lane and prefills only the
    suffix, finished requests park their shared blocks at refcount 0
    for later revival, and eviction happens only under pool pressure.
    This generalizes the constructor-level `prefix_ids` (one global
    system prompt, still supported, mutually exclusive).
    """

    def __init__(
        self,
        dec: Any,
        params: dict,
        *,
        num_blocks: int,
        block_size: int = 16,
        max_batch: int = 4,
        eos_id: int | None = None,
        on_token: Any = None,
        prefix_ids: jax.Array | None = None,
        prefix_cache: bool = False,
        attention: str = "gathered",
        kv_dtype: str = "fp",
        spill_bytes: int = 0,
        decode_window: int = 1,
        spec_draft: Any = None,
        spec_params: dict | None = None,
        spec_k: int = 0,
        prefill_chunk: int | None = None,
        prefill_budget: int | None = None,
        prefill_lookahead: int = 2,
        mesh: Any = None,
        model_axis: str = "model",
        device: Any = None,
        constraints: dict | None = None,
        pp_stages: int = 1,
        pp_inflight: int | None = None,
        pp_cuts: Any = None,
        pp_devices: Any = None,
        pp_remote: dict | None = None,
        pp_balance: str = "equal",
        pp_stage_axis: str = "stage",
    ):
        """`on_token(request_id, token_id, done)` — optional streaming
        callback, same contract as the flat server's.

        `constraints` — named constraint DFAs ({name:
        constrain.TokenDFA}, compiled against this decoder's
        vocabulary, defer_tpu/constrain/) a request selects with
        SamplingParams(constraint=name): that slot's logits are masked
        to grammar-admissible tokens (eos admitted only in accepting
        states) before argmax/categorical, and the DFA state advances
        on device inside the same tick/window/spec programs —
        constrained greedy output is token-identical across
        decode_window, spec_k, attention modes, and meshes. Requires
        `eos_id` (a satisfied constraint must be able to stop). With
        the default None every traced program is byte-identical to a
        server built before this feature existed.

        `pp_stages` — PIPELINE-PARALLEL serving (ARCHITECTURE.md
        "Pipeline-parallel serving"): partition the decoder's layer
        stack into S contiguous stages, each owning ONLY its layers'
        slice of the paged KV block pool (per-stage HBM ~1/S; one
        shared block table / free list indexes every slice), and run
        the decode tick as a pipelined window — `pp_inflight` (M,
        default min(S, max_batch)) microbatch slot groups flow through
        the stage chain round-major with overlapped async dispatch, so
        the schedule's bubble fraction is (S-1)/(K*M + S-1) and is
        MEASURED per window (defer_pp_bubble_fraction), never assumed.
        Greedy output is token-identical to pp_stages=1 across
        attention modes x prefix_cache x decode_window x tp. Stage
        boundaries are activation handoffs behind one interface with
        two placements: in-process device-to-device (stage i on
        `pp_devices[i]`, default jax.devices()), or the framed
        transport for stages served by another process (`pp_remote`,
        runtime/remote_stage.py::serve_pp_stage). With `mesh=` the
        mesh must carry `pp_stage_axis` OUTERMOST around `model_axis`
        (parallel/multihost.py::make_multihost_mesh puts it there), and
        each stage runs tensor-parallel on its own submesh. `pp_cuts`
        pins explicit stage start layers; `pp_balance="probe"`
        auto-balances cuts by per-layer probe cost
        (parallel/pipeline.py::balance_stage_cuts). Admission prefill
        always runs pool-native through the stage chain (chunked by
        `prefill_chunk` when set). Deferred compositions raise with
        the fix spelled out: spec_k > 0, disagg ingest
        (submit_prefilled/deliver_kv), constraints, multi-LoRA,
        constructor prefix_ids, spill_bytes, kv_dtype="int8".

        `spec_k` — speculative decoding (ARCHITECTURE.md "Speculative
        serving"): a DRAFT decoder (`spec_draft`/`spec_params`, same
        tokenizer/vocab, typically much smaller) proposes k greedy
        tokens per GREEDY slot per round, and the target verifies all
        k+1 positions in ONE block-table-indexed multi-token forward —
        accepted rows land in the paged pool as one multi-row scatter,
        rows a slot is not speculating (sampled slots, idle slots)
        redirect to trash block 0, and rejected rows go stale behind
        the position mask until the next round rewrites them. Greedy
        output is bit-identical to spec_k=0; sampled slots ride the
        verify forward's first row and advance one token per round
        from the SAME key stream as spec_k=0. The default 0 keeps the
        classic tick loop untouched. Composes with prefix_cache,
        mixed sampling, decode_window > 1 (the window scan's sub-steps
        become whole draft+verify rounds — W rounds per host
        dispatch), submit_prefilled admissions (the draft lane
        re-prefills locally from the prompt ids), and tensor-parallel
        meshes (the draft is replicated; only the verify forward is
        sharded). Still raises with constructor prefix_ids (the draft
        lane has no shared-prefix plumbing) and multi-LoRA (the draft
        is one model — per-adapter proposals would need per-adapter
        drafts).

        `prefill_chunk` — chunked POOL-NATIVE prefill: admission runs
        the prompt through the multi-token paged step in chunks of
        this many tokens, writing K/V straight into the allocated
        blocks through the block table instead of materializing a
        contiguous max_len lane and paging it in afterwards. With
        attention="blockwise"/"pallas" the chunk's reads scale with
        the prompt's LIVE blocks, never with pool size (the
        `defer_kv_rows_*` counters price it). None (default) keeps
        the contiguous prefill + insert path.

        `prefill_budget` — STALL-FREE continuous batching
        (ARCHITECTURE.md "Continuous batching & prefill scheduling"):
        instead of running each admitted prompt's prefill to
        completion while every live slot stalls, a new request takes
        a SEAT whose `pos` advances chunk by chunk, and each decode
        dispatch carries the live decode rows PLUS up to this many
        prompt tokens from the seated prefills, fused into one
        multi-token forward (runtime/schedule.py plans the tick;
        _tick_mixed dispatches it). Decode rows always advance
        exactly one token per mixed tick — sampling/eos/stop apply
        only to them — and a seat flips to decoding the tick its
        last chunk lands (that chunk's final logits row seeds the
        slot's first token, exactly the stall path's admission draw).
        Greedy output is token-identical to `prefill_budget=None`
        across attention modes x prefix_cache x decode_window x tp;
        radix admits schedule only the non-shared suffix and publish
        their fresh blocks at flip time; `submit_prefilled` seats
        bypass the budget (their compute is already spent). At most
        `prefill_lookahead` seats prefill concurrently (bounded
        lookahead keeps admission near-FIFO). None (default) keeps
        the serialized stall-prefill admission path bit-identically.
        Deferred compositions raise with the fix spelled out:
        spec_k > 0 and pp_stages > 1.

        `decode_window` — decode sub-steps fused into ONE jitted host
        dispatch (K), the paged twin of DecodeServer's parameter (its
        docstring has the full semantics). A `lax.scan` over the raw
        paged step advances every live slot up to K tokens on device;
        rows frozen mid-window (eos / budget) have their position and
        block-table row zeroed per sub-step, so their dead writes land
        in trash block 0 row 0 — exactly where an idle K=1 slot
        writes. One batched [B, K] transfer per window feeds
        streaming/stop consumers; admissions and block
        allocation/release stay at window boundaries. The default 1 is
        the classic tick-per-token loop, bit-identical to before.

        `kv_dtype` — the pool's storage dtype. "fp" (default) keeps
        the compute-dtype pool, bit-identical to before the knob
        existed. "int8" stores K/V rows as symmetric int8 with ONE
        fp32 scale per (layer, block, kv_head) — half the HBM bytes
        of a bf16 pool — quantizing inside the same jitted scatters
        that land KV today and dequantizing on read in all three
        `attention` modes (the pallas kernels take the int8 pool plus
        its scale refs, so read traffic halves too). Greedy output is
        NOT bit-identical to fp — the accuracy contract is the
        bounded logit-error parity pinned in tests/test_kv_quant.py.

        `spill_bytes` — host-RAM spill tier for evicted prefix blocks
        (requires prefix_cache=True): when the radix cache evicts a
        parked block under pool pressure, its payload (quantized rows
        + scales for int8; compute-dtype rows for fp) is snapshotted
        asynchronously and drained to a bounded host store keyed by
        the block's chain digest, off the tick hot path (same
        drain-thread shape as disagg/ingest.py). A later walk miss
        that hits the spill store revives the block into the pool
        token-identically to a resident radix hit instead of
        re-prefilling. 0 (default) disables the tier.

        `attention` — which decode attention path the tick compiles
        (module docstring): "gathered" (contiguous-view reference,
        bit-exact, the default), "blockwise" (pure-XLA block-native,
        reads stop at the deepest live block, tie-tolerant), or
        "pallas" (block-table-indexed kernel, per-slot live-block
        DMA; interpret-mode fallback off-TPU, tie-tolerant).

        `mesh` / `model_axis` — TENSOR-PARALLEL serving
        (ARCHITECTURE.md "Sharded serving"): shard the decoder weights
        (Megatron column/row split + vocab-sharded embedding) and the
        paged KV pool's head axis over the mesh's `model_axis`, and run
        every jitted tick body under shard_map so each device reads
        only its local KV heads. Host-side mechanics (admission, block
        tables, sampling, radix cache, obs) stay single-writer and
        unsharded; sampling sees the replicated post-psum logits, so
        per-window transfer and dispatch counts are unchanged.
        mesh=None (default) is bit-identical to the single-device
        server; a model_axis of size 1 is token-identical to it.

        `device` — pin this server's params/pool (and hence every tick)
        to one specific jax.Device instead of the process default —
        how fleet replicas spread over a multi-chip host without
        tensor parallelism. Mutually exclusive with `mesh`.

        `prefix_ids` [1, P] — SHARED-prefix paging: the system
        prompt's K/V blocks are allocated ONCE and every request's
        block table points at them (the flat server copies the prefix
        lane per admission; here the pool holds one copy, period).
        Requires P to be a block_size multiple so suffix writes can
        never touch a shared block. Admissions prefill only the
        suffix."""
        if getattr(dec, "rolling_cache", False):
            raise ValueError("paged serving does not support rolling caches")
        # Multi-LoRA: adapter banks (parallel/lora.py::stack_adapters)
        # make the slot -> adapter assignment per-slot state, same as
        # the flat server; id 0 = base model.
        from defer_tpu.parallel.lora import adapter_bank_info

        n_adapters = adapter_bank_info(params)
        self.multi_lora = n_adapters is not None
        if self.multi_lora:
            self.num_adapters = n_adapters
        if block_size < 1 or num_blocks < 2:
            raise ValueError(
                f"need block_size >= 1 and num_blocks >= 2 (one trash "
                f"block + one usable), got {block_size}/{num_blocks}"
            )
        if attention not in ("gathered", "blockwise", "pallas"):
            raise ValueError(
                f"attention must be 'gathered', 'blockwise' or "
                f"'pallas', got {attention!r}"
            )
        if kv_dtype not in ("fp", "int8"):
            raise ValueError(
                f"kv_dtype must be 'fp' or 'int8', got {kv_dtype!r}"
            )
        if spill_bytes < 0:
            raise ValueError(
                f"spill_bytes must be >= 0, got {spill_bytes}"
            )
        if spill_bytes and not prefix_cache:
            raise ValueError(
                "spill_bytes > 0 needs prefix_cache=True — the spill "
                "tier stores evicted PREFIX blocks keyed by the radix "
                "cache's chain digests"
            )
        if decode_window < 1:
            raise ValueError(
                f"decode_window must be >= 1, got {decode_window}"
            )
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if (spec_draft is not None or spec_params is not None) and not spec_k:
            raise ValueError(
                "spec_draft/spec_params provided but spec_k == 0 — "
                "pass spec_k >= 1 to turn speculation on"
            )
        if spec_k:
            if spec_draft is None or spec_params is None:
                raise ValueError(
                    "spec_k > 0 needs both spec_draft and spec_params "
                    "(the proposal model and its weights)"
                )
            if prefix_ids is not None:
                raise ValueError(
                    "spec_k > 0 does not compose with constructor "
                    "prefix_ids (the draft lane has no shared-prefix "
                    "plumbing); use prefix_cache=True"
                )
            if self.multi_lora:
                raise ValueError(
                    "spec_k > 0 with multi-LoRA is unsupported: one "
                    "draft model cannot propose for per-slot adapters"
                )
            if spec_draft.cfg.max_len < dec.cfg.max_len:
                raise ValueError(
                    f"draft max_len {spec_draft.cfg.max_len} < target "
                    f"max_len {dec.cfg.max_len}: the draft lane must "
                    "cover every position the target can reach"
                )
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        if prefill_lookahead < 1:
            raise ValueError(
                f"prefill_lookahead must be >= 1, got {prefill_lookahead}"
            )
        if prefill_budget is not None:
            if prefill_budget < 1:
                raise ValueError(
                    f"prefill_budget must be >= 1 prompt tokens per "
                    f"tick, got {prefill_budget}"
                )
            if spec_k:
                raise ValueError(
                    "prefill_budget does not compose with spec_k > 0 "
                    "yet: the verify forward already owns the "
                    "multi-token rows a mixed tick would budget, and "
                    "fusing draft catch-up with mid-prefill seats "
                    "needs a draft-side seat lifecycle. Fix: serve "
                    "speculation on a prefill_budget=None server, or "
                    "set spec_k=0 here."
                )
            if pp_stages > 1:
                raise ValueError(
                    "prefill_budget does not compose with pp_stages "
                    "> 1 yet: the pipelined window schedules whole "
                    "microbatch groups and a mixed tick would need "
                    "per-stage budget accounting across the in-flight "
                    "groups. Fix: run mixed-mode admission on a "
                    "pp_stages=1 server (tensor-parallel via mesh= "
                    "composes), or set prefill_budget=None here."
                )
        if mesh is not None and device is not None:
            raise ValueError(
                "mesh= and device= are mutually exclusive: a mesh "
                "already pins the server to its devices"
            )
        if pp_stages < 1:
            raise ValueError(f"pp_stages must be >= 1, got {pp_stages}")
        self.pp = pp_stages
        if pp_stages == 1 and (
            pp_inflight is not None
            or pp_cuts is not None
            or pp_devices is not None
            or pp_remote is not None
        ):
            raise ValueError(
                "pp_inflight/pp_cuts/pp_devices/pp_remote only apply "
                "with pp_stages > 1"
            )
        _pp_M = 1
        if pp_stages > 1:
            if pp_stages > dec.cfg.num_layers:
                raise ValueError(
                    f"pp_stages={pp_stages} exceeds num_layers="
                    f"{dec.cfg.num_layers}: every stage needs at least "
                    "one layer. Fix: lower pp_stages (or serve a "
                    "deeper model)."
                )
            if spec_k:
                raise ValueError(
                    "spec_k > 0 does not compose with pp_stages > 1 "
                    "yet: the draft lane proposes against a monolithic "
                    "pool and the verify forward would have to thread "
                    "k+1 candidate rows through every stage boundary. "
                    "Fix: serve speculation on a pp_stages=1 server, "
                    "or set spec_k=0 here."
                )
            if constraints is not None:
                raise ValueError(
                    "constraints= does not compose with pp_stages > 1 "
                    "yet: the DFA advance is fused into the monolithic "
                    "window program. Fix: serve constrained requests "
                    "on a pp_stages=1 server."
                )
            if self.multi_lora:
                raise ValueError(
                    "multi-LoRA does not compose with pp_stages > 1: "
                    "adapter banks are not stage-sliced. Fix: merge "
                    "the adapter (parallel/lora.py) or serve adapters "
                    "on pp_stages=1."
                )
            if prefix_ids is not None:
                raise ValueError(
                    "constructor prefix_ids does not compose with "
                    "pp_stages > 1: the one-shot prefix insert runs "
                    "through the monolithic flat path. Fix: use "
                    "prefix_cache=True (shares prefixes per request, "
                    "pool-native) instead."
                )
            if spill_bytes:
                raise ValueError(
                    "spill_bytes > 0 does not compose with "
                    "pp_stages > 1 yet: spill snapshots slice a "
                    "monolithic pool. Fix: set spill_bytes=0 (evicted "
                    "prefix blocks are then re-prefilled)."
                )
            if kv_dtype != "fp":
                raise ValueError(
                    f"kv_dtype={kv_dtype!r} does not compose with "
                    "pp_stages > 1 yet: the per-stage pool slices are "
                    "compute-dtype only. Fix: use kv_dtype='fp' with "
                    "pp, or int8 on a pp_stages=1 server."
                )
            if device is not None:
                raise ValueError(
                    "device= pins ONE device but pp_stages > 1 places "
                    "each stage on its own. Fix: pass the stage "
                    "placement as pp_devices=[dev0, dev1, ...] "
                    "instead."
                )
            if pp_balance not in ("equal", "probe"):
                raise ValueError(
                    f"pp_balance must be 'equal' or 'probe', got "
                    f"{pp_balance!r}"
                )
            _pp_M = (
                pp_inflight
                if pp_inflight is not None
                else min(pp_stages, max_batch)
            )
            if _pp_M < 1:
                raise ValueError(
                    f"pp_inflight must be >= 1, got {_pp_M}"
                )
            if max_batch % _pp_M:
                raise ValueError(
                    f"max_batch={max_batch} does not divide into "
                    f"pp_inflight={_pp_M} equal microbatch slot "
                    "groups. Fix: pick max_batch a multiple of "
                    "pp_inflight (or pass pp_inflight= a divisor of "
                    "max_batch)."
                )
        self.mesh = mesh
        self.model_axis = model_axis
        self.device = device
        self.tp = 1
        self._sdec = None
        if mesh is not None:
            if getattr(dec, "mesh", None) is not None:
                raise ValueError(
                    "pass the plain single-device decoder together "
                    "with mesh= — the server builds its own sharded "
                    "step (an SpmdGptDecoder here would double-wrap "
                    "shard_map)"
                )
            if model_axis not in mesh.axis_names:
                raise ValueError(
                    f"model_axis {model_axis!r} is not an axis of the "
                    f"mesh (axes: {mesh.axis_names}); build the mesh "
                    f"with parallel.mesh.make_mesh({{{model_axis!r}: "
                    "N})"
                )
            tp = int(mesh.shape[model_axis])
            kvh = dec.cfg.kv_heads
            if kvh < tp:
                raise ValueError(
                    f"GQA num_kv_heads={kvh} is smaller than the "
                    f"{model_axis!r} axis size {tp}: the paged pool "
                    "shards whole KV heads, so some devices would own "
                    "none. Fix: serve on a mesh whose model axis has "
                    f"at most {kvh} devices (put the rest on a data "
                    "axis), or replicate KV heads in the checkpoint."
                )
            if kvh % tp:
                fit = max(
                    d for d in range(1, kvh + 1)
                    if kvh % d == 0 and d <= tp
                )
                raise ValueError(
                    f"num_kv_heads={kvh} does not divide by the "
                    f"{model_axis!r} axis size {tp}: each device must "
                    "own an equal whole-head slice of the paged pool. "
                    f"Fix: use a model axis size that divides {kvh} "
                    f"(largest that fits: {fit}), or pad kv_heads to "
                    f"a multiple of {tp} in the checkpoint."
                )
            if self.multi_lora:
                raise ValueError(
                    "mesh= with multi-LoRA is unsupported: the adapter "
                    "banks are not sharded — serve adapters on "
                    "mesh=None"
                )
            self.tp = tp
            if pp_stages > 1:
                # pp x tp: the joint mesh carries the stage axis
                # OUTERMOST (DCN-crossing, one activation per
                # boundary) around the model axis (ICI-heavy psums
                # stay inside a stage's submesh) — the
                # make_multihost_mesh/dcn_aware_axes layout rule.
                from defer_tpu.parallel.multihost import stage_submeshes

                if pp_stage_axis not in mesh.axis_names:
                    raise ValueError(
                        f"pp_stages={pp_stages} with mesh= needs a "
                        f"{pp_stage_axis!r} mesh axis for the stage "
                        f"dimension (axes: {mesh.axis_names}). Fix: "
                        "build the mesh with parallel.multihost."
                        f"make_multihost_mesh({{{pp_stage_axis!r}: "
                        f"{pp_stages}, {model_axis!r}: tp}})."
                    )
                if int(mesh.shape[pp_stage_axis]) != pp_stages:
                    raise ValueError(
                        f"mesh {pp_stage_axis!r} axis has size "
                        f"{int(mesh.shape[pp_stage_axis])} but "
                        f"pp_stages={pp_stages}; the two must match"
                    )
                self._pp_submeshes = stage_submeshes(
                    mesh, pp_stage_axis
                )
        if mesh is not None and pp_stages == 1:
            # One sharded view of the decoder per (dec, mesh, axis):
            # SpmdGptDecoder supplies the param specs, vocab padding,
            # sharded flat prefill step, and the remaining divisibility
            # validation (heads/dim/ffn % tp).
            from defer_tpu.models.gpt import SpmdGptDecoder
            from defer_tpu.utils.memo import cached_step

            self._sdec = cached_step(
                dec,
                ("spmd_view", mesh, model_axis),
                lambda: SpmdGptDecoder(
                    dec.cfg,
                    compute_dtype=dec.compute_dtype,
                    mesh=mesh,
                    tp_axis=model_axis,
                ),
            )
        # Memo-key component for every compiled program: a mesh-built
        # step and a single-device step must never share a cache slot
        # on the same decoder instance.
        self._mesh_key = (mesh, model_axis) if mesh is not None else None
        self.mesh_label = f"{model_axis}={self.tp}" if mesh is not None else None
        # Collectives one sharded forward issues: per layer an attn
        # psum + an ffn psum, plus the embedding psum and the final
        # logits all_gather. Host-side mirror for defer_tp_psum_total.
        self._psums_per_fwd = (
            2 * dec.cfg.num_layers + 2 if mesh is not None else 0
        )
        self.tp_psums = 0
        self.decode_window = decode_window
        self.attention = attention
        self.dec = dec
        self.params = params
        self.B = max_batch
        self.bs = block_size
        self.eos_id = eos_id
        self.on_token = on_token
        cfg = dec.cfg
        # Max logical blocks any sequence can span.
        self.MB = -(-cfg.max_len // block_size)
        dh = cfg.dim // cfg.num_heads
        self.kv_dtype = kv_dtype
        self.num_blocks = num_blocks
        pool_shape = (
            cfg.num_layers, num_blocks, cfg.kv_heads, block_size, dh,
        )
        # int8 pools are a {"q", "s"} pytree: int8 rows plus one fp32
        # scale per (layer, block, kv_head). Scales start at 1.0 so a
        # never-written block dequantizes to the zeros an fp pool
        # holds. The fp pool stays a PLAIN array — its jitted
        # programs trace byte-identical to pre-int8 builds.
        scale_shape = (cfg.num_layers, num_blocks, cfg.kv_heads)
        if self.pp > 1:
            # Pipeline-parallel: the pool never exists monolithically
            # — each _PPLocalStage allocates its own layer slice on
            # its own placement (built below, after the bookkeeping
            # state the cut probe needs). The None handles make any
            # path that would touch a monolithic pool fail loudly.
            self._pool_spec = None
            self._head_spec = None
            self.pool_k = None
            self.pool_v = None
        elif mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as PSpec

            # Pool sharded on the KV-head axis: each device holds
            # [L, num_blocks, kv_heads/tp, block_size, Dh] — every
            # block present on every shard, but only its local heads.
            # Allocated DIRECTLY sharded (no transient replicated
            # pool), params placed by the Megatron specs (vocab table
            # padded to a tp multiple by shard_params). The int8
            # scale tensor splits on the SAME head axis (index 2 in
            # both layouts), so a shard's rows and scales travel
            # together.
            self._pool_spec = PSpec(None, None, model_axis, None, None)
            self._head_spec = PSpec(None, None, model_axis)
            pool_sh = NamedSharding(mesh, self._pool_spec)
            if kv_dtype == "int8":
                scale_sh = NamedSharding(mesh, self._head_spec)
                self.pool_k = {
                    "q": jnp.zeros(pool_shape, jnp.int8, device=pool_sh),
                    "s": jnp.ones(scale_shape, jnp.float32, device=scale_sh),
                }
                self.pool_v = {
                    "q": jnp.zeros(pool_shape, jnp.int8, device=pool_sh),
                    "s": jnp.ones(scale_shape, jnp.float32, device=scale_sh),
                }
            else:
                self.pool_k = jnp.zeros(
                    pool_shape, dec.compute_dtype, device=pool_sh
                )
                self.pool_v = jnp.zeros(
                    pool_shape, dec.compute_dtype, device=pool_sh
                )
            self.params = self._sdec.shard_params(params)
        else:
            self._pool_spec = None
            self._head_spec = None
            if kv_dtype == "int8":
                self.pool_k = {
                    "q": jnp.zeros(pool_shape, jnp.int8),
                    "s": jnp.ones(scale_shape, jnp.float32),
                }
                self.pool_v = {
                    "q": jnp.zeros(pool_shape, jnp.int8),
                    "s": jnp.ones(scale_shape, jnp.float32),
                }
            else:
                self.pool_k = jnp.zeros(pool_shape, dec.compute_dtype)
                self.pool_v = jnp.zeros(pool_shape, dec.compute_dtype)
            if device is not None:
                self.pool_k = jax.device_put(self.pool_k, device)
                self.pool_v = jax.device_put(self.pool_v, device)
                self.params = jax.device_put(params, device)
        # shard_map / with_sharding_constraint spec matching the
        # pool's pytree structure (plain spec for fp, {"q","s"} tree
        # for int8).
        self._pool_specs = (
            {"q": self._pool_spec, "s": self._head_spec}
            if kv_dtype == "int8"
            else self._pool_spec
        )
        self.pool_bytes = sum(
            leaf.nbytes
            for leaf in jax.tree.leaves((self.pool_k, self.pool_v))
        )
        # Pipeline-parallel stage chain (pp_stages > 1): resolve the
        # layer cuts, build one stage per contiguous layer range, and
        # account the pool as the sum of the per-stage slices.
        self._pp_stage_objs: list = []
        self._pp_cut_starts: list[int] = [0]
        self._pp_inflight = _pp_M
        self._pp_groups: list[list[int]] = []
        self.pp_stage_pool_bytes: list[int] = []
        self.pp_stage_dispatch_n: list[int] = []
        self.pp_bubble_last = 0.0
        self.pp_occupancy_last: list[float] = []
        if self.pp > 1:
            from defer_tpu.parallel.pipeline import balance_stage_cuts

            L = cfg.num_layers
            if pp_cuts is not None:
                starts = [int(c) for c in pp_cuts]
                if (
                    len(starts) != self.pp
                    or starts[0] != 0
                    or any(
                        b <= a for a, b in zip(starts, starts[1:])
                    )
                    or starts[-1] >= L
                ):
                    raise ValueError(
                        f"pp_cuts={starts} must be {self.pp} strictly "
                        f"increasing stage START layers beginning at 0 "
                        f"and below num_layers={L} (e.g. [0, "
                        f"{L // 2}] for 2 stages). Fix: pass valid "
                        "cut starts, or drop pp_cuts for balanced "
                        "ones."
                    )
            elif pp_balance == "probe":
                starts = balance_stage_cuts(
                    self._probe_pp_layer_costs(num_blocks), self.pp
                )
            else:
                # Equal layer counts == min-max split of unit costs.
                starts = balance_stage_cuts([1.0] * L, self.pp)
            bounds = starts + [L]
            remote = pp_remote or {}
            if any(s not in range(self.pp) for s in remote):
                raise ValueError(
                    f"pp_remote stage indices {sorted(remote)} must "
                    f"lie in [0, {self.pp})"
                )
            devs = (
                list(pp_devices)
                if pp_devices is not None
                else jax.devices()
            )
            dh_ = cfg.dim // cfg.num_heads
            itemsize = jnp.dtype(dec.compute_dtype).itemsize
            for s in range(self.pp):
                first_l, last_l = bounds[s], bounds[s + 1]
                if s in remote:
                    # The worker owns the slice; account its bytes
                    # here so per-stage HBM ~1/S stays inspectable.
                    stage = _PPTransportStage(
                        remote[s],
                        first=first_l,
                        last=last_l,
                        pool_bytes=2
                        * (last_l - first_l)
                        * num_blocks
                        * cfg.kv_heads
                        * block_size
                        * dh_
                        * itemsize,
                    )
                elif mesh is not None:
                    stage = _PPLocalStage(
                        dec, params, first_l, last_l,
                        num_blocks=num_blocks,
                        block_size=block_size,
                        attention=attention,
                        submesh=self._pp_submeshes[s],
                        model_axis=model_axis,
                    )
                else:
                    stage = _PPLocalStage(
                        dec, params, first_l, last_l,
                        num_blocks=num_blocks,
                        block_size=block_size,
                        attention=attention,
                        device=devs[s % len(devs)],
                    )
                self._pp_stage_objs.append(stage)
            self._pp_cut_starts = starts
            self._pp_groups = microbatch_groups(max_batch, _pp_M)
            self.pp_stage_pool_bytes = [
                st.pool_bytes for st in self._pp_stage_objs
            ]
            self.pool_bytes = sum(self.pp_stage_pool_bytes)
            self.pp_stage_dispatch_n = [0] * self.pp
        # Block 0 is trash: unallocated table entries point at it.
        self.free = list(range(1, num_blocks))
        self.tables = np.zeros((max_batch, self.MB), np.int32)
        self.pos = np.zeros((max_batch,), np.int32)
        self.adapter = np.zeros((max_batch,), np.int32)
        self.slots: list[dict | None] = [None] * max_batch
        # Persistent tick feed: each slot's next input token lives in
        # row i, updated by .at[i].set at admission and one full-vector
        # write after each draw — not rebuilt by concatenating
        # max_batch [1,1] arrays every tick (host dispatch overhead
        # that dominates at small models). Idle rows are dummies.
        self._feed = jnp.zeros((max_batch, 1), jnp.int32)
        self._sampler = SlotSampler(max_batch)
        # deque, not list: admission consumes from the head every
        # _admit pass, and a deep open-loop backlog would turn
        # list.pop(0) into O(queue) per admission.
        self.pending: collections.deque[tuple] = collections.deque()
        # Externally prefilled admissions (disagg/): rid -> request
        # entry whose "kv" field a transport ingest fills in from
        # another thread (deliver_kv). Admission order follows
        # _prefilled_order among entries whose KV has arrived. All
        # POOL mutation stays on the run/_admit thread; the ingest
        # thread only ever assigns the entry's "kv" slot.
        self.pending_prefilled: dict[int, dict] = {}
        self._prefilled_order: list[int] = []
        self.done: dict[int, jax.Array] = {}
        self._next_id = 0
        self.ticks = 0
        self.blocks_peak = 0
        # Dispatch-efficiency accounting (fused windows): host
        # dispatches of the decode program and tokens accepted from
        # them. At decode_window=1, dispatches == ticks.
        self.dispatches = 0
        self.window_tokens = 0
        # Metric handles resolved once; tick/admission paths touch
        # pre-bound attributes only (obs/serving.py).
        self.obs = ServingMetrics("paged", mesh_shape=self.mesh_label)
        self.obs.kv_pool_bytes.set(self.pool_bytes)
        if self.pp > 1:
            # Stage-labeled pp instruments (occupancy gauges + dispatch
            # counters per stage) bind once the stage count is known.
            self.obs.bind_pp(self.pp)
            self.obs.pp_inflight.set(float(self._pp_inflight))
        self._submit_t: dict[int, float] = {}
        self._last_tick_t: float | None = None
        # Constrained decoding tables (defer_tpu/constrain/): stacked
        # [C, S_max, V] transitions + [C, S_max] accepting bits, cid 0
        # the synthetic free row. None when the feature is off — every
        # tick then takes the exact pre-constraint code path. The
        # tables are replicated on a mesh (tiny next to the pool) and
        # pinned with the params on a device= server.
        self._ctrans = None
        self._cacc = None
        self._cnames: dict[str, int] = {}
        self._cdfas: list = [None]
        if constraints is not None:
            if eos_id is None:
                raise ValueError(
                    "constraints= requires eos_id: a satisfied "
                    "constraint stops by emitting eos"
                )
            self._cnames, self._ctrans, self._cacc = (
                crt.stack_token_dfas(constraints, cfg.vocab_size)
            )
            if device is not None:
                self._ctrans = jax.device_put(self._ctrans, device)
                self._cacc = jax.device_put(self._cacc, device)
            self._cdfas += [
                constraints[n]
                for n in sorted(self._cnames, key=self._cnames.get)
            ]
        # Per-request constraint failures (hand-built DFA dead ends):
        # rid -> message. The slot finishes cleanly; compiled DFAs
        # never land here (dfa.py prunes dead states).
        self.errors: dict[int, str] = {}
        self.constrained_tokens_n = 0
        self.constraint_dead_ends_n = 0
        self._step = None
        self._insert = None
        self._insert_dyn = None
        self._import = None
        self._mt = None
        self._spill_up = None
        self.spec_k = spec_k
        self.prefill_chunk = prefill_chunk
        self.prefill_budget = prefill_budget
        self.prefill_lookahead = prefill_lookahead
        # Stall/mixed accounting (host mirrors of the obs instruments,
        # for ServerStats snapshots without a registry read):
        # stall ticks = admission-prefill dispatches issued while at
        # least one decode slot sat waiting (always 0 in mixed mode);
        # mixed tokens = prompt tokens carried by fused mixed ticks.
        self.prefill_stall_ticks_n = 0
        self.mixed_prefill_tokens_n = 0
        self.mixed_ticks_n = 0
        self.decode_stall_fraction_last = 0.0
        # Draft lanes (runtime/decode_server.py::DraftLanes): the
        # draft model's flat per-slot K/V plus host position truth.
        self._draft = (
            DraftLanes(spec_draft, spec_params, max_batch, target=dec)
            if spec_k
            else None
        )
        # Host-side speculation totals (the obs counters' mirrors, for
        # ServerStats snapshots without a registry read).
        self.spec_rounds_n = 0
        self.spec_proposed_n = 0
        self.spec_accepted_n = 0
        self.spec_draft_tokens_n = 0
        self.prefix_len = 0
        self.shared_blocks: list[int] = []
        self._prefix_cache = None
        self.radix: PrefixBlockCache | None = None
        self._gather = None
        self.prefill_tokens_saved = 0
        self._spill: HostKVSpill | None = None
        self.spill_hits_n = 0
        if prefix_cache:
            if prefix_ids is not None:
                raise ValueError(
                    "prefix_cache=True subsumes the global prefix_ids "
                    "— pass the system prompt as part of each "
                    "request's prompt and it shares automatically"
                )
            if self.multi_lora:
                raise ValueError(
                    "prefix_cache + multi-LoRA is unsupported: cached "
                    "prefix K/V would be adapter-dependent"
                )
            if spill_bytes:
                self._spill = HostKVSpill(spill_bytes, obs=self.obs)
            self.radix = PrefixBlockCache(
                obs=self.obs,
                on_evict=(
                    self._spill_block if self._spill is not None else None
                ),
            )
        if prefix_ids is not None:
            if self.multi_lora:
                raise ValueError(
                    "prefix caching + multi-LoRA is unsupported: the "
                    "shared prefix K/V would be adapter-dependent"
                )
            if prefix_ids.ndim != 2 or prefix_ids.shape[0] != 1:
                raise ValueError("prefix_ids must be [1, P]")
            P = int(prefix_ids.shape[1])
            if P % block_size:
                raise ValueError(
                    f"shared-prefix paging needs the prefix length "
                    f"({P}) to be a block_size ({block_size}) multiple "
                    "— otherwise a suffix write would land in a "
                    "SHARED block and corrupt every other request"
                )
            if P >= cfg.max_len:
                raise ValueError(
                    f"prefix of {P} leaves no room under max_len "
                    f"{cfg.max_len}"
                )
            n_shared = P // block_size
            if n_shared > len(self.free):
                raise ValueError(
                    f"prefix needs {n_shared} blocks but the pool has "
                    f"{len(self.free)} usable"
                )
            # One prefix prefill through the flat path; its rows
            # become the pool's single shared copy (a skip-0 insert:
            # admissions later use a skip=n_shared insert that can
            # never write the shared blocks).
            from defer_tpu.utils.memo import cached_step

            full_insert = cached_step(
                dec,
                ("paged_insert", block_size, 0, kv_dtype, self._mesh_key),
                lambda: self._build_insert(0),
            )
            fdec = self._sdec if self._sdec is not None else dec
            pre = fdec.init_cache(1)
            _, pre = fdec.make_step()(self.params, pre, prefix_ids)
            self._account_psums(1)
            self.shared_blocks = [
                self.free.pop() for _ in range(n_shared)
            ]
            shared_row = np.zeros((self.MB,), np.int32)
            for j, blk in enumerate(self.shared_blocks):
                shared_row[j] = blk
            self.pool_k, self.pool_v = full_insert(
                self.pool_k,
                self.pool_v,
                pre["k"],
                pre["v"],
                jnp.asarray(shared_row),
            )
            # Keep the contiguous prefix lane for suffix admissions
            # (the suffix prefill needs the prefix rows in the flat
            # layout to attend at offset P).
            self._prefix_cache = pre
            self.prefix_len = P

    # -- public API -------------------------------------------------------

    def submit(
        self,
        prompt_ids: jax.Array,
        num_steps: int,
        *,
        adapter_id: int = 0,
        sampling: Any = None,
        stop: Any = None,
    ) -> int:
        """`sampling` — optional models/gpt.py SamplingParams: the
        slot then samples inside the shared batched tick from its own
        seeded key stream (bit-identical to solo
        `generate(..., rng=jax.random.key(seed))`); None = greedy.
        `stop` — optional multi-token stop sequences (iterable of int
        sequences, runtime/stopping.py): the request finishes the
        moment its GENERATED tail equals any of them, freeing its
        blocks mid-budget."""
        if prompt_ids.ndim != 2 or prompt_ids.shape[0] != 1:
            raise ValueError("submit one request at a time ([1, T])")
        cid = 0
        if sampling is not None:
            sampling.validate()
            # The constraint survives the greedy normalization below:
            # temperature-0 JSON mode is the common case.
            cid = self._resolve_constraint(sampling.constraint)
            if sampling.temperature == 0:
                sampling = None  # greedy: keep the argmax fast path
        stop_seqs = normalize_stops(stop)
        if adapter_id:
            if not self.multi_lora:
                raise ValueError(
                    "adapter_id set but params carry no adapter banks "
                    "(parallel/lora.py::stack_adapters)"
                )
            if not 0 <= adapter_id < self.num_adapters:
                raise ValueError(
                    f"adapter_id {adapter_id} out of range "
                    f"[0, {self.num_adapters})"
                )
        t0 = prompt_ids.shape[1]
        if t0 < 1 or num_steps < 1:
            raise ValueError("need at least 1 prompt token and 1 step")
        # spec_k rows of write headroom: a verify forward at position
        # p writes candidate rows through p + spec_k, and the gathered
        # path's contiguous-lane write must never clamp (clamping
        # would shift real rows). spec_k is 0 when speculation is off.
        if (
            self.prefix_len + t0 + num_steps + self.spec_k
            > self.dec.cfg.max_len
        ):
            extra = (
                f" + spec_k {self.spec_k} headroom" if self.spec_k else ""
            )
            raise ValueError(
                f"prefix {self.prefix_len} + prompt {t0} + steps "
                f"{num_steps}{extra} exceeds max_len "
                f"{self.dec.cfg.max_len}"
            )
        need = self._own_need(t0, num_steps)
        usable = self.num_blocks - 1 - len(self.shared_blocks)
        if need > usable:
            # Not even an empty pool could hold it — waiting would
            # deadlock the queue.
            raise ValueError(
                f"request needs {need} own blocks but the pool has "
                f"{usable} usable beyond the shared prefix"
            )
        rid = self._next_id
        self._next_id += 1
        self.pending.append(
            (rid, prompt_ids, num_steps, adapter_id, sampling,
             stop_seqs, cid)
        )
        self._submit_t[rid] = time.perf_counter()
        return rid

    def _resolve_constraint(self, name: str | None) -> int:
        return crt.resolve_constraint(
            name, self._ctrans, self._cnames, self._cdfas
        )

    def _own_need(self, t0: int, steps: int) -> int:
        """Blocks a request must own: its total span minus the shared
        prefix blocks its table merely points at."""
        total = -(-(self.prefix_len + t0 + steps) // self.bs)
        return total - len(self.shared_blocks)

    def submit_prefilled(
        self,
        prompt_ids: Any,
        num_steps: int,
        *,
        sampling: Any = None,
        stop: Any = None,
    ) -> int:
        """Register a request whose prefill runs ELSEWHERE (a disagg
        prefill worker): the request waits in `pending_prefilled`
        until `deliver_kv` hands over its finished KV blocks, then
        admission seats those blocks directly in the pool — no local
        prefill step. Same sampling/stop semantics as `submit`.

        Restricted to the base model without a global shared prefix:
        externally computed K/V can't be checked against a
        constructor-level `prefix_ids` lane, and adapter-specific K/V
        from a base-model worker would silently skew LoRA requests.
        (`prefix_cache=True` composes fine — ingested full prompt
        blocks register in the radix cache like locally prefilled
        ones. `spec_k>0` composes too: the TARGET K/V arrives over
        the wire, and admission re-prefills the DRAFT lane locally
        from the prompt ids — draft prefill is the cheap side of the
        asymmetry, so decode-worker speculation keeps the disagg
        split's point.)"""
        if self.pp > 1:
            raise ValueError(
                "disagg ingest (submit_prefilled/deliver_kv) does not "
                "compose with pp_stages > 1 yet: delivered KV blocks "
                "target a monolithic pool, not per-stage slices. Fix: "
                "point the prefill worker at a pp_stages=1 decode "
                "server, or submit() so prefill runs through the "
                "stage chain."
            )
        if self.shared_blocks or self.prefix_len:
            raise ValueError(
                "externally prefilled admission does not compose with "
                "constructor-level prefix_ids; use prefix_cache=True"
            )
        if self.multi_lora:
            raise ValueError(
                "externally prefilled admission supports the base "
                "model only (adapter-specific K/V would need the "
                "worker to run the same adapter banks)"
            )
        prompt = np.asarray(prompt_ids)
        if prompt.ndim != 2 or prompt.shape[0] != 1:
            raise ValueError("submit one request at a time ([1, T])")
        cid = 0
        if sampling is not None:
            sampling.validate()
            cid = self._resolve_constraint(sampling.constraint)
            if sampling.temperature == 0:
                sampling = None
        stop_seqs = normalize_stops(stop)
        t0 = prompt.shape[1]
        if t0 < 1 or num_steps < 1:
            raise ValueError("need at least 1 prompt token and 1 step")
        # Same spec_k write headroom as submit(): verify forwards
        # write candidate rows past the committed position.
        if t0 + num_steps + self.spec_k > self.dec.cfg.max_len:
            extra = (
                f" + spec_k {self.spec_k} headroom" if self.spec_k else ""
            )
            raise ValueError(
                f"prompt {t0} + steps {num_steps}{extra} exceeds "
                f"max_len {self.dec.cfg.max_len}"
            )
        need = self._own_need(t0, num_steps)
        usable = self.num_blocks - 1
        if need > usable:
            raise ValueError(
                f"request needs {need} blocks but the pool has "
                f"{usable} usable"
            )
        rid = self._next_id
        self._next_id += 1
        self.pending_prefilled[rid] = {
            "prompt": prompt.astype(np.int32),
            "steps": num_steps,
            "samp": sampling,
            "stop": stop_seqs,
            "cid": cid,
            "kv": None,
        }
        self._prefilled_order.append(rid)
        self._submit_t[rid] = time.perf_counter()
        return rid

    def deliver_kv(
        self,
        rid: int,
        k_blocks: np.ndarray,
        v_blocks: np.ndarray,
        first_logits: np.ndarray,
    ) -> None:
        """Hand a pending_prefilled request its finished KV state:
        [L, n_blocks, Hkv, bs, Dh] K/V block stacks covering the
        prompt rows, plus the [1, V] logits row of the last prompt
        position (the first generated token is sampled from it).
        Thread-safe against the run loop: this only assigns the
        entry's "kv" slot (one atomic dict write); the pool itself is
        touched exclusively by `_admit` on the serving thread."""
        entry = self.pending_prefilled.get(rid)
        if entry is None:
            raise KeyError(f"no pending prefilled request {rid}")
        t0 = entry["prompt"].shape[1]
        n_need = -(-t0 // self.bs)
        cfg = self.dec.cfg
        expect = (
            cfg.num_layers,
            n_need,
            cfg.kv_heads,
            self.bs,
            cfg.dim // cfg.num_heads,
        )
        if tuple(k_blocks.shape) != expect or tuple(v_blocks.shape) != expect:
            raise ValueError(
                f"KV block stack shape {tuple(k_blocks.shape)}/"
                f"{tuple(v_blocks.shape)} != expected {expect} for "
                f"rid {rid} (t0={t0}, block_size={self.bs})"
            )
        if first_logits.shape != (1, cfg.vocab_size):
            raise ValueError(
                f"first_logits shape {tuple(first_logits.shape)} != "
                f"(1, {cfg.vocab_size})"
            )
        entry["kv"] = (k_blocks, v_blocks, first_logits)

    def run(self) -> dict[int, jax.Array]:
        while self.pending or self.pending_prefilled or any(self.slots):
            self._admit()
            if not any(s is not None for s in self.slots):
                if self.pending_prefilled:
                    # Nothing seated and at least one request is
                    # waiting on EXTERNAL KV delivery — yield instead
                    # of spinning the admit/tick loop hot.
                    time.sleep(1e-3)
                continue
            self._tick()
        return self.done

    @property
    def blocks_in_use(self) -> int:
        if self.radix is not None:
            # Exact pool accounting: everything that is neither free
            # nor parked at refcount 0 is held by an active request
            # (shared blocks counted once, however many slots point at
            # them).
            return (
                (self.num_blocks - 1)
                - len(self.free)
                - len(self.radix.lru)
            )
        return sum(len(s["blocks"]) for s in self.slots if s)

    def resident_digests(self) -> tuple[int, frozenset[bytes]]:
        """Routing advertisement passthrough (PrefixBlockCache
        docstring); (0, empty) without prefix_cache=True so fleet
        callers need no radix check."""
        if self.radix is None:
            return 0, frozenset()
        return self.radix.resident_digests()

    def export_prefix_blocks(
        self, keys: list[bytes]
    ) -> tuple[list[bytes], np.ndarray, np.ndarray] | None:
        """Copy a resident prefix chain OUT of the pool for migration:
        `keys` is a root-anchored run of chained digests (the router's
        walk order); returns (own-block token bytes per block,
        [L, n, Hkv, bs, Dh] K and V block stacks) or None if any key
        was evicted since the advertisement the caller routed on.

        SERVING-THREAD ONLY: the decode step donates the pool buffers,
        so a reader on any other thread can observe an invalidated
        buffer mid-tick. Fleet replicas run this as an ops-queue
        command between ticks. The copy is host-side and
        self-contained — once returned, eviction on this replica
        cannot hurt the importer."""
        if self.radix is None:
            raise ValueError("export needs prefix_cache=True")
        blks: list[int] = []
        toks: list[bytes] = []
        for key in keys:
            blk = self.radix.by_key.get(key)
            if blk is None:
                return None  # evicted since the advert; stale route
            blks.append(blk)
            toks.append(self.radix.tok_of[blk])
        # analysis: ignore[host-sync-in-hot-loop] host-side block-id
        # list becoming device gather indices — no device readback
        idx = jnp.asarray(np.asarray(blks, np.int32))
        if isinstance(self.pool_k, dict):
            # int8 pools dequantize before export: the migration wire
            # format stays the compute-dtype block stack regardless of
            # either end's kv_dtype.
            kd = dequantize_symmetric(
                self.pool_k["q"][:, idx],
                self.pool_k["s"][:, idx][..., None, None],
                self.dec.compute_dtype,
            )
            vd = dequantize_symmetric(
                self.pool_v["q"][:, idx],
                self.pool_v["s"][:, idx][..., None, None],
                self.dec.compute_dtype,
            )
        else:
            kd = self.pool_k[:, idx]
            vd = self.pool_v[:, idx]
        # analysis: ignore[host-sync-in-hot-loop] deliberate sync — a
        # migration ships the payload over a host wire, so the copy to
        # host memory IS the operation
        k = np.asarray(kd)
        # analysis: ignore[host-sync-in-hot-loop] second half of the
        # same deliberate migration copy
        v = np.asarray(vd)
        return toks, k, v

    def _shard_ingest(self, arr) -> jax.Array:
        """Device placement for full-head host K/V entering the pool
        (migration imports, disagg wire blobs, flat-lane inserts). On a
        mesh the array is SPLIT ON ITS HEAD AXIS (index 2 — shared by
        the [L, n, Hkv, bs, Dh] block-stack and [L, 1, Hkv, S, Dh]
        lane layouts) as it lands on device, so each shard receives
        only its local heads and the wire/lane format never changes.
        3-D arrays are int8 block SCALES ([L, n, Hkv]) — same head
        axis, scale-rank spec. On a pinned single device it lands
        there; otherwise this is plain jnp.asarray."""
        if self.mesh is not None:
            from jax.sharding import NamedSharding

            spec = (
                self._head_spec
                if getattr(arr, "ndim", 5) == 3
                else self._pool_spec
            )
            return jax.device_put(arr, NamedSharding(self.mesh, spec))
        if self.device is not None:
            return jax.device_put(arr, self.device)
        return jnp.asarray(arr)

    def _ensure_import(self):
        if self._import is None:
            from defer_tpu.utils.memo import cached_step

            def build():
                def imp(pk, pv, k_blocks, v_blocks, dest):
                    # Pad entries in dest are 0: duplicate writes to
                    # trash block 0 race over garbage, by the module
                    # invariant.
                    if isinstance(pk, dict):
                        # Imported stacks arrive compute-dtype on the
                        # wire; quantize per (layer, block, head) as
                        # they land (imported blocks are always FULL —
                        # every row real prompt content).
                        kq, ks = _quantize_blocks(k_blocks)
                        vq, vs = _quantize_blocks(v_blocks)
                        pk = {
                            "q": pk["q"].at[:, dest].set(kq),
                            "s": pk["s"].at[:, dest].set(ks),
                        }
                        pv = {
                            "q": pv["q"].at[:, dest].set(vq),
                            "s": pv["s"].at[:, dest].set(vs),
                        }
                    else:
                        pk = pk.at[:, dest].set(k_blocks)
                        pv = pv.at[:, dest].set(v_blocks)
                    return self._pool_constraint(pk, pv)

                return jax.jit(imp, donate_argnums=(0, 1))

            self._import = cached_step(
                self.dec,
                ("fleet_import", self.bs, self.kv_dtype, self._mesh_key),
                build,
            )
        return self._import

    def import_prefix_blocks(
        self,
        toks: list[bytes],
        k_blocks: np.ndarray,
        v_blocks: np.ndarray,
    ) -> int:
        """Seat a migrated prefix chain (export_prefix_blocks payload)
        in this pool as PARKED radix entries — the next admission
        sharing the prefix revives them through the normal walk, no
        re-prefill. Chained digests are recomputed HERE from the token
        bytes (never trusted from the wire), so a corrupted payload
        mis-keys into digests nothing will ever look up, not into
        another chain. Already-resident leading blocks are skipped;
        allocation evicts parked LRU blocks under pressure and
        truncates the (deep) tail when the pool still can't cover it —
        the shallow end is the reusable end. Returns blocks imported.

        SERVING-THREAD ONLY, same donation rule as export."""
        if self.radix is None:
            raise ValueError("import needs prefix_cache=True")
        n = len(toks)
        cfg = self.dec.cfg
        expect = (
            cfg.num_layers, n, cfg.kv_heads, self.bs,
            cfg.dim // cfg.num_heads,
        )
        if tuple(k_blocks.shape) != expect or tuple(v_blocks.shape) != expect:
            raise ValueError(
                f"prefix block stack shape {tuple(k_blocks.shape)}/"
                f"{tuple(v_blocks.shape)} != expected {expect}"
            )
        keys: list[bytes] = []
        prev = b""
        for bb in toks:
            prev = PrefixBlockCache._hash(prev, bb)
            keys.append(prev)
        # Skip the already-resident leading run (tok-guarded, same
        # collision discipline as walk()).
        m = 0
        while m < n:
            blk = self.radix.by_key.get(keys[m])
            if blk is None or self.radix.tok_of[blk] != toks[m]:
                break
            m += 1
        if m == n:
            return 0
        need = n - m
        if need > len(self.free):
            self.free.extend(self.radix.evict(need - len(self.free)))
        take = min(need, len(self.free))
        if take == 0:
            return 0
        own = [self.free.pop() for _ in range(take)]
        # Pow2-pad the imported span (capped at MB) so migration draws
        # from the same bounded compile-shape set as prefill; pad dest
        # entries point at trash block 0.
        n_pad = 1 << max(take - 1, 0).bit_length()
        n_pad = min(max(n_pad, 1), self.MB)
        dest = np.zeros((n_pad,), np.int32)
        dest[:take] = own
        kb = np.ascontiguousarray(k_blocks[:, m : m + take])
        vb = np.ascontiguousarray(v_blocks[:, m : m + take])
        if n_pad > take:
            pad = np.zeros(
                (expect[0], n_pad - take, *expect[2:]), kb.dtype
            )
            kb = np.concatenate([kb, pad], axis=1)
            vb = np.concatenate([vb, pad], axis=1)
        imp = self._ensure_import()
        self.pool_k, self.pool_v = imp(
            self.pool_k,
            self.pool_v,
            self._shard_ingest(kb.astype(self.dec.compute_dtype)),
            self._shard_ingest(vb.astype(self.dec.compute_dtype)),
            jnp.asarray(dest),
        )
        for j, blk in enumerate(own):
            displaced = self.radix.register(keys[m + j], toks[m + j], blk)
            if displaced is not None:
                self.free.append(displaced)
        # Park deepest-first (matches _finish): LRU then evicts the
        # deep end of the chain before its shallow prerequisites.
        for blk in reversed(own):
            self.radix.release(blk)
        self._update_pool_gauges()
        return take

    # -- internals --------------------------------------------------------

    def _build(self):
        if self.pp > 1:
            # Pipeline-parallel servers never run the monolithic tick
            # /insert programs: every forward goes through the stage
            # chain (_tick_pp / _prefill_paged), whose programs the
            # stages own.
            return
        if self._step is not None:
            return
        # Memoized ON THE DECODER (utils/memo.py): jit's cache is keyed
        # on the function object, so per-server closures would re-trace
        # and re-compile on every new server over the same decoder
        # (e.g. back-to-back bench runs).
        from defer_tpu.utils.memo import cached_step

        builders = {
            "gathered": self._build_step,
            "blockwise": self._build_step_blockwise,
            "pallas": self._build_step_pallas,
        }
        self._step = cached_step(
            self.dec,
            (
                "paged_step", self.bs, self.attention, self.kv_dtype,
                self._mesh_key,
            ),
            builders[self.attention],
        )
        skip = len(self.shared_blocks)
        self._insert = cached_step(
            self.dec,
            ("paged_insert", self.bs, skip, self.kv_dtype, self._mesh_key),
            lambda: self._build_insert(skip),
        )
        if self.radix is not None and self._gather is None:
            self._gather = cached_step(
                self.dec,
                ("paged_gather", self.bs, self.kv_dtype, self._mesh_key),
                self._build_gather,
            )
            self._insert_dyn = cached_step(
                self.dec,
                (
                    "paged_insert_dyn", self.bs, self.kv_dtype,
                    self._mesh_key,
                ),
                self._build_insert_dynamic,
            )

    def _tp_axis(self):
        """The tp_axis threaded into the tick bodies: the mesh's model
        axis when serving sharded, None otherwise — with None every
        body traces EXACTLY the single-device program (the mesh=None
        bit-identity contract)."""
        return self.model_axis if self.mesh is not None else None

    def _flat_dec(self):
        """The decoder whose contiguous-lane (flat) prefill programs
        this server dispatches: on a mesh the memoized SpmdGptDecoder
        view — its make_step/init_cache produce head-sharded lanes the
        insert programs consume shard-local — otherwise the user's
        decoder, unchanged."""
        return self._sdec if self._sdec is not None else self.dec

    def _account_kv_rows(self, rows_read: int, baseline: int) -> None:
        """Publish one dispatch's KV-row traffic. On a mesh both
        counters report PER-SHARD traffic: each device reads only its
        kv_heads/tp local heads, so rows scale by 1/model-axis-size
        (the counter-pinned TP contract; the read/baseline ratio still
        isolates the blockwise/pallas win because both sides scale)."""
        tp = self.tp
        self.obs.kv_rows_read.inc(rows_read // tp)
        self.obs.kv_rows_gathered.inc(baseline // tp)
        self.obs.kv_rows_last.set(rows_read // tp)

    def _account_psums(self, n_forwards: int) -> None:
        """Count the cross-shard collectives `n_forwards` sharded
        transformer forwards issue (per forward: attn + ffn psum per
        layer, the embedding psum, the final-logits all_gather).
        Host-side mirror of the traced program — no-op on mesh=None,
        where no collective exists."""
        if self._psums_per_fwd:
            n = self._psums_per_fwd * n_forwards
            self.tp_psums += n
            self.obs.tp_psums.inc(n)

    def _jit_tick(self, body, n_rep: int):
        """jit one of the raw tick bodies `(params, pk, pv, *rest) ->
        (out_tree..., pk, pv)`-shaped as `(logits, pk, pv)`. On a mesh
        the body is wrapped in shard_map first: params by the Megatron
        specs, the two pool operands on the KV-head axis, the `n_rep`
        trailing host-fed operands (tables, positions, ids, ...)
        replicated. Logits come back replicated — the body ends in a
        tiled all_gather of the vocab-sharded slices — so sampling
        stays on post-psum logits and check_rep must be off (the
        checker cannot infer the gather's replication)."""
        if self.mesh is None:
            return jax.jit(body, donate_argnums=(1, 2))
        from jax.sharding import PartitionSpec as PSpec

        from defer_tpu.utils.compat import shard_map

        pool, r = self._pool_specs, PSpec()
        sm = shard_map(
            body,
            self.mesh,
            in_specs=(self._sdec._specs(), pool, pool) + (r,) * n_rep,
            out_specs=(r, pool, pool),
            # analysis: ignore[shard-spec] body ends in slot scatters whose replication the checker cannot infer; psum placement is pinned by the defer_tp_psum_total mirror instead
            check_rep=False,
        )
        return jax.jit(sm, donate_argnums=(1, 2))

    def _replicate_logits(self, logits):
        """Inside a shard_map tick body: turn this shard's vocab slice
        [B, T, Vpad/tp] into the full replicated [B, T, V] logits
        (concatenate the slices, drop the vocab padding). Identity on
        mesh=None."""
        if self.mesh is None:
            return logits
        logits = lax.all_gather(
            logits, self.model_axis, axis=-1, tiled=True
        )
        return logits[..., : self.dec.cfg.vocab_size]

    def _build_step(self):
        return self._jit_tick(self._step_body(), n_rep=4)

    def _step_body(self):
        """The RAW (unjitted) gathered-attention step body — jitted
        standalone for the K=1 tick (_build_step) and traced inside
        the fused-window scan (_build_window) for decode_window > 1,
        so both paths run identical math by construction."""
        dec, bs = self.dec, self.bs
        tp = self._tp_axis()

        def step(params, pk, pv, tables, pos, ids, adapter_ids):
            b = ids.shape[0]
            x = dec._embed_tokens(params, ids, pos, tp)
            rows = jnp.arange(b)

            def body(carry, layer):
                x = carry
                p, pk_l, pv_l = layer  # [NB, Hkv, bs, Dh]
                # Gather this slot's pages into the contiguous view
                # the flat block math expects: [B, Hkv, MB*bs, Dh].
                # An int8 pool dequantizes AT the gather (scale folds
                # into the block values), so _block sees fp blocks.
                kc = _pool_gather(pk_l, tables, dec.compute_dtype)
                vc = _pool_gather(pv_l, tables, dec.compute_dtype)
                b_, mb, hkv, _, dh = kc.shape
                kc = kc.transpose(0, 2, 1, 3, 4).reshape(
                    b_, hkv, mb * bs, dh
                )
                vc = vc.transpose(0, 2, 1, 3, 4).reshape(
                    b_, hkv, mb * bs, dh
                )
                out, kc, vc = dec._block(
                    p, x, kc, vc, pos, tp_axis=tp,
                    adapter_ids=adapter_ids,
                )
                # Scatter ONLY the new row back to its page.
                blk = tables[rows, pos // bs]  # [B]
                row = pos % bs
                new_k = kc[rows, :, pos, :]  # [B, Hkv, Dh]
                new_v = vc[rows, :, pos, :]
                pk_l = _pool_write_rows(pk_l, blk, row, new_k)
                pv_l = _pool_write_rows(pv_l, blk, row, new_v)
                return out, (pk_l, pv_l)

            x, (pk, pv) = lax.scan(
                body, x, (params["stack"], pk, pv)
            )
            logits = self._replicate_logits(dec._final_logits(params, x))
            return logits, pk, pv

        return step

    def _build_step_blockwise(self):
        return self._jit_tick(self._step_body_blockwise(), n_rep=4)

    def _step_body_blockwise(self):
        """The block-native pure-XLA step: same embed/projection/FFN
        code as the gathered step (GptDecoder._attn_qkv/_attn_out, so
        the new K/V rows are bit-identical), but attention folds pool
        blocks through the block table directly — no contiguous
        [B, Hkv, MB*bs, Dh] copy is ever materialized, and the fold
        stops at the deepest live block across the batch. The new row
        is scattered into the pool BEFORE attention (write-then-attend,
        like the flat path), through the same (blk, row) indices as
        the gathered path's scatter-back — idle slots write trash
        block 0 row 0, the module invariant."""
        dec, bs = self.dec, self.bs
        window = dec.cfg.window
        tp = self._tp_axis()

        def step(params, pk, pv, tables, pos, ids, adapter_ids):
            b = ids.shape[0]
            x = dec._embed_tokens(params, ids, pos, tp)
            rows = jnp.arange(b)
            blk_w = tables[rows, pos // bs]  # [B]
            row_w = pos % bs
            # Deepest live block over the batch: the fold's traced
            # bound — reads scale with actual depth, not pool size.
            nb_live = jnp.max(pos) // bs + 1

            def body(carry, layer):
                x = carry
                p, pk_l, pv_l = layer  # [NB, Hkv, bs, Dh]
                q, k_new, v_new = dec._attn_qkv(
                    p, x, pos, adapter_ids=adapter_ids
                )
                pk_l = _pool_write_rows(pk_l, blk_w, row_w, k_new[:, :, 0, :])
                pv_l = _pool_write_rows(pv_l, blk_w, row_w, v_new[:, :, 0, :])
                attn = _blockwise_attend(
                    q, pk_l, pv_l, tables, pos, bs, nb_live, window
                )
                out = dec._attn_out(
                    p, x, attn, tp, adapter_ids=adapter_ids
                )
                return out, (pk_l, pv_l)

            x, (pk, pv) = lax.scan(
                body, x, (params["stack"], pk, pv)
            )
            logits = self._replicate_logits(dec._final_logits(params, x))
            return logits, pk, pv

        return step

    def _build_step_pallas(self):
        return self._jit_tick(self._step_body_pallas(), n_rep=4)

    def _step_body_pallas(self):
        """The kernel variant of the block-native step: attention goes
        through ops/pallas_attention.py::paged_flash_decode, whose
        index maps resolve the block table inside the kernel grid —
        per slot only its OWN live blocks are DMAed. Compiles to
        Mosaic on a real TPU; anywhere else the kernel runs through
        the pallas interpreter (functionally identical, slow — the CI
        parity test rides the `slow` marker)."""
        from defer_tpu.models.gpt import _flash_decode_mode
        from defer_tpu.ops.pallas_attention import paged_flash_decode

        dec, bs = self.dec, self.bs
        window = dec.cfg.window
        interpret = _flash_decode_mode() != "tpu"
        tp = self._tp_axis()

        def step(params, pk, pv, tables, pos, ids, adapter_ids):
            b = ids.shape[0]
            x = dec._embed_tokens(params, ids, pos, tp)
            rows = jnp.arange(b)
            blk_w = tables[rows, pos // bs]
            row_w = pos % bs

            def body(carry, layer):
                x = carry
                p, pk_l, pv_l = layer
                q, k_new, v_new = dec._attn_qkv(
                    p, x, pos, adapter_ids=adapter_ids
                )
                pk_l = _pool_write_rows(pk_l, blk_w, row_w, k_new[:, :, 0, :])
                pv_l = _pool_write_rows(pv_l, blk_w, row_w, v_new[:, :, 0, :])
                b_, hq, _, dh = q.shape
                quantized = isinstance(pk_l, dict)
                attn = paged_flash_decode(
                    q[:, :, 0, :],
                    _pool_arr(pk_l),
                    _pool_arr(pv_l),
                    tables,
                    pos,
                    window=window,
                    interpret=interpret,
                    scale_k=pk_l["s"] if quantized else None,
                    scale_v=pv_l["s"] if quantized else None,
                )  # [B, Hq, Dh]
                attn = attn.astype(x.dtype).reshape(b_, 1, hq * dh)
                out = dec._attn_out(
                    p, x, attn, tp, adapter_ids=adapter_ids
                )
                return out, (pk_l, pv_l)

            x, (pk, pv) = lax.scan(
                body, x, (params["stack"], pk, pv)
            )
            logits = self._replicate_logits(dec._final_logits(params, x))
            return logits, pk, pv

        return step

    def _ensure_mt(self):
        """The multi-token paged step (speculative verify forwards and
        chunked pool-native prefill share it): built lazily, memoized
        on the decoder like every other paged program. One memo entry
        per attention mode; jit then caches per (B, T) shape — the
        spec path runs a single (max_batch, k+1) trace in steady
        state, prefill chunks a single (1, chunk) trace plus pow2
        tails."""
        if self._mt is None:
            from defer_tpu.utils.memo import cached_step

            self._mt = cached_step(
                self.dec,
                (
                    "paged_mt", self.bs, self.attention, self.kv_dtype,
                    self._mesh_key,
                ),
                lambda: self._jit_tick(self._mt_body(), n_rep=6),
            )
        return self._mt

    def _mt_body(self):
        """The RAW multi-token paged step: T tokens per slot in one
        forward, reading K/V through the block table and scattering
        all T new rows back in one multi-row write.

        step(params, pk, pv, tables, pos, ids [B, T], n_keep [B],
        keep_from [B], adapter_ids) -> (logits [B, T, V], pk, pv).

        Row t of slot b sits at absolute position pos[b] + t. The
        write DESTINATION redirects to trash block 0 (the module
        invariant) for any row the slot is not keeping: row index
        >= n_keep[b] (a sampled slot keeps only its first row during a
        speculative round, an idle slot none, a prefill tail's pad
        rows none) or absolute position < keep_from[b] (radix HIT
        blocks are other requests' memory — same rule as the
        dynamic-skip insert). Speculative candidate rows ARE kept:
        accepted ones become committed history, rejected ones go
        stale behind the position mask and the next round's verify
        span rewrites them — the dead-write idiom, no second pass.

        Attention per mode mirrors the single-token step bodies:
        gathered runs GptDecoder._block on the contiguous pool view
        (bit-exact reference — row 0's logits are bit-identical to
        the K=1 tick's, which is what pins spec greedy parity);
        blockwise folds the pool through _blockwise_attend_mt;
        pallas calls the block-table-indexed prefill kernel
        (ops/pallas_attention.py::paged_flash_prefill)."""
        dec, bs = self.dec, self.bs
        attention = self.attention
        window = dec.cfg.window
        tp = self._tp_axis()
        if attention == "pallas":
            from defer_tpu.models.gpt import _flash_decode_mode
            from defer_tpu.ops.pallas_attention import (
                paged_flash_prefill,
            )

            interpret = _flash_decode_mode() != "tpu"

        def step(
            params, pk, pv, tables, pos, ids, n_keep, keep_from,
            adapter_ids,
        ):
            b, t = ids.shape
            mb = tables.shape[1]
            rows = jnp.arange(b)
            steps_t = jnp.arange(t)
            pvec = pos[:, None] + steps_t[None, :]  # [B, T]
            # Write destinations: each row's (block, row-in-block),
            # with dropped rows redirected to trash block 0. The
            # block-column clamp keeps headroom rows past the table
            # (only reachable for dead writes) in range.
            blk = tables[
                rows[:, None], jnp.minimum(pvec // bs, mb - 1)
            ]  # [B, T]
            keep = (steps_t[None, :] < n_keep[:, None]) & (
                pvec >= keep_from[:, None]
            )
            dest = jnp.where(keep, blk, 0)
            rowi = pvec % bs
            x = dec._embed_tokens(params, ids, pos, tp)

            if attention == "gathered":

                def body(carry, layer):
                    x = carry
                    p, pk_l, pv_l = layer
                    kc = _pool_gather(pk_l, tables, dec.compute_dtype)
                    vc = _pool_gather(pv_l, tables, dec.compute_dtype)
                    b_, mb_, hkv, _, dh = kc.shape
                    kc = kc.transpose(0, 2, 1, 3, 4).reshape(
                        b_, hkv, mb_ * bs, dh
                    )
                    vc = vc.transpose(0, 2, 1, 3, 4).reshape(
                        b_, hkv, mb_ * bs, dh
                    )
                    out, kc, vc = dec._block(
                        p, x, kc, vc, pos, tp_axis=tp,
                        adapter_ids=adapter_ids,
                    )
                    # Multi-row scatter-back: T fresh rows per slot.
                    new_k = kc[rows[:, None], :, pvec, :]
                    new_v = vc[rows[:, None], :, pvec, :]
                    pk_l = _pool_write_rows_mt(pk_l, dest, rowi, new_k)
                    pv_l = _pool_write_rows_mt(pv_l, dest, rowi, new_v)
                    return out, (pk_l, pv_l)

            elif attention == "blockwise":

                def body(carry, layer):
                    x = carry
                    p, pk_l, pv_l = layer
                    q, k_new, v_new = dec._attn_qkv(
                        p, x, pos, adapter_ids=adapter_ids
                    )  # q [B,Hq,T,Dh]; k/v_new [B,Hkv,T,Dh]
                    # Write-then-attend, like every paged step.
                    pk_l = _pool_write_rows_mt(
                        pk_l, dest, rowi, k_new.transpose(0, 2, 1, 3)
                    )
                    pv_l = _pool_write_rows_mt(
                        pv_l, dest, rowi, v_new.transpose(0, 2, 1, 3)
                    )
                    nb_live = jnp.minimum(
                        (jnp.max(pos) + t - 1) // bs + 1, mb
                    )
                    attn = _blockwise_attend_mt(
                        q, pk_l, pv_l, tables, pos, bs, nb_live,
                        window,
                    )
                    out = dec._attn_out(
                        p, x, attn, tp, adapter_ids=adapter_ids
                    )
                    return out, (pk_l, pv_l)

            else:  # pallas

                def body(carry, layer):
                    x = carry
                    p, pk_l, pv_l = layer
                    q, k_new, v_new = dec._attn_qkv(
                        p, x, pos, adapter_ids=adapter_ids
                    )
                    pk_l = _pool_write_rows_mt(
                        pk_l, dest, rowi, k_new.transpose(0, 2, 1, 3)
                    )
                    pv_l = _pool_write_rows_mt(
                        pv_l, dest, rowi, v_new.transpose(0, 2, 1, 3)
                    )
                    b_, hq, t_, dh = q.shape
                    quantized = isinstance(pk_l, dict)
                    attn = paged_flash_prefill(
                        q,
                        _pool_arr(pk_l),
                        _pool_arr(pv_l),
                        tables,
                        pos,
                        window=window,
                        scale_k=pk_l["s"] if quantized else None,
                        scale_v=pv_l["s"] if quantized else None,
                        interpret=interpret,
                    )  # [B, Hq, T, Dh]
                    attn = (
                        attn.transpose(0, 2, 1, 3)
                        .reshape(b_, t_, hq * dh)
                        .astype(x.dtype)
                    )
                    out = dec._attn_out(
                        p, x, attn, tp, adapter_ids=adapter_ids
                    )
                    return out, (pk_l, pv_l)

            x, (pk, pv) = lax.scan(
                body, x, (params["stack"], pk, pv)
            )
            logits = self._replicate_logits(dec._final_logits(params, x))
            return logits, pk, pv

        return step

    def _build_window(self, mode: str):
        """The fused K-sub-step paged decode program for one sampling
        mode ("argmax" | "nosort" | "sort" — the bit-identical trio
        SlotSampler.draw switches between, picked per window). A
        `lax.scan` over the raw step body (_step_body*) advances every
        row K times per host dispatch; each sub-step zeroes frozen
        rows' position and block-table row (their writes land in trash
        block 0 row 0, the idle-slot invariant), samples on device,
        counts the token against the row's budget, and freezes rows
        that hit eos or budget for the REST of the window. Fixed
        length K — trace-stable regardless of where rows finish.
        Memoized on the decoder (utils/memo.cached_step), where
        analysis/sanitizer.py auto-watches for retraces."""
        from defer_tpu.utils.memo import cached_step

        K = self.decode_window
        eos = self.eos_id
        bodies = {
            "gathered": self._step_body,
            "blockwise": self._step_body_blockwise,
            "pallas": self._step_body_pallas,
        }
        body_builder = bodies[self.attention]

        def build():
            raw = body_builder()

            def window(params, pk, pv, tables, pos, feed, active,
                       keys, temp, topk, topp, minp, budget,
                       adapter_ids):
                def body(carry, _):
                    pk, pv, pos, feed, active, keys, n = carry
                    # Frozen/idle rows: position 0 + all-trash table,
                    # exactly the state _finish leaves a K=1 slot in.
                    pos_eff = jnp.where(active, pos, 0)
                    tab_eff = jnp.where(active[:, None], tables, 0)
                    logits, pk, pv = raw(
                        params, pk, pv, tab_eff, pos_eff, feed,
                        adapter_ids,
                    )
                    ll = logits[:, -1, :]
                    if mode == "argmax":
                        nxt = jnp.argmax(ll, axis=-1)
                    elif mode == "nosort":
                        nxt, keys = sample_token_batched_nosort(
                            ll, keys, temp, minp
                        )
                    else:
                        nxt, keys = sample_token_batched(
                            ll, keys, temp, topk, topp, minp
                        )
                    adv = active.astype(jnp.int32)
                    pos = pos + adv
                    n = n + adv
                    alive = active & (n < budget)
                    if eos is not None:
                        alive = alive & (nxt != eos)
                    feed = nxt[:, None].astype(jnp.int32)
                    return (pk, pv, pos, feed, alive, keys, n), nxt

                init = (
                    pk, pv, pos, feed, active, keys,
                    jnp.zeros_like(budget),
                )
                (pk, pv, pos, feed, alive, keys, n), toks = lax.scan(
                    body, init, None, length=K
                )
                return pk, pv, feed, alive, keys, n, toks.T

            if self.mesh is None:
                return jax.jit(window, donate_argnums=(1, 2))
            # Sharded window: the whole K-sub-step scan runs inside
            # ONE shard_map — per sub-step the raw body all_gathers
            # its vocab slices, so sampling sees replicated post-psum
            # logits and every shard advances the identical feed/keys
            # state (sampler inputs are replicated operands).
            from jax.sharding import PartitionSpec as PSpec

            from defer_tpu.utils.compat import shard_map

            pool, r = self._pool_specs, PSpec()
            sm = shard_map(
                window,
                self.mesh,
                in_specs=(self._sdec._specs(), pool, pool)
                + (r,) * 11,
                out_specs=(pool, pool, r, r, r, r, r),
                # analysis: ignore[shard-spec] same as _jit_tick: scatter-heavy body, replication pinned by the psum mirror
                check_rep=False,
            )
            return jax.jit(sm, donate_argnums=(1, 2))

        return cached_step(
            self.dec,
            ("paged_window", self.bs, self.attention, self.kv_dtype,
             K, mode, eos, self._mesh_key),
            build,
        )

    def _build_window_c(self, mode: str):
        """Constrained variant of the fused paged window: the same
        scan skeleton plus the per-sub-step DFA gather/mask-fold/state
        advance (constrain/runtime.py). A SEPARATE memo key — the
        unconstrained program stays byte-identical to pre-constraint
        builds, and a constrained server pays this trace only while a
        constrained row is live (_tick_window dispatch). On a mesh the
        DFA tables ride in as replicated operands (tiny next to the
        pool) so every shard advances identical constraint state.
        Extra outputs: final DFA states, per-row dead-end flags
        (hand-built DFAs only; the forced-eos token is dropped on
        drain) and the [B, K] masked-fraction buffer for obs."""
        from defer_tpu.utils.memo import cached_step

        K = self.decode_window
        eos = self.eos_id
        bodies = {
            "gathered": self._step_body,
            "blockwise": self._step_body_blockwise,
            "pallas": self._step_body_pallas,
        }
        body_builder = bodies[self.attention]

        def build():
            raw = body_builder()

            def window(params, pk, pv, tables, pos, feed, active,
                       keys, temp, topk, topp, minp, budget,
                       adapter_ids, cid, cstate, ctrans, cacc):
                cvec = cid > 0

                def body(carry, _):
                    (pk, pv, pos, feed, active, keys, n, cstate,
                     died) = carry
                    pos_eff = jnp.where(active, pos, 0)
                    tab_eff = jnp.where(active[:, None], tables, 0)
                    logits, pk, pv = raw(
                        params, pk, pv, tab_eff, pos_eff, feed,
                        adapter_ids,
                    )
                    ll = logits[:, -1, :]
                    crow, acc = crt.constrain_rows(
                        ctrans, cacc, cid, cstate
                    )
                    cmask = crt.constrain_mask(crow, acc, eos)
                    dead = cvec & active & ~cmask.any(-1)
                    ll = crt.fold_mask(ll, cmask)
                    if mode == "argmax":
                        nxt = jnp.argmax(ll, axis=-1)
                    elif mode == "nosort":
                        nxt, keys = sample_token_batched_nosort(
                            ll, keys, temp, minp
                        )
                    else:
                        nxt, keys = sample_token_batched(
                            ll, keys, temp, topk, topp, minp
                        )
                    nxt = jnp.where(dead, eos, nxt)
                    cstate = crt.advance_state(
                        crow, cstate, nxt, cvec & ~dead
                    )
                    frac = crt.masked_frac(cmask, cvec & active)
                    adv = active.astype(jnp.int32)
                    pos = pos + adv
                    n = n + adv
                    alive = active & (n < budget) & (nxt != eos)
                    feed = nxt[:, None].astype(jnp.int32)
                    carry = (
                        pk, pv, pos, feed, alive, keys, n, cstate,
                        died | dead,
                    )
                    return carry, (nxt, frac)

                init = (
                    pk, pv, pos, feed, active, keys,
                    jnp.zeros_like(budget), cstate,
                    jnp.zeros_like(cvec),
                )
                (pk, pv, pos, feed, alive, keys, n, cstate, died), (
                    toks, fracs
                ) = lax.scan(body, init, None, length=K)
                return (
                    pk, pv, feed, alive, keys, n, toks.T, cstate,
                    died, fracs.T,
                )

            if self.mesh is None:
                return jax.jit(window, donate_argnums=(1, 2))
            from jax.sharding import PartitionSpec as PSpec

            from defer_tpu.utils.compat import shard_map

            pool, r = self._pool_specs, PSpec()
            sm = shard_map(
                window,
                self.mesh,
                in_specs=(self._sdec._specs(), pool, pool)
                + (r,) * 15,
                out_specs=(pool, pool, r, r, r, r, r, r, r, r),
                # analysis: ignore[shard-spec] same as _jit_tick: scatter-heavy body, replication pinned by the psum mirror
                check_rep=False,
            )
            return jax.jit(sm, donate_argnums=(1, 2))

        return cached_step(
            self.dec,
            ("paged_window_c", self.bs, self.attention, self.kv_dtype,
             K, mode, eos, self._mesh_key),
            build,
        )

    def _build_spec_window(self, mode: str):
        """The fused spec x decode_window program: W = decode_window
        draft+verify rounds in ONE jitted dispatch. Each scan sub-step
        is a whole speculative round — the DraftLanes propose body
        (decode_server.py::_propose_body) followed by the multi-token
        verify forward (_mt_body) — plus the on-device mirror of the
        host accept test (first proposal/argmax mismatch, then the
        bonus row), eos/budget freezing exactly like _build_window
        (frozen rows pin position 0 and trash-redirect their writes),
        and the pend/lane-position recurrence _tick_spec runs on the
        host between rounds. Greedy rows therefore emit the TARGET's
        own chain token for token; sampled rows draw one token per
        round from the verify forward's row 0 through the same
        batched-sampler trio the plain window uses — streams identical
        to decode_window=1 speculation by construction.

        Per window the host gets ONE batched sync: the [W, B, k+1]
        token buffer plus the small per-round kept/accept vectors that
        drive drain bookkeeping — W rounds (up to W*(k+1) tokens per
        slot) amortize it, vs 2 dispatches + 1 sync per round
        unfused."""
        from defer_tpu.utils.memo import cached_step

        k = self.spec_k
        W = self.decode_window
        eos = self.eos_id
        draft = self._draft

        def build():
            propose_raw = draft._propose_body(k)
            mt_raw = self._mt_body()

            def window(params, pk, pv, dk, dv, dparams, tables, pos,
                       dpos, feed, feed2, adv, active, sampling_row,
                       keys, temp, topk, topp, minp, budget,
                       adapter_ids):
                B = pos.shape[0]
                steps = jnp.arange(k + 1)
                zero_from = jnp.zeros_like(pos)

                def body(carry, _):
                    (pk, pv, dk, dv, pos, dpos, feed, feed2, adv,
                     active, keys, n) = carry
                    greedy = active & ~sampling_row
                    # Draft propose: idle/sampled/frozen lanes pin to
                    # position 0 with adv 0, the idle-lane idiom.
                    dpos_eff = jnp.where(greedy, dpos, 0)
                    adv_eff = jnp.where(greedy, adv, 0)
                    dk, dv, props = propose_raw(
                        dparams, dk, dv, dpos_eff, feed2, adv_eff
                    )
                    # Verify all k+1 candidates; frozen rows write
                    # trash (n_keep 0, position 0, all-trash table).
                    verify_in = jnp.concatenate(
                        [feed, props.astype(jnp.int32)], axis=1
                    )
                    n_keep = jnp.where(
                        active,
                        jnp.where(sampling_row, 1, k + 1),
                        0,
                    ).astype(jnp.int32)
                    pos_eff = jnp.where(active, pos, 0)
                    tab_eff = jnp.where(active[:, None], tables, 0)
                    logits, pk, pv = mt_raw(
                        params, pk, pv, tab_eff, pos_eff, verify_in,
                        n_keep, zero_from, adapter_ids,
                    )
                    preds = jnp.argmax(logits, axis=-1).astype(
                        jnp.int32
                    )
                    # On-device accept test — the batching.py
                    # accept_lengths rule: first props/preds mismatch,
                    # k on full agreement.
                    mismatch = props != preds[:, :k]
                    a = jnp.where(
                        mismatch.any(axis=1),
                        jnp.argmax(mismatch, axis=1),
                        k,
                    ).astype(jnp.int32)
                    bonus = jnp.take_along_axis(
                        preds, a[:, None], axis=1
                    )[:, 0]
                    props_pad = jnp.concatenate(
                        [props, jnp.zeros((B, 1), jnp.int32)], axis=1
                    )
                    toks = jnp.where(
                        steps[None, :] < a[:, None],
                        props_pad,
                        bonus[:, None],
                    )
                    # Sampled rows: one draw per round from row 0 —
                    # the same key/policy stream as the plain paths.
                    ll = logits[:, 0, :]
                    if mode == "argmax":
                        nxt = jnp.argmax(ll, axis=-1).astype(jnp.int32)
                    elif mode == "nosort":
                        nxt, keys = sample_token_batched_nosort(
                            ll, keys, temp, minp
                        )
                    else:
                        nxt, keys = sample_token_batched(
                            ll, keys, temp, topk, topp, minp
                        )
                    nxt = nxt.astype(jnp.int32)
                    toks = jnp.where(
                        sampling_row[:, None], nxt[:, None], toks
                    )
                    cand = jnp.where(sampling_row, 1, a + 1)
                    cand = jnp.where(active, cand, 0)
                    kept = jnp.minimum(
                        cand, jnp.maximum(budget - n, 0)
                    )
                    alive = active
                    if eos is not None:
                        hit = (toks == eos) & (
                            steps[None, :] < kept[:, None]
                        )
                        any_eos = hit.any(axis=1)
                        kept = jnp.where(
                            any_eos,
                            jnp.argmax(hit, axis=1) + 1,
                            kept,
                        )
                        alive = alive & ~any_eos
                    n = n + kept
                    alive = alive & (n < budget)
                    last = jnp.take_along_axis(
                        toks, jnp.maximum(kept - 1, 0)[:, None], axis=1
                    )[:, 0]
                    feed = jnp.where(
                        (kept > 0)[:, None], last[:, None], feed
                    )
                    pos = pos + kept
                    # Continuing greedy rows: partial accept leaves
                    # only the correction token pending (adv 1), full
                    # accept also the never-consumed k-th proposal
                    # (adv 2) — _tick_spec's host recurrence, on
                    # device. Truncated rows froze above, so the
                    # update mask never sees a cut round.
                    full = a == k
                    adv_next = jnp.where(full, 2, 1).astype(jnp.int32)
                    f2a = jnp.where(full, props_pad[:, k - 1], last)
                    upd = alive & ~sampling_row
                    adv = jnp.where(upd, adv_next, adv)
                    feed2 = jnp.where(
                        upd[:, None],
                        jnp.stack([f2a, last], axis=1),
                        feed2,
                    )
                    dpos = jnp.where(upd, pos + 1 - adv_next, dpos)
                    out = (toks, kept, a, greedy, adv_eff)
                    return (
                        (pk, pv, dk, dv, pos, dpos, feed, feed2, adv,
                         alive, keys, n),
                        out,
                    )

                init = (
                    pk, pv, dk, dv, pos, dpos, feed, feed2, adv,
                    active, keys, jnp.zeros_like(budget),
                )
                (
                    (pk, pv, dk, dv, pos, dpos, feed, feed2, adv,
                     alive, keys, n),
                    (toks_a, kept_a, a_a, greedy_a, advu_a),
                ) = lax.scan(body, init, None, length=W)
                return (
                    pk, pv, dk, dv, feed, feed2, adv, alive, keys,
                    toks_a, kept_a, a_a, greedy_a, advu_a,
                )

            if self.mesh is None:
                return jax.jit(window, donate_argnums=(1, 2, 3, 4))
            # Sharded spec window: ONE shard_map around the whole
            # W-round scan. The target verify runs sharded exactly as
            # _ensure_mt's body does; the DRAFT is replicated state —
            # its params, lanes and propose math ride as replicated
            # operands and every shard computes identical proposals
            # (no collectives in the draft forward), so the accept
            # test and sampler advance identically per shard.
            from jax.sharding import PartitionSpec as PSpec

            from defer_tpu.utils.compat import shard_map

            pool, r = self._pool_specs, PSpec()
            sm = shard_map(
                window,
                self.mesh,
                in_specs=(self._sdec._specs(), pool, pool)
                + (r,) * 18,
                out_specs=(pool, pool) + (r,) * 12,
                # analysis: ignore[shard-spec] same as _jit_tick: scatter-heavy body, replication pinned by the psum mirror
                check_rep=False,
            )
            return jax.jit(sm, donate_argnums=(1, 2, 3, 4))

        return cached_step(
            self.dec,
            ("paged_spec_window", self.bs, self.attention,
             self.kv_dtype, W, k, mode, eos, draft.dec.cfg,
             str(draft.dec.compute_dtype), self._mesh_key),
            build,
        )

    def _build_spec_window_c(self, mode: str):
        """Constrained variant of the fused spec window (SEPARATE memo
        key — the unconstrained program stays byte-identical). Each
        scan round swaps in the draft's DFA-masked propose body
        (decode_server.py::_propose_body_c) and replays the
        _constrained_preds target walk in-scan: position j's pred is
        the masked argmax at the state reached via the proposal
        prefix, dead states force the -1 sentinel so the on-device
        accept mirror truncates there, and the emitted correction is
        swapped for a forced eos that freezes the row (the drain
        drops it and surfaces the per-request error — the
        _build_window_c idiom). Committed DFA states ride the carry:
        continuing greedy rows land on the post-state at their accept
        length, sampled rows advance one step by their draw, so the
        next round's draft + target walks resume from exactly the
        states the host would have uploaded between unfused rounds.
        Extra outputs: final states, per-row died flags, and the
        [W, B, k+1] masked-fraction buffer for obs."""
        from defer_tpu.utils.memo import cached_step

        k = self.spec_k
        W = self.decode_window
        eos = self.eos_id
        draft = self._draft

        def build():
            propose_raw = draft._propose_body_c(k, eos)
            mt_raw = self._mt_body()

            def window(params, pk, pv, dk, dv, dparams, tables, pos,
                       dpos, feed, feed2, adv, active, sampling_row,
                       keys, temp, topk, topp, minp, budget,
                       adapter_ids, cid, cstate, ctrans, cacc):
                B = pos.shape[0]
                steps = jnp.arange(k + 1)
                zero_from = jnp.zeros_like(pos)
                cvec = cid > 0

                def body(carry, _):
                    (pk, pv, dk, dv, pos, dpos, feed, feed2, adv,
                     active, keys, n, cstate, died) = carry
                    greedy = active & ~sampling_row
                    dpos_eff = jnp.where(greedy, dpos, 0)
                    adv_eff = jnp.where(greedy, adv, 0)
                    dk, dv, props = propose_raw(
                        dparams, dk, dv, dpos_eff, feed2, adv_eff,
                        cid, cstate, ctrans, cacc,
                    )
                    verify_in = jnp.concatenate(
                        [feed, props.astype(jnp.int32)], axis=1
                    )
                    n_keep = jnp.where(
                        active,
                        jnp.where(sampling_row, 1, k + 1),
                        0,
                    ).astype(jnp.int32)
                    pos_eff = jnp.where(active, pos, 0)
                    tab_eff = jnp.where(active[:, None], tables, 0)
                    logits, pk, pv = mt_raw(
                        params, pk, pv, tab_eff, pos_eff, verify_in,
                        n_keep, zero_from, adapter_ids,
                    )
                    # Target-side constrained walk along the proposal
                    # prefix (_constrained_preds, in-scan).
                    s = cstate
                    preds_l, posts_l = [], []
                    deads_l, fracs_l = [], []
                    crow0 = cmask0 = None
                    for j in range(k + 1):
                        crow_j, acc_j = crt.constrain_rows(
                            ctrans, cacc, cid, s
                        )
                        cmask_j = crt.constrain_mask(crow_j, acc_j, eos)
                        if j == 0:
                            crow0, cmask0 = crow_j, cmask_j
                        dead_j = cvec & ~cmask_j.any(-1)
                        p = jnp.argmax(
                            crt.fold_mask(logits[:, j, :], cmask_j),
                            axis=-1,
                        ).astype(jnp.int32)
                        p = jnp.where(dead_j, -1, p)
                        preds_l.append(p)
                        posts_l.append(
                            crt.advance_state(
                                crow_j, s, jnp.maximum(p, 0),
                                cvec & ~dead_j,
                            )
                        )
                        deads_l.append(dead_j)
                        fracs_l.append(
                            crt.masked_frac(cmask_j, cvec & active)
                        )
                        if j < k:
                            s = crt.advance_state(
                                crow_j, s, props[:, j], cvec
                            )
                    preds = jnp.stack(preds_l, 1)
                    postm = jnp.stack(posts_l, 1)
                    deadm = jnp.stack(deads_l, 1)
                    fracm = jnp.stack(fracs_l, 1)
                    mismatch = props != preds[:, :k]
                    a = jnp.where(
                        mismatch.any(axis=1),
                        jnp.argmax(mismatch, axis=1),
                        k,
                    ).astype(jnp.int32)
                    bonus = jnp.take_along_axis(
                        preds, a[:, None], axis=1
                    )[:, 0]
                    dead_at = jnp.take_along_axis(
                        deadm, a[:, None], axis=1
                    )[:, 0]
                    # The -1 sentinel never enters the stream: the
                    # correction at a dead state becomes a forced eos
                    # that freezes the row; the drain drops it.
                    bonus = jnp.where(dead_at, eos, bonus)
                    props_pad = jnp.concatenate(
                        [props, jnp.zeros((B, 1), jnp.int32)], axis=1
                    )
                    toks = jnp.where(
                        steps[None, :] < a[:, None],
                        props_pad,
                        bonus[:, None],
                    )
                    ll = crt.fold_mask(logits[:, 0, :], cmask0)
                    if mode == "argmax":
                        nxt = jnp.argmax(ll, axis=-1).astype(jnp.int32)
                    elif mode == "nosort":
                        nxt, keys = sample_token_batched_nosort(
                            ll, keys, temp, minp
                        )
                    else:
                        nxt, keys = sample_token_batched(
                            ll, keys, temp, topk, topp, minp
                        )
                    nxt = nxt.astype(jnp.int32)
                    nxt = jnp.where(deadm[:, 0], eos, nxt)
                    toks = jnp.where(
                        sampling_row[:, None], nxt[:, None], toks
                    )
                    cand = jnp.where(sampling_row, 1, a + 1)
                    cand = jnp.where(active, cand, 0)
                    kept = jnp.minimum(
                        cand, jnp.maximum(budget - n, 0)
                    )
                    alive = active
                    hit = (toks == eos) & (
                        steps[None, :] < kept[:, None]
                    )
                    any_eos = hit.any(axis=1)
                    kept = jnp.where(
                        any_eos,
                        jnp.argmax(hit, axis=1) + 1,
                        kept,
                    )
                    alive = alive & ~any_eos
                    # died only when the forced eos actually made the
                    # kept prefix (an earlier natural eos or a budget
                    # cut ends the row without the error).
                    fpos = jnp.where(sampling_row, 0, a)
                    died_now = jnp.where(
                        sampling_row, deadm[:, 0], dead_at
                    )
                    died_now = (
                        died_now & active & (kept == fpos + 1)
                    )
                    n = n + kept
                    alive = alive & (n < budget)
                    last = jnp.take_along_axis(
                        toks, jnp.maximum(kept - 1, 0)[:, None], axis=1
                    )[:, 0]
                    feed = jnp.where(
                        (kept > 0)[:, None], last[:, None], feed
                    )
                    pos = pos + kept
                    full = a == k
                    adv_next = jnp.where(full, 2, 1).astype(jnp.int32)
                    f2a = jnp.where(full, props_pad[:, k - 1], last)
                    upd = alive & ~sampling_row
                    adv = jnp.where(upd, adv_next, adv)
                    feed2 = jnp.where(
                        upd[:, None],
                        jnp.stack([f2a, last], axis=1),
                        feed2,
                    )
                    dpos = jnp.where(upd, pos + 1 - adv_next, dpos)
                    # Commit DFA states for rows continuing past the
                    # round (alive greedy rows always kept a + 1, so
                    # the post-state column at a IS the state after
                    # the round's last emitted token).
                    post_a = jnp.take_along_axis(
                        postm, a[:, None], axis=1
                    )[:, 0]
                    cstate = jnp.where(upd & cvec, post_a, cstate)
                    cstate = crt.advance_state(
                        crow0, cstate, nxt,
                        alive & sampling_row & cvec,
                    )
                    died = died | died_now
                    out = (toks, kept, a, greedy, adv_eff, fracm)
                    return (
                        (pk, pv, dk, dv, pos, dpos, feed, feed2, adv,
                         alive, keys, n, cstate, died),
                        out,
                    )

                init = (
                    pk, pv, dk, dv, pos, dpos, feed, feed2, adv,
                    active, keys, jnp.zeros_like(budget), cstate,
                    jnp.zeros_like(cvec),
                )
                (
                    (pk, pv, dk, dv, pos, dpos, feed, feed2, adv,
                     alive, keys, n, cstate, died),
                    (toks_a, kept_a, a_a, greedy_a, advu_a, fracs_a),
                ) = lax.scan(body, init, None, length=W)
                return (
                    pk, pv, dk, dv, feed, feed2, adv, alive, keys,
                    toks_a, kept_a, a_a, greedy_a, advu_a, cstate,
                    died, fracs_a,
                )

            if self.mesh is None:
                return jax.jit(window, donate_argnums=(1, 2, 3, 4))
            from jax.sharding import PartitionSpec as PSpec

            from defer_tpu.utils.compat import shard_map

            pool, r = self._pool_specs, PSpec()
            sm = shard_map(
                window,
                self.mesh,
                in_specs=(self._sdec._specs(), pool, pool)
                + (r,) * 22,
                out_specs=(pool, pool) + (r,) * 15,
                # analysis: ignore[shard-spec] same as _jit_tick: scatter-heavy body, replication pinned by the psum mirror
                check_rep=False,
            )
            return jax.jit(sm, donate_argnums=(1, 2, 3, 4))

        return cached_step(
            self.dec,
            ("paged_spec_window_c", self.bs, self.attention,
             self.kv_dtype, W, k, mode, eos, draft.dec.cfg,
             str(draft.dec.compute_dtype), self._mesh_key),
            build,
        )

    def _pool_constraint(self, *arrays):
        """Pin pool-layout (or flat-lane) outputs of the plain-jit
        data-movement programs (insert / gather / import) to the
        KV-head sharding when serving on a mesh: the programs stay
        ordinary GSPMD jits — XLA partitions the scatters — but the
        constraint stops the partitioner from ever materializing a
        gathered pool. No-op on mesh=None. All these layouts carry
        their head axis at index 2 — rank picks between the 5-D
        pool/lane spec and the 3-D int8 scale spec, and a {"q","s"}
        pool pytree pins per leaf."""
        if self.mesh is None:
            return arrays if len(arrays) > 1 else arrays[0]
        from jax.sharding import NamedSharding

        pool_sh = NamedSharding(self.mesh, self._pool_spec)
        head_sh = NamedSharding(self.mesh, self._head_spec)

        def pin(leaf):
            sh = head_sh if leaf.ndim == 3 else pool_sh
            return lax.with_sharding_constraint(leaf, sh)

        out = tuple(jax.tree.map(pin, a) for a in arrays)
        return out if len(out) > 1 else out[0]

    def _build_insert(self, skip: int = 0):
        bs = self.bs

        def insert(pk, pv, small_k, small_v, table_row):
            """Scatter a contiguous single-request prefill cache
            ([L, 1, Hkv, S, Dh]) into this request's pool blocks.
            Rows beyond the prompt are garbage the position mask
            hides; unowned table entries point at trash block 0, so
            their writes land in scrap by the module invariant (no
            masking needed — duplicate trash writes just race over
            garbage)."""
            mb = table_row.shape[0]
            s_need = mb * bs
            k_rows = small_k[:, 0]  # [L, Hkv, S, Dh]
            v_rows = small_v[:, 0]
            pad = s_need - k_rows.shape[2]
            if pad > 0:
                k_rows = jnp.pad(
                    k_rows, ((0, 0), (0, 0), (0, pad), (0, 0))
                )
                v_rows = jnp.pad(
                    v_rows, ((0, 0), (0, 0), (0, pad), (0, 0))
                )
            else:
                k_rows = k_rows[:, :, :s_need]
                v_rows = v_rows[:, :, :s_need]
            L, hkv, _, dh = k_rows.shape
            k_blocks = k_rows.reshape(L, hkv, mb, bs, dh).transpose(
                0, 2, 1, 3, 4
            )  # [L, MB, Hkv, bs, Dh]
            v_blocks = v_rows.reshape(L, hkv, mb, bs, dh).transpose(
                0, 2, 1, 3, 4
            )
            # skip > 0 = shared-prefix mode: never write the shared
            # blocks (their rows in the small cache are identical by
            # construction, but they are not this request's to touch).
            dest = table_row[skip:]
            if isinstance(pk, dict):
                # Quantize as the blocks land. Lane rows past the
                # prompt are ZEROS here (flat prefill writes into an
                # init_cache-zeroed lane), so the block scales see
                # only real content.
                kq, ks = _quantize_blocks(k_blocks[:, skip:])
                vq, vs = _quantize_blocks(v_blocks[:, skip:])
                pk = {
                    "q": pk["q"].at[:, dest].set(kq),
                    "s": pk["s"].at[:, dest].set(ks),
                }
                pv = {
                    "q": pv["q"].at[:, dest].set(vq),
                    "s": pv["s"].at[:, dest].set(vs),
                }
            else:
                pk = pk.at[:, dest].set(k_blocks[:, skip:])
                pv = pv.at[:, dest].set(v_blocks[:, skip:])
            return self._pool_constraint(pk, pv)

        return jax.jit(insert, donate_argnums=(0, 1))

    def _build_insert_dynamic(self):
        """The radix variant of _build_insert: `skip` is a RUNTIME
        scalar (per-admission hit count), so one compiled program
        serves every skip value. Leading hit blocks are not this
        request's to touch — and their recomputed rows are only
        equivalent, not guaranteed bit-identical, so rewriting them
        would perturb concurrent readers — hence their writes are
        redirected to trash block 0 (duplicate trash writes race over
        garbage, by the module invariant).

        `valid` (runtime scalar, int8 pools only) — the count of REAL
        lane rows. A radix admission's lane is gathered from the pool,
        so rows past the prompt are a previous tenant's garbage (not
        the zeros a flat-prefill lane carries); folding them into a
        block's amax would inflate its scale and crush the live rows'
        precision, so the int8 path zeroes rows >= valid before
        quantizing. The fp path ignores it (garbage hides behind the
        position mask, and touching it would break bit-identity)."""
        bs = self.bs

        def insert(pk, pv, small_k, small_v, table_row, skip, valid):
            mb = table_row.shape[0]
            s_need = mb * bs
            k_rows = small_k[:, 0]
            v_rows = small_v[:, 0]
            pad = s_need - k_rows.shape[2]
            if pad > 0:
                k_rows = jnp.pad(
                    k_rows, ((0, 0), (0, 0), (0, pad), (0, 0))
                )
                v_rows = jnp.pad(
                    v_rows, ((0, 0), (0, 0), (0, pad), (0, 0))
                )
            else:
                k_rows = k_rows[:, :, :s_need]
                v_rows = v_rows[:, :, :s_need]
            L, hkv, _, dh = k_rows.shape
            if isinstance(pk, dict):
                live = (jnp.arange(s_need) < valid).astype(
                    k_rows.dtype
                )
                k_rows = k_rows * live[None, None, :, None]
                v_rows = v_rows * live[None, None, :, None]
            k_blocks = k_rows.reshape(L, hkv, mb, bs, dh).transpose(
                0, 2, 1, 3, 4
            )
            v_blocks = v_rows.reshape(L, hkv, mb, bs, dh).transpose(
                0, 2, 1, 3, 4
            )
            dest = jnp.where(jnp.arange(mb) >= skip, table_row, 0)
            if isinstance(pk, dict):
                kq, ks = _quantize_blocks(k_blocks)
                vq, vs = _quantize_blocks(v_blocks)
                pk = {
                    "q": pk["q"].at[:, dest].set(kq),
                    "s": pk["s"].at[:, dest].set(ks),
                }
                pv = {
                    "q": pv["q"].at[:, dest].set(vq),
                    "s": pv["s"].at[:, dest].set(vs),
                }
            else:
                pk = pk.at[:, dest].set(k_blocks)
                pv = pv.at[:, dest].set(v_blocks)
            return self._pool_constraint(pk, pv)

        return jax.jit(insert, donate_argnums=(0, 1))

    def _build_gather(self):
        """Jitted (pool_k, pool_v, table_row [MB]) -> flat single-lane
        K/V ([L, 1, Hkv, MB*bs, Dh]) — the exact inverse layout of
        _build_insert, used by radix admissions to hand cached prefix
        blocks to the flat suffix-prefill step. Reads the pool in
        place (no donation: the pool stays live)."""
        def gather(pk, pv, table_row):
            if isinstance(pk, dict):
                # Dequantize at the gather: the flat suffix-prefill
                # step downstream only ever sees compute-dtype lanes.
                kc = dequantize_symmetric(
                    pk["q"][:, table_row],
                    pk["s"][:, table_row][..., None, None],
                    self.dec.compute_dtype,
                )
                vc = dequantize_symmetric(
                    pv["q"][:, table_row],
                    pv["s"][:, table_row][..., None, None],
                    self.dec.compute_dtype,
                )
            else:
                kc = pk[:, table_row]  # [L, MB, Hkv, bs, Dh]
                vc = pv[:, table_row]
            L, mb, hkv, bs, dh = kc.shape
            kc = kc.transpose(0, 2, 1, 3, 4).reshape(
                L, 1, hkv, mb * bs, dh
            )
            vc = vc.transpose(0, 2, 1, 3, 4).reshape(
                L, 1, hkv, mb * bs, dh
            )
            return self._pool_constraint(kc, vc)

        return jax.jit(gather)

    def _prefill_paged(
        self, prompt, table_row, *, base, keep_from, adapter_id
    ):
        """Chunked POOL-NATIVE prefill: run `prompt` through the
        multi-token paged step in prefill_chunk-token chunks, writing
        K/V straight into the allocated blocks through the block
        table — no contiguous max_len lane, no insert pass, and with
        blockwise/pallas attention each chunk reads only the LIVE
        span (accounted per chunk, pool-size independent). Returns
        the [1, V] logits row of the LAST real prompt position (the
        first generated token samples from it).

        `base` — absolute position of prompt[:, 0] (the global
        prefix_ids length, or a radix walk's reuse point); `keep_from`
        — positions below it write to trash block 0 (radix HIT blocks
        already hold those rows and belong to every chain holder).
        Tail chunks pow2-pad, capped so the deepest write stays
        inside the table span (the gathered path's contiguous-lane
        write must never clamp)."""
        mt = self._ensure_mt() if self.pp == 1 else None
        # pp admission is ALWAYS pool-native: with prefill_chunk unset
        # the whole prompt rides one pow2-padded chunk through the
        # stage chain (the cap below bounds it to the table span).
        C = (
            self.prefill_chunk
            if self.prefill_chunk is not None
            else self.MB * self.bs
        )
        t0 = prompt.shape[1]
        tab = jnp.asarray(table_row[None, :].copy())
        adapter = jnp.full((1,), adapter_id, jnp.int32)
        kf = jnp.asarray([keep_from], jnp.int32)
        limit = self.MB * self.bs
        logits_row = None
        start = 0
        while start < t0:
            real = min(C, t0 - start)
            pos0 = base + start
            pad_t = 1 << (real - 1).bit_length()
            pad_t = min(max(pad_t, 1), min(C, limit - pos0))
            chunk = prompt[:, start : start + real]
            if pad_t > real:
                chunk = jnp.concatenate(
                    [chunk, jnp.zeros((1, pad_t - real), chunk.dtype)],
                    axis=1,
                )
            if self.pp > 1:
                # The chunk flows through the stage chain; each stage
                # scatters its own layers' K/V into its pool slice.
                x = chunk.astype(jnp.int32)
                pos_a = jnp.asarray([pos0], jnp.int32)
                nk = jnp.asarray([real], jnp.int32)
                for s, stage in enumerate(self._pp_stage_objs):
                    x = stage.pp_dispatch(tab, pos_a, x, nk, kf, adapter)
                    self.pp_stage_dispatch_n[s] += 1
                    self.obs.pp_stage_dispatches[s].inc()
                logits = x
            else:
                logits, self.pool_k, self.pool_v = mt(
                    self.params,
                    self.pool_k,
                    self.pool_v,
                    tab,
                    jnp.asarray([pos0], jnp.int32),
                    chunk.astype(jnp.int32),
                    jnp.asarray([real], jnp.int32),
                    kf,
                    adapter,
                )
            self._account_kv_rows_prefill(pos0, pad_t)
            self._account_psums(1)
            # Serialized-prefill interference: this chunk dispatch ran
            # INSTEAD of a decode tick for every live slot
            # (prefill_budget= admits through _tick_mixed and never
            # reaches here with decode slots live).
            self._note_prefill_stall(1)
            logits_row = logits[:, real - 1, :]
            start += real
        if self.pp > 1:
            # The sampler's state lives on the default device; commit
            # the last stage's logits row there so admission-side
            # first-token draws stay single-device (async transfer).
            logits_row = jax.device_put(logits_row, jax.devices()[0])
        return logits_row

    def _account_kv_rows_prefill(self, pos0: int, t: int) -> None:
        """Pool rows one prefill chunk's attention read (same
        units/contract as the decode-tick accounting): a B=1
        multi-token step whose deepest query row attends at
        pos0 + t - 1. Everything here derives from max_len (MB) and
        the chunk's live span — NEVER from pool size, the property
        the chunked-prefill acceptance test pins."""
        bs = self.bs
        baseline = self.MB * bs
        if self.attention == "gathered":
            rows_read = baseline
        elif self.attention == "blockwise":
            rows_read = ((pos0 + t - 1) // bs + 1) * bs
        else:  # pallas
            win = self.dec.cfg.window
            hi = (pos0 + t - 1) // bs
            lo = max(pos0 - win + 1, 0) // bs if win is not None else 0
            rows_read = (hi - lo + 1) * bs
        self._account_kv_rows(rows_read, baseline)

    def _spill_block(self, key: bytes, tok: bytes, blk: int) -> None:
        """PrefixBlockCache on_evict hook (serving thread): dispatch
        ASYNC device slices of the block being evicted and hand them
        to the spill drain thread. The slices are fresh buffers cut
        before any later donating dispatch can invalidate the pool;
        the blocking device->host copy happens on the drain thread
        (HostKVSpill._drain_loop), never here — eviction sits inside
        the admission/tick hot path."""
        b = blk  # python int: keepdim slice, no host round-trip
        if isinstance(self.pool_k, dict):
            arrays = (
                self.pool_k["q"][:, b : b + 1],
                self.pool_k["s"][:, b : b + 1],
                self.pool_v["q"][:, b : b + 1],
                self.pool_v["s"][:, b : b + 1],
            )
        else:
            arrays = (
                self.pool_k[:, b : b + 1],
                self.pool_v[:, b : b + 1],
            )
        self._spill.offer(key, tok, arrays)

    def _ensure_spill_up(self):
        """One-block pool upload for spill revival: scatter a stored
        block payload (int8 q + scales, or fp rows) back into block
        `blk`. Memoized like every paged program; donates the pool."""
        if self._spill_up is None:
            from defer_tpu.utils.memo import cached_step

            def build():
                def up(pk, pv, *rest):
                    if isinstance(pk, dict):
                        kq, ks, vq, vs, blk = rest
                        pk = {
                            "q": pk["q"].at[:, blk].set(kq[:, 0]),
                            "s": pk["s"].at[:, blk].set(ks[:, 0]),
                        }
                        pv = {
                            "q": pv["q"].at[:, blk].set(vq[:, 0]),
                            "s": pv["s"].at[:, blk].set(vs[:, 0]),
                        }
                    else:
                        kb, vb, blk = rest
                        pk = pk.at[:, blk].set(kb[:, 0])
                        pv = pv.at[:, blk].set(vb[:, 0])
                    return self._pool_constraint(pk, pv)

                return jax.jit(up, donate_argnums=(0, 1))

            self._spill_up = cached_step(
                self.dec,
                (
                    "paged_spill_up", self.bs, self.kv_dtype,
                    self._mesh_key,
                ),
                build,
            )
        return self._spill_up

    def _revive_spilled(
        self,
        hits: list[int],
        keys: list[bytes],
        toks: list[bytes],
        n_full: int,
    ) -> list[int]:
        """Extend a radix walk's leading hit run from the host spill
        tier: for each miss position, look up the chain digest in the
        spill store and, on a (token-byte-guarded) hit, re-upload the
        EXACT stored payload into a newly allocated block and register
        it. Raw-byte upload means a revived block is bit-identical to
        the parked block it was spilled from — dequantizing and
        re-quantizing instead could perturb values where round(x/s)
        landed on a clip boundary — which is what makes a spill hit
        token-identical to a resident hit. Stops at the first store
        miss (chain order is mandatory: block j is meaningless without
        0..j-1) or when the pool can't yield a block."""
        j = len(hits)
        while j < n_full:
            got = self._spill.get(keys[j], toks[j])
            if got is None:
                break
            if not self.free:
                self.free.extend(self.radix.evict(1))
                if not self.free:
                    break
            blk = self.free.pop()
            up = self._ensure_spill_up()
            if isinstance(self.pool_k, dict):
                kq, ks, vq, vs = got
                self.pool_k, self.pool_v = up(
                    self.pool_k,
                    self.pool_v,
                    self._shard_ingest(kq),
                    self._shard_ingest(ks),
                    self._shard_ingest(vq),
                    self._shard_ingest(vs),
                    jnp.asarray(blk, jnp.int32),
                )
            else:
                kb, vb = got
                self.pool_k, self.pool_v = up(
                    self.pool_k,
                    self.pool_v,
                    self._shard_ingest(kb),
                    self._shard_ingest(vb),
                    jnp.asarray(blk, jnp.int32),
                )
            displaced = self.radix.register(keys[j], toks[j], blk)
            if displaced is not None:
                self.free.append(displaced)
            hits.append(blk)
            self.spill_hits_n += 1
            self.obs.prefix_spill_hits.inc()
            j += 1
        return hits

    def _admit_radix(
        self, i, rid, prompt, steps, adapter_id, samp, stop_seqs,
        cid=0,
    ) -> bool:
        """Admission through the PrefixBlockCache: walk leading full
        prompt blocks for hits (refcount++), allocate the rest
        (evicting parked refcount-0 blocks only under pressure),
        gather the hit blocks into a flat lane, prefill ONLY the
        suffix, then publish this request's fresh full prompt blocks
        for future hits. Returns False (request waits, refcounts
        rolled back) when even eviction cannot cover the need."""
        bs = self.bs
        t0 = prompt.shape[1]
        tokens = np.asarray(prompt)[0]
        n_full = t0 // bs
        total = -(-(t0 + steps) // bs)
        hits, keys, toks = self.radix.walk(tokens, n_full, bs)
        if self._spill is not None and len(hits) < n_full:
            hits = self._revive_spilled(hits, keys, toks, n_full)
        need = total - len(hits)
        if need > len(self.free):
            self.free.extend(
                self.radix.evict(need - len(self.free))
            )
        if need > len(self.free):
            for blk in hits:
                self.radix.release(blk)
            return False
        own = [self.free.pop() for _ in range(need)]
        self.obs.requests_admitted.inc()
        self.obs.prefix_hits.inc(len(hits))
        self.obs.prefix_misses.inc(n_full - len(hits))
        # Strict lookup: an unknown rid would silently observe a zero
        # queue wait — admission without a submit timestamp is a bug.
        self.obs.queue_wait.observe(
            time.perf_counter() - self._submit_t[rid]
        )
        self._build()
        table_row = np.zeros((self.MB,), np.int32)
        for j, blk in enumerate(hits + own):
            table_row[j] = blk
        # Reuse at most t0-1 cached positions: the LAST prompt token
        # must go through the step so its logits exist to sample the
        # first generated token (its K/V row is rewritten with
        # identical content).
        suffix_pos = min(len(hits) * bs, t0 - 1)
        suffix = prompt[:, suffix_pos:]
        ts = suffix.shape[1]
        self.obs.prefill_tokens.inc(ts)
        if self.prefill_chunk is not None or self.pp > 1:
            # Pool-native chunked prefill: the hit blocks are read
            # straight from the pool by the block-table attention (no
            # gather into a flat lane), fresh rows scatter into this
            # request's blocks as each chunk computes, and writes
            # below keep_from (HIT rows, other holders' memory)
            # redirect to trash — the dynamic-skip rule, applied per
            # row instead of per block.
            logits_row = self._prefill_paged(
                suffix,
                table_row,
                base=suffix_pos,
                keep_from=len(hits) * bs,
                adapter_id=adapter_id,
            )
        else:
            if hits:
                gk, gv = self._gather(
                    self.pool_k, self.pool_v, jnp.asarray(table_row)
                )
                small = {
                    "k": gk,
                    "v": gv,
                    "pos": jnp.asarray(suffix_pos, jnp.int32),
                }
            else:
                small = self._flat_dec().init_cache(1)
            pad = 1 << (ts - 1).bit_length()
            pad = min(pad, self.dec.cfg.max_len - suffix_pos)
            padded = jnp.concatenate(
                [suffix, jnp.zeros((1, pad - ts), prompt.dtype)],
                axis=1,
            )
            logits, small = self._flat_dec().make_step()(
                self.params, small, padded
            )
            self._account_psums(1)
            self._note_prefill_stall(1)
            # Dynamic-skip insert: hit blocks are never rewritten
            # (their recomputed rows are equivalent but not guaranteed
            # bit-identical, and they belong to every other holder of
            # the chain); fresh rows land in this request's blocks;
            # unowned tail entries point at trash by the module
            # invariant.
            self.pool_k, self.pool_v = self._insert_dyn(
                self.pool_k,
                self.pool_v,
                small["k"],
                small["v"],
                jnp.asarray(table_row),
                jnp.asarray(len(hits), jnp.int32),
                jnp.asarray(t0, jnp.int32),
            )
            logits_row = logits[:, ts - 1, :]
        for j in range(len(hits), n_full):
            displaced = self.radix.register(
                keys[j], toks[j], int(table_row[j])
            )
            if displaced is not None:
                self.free.append(displaced)
        shared = hits + [int(table_row[j]) for j in range(len(hits), n_full)]
        owned = [int(table_row[j]) for j in range(n_full, total)]
        self.prefill_tokens_saved += suffix_pos
        self.blocks_peak = max(self.blocks_peak, self.blocks_in_use)
        first = self._first_token(
            i, samp, logits_row, prompt.dtype, cid
        )
        self.tables[i] = table_row
        self.pos[i] = t0
        self.adapter[i] = adapter_id
        slot = {
            "rid": rid,
            "remaining": steps - 1,
            "last": first,
            "toks": [prompt, first],
            "blocks": owned,
            "shared": shared,
            "sampling": samp is not None,
            "stop": matcher_or_none(stop_seqs),
            "cid": cid,
        }
        self.slots[i] = slot
        if self._draft is not None and not slot["sampling"]:
            # Seed speculation: the first token anchors the pend list
            # (it is emitted but not yet in any K/V), and the draft
            # lane prefills the FULL prompt — radix hits are a pool
            # concept the draft does not share.
            slot["pend"] = [int(first[0, 0])]
            self._draft.admit(i, prompt)
        self._feed = self._feed.at[i].set(first[0].astype(jnp.int32))
        # ttft spans queue + prefill (popped here, the drain point —
        # entries must not outlive their request).
        self.obs.ttft.observe(
            time.perf_counter() - self._submit_t.pop(rid)
        )
        self._update_pool_gauges()
        need_host = (
            self.eos_id is not None
            or self.on_token is not None
            or slot["stop"] is not None
        )
        self._emit_token(
            i, slot, int(first[0, 0]) if need_host else None
        )
        return True

    def _ensure_insert_dyn(self):
        """The dynamic-skip insert is built lazily for radix servers
        (_build); externally prefilled admission needs it regardless
        of prefix_cache (skip = radix hit count, or 0), under the
        same memo key so the two users share one compile."""
        if self._insert_dyn is None:
            from defer_tpu.utils.memo import cached_step

            self._insert_dyn = cached_step(
                self.dec,
                (
                    "paged_insert_dyn", self.bs, self.kv_dtype,
                    self._mesh_key,
                ),
                self._build_insert_dynamic,
            )
        return self._insert_dyn

    def _blocks_to_lane(self, blocks: np.ndarray) -> jax.Array:
        """[L, n, Hkv, bs, Dh] block stack -> the flat [L, 1, Hkv, S,
        Dh] lane the insert programs take, zero-padded up to a pow2
        block count (capped at MB) so ingest admissions draw from the
        same bounded compile-shape set as pow2-padded prefill."""
        L, n, hkv, bs, dh = blocks.shape
        n_pad = 1 << max(n - 1, 0).bit_length()
        n_pad = min(max(n_pad, 1), self.MB)
        if n_pad > n:
            blocks = np.concatenate(
                [
                    blocks,
                    np.zeros((L, n_pad - n, hkv, bs, dh), blocks.dtype),
                ],
                axis=1,
            )
        lane = blocks.transpose(0, 2, 1, 3, 4).reshape(
            L, hkv, n_pad * bs, dh
        )
        # Under a mesh this is the disagg TP-ingest scatter: the wire
        # blob carries all kv heads, and the head-sharded device_put
        # slices each shard's heads out at ingest (wire unchanged).
        return self._shard_ingest(lane[:, None])

    def _admit_prefilled(self, i: int, rid: int, entry: dict) -> bool:
        """Seat a request whose KV arrived from a prefill worker:
        no prefill step runs here — the delivered block stacks scatter
        straight into allocated pool blocks (dynamic-skip insert, so
        radix HIT blocks are never rewritten), the first token is
        drawn from the shipped logits row, and fresh full prompt
        blocks register in the radix cache exactly like locally
        prefilled ones (cross-host prefix sharing: a later LOCAL
        request can hit blocks this host never prefilled). Returns
        False when the pool can't cover the request even after
        eviction (it stays pending)."""
        prompt = entry["prompt"]
        steps = entry["steps"]
        samp = entry["samp"]
        k_blocks, v_blocks, first_logits = entry["kv"]
        bs = self.bs
        t0 = prompt.shape[1]
        n_full = t0 // bs
        total = -(-(t0 + steps) // bs)
        if self.radix is not None:
            hits, keys, toks = self.radix.walk(prompt[0], n_full, bs)
        else:
            hits, keys, toks = [], [], []
        need = total - len(hits)
        if self.radix is not None and need > len(self.free):
            self.free.extend(self.radix.evict(need - len(self.free)))
        if need > len(self.free):
            for blk in hits:
                self.radix.release(blk)
            return False
        own = [self.free.pop() for _ in range(need)]
        self.obs.requests_admitted.inc()
        if self.radix is not None:
            self.obs.prefix_hits.inc(len(hits))
            self.obs.prefix_misses.inc(n_full - len(hits))
        # Strict lookup: an unknown rid would silently observe a zero
        # queue wait — admission without a submit timestamp is a bug.
        self.obs.queue_wait.observe(
            time.perf_counter() - self._submit_t[rid]
        )
        self._build()
        insert_dyn = self._ensure_insert_dyn()
        table_row = np.zeros((self.MB,), np.int32)
        for j, blk in enumerate(hits + own):
            table_row[j] = blk
        self.pool_k, self.pool_v = insert_dyn(
            self.pool_k,
            self.pool_v,
            self._blocks_to_lane(k_blocks),
            self._blocks_to_lane(v_blocks),
            jnp.asarray(table_row),
            jnp.asarray(len(hits), jnp.int32),
            jnp.asarray(t0, jnp.int32),
        )
        if self.radix is not None:
            for j in range(len(hits), n_full):
                displaced = self.radix.register(
                    keys[j], toks[j], int(table_row[j])
                )
                if displaced is not None:
                    self.free.append(displaced)
            shared = hits + [
                int(table_row[j]) for j in range(len(hits), n_full)
            ]
            owned = [int(table_row[j]) for j in range(n_full, total)]
            self.blocks_peak = max(self.blocks_peak, self.blocks_in_use)
        else:
            shared = None
            owned = own
            self.blocks_peak = max(
                self.blocks_peak, self.blocks_in_use + need
            )
        first = self._first_token(
            i, samp, jnp.asarray(first_logits), jnp.int32,
            entry.get("cid", 0),
        )
        self.tables[i] = table_row
        self.pos[i] = t0
        self.adapter[i] = 0
        slot = {
            "rid": rid,
            "remaining": steps - 1,
            "last": first,
            "toks": [jnp.asarray(prompt), first],
            "blocks": owned,
            "sampling": samp is not None,
            "stop": matcher_or_none(entry["stop"]),
            "cid": entry.get("cid", 0),
        }
        if shared is not None:
            slot["shared"] = shared
        self.slots[i] = slot
        if self._draft is not None and samp is None:
            # The delivered KV covers only the TARGET; the draft lane
            # re-prefills locally from the prompt ids (the draft never
            # saw this prompt on the prefill worker, and shipping its
            # tiny K/V would cost more coordination than recompute).
            slot["pend"] = [int(first[0, 0])]
            self._draft.admit(i, jnp.asarray(prompt))
        self._feed = self._feed.at[i].set(first[0].astype(jnp.int32))
        # ttft spans queue + prefill (popped here, the drain point —
        # entries must not outlive their request).
        self.obs.ttft.observe(
            time.perf_counter() - self._submit_t.pop(rid)
        )
        self._update_pool_gauges()
        need_host = (
            self.eos_id is not None
            or self.on_token is not None
            or slot["stop"] is not None
        )
        self._emit_token(
            i, slot, int(first[0, 0]) if need_host else None
        )
        return True

    def _first_token(self, i, samp, lrow, dtype, cid):
        """Admission's first generated token (the flat server's twin):
        constrained slots mask the prefill logits row with their DFA's
        START-state row before the shared argmax/first-draw, then
        install the advanced state (a device scalar — admission stays
        sync-free beyond its existing bookkeeping)."""
        if cid:
            row = self._ctrans[cid, 0]
            mask = (row >= 0).at[self.eos_id].set(self._cacc[cid, 0])
            lrow = jnp.where(
                mask[None, :], lrow, jnp.finfo(lrow.dtype).min
            )
        first = self._sampler.admit_first(i, samp, lrow, dtype)
        if cid:
            state = jnp.maximum(row[first[0, 0].astype(jnp.int32)], 0)
            self._sampler.admit_constraint(i, cid, state)
            frac = crt.masked_frac(mask[None, :], jnp.asarray([True]))
            # analysis: ignore[host-sync-in-hot-loop] once per
            # CONSTRAINED admission (first token only), not per tick —
            # mixed-mode flips route here but a flip happens once per
            # request; the steady-state tick never reaches this branch
            self.obs.constrain_masked_frac.observe(float(frac[0]))
            self.obs.constrained_tokens.inc()
            self.constrained_tokens_n += 1
        return first

    def _constrained_preds(self, logits, props, k):
        """Target-side constrained greedy walk for one speculative
        round: position j's pred is the masked argmax at state s_j,
        where s_{j+1} = trans[s_j, props_j] follows the PROPOSAL
        chain — exactly the states the committed stream would visit
        if the proposals are accepted, so the accept test truncates
        at the first proposal the target's mask rejects and the
        output stays token-identical to the spec_k=0 constrained
        chain. Dead states force pred to -1 (out of vocab): never
        accepted, and the host drain drops the correction with a
        per-request error. All device jnp (gathers per position) —
        no host DFA lookups; runs eagerly alongside the eager argmax
        it replaces. Returns (preds [B,k+1], crow0, cmask0,
        post_states [B,k+1] = state AFTER committing pred_j,
        dead [B,k+1], fracs [B,k+1])."""
        sm = self._sampler
        cvec = jnp.asarray(sm.row_constrained)
        s = sm.cstate
        preds, posts, deads, fracs = [], [], [], []
        crow0 = cmask0 = None
        for j in range(k + 1):
            crow, acc = crt.constrain_rows(
                self._ctrans, self._cacc, sm.cid, s
            )
            cmask = crt.constrain_mask(crow, acc, self.eos_id)
            if j == 0:
                crow0, cmask0 = crow, cmask
            dead_j = cvec & ~cmask.any(-1)
            p = jnp.argmax(
                crt.fold_mask(logits[:, j, :], cmask), axis=-1
            ).astype(jnp.int32)
            p = jnp.where(dead_j, -1, p)
            preds.append(p)
            posts.append(
                crt.advance_state(
                    crow, s, jnp.maximum(p, 0), cvec & ~dead_j
                )
            )
            deads.append(dead_j)
            fracs.append(crt.masked_frac(cmask, cvec))
            if j < k:
                s = crt.advance_state(crow, s, props[:, j], cvec)
        return (
            jnp.stack(preds, 1), crow0, cmask0,
            jnp.stack(posts, 1), jnp.stack(deads, 1),
            jnp.stack(fracs, 1),
        )

    def _admit_prefilled_ready(self, i: int) -> bool | None:
        """Try to seat the oldest DELIVERED prefilled request in slot
        i. True = seated; False = one was ready but the pool can't
        cover it (caller should wait for a finisher); None = nothing
        deliverable right now."""
        for rid in self._prefilled_order:
            entry = self.pending_prefilled[rid]
            if entry["kv"] is None:
                continue
            if not self._admit_prefilled(i, rid, entry):
                return False
            self._prefilled_order.remove(rid)
            del self.pending_prefilled[rid]
            return True
        return None

    def _admit(self) -> None:
        if self.prefill_budget is not None:
            # Mixed-mode admission: new prompts take SEATS and prefill
            # inside the decode dispatches (runtime/schedule.py) — the
            # serialized stall-prefill path below never runs.
            return self._admit_mixed()
        for i in range(self.B):
            if self.slots[i] is not None:
                continue
            # Externally prefilled requests seat first: their compute
            # is already spent, so every tick they wait is pure added
            # TTFT.
            seated = self._admit_prefilled_ready(i)
            if seated:
                continue
            if seated is False:
                return  # pool exhausted even after eviction
            if not self.pending:
                continue
            (rid, prompt, steps, adapter_id, samp,
             stop_seqs, cid) = self.pending[0]
            if self.radix is not None:
                if not self._admit_radix(
                    i, rid, prompt, steps, adapter_id, samp, stop_seqs,
                    cid,
                ):
                    return  # pool exhausted even after eviction
                self.pending.popleft()
                continue
            t0 = prompt.shape[1]
            P = self.prefix_len
            n_shared = len(self.shared_blocks)
            need = self._own_need(t0, steps)
            if need > len(self.free):
                return  # pool exhausted: wait for a finisher
            self.pending.popleft()
            blocks = [self.free.pop() for _ in range(need)]
            self.obs.requests_admitted.inc()
            self.obs.prefill_tokens.inc(t0)
            # Strict lookup (same rule as the radix/prefilled paths):
            # a missing rid is a bug, not a zero wait.
            self.obs.queue_wait.observe(
                time.perf_counter() - self._submit_t[rid]
            )
            self._build()
            self.blocks_peak = max(
                self.blocks_peak, self.blocks_in_use + need
            )
            table_row = np.zeros((self.MB,), np.int32)
            for j, blk in enumerate(self.shared_blocks):
                table_row[j] = blk
            for j, blk in enumerate(blocks):
                table_row[n_shared + j] = blk
            if self.prefill_chunk is not None or self.pp > 1:
                # Pool-native chunked prefill: rows land in the
                # allocated blocks as each chunk computes, and a
                # global shared prefix (base=P) is read from ITS pool
                # blocks by the block-table attention — no contiguous
                # prefix lane, no insert pass.
                logits_row = self._prefill_paged(
                    prompt,
                    table_row,
                    base=P,
                    keep_from=0,
                    adapter_id=adapter_id,
                )
            else:
                # Contiguous prefill through the flat decoder — pow2
                # bucketed like the flat server, so the compiled
                # prefill shape set stays tiny — then page the rows
                # in. With a shared prefix the suffix prefills at
                # offset P on a COPY of the contiguous prefix lane
                # (the flat step donates its cache), and only rows
                # past the shared blocks are paged.
                pad = 1 << (t0 - 1).bit_length()
                pad = min(pad, self.dec.cfg.max_len - P)
                padded = jnp.concatenate(
                    [prompt, jnp.zeros((1, pad - t0), prompt.dtype)],
                    axis=1,
                )
                # Non-donating prefill step: the master prefix lane is
                # read directly (no per-admission deep copy of two
                # full max_len K/V buffers — the cost this feature
                # exists to avoid); the returned cache is a fresh
                # tree.
                if self._prefix_cache is None:
                    small = self._flat_dec().init_cache(1)
                else:
                    small = dict(self._prefix_cache)
                if self.multi_lora:
                    small["adapter"] = jnp.full(
                        (1,), adapter_id, jnp.int32
                    )
                logits, small = self._flat_dec().make_step(donate=False)(
                    self.params, small, padded
                )
                self._account_psums(1)
                self._note_prefill_stall(1)
                self.pool_k, self.pool_v = self._insert(
                    self.pool_k,
                    self.pool_v,
                    small["k"],
                    small["v"],
                    jnp.asarray(table_row),
                )
                logits_row = logits[:, t0 - 1, :]
            first = self._first_token(
                i, samp, logits_row, prompt.dtype, cid
            )
            self.tables[i] = table_row
            self.pos[i] = P + t0
            self.adapter[i] = adapter_id
            slot = {
                "rid": rid,
                "remaining": steps - 1,
                "last": first,
                "toks": [prompt, first],
                "blocks": blocks,
                "sampling": samp is not None,
                "stop": matcher_or_none(stop_seqs),
                "cid": cid,
            }
            self.slots[i] = slot
            if self._draft is not None and not slot["sampling"]:
                # The first generated token is the slot's initial
                # pending feed; the draft lane prefills the FULL
                # prompt (admission-time host read — not a hot-loop
                # sync, _admit is outside the analysis hot set).
                slot["pend"] = [int(first[0, 0])]
                self._draft.admit(i, prompt)
            self._feed = self._feed.at[i].set(
                first[0].astype(jnp.int32)
            )
            # ttft spans queue + prefill; popped here (the drain
            # point) so entries never outlive their request.
            self.obs.ttft.observe(
                time.perf_counter() - self._submit_t.pop(rid)
            )
            self._update_pool_gauges()
            # Host transfer only when eos/streaming/stop matching
            # consumes the value (same guard as _tick) — the plain
            # path stays async.
            need_host = (
                self.eos_id is not None
                or self.on_token is not None
                or slot["stop"] is not None
            )
            self._emit_token(
                i, slot, int(first[0, 0]) if need_host else None
            )

    # -- mixed-mode admission + tick (prefill_budget=) ----------------

    def _seat_slots(self) -> list[int]:
        """Slot indices currently holding a PREFILL SEAT (admitted,
        mid-prefill, not yet decoding), admission order == slot-scan
        order because _admit_mixed seats the queue head first."""
        return [
            i
            for i, s in enumerate(self.slots)
            if s is not None and "prefill" in s
        ]

    def _note_prefill_stall(self, n_dispatches: int) -> None:
        """Account `n_dispatches` admission-prefill dispatches issued
        by the SERIALIZED path: each one issued while a decode slot is
        live is a stall tick (that slot's tick loop sat waiting).
        Mixed-mode ticks never call this — their prefill rides inside
        the decode dispatch."""
        if any(
            s is not None and "prefill" not in s for s in self.slots
        ):
            self.prefill_stall_ticks_n += n_dispatches
            self.obs.prefill_stall_ticks.inc(n_dispatches)
        self._update_stall_fraction()

    def _update_stall_fraction(self) -> None:
        """Publish decode_stall_fraction = stall_ticks / (decode ticks
        + stall_ticks): of all the dispatch slots that could have
        advanced decode, the fraction admission prefill stole."""
        denom = self.ticks + self.prefill_stall_ticks_n
        frac = self.prefill_stall_ticks_n / denom if denom else 0.0
        self.decode_stall_fraction_last = frac
        self.obs.decode_stall_fraction.set(frac)

    def _admit_mixed(self) -> None:
        """Seat-only admission for `prefill_budget=` servers: a new
        request claims a free slot and its blocks immediately, but NO
        prefill runs here — its prompt tokens ride inside subsequent
        mixed decode dispatches (_tick_mixed) until the last chunk
        lands and the seat flips to decoding (_flip_seat). Externally
        prefilled requests (submit_prefilled) bypass the budget: their
        compute is already spent, so they seat exactly as before."""
        for i in range(self.B):
            if self.slots[i] is not None:
                continue
            seated = self._admit_prefilled_ready(i)
            if seated:
                continue
            if seated is False:
                return  # pool exhausted even after eviction
            if not self.pending:
                continue
            if len(self._seat_slots()) >= self.prefill_lookahead:
                # Bounded lookahead: enough prompts are already
                # sharing the budget — admission stays near-FIFO.
                return
            (rid, prompt, steps, adapter_id, samp,
             stop_seqs, cid) = self.pending[0]
            if self.radix is not None:
                ok = self._seat_radix(
                    i, rid, prompt, steps, adapter_id, samp,
                    stop_seqs, cid,
                )
            else:
                ok = self._seat_plain(
                    i, rid, prompt, steps, adapter_id, samp,
                    stop_seqs, cid,
                )
            if not ok:
                return  # pool exhausted: wait for a finisher
            self.pending.popleft()

    def _seat_common(
        self, i, rid, prompt, steps, adapter_id, samp, stop_seqs,
        cid, seat, blocks, shared,
    ) -> None:
        """Shared tail of both seat paths: install the mid-prefill
        slot dict + host rows. `pos[i]` starts at the seat's base and
        advances per chunk; sampling/stop/constraint state installs
        at FLIP time (admit_first reseeds the sampler row then, so
        sampled streams match the stall path token for token)."""
        self._build()
        self.blocks_peak = max(self.blocks_peak, self.blocks_in_use)
        self.adapter[i] = adapter_id
        self.pos[i] = seat.base
        self.slots[i] = {
            "rid": rid,
            "prefill": seat,
            "meta": {
                "prompt": prompt,
                "steps": steps,
                "samp": samp,
                "stop": stop_seqs,
                "cid": cid,
            },
            "blocks": blocks,
            "shared": shared,
            "sampling": samp is not None,
            "stop": None,
            "cid": 0,
        }
        self._update_pool_gauges()

    def _seat_plain(
        self, i, rid, prompt, steps, adapter_id, samp, stop_seqs, cid
    ) -> bool:
        """Seat a request on a non-radix server: allocate its blocks
        (plus pointers at the global shared prefix), schedule the
        whole prompt at base=prefix_len. False = pool can't cover it
        yet."""
        t0 = prompt.shape[1]
        need = self._own_need(t0, steps)
        if need > len(self.free):
            return False
        blocks = [self.free.pop() for _ in range(need)]
        self.obs.requests_admitted.inc()
        self.obs.prefill_tokens.inc(t0)
        # Strict lookup (satellite of the mixed-mode PR): a missing
        # rid is a bug, not a zero wait.
        self.obs.queue_wait.observe(
            time.perf_counter() - self._submit_t[rid]
        )
        n_shared = len(self.shared_blocks)
        table_row = np.zeros((self.MB,), np.int32)
        for j, blk in enumerate(self.shared_blocks):
            table_row[j] = blk
        for j, blk in enumerate(blocks):
            table_row[n_shared + j] = blk
        self.tables[i] = table_row
        seat = PrefillSeat(
            rid=rid,
            tokens=np.asarray(prompt)[0],
            base=self.prefix_len,
            keep_from=0,
        )
        self._seat_common(
            i, rid, prompt, steps, adapter_id, samp, stop_seqs, cid,
            seat, blocks, [],
        )
        return True

    def _seat_radix(
        self, i, rid, prompt, steps, adapter_id, samp, stop_seqs, cid
    ) -> bool:
        """Seat a request through the PrefixBlockCache: walk leading
        full prompt blocks for hits (refcount++ now — they must stay
        pinned while the seat prefills), allocate the rest, and
        schedule ONLY the non-shared suffix. The request's own fresh
        full-prompt blocks are NOT registered here: mid-prefill they
        hold unwritten rows, so publication waits for _flip_seat."""
        bs = self.bs
        t0 = prompt.shape[1]
        tokens = np.asarray(prompt)[0]
        n_full = t0 // bs
        total = -(-(t0 + steps) // bs)
        hits, keys, toks = self.radix.walk(tokens, n_full, bs)
        if self._spill is not None and len(hits) < n_full:
            hits = self._revive_spilled(hits, keys, toks, n_full)
        need = total - len(hits)
        if need > len(self.free):
            self.free.extend(self.radix.evict(need - len(self.free)))
        if need > len(self.free):
            for blk in hits:
                self.radix.release(blk)
            return False
        own = [self.free.pop() for _ in range(need)]
        self.obs.requests_admitted.inc()
        self.obs.prefix_hits.inc(len(hits))
        self.obs.prefix_misses.inc(n_full - len(hits))
        self.obs.queue_wait.observe(
            time.perf_counter() - self._submit_t[rid]
        )
        table_row = np.zeros((self.MB,), np.int32)
        for j, blk in enumerate(hits + own):
            table_row[j] = blk
        self.tables[i] = table_row
        # Reuse at most t0-1 cached positions: the LAST prompt token
        # must run so its logits exist to seed the first generated
        # token (same rule as the stall path).
        suffix_pos = min(len(hits) * bs, t0 - 1)
        self.obs.prefill_tokens.inc(t0 - suffix_pos)
        self.prefill_tokens_saved += suffix_pos
        seat = PrefillSeat(
            rid=rid,
            tokens=tokens[suffix_pos:],
            base=suffix_pos,
            keep_from=len(hits) * bs,
        )
        meta_extra = {
            "keys": keys,
            "toks": toks,
            "n_full": n_full,
            "n_hits": len(hits),
        }
        self._seat_common(
            i, rid, prompt, steps, adapter_id, samp, stop_seqs, cid,
            seat, own, list(hits),
        )
        self.slots[i]["meta"].update(meta_extra)
        return True

    def _flip_seat(self, i: int, slot: dict, lrow) -> None:
        """The seat's last chunk just landed: seed the first generated
        token from that chunk's final logits row (`lrow`, [1, V] —
        exactly the row the stall path samples at admission) and turn
        the seat into a decoding slot. Radix servers publish the
        request's fresh full-prompt blocks NOW — every row is finally
        written, so other requests may attend to them."""
        meta = slot.pop("meta")
        del slot["prefill"]
        rid = slot["rid"]
        prompt, steps = meta["prompt"], meta["steps"]
        samp, cid = meta["samp"], meta["cid"]
        if self.radix is not None:
            n_hits, n_full = meta["n_hits"], meta["n_full"]
            fresh = []
            for j in range(n_hits, n_full):
                blk = int(self.tables[i, j])
                if meta["keys"][j] in self.radix.by_key:
                    # A concurrently-prefilling seat with the same
                    # prefix flipped first and published this key
                    # (the stall path can't race here — its admits
                    # serialize, so the second one WALKS into a hit).
                    # Our duplicate block stays privately owned and
                    # frees at finish; future walks hit theirs.
                    continue
                displaced = self.radix.register(
                    meta["keys"][j], meta["toks"][j], blk
                )
                if displaced is not None:
                    self.free.append(displaced)
                fresh.append(blk)
            # Registered blocks are shared (released through the
            # radix at finish), no longer privately owned.
            slot["shared"] = slot["shared"] + fresh
            slot["blocks"] = [
                b for b in slot["blocks"] if b not in fresh
            ]
        first = self._first_token(i, samp, lrow, prompt.dtype, cid)
        slot["remaining"] = steps - 1
        slot["last"] = first
        slot["toks"] = [prompt, first]
        slot["stop"] = matcher_or_none(meta["stop"])
        slot["cid"] = cid
        self._feed = self._feed.at[i].set(first[0].astype(jnp.int32))
        # ttft = queue wait + (shared) prefill ticks, observed at the
        # first token like every other admit path; strict pop drains
        # the submit timestamp with the request.
        self.obs.ttft.observe(
            time.perf_counter() - self._submit_t.pop(rid)
        )
        self._update_pool_gauges()
        need_host = (
            self.eos_id is not None
            or self.on_token is not None
            or slot["stop"] is not None
        )
        # analysis: ignore[host-sync-in-hot-loop] one scalar transfer
        # per REQUEST (its first token), and only when an
        # eos/stop/stream consumer needs the value — the admission
        # sync every admit path already performs
        tok = int(first[0, 0]) if need_host else None
        self._emit_token(i, slot, tok)

    def _account_kv_rows_mixed(self, posm, t: int) -> None:
        """Pool rows one mixed dispatch's attention read (decode-tick
        units): a [B, T] multi-token step whose row b attends through
        position posm[b] + t - 1. Derived from max_len (MB) and live
        spans, never pool size."""
        bs = self.bs
        baseline = self.B * self.MB * bs
        if self.attention == "gathered":
            rows_read = baseline
        elif self.attention == "blockwise":
            rows_read = (
                self.B
                * ((int(posm.max()) + t - 1) // bs + 1)
                * bs
            )
        else:  # pallas
            win = self.dec.cfg.window
            hi = (posm + t - 1) // bs
            lo = (
                np.maximum(posm + t - win, 0) // bs
                if win is not None
                else np.zeros_like(posm)
            )
            rows_read = int(np.sum(hi - lo + 1)) * bs
        self._account_kv_rows(rows_read, baseline)

    def _tick_mixed(self) -> None:
        """One MIXED dispatch: every live decode row advances exactly
        one token AND up to `prefill_budget` prompt tokens from the
        prefill seats ride along, all in one jitted multi-token
        forward (_mt_body — the spec-verify/chunked-prefill program).
        Per-row mode: decode rows feed their last token at pos with
        n_keep=1; seat rows feed their next chunk at base+done with
        n_keep=len(chunk); idle rows keep nothing and write trash.
        Sampling/eos/stop apply ONLY to decode rows; seat rows'
        logits are discarded except the final chunk's last position,
        which seeds the flip (_flip_seat)."""
        seats = self._seat_slots()
        decode_live = [
            s is not None and "prefill" not in s for s in self.slots
        ]
        self._build()
        mt = self._ensure_mt()
        limit = self.MB * self.bs
        # The fused program writes T contiguous-lane rows at EVERY
        # row's position (gathered path), so T is bounded by the
        # deepest live row — the same never-clamp invariant as
        # submit()'s spec_k headroom and _prefill_paged's tail cap.
        max_pos = max(
            int(self.pos[i])
            for i, s in enumerate(self.slots)
            if s is not None
        )
        t_limit = limit - max_pos
        chunk_cap = (
            self.prefill_chunk
            if self.prefill_chunk is not None
            else limit
        )
        T, ns = plan_mixed_tick(
            [self.slots[i]["prefill"].remaining for i in seats],
            self.prefill_budget,
            chunk_cap,
            t_limit,
        )
        ids_np = np.zeros((self.B, T), np.int32)
        n_keep = np.zeros((self.B,), np.int32)
        keep_from = np.zeros((self.B,), np.int32)
        posm = np.zeros((self.B,), np.int32)
        emit_idx = np.zeros((self.B,), np.int32)
        for i, s in enumerate(self.slots):
            if decode_live[i]:
                n_keep[i] = 1
                posm[i] = self.pos[i]
        planned: list[tuple[int, int]] = []  # (slot, n) with n >= 1
        total_new = 0
        for i, n in zip(seats, ns):
            if n <= 0:
                continue  # budget exhausted: the seat idles (trash)
            seat = self.slots[i]["prefill"]
            posm[i] = seat.pos
            keep_from[i] = seat.keep_from
            chunk = seat.take(n)
            ids_np[i, :n] = chunk
            n_keep[i] = n
            emit_idx[i] = n - 1
            planned.append((i, n))
            total_new += n
        # Decode rows' input token comes from the persistent device
        # feed — merged on device so the host never syncs on it.
        dec_mask = jnp.asarray(decode_live)[:, None]
        ids = jnp.asarray(ids_np)
        ids = ids.at[:, :1].set(
            jnp.where(dec_mask, self._feed, ids[:, :1])
        )
        logits, self.pool_k, self.pool_v = mt(
            self.params,
            self.pool_k,
            self.pool_v,
            jnp.asarray(self.tables.copy()),
            jnp.asarray(posm),
            ids,
            jnp.asarray(n_keep),
            jnp.asarray(keep_from),
            jnp.asarray(self.adapter.copy()),
        )
        self.ticks += 1
        self.dispatches += 1
        self.mixed_ticks_n += 1
        n_live = sum(decode_live)
        now = time.perf_counter()
        if self._last_tick_t is not None and n_live:
            self.obs.itl.observe(now - self._last_tick_t, n_live)
        self._last_tick_t = now
        self.obs.ticks.inc()
        self.obs.host_dispatches.inc()
        self.obs.mixed_prefill_tokens.inc(total_new)
        self.mixed_prefill_tokens_n += total_new
        self._update_stall_fraction()
        self._account_psums(1)
        self._account_kv_rows_mixed(posm, T)
        # Per-row emit position: 0 for decode rows, the chunk's last
        # real token for seats (only consumed when the seat flips).
        ll = jnp.take_along_axis(
            logits, jnp.asarray(emit_idx)[:, None, None], axis=1
        )[:, 0, :]
        ll_raw = ll  # pre-constraint rows, for seat flips
        sm = self._sampler
        constrained = any(sm.row_constrained)
        if constrained:
            crow, cacc = crt.constrain_rows(
                self._ctrans, self._cacc, sm.cid, sm.cstate
            )
            cmask = crt.constrain_mask(crow, cacc, self.eos_id)
            cvec = jnp.asarray(sm.row_constrained)
            dead = cvec & jnp.asarray(decode_live) & ~cmask.any(-1)
            ll = crt.fold_mask(ll, cmask)
        # Seat rows never steer the draw-vs-argmax choice: their
        # sampler rows install at flip (admit_first reseeds), so the
        # key stream matches the stall path draw for draw.
        if any(
            s is not None and "prefill" not in s and s["sampling"]
            for s in self.slots
        ):
            nxt = self._sampler.draw(ll)
        else:
            nxt = jnp.argmax(ll, axis=-1)
        if constrained:
            nxt = jnp.where(dead, self.eos_id, nxt)
            sm.cstate = crt.advance_state(
                crow, sm.cstate, nxt, cvec & ~dead
            )
            mfrac = crt.masked_frac(
                cmask, cvec & jnp.asarray(decode_live)
            )
        self._feed = nxt[:, None].astype(jnp.int32)
        need_host = (
            self.eos_id is not None
            or self.on_token is not None
            or any(
                s is not None and s.get("stop") is not None
                for s in self.slots
            )
        )
        # analysis: ignore[host-sync-in-hot-loop] single batched
        # transfer per mixed tick, and only when an eos/stop/stream
        # consumer needs host tokens — same guard as every tick path
        host_nxt = np.asarray(nxt) if need_host else None
        if constrained:
            # analysis: ignore[host-sync-in-hot-loop] one batched
            # per-tick transfer of the dead-end flags + mask
            # fractions, only while a constrained row is live
            dead_host = np.asarray(dead)
            # analysis: ignore[host-sync-in-hot-loop] ready with the
            # vector above (same sync point)
            mfrac_host = np.asarray(mfrac)
        accepted = 0
        for i, slot in enumerate(self.slots):
            if slot is None or not decode_live[i]:
                continue
            if constrained and slot["cid"]:
                if bool(dead_host[i]):
                    self.errors[slot["rid"]] = (
                        "constraint dead end: DFA state admits no "
                        "token and is not accepting"
                    )
                    self.constraint_dead_ends_n += 1
                    self.obs.constrain_dead_ends.inc()
                    slot["remaining"] = 0
                    self._finish(i)
                    continue
                self.constrained_tokens_n += 1
                self.obs.constrained_tokens.inc()
                self.obs.constrain_masked_frac.observe(
                    float(mfrac_host[i])
                )
            tok = nxt[i][None, None].astype(slot["last"].dtype)
            slot["last"] = tok
            slot["toks"].append(tok)
            slot["remaining"] -= 1
            self.pos[i] += 1
            accepted += 1
            self._emit_token(
                i,
                slot,
                int(host_nxt[i]) if host_nxt is not None else None,
            )
        # Seats advance AFTER the decode drain: pos moves chunk by
        # chunk, and the seat whose last chunk just landed flips to
        # decoding this very tick.
        for i, n in planned:
            slot = self.slots[i]
            seat = slot["prefill"]
            self.pos[i] = seat.pos
            if seat.finished:
                self._flip_seat(i, slot, ll_raw[i : i + 1])
                accepted += 1
        self.obs.tokens_per_dispatch.set(float(accepted))
        self.window_tokens += accepted

    def _tick(self) -> None:
        if self.pp > 1:
            return self._tick_pp()
        if self.spec_k:
            if self.decode_window > 1:
                return self._tick_spec_window()
            return self._tick_spec()
        if self._seat_slots():
            # Mixed mode engages only while a seat is mid-prefill;
            # pure-decode stretches fall through to the EXACT plain /
            # window programs (the prefill_budget=None bit-identity
            # contract, and the window scan's dispatch amortization).
            return self._tick_mixed()
        if self.decode_window > 1:
            return self._tick_window()
        live = [s is not None for s in self.slots]
        if not any(live):
            return
        self._build()
        # Persistent [B,1] device feed (constructor note): admissions
        # set their row, draws below overwrite the whole vector — no
        # per-tick concat of max_batch [1,1] arrays.
        feed = self._feed
        # Idle slots write into trash block 0 at position 0.
        posm = np.where(live, self.pos, 0).astype(np.int32)
        pos = jnp.asarray(posm)
        # COPY the mutable host state before handing it to the device:
        # jnp.asarray of a numpy array is zero-copy on CPU, and the
        # host loop mutates tables/adapter in place (finish/admission)
        # while the async-dispatched step may still be reading them —
        # the aliasing race corrupts first-execution results.
        logits, self.pool_k, self.pool_v = self._step(
            self.params,
            self.pool_k,
            self.pool_v,
            jnp.asarray(self.tables.copy()),
            pos,
            feed,
            jnp.asarray(self.adapter.copy()),
        )
        self.ticks += 1
        self.dispatches += 1
        n_live = sum(live)
        now = time.perf_counter()
        if self._last_tick_t is not None:
            self.obs.itl.observe(now - self._last_tick_t, n_live)
        self._last_tick_t = now
        self.obs.ticks.inc()
        self.obs.host_dispatches.inc()
        # Every decode tick moves the stall fraction's denominator —
        # republished here so the gauge decays as decode resumes (the
        # [contract.mixed] budget gate reads it).
        self._update_stall_fraction()
        self._account_psums(1)
        self.obs.tokens_per_dispatch.set(float(n_live))
        self.window_tokens += n_live
        # K/V rows the attention path read this tick vs the gathered
        # baseline (host-side, exact — the counters the bandwidth win
        # is pinned by; units in obs/serving.py). "blockwise" reads
        # every slot to the batch's deepest live block; "pallas"
        # clamps per slot, so each reads only its own live span.
        baseline = self.B * self.MB * self.bs
        if self.attention == "gathered":
            rows_read = baseline
        elif self.attention == "blockwise":
            rows_read = (
                self.B * (int(posm.max()) // self.bs + 1) * self.bs
            )
        else:  # pallas
            win = self.dec.cfg.window
            lo = (
                np.maximum(posm - win + 1, 0) // self.bs
                if win is not None
                else 0
            )
            rows_read = int(np.sum(posm // self.bs - lo + 1)) * self.bs
        self._account_kv_rows(rows_read, baseline)
        ll = logits[:, -1, :]
        sm = self._sampler
        # Constrained rows (defer_tpu/constrain/): fold the DFA mask
        # into the batched logits BEFORE argmax/draw, advance states
        # after. Guarded by the host mirror so unconstrained serving
        # dispatches the exact pre-constraint op sequence.
        constrained = any(sm.row_constrained)
        if constrained:
            crow, cacc = crt.constrain_rows(
                self._ctrans, self._cacc, sm.cid, sm.cstate
            )
            cmask = crt.constrain_mask(crow, cacc, self.eos_id)
            cvec = jnp.asarray(sm.row_constrained)
            # Dead end (hand-built DFAs only — dfa.py prunes): no
            # admissible token. Force eos so the row freezes; the
            # drain drops the forced token and surfaces the error.
            dead = cvec & jnp.asarray(live) & ~cmask.any(-1)
            ll = crt.fold_mask(ll, cmask)
        if any(s is not None and s["sampling"] for s in self.slots):
            nxt = self._sampler.draw(ll)
        else:
            nxt = jnp.argmax(ll, axis=-1)
        if constrained:
            nxt = jnp.where(dead, self.eos_id, nxt)
            sm.cstate = crt.advance_state(
                crow, sm.cstate, nxt, cvec & ~dead
            )
            mfrac = crt.masked_frac(cmask, cvec & jnp.asarray(live))
        self._feed = nxt[:, None].astype(jnp.int32)
        # Host transfer only when eos/streaming/stop matching needs
        # the values — the plain path stays async (same guard as the
        # flat server).
        need_host = (
            self.eos_id is not None
            or self.on_token is not None
            or any(
                s is not None and s["stop"] is not None
                for s in self.slots
            )
        )
        # analysis: ignore[host-sync-in-hot-loop] single batched
        # transfer per WINDOW (a window of one token here), and only
        # when an eos/stop/stream consumer needs host tokens — the
        # sync this serving loop is designed around
        host_nxt = np.asarray(nxt) if need_host else None
        if constrained:
            # analysis: ignore[host-sync-in-hot-loop] one batched
            # per-tick transfer of the dead-end flags + mask
            # fractions, and only while a constrained row is live
            dead_host = np.asarray(dead)
            # analysis: ignore[host-sync-in-hot-loop] ready with the
            # vector above (same sync point)
            mfrac_host = np.asarray(mfrac)
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            if constrained and slot["cid"]:
                if bool(dead_host[i]):
                    # The forced eos never enters the output: the
                    # request ends at its last admissible token with
                    # a per-request error, not a hang.
                    self.errors[slot["rid"]] = (
                        "constraint dead end: DFA state admits no "
                        "token and is not accepting"
                    )
                    self.constraint_dead_ends_n += 1
                    self.obs.constrain_dead_ends.inc()
                    slot["remaining"] = 0
                    self._finish(i)
                    continue
                self.constrained_tokens_n += 1
                self.obs.constrained_tokens.inc()
                self.obs.constrain_masked_frac.observe(
                    float(mfrac_host[i])
                )
            tok = nxt[i][None, None].astype(slot["last"].dtype)
            slot["last"] = tok
            slot["toks"].append(tok)
            slot["remaining"] -= 1
            self.pos[i] += 1
            self._emit_token(
                i, slot, int(host_nxt[i]) if host_nxt is not None else None
            )

    def _tick_spec(self) -> None:
        """One speculative round: TWO host dispatches advance every
        greedy slot up to spec_k + 1 tokens (ARCHITECTURE.md
        "Speculative serving" has the full semantics).

        1. DRAFT PROPOSE (DraftLanes.propose, one fused program):
           each greedy slot's lane catches up on its 1-2 pending
           committed tokens, then emits k greedy proposals.
        2. TARGET VERIFY (_mt_body, T = k + 1): row 0 is the slot's
           feed token, rows 1..k the proposals; all k + 1 candidate
           K/V rows scatter into the slot's pool blocks in the same
           dispatch (sampled slots keep row 0 only, idle slots none —
           trash-redirected dead writes).
        3. ONE batched host transfer of (preds, props[, sampled
           draws]) feeds the accept test (batching.accept_lengths):
           slot i emits props[:a] plus the target's own token at the
           first mismatch (or the bonus row on full accept) — the
           greedy chain is the target's chain, token for token, so
           output is bit-identical to spec_k=0. Rejected rows sit
           stale behind the position mask; the next round's verify
           span rewrites them before they can ever be read.

        Sampled slots advance exactly ONE token per round, drawn from
        the verify forward's row 0 through the shared SlotSampler —
        one draw call per round, same as one draw per tick at
        spec_k=0, so sampled streams are bit-identical too."""
        live = [s is not None for s in self.slots]
        if not any(live):
            return
        self._build()
        k = self.spec_k
        mt = self._ensure_mt()
        # Per-slot draft-round inputs. pend = tokens emitted but not
        # yet in the draft lane (1 after a partial accept, 2 after a
        # full accept — the k-th proposal is never self-consumed, and
        # the bonus token never proposed); the lane's write head is
        # pos + 1 - len(pend) by that definition. Idle and sampled
        # rows pin to 0, the idle-lane idiom, so their dead writes
        # stay bounded and every live lane is re-fed from host truth.
        feed2 = np.zeros((self.B, 2), np.int32)
        adv = np.zeros((self.B,), np.int32)
        dposm = np.zeros((self.B,), np.int32)
        for i, slot in enumerate(self.slots):
            if slot is None or slot["sampling"]:
                continue
            pend = slot["pend"]
            adv[i] = len(pend)
            feed2[i, 0] = pend[0]
            feed2[i, 1] = pend[-1]  # len-1 pend feeds its token twice
            dposm[i] = self.pos[i] + 1 - len(pend)
        sm = self._sampler
        constrained = any(sm.row_constrained)
        if constrained:
            # Lane-side masking: the draft's proposal chain walks the
            # slot's DFA from its committed state, so candidates stay
            # grammar-valid (acceptance, not correctness — the
            # target-side masked preds below are the contract).
            props = self._draft.propose_c(
                k, dposm, feed2, adv, self.eos_id,
                sm.cid, sm.cstate, self._ctrans, self._cacc,
            )  # [B, k]
        else:
            props = self._draft.propose(k, dposm, feed2, adv)  # [B, k]
        # Verify all k+1 positions in ONE block-table forward: row 0
        # re-derives each slot's next token from its feed (the greedy
        # correctness anchor), rows 1..k check the proposals.
        verify_in = jnp.concatenate(
            [self._feed, props.astype(jnp.int32)], axis=1
        )
        n_keep = np.zeros((self.B,), np.int32)
        for i, slot in enumerate(self.slots):
            if slot is not None:
                n_keep[i] = 1 if slot["sampling"] else k + 1
        posm = np.where(live, self.pos, 0).astype(np.int32)
        # Same aliasing-copy rule as the K=1 tick: tables/adapter are
        # host-mutated by finish/admission while the dispatched verify
        # may still be reading them.
        logits, self.pool_k, self.pool_v = mt(
            self.params,
            self.pool_k,
            self.pool_v,
            jnp.asarray(self.tables.copy()),
            jnp.asarray(posm),
            verify_in,
            jnp.asarray(n_keep),
            jnp.zeros((self.B,), jnp.int32),
            jnp.asarray(self.adapter.copy()),
        )
        if constrained:
            # Target-side constrained preds: a device state walk along
            # the proposal prefix (pred_j = masked argmax at s_j,
            # s_{j+1} = trans[s_j, props_j]), so the accept rule below
            # truncates at the first proposal the TARGET's mask
            # rejects — constrained greedy output is the spec_k=0
            # constrained chain, token for token. Dead states force
            # pred_j to -1 (out of vocab): never accepted, and the
            # correction token is dropped host-side with the error.
            (preds, crow0, cmask0, post_states, dead_all,
             fracs) = self._constrained_preds(logits, props, k)
        else:
            preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        any_sampling = any(
            s is not None and s["sampling"] for s in self.slots
        )
        draw = None
        if any_sampling:
            ll0 = logits[:, 0, :]
            if constrained:
                # Sampled constrained rows draw from the masked row;
                # free rows' fold is an exact no-op (cid-0 mask).
                ll0 = crt.fold_mask(ll0, cmask0)
            draw = self._sampler.draw(ll0)
        self.ticks += 1
        self.dispatches += 2
        n_live = sum(live)
        now = time.perf_counter()
        if self._last_tick_t is not None:
            self.obs.itl.observe(now - self._last_tick_t, n_live)
        self._last_tick_t = now
        self.obs.ticks.inc()
        self.obs.host_dispatches.inc(2)
        # Only the verify forward runs sharded; the draft's flat lanes
        # are replicated host-side state, no collectives.
        self._account_psums(1)
        # Pool rows the verify forward read (same units/contract as
        # the K=1 tick; the draft reads its own flat lanes, not the
        # pool). The deepest query row of slot i attends at pos + k.
        baseline = self.B * self.MB * self.bs
        if self.attention == "gathered":
            rows_read = baseline
        elif self.attention == "blockwise":
            rows_read = (
                self.B
                * ((int(posm.max()) + k) // self.bs + 1)
                * self.bs
            )
        else:  # pallas
            win = self.dec.cfg.window
            hi = (posm + k) // self.bs
            lo = (
                np.maximum(posm - win + 1, 0) // self.bs
                if win is not None
                else np.zeros_like(posm)
            )
            rows_read = int(np.sum(hi - lo + 1)) * self.bs
        self._account_kv_rows(rows_read, baseline)
        # analysis: ignore[host-sync-in-hot-loop] the ONE batched
        # accept-test transfer per speculative ROUND — up to k+1
        # tokens per slot amortize it, the sync the round is designed
        # around (spec_accept fixtures pin the shape)
        preds_host = np.asarray(preds)
        # analysis: ignore[host-sync-in-hot-loop] proposal half of the
        # same batched round transfer (ready with the verify above)
        props_host = np.asarray(props)
        if draw is not None:
            # analysis: ignore[host-sync-in-hot-loop] sampled rows'
            # slice of the same per-round sync point
            draw_host = np.asarray(draw)
        if constrained:
            # analysis: ignore[host-sync-in-hot-loop] dead-end flags +
            # mask fractions ride the same batched round transfer,
            # only while a constrained row is live
            dead_host = np.asarray(dead_all)
            # analysis: ignore[host-sync-in-hot-loop] same per-round
            # sync point (ready with the matrix above)
            fracs_host = np.asarray(fracs)
        a_vec = accept_lengths(props_host, preds_host[:, :k])
        proposed = 0
        accepted_draft = 0
        draft_toks = 0
        accepted = [0] * self.B
        finishing = [False] * self.B
        toks_host: list[list[int] | None] = [None] * self.B
        feedv = np.zeros((self.B,), np.int32)
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            dead_i = False
            if slot["sampling"]:
                emitted = [int(draw_host[i])]
                if constrained and slot["cid"] and dead_host[i][0]:
                    dead_i, emitted = True, []
            else:
                # analysis: ignore[host-sync-in-hot-loop] a_vec is
                # host numpy (accept_lengths of the batched fetch)
                a = int(a_vec[i])
                proposed += k
                accepted_draft += a
                # analysis: ignore[host-sync-in-hot-loop] adv is the
                # host round-0 seed (np.zeros filled from slot pend)
                draft_toks += int(adv[i]) + k - 1
                self.obs.spec_acceptance.observe(a)
                emitted = [int(t) for t in props_host[i, :a]]
                emitted.append(int(preds_host[i, a]))
                if constrained and slot["cid"] and dead_host[i][a]:
                    # The correction position hit a dead DFA state:
                    # its pred is the -1 sentinel, dropped here, so
                    # the stream ends at the still-valid accepted
                    # prefix with a per-request error, not a hang.
                    dead_i = True
                    emitted = emitted[:-1]
            # Per-token drain, K=1-equivalent: budget, then eos, then
            # stop — the first terminator wins and everything after it
            # is discarded (a truncated slot always finishes, so the
            # continuing-slot feed/pend math below never sees a cut).
            room = slot["remaining"]
            kept = 0
            stopped = False
            for tok in emitted:
                if kept >= room:
                    break
                kept += 1
                if self.eos_id is not None and tok == self.eos_id:
                    stopped = True
                    break
                if slot["stop"] is not None and slot["stop"].push(tok):
                    stopped = True
                    break
            if kept < len(emitted):
                self.obs.window_truncated.inc()
            slot["remaining"] -= kept
            if stopped:
                slot["remaining"] = 0
            if dead_i and kept == len(emitted) and not stopped:
                # Dead end actually reached (not pre-empted by a
                # budget cut or stop hit inside the kept prefix).
                slot["remaining"] = 0
                self.errors[slot["rid"]] = (
                    "constraint dead end: DFA state admits no token "
                    "and is not accepting"
                )
                self.constraint_dead_ends_n += 1
                self.obs.constrain_dead_ends.inc()
            if constrained and slot["cid"]:
                self.constrained_tokens_n += kept
                if kept:
                    self.obs.constrained_tokens.inc(kept)
                for j in range(kept):
                    self.obs.constrain_masked_frac.observe(
                        float(fracs_host[i][j])
                    )
            # analysis: ignore[host-sync-in-hot-loop] emitted is a
            # host int list — this UPLOADS the kept tokens, no fetch
            kept_arr = np.asarray(emitted[:kept], np.int32)[None, :]
            tok_block = jnp.asarray(kept_arr).astype(
                slot["last"].dtype
            )
            slot["toks"].append(tok_block)
            slot["last"] = tok_block[:, -1:]
            self.pos[i] += kept
            accepted[i] = kept
            toks_host[i] = emitted[:kept]
            finishing[i] = slot["remaining"] == 0
            self.obs.tokens_generated.inc(kept)
            self.window_tokens += kept
            feedv[i] = emitted[-1] if emitted else 0
            if not slot["sampling"] and not finishing[i]:
                # kept == a + 1 here (truncation implies finish):
                # partial accept leaves only the correction token
                # pending; full accept also leaves the never-consumed
                # k-th proposal.
                if a < k:
                    slot["pend"] = [emitted[-1]]
                else:
                    slot["pend"] = [
                        int(props_host[i, k - 1]), emitted[-1],
                    ]
                self._draft.pos[i] = (
                    self.pos[i] + 1 - len(slot["pend"])
                )
        if constrained:
            # Commit DFA states for rows continuing past the round —
            # greedy rows select the post-state column at their accept
            # length (the state after the round's LAST emitted token),
            # sampled rows advance one step by their draw. Pure UPLOAD
            # + device gather; finishing rows keep their state and are
            # reset by release below.
            sel = np.zeros((self.B,), np.int32)
            use_post = np.zeros((self.B,), bool)
            use_draw = np.zeros((self.B,), bool)
            for i, slot in enumerate(self.slots):
                if slot is None or not slot["cid"] or finishing[i]:
                    continue
                if slot["sampling"]:
                    use_draw[i] = True
                else:
                    use_post[i] = True
                    # analysis: ignore[host-sync-in-hot-loop] a_vec is
                    # host numpy (accept_lengths of the batched fetch)
                    sel[i] = int(a_vec[i])
            new_c = jnp.take_along_axis(
                post_states, jnp.asarray(sel)[:, None], 1
            )[:, 0]
            cst = jnp.where(jnp.asarray(use_post), new_c, sm.cstate)
            if draw is not None:
                cst = crt.advance_state(
                    crow0, cst, draw, jnp.asarray(use_draw)
                )
            sm.cstate = cst
        self._feed = jnp.asarray(feedv[:, None])
        self.spec_rounds_n += 1
        self.spec_proposed_n += proposed
        self.spec_accepted_n += accepted_draft
        self.spec_draft_tokens_n += draft_toks
        self.obs.spec_rounds.inc()
        if proposed:
            self.obs.spec_proposed.inc(proposed)
        if accepted_draft:
            self.obs.spec_accepted.inc(accepted_draft)
        if draft_toks:
            self.obs.spec_draft_tokens.inc(draft_toks)
        # Mean per-dispatch yield: a round is two dispatches.
        self.obs.tokens_per_dispatch.set(float(sum(accepted)) / 2.0)
        if self.on_token is not None:
            for t, i in window_drain_order(accepted, k + 1):
                slot = self.slots[i]
                self.on_token(
                    slot["rid"],
                    toks_host[i][t],
                    finishing[i] and t == accepted[i] - 1,
                )
        for i in range(self.B):
            if finishing[i]:
                self._finish(i)

    def _tick_spec_window(self) -> None:
        """W = decode_window speculative rounds in ONE host dispatch
        (_build_spec_window): the draft propose + target verify +
        accept test + pend recurrence all live inside the fused scan,
        so a window costs 1 dispatch and 1 batched sync where the
        unfused path costs 2W dispatches and W syncs. Greedy output
        is token-identical to spec_k=0 (and to decode_window=1
        speculation); stop sequences cut on drain with overshoot
        discarded, the _tick_window contract."""
        live = [s is not None for s in self.slots]
        if not any(live):
            return
        self._build()
        k, W = self.spec_k, self.decode_window
        sampling_rows = [
            s is not None and s["sampling"] for s in self.slots
        ]
        if not any(sampling_rows):
            mode = "argmax"
        elif any(self._sampler.row_sort):
            mode = "sort"
        else:
            mode = "nosort"
        constrained = any(self._sampler.row_constrained)
        prog = (
            self._build_spec_window_c(mode)
            if constrained
            else self._build_spec_window(mode)
        )
        # Round-0 seeds from host truth, exactly _tick_spec's: pend =
        # committed-but-unconsumed draft tokens, lane write head
        # pos + 1 - len(pend).
        feed2 = np.zeros((self.B, 2), np.int32)
        adv = np.zeros((self.B,), np.int32)
        dposm = np.zeros((self.B,), np.int32)
        for i, slot in enumerate(self.slots):
            if slot is None or slot["sampling"]:
                continue
            pend = slot["pend"]
            adv[i] = len(pend)
            feed2[i, 0] = pend[0]
            feed2[i, 1] = pend[-1]
            dposm[i] = self.pos[i] + 1 - len(pend)
        budget = [
            s["remaining"] if s is not None else 0
            for s in self.slots
        ]
        posm = np.where(live, self.pos, 0).astype(np.int32)
        sm = self._sampler
        # Same aliasing-copy rule as every tick: tables/adapter are
        # host-mutated by finish/admission while the dispatched window
        # may still be reading them.
        operands = (
            self.params, self.pool_k, self.pool_v,
            self._draft.ck, self._draft.cv, self._draft.params,
            jnp.asarray(self.tables.copy()), jnp.asarray(posm),
            jnp.asarray(dposm), self._feed, jnp.asarray(feed2),
            jnp.asarray(adv), jnp.asarray(live),
            jnp.asarray(sampling_rows), sm.keys, sm.temp, sm.topk,
            sm.topp, sm.minp, jnp.asarray(budget, jnp.int32),
            jnp.asarray(self.adapter.copy()),
        )
        died = fracs_a = None
        if constrained:
            (self.pool_k, self.pool_v, dk, dv, feed, feed2_o, adv_o,
             alive, keys, toks_a, kept_a, a_a, greedy_a, advu_a,
             cstate, died, fracs_a) = prog(
                *operands, sm.cid, sm.cstate, self._ctrans, self._cacc,
            )
            sm.cstate = cstate
        else:
            (self.pool_k, self.pool_v, dk, dv, feed, feed2_o, adv_o,
             alive, keys, toks_a, kept_a, a_a, greedy_a,
             advu_a) = prog(*operands)
        self._draft.ck, self._draft.cv = dk, dv
        self._feed = feed
        sm.keys = keys
        self.ticks += 1
        self.dispatches += 1
        n_live = sum(live)
        now = time.perf_counter()
        if self._last_tick_t is not None:
            self.obs.itl.observe(now - self._last_tick_t, n_live)
        self._last_tick_t = now
        self.obs.ticks.inc()
        self.obs.host_dispatches.inc()
        # W verify forwards' worth of collectives per dispatch (the
        # draft forward is replicated, no psums — _tick_spec's rule).
        self._account_psums(W)
        # The ONE batched sync per window: the [W, B, k+1] token
        # buffer plus the per-round kept/accept vectors — every piece
        # of drain bookkeeping reads these host copies.
        # analysis: ignore[host-sync-in-hot-loop] the ONE batched
        # [W, B, k+1] token transfer per fused spec window — up to
        # W*(k+1) tokens per slot amortize it (spec_window fixtures
        # pin the shape)
        toks_h = np.asarray(toks_a)
        # analysis: ignore[host-sync-in-hot-loop] per-round kept
        # counts, same per-window sync point (ready with the tokens)
        kept_h = np.asarray(kept_a)
        # analysis: ignore[host-sync-in-hot-loop] per-round accept
        # lengths, same batched per-window sync point
        a_h = np.asarray(a_a)
        # analysis: ignore[host-sync-in-hot-loop] per-round proposer
        # masks, same batched sync point
        greedy_h = np.asarray(greedy_a)
        # analysis: ignore[host-sync-in-hot-loop] per-round draft
        # catch-up counts, same batched sync point
        advu_h = np.asarray(advu_a)
        # analysis: ignore[host-sync-in-hot-loop] final liveness, same
        # batched sync point
        alive_h = np.asarray(alive)
        # analysis: ignore[host-sync-in-hot-loop] pend recurrence feed
        # pair, same batched sync point
        feed2_h = np.asarray(feed2_o)
        # analysis: ignore[host-sync-in-hot-loop] pend recurrence
        # advance, same batched sync point
        adv_h = np.asarray(adv_o)
        if constrained:
            # analysis: ignore[host-sync-in-hot-loop] dead-end flags,
            # same batched per-window sync point
            died_h = np.asarray(died)
            # analysis: ignore[host-sync-in-hot-loop] masked-fraction
            # buffer for obs, same batched per-window sync point
            fracs_h = np.asarray(fracs_a)
        # Verify-read accounting: the per-round mirror of _tick_spec's
        # (active rows read to pos_r + k; frozen rows sit at trash
        # position 0). Pure host python over the fetched counts.
        baseline = W * self.B * self.MB * self.bs
        if self.attention == "gathered":
            rows_read = baseline
        else:
            win = self.dec.cfg.window
            pos_l = posm.tolist()
            rows_read = 0
            for r in range(W):
                pe = [
                    p if kept_h[r][i] > 0 else 0
                    for i, p in enumerate(pos_l)
                ]
                if self.attention == "blockwise":
                    rows_read += (
                        self.B
                        * ((max(pe) + k) // self.bs + 1)
                        * self.bs
                    )
                else:  # pallas
                    rows_read += self.bs * sum(
                        (p + k) // self.bs
                        - (max(p - win + 1, 0) // self.bs
                           if win is not None else 0)
                        + 1
                        for p in pe
                    )
                pos_l = [
                    p + int(kept_h[r][i])
                    for i, p in enumerate(pos_l)
                ]
        self._account_kv_rows(rows_read, baseline)
        # Drain: per slot, walk the rounds in order; stop sequences
        # cut on the host (push_window per round) and discard the
        # overshoot the device kept generating — the _tick_window
        # contract. eos/budget freezes already happened on device.
        proposed = 0
        accepted_draft = 0
        draft_toks = 0
        rounds_run = 0
        kept_rounds: list[list[int]] = [[0] * self.B for _ in range(W)]
        total = [0] * self.B
        finishing = [False] * self.B
        stream_toks: list[list[list[int]] | None] = [None] * self.B
        for r in range(W):
            ran = False
            for i, slot in enumerate(self.slots):
                if slot is None:
                    continue
                if greedy_h[r][i]:
                    ran = True
                    proposed += k
                    a_r = int(a_h[r][i])
                    accepted_draft += a_r
                    draft_toks += int(advu_h[r][i]) + k - 1
                    self.obs.spec_acceptance.observe(a_r)
                n_r = int(kept_h[r][i])
                if n_r == 0:
                    continue
                row = [int(t) for t in toks_h[r][i][:n_r]]
                if finishing[i]:
                    row = []  # overshoot past a stop cut
                elif slot["stop"] is not None:
                    hit = slot["stop"].push_window(row)
                    if hit is not None:
                        row = row[:hit]
                        finishing[i] = True
                        self.obs.window_truncated.inc()
                kept_rounds[r][i] = len(row)
                total[i] += len(row)
                if stream_toks[i] is None:
                    stream_toks[i] = [[] for _ in range(W)]
                stream_toks[i][r] = row
            if ran:
                rounds_run += 1
        for i, slot in enumerate(self.slots):
            if slot is None or not constrained:
                continue
            if slot["cid"] and died_h[i] and not finishing[i]:
                # Dead-end DFA state mid-window: the device froze the
                # row with a forced eos — the slot's LAST kept token
                # (a stop cut would have discarded it as overshoot,
                # hence the finishing guard). Drop it so the output
                # ends at the last admissible token and the failure
                # surfaces as a per-request error, not a hang.
                for r in range(W - 1, -1, -1):
                    if kept_rounds[r][i]:
                        kept_rounds[r][i] -= 1
                        stream_toks[i][r].pop()
                        total[i] -= 1
                        break
                self.errors[slot["rid"]] = (
                    "constraint dead end: DFA state admits no token "
                    "and is not accepting"
                )
                self.constraint_dead_ends_n += 1
                self.obs.constrain_dead_ends.inc()
            if slot["cid"]:
                for r in range(W):
                    kr = kept_rounds[r][i]
                    self.constrained_tokens_n += kr
                    if kr:
                        self.obs.constrained_tokens.inc(kr)
                    for j in range(kr):
                        self.obs.constrain_masked_frac.observe(
                            float(fracs_h[r][i][j])
                        )
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            n_i = total[i]
            slot["remaining"] -= n_i
            if finishing[i] or not alive_h[i]:
                slot["remaining"] = 0
            # analysis: ignore[host-sync-in-hot-loop] packs already-
            # fetched host token lists (no device fetch)
            kept_arr = np.asarray(
                [
                    t
                    for r in range(W)
                    for t in (stream_toks[i][r]
                              if stream_toks[i] else [])
                ],
                np.int32,
            )[None, :]
            # jnp.asarray is a host->device upload of the kept tokens
            # (no fetch) — _tick_spec's idiom; not a sync hazard.
            tok_block = jnp.asarray(kept_arr).astype(
                slot["last"].dtype
            )
            if n_i:
                slot["toks"].append(tok_block)
                slot["last"] = tok_block[:, -1:]
            self.pos[i] += n_i
            finishing[i] = slot["remaining"] == 0
            self.obs.tokens_generated.inc(n_i)
            self.window_tokens += n_i
            if not slot["sampling"] and not finishing[i]:
                # Continuing greedy rows: reconstruct pend from the
                # device recurrence's final (feed2, adv) — host truth
                # for the next window's round-0 seed.
                av = int(adv_h[i])
                slot["pend"] = [
                    int(t) for t in feed2_h[i][2 - av:]
                ]
                self._draft.pos[i] = (
                    self.pos[i] + 1 - len(slot["pend"])
                )
        self.spec_rounds_n += rounds_run
        self.spec_proposed_n += proposed
        self.spec_accepted_n += accepted_draft
        self.spec_draft_tokens_n += draft_toks
        self.obs.spec_rounds.inc(rounds_run)
        if proposed:
            self.obs.spec_proposed.inc(proposed)
        if accepted_draft:
            self.obs.spec_accepted.inc(accepted_draft)
        if draft_toks:
            self.obs.spec_draft_tokens.inc(draft_toks)
        self.obs.tokens_per_dispatch.set(float(sum(total)))
        if self.on_token is not None:
            last_r = [
                max(
                    (r for r in range(W) if kept_rounds[r][i]),
                    default=0,
                )
                for i in range(self.B)
            ]
            for r in range(W):
                for t, i in window_drain_order(
                    kept_rounds[r], k + 1
                ):
                    slot = self.slots[i]
                    self.on_token(
                        slot["rid"],
                        stream_toks[i][r][t],
                        finishing[i]
                        and r == last_r[i]
                        and t == kept_rounds[r][i] - 1,
                    )
        for i in range(self.B):
            if finishing[i]:
                self._finish(i)

    def _tick_window(self) -> None:
        """One fused dispatch of up to decode_window tokens per live
        slot (_build_window); ONE batched host transfer drains the
        [B, K] token buffer (plus tiny valid-length/alive vectors when
        eos is configured)."""
        live = [s is not None for s in self.slots]
        if not any(live):
            return
        self._build()
        K = self.decode_window
        sampling = any(
            s is not None and s["sampling"] for s in self.slots
        )
        if not sampling:
            mode = "argmax"
        elif any(self._sampler.row_sort):
            mode = "sort"
        else:
            mode = "nosort"
        budget = [
            s["remaining"] if s is not None else 0
            for s in self.slots
        ]
        posm = np.where(live, self.pos, 0).astype(np.int32)
        sm = self._sampler
        constrained = any(sm.row_constrained)
        died = fracs = None
        # Same aliasing-copy rule as the K=1 tick: tables/adapter are
        # mutated by the host (finish/admission) while the dispatched
        # window may still be reading them.
        if constrained:
            window = self._build_window_c(mode)
            (self.pool_k, self.pool_v, feed, alive, keys, n_dev,
             toks, cstate, died, fracs) = window(
                self.params, self.pool_k, self.pool_v,
                jnp.asarray(self.tables.copy()), jnp.asarray(posm),
                self._feed, jnp.asarray(live), sm.keys, sm.temp,
                sm.topk, sm.topp, sm.minp,
                jnp.asarray(budget, jnp.int32),
                jnp.asarray(self.adapter.copy()),
                sm.cid, sm.cstate, self._ctrans, self._cacc,
            )
            sm.cstate = cstate
        else:
            window = self._build_window(mode)
            (self.pool_k, self.pool_v, feed, alive, keys, n_dev,
             toks) = window(
                self.params, self.pool_k, self.pool_v,
                jnp.asarray(self.tables.copy()), jnp.asarray(posm),
                self._feed, jnp.asarray(live), sm.keys, sm.temp,
                sm.topk, sm.topp, sm.minp,
                jnp.asarray(budget, jnp.int32),
                jnp.asarray(self.adapter.copy()),
            )
        self._feed = feed
        sm.keys = keys
        self.ticks += 1
        self.dispatches += 1
        n_live = sum(live)
        now = time.perf_counter()
        if self._last_tick_t is not None:
            self.obs.itl.observe(now - self._last_tick_t, n_live)
        self._last_tick_t = now
        self.obs.ticks.inc()
        self.obs.host_dispatches.inc()
        self._update_stall_fraction()
        # The fused window scans K sub-steps inside ONE sharded
        # program: K forwards' worth of collectives per dispatch.
        self._account_psums(K)
        need_toks = self.on_token is not None or any(
            s is not None and s["stop"] is not None
            for s in self.slots
        )
        if self.eos_id is not None:
            # analysis: ignore[host-sync-in-hot-loop] one batched
            # per-WINDOW transfer of the valid-length/alive vectors
            # — K tokens amortize this sync, the point of the window
            emitted = np.asarray(n_dev).tolist()
            # analysis: ignore[host-sync-in-hot-loop] same per-window
            # sync point (ready with the vector above)
            alive_host = np.asarray(alive).tolist()
        else:
            # No eos: the device can only freeze rows on budget,
            # which the host already knows — no transfer needed.
            emitted = [min(b, K) for b in budget]
            alive_host = [b > K for b in budget]
        # analysis: ignore[host-sync-in-hot-loop] the ONE batched
        # [B, K] token transfer per window that replaces K per-tick
        # [B, 1] transfers — only when a stream/stop consumer exists
        toks_host = np.asarray(toks).tolist() if need_toks else None
        died_host = fracs_host = None
        if constrained:
            # analysis: ignore[host-sync-in-hot-loop] rides the same
            # per-window sync: batched dead-end flags + [B, K] mask
            # fractions, only while a constrained row is live
            died_host = np.asarray(died).tolist()
            # analysis: ignore[host-sync-in-hot-loop] same per-window
            # sync point (ready with the vector above)
            fracs_host = np.asarray(fracs)
        self._account_kv_rows_window(posm, emitted)
        self._drain_window(toks, toks_host, emitted, alive_host,
                           budget, died_host, fracs_host)

    def _probe_pp_layer_costs(self, num_blocks: int) -> list[float]:
        """Per-layer amortized step cost for pp_balance="probe"
        (parallel/pipeline.py::probe_latency methodology): each layer
        is wrapped in a throwaway single-layer stage with a 2-block
        pool and timed on a [1, 1] decode round. Boundary costs are
        attributed honestly — layer 0 carries the embedding, the last
        layer the final norm + head — so balance_stage_cuts sees the
        work a stage would actually run."""
        from defer_tpu.parallel.pipeline import probe_latency

        cfg = self.dec.cfg
        tab = jnp.zeros((1, self.MB), jnp.int32)
        pos = jnp.zeros((1,), jnp.int32)
        nk = jnp.ones((1,), jnp.int32)
        kf = jnp.zeros((1,), jnp.int32)
        ad = jnp.zeros((1,), jnp.int32)
        ids = jnp.zeros((1, 1), jnp.int32)
        act = jnp.zeros((1, 1, cfg.dim), self.dec.compute_dtype)
        costs = []
        for layer in range(cfg.num_layers):
            stage = _PPLocalStage(
                self.dec, self.params, layer, layer + 1,
                num_blocks=2,
                block_size=self.bs,
                attention=self.attention,
            )
            xin = ids if layer == 0 else act
            sample = probe_latency(
                stage.pp_dispatch, tab, pos, xin, nk, kf, ad, iters=3
            )
            costs.append(sample["amortized_s"])
        return costs

    def _build_pp_ctl(self, mode: str):
        """Jitted per-round controller for the pipelined decode loop:
        the sample/advance/freeze tail of ONE _build_window sub-step,
        lifted out of the stage programs so it runs once per
        (round, group) on the last stage's output. The freeze math is
        copied verbatim from the window body — same argmax/draw trio,
        same budget/eos gating, same pos/table zeroing — which is what
        pins pp greedy output token-identical to pp_stages=1."""
        from defer_tpu.utils.memo import cached_step

        eos = self.eos_id

        def build():
            def ctl(ll, keys, temp, topk, topp, minp, pos, n, active,
                    budget, tables):
                if mode == "argmax":
                    nxt = jnp.argmax(ll, axis=-1)
                elif mode == "nosort":
                    nxt, keys = sample_token_batched_nosort(
                        ll, keys, temp, minp
                    )
                else:
                    nxt, keys = sample_token_batched(
                        ll, keys, temp, topk, topp, minp
                    )
                adv = active.astype(jnp.int32)
                pos = pos + adv
                n = n + adv
                alive = active & (n < budget)
                if eos is not None:
                    alive = alive & (nxt != eos)
                feed = nxt[:, None].astype(jnp.int32)
                pos_eff = jnp.where(alive, pos, 0)
                tab_eff = jnp.where(alive[:, None], tables, 0)
                return (
                    nxt, keys, pos, n, alive, feed, pos_eff, tab_eff,
                )

            return jax.jit(ctl)

        return cached_step(
            self.dec, ("paged_pp_ctl", mode, eos), build
        )

    def _tick_pp(self) -> None:
        """One PIPELINED decode window: decode_window rounds for each
        of M in-flight microbatch slot groups, chained through the S
        stages round-major (GPipe schedule). Every stage dispatch is
        asynchronous — while stage s computes group g's round, the
        host has already enqueued group g+1 on stage s-1 — so up to M
        chains overlap in flight and only the drain at the bottom
        synchronizes.

        Occupancy is MEASURED at the schedule level, which is
        placement-independent: dispatch (round k, group g) enters
        stage s at slot k*M_live + g + s, each stage is busy for
        `chains` of the span's `chains + S - 1` slots, and the bubble
        fraction published per window is 1 - mean occupancy =
        (S-1)/(K*M_live + S-1) — groups with no live slot at the
        window boundary are skipped, which is what makes the number
        measured rather than the closed form."""
        live = [s is not None for s in self.slots]
        if not any(live):
            return
        K = self.decode_window
        S = self.pp
        stages = self._pp_stage_objs
        sm = self._sampler
        sampling = any(
            s is not None and s["sampling"] for s in self.slots
        )
        if not sampling:
            mode = "argmax"
        elif any(sm.row_sort):
            mode = "sort"
        else:
            mode = "nosort"
        budget = [
            s["remaining"] if s is not None else 0
            for s in self.slots
        ]
        posm = np.where(live, self.pos, 0).astype(np.int32)
        ctl = self._build_pp_ctl(mode)
        put = stages[-1]._put if hasattr(stages[-1], "_put") else jnp.asarray
        groups = self._pp_groups
        Bg = len(groups[0])
        nk1 = jnp.ones((Bg,), jnp.int32)
        kf0 = jnp.zeros((Bg,), jnp.int32)
        # Per-group device state on the CONTROLLER placement (the last
        # stage's): the same aliasing-copy rule as _tick_window for
        # tables/adapter, the same host-side round-0 freeze masks the
        # window body computes from its initial `active`.
        st: list[dict | None] = [None] * len(groups)
        for g, idx in enumerate(groups):
            if not any(live[i] for i in idx):
                continue
            # analysis: ignore[host-sync-in-hot-loop] host index list
            # (python ints), no device buffer crosses here
            ia = np.asarray(idx)
            # analysis: ignore[host-sync-in-hot-loop] host bool list
            live_g = np.asarray([live[i] for i in idx])
            tab_g = self.tables[ia].copy()
            pos_g = posm[ia]
            st[g] = {
                "tables": put(tab_g),
                "tab_eff": put(np.where(live_g[:, None], tab_g, 0)),
                "pos": put(pos_g),
                "pos_eff": put(np.where(live_g, pos_g, 0)),
                "n": put(np.zeros(len(idx), np.int32)),
                "active": put(live_g),
                "budget": put(
                    # analysis: ignore[host-sync-in-hot-loop] host ints
                    np.asarray([budget[i] for i in idx], np.int32)
                ),
                "feed": put(self._feed[ia]),
                "keys": put(sm.keys[ia]),
                "temp": put(sm.temp[ia]),
                "topk": put(sm.topk[ia]),
                "topp": put(sm.topp[ia]),
                "minp": put(sm.minp[ia]),
                "adapter": put(self.adapter[ia].copy()),
                "toks": [],
            }
        disp = self.obs.pp_stage_dispatches
        chains = 0
        for _k in range(K):
            for g, state in enumerate(st):
                if state is None:
                    continue
                x = state["feed"]
                for s, stage in enumerate(stages):
                    x = stage.pp_dispatch(
                        state["tab_eff"], state["pos_eff"], x, nk1,
                        kf0, state["adapter"],
                    )
                    self.pp_stage_dispatch_n[s] += 1
                    disp[s].inc()
                chains += 1
                (nxt, keys, pos, n, alive, feed, pos_eff,
                 tab_eff) = ctl(
                    put(x[:, -1, :]), state["keys"], state["temp"],
                    state["topk"], state["topp"], state["minp"],
                    state["pos"], state["n"], state["active"],
                    state["budget"], state["tables"],
                )
                state.update(
                    keys=keys, pos=pos, n=n, active=alive, feed=feed,
                    pos_eff=pos_eff, tab_eff=tab_eff,
                )
                state["toks"].append(nxt)
        # Write the per-group sampler/feed state back to the full-B
        # vectors on their home device (async device-to-device puts).
        dev0 = jax.devices()[0]
        for g, state in enumerate(st):
            if state is None:
                continue
            ia = jnp.asarray(groups[g])
            self._feed = self._feed.at[ia].set(
                jax.device_put(state["feed"], dev0)
            )
            sm.keys = sm.keys.at[ia].set(
                jax.device_put(state["keys"], dev0)
            )
        self.ticks += 1
        self.dispatches += 1
        n_live = sum(live)
        now = time.perf_counter()
        if self._last_tick_t is not None:
            self.obs.itl.observe(now - self._last_tick_t, n_live)
        self._last_tick_t = now
        self.obs.ticks.inc()
        self.obs.host_dispatches.inc()
        # Every chain is one full forward spread over the S stages:
        # its collectives sum to the same 2L+2 the monolithic sharded
        # forward issues (psum mirror contract).
        self._account_psums(chains)
        occ, bubble = pp_schedule_occupancy(
            [chains] * S, chains + S - 1
        )
        self.pp_occupancy_last = occ
        self.pp_bubble_last = bubble
        self.obs.pp_bubble_fraction.set(bubble)
        for s, o in enumerate(occ):
            self.obs.pp_stage_occupancy[s].set(o)
        need_toks = self.on_token is not None or any(
            s is not None and s["stop"] is not None
            for s in self.slots
        )
        if self.eos_id is not None:
            emitted: list[int] = []
            alive_host: list[bool] = []
            for g, idx in enumerate(groups):
                if st[g] is None:
                    emitted += [0] * len(idx)
                    alive_host += [False] * len(idx)
                    continue
                # analysis: ignore[host-sync-in-hot-loop] one batched
                # per-WINDOW transfer of the group's valid-length /
                # alive vectors — K tokens amortize it, same waiver
                # as _tick_window
                emitted += np.asarray(st[g]["n"]).tolist()
                # analysis: ignore[host-sync-in-hot-loop] same
                # per-window sync point (ready with the vector above)
                act_g = np.asarray(st[g]["active"]).tolist()
                alive_host += [bool(a) for a in act_g]
        else:
            emitted = [min(b, K) for b in budget]
            alive_host = [b > K for b in budget]
        # Assemble the full-B [B, K] token buffer on the home device
        # (groups are contiguous ascending index ranges, so group
        # order IS slot order); skipped groups contribute zeros their
        # emitted=0 drain never reads.
        parts = []
        for g, state in enumerate(st):
            if state is None:
                parts.append(jnp.zeros((Bg, K), jnp.int32))
                continue
            parts.append(
                jax.device_put(
                    jnp.stack(state["toks"], axis=1), dev0
                ).astype(jnp.int32)
            )
        toks = jnp.concatenate(parts, axis=0)
        # analysis: ignore[host-sync-in-hot-loop] the ONE batched
        # [B, K] token transfer per window — only when a stream/stop
        # consumer exists, same waiver as _tick_window
        toks_host = np.asarray(toks).tolist() if need_toks else None
        self._account_kv_rows_window(posm, emitted)
        self._drain_window(toks, toks_host, emitted, alive_host,
                           budget)

    def close_pp(self) -> None:
        """Release pipeline-stage resources: transport-placed stages
        send their STOP frame so remote workers' serve loops exit
        (in-process stages are no-ops)."""
        for stage in self._pp_stage_objs:
            stage.close()

    def _account_kv_rows_window(self, posm, emitted) -> None:
        """Windowed K/V-row accounting: the exact host-side mirror of
        what each attention path read across the window's K sub-steps
        (same units/contract as the K=1 tick's accounting). A row
        active at sub-step t (t < emitted[i]) reads at depth
        posm[i] + t; frozen and idle rows sit at position 0 (trash
        block), exactly as the device's pos_eff zeroing makes them."""
        K = self.decode_window
        bs = self.bs
        baseline = K * self.B * self.MB * bs
        if self.attention == "gathered":
            rows_read = baseline
        else:
            # Pure-python mirror over host ints (posm/emitted are
            # already host-side — nothing here touches the device).
            pos_l = posm.tolist()
            win = self.dec.cfg.window
            rows_read = 0
            for t in range(K):
                pe = [
                    p + t if t < e else 0
                    for p, e in zip(pos_l, emitted)
                ]
                if self.attention == "blockwise":
                    rows_read += (
                        self.B * (max(pe) // bs + 1) * bs
                    )
                else:  # pallas
                    rows_read += bs * sum(
                        p // bs
                        - (max(p - win + 1, 0) // bs
                           if win is not None else 0)
                        + 1
                        for p in pe
                    )
        self._account_kv_rows(rows_read, baseline)

    def _drain_window(
        self, toks, toks_host, emitted, alive_host, budget,
        died_host=None, fracs_host=None,
    ) -> None:
        """Host-side window drain, per-token-equivalent to the K=1
        tick loop (flat-server _drain_window docstring has the
        contract): stop sequences truncate overshoot, budgets and
        finishes mirror the per-token bookkeeping, streaming fires in
        tick-major order, and block release (_finish) happens at the
        window boundary."""
        K = self.decode_window
        accepted = [0] * self.B
        finishing = [False] * self.B
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            n_i = emitted[i]
            a_i = n_i
            stopped = False
            dead = bool(
                died_host is not None and died_host[i]
                and slot.get("cid")
            )
            if dead:
                # Dead-end DFA state mid-window: the device froze the
                # row with a FORCED eos (counted in n_i) — drop it, so
                # the output ends at the last admissible token and the
                # failure surfaces as a per-request error, not a hang.
                a_i = n_i - 1
            if slot["stop"] is not None:
                hit = slot["stop"].push_window(toks_host[i][:a_i])
                if hit is not None:
                    a_i, stopped = hit, True
            accepted[i] = a_i
            if a_i < min(budget[i], K):
                self.obs.window_truncated.inc()
            slot["remaining"] -= a_i
            if stopped or not alive_host[i]:
                # eos froze the row on device, a stop sequence cut it
                # on drain, or its budget ran out mid-window.
                slot["remaining"] = 0
            if dead:
                slot["remaining"] = 0
                self.errors[slot["rid"]] = (
                    "constraint dead end: DFA state admits no token "
                    "and is not accepting"
                )
                self.constraint_dead_ends_n += 1
                self.obs.constrain_dead_ends.inc()
            if slot.get("cid") and fracs_host is not None:
                self.constrained_tokens_n += a_i
                if a_i:
                    self.obs.constrained_tokens.inc(a_i)
                for fr in fracs_host[i][:a_i].tolist():
                    self.obs.constrain_masked_frac.observe(fr)
            tok_block = toks[i, :a_i][None, :].astype(
                slot["last"].dtype
            )
            slot["toks"].append(tok_block)
            slot["last"] = tok_block[:, -1:]
            self.pos[i] += a_i
            finishing[i] = slot["remaining"] == 0
            self.obs.tokens_generated.inc(a_i)
            self.window_tokens += a_i
        self.obs.tokens_per_dispatch.set(float(sum(accepted)))
        if self.on_token is not None:
            for t, i in window_drain_order(accepted, K):
                slot = self.slots[i]
                self.on_token(
                    slot["rid"],
                    toks_host[i][t],
                    finishing[i] and t == accepted[i] - 1,
                )
        for i in range(self.B):
            if finishing[i]:
                self._finish(i)

    def _emit_token(self, i: int, slot: dict, tok: int | None) -> None:
        """Shared eos/streaming/finish bookkeeping for one emitted
        token (admission first-token and every tick): `tok` is the
        host-side token value, or None when neither eos nor streaming
        needed the transfer."""
        self.obs.tokens_generated.inc()
        if (
            self.eos_id is not None
            and tok is not None
            and tok == self.eos_id
        ):
            slot["remaining"] = 0
        if (
            slot["stop"] is not None
            and tok is not None
            and slot["stop"].push(tok)
        ):
            slot["remaining"] = 0
        if self.on_token is not None:
            self.on_token(slot["rid"], tok, slot["remaining"] == 0)
        if slot["remaining"] == 0:
            self._finish(i)

    def _update_pool_gauges(self) -> None:
        self.obs.pool_blocks_free.set(len(self.free))
        self.obs.pool_blocks_used.set(self.blocks_in_use)

    def _finish(self, i: int) -> None:
        slot = self.slots[i]
        self.obs.requests_finished.inc()
        self.done[slot["rid"]] = jnp.concatenate(slot["toks"], axis=1)
        if self.radix is not None:
            # Shared blocks deref (parking at refcount 0 for later
            # revival); only privately owned blocks free immediately.
            # Released DEEPEST-FIRST so LRU eviction reclaims the
            # deep end of a chain before its shallow (more reusable,
            # and prerequisite-for-lookup) blocks.
            for blk in reversed(slot.get("shared", ())):
                self.radix.release(blk)
        self.free.extend(slot["blocks"])
        self.tables[i] = 0
        self.pos[i] = 0
        self.adapter[i] = 0
        self.slots[i] = None
        if self._draft is not None:
            self._draft.release(i)
        # Release the slot's sampling policy row NOW, not at reuse —
        # a lingering row_sort would drag every later tick through the
        # sorting sampler (decode_server.SlotSampler.release).
        self._sampler.release(i)
        self._update_pool_gauges()


def serve_paged(
    dec: Any,
    params: dict,
    requests: list[tuple[jax.Array, int]],
    *,
    num_blocks: int,
    block_size: int = 16,
    max_batch: int = 4,
    eos_id: int | None = None,
    adapter_ids: list | None = None,
    prefix_ids: jax.Array | None = None,
    prefix_cache: bool = False,
    sampling: list | None = None,
    attention: str = "gathered",
    kv_dtype: str = "fp",
    spill_bytes: int = 0,
    decode_window: int = 1,
    spec_draft: Any = None,
    spec_params: dict | None = None,
    spec_k: int = 0,
    prefill_chunk: int | None = None,
    prefill_budget: int | None = None,
    prefill_lookahead: int = 2,
    mesh: Any = None,
    model_axis: str = "model",
    constraints: dict | None = None,
    pp_stages: int = 1,
    pp_inflight: int | None = None,
    pp_cuts: Any = None,
    pp_devices: Any = None,
    pp_remote: dict | None = None,
    pp_balance: str = "equal",
) -> tuple[list[jax.Array], dict]:
    """One-shot paged serving; returns (outputs in submission order,
    stats incl. peak pool usage). `adapter_ids` optionally assigns a
    LoRA adapter per request (parallel/lora.py::stack_adapters);
    `sampling` optionally assigns a SamplingParams per request;
    `attention` selects the decode attention path
    (PagedDecodeServer docstring / module docstring).

    `decode_window=K` fuses K decode sub-steps into one host dispatch
    (PagedDecodeServer docstring has the semantics); outputs stay
    token-identical to the default K=1. Stats then also carry
    `decode_window`, `host_dispatches` (decode dispatches issued) and
    `tokens_per_dispatch` (mean tokens accepted per dispatch — the
    dispatch-amortization win, approaching K * live slots).

    `spec_k=k` with `spec_draft`/`spec_params` turns on paged
    speculative decoding (PagedDecodeServer docstring): greedy
    outputs stay token-identical to `spec_k=0`; stats then also carry
    `spec_rounds` / `spec_proposed` / `spec_accepted` /
    `spec_acceptance` / `spec_draft_tokens`. `prefill_chunk=C`
    switches admission to the pool-native chunked prefill path.

    `prefill_budget=N` turns on STALL-FREE continuous batching
    (PagedDecodeServer docstring): admission prefill rides inside the
    decode dispatches, up to N prompt tokens per tick, token-identical
    greedy output to the default None. Stats always carry
    `prefill_budget`, `prefill_stall_ticks` (serialized-prefill
    dispatches issued while decode slots waited), `mixed_ticks`,
    `mixed_prefill_tokens`, and `decode_stall_fraction`.

    `mesh=` / `model_axis=` run the server tensor-parallel: weights
    and the KV block pool shard over the named mesh axis and every
    tick body runs under shard_map (PagedDecodeServer docstring has
    the layout). Greedy output is token-identical to `mesh=None`;
    stats then also carry `mesh_shape` and `tp_psums`.

    `kv_dtype="int8"` stores the pool quantized (PagedDecodeServer
    docstring: half the HBM bytes, bounded-logit-error accuracy
    contract); `spill_bytes=N` adds the host-RAM spill tier for
    evicted prefix blocks (needs prefix_cache=True). Stats carry
    `kv_dtype`, `pool_bytes` and the spill totals either way.

    `constraints={name: TokenDFA}` registers compiled grammars
    (defer_tpu/constrain/) that per-request SamplingParams can opt
    into via `constraint="name"`; stats then also carry
    `constrained_tokens` / `constraint_dead_ends`.

    `pp_stages=S` runs the server pipeline-parallel (PagedDecodeServer
    docstring: staged layer stack, per-stage KV pool slices, M
    in-flight microbatch groups). Greedy output is token-identical to
    `pp_stages=1`; stats then also carry `pp_stages` / `pp_inflight` /
    `pp_bubble_fraction` (measured, last window) /
    `pp_stage_occupancy` / `pp_stage_dispatches` /
    `pp_stage_pool_bytes`."""
    srv = PagedDecodeServer(
        dec,
        params,
        num_blocks=num_blocks,
        block_size=block_size,
        max_batch=max_batch,
        eos_id=eos_id,
        prefix_ids=prefix_ids,
        prefix_cache=prefix_cache,
        attention=attention,
        kv_dtype=kv_dtype,
        spill_bytes=spill_bytes,
        decode_window=decode_window,
        spec_draft=spec_draft,
        spec_params=spec_params,
        spec_k=spec_k,
        prefill_chunk=prefill_chunk,
        prefill_budget=prefill_budget,
        prefill_lookahead=prefill_lookahead,
        mesh=mesh,
        model_axis=model_axis,
        constraints=constraints,
        pp_stages=pp_stages,
        pp_inflight=pp_inflight,
        pp_cuts=pp_cuts,
        pp_devices=pp_devices,
        pp_remote=pp_remote,
        pp_balance=pp_balance,
    )
    aids = adapter_ids or [0] * len(requests)
    if len(aids) != len(requests):
        raise ValueError(
            f"adapter_ids has {len(aids)} entries for "
            f"{len(requests)} requests"
        )
    samps = sampling or [None] * len(requests)
    if len(samps) != len(requests):
        raise ValueError(
            f"sampling has {len(samps)} entries for "
            f"{len(requests)} requests"
        )
    rids = [
        srv.submit(p, s, adapter_id=a, sampling=sp)
        for (p, s), a, sp in zip(requests, aids, samps)
    ]
    done = srv.run()
    if srv.pp > 1:
        srv.close_pp()
    if srv._spill is not None:
        # Drain pending spill copies so the stats snapshot (and any
        # caller inspecting the store) sees a settled tier.
        srv._spill.flush()
    stats = ServerStats.snapshot(
        srv.obs.registry,
        ticks=srv.ticks,
        attention=attention,
        peak_blocks=srv.blocks_peak,
        pool_blocks=srv.num_blocks - 1,
        block_size=block_size,
        flat_equivalent_rows=max_batch * dec.cfg.max_len,
        shared_prefix_blocks=len(srv.shared_blocks),
        prefill_tokens_saved=srv.prefill_tokens_saved,
        cached_blocks=(
            srv.radix.cached_blocks if srv.radix is not None else 0
        ),
        decode_window=srv.decode_window,
        host_dispatches=srv.dispatches,
        tokens_per_dispatch=(
            srv.window_tokens / srv.dispatches if srv.dispatches else 0.0
        ),
        spec_k=srv.spec_k,
        spec_rounds=srv.spec_rounds_n,
        spec_proposed=srv.spec_proposed_n,
        spec_accepted=srv.spec_accepted_n,
        spec_acceptance=(
            srv.spec_accepted_n / srv.spec_proposed_n
            if srv.spec_proposed_n
            else 0.0
        ),
        spec_draft_tokens=srv.spec_draft_tokens_n,
        prefill_chunk=srv.prefill_chunk,
        prefill_budget=srv.prefill_budget,
        prefill_stall_ticks=srv.prefill_stall_ticks_n,
        mixed_ticks=srv.mixed_ticks_n,
        mixed_prefill_tokens=srv.mixed_prefill_tokens_n,
        decode_stall_fraction=srv.decode_stall_fraction_last,
        mesh_shape=srv.mesh_label,
        tp_psums=srv.tp_psums,
        kv_dtype=srv.kv_dtype,
        pool_bytes=srv.pool_bytes,
        spilled_blocks=(
            srv._spill.stored_blocks if srv._spill is not None else 0
        ),
        spill_hits=srv.spill_hits_n,
        spill_stored_bytes=(
            srv._spill.stored_bytes if srv._spill is not None else 0
        ),
        constrained_tokens=srv.constrained_tokens_n,
        constraint_dead_ends=srv.constraint_dead_ends_n,
        pp_stages=srv.pp,
        pp_inflight=srv._pp_inflight if srv.pp > 1 else 0,
        pp_bubble_fraction=srv.pp_bubble_last,
        pp_stage_occupancy=list(srv.pp_occupancy_last),
        pp_stage_dispatches=list(srv.pp_stage_dispatch_n),
        pp_stage_pool_bytes=list(srv.pp_stage_pool_bytes),
        pp_cut_starts=list(srv._pp_cut_starts),
    )
    return [done[r] for r in rids], stats
