"""Multi-token stop sequences: host-side suffix matching on streamed
tokens.

A single stop TOKEN (eos_id) jits cleanly — it is a per-row equality
in the device step (models/gpt.py apply_eos). A stop SEQUENCE cannot:
the match window spans ticks, and serving must stop the request the
moment the suffix completes, mid-budget. The natural seam is the same
host-side point where streamed tokens already surface (the servers'
`_emit_token` paths and `sampled_decode_loop`'s per-token host sync):
each stream keeps the last max_stop-1 tokens and an O(num_stops)
suffix compare per emitted token — exact, allocation-free, and
decoupled from the jitted tick, which never learns stop sequences
exist.

Matching covers GENERATED tokens only (the serving-standard contract:
a stop sequence never triggers on prompt content, and the emitted
output ENDS WITH the stop sequence, mirroring eos). The reference has
no text generation at all (it streams CNN frames, reference
src/test.py:30-41); this generalizes the stop-token machinery of the
beyond-reference serving surface.
"""

from __future__ import annotations


def normalize_stops(stop_sequences) -> tuple[tuple[int, ...], ...]:
    """Validate and canonicalize `stop_sequences` (an iterable of
    non-empty int sequences) to a tuple of int tuples."""
    if stop_sequences is None:
        return ()
    seqs = []
    for s in stop_sequences:
        t = tuple(int(x) for x in s)
        if not t:
            raise ValueError("empty stop sequence")
        seqs.append(t)
    return tuple(seqs)


def matcher_or_none(seqs: tuple[tuple[int, ...], ...]):
    """One StopMatcher per request when stop sequences were given,
    else None — the construction every server admission shares."""
    return StopMatcher(seqs) if seqs else None


class StopMatcher:
    """Suffix matcher for ONE token stream: push() each generated
    token; returns True the moment the stream's tail equals any stop
    sequence. Keeps only the longest-stop-minus-one history."""

    __slots__ = ("seqs", "keep", "hist")

    def __init__(self, seqs: tuple[tuple[int, ...], ...]):
        if not seqs:
            raise ValueError("StopMatcher needs at least one sequence")
        self.seqs = seqs
        self.keep = max(len(s) for s in seqs)
        self.hist: list[int] = []

    def push(self, tok: int) -> bool:
        self.hist.append(int(tok))
        if len(self.hist) > self.keep:
            del self.hist[: len(self.hist) - self.keep]
        h = self.hist
        n = len(h)
        for s in self.seqs:
            if n >= len(s) and tuple(h[n - len(s):]) == s:
                return True
        return False

    def push_window(self, toks) -> int | None:
        """Window drain: push a whole window's worth of one stream's
        tokens and return the ACCEPTED count — index of the first
        match plus one, so the output ends with the stop sequence —
        or None if nothing matched. Tokens past the match are never
        pushed: they are window overshoot (the device ran the rest of
        the window blind to stop sequences) and must not pollute the
        history a later window matches against."""
        for j, tok in enumerate(toks):
            if self.push(tok):
                return j + 1
        return None
