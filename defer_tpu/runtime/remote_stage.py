"""Remote stage worker: the reference's compute node, as a process.

The reference's deployment unit is `python node.py` on another machine:
it receives architecture JSON (port 5001), weights (port 5002), its
successor's address, then relays activations (port 5000) through
`model.predict` forever (reference src/node.py:135-152). This module is
that capability for the native IR over the DCN transport seam — ONE
stream carries the whole session:

    frame 1      uint8 bytes of the stage's graph JSON
                 (defer_tpu/graph/serialize.py)
    frame 2      uint8 bytes of the param manifest (JSON list of
                 'node/param' paths)
    frames 3..   one array per manifest entry (the weights wire,
                 reference src/dispatcher.py:75-88)
    then         activation frames — len(input_names) frames per
                 microbatch for bundle boundaries; results stream to
                 the --next peer as len(output_names) frames
    STOP         ends the session (the shutdown the reference lacks)

Worker CLI (the `node.py` analogue; chain wiring via --next replaces
the reference's nextNode message, src/dispatcher.py:54-58):

    python -m defer_tpu.runtime.remote_stage --listen 0 \
        --next 10.0.0.2:5000

Dispatcher side: `dispatch_stage(sender, stage, params)` then
`send_activation(sender, x)` per microbatch.

CHAIN ORDERING CONTRACT: a worker identifies the FIRST accepted
connection as its dispatch stream, so chains must be dispatched
tail-first (last stage's worker first) — each worker only connects to
its --next peer after its own dispatch completes, which guarantees the
downstream worker has already consumed its dispatch. Dispatching
head-first lets an upstream worker's activation connection win the
downstream accept race; the worker then fails fast with a GraphError
naming this contract.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

from defer_tpu.graph.serialize import (
    frames_to_params,
    graph_from_json,
    graph_to_json,
    params_to_frames,
)
from defer_tpu.runtime.transport import (
    ArrayReceiver,
    ArraySender,
    TransportError,
)
from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)


def _num_inputs(stage: Any) -> int:
    return len(getattr(stage, "input_names", ("x",)))


def _num_outputs(stage: Any) -> int:
    return len(getattr(stage, "output_names", ("y",)))


def _send_blob(sender: ArraySender, data: bytes) -> None:
    sender.send(np.frombuffer(data, np.uint8))


def dispatch_stage(sender: ArraySender, stage: Any, params: Any) -> None:
    """Ship a stage (architecture + weights) to a worker — the
    reference's `_dispatchModels` for one node (src/dispatcher.py:47-73).

    Weights always go LOSSLESS: a sender's quantize mode is an
    activation-transfer optimization; int8-roundtripping parameters
    would silently skew every result the worker ever produces."""
    saved_quant = sender.quantize
    sender.quantize = None
    try:
        _send_blob(sender, graph_to_json(stage).encode())
        pairs = params_to_frames(params)
        _send_blob(sender, json.dumps([p for p, _ in pairs]).encode())
        for _, arr in pairs:
            sender.send(np.asarray(arr))
    finally:
        sender.quantize = saved_quant


def send_activation(sender: ArraySender, x: Any) -> None:
    """One microbatch: a single array, or a tuple for bundle cuts."""
    xs = x if isinstance(x, (tuple, list)) else (x,)
    for t in xs:
        sender.send(np.asarray(t))


def _read_bundle(it, n: int):
    """Read one microbatch's n frames; None at a clean stream end,
    RuntimeError if the stream dies mid-bundle."""
    frames = []
    for i in range(n):
        try:
            frames.append(next(it))
        except StopIteration:
            if i:
                raise RuntimeError(
                    "stream ended mid-microbatch (partial bundle)"
                ) from None
            return None
    return tuple(frames)


def recv_results(
    receiver: ArrayReceiver, num_outputs: int = 1
):
    """Iterate per-microbatch results arriving from the chain's last
    worker (the reference's `_result_server`, src/dispatcher.py:105-118).
    Yields arrays, or tuples when the final boundary is a bundle."""
    it = iter(receiver)
    while True:
        outs = _read_bundle(it, num_outputs)
        if outs is None:
            return
        yield outs if num_outputs > 1 else outs[0]


def serve_stage(
    listen_port: int,
    next_host: str,
    next_port: int,
    *,
    listen_host: str = "0.0.0.0",
    accept_timeout_s: float = 120.0,
    handoff_timeout_s: float = 60.0,
    expect_activation_peer: bool = False,
    announce=None,
) -> int:
    """Run one worker session to completion; returns microbatches
    relayed. `announce(port)` is called once the listen socket is bound
    (drivers/tests use it to learn an ephemeral port).

    ``expect_activation_peer=True`` declares this worker mid-chain: an
    upstream hop WILL connect, so a handoff-accept timeout is a hard
    error instead of a clean zero-work exit — without it a slow
    upstream start (cold Python+JAX easily takes seconds) would make
    the chain silently produce zero results with rc=0."""
    import jax

    recv = ArrayReceiver(
        listen_port, host=listen_host, accept_timeout_s=accept_timeout_s
    )
    if announce is not None:
        announce(recv.port)
    it = iter(recv)
    try:
        first = next(it)
        try:
            stage = graph_from_json(bytes(bytearray(first)).decode())
        except Exception as e:  # noqa: BLE001 — re-raise with context
            from defer_tpu.graph.ir import GraphError

            raise GraphError(
                "first frame on the dispatch stream is not a stage "
                "graph — if this worker is mid-chain, the chain was "
                "probably dispatched head-first; dispatch tail-first "
                "(see module docstring)"
            ) from e
        manifest = json.loads(bytes(bytearray(next(it))).decode())
        # Explicit loop, not a generator fed to frames_to_params: a
        # StopIteration inside a generator becomes PEP 479's opaque
        # RuntimeError and would never reach the except below.
        pairs = [(path, next(it)) for path in manifest]
    except StopIteration:
        raise RuntimeError(
            "peer closed before the stage was fully dispatched"
        ) from None
    params = frames_to_params(pairs)
    n_in, n_out = _num_inputs(stage), _num_outputs(stage)
    fn = jax.jit(stage.apply)
    log.info(
        "remote stage %r ready (%d params, %d->%d tensors); relaying to "
        "%s:%d",
        stage.name,
        len(manifest),
        n_in,
        n_out,
        next_host,
        next_port,
    )
    sender = ArraySender(next_host, next_port)
    count = 0
    # Two session shapes (the reference used separate ports per role,
    # src/node.py:18; here roles share the listen socket):
    #   * single-peer: the dispatcher keeps streaming activations on
    #     the dispatch connection (the simple two-process case);
    #   * chained: the dispatch stream ENDS after the weights, and the
    #     activation stream arrives as a SECOND connection from the
    #     previous chain hop.
    accepted_second = False
    try:
        while True:
            try:
                acts = _read_bundle(it, n_in)
            except TransportError:
                if (
                    accepted_second
                    and count == 0
                    and recv._conn is None
                ):
                    # The HANDOFF ACCEPT timed out with no peer ever
                    # connecting. (A peer that connected and died
                    # mid-frame leaves recv._conn set — that is a real
                    # failure and re-raises.)
                    if expect_activation_peer:
                        raise RuntimeError(
                            f"remote stage {stage.name!r}: expected an "
                            f"upstream activation peer but none "
                            f"connected within {handoff_timeout_s:.0f}s"
                        ) from None
                    # Not declared mid-chain: a dispatch-only session,
                    # clean zero-work exit.
                    log.info(
                        "remote stage %r: no activation peer arrived; "
                        "dispatch-only session",
                        stage.name,
                    )
                    return count
                raise
            if acts is None:
                if count == 0 and not accepted_second:
                    log.info(
                        "remote stage %r: dispatch stream closed; "
                        "awaiting the activation peer (<= %.0fs)",
                        stage.name,
                        handoff_timeout_s,
                    )
                    recv.next_peer()
                    # Bound the handoff wait separately: a dispatch-
                    # only session should exit in seconds, not the
                    # full accept timeout; chains must connect their
                    # next hop within this budget.
                    recv._server.settimeout(handoff_timeout_s)
                    it = iter(recv)
                    accepted_second = True
                    continue
                return count
            out = fn(params, acts if n_in > 1 else acts[0])
            outs = out if isinstance(out, tuple) else (out,)
            for t in outs:
                sender.send(np.asarray(t))
            count += 1
    finally:
        sender.close()
        recv.close()


# analysis: domain(pp-stage-worker) the whole session — stage pools and
# the result stream — is owned by this worker thread; the controller
# only ever talks to it through the framed transport
def serve_pp_stage(
    dec: Any,
    params: Any,
    first: int,
    last: int,
    *,
    num_blocks: int,
    block_size: int,
    attention: str = "gathered",
    listen_port: int = 0,
    result_host: str = "127.0.0.1",
    result_port: int = 5000,
    listen_host: str = "0.0.0.0",
    accept_timeout_s: float = 120.0,
    announce=None,
) -> int:
    """Serve ONE pipeline stage of a paged decode server
    (PagedDecodeServer(pp_remote=...)) to a remote controller — the
    decode-time sibling of `serve_stage`, same session shape, different
    payload: each microbatch is the SIX stage-boundary operands
    (tables, pos, xin, n_keep, keep_from, adapter_ids) and the reply is
    the one boundary activation (or, on the last stage, logits) array.

    The worker wraps the same `_PPLocalStage` the in-process tier uses
    — its layer slice of the params and its own KV-pool slice live
    here, so the controller's per-stage HBM claim holds across hosts
    too. Unlike `serve_stage`, the stage definition is NOT shipped over
    the wire: decoders aren't graph-serializable, so the worker process
    is handed `(dec, params)` directly (tests run it in a thread;
    cross-host drivers load the checkpoint themselves). Runs until the
    controller's STOP frame; returns microbatches served."""
    from defer_tpu.runtime.paged import _PPLocalStage

    stage = _PPLocalStage(
        dec, params, first, last,
        num_blocks=num_blocks, block_size=block_size,
        attention=attention,
    )
    recv = ArrayReceiver(
        listen_port, host=listen_host, accept_timeout_s=accept_timeout_s
    )
    if announce is not None:
        announce(recv.port)
    it = iter(recv)
    log.info(
        "pp stage worker ready (layers [%d, %d), pool %d bytes); "
        "results to %s:%d",
        first, last, stage.pool_bytes, result_host, result_port,
    )
    sender = ArraySender(result_host, result_port)
    count = 0
    try:
        while True:
            bundle = _read_bundle(it, 6)
            if bundle is None:
                return count
            tables, pos, xin, n_keep, keep_from, adapter = bundle
            out = stage.pp_dispatch(
                tables, pos, xin, n_keep, keep_from, adapter
            )
            # analysis: ignore[host-sync-in-hot-loop] the worker's job
            # is to frame the result back onto the wire — this
            # device->host copy IS the stage boundary here
            sender.send(np.asarray(out))
            count += 1
    finally:
        sender.close()
        recv.close()
        stage.close()


def main(argv: list[str] | None = None) -> None:
    import argparse

    from defer_tpu.utils.platform import honor_env_platform

    honor_env_platform()

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--listen", type=int, default=5000)
    ap.add_argument(
        "--next", required=True, help="host:port of the next chain hop"
    )
    ap.add_argument("--accept-timeout", type=float, default=120.0)
    ap.add_argument("--handoff-timeout", type=float, default=60.0)
    ap.add_argument(
        "--expect-peer",
        action="store_true",
        help="this worker is mid-chain: treat a missing upstream "
        "activation peer as a hard error, never a clean zero-work exit",
    )
    args = ap.parse_args(argv)
    host, _, port = args.next.rpartition(":")
    n = serve_stage(
        args.listen,
        host or "127.0.0.1",
        int(port),
        accept_timeout_s=args.accept_timeout,
        handoff_timeout_s=args.handoff_timeout,
        expect_activation_peer=args.expect_peer,
        announce=lambda p: print(f"LISTENING {p}", flush=True),
    )
    print(f"DONE {n}", flush=True)


if __name__ == "__main__":
    main()
