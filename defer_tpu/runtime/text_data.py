"""Packed token pipeline for LM training.

The reference's data plane decodes images for CNN inference
(src/local_infer.py, here runtime/data.py); the LM-training
counterpart is a TOKEN pipeline: variable-length documents packed into
the fixed [num_microbatches, batch, seq] blocks the jitted train step
consumes (parallel/train.py::make_lm_train_step). TPU-shaped choices:

  * PACKING, not padding: documents concatenate into one token stream
    separated by eos, and fixed windows are cut from the stream — the
    standard pretraining layout. Every position is a real training
    target (vs pad-and-mask, which wastes MXU work on pad rows), and
    shapes are static so the step compiles once.
  * the host side is pure numpy (cheap, threaded prefetch via
    data.prefetch_to_device); the device never sees ragged data.
  * deterministic: a seeded shuffle of document order, so a run is
    reproducible and a resumed run can skip consumed steps.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Sequence

import numpy as np


def pack_documents(
    docs: Iterable[Sequence[int]],
    seq_len: int,
    *,
    eos_id: int,
    drop_remainder: bool = True,
) -> Iterator[np.ndarray]:
    """Concatenate token documents (eos-separated) into a stream and
    cut fixed [seq_len] windows from it.

    Every document contributes `len(doc) + 1` stream tokens (its eos
    separator teaches the model where documents end). The final
    partial window is dropped by default (a padded tail would need a
    loss mask the packed layout exists to avoid).
    """
    if seq_len < 2:
        raise ValueError(f"seq_len={seq_len}: need at least 2 tokens")
    buf = np.empty((0,), np.int32)
    for doc in docs:
        arr = np.asarray(doc, np.int32)
        if arr.ndim != 1:
            raise ValueError(f"documents must be 1-D, got {arr.shape}")
        buf = np.concatenate([buf, arr, np.asarray([eos_id], np.int32)])
        while len(buf) >= seq_len:
            yield buf[:seq_len].copy()
            buf = buf[seq_len:]
    if len(buf) and not drop_remainder:
        pad = np.full((seq_len - len(buf),), eos_id, np.int32)
        yield np.concatenate([buf, pad])


def lm_batches(
    docs: Sequence[Sequence[int]],
    *,
    seq_len: int,
    batch: int,
    num_microbatches: int,
    eos_id: int,
    seed: int = 0,
    epochs: int = 1,
) -> Iterator[np.ndarray]:
    """[num_microbatches, batch, seq_len] int32 blocks for the LM
    train step, from a document set: seeded document shuffle per
    epoch, packed stream, fixed-shape blocks (ragged tails dropped —
    static shapes are what keep the step compiled once)."""
    if not docs:
        raise ValueError("no documents")
    need = num_microbatches * batch
    # One epoch must fill at least one block — a too-small corpus
    # would otherwise yield NOTHING and a training loop would
    # "complete" having trained zero steps.
    rows_per_epoch = token_count(docs) // seq_len
    if rows_per_epoch < need:
        raise ValueError(
            f"corpus packs to {rows_per_epoch} rows of {seq_len} per "
            f"epoch but one [M={num_microbatches}, B={batch}] block "
            f"needs {need} — add documents or shrink the block"
        )
    rng = np.random.default_rng(seed)
    for epoch in range(epochs):
        order = rng.permutation(len(docs))
        rows: list[np.ndarray] = []
        for row in pack_documents(
            (docs[i] for i in order), seq_len, eos_id=eos_id
        ):
            rows.append(row)
            if len(rows) == need:
                yield (
                    np.stack(rows)
                    .reshape(num_microbatches, batch, seq_len)
                )
                rows = []


def token_count(docs: Sequence[Sequence[int]]) -> int:
    """Stream length the packer will produce (docs + eos separators) —
    for sizing epochs/steps up front."""
    return sum(len(d) + 1 for d in docs)
