"""Dynamic batching for the streaming serve path.

The reference streams batch-1 frames end to end (one image per queue
item, reference src/test.py:52-54) — fine for CPUs, ruinous on a TPU:
the measured single-chip gap is ~50x between batch-1 and batch-256
ResNet50 throughput (bench.py sweep). This adapter coalesces adjacent
queue items into one device batch under a latency SLO, and splits the
batched output back into per-item results, so the reference's
item-in/item-out queue contract survives while the MXU sees real
batches.

Enable via DeferConfig(dynamic_batch_size=N, batch_wait_s=SLO):
`DEFER.run_defer` then gathers up to N items per dispatch, waiting at
most `batch_wait_s` after the first item of a batch arrives.
"""

from __future__ import annotations

import queue as queue_mod
import time
from typing import Any

import jax.numpy as jnp
import numpy as np

from defer_tpu.obs.metrics import get_registry
from defer_tpu.runtime.host_io import STOP

# Leading-dim buckets 1..1024: one histogram bucket per pow2 compile
# bucket, so occupancy reads directly against the compile-cache story.
_ROW_BUCKETS = tuple(float(1 << i) for i in range(11))


class Deadline:
    """Monotonic SLO deadline: one start-time capture plus
    remaining-budget arithmetic, shared by every wait loop that blocks
    "at most X seconds after the first event" (the batch gatherer's
    flush SLO here, the fleet admission queues in
    fleet/admission.py). Centralizing it keeps the `time.monotonic`
    bookkeeping in one place — a wait loop that recomputes its own
    deadline from `time.time` or re-anchors per iteration silently
    stretches the SLO."""

    __slots__ = ("t0", "at")

    def __init__(self, budget_s: float):
        self.t0 = time.monotonic()
        self.at = self.t0 + budget_s

    def remaining(self) -> float:
        """Seconds of budget left (negative once expired)."""
        return self.at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0

    def elapsed(self) -> float:
        """Seconds since the deadline was armed."""
        return time.monotonic() - self.t0


class BatchGatherer:
    """Coalesce queue items (arrays with a leading batch dim) into one
    stacked batch per dispatch.

    Items with mismatched trailing shapes or dtypes are never mixed: a
    mismatch flushes the current batch and the odd item starts the
    next one (carried between calls).
    """

    def __init__(
        self, batch_size: int, max_wait_s: float, *, pad_to_buckets: bool = True
    ):
        if batch_size < 2:
            raise ValueError("dynamic batching needs batch_size >= 2")
        self.batch_size = batch_size
        self.max_wait_s = max_wait_s
        # Pad partial batches up to the next power-of-two bucket
        # (<= batch_size): every distinct leading dim is a fresh XLA
        # compile of the whole stage chain, so unbucketed bursty
        # traffic (256, 113, 41, 7, ...) would turn the ms-level SLO
        # into multi-second compile stalls. Buckets bound the compile
        # cache to log2(batch_size) shapes; split_output drops the pad
        # rows by construction (sizes sum to the real total).
        self.pad_to_buckets = pad_to_buckets
        self._carry: Any = None
        # Metric handles resolved once (obs/metrics.py); gather() then
        # pays one histogram observe + counter inc per FLUSH, nothing
        # per item.
        reg = get_registry()
        self._obs_rows = reg.histogram(
            "defer_batch_rows",
            "Device-batch occupancy (rows) per dispatch",
            _ROW_BUCKETS,
        )
        self._obs_wait = reg.histogram(
            "defer_batch_wait_seconds",
            "First item to flush (bounded by the batch_wait_s SLO)",
        )
        self._obs_flush = {
            reason: reg.counter(
                "defer_batch_flush_total",
                "Batches flushed, by why gathering stopped",
                {"reason": reason},
            )
            for reason in ("full", "timeout", "eos", "mismatch")
        }

    @staticmethod
    def _compatible(a: Any, b: Any) -> bool:
        return (
            getattr(a, "ndim", 0) >= 1
            and getattr(b, "ndim", 0) >= 1
            and a.shape[1:] == b.shape[1:]
            and a.dtype == b.dtype
        )

    def gather(
        self, input_stream: "queue_mod.Queue[Any]", poll_s: float = 0.05
    ) -> tuple[Any, list[int] | None, bool]:
        """Pull one batch. Returns (batch, sizes, eos):

        * batch: stacked array (or None if only the sentinel / nothing
          arrived); sizes: per-item leading-dim sizes for the splitter.
        * eos: the STOP/None sentinel was consumed.

        Blocks at most `poll_s` for the FIRST item (so the caller's
        idle loop keeps servicing results), then at most `max_wait_s`
        total for the rest of the batch.
        """
        items: list[Any] = []
        if self._carry is not None:
            items.append(self._carry)
            self._carry = None
        eos = False
        if not items:
            try:
                first = input_stream.get(timeout=poll_s)
            except queue_mod.Empty:
                return None, None, False
            if first is None or first is STOP:
                return None, None, True
            items.append(first)
        if getattr(items[0], "ndim", 0) < 1:
            raise ValueError(
                "dynamic batching requires queue items with a leading "
                f"batch dim; got shape {getattr(items[0], 'shape', ())} — "
                "disable dynamic_batch_size or add a batch axis"
            )
        # batch_size bounds ROWS (the device batch), not item count —
        # multi-row items fill it proportionally faster. An item that
        # would overflow the bound is carried to the next batch, so
        # the device batch never exceeds batch_size (unless a single
        # item is itself larger — items are atomic).
        total = int(items[0].shape[0])
        dl = Deadline(self.max_wait_s)
        reason = "full"  # loop exits via its condition when filled
        while total < self.batch_size:
            remaining = dl.remaining()
            if remaining <= 0:
                reason = "timeout"
                break
            try:
                nxt = input_stream.get(timeout=remaining)
            except queue_mod.Empty:
                reason = "timeout"
                break
            if nxt is None or nxt is STOP:
                eos = True
                reason = "eos"
                break
            if (
                not self._compatible(items[0], nxt)
                or total + int(nxt.shape[0]) > self.batch_size
            ):
                # Flush what we have; the odd item opens the next batch.
                self._carry = nxt
                reason = "mismatch"
                break
            items.append(nxt)
            total += int(nxt.shape[0])
        self._obs_rows.observe(float(total))
        self._obs_wait.observe(dl.elapsed())
        self._obs_flush[reason].inc()
        sizes = [int(x.shape[0]) for x in items]
        pad = 0
        if self.pad_to_buckets and total < self.batch_size:
            bucket = 1
            while bucket < total:
                bucket *= 2
            pad = min(bucket, self.batch_size) - total
        if pad:
            items.append(
                jnp.zeros((pad, *items[0].shape[1:]), items[0].dtype)
            )
        batch = (
            items[0]
            if len(items) == 1
            else jnp.concatenate(items, axis=0)
        )
        return batch, sizes, eos

    def pending(self) -> bool:
        return self._carry is not None


class TimedQueue:
    """Thread-safe FIFO that times each item from put() to pop() into a
    caller-supplied histogram — how long produced work sat waiting for
    its consumer. The disagg ingest path uses this to surface
    `defer_kv_ingest_wait_seconds` (disagg/ingest.py): prefill blocks
    landing faster than decode admits them shows up here as a growing
    wait, the early-warning signal for a prefill/decode capacity
    imbalance."""

    def __init__(self, histogram=None, maxsize: int = 0):
        self._q: "queue_mod.Queue[tuple[float, Any]]" = queue_mod.Queue(
            maxsize
        )
        self._hist = histogram

    def put(self, item: Any) -> None:
        self._q.put((time.monotonic(), item))

    def pop(self, timeout: float | None = None) -> Any:
        """Blocking get; raises queue.Empty on timeout like Queue.get."""
        t_in, item = self._q.get(timeout=timeout)
        if self._hist is not None:
            self._hist.observe(time.monotonic() - t_in)
        return item

    def qsize(self) -> int:
        return self._q.qsize()


def window_drain_order(valid_lens, width: int):
    """Tick-major iteration order for draining a fused-decode window
    buffer ([B, K] tokens plus per-slot valid lengths): yields (t, i)
    for every accepted token, sub-step first and slot second, so
    streaming callbacks fire in exactly the interleaving a
    decode_window=1 loop produces (all slots' token t before any
    slot's token t+1). Shared by both decode servers' window drains
    (runtime/decode_server.py / runtime/paged.py)."""
    for t in range(width):
        for i, n in enumerate(valid_lens):
            if t < n:
                yield t, i


def accept_lengths(props, preds):
    """Greedy speculative accept test, batched (the Leviathan/Chen
    rule at temperature 0): per row, the accepted length is the index
    of the FIRST draft token that disagrees with the target's argmax
    at the same position — or k when the whole proposal matches.
    `props` [B, k] draft proposals; `preds` [B, k] target argmax at
    the k proposal positions (verify-forward rows 0..k-1: row j is
    the target's choice GIVEN props[:j] accepted). Host-side numpy on
    already-fetched values — the single batched accept-test sync both
    speculative drivers (models/speculative.py solo loop,
    runtime/paged.py `spec_k`) share, so their accept semantics can
    never drift. Returns [B] int64."""
    # analysis: ignore[host-sync-in-hot-loop] no-op on the host numpy
    # both callers pass (their round's ONE batched transfer happens —
    # and is justified — at the fetch site)
    props = np.asarray(props)
    # analysis: ignore[host-sync-in-hot-loop] same: already host-side
    preds = np.asarray(preds)
    if props.shape != preds.shape or props.ndim != 2:
        raise ValueError(
            f"props/preds must be matching [B, k], got "
            f"{props.shape}/{preds.shape}"
        )
    mismatch = props != preds
    # argmax of an all-False row is 0; the any() mask routes those
    # (full-accept) rows to k.
    first_bad = mismatch.argmax(axis=1)
    return np.where(mismatch.any(axis=1), first_bad, props.shape[1])


def poisson_arrivals(n: int, rate: float, seed: int = 0) -> np.ndarray:
    """Deterministic open-loop arrival schedule: `n` absolute arrival
    offsets (seconds, float64, non-decreasing, starting at 0.0) drawn
    from a Poisson process of `rate` requests/second. Open-loop means
    arrivals do NOT wait for service — the schedule is fixed up front,
    so a slow server accumulates backlog instead of throttling its
    own offered load (the closed-loop artifact that hides stalls).
    Seeded numpy, no wall clock: the same (n, rate, seed) is the same
    trace everywhere it's replayed (scripts/bench_paged.py
    --mixed-sweep prices prefill/decode interference against it)."""
    if n < 1:
        raise ValueError(f"need n >= 1 arrivals, got {n}")
    if rate <= 0:
        raise ValueError(f"arrival rate must be > 0, got {rate}")
    gaps = np.random.default_rng(seed).exponential(1.0 / rate, size=n)
    gaps[0] = 0.0  # first request arrives at t=0
    return np.cumsum(gaps)


def microbatch_groups(max_batch: int, num_groups: int) -> list[list[int]]:
    """Partition the slot indices [0, max_batch) into `num_groups`
    contiguous microbatch groups for pipelined decode
    (runtime/paged.py pp_stages=). Groups must tile the batch evenly:
    every group's state rides the same compiled stage programs, so a
    ragged tail group would double the traced shape set per stage."""
    if num_groups < 1:
        raise ValueError(f"num_groups must be >= 1, got {num_groups}")
    if max_batch % num_groups:
        raise ValueError(
            f"max_batch {max_batch} must divide evenly into "
            f"{num_groups} microbatch groups — pick max_batch a "
            f"multiple of the in-flight count (pp_inflight)"
        )
    g = max_batch // num_groups
    return [
        list(range(k * g, (k + 1) * g)) for k in range(num_groups)
    ]


def pp_schedule_occupancy(
    busy_slots: list[int], total_slots: int
) -> tuple[list[float], float]:
    """Per-stage occupancy and bubble fraction of one realized
    pipelined-decode window, from dispatch-slot accounting:
    `busy_slots[s]` = stage-step dispatches stage s actually issued,
    `total_slots` = schedule slots spanned from the first stage-0
    dispatch to the last final-stage dispatch. In the full GPipe
    schedule (M groups x W rounds, no early freezes) this recovers
    the closed-form bubble (S-1)/(S-1+M*W); groups that freeze or
    drain mid-window lower the measured occupancy below it. Schedule
    slots are logical dispatch positions, so the numbers are
    placement- and hardware-independent (the wall-clock win is the
    sweep's separate tokens/sec column)."""
    if total_slots <= 0:
        return [0.0] * len(busy_slots), 0.0
    occ = [min(b / total_slots, 1.0) for b in busy_slots]
    mean = sum(occ) / len(occ) if occ else 0.0
    return occ, 1.0 - mean


def split_output(out: Any, sizes: list[int]) -> list[Any]:
    """Invert the gather: slice the batched output back into per-item
    results (device-side slices; no host transfer). Pad rows beyond
    sum(sizes) — bucket padding — are dropped by construction."""
    if len(sizes) == 1:
        # Only skip the slice when there was no padding: a padded
        # single-item batch must not leak its garbage pad rows.
        if getattr(out, "ndim", 0) >= 1 and out.shape[0] == sizes[0]:
            return [out]
        return [out[: sizes[0]]]
    parts = []
    off = 0
    for s in sizes:
        parts.append(out[off : off + s])
        off += s
    return parts
