"""Continuous-batching decode server: admit requests into batch slots
mid-flight.

A plain batched `generate` convoys requests: the batch finishes when
its LAST member does, and new arrivals wait for the whole batch. Here
the decode batch is a set of SLOTS, each at its own depth — the cache
write head is a (B,) position VECTOR (models/gpt.py `per_slot`), so
one jitted (B, 1) step advances every active request regardless of
age, and a finished slot is immediately re-admitted with the next
queued request:

  * admission = single-request prefill (prompt padded to a pow2
    bucket, so the compiled-shape set stays tiny) whose K/V rows are
    inserted into the slot's lane of the big cache; stale rows past
    the slot's position are never attended (position masking) and are
    overwritten as the slot advances;
  * every decode tick is ONE weight read shared by all active slots —
    exactly the batching economics decode wants (weights dominate,
    models/gpt.py), now without convoy latency;
  * shapes are static everywhere: max_batch slots, bucketed prefill,
    (B, 1) ticks; inactive slots decode a dummy token into row 0 and
    their position is pinned back to 0 after each tick.

Greedy only, and each request's output is BIT-IDENTICAL to a solo
`dec.generate` of that request at the tested scales — the correctness
contract the tests pin. (At large widths/vocabs with random weights,
greedy decoding itself is ill-conditioned: near-ties in the softmax
mean the bucketed/offset prefill's different-but-equivalent reduction
shapes can flip an argmax; examples/serve_decode.py --check therefore
verifies greedy-validity under a tie tolerance instead.) The
reference's serving story is a fixed stream of identical CNN frames
(reference src/test.py:30-41); this is the autoregressive
counterpart, composing with runtime/batching.py's request coalescing.

Prefix caching (`prefix_ids=`): serving workloads share a system
prompt; its K/V rows are identical for every request, so the server
prefills the prefix ONCE into a one-lane cache and each admission
copies that lane and prefills only the request's suffix — admission
cost drops from O(prefix + prompt) to O(prompt) while outputs stay
bit-identical to solo generation over the concatenated ids.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class _Slot:
    req: int | None = None
    remaining: int = 0
    last: Any = None  # next token to feed, [1, 1]
    toks: list | None = None


class DecodeServer:
    """Greedy continuous-batching decoder over `max_batch` slots."""

    def __init__(
        self,
        dec: Any,
        params: dict,
        *,
        max_batch: int = 4,
        prefix_ids: jax.Array | None = None,
        on_token: Any = None,
        eos_id: int | None = None,
    ):
        """`on_token(request_id, token_id, done)` — optional streaming
        callback fired for every generated token as its batched tick
        resolves (`done=True` on the request's final token). Keep it
        cheap: it runs on the serving thread between ticks.

        `eos_id` — stop token: a request that emits it finishes
        immediately (its output ends with the eos) and its slot
        re-admits the next queued request, so num_steps becomes a
        budget rather than an exact length."""
        self.dec = dec
        self.params = params
        self.B = max_batch
        self.step = dec.make_step()  # batched ticks (donating)
        cache = dec.init_cache(max_batch)
        cache["pos"] = jnp.zeros((max_batch,), jnp.int32)
        # Multi-LoRA serving: adapter banks attached to the params
        # (parallel/lora.py::stack_adapters) make the slot -> adapter
        # assignment per-slot cache state; id 0 = base model.
        from defer_tpu.parallel.lora import adapter_bank_info

        n_adapters = adapter_bank_info(params)
        self.multi_lora = n_adapters is not None
        if self.multi_lora:
            cache["adapter"] = jnp.zeros((max_batch,), jnp.int32)
            self.num_adapters = n_adapters
        self.cache = cache
        self.prefix_len = 0
        self._prefix_cache = None
        if prefix_ids is not None:
            if self.multi_lora:
                raise ValueError(
                    "prefix caching + multi-LoRA is unsupported: the "
                    "shared prefix K/V would be adapter-dependent"
                )
            if getattr(dec, "rolling_cache", False):
                raise ValueError(
                    "prefix caching over a rolling cache is not "
                    "supported (prefix rows would be recycled)"
                )
            if prefix_ids.ndim != 2 or prefix_ids.shape[0] != 1:
                raise ValueError("prefix_ids must be [1, P]")
            self.prefix_len = int(prefix_ids.shape[1])
            if self.prefix_len >= dec.cfg.max_len:
                raise ValueError(
                    f"prefix of {self.prefix_len} leaves no room under "
                    f"max_len {dec.cfg.max_len}"
                )
            # One shared prefill; every admission copies this lane.
            pre = dec.init_cache(1)
            _, pre = self.step(params, pre, prefix_ids)
            self._prefix_cache = pre
        self.slots = [_Slot() for _ in range(max_batch)]
        self.pending: list[tuple[int, jax.Array, int, int]] = []
        self.done: dict[int, jax.Array] = {}
        self._next_id = 0
        self.ticks = 0
        self.on_token = on_token
        self.eos_id = eos_id
        self.solo_steps = 0  # what per-request loops would have cost

    # -- public API -------------------------------------------------------

    def submit(
        self,
        prompt_ids: jax.Array,
        num_steps: int,
        *,
        adapter_id: int = 0,
    ) -> int:
        """Queue a request; returns its id (resolved in .done).
        `adapter_id` selects the request's LoRA adapter when banks are
        attached (0 = base model)."""
        if prompt_ids.shape[0] != 1:
            raise ValueError("submit one request at a time ([1, T])")
        if adapter_id:
            if not self.multi_lora:
                raise ValueError(
                    "adapter_id set but params carry no adapter banks "
                    "(parallel/lora.py::stack_adapters)"
                )
            if not 0 <= adapter_id < self.num_adapters:
                raise ValueError(
                    f"adapter_id {adapter_id} out of range "
                    f"[0, {self.num_adapters})"
                )
        t0 = prompt_ids.shape[1]
        if t0 < 1:
            raise ValueError("prompt must have at least one token")
        if num_steps < 1:
            raise ValueError(
                f"num_steps={num_steps}: need at least one generated "
                "token (a non-positive count would never complete)"
            )
        if (
            not getattr(self.dec, "rolling_cache", False)
            and self.prefix_len + t0 + num_steps > self.dec.cfg.max_len
        ):
            # Rolling caches have no length bound — slots recycle.
            raise ValueError(
                f"prefix {self.prefix_len} + prompt {t0} + steps "
                f"{num_steps} exceeds max_len {self.dec.cfg.max_len}"
            )
        rid = self._next_id
        self._next_id += 1
        self.pending.append((rid, prompt_ids, num_steps, adapter_id))
        self.solo_steps += num_steps
        return rid

    def run(self) -> dict[int, jax.Array]:
        """Serve until every submitted request completes; returns
        {request_id: ids [1, T0 + num_steps]}."""
        while self.pending or any(s.req is not None for s in self.slots):
            self._admit()
            self._tick()
        return self.done

    # -- internals --------------------------------------------------------

    def _admit(self) -> None:
        for i, slot in enumerate(self.slots):
            if slot.req is not None or not self.pending:
                continue
            rid, prompt, steps, adapter_id = self.pending.pop(0)
            t0 = prompt.shape[1]
            P = self.prefix_len
            rolling = getattr(self.dec, "rolling_cache", False)
            win = self.dec.cfg.window if rolling else None
            if rolling and t0 > win:
                # Longer-than-window prompt: window-chunked rolling
                # prefill (fixed window pieces + at most `win` distinct
                # tail shapes — bounded compile set; padding a rolling
                # step on a WARM cache would evict live slots).
                small = self.dec.init_cache(1)
                if self.multi_lora:
                    small["adapter"] = jnp.full(
                        (1,), adapter_id, jnp.int32
                    )
                last, small = self.dec.prefill(
                    self.params, small, prompt, chunk=win
                )
                first = jnp.argmax(last, axis=-1)[:, None].astype(
                    prompt.dtype
                )
                self._install_lane(
                    i, slot, rid, steps, prompt, small, first,
                    t0, adapter_id,
                )
                continue
            # Bucketed prefill keeps the compiled-shape set small.
            # Rolling admission always starts from a FRESH lane, so
            # padded rows sit at held < 0 (masked) and the window caps
            # the bucket instead of max_len.
            pad = 1 << (t0 - 1).bit_length()
            pad = min(pad, win if rolling else self.dec.cfg.max_len - P)
            padded = jnp.concatenate(
                [prompt, jnp.zeros((1, pad - t0), prompt.dtype)], axis=1
            )
            if self._prefix_cache is None:
                small = self.dec.init_cache(1)
                if self.multi_lora:
                    small["adapter"] = jnp.full(
                        (1,), adapter_id, jnp.int32
                    )
                logits, small = self.step(self.params, small, padded)
            else:
                # Suffix prefill through a NON-donating step: the
                # master prefix lane is read in place (no per-admission
                # deep copy of two [L, 1, Hkv, max_len, Dh] buffers —
                # the cost prefix caching exists to avoid) and the
                # returned cache is a fresh tree. (prefix caching +
                # multi-LoRA is rejected at construction.)
                small = dict(self._prefix_cache)
                logits, small = self.dec.make_step(donate=False)(
                    self.params, small, padded
                )
            first = jnp.argmax(logits[:, t0 - 1, :], axis=-1)[
                :, None
            ].astype(prompt.dtype)
            self._install_lane(
                i, slot, rid, steps, prompt, small, first,
                P + t0, adapter_id,
            )

    def _install_lane(
        self, i, slot, rid, steps, prompt, small, first, pos_val,
        adapter_id,
    ) -> None:
        """The one admission tail both prefill paths share: insert the
        prefilled lane into slot i (rows past pos_val are stale but
        position-masked until overwritten), set per-slot state, and
        run the eos/streaming/finish bookkeeping."""
        new_cache = {
            "k": jax.lax.dynamic_update_slice(
                self.cache["k"], small["k"], (0, i, 0, 0, 0)
            ),
            "v": jax.lax.dynamic_update_slice(
                self.cache["v"], small["v"], (0, i, 0, 0, 0)
            ),
            "pos": self.cache["pos"].at[i].set(pos_val),
        }
        if self.multi_lora:
            new_cache["adapter"] = (
                self.cache["adapter"].at[i].set(adapter_id)
            )
        self.cache = new_cache
        slot.req = rid
        slot.remaining = steps - 1
        slot.last = first
        slot.toks = [prompt, first]
        if self.eos_id is not None and int(first[0, 0]) == self.eos_id:
            slot.remaining = 0
        if self.on_token is not None:
            self.on_token(rid, int(first[0, 0]), slot.remaining == 0)
        if slot.remaining == 0:
            self._finish(slot)

    def _tick(self) -> None:
        active = [s.req is not None for s in self.slots]
        if not any(active):
            return
        feed = jnp.concatenate(
            [
                s.last
                if s.req is not None
                else jnp.zeros((1, 1), jnp.int32)
                for s in self.slots
            ],
            axis=0,
        )
        logits, cache = self.step(self.params, self.cache, feed)
        self.ticks += 1
        # Inactive slots wrote a dummy row at their position; pin them
        # back to 0 so they never creep toward max_len.
        mask = jnp.asarray(active)
        cache = {**cache, "pos": jnp.where(mask, cache["pos"], 0)}
        self.cache = cache
        nxt = jnp.argmax(logits[:, -1, :], axis=-1)  # (B,)
        # One device->host transfer per tick for streaming/eos, not
        # one blocking int() per slot.
        need_host = self.on_token is not None or self.eos_id is not None
        host_nxt = np.asarray(nxt) if need_host else None
        for i, slot in enumerate(self.slots):
            if slot.req is None:
                continue
            tok = nxt[i][None, None].astype(slot.last.dtype)
            slot.last = tok
            slot.toks.append(tok)
            slot.remaining -= 1
            if (
                self.eos_id is not None
                and int(host_nxt[i]) == self.eos_id
            ):
                slot.remaining = 0
            if self.on_token is not None:
                self.on_token(
                    slot.req, int(host_nxt[i]), slot.remaining == 0
                )
            if slot.remaining == 0:
                self._finish(slot)

    def _finish(self, slot: _Slot) -> None:
        self.done[slot.req] = jnp.concatenate(slot.toks, axis=1)
        slot.req = None
        slot.toks = None
        slot.last = None


def serve_greedy(
    dec: Any,
    params: dict,
    requests: list[tuple[jax.Array, int]],
    *,
    max_batch: int = 4,
    prefix_ids: jax.Array | None = None,
    eos_id: int | None = None,
) -> tuple[list[jax.Array], dict]:
    """One-shot convenience: serve `[(prompt, steps), ...]`, returning
    outputs in submission order plus stats (`ticks` batched decode
    steps taken vs `solo_steps` a per-request loop would take; with a
    shared prefix, `saved_prefill_tokens` counts the K/V rows each
    admission reused instead of recomputing). With `prefix_ids`, each
    prompt is the per-request SUFFIX and outputs cover suffix +
    generation (the prefix ids are not repeated in the result)."""
    srv = DecodeServer(
        dec, params, max_batch=max_batch, prefix_ids=prefix_ids,
        eos_id=eos_id,
    )
    rids = [srv.submit(p, s) for p, s in requests]
    done = srv.run()
    stats = {
        "ticks": srv.ticks,
        "solo_steps": srv.solo_steps,
        "saved_prefill_tokens": srv.prefix_len * len(requests),
    }
    return [done[r] for r in rids], stats
