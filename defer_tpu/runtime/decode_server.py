"""Continuous-batching decode server: admit requests into batch slots
mid-flight.

A plain batched `generate` convoys requests: the batch finishes when
its LAST member does, and new arrivals wait for the whole batch. Here
the decode batch is a set of SLOTS, each at its own depth — the cache
write head is a (B,) position VECTOR (models/gpt.py `per_slot`), so
one jitted (B, 1) step advances every active request regardless of
age, and a finished slot is immediately re-admitted with the next
queued request:

  * admission = single-request prefill (prompt padded to a pow2
    bucket, so the compiled-shape set stays tiny) whose K/V rows are
    inserted into the slot's lane of the big cache; stale rows past
    the slot's position are never attended (position masking) and are
    overwritten as the slot advances;
  * every decode tick is ONE weight read shared by all active slots —
    exactly the batching economics decode wants (weights dominate,
    models/gpt.py), now without convoy latency;
  * shapes are static everywhere: max_batch slots, bucketed prefill,
    (B, 1) ticks; inactive slots decode a dummy token into row 0 and
    their position is pinned back to 0 after each tick.

Greedy by default, per-request sampling on demand: `submit(...,
sampling=SamplingParams(temperature, top_k, top_p, min_p, seed))`
routes that slot through a batched in-tick sampler keyed by its OWN
seeded PRNG stream (SlotSampler), while greedy slots keep the argmax
fast path. Either way each request's output is BIT-IDENTICAL to a solo
`dec.generate` of that request (same seed) at the tested scales — the
correctness contract the tests pin. (At large widths/vocabs with random weights,
greedy decoding itself is ill-conditioned: near-ties in the softmax
mean the bucketed/offset prefill's different-but-equivalent reduction
shapes can flip an argmax; examples/serve_decode.py --check therefore
verifies greedy-validity under a tie tolerance instead.) The
reference's serving story is a fixed stream of identical CNN frames
(reference src/test.py:30-41); this is the autoregressive
counterpart, composing with runtime/batching.py's request coalescing.

Prefix caching (`prefix_ids=`): serving workloads share a system
prompt; its K/V rows are identical for every request, so the server
prefills the prefix ONCE into a one-lane cache and each admission
copies that lane and prefills only the request's suffix — admission
cost drops from O(prefix + prompt) to O(prompt) while outputs stay
bit-identical to solo generation over the concatenated ids.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from defer_tpu.constrain import runtime as crt
from defer_tpu.models.gpt import (
    sample_token_batched,
    sample_token_batched_nosort,
)
from defer_tpu.obs.serving import ServerStats, ServingMetrics
from defer_tpu.runtime.batching import window_drain_order
from defer_tpu.runtime.stopping import matcher_or_none, normalize_stops
from defer_tpu.utils.memo import cached_step


class SlotSampler:
    """Per-slot sampling state shared by both continuous-batching
    servers (flat and paged): one PRNG key per slot plus the policy
    vectors sample_token_batched reads. A slot admitted with
    SamplingParams draws inside the shared batched tick from its OWN
    key stream (jax.random.key(seed), one split per emitted token —
    the schedule solo generate follows), so its output reproduces
    `generate(..., rng=jax.random.key(seed))` bit-for-bit. Greedy
    slots keep the argmax fast path."""

    def __init__(self, max_batch: int):
        self.keys = jax.vmap(jax.random.key)(
            jnp.zeros((max_batch,), jnp.uint32)
        )
        self.temp = jnp.zeros((max_batch,), jnp.float32)
        self.topk = jnp.zeros((max_batch,), jnp.int32)
        self.topp = jnp.ones((max_batch,), jnp.float32)
        self.minp = jnp.zeros((max_batch,), jnp.float32)
        # Host mirror of `temp`: a greedy admission into a slot a
        # sampled request vacated must reset that row (a stale
        # temperature would re-route the greedy slot through the
        # categorical path).
        self.row_temp = [0.0] * max_batch
        # Host mirror of "this row's policy needs the sorting filters"
        # (top_k or top_p enabled). While no admitted row does, draw()
        # routes through the sort-free tick variant — same bits, no
        # O(V log V) sorts. Rows are set at admission and cleared by
        # release() the moment the slot finishes, so one top-k request
        # costs the batch the sorting path only while it is actually
        # live.
        self.row_sort = [False] * max_batch
        # Host mirror of "this row installed truncation filters"
        # (top_k/top_p/min_p): release() must reset those device rows
        # too — see release() — and the mirror keeps the greedy
        # common case free of device writes.
        self.row_filters = [False] * max_batch
        # Constrained decoding (defer_tpu/constrain/): per-slot DFA
        # policy rows — which stacked constraint table (cid, 0 = the
        # free accept-everything row) and the current DFA state. The
        # host mirror routes ticks through the constrained program
        # variants only while a constrained row is live (the row_sort
        # dispatch pattern).
        self.cid = jnp.zeros((max_batch,), jnp.int32)
        self.cstate = jnp.zeros((max_batch,), jnp.int32)
        self.row_constrained = [False] * max_batch

    def admit_first(self, i, samp, logits_row, dtype):
        """First generated token of an admission [1, 1]: greedy
        argmax, or the first draw of the request's key stream, with
        the advanced key and policy installed into slot i's rows."""
        if samp is None:
            self.row_sort[i] = False
            if self.row_temp[i] != 0.0:
                self.temp = self.temp.at[i].set(0.0)
                self.row_temp[i] = 0.0
            return jnp.argmax(logits_row, axis=-1)[:, None].astype(
                dtype
            )
        tok, key1 = sample_token_batched(
            logits_row,
            jax.random.key(samp.seed)[None],
            jnp.full((1,), samp.temperature, jnp.float32),
            jnp.full((1,), samp.top_k, jnp.int32),
            jnp.full((1,), samp.top_p, jnp.float32),
            jnp.full((1,), samp.min_p, jnp.float32),
        )
        self.keys = self.keys.at[i].set(key1[0])
        self.temp = self.temp.at[i].set(samp.temperature)
        self.topk = self.topk.at[i].set(samp.top_k)
        self.topp = self.topp.at[i].set(samp.top_p)
        self.minp = self.minp.at[i].set(samp.min_p)
        self.row_temp[i] = samp.temperature
        self.row_sort[i] = samp.top_k > 0 or samp.top_p < 1.0
        self.row_filters[i] = (
            samp.top_k > 0 or samp.top_p < 1.0 or samp.min_p > 0.0
        )
        return tok[:, None].astype(dtype)

    def admit_constraint(self, i: int, cid, state) -> None:
        """Install slot i's constraint policy rows (cid into the
        server's stacked DFA tables, state AFTER the admission's first
        token — a device scalar, no sync). The host mirror routes
        later ticks through the constrained program variants."""
        self.cid = self.cid.at[i].set(cid)
        self.cstate = self.cstate.at[i].set(state)
        self.row_constrained[i] = True

    def release(self, i: int) -> None:
        """Retire slot i's sampling policy the moment its request
        FINISHES (both servers' _finish), not when the slot is next
        reused: a stale row_sort=True would keep routing every tick
        through the sorting sampler long after the top-k request is
        gone, and a stale temperature would route the idle row's dummy
        draw through the categorical path. ALL policy rows reset —
        temperature AND the top_k/top_p/min_p filter rows (a greedy
        re-admit into a vacated sampled slot routes through the argmax
        path, but a later sampled temp-only admit into that slot would
        otherwise inherit the dead request's filters) AND the
        constraint rows. Greedy unconstrained rows are already
        released — the common case stays free of device writes. Idle
        rows' keys keep advancing in draw(), which is fine: admission
        re-seeds them."""
        self.row_sort[i] = False
        if self.row_temp[i] != 0.0:
            self.temp = self.temp.at[i].set(0.0)
            self.row_temp[i] = 0.0
        if self.row_filters[i]:
            self.topk = self.topk.at[i].set(0)
            self.topp = self.topp.at[i].set(1.0)
            self.minp = self.minp.at[i].set(0.0)
            self.row_filters[i] = False
        if self.row_constrained[i]:
            self.cid = self.cid.at[i].set(0)
            self.cstate = self.cstate.at[i].set(0)
            self.row_constrained[i] = False

    def draw(self, logits_last):
        """One batched draw over every slot's policy (B,): sampled
        rows split their own key exactly once, greedy rows reduce to
        the same argmax as the fast path. Advances the key state.
        While no admitted row enables top-k/top-p, the draw takes the
        sort-free variant (bit-identical, see
        sample_token_batched_nosort)."""
        if not any(self.row_sort):
            nxt, self.keys = sample_token_batched_nosort(
                logits_last, self.keys, self.temp, self.minp
            )
            return nxt
        nxt, self.keys = sample_token_batched(
            logits_last,
            self.keys,
            self.temp,
            self.topk,
            self.topp,
            self.minp,
        )
        return nxt


class DraftLanes:
    """Per-slot flat lanes for a speculative DRAFT decoder — the
    draft-side bookkeeping seam the paged server's `spec_k` mode rides
    (runtime/paged.py).

    The target's K/V lives in the paged pool; the draft keeps a plain
    flat cache of max_batch lanes (draft models are small, so lane
    waste is cheap and the contiguous layout keeps the k-step proposal
    scan trivial). Host-side `pos` is the truth for how many COMMITTED
    tokens each lane covers: the server passes it down every round
    (idle/non-speculating rows pinned to 0, the flat server's
    idle-slot idiom), so device-side position drift from dummy rows
    can never accumulate.

    `propose()` is ONE fused dispatch per round: a [B, 2] catch-up
    step consumes each slot's 1-2 committed-but-unconsumed tokens
    (1 after a rejection, 2 after a full accept — the lag the solo
    speculative loop's `n0 - d_pos in (1, 2)` assertion pins), then a
    `lax.scan` of k-1 single-token greedy steps emits the remaining
    proposals. Slots with lag 1 feed their token twice and advance by
    1 — the duplicate row is written at pos+1 and immediately
    overwritten by the first scan step."""

    def __init__(
        self,
        dec: Any,
        params: dict,
        max_batch: int,
        *,
        target: Any = None,
    ):
        if getattr(dec, "rolling_cache", False):
            raise ValueError(
                "a rolling-cache draft cannot rewind rejected rows"
            )
        if getattr(dec, "decode_step_fn", None) is None:
            raise ValueError(
                "the draft decoder must expose decode_step_fn() "
                f"(models/gpt.py GptDecoder); {type(dec).__name__} "
                "does not"
            )
        dec.decode_step_fn()  # SpmdGptDecoder raises at construction
        if target is not None:
            self._check_geometry(dec.cfg, target.cfg)
        self.dec = dec
        self.params = params
        self.B = max_batch
        cache = dec.init_cache(max_batch)
        self.ck = cache["k"]
        self.cv = cache["v"]
        self.pos = np.zeros((max_batch,), np.int32)

    def admit(self, i: int, prompt: jax.Array) -> None:
        """Prefill slot i's draft lane with the request's FULL prompt
        (pow2-bucketed, the shared admission idiom) and lane-insert it
        — `_install_lane` for the draft cache. Afterwards the lane
        covers the t0 prompt tokens; the first generated token is the
        slot's initial pending feed (server-side)."""
        t0 = prompt.shape[1]
        pad = 1 << (t0 - 1).bit_length()
        pad = min(pad, self.dec.cfg.max_len)
        padded = jnp.concatenate(
            [prompt, jnp.zeros((1, pad - t0), prompt.dtype)], axis=1
        )
        small = self.dec.init_cache(1)
        _, small = self.dec.make_step()(self.params, small, padded)
        self.ck = lax.dynamic_update_slice(
            self.ck, small["k"], (0, i, 0, 0, 0)
        )
        self.cv = lax.dynamic_update_slice(
            self.cv, small["v"], (0, i, 0, 0, 0)
        )
        self.pos[i] = t0

    @staticmethod
    def _check_geometry(draft_cfg, target_cfg) -> None:
        """Draft-vs-target geometry gates, each with the fix spelled
        out. The draft proposes TOKEN IDS the target scores, so the
        vocabularies must be the same id space; kv_heads and the
        position encoding must match so a transplant-carved draft
        (models/transplant.py::make_draft) is attending with the same
        per-head/rotary geometry the verifier will re-score under —
        anything else silently tanks acceptance."""
        if draft_cfg.vocab_size != target_cfg.vocab_size:
            raise ValueError(
                f"draft vocab_size={draft_cfg.vocab_size} != target "
                f"vocab_size={target_cfg.vocab_size}: proposals are "
                "target-vocab token ids. Fix: build the draft from the "
                "target with models/transplant.py::make_draft (it "
                "preserves the vocabulary), or retrain the draft on "
                "the target's tokenizer."
            )
        if draft_cfg.kv_heads != target_cfg.kv_heads:
            raise ValueError(
                f"draft kv_heads={draft_cfg.kv_heads} != target "
                f"kv_heads={target_cfg.kv_heads}. Fix: carve the draft "
                "with make_draft(width=...) — it prunes QUERY heads to "
                "a multiple of the target's kv_heads and never touches "
                "the KV width — instead of hand-shrinking num_kv_heads."
            )
        if draft_cfg.pos_style != target_cfg.pos_style:
            raise ValueError(
                f"draft pos_style={draft_cfg.pos_style!r} != target "
                f"pos_style={target_cfg.pos_style!r}: the two models "
                "would disagree about every position. Fix: make_draft "
                "keeps the target's position encoding; use it."
            )
        if (
            draft_cfg.pos_style == "rope"
            and draft_cfg.rope_theta != target_cfg.rope_theta
        ):
            raise ValueError(
                f"draft rope_theta={draft_cfg.rope_theta} != target "
                f"rope_theta={target_cfg.rope_theta}: rotary frequency "
                "bases must match or long-context proposals rotate "
                "away from the verifier. Fix: make_draft preserves "
                "rope_theta (and the head dim it applies to); rebuild "
                "the draft with it."
            )

    def release(self, i: int) -> None:
        """Clear lane i COMPLETELY: pos back to 0 AND the cached K/V
        rows zeroed. pos alone is not enough — an idle lane still
        rides through every propose dispatch (masked by posm=0), and
        stale rows from a slot retired MID-ROUND would otherwise sit
        in device memory until the next admit overwrites them."""
        self.pos[i] = 0
        self.ck = self.ck.at[:, i].set(0)
        self.cv = self.cv.at[:, i].set(0)

    def release_all(self) -> None:
        """Drop every lane — the replica-death / server-teardown path
        (fleet/replica.py): no slot survives, so no lane may either."""
        self.pos[:] = 0
        self.ck = jnp.zeros_like(self.ck)
        self.cv = jnp.zeros_like(self.cv)

    def _propose_body(self, k: int):
        """The RAW (unjitted) propose body `(params, dk, dv, dpos,
        feed2, adv) -> (dk, dv, props)` — trace-compatible with
        `lax.scan`, so the paged server can fuse W draft+verify rounds
        into ONE `decode_window` program (runtime/paged.py::
        _tick_spec_window) instead of dispatching propose W times."""
        raw = self.dec.decode_step_fn()

        def propose(params, dk, dv, dpos, feed2, adv):
            cache = {"k": dk, "v": dv, "pos": dpos}
            logits2, cache = raw(params, cache, feed2)
            # Row adv-1 is the prediction after the LAST real
            # pending token; later rows are duplicate-feed noise.
            first_l = jnp.take_along_axis(
                logits2,
                jnp.maximum(adv - 1, 0)[:, None, None],
                axis=1,
            )[:, 0, :]
            nxt = jnp.argmax(first_l, axis=-1).astype(jnp.int32)
            # Correct per-slot positions after the variable-lag
            # catch-up (the raw step advanced every row by 2).
            pos1 = dpos + adv

            def body(carry, _):
                ck, cv, pos, tok = carry
                lg, c2 = raw(
                    params,
                    {"k": ck, "v": cv, "pos": pos},
                    tok[:, None],
                )
                t2 = jnp.argmax(lg[:, -1, :], axis=-1).astype(
                    jnp.int32
                )
                return (c2["k"], c2["v"], c2["pos"], t2), t2

            (dk, dv, _, _), rest = lax.scan(
                body,
                (cache["k"], cache["v"], pos1, nxt),
                None,
                length=k - 1,
            )
            props = jnp.concatenate([nxt[:, None], rest.T], axis=1)
            return dk, dv, props

        return propose

    def _propose_body_c(self, k: int, eos: int):
        """Constrained propose body (defer_tpu/constrain/): the same
        catch-up + k-step greedy scan, but each proposal argmax is
        masked by the slot's DFA row and a LOCAL DFA state walks
        forward with the proposals — so a constrained slot's draft
        chain stays inside its grammar and the target's accept rule
        sees grammar-valid candidates instead of rejecting everything
        at position 0. Free rows (cid 0) fold an all-True mask: their
        proposals are bit-identical to _propose_body's. A dead local
        state needs no special case: its garbage argmax can never
        match the target's forced out-of-vocab pred, so acceptance
        truncates there."""
        from defer_tpu.constrain import runtime as crt

        raw = self.dec.decode_step_fn()

        def propose(params, dk, dv, dpos, feed2, adv, cid, cstate,
                    ctrans, cacc):
            cvec = cid > 0
            cache = {"k": dk, "v": dv, "pos": dpos}
            logits2, cache = raw(params, cache, feed2)
            first_l = jnp.take_along_axis(
                logits2,
                jnp.maximum(adv - 1, 0)[:, None, None],
                axis=1,
            )[:, 0, :]
            crow, acc = crt.constrain_rows(ctrans, cacc, cid, cstate)
            cmask = crt.constrain_mask(crow, acc, eos)
            nxt = jnp.argmax(
                crt.fold_mask(first_l, cmask), axis=-1
            ).astype(jnp.int32)
            cstate = crt.advance_state(crow, cstate, nxt, cvec)
            pos1 = dpos + adv

            def body(carry, _):
                ck, cv, pos, tok, cs = carry
                lg, c2 = raw(
                    params,
                    {"k": ck, "v": cv, "pos": pos},
                    tok[:, None],
                )
                crow, acc = crt.constrain_rows(ctrans, cacc, cid, cs)
                cmask = crt.constrain_mask(crow, acc, eos)
                t2 = jnp.argmax(
                    crt.fold_mask(lg[:, -1, :], cmask), axis=-1
                ).astype(jnp.int32)
                cs = crt.advance_state(crow, cs, t2, cvec)
                return (c2["k"], c2["v"], c2["pos"], t2, cs), t2

            (dk, dv, _, _, _), rest = lax.scan(
                body,
                (cache["k"], cache["v"], pos1, nxt, cstate),
                None,
                length=k - 1,
            )
            props = jnp.concatenate([nxt[:, None], rest.T], axis=1)
            return dk, dv, props

        return propose

    def _build_propose(self, k: int):
        def build():
            return jax.jit(self._propose_body(k), donate_argnums=(1, 2))

        return cached_step(self.dec, ("spec_propose", self.B, k), build)

    def _build_propose_c(self, k: int, eos: int):
        def build():
            return jax.jit(
                self._propose_body_c(k, eos), donate_argnums=(1, 2)
            )

        return cached_step(
            self.dec, ("spec_propose_c", self.B, k, eos), build
        )

    def propose_c(self, k, posm, feed2, adv, eos, cid, cstate,
                  ctrans, cacc):
        """Constrained twin of propose() (separate memo key — the
        unconstrained program is untouched): proposals are masked by
        each slot's DFA walk (_propose_body_c). `cstate` is the
        server's COMMITTED per-slot state — every emitted token is
        already folded in, so the local walk continues exactly where
        the target's mask will check."""
        prog = self._build_propose_c(k, eos)
        self.ck, self.cv, props = prog(
            self.params,
            self.ck,
            self.cv,
            jnp.asarray(posm, jnp.int32),
            jnp.asarray(feed2, jnp.int32),
            jnp.asarray(adv, jnp.int32),
            cid,
            cstate,
            ctrans,
            cacc,
        )
        return props

    def propose(self, k, posm, feed2, adv):
        """One fused draft dispatch: catch up on pending committed
        tokens, then emit k greedy proposals per slot. `posm` [B] =
        host-truth lane coverage, non-speculating rows 0; `feed2`
        [B, 2] pending tokens (lag-1 rows duplicated); `adv` [B] in
        {0, 1, 2} = real pending count. Returns device [B, k]
        proposals (garbage rows for adv=0 slots — the caller masks by
        slot). Lane coverage afterwards is posm + adv + k - 1 for
        speculating rows: the k-th proposal is never self-consumed."""
        prog = self._build_propose(k)
        self.ck, self.cv, props = prog(
            self.params,
            self.ck,
            self.cv,
            jnp.asarray(posm, jnp.int32),
            jnp.asarray(feed2, jnp.int32),
            jnp.asarray(adv, jnp.int32),
        )
        return props


@dataclasses.dataclass
class _Slot:
    req: int | None = None
    remaining: int = 0
    last: Any = None  # next token to feed, [1, 1]
    toks: list | None = None
    sampling: bool = False  # this request runs at temperature > 0
    stop: Any = None  # per-request StopMatcher (runtime/stopping.py)
    cid: int = 0  # stacked-constraint index (0 = unconstrained)


class DecodeServer:
    """Continuous-batching decoder over `max_batch` slots; greedy by
    default, per-request sampling via `submit(..., sampling=)`."""

    def __init__(
        self,
        dec: Any,
        params: dict,
        *,
        max_batch: int = 4,
        prefix_ids: jax.Array | None = None,
        on_token: Any = None,
        eos_id: int | None = None,
        decode_window: int = 1,
        constraints: dict | None = None,
    ):
        """`on_token(request_id, token_id, done)` — optional streaming
        callback fired for every generated token as its batched tick
        resolves (`done=True` on the request's final token). Keep it
        cheap: it runs on the serving thread between ticks.

        `constraints` — named constraint DFAs ({name:
        constrain.TokenDFA}, compiled against this decoder's
        vocabulary) a request selects with
        SamplingParams(constraint=name): that slot's logits are
        masked to grammar-admissible tokens (eos admitted only in
        accepting states) before argmax/categorical, and the DFA
        state advances on device inside the same tick/window
        programs. Requires `eos_id` (a satisfied constraint must be
        able to stop). With the default None, every traced program is
        byte-identical to a server built before this feature existed.

        `eos_id` — stop token: a request that emits it finishes
        immediately (its output ends with the eos) and its slot
        re-admits the next queued request, so num_steps becomes a
        budget rather than an exact length.

        `decode_window` — decode sub-steps fused into ONE jitted host
        dispatch (K). At the default 1 the server is the classic
        tick-per-token loop, bit-identical to before the window path
        existed. At K > 1 a `lax.scan` advances every active slot up
        to K tokens on device — sampling and eos detection included —
        and the host sees one batched [B, K] transfer per WINDOW
        instead of one [B, 1] transfer per token; admissions and
        retirements happen at window boundaries. Outputs stay
        token-identical to decode_window=1 (greedy bit-identical;
        sampled streams follow the same per-slot key schedule). A slot
        that hits eos or its budget mid-window is frozen on device
        (its position pinned, its tail tokens discarded on drain) —
        the latency cost of a larger K is finishing slots idling until
        the window boundary."""
        if decode_window < 1:
            raise ValueError(
                f"decode_window must be >= 1, got {decode_window}"
            )
        self.decode_window = decode_window
        if decode_window > 1:
            raw = getattr(dec, "decode_step_fn", None)
            if raw is None:
                raise ValueError(
                    "decode_window > 1 needs a decoder exposing "
                    "decode_step_fn() (models/gpt.py GptDecoder); "
                    f"{type(dec).__name__} does not"
                )
            raw()  # SpmdGptDecoder raises here: fail at construction
        self.dec = dec
        self.params = params
        self.B = max_batch
        self.step = dec.make_step()  # batched ticks (donating)
        cache = dec.init_cache(max_batch)
        cache["pos"] = jnp.zeros((max_batch,), jnp.int32)
        # Multi-LoRA serving: adapter banks attached to the params
        # (parallel/lora.py::stack_adapters) make the slot -> adapter
        # assignment per-slot cache state; id 0 = base model.
        from defer_tpu.parallel.lora import adapter_bank_info

        n_adapters = adapter_bank_info(params)
        self.multi_lora = n_adapters is not None
        if self.multi_lora:
            cache["adapter"] = jnp.zeros((max_batch,), jnp.int32)
            self.num_adapters = n_adapters
        self.cache = cache
        self.prefix_len = 0
        self._prefix_cache = None
        if prefix_ids is not None:
            if self.multi_lora:
                raise ValueError(
                    "prefix caching + multi-LoRA is unsupported: the "
                    "shared prefix K/V would be adapter-dependent"
                )
            if getattr(dec, "rolling_cache", False):
                raise ValueError(
                    "prefix caching over a rolling cache is not "
                    "supported (prefix rows would be recycled)"
                )
            if prefix_ids.ndim != 2 or prefix_ids.shape[0] != 1:
                raise ValueError("prefix_ids must be [1, P]")
            self.prefix_len = int(prefix_ids.shape[1])
            if self.prefix_len >= dec.cfg.max_len:
                raise ValueError(
                    f"prefix of {self.prefix_len} leaves no room under "
                    f"max_len {dec.cfg.max_len}"
                )
            # One shared prefill; every admission copies this lane.
            pre = dec.init_cache(1)
            _, pre = self.step(params, pre, prefix_ids)
            self._prefix_cache = pre
        # Constrained decoding tables (defer_tpu/constrain/): stacked
        # [C, S_max, V] transitions + [C, S_max] accepting bits, cid 0
        # the synthetic free row. None when the feature is off — every
        # tick then takes the exact pre-constraint code path.
        self._ctrans = None
        self._cacc = None
        self._cnames: dict[str, int] = {}
        self._cdfas: list = [None]
        if constraints is not None:
            if eos_id is None:
                raise ValueError(
                    "constraints= requires eos_id: a satisfied "
                    "constraint stops by emitting eos"
                )
            self._cnames, self._ctrans, self._cacc = (
                crt.stack_token_dfas(constraints, dec.cfg.vocab_size)
            )
            self._cdfas += [
                constraints[n]
                for n in sorted(self._cnames, key=self._cnames.get)
            ]
        # Per-request constraint failures (hand-built DFA dead ends):
        # rid -> message. The slot finishes cleanly; compiled DFAs
        # never land here (dfa.py prunes dead states).
        self.errors: dict[int, str] = {}
        self.constrained_tokens_n = 0
        self.constraint_dead_ends_n = 0
        self.slots = [_Slot() for _ in range(max_batch)]
        # Persistent tick feed: each slot's next input token lives in
        # row i, updated by .at[i].set at admission and one
        # full-vector write after each draw — not rebuilt by
        # concatenating max_batch [1,1] arrays every tick (host
        # dispatch overhead that dominates at small models). Idle
        # rows are dummies.
        self._feed = jnp.zeros((max_batch, 1), jnp.int32)
        self._sampler = SlotSampler(max_batch)
        # Deque, not list: admission pops from the head every time a
        # seat frees, and a list's pop(0) is O(queue depth) — a deep
        # backlog would make each admission scan the whole tail.
        self.pending: collections.deque[tuple] = collections.deque()
        self.done: dict[int, jax.Array] = {}
        self._next_id = 0
        self.ticks = 0
        self.on_token = on_token
        self.eos_id = eos_id
        self.solo_steps = 0  # what per-request loops would have cost
        # Dispatch-efficiency accounting (fused windows): host
        # dispatches of the decode program and tokens accepted from
        # them. At decode_window=1, dispatches == ticks.
        self.dispatches = 0
        self.window_tokens = 0
        # Metric handles resolved once; the tick/admission paths touch
        # pre-bound attributes only (obs/serving.py).
        self.obs = ServingMetrics("flat")
        self._submit_t: dict[int, float] = {}
        self._last_tick_t: float | None = None

    # -- public API -------------------------------------------------------

    def submit(
        self,
        prompt_ids: jax.Array,
        num_steps: int,
        *,
        adapter_id: int = 0,
        sampling: Any = None,
        stop: Any = None,
    ) -> int:
        """Queue a request; returns its id (resolved in .done).
        `adapter_id` selects the request's LoRA adapter when banks are
        attached (0 = base model). `sampling` — an optional
        models/gpt.py SamplingParams: the slot then samples inside the
        shared batched tick with its own temperature/top-k/top-p/min-p
        and a per-request key, reproducing
        `generate(..., rng=jax.random.key(seed))` bit-for-bit; None =
        greedy (the temperature-0 special case). `stop` — optional
        multi-token stop sequences (iterable of int sequences,
        runtime/stopping.py): the request finishes the moment its
        GENERATED tail equals any of them, output ending with the stop
        sequence — the multi-token generalization of `eos_id`."""
        if prompt_ids.shape[0] != 1:
            raise ValueError("submit one request at a time ([1, T])")
        cid = 0
        if sampling is not None:
            sampling.validate()
            # The constraint survives the greedy normalization below:
            # temperature-0 JSON mode is the common case.
            cid = self._resolve_constraint(sampling.constraint)
            if sampling.temperature == 0:
                sampling = None  # greedy: keep the argmax fast path
        stop_seqs = normalize_stops(stop)
        if adapter_id:
            if not self.multi_lora:
                raise ValueError(
                    "adapter_id set but params carry no adapter banks "
                    "(parallel/lora.py::stack_adapters)"
                )
            if not 0 <= adapter_id < self.num_adapters:
                raise ValueError(
                    f"adapter_id {adapter_id} out of range "
                    f"[0, {self.num_adapters})"
                )
        t0 = prompt_ids.shape[1]
        if t0 < 1:
            raise ValueError("prompt must have at least one token")
        if num_steps < 1:
            raise ValueError(
                f"num_steps={num_steps}: need at least one generated "
                "token (a non-positive count would never complete)"
            )
        if (
            not getattr(self.dec, "rolling_cache", False)
            and self.prefix_len + t0 + num_steps > self.dec.cfg.max_len
        ):
            # Rolling caches have no length bound — slots recycle.
            raise ValueError(
                f"prefix {self.prefix_len} + prompt {t0} + steps "
                f"{num_steps} exceeds max_len {self.dec.cfg.max_len}"
            )
        rid = self._next_id
        self._next_id += 1
        self.pending.append(
            (rid, prompt_ids, num_steps, adapter_id, sampling,
             stop_seqs, cid)
        )
        self.solo_steps += num_steps
        self._submit_t[rid] = time.perf_counter()
        return rid

    def _resolve_constraint(self, name: str | None) -> int:
        return crt.resolve_constraint(
            name, self._ctrans, self._cnames, self._cdfas
        )

    def run(self) -> dict[int, jax.Array]:
        """Serve until every submitted request completes; returns
        {request_id: ids [1, T0 + num_steps]}."""
        while self.pending or any(s.req is not None for s in self.slots):
            self._admit()
            self._tick()
        return self.done

    # -- internals --------------------------------------------------------

    def _admit(self) -> None:
        for i, slot in enumerate(self.slots):
            if slot.req is not None or not self.pending:
                continue
            (rid, prompt, steps, adapter_id, samp,
             stop_seqs, cid) = self.pending.popleft()
            t0 = prompt.shape[1]
            self.obs.requests_admitted.inc()
            self.obs.prefill_tokens.inc(t0)
            # Strict lookup: an unknown rid would silently observe a
            # zero queue wait — a missing submit timestamp is a bug.
            self.obs.queue_wait.observe(
                time.perf_counter() - self._submit_t[rid]
            )
            P = self.prefix_len
            rolling = getattr(self.dec, "rolling_cache", False)
            win = self.dec.cfg.window if rolling else None
            if rolling and t0 > win:
                # Longer-than-window prompt: window-chunked rolling
                # prefill (fixed window pieces + at most `win` distinct
                # tail shapes — bounded compile set; padding a rolling
                # step on a WARM cache would evict live slots).
                small = self.dec.init_cache(1)
                if self.multi_lora:
                    small["adapter"] = jnp.full(
                        (1,), adapter_id, jnp.int32
                    )
                last, small = self.dec.prefill(
                    self.params, small, prompt, chunk=win
                )
                first = self._first_token(i, samp, last, prompt.dtype,
                                          cid)
                self._install_lane(
                    i, slot, rid, steps, prompt, small, first,
                    t0, adapter_id, samp, stop_seqs, cid,
                )
                continue
            # Bucketed prefill keeps the compiled-shape set small.
            # Rolling admission always starts from a FRESH lane, so
            # padded rows sit at held < 0 (masked) and the window caps
            # the bucket instead of max_len.
            pad = 1 << (t0 - 1).bit_length()
            pad = min(pad, win if rolling else self.dec.cfg.max_len - P)
            padded = jnp.concatenate(
                [prompt, jnp.zeros((1, pad - t0), prompt.dtype)], axis=1
            )
            if self._prefix_cache is None:
                small = self.dec.init_cache(1)
                if self.multi_lora:
                    small["adapter"] = jnp.full(
                        (1,), adapter_id, jnp.int32
                    )
                logits, small = self.step(self.params, small, padded)
            else:
                # Suffix prefill through a NON-donating step: the
                # master prefix lane is read in place (no per-admission
                # deep copy of two [L, 1, Hkv, max_len, Dh] buffers —
                # the cost prefix caching exists to avoid) and the
                # returned cache is a fresh tree. (prefix caching +
                # multi-LoRA is rejected at construction.)
                small = dict(self._prefix_cache)
                logits, small = self.dec.make_step(donate=False)(
                    self.params, small, padded
                )
            first = self._first_token(
                i, samp, logits[:, t0 - 1, :], prompt.dtype, cid
            )
            self._install_lane(
                i, slot, rid, steps, prompt, small, first,
                P + t0, adapter_id, samp, stop_seqs, cid,
            )

    def _first_token(self, i, samp, lrow, dtype, cid):
        """Admission's first generated token: constrained slots mask
        the prefill logits row with their DFA's START-state row before
        the shared argmax/first-draw, then install the advanced state
        (a device scalar — admission stays sync-free beyond its
        existing bookkeeping)."""
        if cid:
            row = self._ctrans[cid, 0]
            mask = (row >= 0).at[self.eos_id].set(self._cacc[cid, 0])
            lrow = jnp.where(mask[None, :], lrow,
                             jnp.finfo(lrow.dtype).min)
        first = self._sampler.admit_first(i, samp, lrow, dtype)
        if cid:
            state = jnp.maximum(row[first[0, 0].astype(jnp.int32)], 0)
            self._sampler.admit_constraint(i, cid, state)
            frac = crt.masked_frac(mask[None, :], jnp.asarray([True]))
            # analysis: ignore[host-sync-in-hot-loop] once per
            # CONSTRAINED admission (first token only), not per tick —
            # the paged server's mixed-mode flips made _first_token
            # tick-reachable by name; the steady-state tick never
            # reaches this branch in either server
            self.obs.constrain_masked_frac.observe(float(frac[0]))
            self.obs.constrained_tokens.inc()
            self.constrained_tokens_n += 1
        return first

    def _install_lane(
        self, i, slot, rid, steps, prompt, small, first, pos_val,
        adapter_id, samp=None, stop_seqs=(), cid=0,
    ) -> None:
        """The one admission tail both prefill paths share: insert the
        prefilled lane into slot i (rows past pos_val are stale but
        position-masked until overwritten), set per-slot state, and
        run the eos/streaming/finish bookkeeping."""
        new_cache = {
            "k": jax.lax.dynamic_update_slice(
                self.cache["k"], small["k"], (0, i, 0, 0, 0)
            ),
            "v": jax.lax.dynamic_update_slice(
                self.cache["v"], small["v"], (0, i, 0, 0, 0)
            ),
            "pos": self.cache["pos"].at[i].set(pos_val),
        }
        if self.multi_lora:
            new_cache["adapter"] = (
                self.cache["adapter"].at[i].set(adapter_id)
            )
        self.cache = new_cache
        # TTFT is host-side: submit() to first-token DISPATCH (the
        # token array may still be in flight on device — honesty note
        # in ARCHITECTURE.md "Observability").
        # ttft spans queue + prefill (popped here, the drain point —
        # strict: a missing rid means the timestamp was never pinned).
        self.obs.ttft.observe(
            time.perf_counter() - self._submit_t.pop(rid)
        )
        self.obs.tokens_generated.inc()
        slot.req = rid
        slot.remaining = steps - 1
        slot.last = first
        slot.toks = [prompt, first]
        slot.sampling = samp is not None
        slot.stop = matcher_or_none(stop_seqs)
        slot.cid = cid
        self._feed = self._feed.at[i].set(first[0].astype(jnp.int32))
        need_host = (
            self.eos_id is not None
            or self.on_token is not None
            or slot.stop is not None
        )
        tok_host = int(first[0, 0]) if need_host else None
        if self.eos_id is not None and tok_host == self.eos_id:
            slot.remaining = 0
        if slot.stop is not None and slot.stop.push(tok_host):
            slot.remaining = 0
        if self.on_token is not None:
            self.on_token(rid, tok_host, slot.remaining == 0)
        if slot.remaining == 0:
            self._finish(i, slot)

    def _tick(self) -> None:
        if self.decode_window > 1:
            return self._tick_window()
        active = [s.req is not None for s in self.slots]
        if not any(active):
            return
        # Persistent [B,1] device feed (constructor note): admissions
        # set their row, draws below overwrite the whole vector.
        logits, cache = self.step(self.params, self.cache, self._feed)
        self.ticks += 1
        self.dispatches += 1
        n_active = sum(active)
        now = time.perf_counter()
        if self._last_tick_t is not None:
            self.obs.itl.observe(now - self._last_tick_t, n_active)
        self._last_tick_t = now
        self.obs.ticks.inc()
        self.obs.host_dispatches.inc()
        self.obs.tokens_per_dispatch.set(float(n_active))
        self.window_tokens += n_active
        self.obs.tokens_generated.inc(n_active)
        # Inactive slots wrote a dummy row at their position; pin them
        # back to 0 so they never creep toward max_len.
        mask = jnp.asarray(active)
        cache = {**cache, "pos": jnp.where(mask, cache["pos"], 0)}
        self.cache = cache
        ll = logits[:, -1, :]
        sm = self._sampler
        # Constrained rows (defer_tpu/constrain/): fold the DFA mask
        # into the batched logits BEFORE argmax/draw, advance states
        # after. Guarded by the host mirror so unconstrained serving
        # dispatches the exact pre-constraint op sequence.
        constrained = any(sm.row_constrained)
        if constrained:
            crow, cacc = crt.constrain_rows(
                self._ctrans, self._cacc, sm.cid, sm.cstate
            )
            cmask = crt.constrain_mask(crow, cacc, self.eos_id)
            cvec = jnp.asarray(sm.row_constrained)
            # Dead end (hand-built DFAs only — dfa.py prunes): no
            # admissible token. Force eos so the row freezes; the
            # drain drops the forced token and surfaces the error.
            dead = cvec & mask & ~cmask.any(-1)
            ll = crt.fold_mask(ll, cmask)
        if any(
            s.req is not None and s.sampling for s in self.slots
        ):
            nxt = self._sampler.draw(ll)
        else:
            nxt = jnp.argmax(ll, axis=-1)  # (B,)
        if constrained:
            nxt = jnp.where(dead, self.eos_id, nxt)
            sm.cstate = crt.advance_state(
                crow, sm.cstate, nxt, cvec & ~dead
            )
            mfrac = crt.masked_frac(cmask, cvec & mask)
        self._feed = nxt[:, None].astype(jnp.int32)
        # One device->host transfer per tick for streaming/eos/stop
        # matching, not one blocking int() per slot.
        need_host = (
            self.on_token is not None
            or self.eos_id is not None
            or any(
                s.req is not None and s.stop is not None
                for s in self.slots
            )
        )
        # analysis: ignore[host-sync-in-hot-loop] single batched
        # transfer per WINDOW (a window of one token here), and only
        # when an eos/stop/stream consumer needs host tokens — the
        # sync this serving loop is designed around
        host_nxt = np.asarray(nxt) if need_host else None
        if constrained:
            # analysis: ignore[host-sync-in-hot-loop] one batched
            # per-tick transfer of the dead-end flags + mask
            # fractions, and only while a constrained row is live
            dead_host = np.asarray(dead)
            # analysis: ignore[host-sync-in-hot-loop] ready with the
            # vector above (same sync point)
            mfrac_host = np.asarray(mfrac)
        for i, slot in enumerate(self.slots):
            if slot.req is None:
                continue
            if constrained and slot.cid:
                if bool(dead_host[i]):
                    # The forced eos never enters the output: the
                    # request ends at its last admissible token with
                    # a per-request error, not a hang.
                    self.errors[slot.req] = (
                        "constraint dead end: DFA state admits no "
                        "token and is not accepting"
                    )
                    self.constraint_dead_ends_n += 1
                    self.obs.constrain_dead_ends.inc()
                    slot.remaining = 0
                    self._finish(i, slot)
                    continue
                self.constrained_tokens_n += 1
                self.obs.constrained_tokens.inc()
                self.obs.constrain_masked_frac.observe(
                    float(mfrac_host[i])
                )
            tok = nxt[i][None, None].astype(slot.last.dtype)
            slot.last = tok
            slot.toks.append(tok)
            slot.remaining -= 1
            if (
                self.eos_id is not None
                and int(host_nxt[i]) == self.eos_id
            ):
                slot.remaining = 0
            if slot.stop is not None and slot.stop.push(
                int(host_nxt[i])
            ):
                slot.remaining = 0
            if self.on_token is not None:
                self.on_token(
                    slot.req, int(host_nxt[i]), slot.remaining == 0
                )
            if slot.remaining == 0:
                self._finish(i, slot)

    def _build_window(self, mode: str):
        """The fused K-sub-step decode program for one sampling mode
        ("argmax" | "nosort" | "sort" — picked per window, same
        bit-identical trio SlotSampler.draw switches between). A
        `lax.scan` over the raw single-step body (decode_step_fn)
        advances every row; each sub-step pins inactive rows' position
        (the K=1 tick's exact rule, applied with the sub-step-START
        active mask), samples on device, counts the token against the
        row's budget, and freezes rows that hit eos or budget for the
        REST of the window. Fixed length K — no early exit — so the
        trace is stable regardless of where rows finish. Memoized on
        the decoder (utils/memo.cached_step), which also puts it where
        analysis/sanitizer.py auto-watches for retraces."""
        K = self.decode_window
        eos = self.eos_id
        dec = self.dec

        def build():
            raw = dec.decode_step_fn()

            def window(params, cache, feed, active, keys, temp,
                       topk, topp, minp, budget):
                def body(carry, _):
                    cache, feed, active, keys, n = carry
                    logits, cache = raw(params, cache, feed)
                    cache = {
                        **cache,
                        "pos": jnp.where(active, cache["pos"], 0),
                    }
                    ll = logits[:, -1, :]
                    if mode == "argmax":
                        nxt = jnp.argmax(ll, axis=-1)
                    elif mode == "nosort":
                        nxt, keys = sample_token_batched_nosort(
                            ll, keys, temp, minp
                        )
                    else:
                        nxt, keys = sample_token_batched(
                            ll, keys, temp, topk, topp, minp
                        )
                    n = n + active.astype(jnp.int32)
                    alive = active & (n < budget)
                    if eos is not None:
                        alive = alive & (nxt != eos)
                    feed = nxt[:, None].astype(jnp.int32)
                    return (cache, feed, alive, keys, n), nxt

                init = (
                    cache, feed, active, keys,
                    jnp.zeros_like(budget),
                )
                (cache, feed, alive, keys, n), toks = lax.scan(
                    body, init, None, length=K
                )
                return cache, feed, alive, keys, n, toks.T

            return jax.jit(window, donate_argnums=(1,))

        return cached_step(
            self.dec, ("flat_window", K, mode, eos), build
        )

    def _build_window_c(self, mode: str):
        """Constrained variant of the fused window program: same scan
        skeleton plus the per-sub-step DFA gather/mask-fold/advance
        (constrain/runtime.py). A SEPARATE memo key — the
        unconstrained program stays byte-identical to pre-constraint
        builds, and a constrained server only pays this trace while a
        constrained row is actually live (_tick_window dispatch).
        Extra outputs: final DFA states, a per-row "hit a dead end"
        flag (hand-built DFAs only; the forced-eos token is dropped on
        drain) and the [B, K] masked-fraction buffer for obs."""
        K = self.decode_window
        eos = self.eos_id
        dec = self.dec

        def build():
            raw = dec.decode_step_fn()

            def window(params, cache, feed, active, keys, temp,
                       topk, topp, minp, budget, cid, cstate,
                       ctrans, cacc):
                cvec = cid > 0

                def body(carry, _):
                    cache, feed, active, keys, n, cstate, died = carry
                    logits, cache = raw(params, cache, feed)
                    cache = {
                        **cache,
                        "pos": jnp.where(active, cache["pos"], 0),
                    }
                    ll = logits[:, -1, :]
                    crow, acc = crt.constrain_rows(
                        ctrans, cacc, cid, cstate
                    )
                    cmask = crt.constrain_mask(crow, acc, eos)
                    dead = cvec & active & ~cmask.any(-1)
                    ll = crt.fold_mask(ll, cmask)
                    if mode == "argmax":
                        nxt = jnp.argmax(ll, axis=-1)
                    elif mode == "nosort":
                        nxt, keys = sample_token_batched_nosort(
                            ll, keys, temp, minp
                        )
                    else:
                        nxt, keys = sample_token_batched(
                            ll, keys, temp, topk, topp, minp
                        )
                    nxt = jnp.where(dead, eos, nxt)
                    cstate = crt.advance_state(
                        crow, cstate, nxt, cvec & ~dead
                    )
                    frac = crt.masked_frac(cmask, cvec & active)
                    n = n + active.astype(jnp.int32)
                    alive = active & (n < budget) & (nxt != eos)
                    feed = nxt[:, None].astype(jnp.int32)
                    carry = (
                        cache, feed, alive, keys, n, cstate,
                        died | dead,
                    )
                    return carry, (nxt, frac)

                init = (
                    cache, feed, active, keys,
                    jnp.zeros_like(budget), cstate,
                    jnp.zeros_like(cvec),
                )
                (cache, feed, alive, keys, n, cstate, died), (
                    toks, fracs
                ) = lax.scan(body, init, None, length=K)
                return (
                    cache, feed, alive, keys, n, toks.T, cstate,
                    died, fracs.T,
                )

            return jax.jit(window, donate_argnums=(1,))

        return cached_step(
            self.dec, ("flat_window_c", K, mode, eos), build
        )

    def _tick_window(self) -> None:
        """One fused dispatch of up to decode_window tokens per active
        slot; ONE batched host transfer drains the [B, K] token buffer
        (plus tiny per-slot valid-length/alive vectors when eos is
        configured)."""
        active = [s.req is not None for s in self.slots]
        if not any(active):
            return
        K = self.decode_window
        sampling = any(
            s.req is not None and s.sampling for s in self.slots
        )
        if not sampling:
            mode = "argmax"
        elif any(self._sampler.row_sort):
            mode = "sort"
        else:
            mode = "nosort"
        budget = [
            s.remaining if s.req is not None else 0
            for s in self.slots
        ]
        sm = self._sampler
        constrained = any(sm.row_constrained)
        died = fracs = None
        if constrained:
            window = self._build_window_c(mode)
            (cache, feed, alive, keys, n_dev, toks, cstate, died,
             fracs) = window(
                self.params, self.cache, self._feed,
                jnp.asarray(active), sm.keys, sm.temp, sm.topk,
                sm.topp, sm.minp, jnp.asarray(budget, jnp.int32),
                sm.cid, sm.cstate, self._ctrans, self._cacc,
            )
            sm.cstate = cstate
        else:
            window = self._build_window(mode)
            cache, feed, alive, keys, n_dev, toks = window(
                self.params, self.cache, self._feed,
                jnp.asarray(active), sm.keys, sm.temp, sm.topk,
                sm.topp, sm.minp, jnp.asarray(budget, jnp.int32),
            )
        self.cache = cache
        self._feed = feed
        sm.keys = keys
        self.ticks += 1
        self.dispatches += 1
        n_live = sum(active)
        now = time.perf_counter()
        if self._last_tick_t is not None:
            self.obs.itl.observe(now - self._last_tick_t, n_live)
        self._last_tick_t = now
        self.obs.ticks.inc()
        self.obs.host_dispatches.inc()
        need_toks = self.on_token is not None or any(
            s.req is not None and s.stop is not None
            for s in self.slots
        )
        if self.eos_id is not None:
            # analysis: ignore[host-sync-in-hot-loop] one batched
            # per-WINDOW transfer of the valid-length/alive vectors
            # — K tokens amortize this sync, the point of the window
            emitted = np.asarray(n_dev).tolist()
            # analysis: ignore[host-sync-in-hot-loop] same per-window
            # sync point (ready with the vector above)
            alive_host = np.asarray(alive).tolist()
        else:
            # No eos: the device can only freeze rows on budget, which
            # the host already knows — no transfer needed.
            emitted = [min(b, K) for b in budget]
            alive_host = [b > K for b in budget]
        # analysis: ignore[host-sync-in-hot-loop] the ONE batched
        # [B, K] token transfer per window that replaces K per-tick
        # [B, 1] transfers — only when a stream/stop consumer exists
        toks_host = np.asarray(toks).tolist() if need_toks else None
        died_host = fracs_host = None
        if constrained:
            # analysis: ignore[host-sync-in-hot-loop] rides the same
            # per-window sync: batched dead-end flags + [B, K] mask
            # fractions, only while a constrained row is live
            died_host = np.asarray(died).tolist()
            # analysis: ignore[host-sync-in-hot-loop] same per-window
            # sync point (ready with the vector above)
            fracs_host = np.asarray(fracs)
        self._drain_window(toks, toks_host, emitted, alive_host,
                           budget, died_host, fracs_host)

    def _drain_window(
        self, toks, toks_host, emitted, alive_host, budget,
        died_host=None, fracs_host=None,
    ) -> None:
        """Host-side window drain, per-token-equivalent to the K=1
        tick loop: stop sequences truncate the window's overshoot
        (StopMatcher.push_window — discarded tokens never enter the
        match history), budgets and finishes mirror the per-token
        bookkeeping, and streaming callbacks fire in tick-major order
        (batching.window_drain_order) so consumers see the exact
        K=1 interleaving."""
        K = self.decode_window
        accepted = [0] * self.B
        finishing = [False] * self.B
        for i, slot in enumerate(self.slots):
            if slot.req is None:
                continue
            n_i = emitted[i]
            a_i = n_i
            stopped = False
            dead = bool(
                died_host is not None and died_host[i] and slot.cid
            )
            if dead:
                # Dead-end DFA state mid-window: the device froze the
                # row with a FORCED eos (counted in n_i) — drop it, so
                # the output ends at the last admissible token and the
                # failure surfaces as a per-request error, not a hang.
                a_i = n_i - 1
            if slot.stop is not None:
                hit = slot.stop.push_window(toks_host[i][:a_i])
                if hit is not None:
                    a_i, stopped = hit, True
            accepted[i] = a_i
            if a_i < min(budget[i], K):
                self.obs.window_truncated.inc()
            slot.remaining -= a_i
            if stopped or not alive_host[i]:
                # eos froze the row on device, a stop sequence cut it
                # on drain, or its budget ran out mid-window.
                slot.remaining = 0
            if dead:
                slot.remaining = 0
                self.errors[slot.req] = (
                    "constraint dead end: DFA state admits no token "
                    "and is not accepting"
                )
                self.constraint_dead_ends_n += 1
                self.obs.constrain_dead_ends.inc()
            if slot.cid and fracs_host is not None:
                self.constrained_tokens_n += a_i
                if a_i:
                    self.obs.constrained_tokens.inc(a_i)
                for fr in fracs_host[i][:a_i].tolist():
                    self.obs.constrain_masked_frac.observe(fr)
            tok_block = toks[i, :a_i][None, :].astype(
                slot.last.dtype
            )
            slot.toks.append(tok_block)
            slot.last = tok_block[:, -1:]
            finishing[i] = slot.remaining == 0
            self.obs.tokens_generated.inc(a_i)
            self.window_tokens += a_i
        self.obs.tokens_per_dispatch.set(float(sum(accepted)))
        if self.on_token is not None:
            for t, i in window_drain_order(accepted, K):
                slot = self.slots[i]
                self.on_token(
                    slot.req,
                    toks_host[i][t],
                    finishing[i] and t == accepted[i] - 1,
                )
        for i, slot in enumerate(self.slots):
            if finishing[i]:
                self._finish(i, slot)

    def _finish(self, i: int, slot: _Slot) -> None:
        self.obs.requests_finished.inc()
        self.done[slot.req] = jnp.concatenate(slot.toks, axis=1)
        slot.req = None
        slot.toks = None
        slot.last = None
        slot.sampling = False
        slot.stop = None
        slot.cid = 0
        # Release the slot's sampling policy row NOW, not at reuse —
        # a lingering row_sort would drag every later tick through
        # the sorting sampler (SlotSampler.release).
        self._sampler.release(i)


def serve_greedy(
    dec: Any,
    params: dict,
    requests: list[tuple[jax.Array, int]],
    *,
    max_batch: int = 4,
    prefix_ids: jax.Array | None = None,
    eos_id: int | None = None,
    sampling: list | None = None,
    decode_window: int = 1,
    constraints: dict | None = None,
) -> tuple[list[jax.Array], dict]:
    """One-shot convenience: serve `[(prompt, steps), ...]`, returning
    outputs in submission order plus stats (`ticks` batched decode
    steps taken vs `solo_steps` a per-request loop would take; with a
    shared prefix, `saved_prefill_tokens` counts the K/V rows each
    admission reused instead of recomputing). Stats is an
    obs.ServerStats: the same dict plus attribute access and the
    process metrics snapshot under `stats.metrics`. With `prefix_ids`, each
    prompt is the per-request SUFFIX and outputs cover suffix +
    generation (the prefix ids are not repeated in the result).

    `decode_window=K` fuses K decode sub-steps into one host dispatch
    (DecodeServer docstring has the semantics); outputs stay
    token-identical to the default K=1. Stats then also carry
    `decode_window`, `host_dispatches` (decode dispatches issued) and
    `tokens_per_dispatch` (mean tokens accepted per dispatch — the
    dispatch-amortization win, approaching K * active slots).

    `constraints={name: TokenDFA}` registers grammar constraints
    (defer_tpu/constrain/) a request selects via
    SamplingParams(constraint=name)."""
    srv = DecodeServer(
        dec, params, max_batch=max_batch, prefix_ids=prefix_ids,
        eos_id=eos_id, decode_window=decode_window,
        constraints=constraints,
    )
    samps = sampling or [None] * len(requests)
    if len(samps) != len(requests):
        raise ValueError(
            f"sampling has {len(samps)} entries for "
            f"{len(requests)} requests"
        )
    rids = [
        srv.submit(p, s, sampling=sp)
        for (p, s), sp in zip(requests, samps)
    ]
    done = srv.run()
    stats = ServerStats.snapshot(
        srv.obs.registry,
        ticks=srv.ticks,
        solo_steps=srv.solo_steps,
        saved_prefill_tokens=srv.prefix_len * len(requests),
        decode_window=srv.decode_window,
        host_dispatches=srv.dispatches,
        tokens_per_dispatch=(
            srv.window_tokens / srv.dispatches if srv.dispatches else 0.0
        ),
        constrained_tokens=srv.constrained_tokens_n,
        constraint_dead_ends=srv.constraint_dead_ends_n,
    )
    return [done[r] for r in rids], stats
