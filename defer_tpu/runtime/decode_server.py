"""Continuous-batching decode server: admit requests into batch slots
mid-flight.

A plain batched `generate` convoys requests: the batch finishes when
its LAST member does, and new arrivals wait for the whole batch. Here
the decode batch is a set of SLOTS, each at its own depth — the cache
write head is a (B,) position VECTOR (models/gpt.py `per_slot`), so
one jitted (B, 1) step advances every active request regardless of
age, and a finished slot is immediately re-admitted with the next
queued request:

  * admission = single-request prefill (prompt padded to a pow2
    bucket, so the compiled-shape set stays tiny) whose K/V rows are
    inserted into the slot's lane of the big cache; stale rows past
    the slot's position are never attended (position masking) and are
    overwritten as the slot advances;
  * every decode tick is ONE weight read shared by all active slots —
    exactly the batching economics decode wants (weights dominate,
    models/gpt.py), now without convoy latency;
  * shapes are static everywhere: max_batch slots, bucketed prefill,
    (B, 1) ticks; inactive slots decode a dummy token into row 0 and
    their position is pinned back to 0 after each tick.

Greedy only, and each request's output is BIT-IDENTICAL to a solo
`dec.generate` of that request — the correctness contract the tests
pin. The reference's serving story is a fixed stream of identical
CNN frames (reference src/test.py:30-41); this is the autoregressive
counterpart, composing with runtime/batching.py's request coalescing.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class _Slot:
    req: int | None = None
    remaining: int = 0
    last: Any = None  # next token to feed, [1, 1]
    toks: list | None = None


class DecodeServer:
    """Greedy continuous-batching decoder over `max_batch` slots."""

    def __init__(
        self,
        dec: Any,
        params: dict,
        *,
        max_batch: int = 4,
    ):
        self.dec = dec
        self.params = params
        self.B = max_batch
        self.step = dec.make_step()  # batched ticks (donating)
        cache = dec.init_cache(max_batch)
        cache["pos"] = jnp.zeros((max_batch,), jnp.int32)
        self.cache = cache
        self.slots = [_Slot() for _ in range(max_batch)]
        self.pending: list[tuple[int, jax.Array, int]] = []
        self.done: dict[int, jax.Array] = {}
        self._next_id = 0
        self.ticks = 0
        self.solo_steps = 0  # what per-request loops would have cost

    # -- public API -------------------------------------------------------

    def submit(self, prompt_ids: jax.Array, num_steps: int) -> int:
        """Queue a request; returns its id (resolved in .done)."""
        if prompt_ids.shape[0] != 1:
            raise ValueError("submit one request at a time ([1, T])")
        t0 = prompt_ids.shape[1]
        if t0 < 1:
            raise ValueError("prompt must have at least one token")
        if num_steps < 1:
            raise ValueError(
                f"num_steps={num_steps}: need at least one generated "
                "token (a non-positive count would never complete)"
            )
        if t0 + num_steps > self.dec.cfg.max_len:
            raise ValueError(
                f"prompt {t0} + steps {num_steps} exceeds max_len "
                f"{self.dec.cfg.max_len}"
            )
        rid = self._next_id
        self._next_id += 1
        self.pending.append((rid, prompt_ids, num_steps))
        self.solo_steps += num_steps
        return rid

    def run(self) -> dict[int, jax.Array]:
        """Serve until every submitted request completes; returns
        {request_id: ids [1, T0 + num_steps]}."""
        while self.pending or any(s.req is not None for s in self.slots):
            self._admit()
            self._tick()
        return self.done

    # -- internals --------------------------------------------------------

    def _admit(self) -> None:
        for i, slot in enumerate(self.slots):
            if slot.req is not None or not self.pending:
                continue
            rid, prompt, steps = self.pending.pop(0)
            t0 = prompt.shape[1]
            # Bucketed prefill keeps the compiled-shape set small.
            pad = 1 << (t0 - 1).bit_length()
            pad = min(pad, self.dec.cfg.max_len)
            padded = jnp.concatenate(
                [prompt, jnp.zeros((1, pad - t0), prompt.dtype)], axis=1
            )
            small = self.dec.init_cache(1)
            logits, small = self.step(self.params, small, padded)
            # Insert the lane: K/V rows land in slot i; rows past t0
            # are stale but position-masked until overwritten.
            self.cache = {
                "k": jax.lax.dynamic_update_slice(
                    self.cache["k"], small["k"], (0, i, 0, 0, 0)
                ),
                "v": jax.lax.dynamic_update_slice(
                    self.cache["v"], small["v"], (0, i, 0, 0, 0)
                ),
                "pos": self.cache["pos"].at[i].set(t0),
            }
            first = jnp.argmax(logits[:, t0 - 1, :], axis=-1)[
                :, None
            ].astype(prompt.dtype)
            slot.req = rid
            slot.remaining = steps - 1
            slot.last = first
            slot.toks = [prompt, first]
            if slot.remaining == 0:
                self._finish(slot)

    def _tick(self) -> None:
        active = [s.req is not None for s in self.slots]
        if not any(active):
            return
        feed = jnp.concatenate(
            [
                s.last
                if s.req is not None
                else jnp.zeros((1, 1), jnp.int32)
                for s in self.slots
            ],
            axis=0,
        )
        logits, cache = self.step(self.params, self.cache, feed)
        self.ticks += 1
        # Inactive slots wrote a dummy row at their position; pin them
        # back to 0 so they never creep toward max_len.
        mask = jnp.asarray(active)
        cache = {**cache, "pos": jnp.where(mask, cache["pos"], 0)}
        self.cache = cache
        nxt = jnp.argmax(logits[:, -1, :], axis=-1)  # (B,)
        for i, slot in enumerate(self.slots):
            if slot.req is None:
                continue
            tok = nxt[i][None, None].astype(slot.last.dtype)
            slot.last = tok
            slot.toks.append(tok)
            slot.remaining -= 1
            if slot.remaining == 0:
                self._finish(slot)

    def _finish(self, slot: _Slot) -> None:
        self.done[slot.req] = jnp.concatenate(slot.toks, axis=1)
        slot.req = None
        slot.toks = None
        slot.last = None


def serve_greedy(
    dec: Any,
    params: dict,
    requests: list[tuple[jax.Array, int]],
    *,
    max_batch: int = 4,
) -> tuple[list[jax.Array], dict]:
    """One-shot convenience: serve `[(prompt, steps), ...]`, returning
    outputs in submission order plus stats (`ticks` batched decode
    steps taken vs `solo_steps` a per-request loop would take)."""
    srv = DecodeServer(dec, params, max_batch=max_batch)
    rids = [srv.submit(p, s) for p, s in requests]
    done = srv.run()
    stats = {"ticks": srv.ticks, "solo_steps": srv.solo_steps}
    return [done[r] for r in rids], stats
