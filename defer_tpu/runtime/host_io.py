"""Host-side input feed and result drain.

The reference's equivalents: `_startDistEdgeInference` pulls from the
input queue, compresses, and sockets to node 0 (reference
src/dispatcher.py:93-103); `_result_server` accepts the last node's
connection and pushes decompressed results to the output queue
(src/dispatcher.py:105-118). Here both ends are queue adapters around
the async pipeline stream — `device_put` to stage 0's core replaces the
socket send, fetching the output array replaces the result server.
"""

from __future__ import annotations

import threading
import time

# End-of-stream sentinel a producer can put on the input queue (a None
# works too). The reference's feed loop blocks forever on `input_q.get()`
# (reference src/dispatcher.py:100) with no shutdown path at all.
STOP = object()


class ProgressMonitor:
    """Deadlock watchdog for the streaming loop.

    The reference hangs forever if a node dies mid-stream (single
    accepted peer, no timeout on the data path — reference
    src/node.py:102-103). Here: if no microbatch completes within
    `timeout_s` while work is outstanding, `check()` raises.
    """

    def __init__(self, timeout_s: float):
        self.timeout_s = timeout_s
        self._last_progress = time.monotonic()
        self._outstanding = 0
        self._lock = threading.Lock()

    def submitted(self) -> None:
        with self._lock:
            if self._outstanding == 0:
                # Idle time (or first-compile time) before this submission
                # must not count against the watchdog.
                self._last_progress = time.monotonic()
            self._outstanding += 1

    def completed(self) -> None:
        with self._lock:
            self._outstanding -= 1
            self._last_progress = time.monotonic()

    def dropped(self, n: int) -> None:
        """Credit n submitted microbatches that were abandoned (elastic
        re-dispatch discards in-flight work) so the watchdog does not
        hold the recovered loop accountable for them forever."""
        with self._lock:
            self._outstanding -= n
            self._last_progress = time.monotonic()

    def check(self) -> None:
        with self._lock:
            stalled = (
                self._outstanding > 0
                and time.monotonic() - self._last_progress > self.timeout_s
            )
        if stalled:
            raise TimeoutError(
                f"pipeline made no progress for {self.timeout_s:.0f}s with "
                f"{self._outstanding} microbatch(es) outstanding — a stage "
                "or transfer is stuck"
            )
