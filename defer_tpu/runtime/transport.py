"""Host-to-host activation transport for the DCN / cross-slice path.

Inside one slice, stage-to-stage traffic rides ICI via device transfers
(defer_tpu/parallel/pipeline.py) or XLA collectives — no host code. But
a pipeline spanning *slices* (or heterogeneous hosts, the reference's
whole deployment model) needs a host relay. This module is that seam,
rebuilt from the reference's hand-rolled socket layer (reference
src/node_state.py:43-101: 8-byte big-endian length framing, 512 KB
chunks, select() on EAGAIN) with the parts that were wrong or missing
fixed:

  * framing: length-prefixed, but over a blocking socket with
    sendall/recv_into — the reference's non-blocking + select loop
    burns CPU for no benefit on a dedicated relay thread;
  * payloads: arrays go through the native byteshuffle+zstd codec
    (defer_tpu/runtime/codec.py) exactly where the reference ran
    ZFP+LZ4 (reference src/dispatcher.py:89-92), toggleable per link
    since DCN is fast enough that compression can lose;
  * shutdown: explicit STOP frame and timeouts — the reference hangs
    forever when a peer dies (reference src/node.py:102-103).

Wire format per frame: 1-byte tag ('A' array / 'S' stop), 8-byte
big-endian payload length, payload bytes (a codec frame for arrays).
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Iterator

import numpy as np

from defer_tpu.obs.metrics import get_registry
from defer_tpu.runtime import codec
from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)

_reg = get_registry()
_obs_tx_bytes = _reg.counter(
    "defer_transport_tx_bytes_total", "Frame bytes written to the wire"
)
_obs_tx_frames = _reg.counter(
    "defer_transport_tx_frames_total", "Array frames sent"
)
_obs_rx_bytes = _reg.counter(
    "defer_transport_rx_bytes_total", "Frame bytes read off the wire"
)
_obs_rx_frames = _reg.counter(
    "defer_transport_rx_frames_total", "Array frames received"
)
_obs_retries = _reg.counter(
    "defer_transport_connect_retries_total",
    "Failed connect attempts that were retried",
)
_obs_timeouts = _reg.counter(
    "defer_transport_timeouts_total",
    "Accept/connect timeouts surfaced as TransportError",
)

_TAG_ARRAY = b"A"
_TAG_STOP = b"S"
_HEADER = struct.Struct(">cQ")


class TransportError(ConnectionError):
    pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            r = sock.recv_into(view[got:], n - got)
        except socket.timeout:
            # A configured read timeout (ArrayReceiver read_timeout_s)
            # turns a dead/stalled peer into a typed error the caller
            # can retry around, instead of a forever-blocked recv.
            _obs_timeouts.inc()
            raise TransportError(
                f"read timed out mid-frame ({got}/{n} bytes)"
            ) from None
        if r == 0:
            raise TransportError("peer closed mid-frame")
        got += r
    return bytes(buf)


class ArraySender:
    """Client side: connect to a peer relay and stream arrays.

    The analogue of the reference's `_data_client` (reference
    src/node.py:113-133), minus the polling sleep loop.
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        compress: bool = True,
        level: int = 3,
        quantize: str | None = None,
        connect_timeout_s: float = 30.0,
        retries: int = 10,
        backoff_base_s: float = 0.1,
        backoff_cap_s: float = 2.0,
    ):
        """`retries` failed connect attempts are spaced by exponential
        backoff: backoff_base_s * 2**attempt, capped at backoff_cap_s.
        With the defaults a peer that is merely slow to bind (cold
        Python+JAX start) is absorbed as a bounded queue-wait; a peer
        that never appears surfaces as TransportError after
        ~retries * backoff_cap_s seconds instead of hanging."""
        self.compress = compress
        self.level = level
        # Lossy int8 quantize-for-transfer (codec.SCHEME_Q8) — the DCN
        # analogue of the reference's ZFP fixed-precision mode; only
        # floating payloads are quantized, others pass through.
        if quantize not in (None, "int8"):
            # Fail at construction, not on the first float send
            # mid-stream.
            raise ValueError(f"unknown quantize mode {quantize!r}")
        self.quantize = quantize
        if backoff_base_s < 0 or backoff_cap_s < backoff_base_s:
            raise ValueError(
                f"need 0 <= backoff_base_s <= backoff_cap_s, got "
                f"{backoff_base_s}/{backoff_cap_s}"
            )
        last: Exception | None = None
        for attempt in range(retries):
            try:
                self._sock = socket.create_connection(
                    (host, port), timeout=connect_timeout_s
                )
                break
            except OSError as e:
                last = e
                _obs_retries.inc()
                threading.Event().wait(
                    min(backoff_base_s * 2**attempt, backoff_cap_s)
                )
        else:
            _obs_timeouts.inc()
            raise TransportError(
                f"could not connect to {host}:{port}: {last}"
            )
        self._sock.settimeout(None)
        self._lock = threading.Lock()

    def send(self, arr: np.ndarray) -> int:
        """Frame and write one array; returns the frame's wire bytes
        (header + codec payload) so callers can account per-stream
        traffic (e.g. disagg/wire.py's KV-block byte counters) on top
        of the process-global transport counters."""
        # level=0 is the codec's raw-passthrough scheme.
        # analysis: ignore[host-sync-in-hot-loop] framing the payload
        # for the wire IS a host copy by design; reached from the pp
        # transport stage boundary, which documents the sync it pays
        a = np.asarray(arr)
        quant = (
            self.quantize
            if self.quantize and np.issubdtype(a.dtype, np.floating)
            else None
        )
        level = self.level if self.compress else 0
        try:
            frame = codec.encode(a, level=level, quantize=quant)
        except ValueError:
            if quant is None:
                raise
            # Non-finite values can't be quantized (codec refuses
            # rather than silently corrupting); one bad tensor must
            # not tear down the whole stream — ship it losslessly.
            log.warning(
                "tensor contains NaN/Inf; sending losslessly instead of "
                "quantized"
            )
            frame = codec.encode(a, level=level)
        with self._lock:
            # analysis: ignore[lock-discipline] serializing whole
            # frames onto one socket is this lock's entire job;
            # concurrent senders must queue behind the write
            self._sock.sendall(_HEADER.pack(_TAG_ARRAY, len(frame)) + frame)
        _obs_tx_frames.inc()
        nbytes = _HEADER.size + len(frame)
        _obs_tx_bytes.inc(nbytes)
        return nbytes

    def close(self) -> None:
        """Send the STOP frame (the graceful shutdown the reference
        lacks) and close."""
        try:
            with self._lock:
                # analysis: ignore[lock-discipline] the STOP frame must
                # not interleave mid-frame with a concurrent send
                self._sock.sendall(_HEADER.pack(_TAG_STOP, 0))
            self._sock.close()
        except OSError:
            pass


class ArrayReceiver:
    """Server side: accept one peer and iterate received arrays.

    The analogue of the reference's `_data_server` (reference
    src/node.py:97-111). `accept_timeout_s` bounds the wait for the
    peer; the reference blocks forever (reference src/node.py:103).
    """

    def __init__(
        self,
        port: int,
        *,
        host: str = "0.0.0.0",
        accept_timeout_s: float = 120.0,
        read_timeout_s: float | None = None,
    ):
        """`read_timeout_s` bounds every in-stream recv on the accepted
        connection: a peer that connects and then stalls (or dies
        without a FIN reaching us) surfaces as a TransportError after
        this many silent seconds instead of blocking forever. None
        keeps the historical block-forever behavior for links where
        arbitrarily long gaps between frames are legitimate."""
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(1)
        self._server.settimeout(accept_timeout_s)
        self.port = self._server.getsockname()[1]
        self.read_timeout_s = read_timeout_s
        self._conn: socket.socket | None = None
        # Cumulative wire bytes read off accepted connections —
        # per-stream accounting for callers that need more than the
        # process-global counters (survives next_peer handoffs).
        self.rx_frame_bytes = 0

    def _accept(self) -> socket.socket:
        if self._conn is None:
            try:
                self._conn, peer = self._server.accept()
            except socket.timeout:
                _obs_timeouts.inc()
                raise TransportError(
                    "no peer connected within the accept timeout"
                ) from None
            self._conn.settimeout(self.read_timeout_s)
            log.info("transport: accepted peer %s", peer)
        return self._conn

    def __iter__(self) -> Iterator[np.ndarray]:
        conn = self._accept()
        while True:
            tag, length = _HEADER.unpack(_recv_exact(conn, _HEADER.size))
            if tag == _TAG_STOP:
                return
            if tag != _TAG_ARRAY:
                raise TransportError(f"unknown frame tag {tag!r}")
            payload = _recv_exact(conn, length)
            _obs_rx_frames.inc()
            _obs_rx_bytes.inc(_HEADER.size + length)
            self.rx_frame_bytes += _HEADER.size + length
            yield codec.decode(payload)

    def next_peer(self) -> None:
        """Drop the current peer and accept a fresh one on the same
        listening socket — session handoff for multi-role streams (a
        remote stage worker takes its DISPATCH stream from the
        dispatcher, then its ACTIVATION stream from the previous chain
        hop; the reference used separate ports per role, reference
        src/node.py:18)."""
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def close(self) -> None:
        for s in (self._conn, self._server):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
