"""Keras `model.to_json()` ingester -> IR Graph.

The reference's wire format for model architectures IS Keras JSON: the
dispatcher ships `model.to_json()` strings (reference
src/dispatcher.py:52) and nodes rebuild with `model_from_json`
(reference src/node.py:38). This module is the compatibility path for
that ecosystem: a user bringing a serialized Keras model (plus an h5
weights file via `transplant.load_keras_h5`) gets an IR Graph that
partitions/pipelines like any zoo model.

Supports both JSON dialects: the classic functional layout
(`config.layers` with `inbound_nodes` as
`[[[layer, node_idx, tensor_idx, kwargs]...]]`) that TF1-era Keras —
the reference's environment (reference src/node.py:19-20) — emits, and
the Keras 3 layout (`inbound_nodes` as `[{"args": ..., "kwargs": ...}]`
with `__keras_tensor__`/`keras_history` entries, `batch_shape` inputs,
flat single-io `input_layers`) that current `tf.keras` emits.
Restricted to single-input single-output graphs (the same restriction
the reference's partitioner has, reference src/dag_util.py:29-33).

Layers with fused activations (e.g. Conv2D(activation='relu')) expand
to two IR nodes; the activation node is named `<layer>_activation_fused`
and downstream edges re-point to it, while the parameterized node keeps
the layer name so `KerasWeights`' identity name_map finds its arrays.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Mapping

from defer_tpu.graph.ir import Graph, GraphBuilder


class KerasImportError(ValueError):
    pass


def _pad_attr(cfg: Mapping[str, Any]) -> str:
    return str(cfg.get("padding", "valid")).upper()


_ACTIVATIONS = {
    "relu": "relu",
    "relu6": "relu6",
    "sigmoid": "sigmoid",
    "tanh": "tanh",
    "swish": "swish",
    "silu": "swish",
    "gelu": "gelu",
    "softmax": "softmax",
    "linear": None,
}


def _activation_op(name: str) -> str | None:
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise KerasImportError(
            f"unsupported Keras activation {name!r}; supported: "
            f"{sorted(k for k in _ACTIVATIONS)}"
        ) from None


# Each handler: (builder, name, config, inputs) -> output node name.
_HANDLERS: dict[str, Callable] = {}


def _handler(*class_names: str) -> Callable:
    def deco(fn: Callable) -> Callable:
        for cn in class_names:
            _HANDLERS[cn] = fn
        return fn

    return deco


def _fused_activation(b: GraphBuilder, x: str, name: str, cfg) -> str:
    act = cfg.get("activation")
    if act in (None, "linear"):
        return x
    op = _activation_op(act)
    return b.add(op, x, name=f"{name}_activation_fused")


@_handler("Conv2D")
def _conv(b: GraphBuilder, name: str, cfg, inputs):
    x = b.add(
        "conv",
        inputs[0],
        name=name,
        features=int(cfg["filters"]),
        kernel_size=tuple(cfg["kernel_size"]),
        strides=tuple(cfg.get("strides", (1, 1))),
        padding=_pad_attr(cfg),
        dilation=tuple(cfg.get("dilation_rate", (1, 1))),
        groups=int(cfg.get("groups", 1)),
        use_bias=bool(cfg.get("use_bias", True)),
    )
    return _fused_activation(b, x, name, cfg)


@_handler("DepthwiseConv2D")
def _depthwise(b: GraphBuilder, name: str, cfg, inputs):
    x = b.add(
        "depthwise_conv",
        inputs[0],
        name=name,
        kernel_size=tuple(cfg["kernel_size"]),
        strides=tuple(cfg.get("strides", (1, 1))),
        padding=_pad_attr(cfg),
        dilation=tuple(cfg.get("dilation_rate", (1, 1))),
        depth_multiplier=int(cfg.get("depth_multiplier", 1)),
        use_bias=bool(cfg.get("use_bias", True)),
    )
    return _fused_activation(b, x, name, cfg)


@_handler("SeparableConv2D")
def _separable(b: GraphBuilder, name: str, cfg, inputs):
    x = b.add(
        "separable_conv",
        inputs[0],
        name=name,
        features=int(cfg["filters"]),
        kernel_size=tuple(cfg["kernel_size"]),
        strides=tuple(cfg.get("strides", (1, 1))),
        padding=_pad_attr(cfg),
        dilation=tuple(cfg.get("dilation_rate", (1, 1))),
        depth_multiplier=int(cfg.get("depth_multiplier", 1)),
        use_bias=bool(cfg.get("use_bias", True)),
    )
    return _fused_activation(b, x, name, cfg)


@_handler("Dense")
def _dense(b: GraphBuilder, name: str, cfg, inputs):
    x = b.add(
        "dense",
        inputs[0],
        name=name,
        features=int(cfg["units"]),
        use_bias=bool(cfg.get("use_bias", True)),
    )
    return _fused_activation(b, x, name, cfg)


@_handler("BatchNormalization")
def _bn(b: GraphBuilder, name: str, cfg, inputs):
    axis = cfg.get("axis", -1)
    if isinstance(axis, (list, tuple)):
        axis = axis[0]
    if axis not in (-1, 3):
        raise KerasImportError(
            f"BatchNormalization {name!r}: only channels-last (axis=-1/3) "
            f"is supported, got axis={axis}"
        )
    return b.add(
        "batch_norm", inputs[0], name=name, eps=float(cfg.get("epsilon", 1e-3))
    )


@_handler("Activation")
def _activation(b: GraphBuilder, name: str, cfg, inputs):
    op = _activation_op(cfg["activation"])
    if op is None:
        return b.add("identity", inputs[0], name=name)
    return b.add(op, inputs[0], name=name)


@_handler("ReLU")
def _relu_layer(b: GraphBuilder, name: str, cfg, inputs):
    slope = float(cfg.get("negative_slope") or 0.0)
    threshold = float(cfg.get("threshold") or 0.0)
    if slope != 0.0 or threshold != 0.0:
        raise KerasImportError(
            f"ReLU {name!r}: negative_slope/threshold variants are not "
            f"supported (got slope={slope}, threshold={threshold})"
        )
    mv = cfg.get("max_value")
    if mv is not None and float(mv) == 6.0:
        return b.add("relu6", inputs[0], name=name)
    if mv is not None:
        raise KerasImportError(f"ReLU {name!r}: unsupported max_value {mv}")
    return b.add("relu", inputs[0], name=name)


@_handler("Softmax")
def _softmax_layer(b: GraphBuilder, name: str, cfg, inputs):
    return b.add("softmax", inputs[0], name=name, axis=int(cfg.get("axis", -1)))


@_handler("MaxPooling2D")
def _max_pool(b: GraphBuilder, name: str, cfg, inputs):
    return b.add(
        "max_pool",
        inputs[0],
        name=name,
        window=tuple(cfg.get("pool_size", (2, 2))),
        strides=tuple(cfg.get("strides") or cfg.get("pool_size", (2, 2))),
        padding=_pad_attr(cfg),
    )


@_handler("AveragePooling2D")
def _avg_pool(b: GraphBuilder, name: str, cfg, inputs):
    return b.add(
        "avg_pool",
        inputs[0],
        name=name,
        window=tuple(cfg.get("pool_size", (2, 2))),
        strides=tuple(cfg.get("strides") or cfg.get("pool_size", (2, 2))),
        padding=_pad_attr(cfg),
    )


@_handler("GlobalAveragePooling2D")
def _gap(b: GraphBuilder, name: str, cfg, inputs):
    return b.add(
        "global_avg_pool", inputs[0], name=name,
        keepdims=bool(cfg.get("keepdims", False)),
    )


@_handler("GlobalMaxPooling2D")
def _gmp(b: GraphBuilder, name: str, cfg, inputs):
    return b.add(
        "global_max_pool", inputs[0], name=name,
        keepdims=bool(cfg.get("keepdims", False)),
    )


@_handler("ZeroPadding2D")
def _zero_pad(b: GraphBuilder, name: str, cfg, inputs):
    pad = cfg["padding"]
    if isinstance(pad, int):
        pad = ((pad, pad), (pad, pad))
    else:
        pad = tuple(
            (p, p) if isinstance(p, int) else tuple(p) for p in pad
        )
    return b.add("zero_pad", inputs[0], name=name, padding=pad)


@_handler("Cropping2D")
def _crop(b: GraphBuilder, name: str, cfg, inputs):
    crop = cfg["cropping"]
    if isinstance(crop, int):
        crop = ((crop, crop), (crop, crop))
    else:
        crop = tuple(
            (c, c) if isinstance(c, int) else tuple(c) for c in crop
        )
    return b.add("crop", inputs[0], name=name, cropping=crop)


@_handler("Flatten")
def _flatten(b: GraphBuilder, name: str, cfg, inputs):
    return b.add("flatten", inputs[0], name=name)


@_handler("Reshape")
def _reshape(b: GraphBuilder, name: str, cfg, inputs):
    return b.add(
        "reshape", inputs[0], name=name, shape=tuple(cfg["target_shape"])
    )


@_handler("Dropout", "SpatialDropout2D", "GaussianDropout")
def _dropout(b: GraphBuilder, name: str, cfg, inputs):
    return b.add("dropout", inputs[0], name=name)


@_handler("Rescaling")
def _rescaling(b: GraphBuilder, name: str, cfg, inputs):
    return b.add(
        "rescale",
        inputs[0],
        name=name,
        scale=float(cfg.get("scale", 1.0)),
        offset=float(cfg.get("offset", 0.0)),
    )


@_handler("Normalization")
def _normalization(b: GraphBuilder, name: str, cfg, inputs):
    axis = cfg.get("axis", -1)
    if isinstance(axis, (list, tuple)):
        axis = axis[0] if len(axis) == 1 else axis
    if axis not in (-1, 3):
        raise KerasImportError(
            f"Normalization {name!r}: only channels-last (axis=-1/3) is "
            f"supported, got axis={axis}"
        )
    if cfg.get("invert"):
        raise KerasImportError(
            f"Normalization {name!r}: invert=True is not supported"
        )
    attrs = {}
    if cfg.get("mean") is not None:
        attrs = {"mean": cfg["mean"], "variance": cfg["variance"]}
    return b.add("normalization", inputs[0], name=name, **attrs)


@_handler("Add")
def _add(b: GraphBuilder, name: str, cfg, inputs):
    return b.add("add", *inputs, name=name)


@_handler("CustomScaleLayer")
def _custom_scale(b: GraphBuilder, name: str, cfg, inputs):
    """Keras applications' InceptionResNetV2 residual scaling:
    inputs[0] + inputs[1] * scale."""
    if len(inputs) != 2:
        raise KerasImportError(
            f"CustomScaleLayer {name!r} expects 2 inputs, got {len(inputs)}"
        )
    scaled = b.add(
        "scale",
        inputs[1],
        name=f"{name}_scaled",
        value=float(cfg.get("scale", 1.0)),
    )
    return b.add("add", inputs[0], scaled, name=name)


@_handler("Multiply")
def _multiply(b: GraphBuilder, name: str, cfg, inputs):
    return b.add("multiply", *inputs, name=name)


@_handler("Concatenate")
def _concat(b: GraphBuilder, name: str, cfg, inputs):
    return b.add("concat", *inputs, name=name, axis=int(cfg.get("axis", -1)))


def supported_layers() -> list[str]:
    return sorted(_HANDLERS)


def _check_history(name: str, node_idx: int, tensor_idx: int) -> str:
    if node_idx != 0 or tensor_idx != 0:
        raise KerasImportError(
            f"non-trivial inbound node ({name}, {node_idx}, "
            f"{tensor_idx}) is not supported"
        )
    return name


def _collect_keras3_tensors(obj: Any, names: list[str]) -> None:
    """Depth-first collect `__keras_tensor__` histories from a Keras 3
    node-args structure (tensors may be nested in lists, e.g. Add/
    Concatenate take a list of tensors as one positional arg)."""
    if isinstance(obj, Mapping):
        if obj.get("class_name") == "__keras_tensor__":
            hist = obj.get("config", {}).get("keras_history")
            if not hist or len(hist) < 3:
                raise KerasImportError(
                    f"__keras_tensor__ lacks keras_history: {obj!r}"
                )
            names.append(_check_history(hist[0], hist[1], hist[2]))
        else:
            for v in obj.values():
                _collect_keras3_tensors(v, names)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _collect_keras3_tensors(v, names)


def _inbound_names(inbound_nodes: Any) -> list[str]:
    """Extract producer layer names from inbound_nodes JSON.

    Two dialects: classic TF1-era
    `[[[layer_name, node_index, tensor_index, kwargs], ...]]` (the
    reference's environment, reference src/node.py:19-20) and Keras 3
    `[{"args": [...], "kwargs": {...}}]` where tensors appear as
    `__keras_tensor__` dicts carrying `keras_history`. One inbound node
    only — shared layers called multiple times are out of scope, as in
    the reference."""
    if not inbound_nodes:
        return []
    if len(inbound_nodes) != 1:
        raise KerasImportError(
            "shared layers (multiple inbound nodes) are not supported"
        )
    node = inbound_nodes[0]
    names: list[str] = []
    if isinstance(node, Mapping):  # Keras 3 dialect
        _collect_keras3_tensors(node.get("args", []), names)
        if not names:
            raise KerasImportError(
                f"Keras 3 inbound node has no tensor args: {node!r}"
            )
        return names
    for entry in node:
        names.append(_check_history(entry[0], entry[1], entry[2]))
    return names


def _io_layer_name(specs: Any, which: str) -> str:
    """Single input/output layer name from `input_layers` /
    `output_layers`, accepting classic `[["name", 0, 0]]` and Keras 3
    flat `["name", 0, 0]` forms."""
    if not isinstance(specs, (list, tuple)) or not specs:
        raise KerasImportError(f"model JSON lacks {which}")
    if isinstance(specs[0], str):  # Keras 3 single-io flat form
        entry = specs
    elif len(specs) != 1:
        raise KerasImportError(
            "only single-input single-output models are supported (the "
            "reference has the same restriction)"
        )
    else:
        entry = specs[0]
    if not isinstance(entry, (list, tuple)) or len(entry) < 3:
        raise KerasImportError(f"malformed {which} entry: {entry!r}")
    return _check_history(entry[0], entry[1], entry[2])


def _sequential_to_functional(spec: Mapping[str, Any]) -> dict:
    """Rewrite a Sequential model JSON as the functional layout: each
    layer's inbound node is simply the previous layer."""
    cfg = spec.get("config")
    layers = cfg.get("layers") if isinstance(cfg, Mapping) else cfg
    if not isinstance(layers, (list, tuple)):
        raise KerasImportError(
            "Sequential JSON has no config.layers list; expected the "
            "functional layout or a Sequential config with layers, got "
            f"config={cfg!r}"
        )
    out_layers = []
    prev: str | None = None
    for layer in layers:
        if not isinstance(layer, Mapping) or "class_name" not in layer:
            raise KerasImportError(
                f"malformed Sequential layer entry (need a mapping with "
                f"class_name/config): {layer!r}"
            )
        layer = dict(layer)
        layer_cfg = layer.get("config")
        if not isinstance(layer_cfg, Mapping):
            raise KerasImportError(
                f"Sequential layer {layer.get('name', layer['class_name'])!r} "
                f"has no config mapping"
            )
        name = layer.get("name") or layer_cfg.get("name")
        if layer["class_name"] == "InputLayer":
            prev = name
            layer.setdefault("inbound_nodes", [])
            out_layers.append(layer)
            continue
        if prev is None:
            # Sequential without an explicit InputLayer: the first real
            # layer carries batch_input_shape (classic) or the config
            # has build_input_shape (Keras 3); synthesize the input.
            shape = (
                layer_cfg.get("batch_input_shape")
                or layer_cfg.get("batch_shape")
                or (cfg.get("build_input_shape")
                    if isinstance(cfg, Mapping) else None)
            )
            if shape is None:
                raise KerasImportError(
                    "Sequential JSON lacks an InputLayer and the first "
                    "layer has no batch_input_shape"
                )
            out_layers.append(
                {
                    "class_name": "InputLayer",
                    "name": "seq_input",
                    "config": {
                        "name": "seq_input",
                        "batch_input_shape": shape,
                    },
                    "inbound_nodes": [],
                }
            )
            prev = "seq_input"
        layer["inbound_nodes"] = [[[prev, 0, 0, {}]]]
        out_layers.append(layer)
        prev = name
    if prev is None:
        raise KerasImportError("Sequential model has no layers")
    return {
        "class_name": "Functional",
        "config": {
            "name": (cfg.get("name", "sequential") if isinstance(cfg, Mapping)
                     else "sequential"),
            "layers": out_layers,
            "input_layers": [[out_layers[0]["name"], 0, 0]],
            "output_layers": [[prev, 0, 0]],
        },
    }


def from_keras_json(text: str | Mapping[str, Any]) -> tuple[Graph, tuple[int, ...]]:
    """Parse a Keras functional-model JSON into (Graph, input_shape).

    input_shape excludes the batch dimension. Raises KerasImportError
    for unsupported layer classes/configs with an explicit message —
    the reference would fail deep inside deserialization instead.
    """
    spec = json.loads(text) if isinstance(text, str) else text
    if spec.get("class_name") == "Sequential":
        spec = _sequential_to_functional(spec)
    if spec.get("class_name") not in ("Functional", "Model"):
        raise KerasImportError(
            f"expected a functional or Sequential model JSON, got class "
            f"{spec.get('class_name')!r}"
        )
    cfg = spec["config"]
    layers = cfg["layers"]

    input_layer = _io_layer_name(cfg.get("input_layers"), "input_layers")
    output_layer = _io_layer_name(cfg.get("output_layers"), "output_layers")

    b = GraphBuilder(cfg.get("name", "keras_model"))
    produced: dict[str, str] = {}  # layer name -> IR node producing its output
    input_shape: tuple[int, ...] | None = None

    for layer in layers:
        cls = layer["class_name"]
        lcfg = layer["config"]
        name = layer.get("name", lcfg.get("name"))
        if cls == "InputLayer":
            if name != input_layer:
                raise KerasImportError(
                    f"unexpected extra InputLayer {name!r}"
                )
            shape = lcfg.get("batch_input_shape") or lcfg.get("batch_shape")
            if shape:
                if any(d is None for d in shape[1:]):
                    raise KerasImportError(
                        f"InputLayer {name!r} has variable dims "
                        f"{shape}: XLA needs static shapes — re-export "
                        "the model with a concrete input size"
                    )
                input_shape = tuple(int(d) for d in shape[1:])
            produced[name] = b.input(name)
            continue
        handler = _HANDLERS.get(cls)
        if handler is None:
            raise KerasImportError(
                f"unsupported Keras layer class {cls!r} (layer {name!r}); "
                f"supported: {supported_layers()}"
            )
        if lcfg.get("data_format") == "channels_first":
            raise KerasImportError(
                f"layer {name!r} uses data_format='channels_first'; only "
                "channels-last models are supported (the TPU-native layout "
                "is NHWC)"
            )
        srcs = _inbound_names(layer.get("inbound_nodes"))
        if not srcs:
            raise KerasImportError(f"layer {name!r} has no inbound nodes")
        try:
            inputs = [produced[s] for s in srcs]
        except KeyError as e:
            raise KerasImportError(
                f"layer {name!r} consumes undeclared layer {e.args[0]!r}"
            ) from None
        produced[name] = handler(b, name, lcfg, inputs)

    if output_layer not in produced:
        raise KerasImportError(f"output layer {output_layer!r} not found")
    graph = b.build(produced[output_layer])
    if input_shape is None:
        raise KerasImportError("InputLayer lacks batch_input_shape")
    return graph, input_shape


def model_from_keras(
    text: str | Mapping[str, Any],
    *,
    weights_h5: str | None = None,
    params=None,
    rng=None,
):
    """Keras JSON (+ optional h5 weights) -> (Model, params | None).

    The full compatibility path: the artifacts a reference user already
    has (`model.to_json()` string, `save_weights` h5) become a zoo-style
    Model with auto-discovered cut candidates, ready for
    `DEFER().run_defer`. Returns (model, params); params is None unless
    weights_h5 is given (init with `model.init(rng)` as usual).
    """
    import jax

    from defer_tpu.graph.partition import chain_boundaries
    from defer_tpu.models import Model

    graph, input_shape = from_keras_json(text)
    model = Model(
        name=graph.name,
        graph=graph,
        input_shape=input_shape,
        # Width-2 discovery keeps single-tensor articulation points as
        # plain names and adds (a, b) bundles where no single tensor
        # separates the chain (NASNet-class imports).
        cut_candidates=tuple(chain_boundaries(graph, max_width=2)),
    )
    loaded = params
    if weights_h5 is not None:
        from defer_tpu.models.transplant import (
            KerasWeights,
            load_keras_h5,
            transplant,
        )

        base = model.init(rng if rng is not None else jax.random.key(0))
        loaded = transplant(
            graph, base, KerasWeights(load_keras_h5(weights_h5, text))
        )
    return model, loaded
