"""Wire serialization for IR graphs and stage params.

The reference ships each partition to its compute node as Keras
architecture JSON (port 5001, reference src/dispatcher.py:65-70) plus a
framed weights stream (port 5002, src/dispatcher.py:75-88). This is the
same capability for the native IR: a Graph or StageGraph round-trips
through JSON, and params ride the codec's self-describing array frames
— so a stage can be dispatched to a remote host that shares only this
package, no model-zoo code or checkpoint files.

Attrs must be JSON-representable (ints/floats/strings/bools and
nested lists/tuples thereof — the same "hashable, jit-bakeable"
contract OpNode already imposes); tuples are canonicalized back from
JSON lists on load.
"""

from __future__ import annotations

import json
from typing import Any

from defer_tpu.graph.ir import Graph, GraphError, OpNode
from defer_tpu.graph.partition import StageGraph

_WIRE_VERSION = 1


def _freeze(v: Any) -> Any:
    """JSON lists -> tuples, recursively (ops index attrs as tuples and
    OpNode's jit-baking contract wants immutables)."""
    if isinstance(v, list):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return {k: _freeze(x) for k, x in v.items()}
    return v


def _check_attrs(name: str, attrs: Any) -> None:
    try:
        json.dumps(attrs)
    except (TypeError, ValueError) as e:
        raise GraphError(
            f"node {name!r} has non-JSON-serializable attrs: {e}"
        ) from e


def graph_to_json(g: Graph | StageGraph) -> str:
    """Graph/StageGraph -> JSON string (the architecture wire format)."""
    nodes = [
        {
            "name": n.name,
            "op": n.op,
            "inputs": list(n.inputs),
            "attrs": dict(n.attrs),
        }
        for n in g.nodes
    ]
    for n in nodes:
        _check_attrs(n["name"], n["attrs"])
    doc: dict[str, Any] = {
        "wire_version": _WIRE_VERSION,
        "name": g.name,
        "nodes": nodes,
    }
    if isinstance(g, StageGraph):
        doc["kind"] = "stage"
        doc["input_names"] = list(g.input_names)
        doc["output_names"] = list(g.output_names)
    else:
        doc["kind"] = "graph"
        doc["input_name"] = g.input_name
        doc["output_name"] = g.output_name
    return json.dumps(doc)


def graph_from_json(s: str) -> Graph | StageGraph:
    """Inverse of graph_to_json. Raises GraphError on malformed input."""
    try:
        doc = json.loads(s)
    except json.JSONDecodeError as e:
        raise GraphError(f"not a graph JSON document: {e}") from e
    if not isinstance(doc, dict) or "nodes" not in doc:
        raise GraphError("not a graph JSON document (no 'nodes')")
    ver = doc.get("wire_version")
    if ver != _WIRE_VERSION:
        raise GraphError(
            f"unsupported graph wire version {ver!r} "
            f"(this build speaks {_WIRE_VERSION})"
        )
    try:
        nodes = tuple(
            OpNode(
                name=n["name"],
                op=n["op"],
                inputs=tuple(n["inputs"]),
                attrs=_freeze(n.get("attrs", {})),
            )
            for n in doc["nodes"]
        )
        if doc.get("kind") == "stage":
            return StageGraph(
                name=doc["name"],
                nodes=nodes,
                input_names=tuple(doc["input_names"]),
                output_names=tuple(doc["output_names"]),
            )
        return Graph(
            name=doc["name"],
            nodes=nodes,
            input_name=doc["input_name"],
            output_name=doc["output_name"],
        )
    except (KeyError, TypeError) as e:
        raise GraphError(f"malformed graph JSON: {e!r}") from e


def params_to_frames(params: Any) -> list[tuple[str, Any]]:
    """GraphParams -> ordered (path, array) pairs for the weights wire
    ('node/param' paths; deterministic order)."""
    out = []
    for node in sorted(params):
        for pname in sorted(params[node]):
            if "/" in pname:
                # rpartition on the way back would mis-split the path
                # (same guard as checkpoint.py's _flatten).
                raise GraphError(
                    f"param name {pname!r} under node {node!r} contains "
                    "'/' — not representable on the weights wire"
                )
            out.append((f"{node}/{pname}", params[node][pname]))
    return out


def frames_to_params(pairs: Any) -> dict:
    """Inverse of params_to_frames."""
    params: dict[str, dict] = {}
    for path, arr in pairs:
        node, _, pname = path.rpartition("/")
        if not node:
            raise GraphError(f"malformed param path {path!r}")
        params.setdefault(node, {})[pname] = arr
    return params
