"""Cut-point partitioner: Graph -> chain of stage Graphs.

The reference's partitioner (reference src/dispatcher.py:30-45 driving
src/dag_util.py:29-33) rebuilds Keras sub-models by recursive backward
traversal. It has two defects this module fixes by construction:

  1. No cut validation — a cut through the middle of a residual branch
    silently miscompiles (reference src/dag_util.py has no check; see
    the warning comment at reference src/test.py:24-28).
    `validate_cut_points` proves each cut is a single-tensor articulation
    point: every edge crossing the cut boundary originates at the cut
    node itself.
  2. No memoization — layers reachable along multiple paths are re-called
    once per path (reference src/dag_util.py:18-19). Here stages are
    induced subgraphs; each op appears in exactly one stage, once.

A graph cut at [c1, ..., cN] yields N+1 stages (reference
src/dispatcher.py:33 loops len(cuts)+1 times the same way).

**Multi-tensor boundaries** (beyond the reference): a cut may be a
*tuple* of node names, meaning the pipeline relays that bundle of
tensors across the boundary together. This is what makes NASNet-class
graphs pipelinable at all — each cell consumes both its predecessor and
pre-predecessor, so no single tensor separates the chain, but the pair
(cell_i, cell_{i-1}) does. The reference cannot express this (its wire
protocol ships exactly one activation per hop, reference
src/node.py:125-133); here a boundary's stages exchange a tuple and
stay jit-compiled end to end.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Union

from defer_tpu.graph.ir import (
    INPUT_OP,
    Graph,
    GraphParams,
    OpNode,
    execute_nodes,
)

# One boundary: a single articulation node, or a bundle of nodes whose
# outputs jointly separate the chain.
CutSpec = Union[str, Sequence[str]]


class PartitionError(ValueError):
    pass


def _as_bundle(cut: CutSpec) -> tuple[str, ...]:
    return (cut,) if isinstance(cut, str) else tuple(cut)


@dataclasses.dataclass(frozen=True)
class StageGraph:
    """A pipeline stage with a multi-tensor entry and/or exit.

    Same execution contract as Graph.apply, but `apply` takes/returns a
    tuple when the boundary carries more than one tensor. Single-tensor
    boundaries keep plain arrays, so downstream code (device transfer,
    donation, sync) treats both uniformly as pytrees.
    """

    name: str
    nodes: tuple[OpNode, ...]
    input_names: tuple[str, ...]
    output_names: tuple[str, ...]

    def apply(self, params: GraphParams, x):
        xs = tuple(x) if isinstance(x, (tuple, list)) else (x,)
        if len(xs) != len(self.input_names):
            raise PartitionError(
                f"stage {self.name!r} expects {len(self.input_names)} input "
                f"tensors {self.input_names}, got {len(xs)}"
            )
        out = execute_nodes(
            self.nodes, params, dict(zip(self.input_names, xs)),
            self.output_names,
        )
        outs = tuple(out[o] for o in self.output_names)
        return outs if len(outs) > 1 else outs[0]


def _bundle_ancestors(graph: Graph, bundle: tuple[str, ...]) -> set[str]:
    anc: set[str] = set()
    for c in bundle:
        anc |= graph.ancestors(c)
    return anc


def validate_cut_points(
    graph: Graph, cuts: Sequence[CutSpec]
) -> list[set[str]]:
    """Raise PartitionError unless every cut is a valid chain boundary;
    returns each boundary's ancestor set (reused by partition() so the
    O(V+E) sweeps aren't repeated).

    A boundary B (one node, or a bundle) is valid iff every edge
    (u -> v) with u on B's ancestor side and v on the other side
    originates at a member of B; then exactly the bundle's outputs cross
    the boundary, which is what the pipeline relays to the next stage
    (the analogue of the single activation the reference ships per hop,
    reference src/node.py:125-133).
    """
    node_map = graph.node_map
    ancestor_sets: list[set[str]] = []
    prev_ancestors: set[str] = set()
    prev_bundle: set[str] = set()
    for cut in cuts:
        bundle = _as_bundle(cut)
        if not bundle:
            raise PartitionError("empty cut bundle")
        for c in bundle:
            if c not in node_map:
                raise PartitionError(
                    f"cut point {c!r} is not a node of graph {graph.name!r}"
                )
            if c in (graph.input_name, graph.output_name):
                raise PartitionError(
                    f"cut point {c!r} cannot be the graph input/output"
                )
        if len(set(bundle)) != len(bundle):
            raise PartitionError(f"duplicate node in cut bundle {bundle!r}")
        anc = _bundle_ancestors(graph, bundle)
        if not prev_ancestors <= anc:
            raise PartitionError(
                f"cut points must be in topological chain order; {bundle!r} "
                "does not dominate the previous cut"
            )
        if prev_ancestors >= anc:
            raise PartitionError(
                f"cut {bundle!r} adds no nodes beyond the previous "
                "boundary — stages must be non-empty"
            )
        for c in bundle:
            # A member computed before the previous boundary is only
            # available here if the previous boundary relayed it.
            if c in prev_ancestors and c not in prev_bundle:
                raise PartitionError(
                    f"bundle member {c!r} is computed before the previous "
                    f"boundary but not carried across it; add {c!r} to the "
                    "previous bundle so its activation is relayed through"
                )
        bundle_set = set(bundle)
        for node in graph.nodes:
            if node.name in anc:
                continue
            for inp in node.inputs:
                if inp in anc and inp not in bundle_set:
                    raise PartitionError(
                        f"invalid cut at {bundle!r}: edge {inp!r} -> "
                        f"{node.name!r} crosses the boundary, so the cut "
                        "does not separate the chain (e.g. a cut inside a "
                        f"residual branch). Add {inp!r} to the bundle or "
                        "move the cut."
                    )
        ancestor_sets.append(anc)
        prev_ancestors = anc
        prev_bundle = set(bundle)
    return ancestor_sets


def articulation_points(graph: Graph) -> list[str]:
    """All valid single-tensor cut points, in topological order.

    The discovery the reference leaves to the user: its README-era cut
    lists were found by hand (reference src/test.py:24-28 documents
    them in a comment). A node c qualifies iff every edge leaving c's
    ancestor set originates at c itself.

    Candidates are restricted to ancestors of the output: a cut at a
    node the output doesn't depend on would satisfy the raw edge
    condition in degenerate graphs (a dead sink that consumes
    everything) but partition() cannot build a stage chain from it, so
    such nodes are excluded by design.

    This is exactly the width-1 case of chain_boundaries' frontier
    sweep: a node is an articulation point iff, right after it is
    processed, it is the sole producer with open out-edges.
    """
    return [
        c for c in chain_boundaries(graph, max_width=1)
        if isinstance(c, str)
    ]


def chain_boundaries(
    graph: Graph, max_width: int = 2
) -> list[CutSpec]:
    """All valid chain boundaries up to `max_width` tensors, topo order.

    Generalizes articulation_points to multi-tensor bundles: at each
    topological position the *frontier* — live producers with an edge
    still open to a later (or dead) consumer — is exactly the value
    set a boundary there must relay. Width 1 is a single-tensor cut
    (returned as a plain name); width 2..max_width is a bundle tuple.
    This is the discovery that makes NASNet-class graphs pipelinable
    without hand-written cut lists: no single tensor separates the
    cell chain, but the (cell_i, cell_i-1) frontier does.

    Edges into dead nodes (non-ancestors of the output) are never
    closed, keeping discovery consistent with validate_cut_points:
    a producer feeding a dead consumer must ride every later boundary.
    """
    if max_width < 1:
        raise PartitionError("max_width must be >= 1")
    live = graph.ancestors(graph.output_name)
    consumers = graph.consumers()
    topo_index = {node.name: i for i, node in enumerate(graph.nodes)}
    open_edges: dict[str, int] = {}
    frontier: set[str] = set()
    out: list[CutSpec] = []
    for node in graph.nodes:
        if node.name not in live:
            continue  # dead consumers never close their in-edges
        for inp in node.inputs:
            open_edges[inp] -= 1
            if open_edges[inp] == 0:
                frontier.discard(inp)
        deg = len(consumers[node.name])
        if deg:
            open_edges[node.name] = deg
            frontier.add(node.name)
        if node.name == graph.output_name:
            continue
        if (
            1 <= len(frontier) <= max_width
            and graph.input_name not in frontier
        ):
            members = sorted(frontier, key=topo_index.__getitem__)
            out.append(members[0] if len(members) == 1 else tuple(members))
    return out


def partition(
    graph: Graph, cuts: Sequence[CutSpec]
) -> list[Graph | StageGraph]:
    """Split `graph` at `cuts` into a chain of stages.

    Stage i's input placeholders keep the *cut nodes' names* (op
    rewritten to "input"), so parameters keep their global node-name
    keys and `stage_params` is a plain dict slice. Single-tensor
    boundaries yield plain Graph stages; bundle boundaries yield
    StageGraph stages whose apply exchanges tuples.
    """
    bundles = [_as_bundle(c) for c in cuts]
    ancestor_sets = validate_cut_points(graph, bundles)

    entries = [(graph.input_name,), *bundles]
    exits = [*bundles, (graph.output_name,)]
    segment_of: dict[str, int] = {}
    prev_anc: set[str] = set()
    for i, anc in enumerate(ancestor_sets):
        for name in anc - prev_anc:
            segment_of[name] = i
        prev_anc = anc
    for node in graph.nodes:
        if node.name not in segment_of:
            segment_of[node.name] = len(bundles)

    stages: list[Graph | StageGraph] = []
    for i in range(len(bundles) + 1):
        entry = entries[i]
        entry_set = set(entry)
        nodes: list[OpNode] = [OpNode(e, INPUT_OP, ()) for e in entry]
        for node in graph.nodes:
            # Cut nodes belong to the producing segment (each is its own
            # ancestor); the consuming stage sees them only as the
            # placeholders created above.
            if segment_of[node.name] != i or node.name in entry_set:
                continue
            nodes.append(node)
        if len(entry) == 1 and len(exits[i]) == 1:
            stages.append(
                Graph(
                    name=f"{graph.name}.stage{i}",
                    nodes=tuple(nodes),
                    input_name=entry[0],
                    output_name=exits[i][0],
                )
            )
        else:
            stages.append(
                StageGraph(
                    name=f"{graph.name}.stage{i}",
                    nodes=tuple(nodes),
                    input_names=entry,
                    output_names=exits[i],
                )
            )
    return stages


def stage_params(params: GraphParams, stage: Graph | StageGraph) -> dict:
    """Slice the full parameter pytree down to one stage's nodes.

    Entry placeholders are excluded: a cut node's parameters live in
    the stage that *computes* it — the consuming stage only receives
    its activation, so shipping the weights there too would waste HBM.
    """
    names = {n.name for n in stage.nodes if n.op != INPUT_OP}
    return {k: v for k, v in params.items() if k in names and v}
