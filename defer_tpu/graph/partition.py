"""Cut-point partitioner: Graph -> chain of stage Graphs.

The reference's partitioner (reference src/dispatcher.py:30-45 driving
src/dag_util.py:29-33) rebuilds Keras sub-models by recursive backward
traversal. It has two defects this module fixes by construction:

  1. No cut validation — a cut through the middle of a residual branch
    silently miscompiles (reference src/dag_util.py has no check; see
    the warning comment at reference src/test.py:24-28).
    `validate_cut_points` proves each cut is a single-tensor articulation
    point: every edge crossing the cut boundary originates at the cut
    node itself.
  2. No memoization — layers reachable along multiple paths are re-called
    once per path (reference src/dag_util.py:18-19). Here stages are
    induced subgraphs; each op appears in exactly one stage, once.

A graph cut at [c1, ..., cN] yields N+1 stages (reference
src/dispatcher.py:33 loops len(cuts)+1 times the same way).
"""

from __future__ import annotations

from typing import Sequence

from defer_tpu.graph.ir import INPUT_OP, Graph, GraphParams, OpNode


class PartitionError(ValueError):
    pass


def validate_cut_points(graph: Graph, cuts: Sequence[str]) -> None:
    """Raise PartitionError unless every cut is a valid chain boundary.

    A cut node c is valid iff every edge (u -> v) with u on c's ancestor
    side and v on the other side has u == c; then the only tensor
    crossing the boundary is c's output, which is what the pipeline
    relays to the next stage (the analogue of the single activation the
    reference ships per hop, reference src/node.py:125-133).
    """
    node_map = graph.node_map
    seen: set[str] = set()
    prev_ancestors: set[str] = set()
    for cut in cuts:
        if cut not in node_map:
            raise PartitionError(
                f"cut point {cut!r} is not a node of graph {graph.name!r}"
            )
        if cut in seen:
            raise PartitionError(f"duplicate cut point {cut!r}")
        seen.add(cut)
        if cut in (graph.input_name, graph.output_name):
            raise PartitionError(
                f"cut point {cut!r} cannot be the graph input/output"
            )
        anc = graph.ancestors(cut)
        if not prev_ancestors <= anc:
            raise PartitionError(
                f"cut points must be in topological chain order; {cut!r} "
                "does not dominate the previous cut"
            )
        for node in graph.nodes:
            if node.name in anc:
                continue
            for inp in node.inputs:
                if inp in anc and inp != cut:
                    raise PartitionError(
                        f"invalid cut at {cut!r}: edge {inp!r} -> "
                        f"{node.name!r} crosses the boundary, so the cut is "
                        "not a single-tensor articulation point (e.g. a cut "
                        "inside a residual branch)"
                    )
        prev_ancestors = anc


def articulation_points(graph: Graph) -> list[str]:
    """All valid single-tensor cut points, in topological order.

    The discovery the reference leaves to the user: its README-era cut
    lists were found by hand (reference src/test.py:24-28 documents
    them in a comment). A node c qualifies iff every edge leaving c's
    ancestor set originates at c itself.

    Candidates are restricted to ancestors of the output: a cut at a
    node the output doesn't depend on would satisfy the raw edge
    condition in degenerate graphs (a dead sink that consumes
    everything) but partition() cannot build a stage chain from it, so
    such nodes are excluded by design.

    Single O(V+E) sweep: for a valid c every live node is comparable to
    c, so anc(c) is exactly the topological prefix of live nodes ending
    at c — c is valid iff, right after processing it, every still-open
    edge (one whose consumer hasn't been processed) originates at c.
    Edges into dead nodes are never consumed: a dead consumer lands on
    the far side of every later cut while its producer stays on the
    near side, which is exactly the crossing edge the ancestors-based
    definition rejects.
    """
    live = graph.ancestors(graph.output_name)
    consumers = graph.consumers()
    total_open = 0
    points: list[str] = []
    for node in graph.nodes:
        if node.name in live:
            total_open -= len(node.inputs)
        # At this instant none of this node's own out-edges can have
        # been consumed yet, so "every open edge originates here" is
        # exactly total_open == out_degree.
        out_degree = len(consumers[node.name])
        total_open += out_degree
        if (
            node.name in live
            and node.name not in (graph.input_name, graph.output_name)
            and total_open == out_degree
        ):
            points.append(node.name)
    return points


def partition(graph: Graph, cuts: Sequence[str]) -> list[Graph]:
    """Split `graph` at `cuts` into a chain of stage graphs.

    Stage i's input node keeps the *cut node's name* (op rewritten to
    "input"), so parameters keep their global node-name keys and
    `stage_params` is a plain dict slice.
    """
    cuts = list(cuts)
    validate_cut_points(graph, cuts)

    boundaries = [graph.input_name, *cuts]
    segment_of: dict[str, int] = {}
    prev_anc: set[str] = set()
    for i, cut in enumerate(cuts):
        anc = graph.ancestors(cut)
        for name in anc - prev_anc:
            segment_of[name] = i
        prev_anc = anc
    for node in graph.nodes:
        if node.name not in segment_of:
            segment_of[node.name] = len(cuts)

    stages: list[Graph] = []
    for i in range(len(cuts) + 1):
        entry = boundaries[i]
        nodes: list[OpNode] = []
        for node in graph.nodes:
            if segment_of[node.name] != i:
                continue
            if node.name == entry:
                nodes.append(OpNode(entry, INPUT_OP, ()))
            else:
                nodes.append(node)
        if i > 0 and not any(n.name == entry for n in nodes):
            # The cut node was assigned to segment i-1 (it is its own
            # ancestor); stage i still needs it as its input placeholder.
            nodes.insert(0, OpNode(entry, INPUT_OP, ()))
        out = cuts[i] if i < len(cuts) else graph.output_name
        stages.append(
            Graph(
                name=f"{graph.name}.stage{i}",
                nodes=tuple(nodes),
                input_name=entry,
                output_name=out,
            )
        )
    return stages


def stage_params(params: GraphParams, stage: Graph) -> dict:
    """Slice the full parameter pytree down to one stage's nodes."""
    names = {n.name for n in stage.nodes}
    return {k: v for k, v in params.items() if k in names and v}
