from defer_tpu.graph.ir import Graph, GraphBuilder, OpNode
from defer_tpu.graph.partition import (
    PartitionError,
    partition,
    stage_params,
    validate_cut_points,
)

__all__ = [
    "Graph",
    "GraphBuilder",
    "OpNode",
    "PartitionError",
    "partition",
    "stage_params",
    "validate_cut_points",
]
