"""Framework-neutral DAG IR for models.

The reference has no IR: it partitions live Keras objects by recursively
re-calling layers (reference src/dag_util.py:11-27), which re-executes any
layer reachable along multiple paths (no memoization — reference
src/dag_util.py:18-19) and cannot validate cut-points. Here a model is an
explicit DAG of named ops; execution walks the topological order once with
a value cache, so multi-branch models (ResNet adds, Inception concats)
cost each op exactly once, and partitioning is ordinary graph surgery.

Shapes/dtypes are static and inferred from the op `apply` functions via
``jax.eval_shape`` — exactly the property XLA needs to tile convs/matmuls
onto the MXU.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp

# Params for one node: dict of named arrays (possibly empty).
NodeParams = Mapping[str, jax.Array]
# Params for a graph: node name -> NodeParams. An ordinary pytree, so it
# slices cleanly per stage and works with jit/device_put/shard_map.
GraphParams = Mapping[str, NodeParams]

INPUT_OP = "input"


@dataclasses.dataclass(frozen=True)
class OpNode:
    """One named op in the DAG.

    Attributes:
      name: unique node name (the analogue of a Keras layer name; cut
        points are specified by these names, as in reference
        src/test.py:28).
      op: op kind, resolved against the op registry (defer_tpu.ops).
      inputs: names of producer nodes, in argument order.
      attrs: static attributes (strides, padding, ...). Must be hashable
        values only; they are baked into the jitted program.
    """

    name: str
    op: str
    inputs: tuple[str, ...]
    attrs: Mapping[str, Any] = dataclasses.field(default_factory=dict)


class GraphError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class Graph:
    """A single-input single-output DAG of ops in topological order.

    Same model class the reference supports: its partitioner assumes one
    input and one output tensor (reference src/dag_util.py:29-33).
    """

    name: str
    nodes: tuple[OpNode, ...]
    input_name: str
    output_name: str

    def __post_init__(self) -> None:
        seen: set[str] = set()
        for node in self.nodes:
            if node.name in seen:
                raise GraphError(f"duplicate node name {node.name!r}")
            for inp in node.inputs:
                if inp not in seen:
                    raise GraphError(
                        f"node {node.name!r} consumes {inp!r} before it is "
                        "defined — nodes must be topologically ordered"
                    )
            seen.add(node.name)
        if self.input_name not in seen:
            raise GraphError(f"input node {self.input_name!r} not in graph")
        if self.output_name not in seen:
            raise GraphError(f"output node {self.output_name!r} not in graph")

    # -- lookups ---------------------------------------------------------

    @property
    def node_map(self) -> dict[str, OpNode]:
        return {n.name: n for n in self.nodes}

    def __contains__(self, name: str) -> bool:
        return any(n.name == name for n in self.nodes)

    def consumers(self) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {n.name: [] for n in self.nodes}
        for n in self.nodes:
            for inp in n.inputs:
                out[inp].append(n.name)
        return out

    def ancestors(self, name: str) -> set[str]:
        """All nodes from which `name` is reachable, inclusive."""
        node_map = self.node_map
        if name not in node_map:
            raise GraphError(f"no node named {name!r} in graph {self.name!r}")
        result: set[str] = set()
        stack = [name]
        while stack:
            cur = stack.pop()
            if cur in result:
                continue
            result.add(cur)
            stack.extend(node_map[cur].inputs)
        return result

    # -- init / apply ----------------------------------------------------

    def init(
        self,
        rng: jax.Array,
        input_shape: Sequence[int],
        *,
        param_dtype: Any = jnp.float32,
        input_dtype: Any = jnp.float32,
    ) -> GraphParams:
        """Initialize parameters for every node.

        Output shapes are derived from each op's `apply` via
        ``jax.eval_shape`` so there is exactly one source of shape truth.
        """
        from defer_tpu.ops import get_op

        shapes: dict[str, tuple[int, ...]] = {}
        dtypes: dict[str, Any] = {}
        params: dict[str, dict[str, jax.Array]] = {}
        for node in self.nodes:
            if node.op == INPUT_OP:
                shapes[node.name] = tuple(input_shape)
                dtypes[node.name] = input_dtype
                params[node.name] = {}
                continue
            op = get_op(node.op)
            in_shapes = [shapes[i] for i in node.inputs]
            rng, sub = jax.random.split(rng)
            node_params = op.init(sub, node.attrs, in_shapes, param_dtype)
            params[node.name] = node_params
            out = jax.eval_shape(
                lambda p, xs, _op=op, _attrs=node.attrs: _op.apply(p, xs, _attrs),
                node_params,
                [
                    jax.ShapeDtypeStruct(shapes[i], dtypes[i])
                    for i in node.inputs
                ],
            )
            shapes[node.name] = tuple(out.shape)
            dtypes[node.name] = out.dtype
        return params

    def infer_shapes(
        self,
        params: GraphParams,
        input_shape: Sequence[int],
        dtype: Any = jnp.float32,
    ) -> dict[str, jax.ShapeDtypeStruct]:
        """Shape/dtype of every node's output for a given input spec."""
        from defer_tpu.ops import get_op

        specs: dict[str, jax.ShapeDtypeStruct] = {}
        for node in self.nodes:
            if node.op == INPUT_OP:
                specs[node.name] = jax.ShapeDtypeStruct(
                    tuple(input_shape), dtype
                )
                continue
            op = get_op(node.op)
            specs[node.name] = jax.eval_shape(
                lambda p, xs, _op=op, _attrs=node.attrs: _op.apply(
                    p, xs, _attrs
                ),
                params.get(node.name, {}),
                [specs[i] for i in node.inputs],
            )
        return specs

    def apply(self, params: GraphParams, x: jax.Array) -> jax.Array:
        """Run the graph. Single topological pass with a value cache —
        the memoized fix for the reference's exponential re-traversal of
        multi-path DAGs (reference src/dag_util.py:18-19)."""
        return execute_nodes(
            self.nodes, params, {self.input_name: x}, (self.output_name,)
        )[self.output_name]

    def output_spec(
        self,
        params: GraphParams,
        input_shape: Sequence[int],
        dtype: Any = jnp.float32,
    ) -> jax.ShapeDtypeStruct:
        return jax.eval_shape(
            self.apply, params, jax.ShapeDtypeStruct(tuple(input_shape), dtype)
        )

    def param_count(self, params: GraphParams) -> int:
        return sum(
            leaf.size for leaf in jax.tree_util.tree_leaves(params)
        )


def execute_nodes(
    nodes: Sequence[OpNode],
    params: GraphParams,
    seeded: Mapping[str, jax.Array],
    outputs: Sequence[str],
) -> dict[str, jax.Array]:
    """Topological walk shared by Graph.apply and multi-tensor stages
    (defer_tpu/graph/partition.py): run `nodes` with `seeded` values
    standing in for input placeholders, return the named `outputs`.

    Dead intermediates are evicted eagerly so tracing giant graphs
    (NASNet) doesn't hold every activation alive.
    """
    from defer_tpu.ops import get_op

    cache: dict[str, jax.Array] = dict(seeded)
    consumers_left: dict[str, int] = {n.name: 0 for n in nodes}
    for n in nodes:
        for i in n.inputs:
            consumers_left[i] += 1
    for o in outputs:
        consumers_left[o] += 1  # never evict requested outputs
    for node in nodes:
        if node.op == INPUT_OP:
            if node.name not in cache:
                raise GraphError(
                    f"no value seeded for input placeholder {node.name!r}"
                )
            continue
        op = get_op(node.op)
        inputs = [cache[i] for i in node.inputs]
        cache[node.name] = op.apply(
            params.get(node.name, {}), inputs, node.attrs
        )
        for i in node.inputs:
            consumers_left[i] -= 1
            if consumers_left[i] == 0:
                del cache[i]
    return {o: cache[o] for o in outputs}


class GraphBuilder:
    """Imperative builder producing an immutable `Graph`.

    Auto-names nodes per op kind (conv, conv_1, conv_2, ...) unless an
    explicit name is given — mirroring Keras naming so reference-style
    cut lists like ["add_2", "add_4", ...] (reference src/test.py:27)
    carry over unchanged.
    """

    def __init__(self, name: str):
        self.name = name
        self._nodes: list[OpNode] = []
        self._names: set[str] = set()
        self._counters: dict[str, int] = {}
        self._input_name: str | None = None

    def _fresh(self, op: str) -> str:
        n = self._counters.get(op, 0)
        self._counters[op] = n + 1
        return op if n == 0 else f"{op}_{n}"

    def input(self, name: str = "input") -> str:
        if self._input_name is not None:
            raise GraphError("graph already has an input node")
        self._input_name = name
        return self.add(INPUT_OP, name=name)

    def add(
        self,
        op: str,
        *inputs: str,
        name: str | None = None,
        **attrs: Any,
    ) -> str:
        if name is None:
            name = self._fresh(op)
        if name in self._names:
            raise GraphError(f"duplicate node name {name!r}")
        for inp in inputs:
            if inp not in self._names:
                raise GraphError(
                    f"node {name!r}: unknown input {inp!r} (must be added "
                    "before use)"
                )
        self._names.add(name)
        self._nodes.append(OpNode(name, op, tuple(inputs), dict(attrs)))
        return name

    def build(self, output: str) -> Graph:
        if self._input_name is None:
            raise GraphError("graph has no input node")
        return Graph(
            name=self.name,
            nodes=tuple(self._nodes),
            input_name=self._input_name,
            output_name=output,
        )
