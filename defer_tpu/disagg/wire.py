"""KV-block wire format: finished prefill state as a transport payload.

The DEFER thesis is streaming intermediate state between specialized
nodes (PAPER.md); disaggregated serving applies it to the two phases
of LLM inference — compute-bound prefill and cache-read-bound decode —
by streaming finished KV *blocks* instead of activations. This module
is the format layer: it frames per-layer K/V block tensors plus the
metadata the decode server needs to seat them, through the existing
`runtime/transport.py` framing (1-byte tag + length + codec frame) and
`runtime/codec.py` compression seam, including the int8
quantize-for-transfer mode.

One dispatch stream (decode host -> prefill worker), mirroring
`runtime/remote_stage.py`'s session shape (blob = uint8 JSON frame):

    blob   hello       {magic, version, result_host/port, block_size,
                        codec knobs, chunk_len}
    blob   decoder     TransformerConfig + compute_dtype (the worker
                       rebuilds its own GptDecoder — no pickle)
    blob   params      manifest: [[path, dtype_token], ...]
    frames              one array per manifest entry, ALWAYS lossless
    then   per request: blob {kind: prefill, rid} + prompt frame
    STOP               ends the session

One result stream (worker -> decode host), per request ("payload"):

    blob   kv meta     {kind: kv, version, rid, t0, n_blocks, layers,
                        block_size, kv_heads, head_dim, dtype,
                        quantized}
    frame  logits      the last prompt position's [1, V] logits row,
                       ALWAYS lossless (the first generated token is
                       sampled from it — a lossy row would fork the
                       stream vs monolithic serving)
    frames K/V         2 * layers frames, layer-major K-then-V, each
                       [n_blocks, kv_heads, block_size, head_dim];
                       these ride the sender's quantize mode (int8 =
                       the lossy transfer the reference ran as ZFP)

bfloat16 tensors cross the wire as uint16 VIEWS plus a dtype token
(the codec speaks numpy dtype strings only); the int8 quantized mode
therefore applies to real float dtypes and bf16 ships lossless.

Versioning: every blob carries `version`; readers reject mismatches
loudly (a silent format skew would corrupt KV state, the worst kind of
serving bug).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Iterator

import numpy as np

from defer_tpu.runtime.transport import ArrayReceiver, ArraySender, TransportError
from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)

WIRE_VERSION = 1
MAGIC = "defer-disagg"

_BF16 = "bfloat16"


def _bf16_dtype():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def to_wire_array(arr: Any) -> tuple[np.ndarray, str]:
    """(codec-safe array, dtype token). bfloat16 — which the codec's
    numpy dtype strings cannot express — travels as a uint16 view."""
    a = np.asarray(arr)
    if a.dtype == _bf16_dtype():
        return a.view(np.uint16), _BF16
    return a, a.dtype.name


def from_wire_array(arr: np.ndarray, token: str) -> np.ndarray:
    if token == _BF16:
        return arr.view(_bf16_dtype())
    if arr.dtype.name != token:
        # The codec already restored the original dtype (including
        # after int8 quantization); a mismatch means sender and
        # receiver disagree about what was shipped.
        raise TransportError(
            f"frame dtype {arr.dtype.name} != declared {token}"
        )
    return arr


def send_blob(sender: ArraySender, obj: dict) -> int:
    """JSON dict -> one uint8 frame (remote_stage's blob idiom),
    always lossless. Returns wire bytes."""
    saved = sender.quantize
    sender.quantize = None
    try:
        return sender.send(
            np.frombuffer(json.dumps(obj).encode(), np.uint8)
        )
    finally:
        sender.quantize = saved


def read_blob(it: Iterator[np.ndarray]) -> dict | None:
    """Next frame as a JSON dict; None at a clean stream end."""
    try:
        frame = next(it)
    except StopIteration:
        return None
    try:
        return json.loads(bytes(bytearray(frame)).decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise TransportError(f"expected a JSON blob frame: {e}") from None


def expect_blob(it: Iterator[np.ndarray], kind: str) -> dict:
    blob = read_blob(it)
    if blob is None:
        raise TransportError(f"stream ended awaiting {kind!r} blob")
    got = blob.get("kind")
    if got != kind:
        raise TransportError(f"expected {kind!r} blob, got {got!r}")
    if blob.get("version") != WIRE_VERSION:
        raise TransportError(
            f"wire version {blob.get('version')} != {WIRE_VERSION}"
        )
    return blob


def _next_frame(it: Iterator[np.ndarray], what: str) -> np.ndarray:
    """next() that converts a mid-payload stream end into a typed
    TransportError — and, inside generators, dodges PEP 479 turning
    the StopIteration into an opaque RuntimeError."""
    try:
        return next(it)
    except StopIteration:
        raise TransportError(f"stream ended awaiting {what}") from None


# -- decoder + params ------------------------------------------------------


def decoder_to_wire(dec: Any) -> dict:
    """GptDecoder -> a JSON-able architecture blob body. No pickle:
    the worker reconstructs from the frozen TransformerConfig fields
    (all JSON-able scalars/tuples)."""
    cfg = dataclasses.asdict(dec.cfg)
    return {
        "cfg": cfg,
        "compute_dtype": np.dtype(dec.compute_dtype).name,
        "rolling_cache": bool(getattr(dec, "rolling_cache", False)),
    }


_DTYPE_BY_NAME = None


def _dtype_from_name(name: str):
    global _DTYPE_BY_NAME
    if _DTYPE_BY_NAME is None:
        import jax.numpy as jnp

        _DTYPE_BY_NAME = {
            "bfloat16": jnp.bfloat16,
            "float16": jnp.float16,
            "float32": jnp.float32,
            "float64": jnp.float64,
        }
    try:
        return _DTYPE_BY_NAME[name]
    except KeyError:
        raise TransportError(f"unknown compute dtype {name!r}") from None


def decoder_from_wire(body: dict) -> Any:
    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.parallel.transformer_stack import TransformerConfig

    cfg_d = dict(body["cfg"])
    # JSON has no tuples; the frozen config declares one.
    cfg_d["lora_targets"] = tuple(cfg_d.get("lora_targets", ()))
    cfg = TransformerConfig(**cfg_d)
    return GptDecoder(
        cfg,
        compute_dtype=_dtype_from_name(body["compute_dtype"]),
        rolling_cache=body.get("rolling_cache", False),
    )


def flatten_params(tree: dict, prefix: str = "") -> list[tuple[str, np.ndarray]]:
    """Nested dict-of-arrays -> sorted (slash-path, array) pairs.
    graph/serialize.py's params_to_frames is two-level only (node/
    param); decoder params mix leaf and dict values at the top level,
    so this walks arbitrary nesting."""
    out: list[tuple[str, np.ndarray]] = []
    for key in sorted(tree):
        if "/" in key:
            raise ValueError(f"param key {key!r} contains the path separator")
        val = tree[key]
        if isinstance(val, dict):
            out.extend(flatten_params(val, f"{prefix}{key}/"))
        else:
            out.append((f"{prefix}{key}", np.asarray(val)))
    return out


def unflatten_params(pairs: list[tuple[str, np.ndarray]]) -> dict:
    tree: dict = {}
    for path, arr in pairs:
        node = tree
        *parents, leaf = path.split("/")
        for p in parents:
            node = node.setdefault(p, {})
        node[leaf] = arr
    return tree


def send_params(sender: ArraySender, params: dict) -> int:
    """Manifest blob + one frame per leaf, ALWAYS lossless (same rule
    as remote_stage.dispatch_stage: int8-roundtripped weights would
    skew every token the worker ever prefills). Returns wire bytes."""
    pairs = flatten_params(params)
    manifest = []
    frames = []
    for path, arr in pairs:
        wired, token = to_wire_array(arr)
        manifest.append([path, token])
        frames.append(wired)
    n = send_blob(
        sender,
        {"kind": "params", "version": WIRE_VERSION, "manifest": manifest},
    )
    saved = sender.quantize
    sender.quantize = None
    try:
        for wired in frames:
            n += sender.send(wired)
    finally:
        sender.quantize = saved
    return n


def read_params(it: Iterator[np.ndarray]) -> dict:
    blob = expect_blob(it, "params")
    pairs = []
    for path, token in blob["manifest"]:
        arr = _next_frame(it, f"param frame {path!r}")
        pairs.append((path, from_wire_array(arr, token)))
    return unflatten_params(pairs)


# -- dispatch stream (decode host -> prefill worker) -----------------------


def send_hello(
    sender: ArraySender,
    *,
    result_host: str,
    result_port: int,
    block_size: int,
    chunk_len: int | None = None,
) -> int:
    """First dispatch frame: where results go and how to block them.
    Codec knobs travel implicitly — the worker mirrors them onto its
    result sender."""
    return send_blob(
        sender,
        {
            "kind": "hello",
            "version": WIRE_VERSION,
            "magic": MAGIC,
            "result_host": result_host,
            "result_port": result_port,
            "block_size": block_size,
            "chunk_len": chunk_len,
            "compress": sender.compress,
            "level": sender.level,
            "quantize": sender.quantize,
        },
    )


def expect_hello(it: Iterator[np.ndarray]) -> dict:
    hello = expect_blob(it, "hello")
    if hello.get("magic") != MAGIC:
        raise TransportError(
            f"dispatch stream magic {hello.get('magic')!r} != {MAGIC!r} "
            "— is a non-disagg peer connected to this worker?"
        )
    return hello


def send_prefill_request(
    sender: ArraySender, rid: int, prompt: np.ndarray
) -> int:
    n = send_blob(
        sender, {"kind": "prefill", "version": WIRE_VERSION, "rid": rid}
    )
    saved = sender.quantize
    sender.quantize = None  # token ids are exact or useless
    try:
        n += sender.send(np.asarray(prompt, np.int32))
    finally:
        sender.quantize = saved
    return n


# -- result stream (prefill worker -> decode host) -------------------------


@dataclasses.dataclass
class KVPayload:
    """One request's finished prefill state, decode-server-shaped:
    `k`/`v` are [layers, n_blocks, kv_heads, block_size, head_dim]
    block stacks (the pool layout minus the pool axis), `logits` the
    [1, V] last-prompt-position row the first token is sampled from."""

    rid: int
    t0: int
    k: np.ndarray
    v: np.ndarray
    logits: np.ndarray
    wire_bytes: int = 0
    quantized: bool = False


def send_kv_payload(
    sender: ArraySender, payload: KVPayload, obs: Any = None
) -> int:
    """Frame one payload onto the result stream. K/V frames ride the
    sender's quantize mode; meta and the logits row are pinned
    lossless. `obs` — optional obs.serving.DisaggMetrics to account
    blocks/bytes against. Returns wire bytes sent."""
    L, n_blocks, hkv, bs, dh = payload.k.shape
    k_w, token = to_wire_array(payload.k)
    v_w, _ = to_wire_array(payload.v)
    quant = sender.quantize is not None and token != _BF16
    n = send_blob(
        sender,
        {
            "kind": "kv",
            "version": WIRE_VERSION,
            "rid": payload.rid,
            "t0": payload.t0,
            "n_blocks": n_blocks,
            "layers": L,
            "block_size": bs,
            "kv_heads": hkv,
            "head_dim": dh,
            "dtype": token,
            "quantized": quant,
        },
    )
    logits_w, ltoken = to_wire_array(payload.logits)
    saved = sender.quantize
    sender.quantize = None
    try:
        n += sender.send(logits_w)
    finally:
        sender.quantize = saved
    if ltoken == _BF16:
        raise ValueError("logits row must be a real float dtype")
    for layer in range(L):
        n += sender.send(k_w[layer])
        n += sender.send(v_w[layer])
    if obs is not None:
        obs.kv_blocks_shipped.inc(n_blocks)
        obs.kv_bytes_sent.inc(n)
    return n


@dataclasses.dataclass
class PrefixPayload:
    """A root-anchored radix prefix chain lifted out of one replica's
    pool for fleet migration: per-block OWN-token bytes (int64, the
    radix cache's tok_of encoding) plus [layers, n, kv_heads,
    block_size, head_dim] K/V block stacks. Deliberately carries token
    bytes and NOT digests — the importer recomputes the chained keys
    itself (runtime/paged.py::import_prefix_blocks), so a corrupted or
    hostile payload mis-keys into digests nothing looks up instead of
    aliasing a resident chain."""

    toks: list[bytes]
    k: np.ndarray
    v: np.ndarray
    wire_bytes: int = 0


def send_prefix_payload(
    sender: ArraySender, payload: PrefixPayload
) -> int:
    """Frame one prefix chain onto a stream. Pinned LOSSLESS end to
    end, unlike per-request KV transfer: a migrated block becomes
    long-lived shared cache state on the importer, so a lossy copy
    would skew every future sharer — not one opted-in request.
    Returns wire bytes sent."""
    L, n_blocks, hkv, bs, dh = payload.k.shape
    if len(payload.toks) != n_blocks:
        raise ValueError(
            f"{len(payload.toks)} token blobs for {n_blocks} blocks"
        )
    k_w, token = to_wire_array(payload.k)
    v_w, _ = to_wire_array(payload.v)
    n = send_blob(
        sender,
        {
            "kind": "prefix",
            "version": WIRE_VERSION,
            "n_blocks": n_blocks,
            "layers": L,
            "block_size": bs,
            "kv_heads": hkv,
            "head_dim": dh,
            "dtype": token,
            "toks": [t.hex() for t in payload.toks],
        },
    )
    saved = sender.quantize
    sender.quantize = None
    try:
        for layer in range(L):
            n += sender.send(k_w[layer])
            n += sender.send(v_w[layer])
    finally:
        sender.quantize = saved
    return n


def read_prefix_payload(
    it: Iterator[np.ndarray], receiver: ArrayReceiver | None = None
) -> PrefixPayload | None:
    """Next prefix chain off a stream (None at a clean end). Pass the
    receiver to account wire bytes on the payload."""
    start = receiver.rx_frame_bytes if receiver is not None else 0
    meta = read_blob(it)
    if meta is None:
        return None
    if meta.get("kind") != "prefix":
        raise TransportError(
            f"expected 'prefix' blob, got {meta.get('kind')!r}"
        )
    if meta.get("version") != WIRE_VERSION:
        raise TransportError(
            f"wire version {meta.get('version')} != {WIRE_VERSION}"
        )
    L = meta["layers"]
    token = meta["dtype"]
    ks, vs = [], []
    for layer in range(L):
        ks.append(
            from_wire_array(
                _next_frame(it, f"layer {layer} prefix K frame"), token
            )
        )
        vs.append(
            from_wire_array(
                _next_frame(it, f"layer {layer} prefix V frame"), token
            )
        )
    nbytes = (
        receiver.rx_frame_bytes - start if receiver is not None else 0
    )
    return PrefixPayload(
        toks=[bytes.fromhex(t) for t in meta["toks"]],
        k=np.stack(ks),
        v=np.stack(vs),
        wire_bytes=nbytes,
    )


def iter_kv_payloads(
    receiver: ArrayReceiver, obs: Any = None
) -> Iterator[KVPayload]:
    """Yield payloads off the result stream until the worker's STOP.
    A stream that dies mid-payload raises TransportError with nothing
    partial yielded — payload delivery is atomic, which is what makes
    the retry path's "re-request everything undelivered" accounting
    sound. `obs` — optional DisaggMetrics for received-byte
    accounting."""
    it = iter(receiver)
    while True:
        start = receiver.rx_frame_bytes
        meta = read_blob(it)
        if meta is None:
            return
        if meta.get("kind") != "kv":
            raise TransportError(
                f"expected 'kv' blob on the result stream, got "
                f"{meta.get('kind')!r}"
            )
        if meta.get("version") != WIRE_VERSION:
            raise TransportError(
                f"wire version {meta.get('version')} != {WIRE_VERSION}"
            )
        logits = _next_frame(it, "logits frame")
        L = meta["layers"]
        token = meta["dtype"]
        ks, vs = [], []
        for layer in range(L):
            ks.append(
                from_wire_array(
                    _next_frame(it, f"layer {layer} K frame"), token
                )
            )
            vs.append(
                from_wire_array(
                    _next_frame(it, f"layer {layer} V frame"), token
                )
            )
        nbytes = receiver.rx_frame_bytes - start
        if obs is not None:
            obs.kv_bytes_recv.inc(nbytes)
        yield KVPayload(
            rid=meta["rid"],
            t0=meta["t0"],
            k=np.stack(ks),
            v=np.stack(vs),
            logits=logits,
            wire_bytes=nbytes,
            quantized=meta.get("quantized", False),
        )
