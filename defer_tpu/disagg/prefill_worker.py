"""Prefill worker: the compute-bound half of disaggregated serving.

Runs as its own process (CLI below) or an in-process thread (tests,
single-host splits): receives a decoder architecture + weights over
the dispatch stream, then for each prefill request runs (optionally
chunked) prefill and streams the finished KV blocks + first-token
logits back to the decode host's ingest (`disagg/ingest.py`), which
seats them directly in the paged pool. The session/stream shapes
mirror `runtime/remote_stage.py` (same listen-then-connect-back
contract); the payload format is `disagg/wire.py`.

Parity contract: with `chunk_len=None` the worker prefills each prompt
in ONE pow2-padded step — the exact shape schedule the monolithic
server's admission uses — so the K/V rows and the last-position logits
are bit-identical to what `serve_paged` would have computed locally,
and greedy decode is token-identical end to end. Chunked prefill
(`chunk_len=C`) bounds the compile-shape set and the per-dispatch
FLOPs for long prompts: full chunks run at EXACTLY C tokens (a padded
mid-chunk would advance the cache write head past real content and
corrupt every later row), only the tail chunk is pow2-padded.

Crash injection: `fail_after_requests=N` hard-closes both sockets
after N payloads without the STOP frame — the decode side sees a
mid-stream peer death, which is the retry path the worker-drop test
exercises.
"""

from __future__ import annotations

import numpy as np

from defer_tpu.disagg import wire
from defer_tpu.obs.serving import DisaggMetrics
from defer_tpu.runtime.transport import ArrayReceiver, ArraySender
from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)


def prefill_schedule(t0: int, chunk_len: int | None) -> list[int]:
    """Chunk lengths covering t0 tokens: full chunks of exactly
    chunk_len, then a 1..chunk_len tail (the only chunk the runner may
    pad). chunk_len=None = one chunk = the monolithic schedule."""
    if t0 < 1:
        raise ValueError("need at least one prompt token")
    if chunk_len is None or chunk_len >= t0:
        return [t0]
    if chunk_len < 1:
        raise ValueError(f"chunk_len must be >= 1, got {chunk_len}")
    n_full = (t0 - 1) // chunk_len
    tail = t0 - n_full * chunk_len
    return [chunk_len] * n_full + [tail]


def run_prefill(
    dec,
    params: dict,
    prompt: np.ndarray,
    *,
    block_size: int,
    chunk_len: int | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Prefill one prompt and cut the cache into pool-shaped blocks.

    Returns (k_blocks, v_blocks, logits_row): [L, n_blocks, Hkv, bs,
    Dh] stacks covering rows 0..t0-1 (tail rows beyond t0 zero-padded
    — the decode server masks them, and its first decode write lands
    at row t0), plus the [1, V] logits row of the LAST REAL prompt
    position, which the decode side samples the first token from."""
    import jax.numpy as jnp

    t0 = int(prompt.shape[1])
    max_len = dec.cfg.max_len
    if t0 >= max_len:
        raise ValueError(f"prompt of {t0} leaves no room under max_len {max_len}")
    cache = dec.init_cache(1)
    step = dec.make_step()
    prompt_j = jnp.asarray(prompt, jnp.int32)
    logits_row = None
    pos = 0
    chunks = prefill_schedule(t0, chunk_len)
    for ci, chunk in enumerate(chunks):
        ids = prompt_j[:, pos : pos + chunk]
        if ci == len(chunks) - 1:
            # Tail: pow2-pad like the monolithic admission (the pad
            # rows are garbage past t0, masked until the first decode
            # write overwrites row t0).
            pad = 1 << (chunk - 1).bit_length()
            pad = min(pad, max_len - pos)
            if pad > chunk:
                ids = jnp.concatenate(
                    [ids, jnp.zeros((1, pad - chunk), jnp.int32)], axis=1
                )
        logits, cache = step(params, cache, ids)
        logits_row = logits[:, chunk - 1, :]
        pos += chunk
    L = dec.cfg.num_layers
    hkv = dec.cfg.kv_heads
    dh = dec.cfg.dim // dec.cfg.num_heads
    n_blocks = -(-t0 // block_size)
    # Host transfer of the finished cache — the whole point of the
    # worker: these rows ship to the decode host instead of living
    # here.
    k = np.asarray(cache["k"])[:, 0, :, :t0, :]  # [L, Hkv, t0, Dh]
    v = np.asarray(cache["v"])[:, 0, :, :t0, :]
    row_pad = n_blocks * block_size - t0
    if row_pad:
        k = np.pad(k, ((0, 0), (0, 0), (0, row_pad), (0, 0)))
        v = np.pad(v, ((0, 0), (0, 0), (0, row_pad), (0, 0)))
    k_blocks = k.reshape(L, hkv, n_blocks, block_size, dh).transpose(
        0, 2, 1, 3, 4
    )
    v_blocks = v.reshape(L, hkv, n_blocks, block_size, dh).transpose(
        0, 2, 1, 3, 4
    )
    return (
        np.ascontiguousarray(k_blocks),
        np.ascontiguousarray(v_blocks),
        np.asarray(logits_row),
    )


# analysis: domain(transport) one worker session per thread; all state is session-local, results cross by wire only
def serve_prefill(
    listen_port: int = 0,
    *,
    listen_host: str = "127.0.0.1",
    accept_timeout_s: float = 120.0,
    read_timeout_s: float | None = None,
    connect_timeout_s: float = 30.0,
    announce=None,
    fail_after_requests: int | None = None,
) -> int:
    """Run one prefill-worker session to completion; returns requests
    served. `announce(port)` fires once the listen socket is bound
    (drivers/tests learn the ephemeral port). Architecture, weights
    and every prompt arrive over the wire — the worker process needs
    no local model state at all."""
    recv = ArrayReceiver(
        listen_port,
        host=listen_host,
        accept_timeout_s=accept_timeout_s,
        read_timeout_s=read_timeout_s,
    )
    if announce is not None:
        announce(recv.port)
    obs = DisaggMetrics("prefill")
    sender = None
    count = 0
    try:
        it = iter(recv)
        hello = wire.expect_hello(it)
        dec = wire.decoder_from_wire(wire.expect_blob(it, "decoder"))
        params = wire.read_params(it)
        block_size = int(hello["block_size"])
        chunk_len = hello.get("chunk_len")
        log.info(
            "prefill worker ready: %d layers, block_size=%d, "
            "results -> %s:%d",
            dec.cfg.num_layers,
            block_size,
            hello["result_host"],
            hello["result_port"],
        )
        sender = ArraySender(
            hello["result_host"],
            hello["result_port"],
            compress=hello.get("compress", True),
            level=hello.get("level", 3),
            quantize=hello.get("quantize"),
            connect_timeout_s=connect_timeout_s,
        )
        while True:
            req = wire.read_blob(it)
            if req is None:
                break  # clean STOP from the dispatcher
            if req.get("kind") != "prefill":
                raise wire.TransportError(
                    f"expected 'prefill' blob, got {req.get('kind')!r}"
                )
            prompt = wire._next_frame(it, "prompt frame")
            k_blocks, v_blocks, logits_row = run_prefill(
                dec,
                params,
                np.asarray(prompt)[None]
                if np.asarray(prompt).ndim == 1
                else np.asarray(prompt),
                block_size=block_size,
                chunk_len=chunk_len,
            )
            wire.send_kv_payload(
                sender,
                wire.KVPayload(
                    rid=int(req["rid"]),
                    t0=int(np.asarray(prompt).shape[-1]),
                    k=k_blocks,
                    v=v_blocks,
                    logits=logits_row,
                ),
                obs=obs,
            )
            count += 1
            if (
                fail_after_requests is not None
                and count >= fail_after_requests
            ):
                # Simulated crash: kill both sockets with no STOP —
                # the decode side must see a mid-stream peer death.
                log.info(
                    "prefill worker: injected failure after %d "
                    "request(s)",
                    count,
                )
                sender._sock.close()
                sender = None
                return count
        sender.close()
        sender = None
        return count
    finally:
        if sender is not None:
            sender.close()
        recv.close()


def main(argv: list[str] | None = None) -> None:
    import argparse

    from defer_tpu.utils.platform import honor_env_platform

    honor_env_platform()

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--listen", type=int, default=5100)
    ap.add_argument("--listen-host", default="0.0.0.0")
    ap.add_argument("--accept-timeout", type=float, default=120.0)
    ap.add_argument(
        "--read-timeout",
        type=float,
        default=None,
        help="per-recv timeout on the dispatch stream (None = block)",
    )
    args = ap.parse_args(argv)
    n = serve_prefill(
        args.listen,
        listen_host=args.listen_host,
        accept_timeout_s=args.accept_timeout,
        read_timeout_s=args.read_timeout,
        announce=lambda p: print(f"LISTENING {p}", flush=True),
    )
    print(f"DONE {n}", flush=True)


if __name__ == "__main__":
    main()
