"""Decode-side KV ingest: receive payloads, seat them in the pool.

`KVBlockIngest` owns the result stream from a prefill worker
(`disagg/prefill_worker.py`) and splits the work across two threads by
MUTATION DOMAIN, not by convenience:

  * the DRAIN thread does transport work only — it iterates
    `wire.iter_kv_payloads`, validates each payload against the decode
    server's geometry, and parks it in a `batching.TimedQueue`. It
    never touches the pool.
  * the SERVING thread (whoever runs the decode loop) calls
    `pump()` between ticks: pop parked payloads — timing their queue
    wait into `defer_kv_ingest_wait_seconds` — and hand each to
    `PagedDecodeServer.deliver_kv`. Every pool/block-table mutation
    therefore stays on the serving thread, the same single-writer
    discipline the server's own admission path relies on.

The same split carries the quantized pool (runtime/paged.py
`kv_dtype="int8"`) for free: payloads stay in the wire's compute
dtype all the way to `deliver_kv`, and the requantize happens inside
`_admit`'s jitted scatter on the serving thread — the drain thread
never needs to know the pool dtype. The host-RAM spill tier
(`runtime/paged.py::HostKVSpill`) runs this exact mutation-domain
split in the other direction: its drain thread does device->host
copies only, while pool revival stays on the serving thread.

Speculative decode (`spec_k>0` on the decode server) rides the same
path untouched: the wire carries TARGET K/V only, and the serving
thread's `_admit_prefilled` seeds the DRAFT lane by re-prefilling it
locally from the prompt ids after the delivered blocks seat — the
ingest layer never sees draft state.

Failure protocol (the retry seam `disagg/api.py` drives): a transport
death flips `failed` and parks the drain thread; the orchestrator
drops the dead peer (`receiver.next_peer()`), respawns a worker,
re-dispatches whatever is still undelivered, then `resume()`s the
drain thread onto the fresh connection. Payload delivery is atomic
(wire.py), so "undelivered" is exactly the set to re-request — no
double-seating, no holes.
"""

from __future__ import annotations

import queue as queue_mod
import threading
from typing import Any

from defer_tpu.disagg import wire
from defer_tpu.obs.serving import DisaggMetrics
from defer_tpu.runtime.batching import TimedQueue
from defer_tpu.runtime.transport import ArrayReceiver, TransportError
from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)


class IngestError(RuntimeError):
    """A payload failed validation — a protocol/config skew, not a
    transient transport fault; retrying the worker won't fix it."""


class KVBlockIngest:
    """Drain one worker result stream into a PagedDecodeServer."""

    def __init__(
        self,
        server: Any,
        receiver: ArrayReceiver,
        *,
        obs: DisaggMetrics | None = None,
    ):
        self.server = server
        self.receiver = receiver
        self.obs = obs if obs is not None else DisaggMetrics("decode")
        self._queue = TimedQueue(self.obs.ingest_wait)
        self.delivered: set[int] = set()
        self.failed = threading.Event()
        self.error: BaseException | None = None
        self.eof = threading.Event()
        self._resume = threading.Event()
        self._closed = False
        self._thread: threading.Thread | None = None

    # -- drain thread -----------------------------------------------------

    def start(self) -> None:
        """Start the drain thread. Must run BEFORE the worker is
        dispatched: the thread performs the blocking accept the
        worker's result connection lands on."""
        self._thread = threading.Thread(
            target=self._drain_loop, name="kv-ingest", daemon=True
        )
        self._thread.start()

    # analysis: domain(drain) owns the blocking receive; payloads park in _queue for the serving thread to pump
    def _drain_loop(self) -> None:
        while not self._closed:
            try:
                for payload in wire.iter_kv_payloads(
                    self.receiver, obs=self.obs
                ):
                    self._validate(payload)
                    self._queue.put(payload)
                self.eof.set()
                return
            except TransportError as e:
                # analysis: ignore[cross-domain-write] error/failed are an Event-mediated handoff: write error THEN set failed; readers check failed first
                self.error = e
                self.failed.set()
            except Exception as e:  # noqa: BLE001 — surfaced to the
                # orchestrator; a validation/shape error must not die
                # silently on a daemon thread
                # analysis: ignore[cross-domain-write] same Event-mediated handoff as the TransportError arm
                self.error = e
                self.failed.set()
                return
            # Transport fault: park until the orchestrator has rewired
            # the session (next_peer + respawned worker), then drain
            # the fresh connection.
            self._resume.wait()
            self._resume.clear()

    def _validate(self, payload: wire.KVPayload) -> None:
        srv = self.server
        cfg = srv.dec.cfg
        if payload.rid not in srv.pending_prefilled:
            raise IngestError(
                f"payload for unknown/already-admitted rid {payload.rid}"
            )
        t0 = srv.pending_prefilled[payload.rid]["prompt"].shape[1]
        if payload.t0 != t0:
            raise IngestError(
                f"payload t0 {payload.t0} != submitted prompt length "
                f"{t0} for rid {payload.rid}"
            )
        expect = (
            cfg.num_layers,
            -(-t0 // srv.bs),
            cfg.kv_heads,
            srv.bs,
            cfg.dim // cfg.num_heads,
        )
        if tuple(payload.k.shape) != expect:
            raise IngestError(
                f"payload K shape {tuple(payload.k.shape)} != "
                f"{expect} — worker and server disagree on model "
                f"geometry or block_size"
            )

    # -- serving thread ---------------------------------------------------

    # analysis: domain(serving) the pop half of the park/pump handoff
    def pump(self) -> int:
        """Pop every parked payload and deliver it to the server
        (serving-thread-only, see module docstring). Returns payloads
        delivered. Raises the drain thread's error if it was fatal
        (IngestError); transport faults are left for the orchestrator
        to read via `failed`."""
        n = 0
        while True:
            try:
                payload = self._queue.pop(timeout=0)
            except queue_mod.Empty:
                break
            self.server.deliver_kv(
                payload.rid, payload.k, payload.v, payload.logits
            )
            self.delivered.add(payload.rid)
            n += 1
        if self.failed.is_set() and isinstance(self.error, IngestError):
            raise self.error
        return n

    def undelivered(self) -> list[int]:
        """Rids submitted as prefilled but not yet handed to the
        server — the set a retry must re-request. Call after pump():
        a payload parked in the queue is not yet delivered."""
        return [
            rid
            for rid in self.server._prefilled_order
            if rid not in self.delivered
        ]

    # analysis: domain(serving) orchestrator-side rewire path
    def resume(self) -> None:
        """Un-park the drain thread onto a rewired connection."""
        # analysis: ignore[cross-domain-write] the reverse leg of the Event handoff: drain is parked on _resume, so it cannot race this clear
        self.error = None
        self.failed.clear()
        self._resume.set()

    def close(self) -> None:
        self._closed = True
        self._resume.set()
