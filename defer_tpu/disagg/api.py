"""`serve_disagg()`: disaggregated prefill/decode serving, one call.

Runs a `PagedDecodeServer` locally and ships every request's prefill
to a prefill worker (`disagg/prefill_worker.py`) over the transport
seam; finished KV blocks stream back through `disagg/ingest.py`
straight into the paged pool. Greedy outputs are token-identical to
monolithic `serve_paged` (the worker's default prefill schedule is
bit-compatible — prefill_worker.py's parity contract), and with
`prefix_cache=True` the ingested blocks register in the radix cache,
so requests prefilled on ANOTHER HOST seed local prefix sharing.

Session lifecycle (ordering matters — each step unblocks the next):

    1. bind the result receiver (ephemeral port)
    2. start the ingest drain thread (it owns the blocking accept)
    3. spawn the worker (it binds and announces its dispatch port)
    4. dispatch hello/decoder/params + every request
    5. decode loop: pump ingest -> admit -> tick
    6. worker death mid-stream: drop peer, respawn, re-dispatch the
       undelivered tail (bounded by `worker_retries`)

Default worker placement is an in-process thread — the loopback proof
and the single-host split. Pass `spawn_worker` to place it anywhere
else (another process/host): it must return (host, port) of a
listening `serve_prefill`.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Any

import jax

from defer_tpu.disagg import wire
from defer_tpu.disagg.ingest import IngestError, KVBlockIngest
from defer_tpu.disagg.prefill_worker import serve_prefill
from defer_tpu.obs.serving import DisaggMetrics, ServerStats
from defer_tpu.runtime.paged import PagedDecodeServer
from defer_tpu.runtime.transport import ArrayReceiver, ArraySender, TransportError
from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)


def _thread_worker_spawner(**serve_kwargs):
    """Default spawn_worker: serve_prefill on an in-process daemon
    thread, ephemeral port. Returns ("127.0.0.1", port) once the
    worker is listening."""

    def spawn() -> tuple[str, int]:
        ports: "queue_mod.Queue[int]" = queue_mod.Queue()
        t = threading.Thread(
            target=serve_prefill,
            kwargs={
                "listen_port": 0,
                "announce": ports.put,
                **serve_kwargs,
            },
            name="prefill-worker",
            daemon=True,
        )
        t.start()
        return "127.0.0.1", ports.get(timeout=30.0)

    return spawn


class _Session:
    """One worker session: the dispatch sender plus what was sent."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        result_port: int,
        dec,
        params,
        block_size: int,
        chunk_len: int | None,
        compress: bool,
        level: int,
        quantize: str | None,
        connect_timeout_s: float,
    ):
        self.sender = ArraySender(
            host,
            port,
            compress=compress,
            level=level,
            quantize=quantize,
            connect_timeout_s=connect_timeout_s,
        )
        self.dispatch_bytes = wire.send_hello(
            self.sender,
            result_host="127.0.0.1",
            result_port=result_port,
            block_size=block_size,
            chunk_len=chunk_len,
        )
        self.dispatch_bytes += wire.send_blob(
            self.sender,
            {"kind": "decoder", "version": wire.WIRE_VERSION,
             **wire.decoder_to_wire(dec)},
        )
        self.dispatch_bytes += wire.send_params(self.sender, params)

    def send_request(self, rid: int, prompt) -> None:
        self.dispatch_bytes += wire.send_prefill_request(
            self.sender, rid, prompt
        )

    def close(self) -> None:
        self.sender.close()


def serve_disagg(
    dec: Any,
    params: dict,
    requests: list[tuple[jax.Array, int]],
    *,
    num_blocks: int,
    block_size: int = 16,
    max_batch: int = 4,
    eos_id: int | None = None,
    prefix_cache: bool = False,
    attention: str = "gathered",
    kv_dtype: str = "fp",
    decode_window: int = 1,
    spec_k: int = 0,
    spec_draft: Any = None,
    spec_params: dict | None = None,
    sampling: list | None = None,
    stop: list | None = None,
    quantize: str | None = None,
    compress: bool = True,
    level: int = 3,
    chunk_len: int | None = None,
    worker_retries: int = 1,
    spawn_worker: Any = None,
    server: PagedDecodeServer | None = None,
    accept_timeout_s: float = 60.0,
    read_timeout_s: float | None = 60.0,
    connect_timeout_s: float = 30.0,
    constraints: dict | None = None,
) -> tuple[list[jax.Array], dict]:
    """Disaggregated serving; same contract as `serve_paged` (outputs
    in submission order + ServerStats) with the prefill phase running
    on a worker. `quantize="int8"` turns on lossy KV transfer (codec
    SCHEME_Q8; the logits row stays lossless either way — a lossy row
    would fork the first token). `server=` reuses an existing
    PagedDecodeServer so ingested prefix blocks survive into later
    local serving (cross-host prefix warm-up). `worker_retries` bounds
    mid-stream worker replacements before giving up.

    `kv_dtype="int8"` stores the decode pool quantized: `deliver_kv`'s
    jitted scatter requantizes the decoded wire blocks on landing, so
    a Q8 transfer (`quantize="int8"`) feeding an int8 pool never holds
    a widened copy beyond the ingest staging buffer — the wire format
    itself is unchanged.

    `spec_k>0` (with `spec_draft`/`spec_params`) speculates on the
    decode side: the worker ships TARGET K/V only, and each prefilled
    admission re-prefills the draft lane locally from the prompt ids
    (PagedDecodeServer._admit_prefilled) — the draft's prefill is the
    cheap side of the compute asymmetry the disagg split exists for,
    so recompute beats shipping a second KV stream. Greedy outputs
    stay token-identical to the non-speculative split.

    `constraints={name: TokenDFA}` registers compiled grammars on the
    DECODE side (defer_tpu/constrain/; per-request opt-in via
    `SamplingParams(constraint="name")`) — prefill ships plain K/V, so
    the worker needs no DFA tables."""
    srv = server
    if srv is None:
        srv = PagedDecodeServer(
            dec,
            params,
            num_blocks=num_blocks,
            block_size=block_size,
            max_batch=max_batch,
            eos_id=eos_id,
            prefix_cache=prefix_cache,
            attention=attention,
            kv_dtype=kv_dtype,
            decode_window=decode_window,
            spec_k=spec_k,
            spec_draft=spec_draft,
            spec_params=spec_params,
            constraints=constraints,
        )
    samps = sampling or [None] * len(requests)
    stops = stop or [None] * len(requests)
    if len(samps) != len(requests) or len(stops) != len(requests):
        raise ValueError(
            "sampling/stop must have one entry per request when given"
        )
    obs = DisaggMetrics("decode")
    recv = ArrayReceiver(
        0,
        host="127.0.0.1",
        accept_timeout_s=accept_timeout_s,
        read_timeout_s=read_timeout_s,
    )
    if spawn_worker is None:
        spawn_worker = _thread_worker_spawner(
            read_timeout_s=read_timeout_s,
            connect_timeout_s=connect_timeout_s,
        )
    ingest = KVBlockIngest(srv, recv, obs=obs)
    session: _Session | None = None
    restarts = 0
    dispatch_bytes_total = 0

    def open_session() -> _Session:
        host, port = spawn_worker()
        return _Session(
            host,
            port,
            result_port=recv.port,
            dec=srv.dec,
            params=srv.params,
            block_size=srv.bs,
            chunk_len=chunk_len,
            compress=compress,
            level=level,
            quantize=quantize,
            connect_timeout_s=connect_timeout_s,
        )

    try:
        # Drain thread first: it owns the blocking accept the worker's
        # result connection lands on (module docstring, step 2).
        ingest.start()
        session = open_session()
        rids = [
            srv.submit_prefilled(p, s, sampling=sp, stop=st)
            for (p, s), sp, st in zip(requests, samps, stops)
        ]
        for rid, (p, _) in zip(rids, requests):
            session.send_request(rid, p)

        while srv.pending_prefilled or srv.pending or any(srv.slots):
            if ingest.failed.is_set():
                err = ingest.error
                if isinstance(err, IngestError):
                    # Validation failure = protocol/config skew; a
                    # fresh worker would ship the same bad payload.
                    raise err
                if restarts >= worker_retries:
                    raise TransportError(
                        f"prefill worker died and {restarts} "
                        f"restart(s) were already spent: {err}"
                    )
                restarts += 1
                obs.worker_restarts.inc()
                log.warning(
                    "prefill worker session died (%s); restarting "
                    "(%d/%d)",
                    err,
                    restarts,
                    worker_retries,
                )
                # Deliver everything the dead session DID land before
                # computing the re-request set (the drain thread is
                # parked, so the queue is quiescent): a payload parked
                # but not yet pumped is delivered work, and
                # re-requesting it would hand the drain thread a
                # duplicate for an already-admitted rid — a fatal
                # validation error.
                ingest.pump()
                missing = ingest.undelivered()
                dispatch_bytes_total += session.dispatch_bytes
                session.close()
                # Drop the dead result peer BEFORE resuming the drain
                # thread, so its fresh accept can only land the NEW
                # worker's connection.
                recv.next_peer()
                ingest.resume()
                session = open_session()
                by_rid = dict(zip(rids, requests))
                for rid in missing:
                    session.send_request(rid, by_rid[rid][0])
            ingest.pump()
            srv._admit()
            if any(s is not None for s in srv.slots):
                srv._tick()
            else:
                # Nothing seated: we're waiting on the wire, not the
                # device — yield instead of spinning admit hot.
                time.sleep(1e-3)
        done = srv.done
    finally:
        if session is not None:
            dispatch_bytes_total += session.dispatch_bytes
            session.close()
        ingest.close()
        recv.close()

    n_req = max(len(requests), 1)
    stats = ServerStats.snapshot(
        srv.obs.registry,
        ticks=srv.ticks,
        attention=srv.attention,
        peak_blocks=srv.blocks_peak,
        pool_blocks=srv.num_blocks - 1,
        block_size=srv.bs,
        decode_window=srv.decode_window,
        host_dispatches=srv.dispatches,
        tokens_per_dispatch=(
            srv.window_tokens / srv.dispatches if srv.dispatches else 0.0
        ),
        cached_blocks=(
            srv.radix.cached_blocks if srv.radix is not None else 0
        ),
        prefill_tokens_saved=srv.prefill_tokens_saved,
        prefill_budget=srv.prefill_budget,
        prefill_stall_ticks=srv.prefill_stall_ticks_n,
        mixed_ticks=srv.mixed_ticks_n,
        mixed_prefill_tokens=srv.mixed_prefill_tokens_n,
        decode_stall_fraction=srv.decode_stall_fraction_last,
        kv_dtype=srv.kv_dtype,
        pool_bytes=srv.pool_bytes,
        spec_k=srv.spec_k,
        spec_rounds=srv.spec_rounds_n,
        spec_proposed=srv.spec_proposed_n,
        spec_accepted=srv.spec_accepted_n,
        spec_acceptance=(
            srv.spec_accepted_n / srv.spec_proposed_n
            if srv.spec_proposed_n
            else 0.0
        ),
        spec_draft_tokens=srv.spec_draft_tokens_n,
        disagg=True,
        quantize=quantize,
        kv_bytes_recv=recv.rx_frame_bytes,
        kv_bytes_recv_per_request=recv.rx_frame_bytes / n_req,
        dispatch_bytes_sent=dispatch_bytes_total,
        worker_restarts=restarts,
        constrained_tokens=srv.constrained_tokens_n,
        constraint_dead_ends=srv.constraint_dead_ends_n,
    )
    return [done[r] for r in rids], stats
