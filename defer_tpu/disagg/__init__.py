"""defer_tpu.disagg — disaggregated prefill/decode serving.

The DEFER deployment model (PAPER.md) applied to the two phases of LLM
inference: prefill is compute-bound, decode is cache-read-bound, so a
fleet serves better with the phases on SEPARATE nodes sized for each.
The seam between them is finished KV state, streamed as pool-shaped
blocks over the same host transport the pipeline runtime uses:

  * `wire`            — the versioned KV-block wire format
  * `prefill_worker`  — `serve_prefill()` + the
                        `python -m defer_tpu.disagg.prefill_worker` CLI
  * `ingest`          — `KVBlockIngest`, the decode-side drain that
                        seats received blocks in the paged pool
  * `api`             — `serve_disagg()`, the one-call split-serving
                        entrypoint (token-identical greedy vs
                        monolithic `serve_paged`)

See ARCHITECTURE.md "Disaggregated serving".
"""

from defer_tpu.disagg.api import serve_disagg
from defer_tpu.disagg.ingest import IngestError, KVBlockIngest
from defer_tpu.disagg.prefill_worker import (
    prefill_schedule,
    run_prefill,
    serve_prefill,
)
from defer_tpu.disagg.wire import KVPayload, PrefixPayload, WIRE_VERSION

__all__ = [
    "IngestError",
    "KVBlockIngest",
    "KVPayload",
    "PrefixPayload",
    "WIRE_VERSION",
    "prefill_schedule",
    "run_prefill",
    "serve_disagg",
    "serve_prefill",
]
