"""Package CLI: `python -m defer_tpu <command>`.

The reference has no tooling surface at all (drivers are edited by
hand, reference src/test.py:13-28); these subcommands cover the
workflows its users actually performed manually:

    info         topology + registered models/ops
    partition    compute a cut list (the reference documents its own
                 in a comment, src/test.py:24-28)
    roofline     analytic perf triage for a zoo model
    serve-stage  run a remote stage worker (the `node.py` analogue)
"""

from __future__ import annotations

import argparse
import json

from defer_tpu.utils.platform import honor_env_platform as _init_platform


def cmd_info(args: argparse.Namespace) -> None:
    _init_platform()
    from defer_tpu.models import model_names
    from defer_tpu.ops.registry import op_names
    from defer_tpu.utils.platform import BackendInitHang, devices_with_deadline

    try:
        devices_with_deadline(60.0)
        from defer_tpu.parallel.mesh import describe_topology

        topology: dict = describe_topology()
    except BackendInitHang as e:
        # A wedged device transport must not hang the CLI forever.
        topology = {"error": str(e)}
    print(json.dumps(
        {
            "topology": topology,
            "models": model_names(),
            "num_ops": len(op_names()),
        },
        indent=2,
    ))


def cmd_partition(args: argparse.Namespace) -> None:
    _init_platform()
    import jax

    from defer_tpu.graph.partition import partition
    from defer_tpu.models import get_model
    from defer_tpu.utils.flops import balanced_cuts, flops_by_node

    model = get_model(args.model)
    params = model.init(jax.random.key(0))
    shape = (1, *model.input_shape)
    if args.auto:
        cuts = balanced_cuts(
            model.graph,
            params,
            shape,
            args.stages,
            model.cut_candidates or None,
            input_dtype=model.input_dtype,
        )
    else:
        cuts = model.default_cuts(args.stages)
    stages = partition(model.graph, cuts) if cuts else [model.graph]
    per = flops_by_node(
        model.graph, params, shape, input_dtype=model.input_dtype
    )
    total = sum(per.values())
    print(f"{args.model}: {args.stages} stages, cuts = {list(cuts)}")
    for i, s in enumerate(stages):
        fl = sum(per[n.name] for n in s.nodes if n.op != "input")
        print(
            f"  stage {i}: {len(s.nodes):4d} nodes, "
            f"{fl / 1e9:8.2f} GFLOP ({fl / total:5.1%})"
        )


def cmd_roofline(args: argparse.Namespace) -> None:
    _init_platform()
    import jax
    import jax.numpy as jnp

    from defer_tpu.config import DeferConfig
    from defer_tpu.models import get_model
    from defer_tpu.parallel.pipeline import cast_params_to_storage
    from defer_tpu.utils.roofline import format_report, roofline_report

    model = get_model(args.model)
    params = model.init(jax.random.key(0))
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    if model.input_dtype is not None and not jnp.issubdtype(
        model.input_dtype, jnp.floating
    ):
        in_dtype = model.input_dtype  # token ids stay integral
    else:
        in_dtype = dtype
    params = cast_params_to_storage(
        params, DeferConfig(compute_dtype=dtype)
    )
    kind = args.device_kind
    if kind is None:
        devs = jax.devices()
        kind = devs[0].device_kind if devs else "unknown"
    print(
        format_report(
            roofline_report(
                model.graph,
                params,
                (args.batch, *model.input_shape),
                kind,
                input_dtype=in_dtype,
                top=args.top,
            )
        )
    )


def cmd_serve_stage(args: argparse.Namespace) -> None:
    from defer_tpu.runtime.remote_stage import main as serve_main

    argv = ["--listen", str(args.listen), "--next", args.next]
    if args.accept_timeout is not None:
        argv += ["--accept-timeout", str(args.accept_timeout)]
    if args.handoff_timeout is not None:
        argv += ["--handoff-timeout", str(args.handoff_timeout)]
    if args.expect_peer:
        argv += ["--expect-peer"]
    serve_main(argv)


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(prog="defer_tpu", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    sub.add_parser("info", help="topology + registered models/ops")

    p = sub.add_parser("partition", help="compute and describe a cut list")
    p.add_argument("model")
    p.add_argument("--stages", type=int, default=2)
    p.add_argument(
        "--auto",
        action="store_true",
        help="FLOPs-balanced cuts instead of the model's defaults",
    )

    p = sub.add_parser("roofline", help="analytic perf triage")
    p.add_argument("model")
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--dtype", choices=["bfloat16", "float32"],
                   default="bfloat16")
    p.add_argument(
        "--device-kind",
        default=None,
        help="e.g. 'TPU v5 lite'; default: the first visible device",
    )
    p.add_argument("--top", type=int, default=8)

    p = sub.add_parser(
        "serve-stage", help="run a remote stage worker (node.py analogue)"
    )
    p.add_argument("--listen", type=int, default=5000)
    p.add_argument("--next", required=True)
    p.add_argument("--accept-timeout", type=float, default=None)
    p.add_argument("--handoff-timeout", type=float, default=None)
    p.add_argument(
        "--expect-peer",
        action="store_true",
        help="mid-chain worker: a missing upstream activation peer is "
        "a hard error, not a clean zero-work exit",
    )

    args = ap.parse_args(argv)
    {
        "info": cmd_info,
        "partition": cmd_partition,
        "roofline": cmd_roofline,
        "serve-stage": cmd_serve_stage,
    }[args.cmd](args)


if __name__ == "__main__":
    main()
