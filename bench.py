#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line on stdout; diagnostics on
stderr. The JSON line is always emitted — on failure it carries an
`error` field instead of a number.

Process structure: by default this file is a SUPERVISOR that re-execs
itself as a measurement child (DEFER_BENCH_CHILD=1) and enforces two
deadlines — total wall clock and max seconds between section
completions. The child appends a JSON snapshot of its result-so-far to
$DEFER_BENCH_SNAPSHOT after every section, so if any single section
wedges the device transport (observed: a Mosaic kernel compile hanging
the tunneled-TPU backend — killable only from outside the process),
the supervisor kills the child and still emits the already-measured
headline instead of timing out with nothing.

Protocol (mirrors the reference's measurement design, reference
src/test.py:30-41 and src/local_infer.py:16-23, adapted to TPU):

  * headline metric: ResNet50 images/sec streamed through the DEFER
    pipeline across every visible TPU device (one stage per device;
    on a 1-chip host that is a single stage).
  * baseline: the paper's comparison point is an 8-node CPU chain that
    beat one CPU device by +53% (reference README.md:12). We measure a
    single-CPU-device ResNet50 loop with this same framework in a
    subprocess, and BASELINE.json's north star is >= 8x that.
    vs_baseline = ours / (8 x single-CPU images/sec), so >= 1.0 beats
    the north star.
  * microbatch size is a tunable of our pipeline (the reference streams
    batch-1 frames); we sweep and report the best, with the sweep on
    stderr.
  * mfu: achieved FLOP/s over the chip's bf16 peak, from analytic IR
    FLOPs (utils/flops.py) — the honesty check raw images/sec lacks.
  * extras: a multi-STAGE pipeline datapoint (round-robin on one chip —
    the reference's headline is pipelined throughput, reference
    src/test.py:30-41) and a single-chip SPMD BERT-base datapoint.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import traceback


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


CHILD_ENV = "DEFER_BENCH_CHILD"
SNAPSHOT_ENV = "DEFER_BENCH_SNAPSHOT"


def snapshot(result: dict) -> None:
    """Append the result-so-far to the supervisor's snapshot file (one
    JSON object per line; last line wins). Fsync so the line survives
    the child being SIGKILLed mid-section."""
    path = os.environ.get(SNAPSHOT_ENV)
    if not path:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps(result) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError as e:  # noqa: PERF203 — diagnostics only
        log(f"snapshot write failed: {e}")


def read_snapshot(path: str) -> dict | None:
    """Last complete JSON line of the snapshot file, or None."""
    try:
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError:
        return None
    for ln in reversed(lines):
        try:
            return json.loads(ln)
        except json.JSONDecodeError:
            continue
    return None


def _clear_backends() -> None:
    """Drop cached XLA backends so a retry truly re-attempts plugin
    init (a failed TPU init can leave a CPU-only cache behind, which
    would silently turn the TPU headline into a CPU run)."""
    import jax

    try:
        jax.extend.backend.clear_backends()
    except Exception:  # noqa: BLE001
        try:
            from jax._src import xla_bridge

            xla_bridge._clear_backends()
        except Exception:  # noqa: BLE001
            pass


def _want_cpu() -> bool:
    want = os.environ.get("JAX_PLATFORMS", "")
    return want.split(",")[0].strip() == "cpu" if want else False


def _is_init_error(err: str | None) -> bool:
    """Did this attempt die without a headline, for an environmental
    reason a fresh subprocess might not hit? Backend-init failures are
    process-local (a hung probe thread wedges only its own process),
    and tunneled-TPU transport deaths (the remote-compile endpoint
    refusing connections mid-run — observed when the axon tunnel
    restarts) heal on the tunnel's side; both deserve the
    TPU-reacquisition loop rather than an immediate CPU fallback."""
    if not err:
        return False
    return any(
        s in err
        for s in (
            "BackendInitHang",
            "backend init",
            "requested platform",
            "UNAVAILABLE",
            "Connection refused",
            "Connection Failed",
        )
    )


# The supervisor half of this file must stay import-light: jax /
# defer_tpu load only in functions the measurement CHILD reaches, so a
# broken install still produces an error JSON line instead of a bare
# import traceback. The bounded-init helpers live in
# defer_tpu/utils/platform.py and are imported lazily below.


def init_backend_with_retry(attempts: int = 3):
    """First backend use can fail transiently (remote TPU tunnel);
    retry with backoff instead of surfacing a stack trace as the
    round's headline artifact."""
    import jax

    from defer_tpu.utils.platform import (
        BackendInitHang,
        devices_with_deadline as _devices_with_deadline,
    )

    want = os.environ.get("JAX_PLATFORMS", "")
    want_cpu = _want_cpu()
    delay = 5.0
    for i in range(attempts):
        try:
            devs = _devices_with_deadline(180.0)
            if (
                not want_cpu
                and i < attempts - 1
                and all(d.platform == "cpu" for d in devs)
            ):
                # CPU was not explicitly requested but init produced
                # only CPU devices — on a TPU host that is a silent
                # plugin-init fallback; treat as failure and retry for
                # real. (A genuinely CPU-only run pays two quick
                # retries, then the last attempt accepts CPU.)
                raise RuntimeError(
                    f"requested platform {want or '<default>'!r} but got "
                    "CPU devices"
                )
            log(f"backend: {jax.default_backend()}, devices: {devs}")
            return devs
        except BackendInitHang:
            # A HUNG init leaves its thread inside xla_bridge holding
            # the module lock: every in-process retry (and
            # clear_backends itself) would block on it forever. Fail
            # now; main() falls back to a fresh CPU subprocess.
            raise
        except Exception as e:  # noqa: BLE001
            if i == attempts - 1:
                raise
            log(f"backend init failed ({e!r}); retrying in {delay:.0f}s")
            _clear_backends()
            time.sleep(delay)
            delay *= 3.0


def cpu_baseline_subprocess(duration_s: float = 6.0) -> float:
    """Single-CPU-device ResNet50 images/sec, measured in a fresh
    process (this process owns the TPU backend)."""
    code = (
        "import jax, json;"
        "jax.config.update('jax_platforms','cpu');"
        "from defer_tpu.api import run_local_inference;"
        "from defer_tpu.models import get_model;"
        f"r = run_local_inference(get_model('resnet50'), duration_s={duration_s});"
        "print(json.dumps(r))"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        timeout=600,
    )
    if out.returncode != 0:
        log(f"cpu baseline failed:\n{out.stderr[-2000:]}")
        return float("nan")
    return json.loads(out.stdout.strip().splitlines()[-1])["items_per_sec"]


def _measure(pipe, batch: int, target_s: float = 4.0) -> dict:
    import jax.numpy as jnp

    # Feed bf16 end-to-end: the host pipeline emits bf16
    # (imagenet_preprocess out_dtype), so no per-microbatch fp32->bf16
    # cast pass over HBM.
    x = jnp.ones((batch, 224, 224, 3), jnp.bfloat16)
    probe = pipe.throughput(x, num_microbatches=32)
    num_mb = max(32, int(32 * target_s / max(probe["seconds"], 1e-6)))
    return (
        probe if num_mb <= 32 else pipe.throughput(x, num_microbatches=num_mb)
    )


def bench_vit(devices) -> dict:
    """Single-chip ViT-S/16 streamed-pipeline throughput + MFU (the
    attention-era vision counterpart of the resnet50 headline)."""
    import jax
    import jax.numpy as jnp

    from defer_tpu.config import DeferConfig
    from defer_tpu.models import get_model
    from defer_tpu.parallel.mesh import pipeline_devices
    from defer_tpu.parallel.pipeline import Pipeline
    from defer_tpu.utils.flops import graph_flops, peak_flops

    model = get_model("vit_s16")
    params = model.init(jax.random.key(0))
    pipe = Pipeline(
        [model.graph],
        params,
        pipeline_devices(1, devices[:1]),
        DeferConfig(compute_dtype=jnp.bfloat16, max_inflight=64),
    )
    batch = 128
    stats = _measure(pipe, batch)
    fl = graph_flops(model.graph, params, (1, 224, 224, 3))
    peak = peak_flops(devices[0].device_kind)
    rec = {
        "images_per_sec": round(stats["items_per_sec"], 1),
        "batch": batch,
        "mfu": round(stats["items_per_sec"] * fl / peak, 4) if peak else None,
    }
    log(f"vit-s16 single-chip: {rec}")
    return rec


def bench_gpt_decode(devices) -> dict:
    """KV-cache decode: steady-state ms/token and tokens/sec for a
    GPT-2-small-shaped decoder (batch 8)."""
    from defer_tpu.parallel.transformer_stack import TransformerConfig

    return _bench_decode(
        devices,
        TransformerConfig(
            num_layers=12,
            dim=768,
            num_heads=12,
            ffn_dim=3072,
            vocab_size=32000,
            max_len=512,
            norm_style="pre",
        ),
        "gpt-small",
    )


def bench_llama_decode(devices) -> dict:
    """Llama-architecture decode (RMSNorm + rotary + GQA + SwiGLU) at
    ~1B scale: the modern serving shape, with the KV cache narrowed to
    the GQA head count."""
    from defer_tpu.models.llama import llama_config

    return _bench_decode(
        devices,
        llama_config(
            num_layers=16,
            dim=2048,
            num_heads=16,
            num_kv_heads=4,
            ffn_dim=5632,
            vocab_size=32000,
            max_len=512,
        ),
        "llama-1b-gqa",
        with_int8=True,
    )


def _bench_decode(devices, cfg, label: str, with_int8: bool = False) -> dict:
    import jax
    import jax.numpy as jnp

    from defer_tpu.models.gpt import GptDecoder, sample_token
    from defer_tpu.utils.roofline import peak_bandwidth

    dec = GptDecoder(cfg, compute_dtype=jnp.bfloat16)
    init = dec.init(jax.random.key(0))
    batch, prompt_len, steps = 8, 128, 64
    step = dec.make_step()
    ids = jax.random.randint(
        jax.random.key(1), (batch, prompt_len), 0, cfg.vocab_size
    )
    dh = cfg.dim // cfg.num_heads
    # The decode step contracts over the FULL static [.., max_len, ..]
    # cache buffer every token (masking happens after the read), so
    # that is the KV traffic — not just the live prefix.
    kv_bytes = (
        2 * cfg.num_layers * batch * cfg.kv_heads * cfg.max_len * dh * 2
    )
    bw = peak_bandwidth(devices[0].device_kind)

    def measure(params) -> dict:
        # Warm both compiled shapes on a throwaway cache so the
        # timings measure compute, not XLA compilation.
        warm_cache = dec.init_cache(batch)
        _, warm_cache = step(params, warm_cache, ids)
        _, warm_cache = step(
            params, warm_cache, jnp.zeros((batch, 1), ids.dtype)
        )
        # Block on the SECOND step's cache so no warm-up work is
        # still queued when the prefill timer starts.
        jax.block_until_ready(warm_cache)
        rng = jax.random.key(2)
        cache = dec.init_cache(batch)
        t0 = time.perf_counter()
        logits, cache = step(params, cache, ids)
        logits.block_until_ready()
        prefill_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(steps):
            nxt, rng = sample_token(logits[:, -1:], rng, 0.0)
            logits, cache = step(params, cache, nxt.astype(ids.dtype))
        logits.block_until_ready()
        per_tok = (time.perf_counter() - t0) / steps
        # Decode is HBM-read bound: per step the chip reads every
        # weight once (shared by the batch) plus the live KV prefix.
        # Achieved GB/s against HBM peak is decode's MFU analogue.
        param_bytes = sum(
            a.size * a.dtype.itemsize
            for a in jax.tree_util.tree_leaves(params)
        )
        achieved = (param_bytes + kv_bytes) / per_tok
        return {
            "ms_per_token": round(per_tok * 1e3, 3),
            "tokens_per_sec": round(batch / per_tok, 1),
            "batch": batch,
            "prefill_s": round(prefill_s, 3),
            "achieved_gbps": round(achieved / 1e9, 1),
            "hbm_frac": round(achieved / bw, 3) if bw else None,
        }

    # Serving storage: bf16 params (decode reads every weight per
    # token; fp32 storage would double the HBM traffic that bounds it).
    rec = measure(jax.device_put(dec.cast_params(init), devices[0]))
    log(f"{label} decode single-chip: {rec}")
    if with_int8:
        # Weight-only int8 (models/quant.py): half the weight bytes
        # again; quantize from the fp32 init for faithful scales.
        from defer_tpu.models.quant import quantize_decoder_params

        qrec = measure(
            jax.device_put(quantize_decoder_params(init), devices[0])
        )
        qrec.pop("batch", None)
        rec["int8"] = qrec
        log(f"{label} int8 decode single-chip: {qrec}")
    return rec


def bench_decode_server(devices) -> dict:
    """Continuous batching (runtime/decode_server.py): a mixed stream
    of requests through 4 slots on the ~1B llama shape — the serving
    number a per-request loop cannot reach (`tick_sharing` = solo
    steps per batched weight read)."""
    import jax

    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import llama_config
    from defer_tpu.runtime.decode_server import DecodeServer

    import jax.numpy as jnp

    cfg = llama_config(
        num_layers=16,
        dim=2048,
        num_heads=16,
        num_kv_heads=4,
        ffn_dim=5632,
        vocab_size=32000,
        max_len=512,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.bfloat16)
    params = jax.device_put(
        dec.cast_params(dec.init(jax.random.key(0))), devices[0]
    )

    def requests():
        reqs = []
        for i in range(12):
            t0 = 16 + (i * 23) % 112
            steps = 16 + (i * 11) % 48
            prompt = jax.random.randint(
                jax.random.fold_in(jax.random.key(1), i),
                (1, t0),
                0,
                cfg.vocab_size,
            )
            reqs.append((prompt, steps))
        return reqs

    def run() -> tuple[float, Any]:
        srv = DecodeServer(dec, params, max_batch=4)
        rids = [srv.submit(p, s) for p, s in requests()]
        t0 = time.perf_counter()
        done = srv.run()
        jax.block_until_ready(done[rids[-1]])
        return time.perf_counter() - t0, srv

    run()  # compile pass (prefill buckets + tick shape)
    dt, srv = run()
    total = srv.solo_steps
    rec = {
        "requests": 12,
        "slots": 4,
        "tokens_per_sec": round(total / dt, 1),
        "ticks": srv.ticks,
        "tick_sharing": round(total / max(1, srv.ticks), 2),
    }
    log(f"decode server (llama-1b, continuous batching): {rec}")
    return rec


def bench_paged_server(devices) -> dict:
    """Paged-KV serving (runtime/paged.py): the decode-server workload
    through a block pool at a fraction of the flat-lane rows — the
    serving-memory headline (cache rows scale with request budgets,
    not slots x max_len) with throughput recorded alongside."""
    import jax
    import jax.numpy as jnp

    from defer_tpu import obs
    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import llama_config
    from defer_tpu.runtime.paged import serve_paged

    cfg = llama_config(
        num_layers=16,
        dim=2048,
        num_heads=16,
        num_kv_heads=4,
        ffn_dim=5632,
        vocab_size=32000,
        max_len=512,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.bfloat16)
    params = jax.device_put(
        dec.cast_params(dec.init(jax.random.key(0))), devices[0]
    )
    reqs = []
    for i in range(8):
        t0 = 16 + (i * 23) % 112
        steps = 16 + (i * 11) % 48
        prompt = jax.random.randint(
            jax.random.fold_in(jax.random.key(1), i),
            (1, t0),
            0,
            cfg.vocab_size,
        )
        reqs.append((prompt, steps))

    def run():
        # Zero the process registry so the latency distributions below
        # cover only this pass (the compile pass would skew TTFT).
        obs.reset()
        t0 = time.perf_counter()
        outs, stats = serve_paged(
            dec, params, reqs, num_blocks=49, block_size=16, max_batch=4
        )
        jax.block_until_ready(outs[-1])
        return time.perf_counter() - t0, stats

    run()  # compile pass
    dt, stats = run()
    total = sum(s for _, s in reqs)
    pool_rows = stats["pool_blocks"] * stats["block_size"]
    reg = obs.get_registry()
    lab = {"server": "paged"}
    ttft = reg.histogram("defer_ttft_seconds", labels=lab)
    itl = reg.histogram("defer_itl_seconds", labels=lab)
    rec = {
        "requests": len(reqs),
        "slots": 4,
        "tokens_per_sec": round(total / dt, 1),
        "pool_rows": pool_rows,
        "flat_rows": stats["flat_equivalent_rows"],
        "cache_mem_ratio": round(
            pool_rows / stats["flat_equivalent_rows"], 3
        ),
        "peak_blocks": stats["peak_blocks"],
        # Host-side dispatch latency (see ARCHITECTURE.md
        # "Observability" for the async-dispatch caveat).
        "ttft_p50_ms": round(1e3 * ttft.approx_quantile(0.5), 2),
        "itl_p50_ms": round(1e3 * itl.approx_quantile(0.5), 3),
        "tokens_counted": reg.value(
            "defer_tokens_generated_total", **lab
        ),
    }
    log(f"paged server (llama-1b, block pool): {rec}")
    return rec


def bench_paged_attention(devices) -> dict:
    """Paged-decode attention modes (scripts/bench_paged.py): the same
    request mix through gathered vs block-native attention, pricing
    tokens/sec and the per-tick K/V rows actually read. The ratio is
    the bandwidth story; the obs counters make it exact."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "scripts",
        "bench_paged.py",
    )
    spec = importlib.util.spec_from_file_location("bench_paged", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.run_microbench(devices)
    log(f"paged attention modes: {rec}")
    return rec


def bench_decode_window(devices) -> dict:
    """Fused decode windows (scripts/bench_paged.py): the same request
    mix served at decode_window = K for K in {1,4,8,16}, pricing host
    dispatches per token against tokens/sec. Dispatches-per-token
    falls toward 1/K; on dispatch-bound tiers the tokens/sec follows."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "scripts",
        "bench_paged.py",
    )
    spec = importlib.util.spec_from_file_location("bench_paged", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.run_window_sweep(devices)
    log(f"decode window sweep: {rec}")
    return rec


def bench_mixed_serving(devices) -> dict:
    """Mixed-mode continuous batching (scripts/bench_paged.py): the
    same request mix offered open-loop, served with stall-mode
    admission vs prefill_budget in {64,128,256,inf}, pricing the live
    slots' ITL p99 (where admission-prefill stalls land) against TTFT
    and the decode-stall fraction per budget."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "scripts",
        "bench_paged.py",
    )
    spec = importlib.util.spec_from_file_location("bench_paged", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.run_mixed_sweep(devices)
    log(f"mixed serving sweep: {rec}")
    return rec


def bench_speculative(devices) -> dict:
    """Paged speculative decoding (scripts/bench_paged.py): the same
    request mix served at spec_k in {0,2,4} crossed with the draft
    axis (self | trunc:L/2 | trunc:L/4 | width:1/2, built with
    models/transplant.py make_draft), pricing MEASURED acceptance,
    tokens/sec and dispatches-per-token per (draft, k) — the
    acceptance-vs-speedup frontier. The self-draft column isolates
    the dispatch-amortization term (acceptance 1.0); the truncated/
    pruned columns price what a real small draft pays."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "scripts",
        "bench_paged.py",
    )
    spec = importlib.util.spec_from_file_location("bench_paged", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.run_spec_sweep(devices)
    log(f"speculative sweep: {rec}")
    return rec


def bench_tp_serving(devices) -> dict:
    """Tensor-parallel paged serving (scripts/bench_paged.py): the
    same request mix on a {"model": m} mesh for m in {1,2,4,8},
    pricing tokens/sec and tokens-per-dispatch against per-shard KV
    rows read. Host dispatches per token must not move with m; KV rows
    per shard fall as 1/m — the mesh-labeled obs counters make both
    exact."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "scripts",
        "bench_paged.py",
    )
    spec = importlib.util.spec_from_file_location("bench_paged", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.run_tp_sweep(devices)
    log(f"tp serving sweep: {rec}")
    return rec


def bench_pp_serving(devices) -> dict:
    """Pipeline-parallel paged serving (scripts/bench_paged.py): the
    same request mix with the layer stack cut into S stages — one
    device and one KV-pool slice each — at M in-flight microbatch
    groups, for (S, M) in {1,2,4} x {2,4}. Prices tokens/sec against
    the MEASURED dispatch-schedule bubble fraction and per-stage
    occupancy; per-stage pool bytes must sum to ~the S=1 pool. The
    [contract.pp] budget gates the s4_m4 bubble fraction."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "scripts",
        "bench_paged.py",
    )
    spec = importlib.util.spec_from_file_location("bench_paged", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.run_pp_sweep(devices)
    log(f"pp serving sweep: {rec}")
    return rec


def bench_kv_quant(devices) -> dict:
    """KV quantization + spill tier (scripts/bench_paged.py): the same
    over-subscribed Zipf prefix mix served with a fp pool vs an
    int8+scales pool, spill tier on — pricing tokens/sec,
    resident-requests-per-pool-MiB (the capacity headline: int8 holds
    the same blocks in itemsize-fold fewer bytes) and the spill
    revival rate, with prefill tokens vs a no-spill baseline showing
    the rows revivals saved."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "scripts",
        "bench_paged.py",
    )
    spec = importlib.util.spec_from_file_location("bench_paged", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.run_kv_quant_sweep(devices)
    log(f"kv quant sweep: {rec}")
    return rec


def bench_constrain(devices) -> dict:
    """Constrained decoding (scripts/bench_paged.py +
    defer_tpu/constrain/): the same request mix served free vs
    regex-constrained vs JSON-schema-constrained — pricing the
    on-device DFA mask fold against the free baseline, the one-off
    host compile (regex -> char DFA -> token lift -> prune) and the
    mean fraction of the vocabulary the grammar removed per token."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "scripts",
        "bench_paged.py",
    )
    spec = importlib.util.spec_from_file_location("bench_paged", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.run_constrain_sweep(devices)
    log(f"constrain sweep: {rec}")
    return rec


def bench_disagg(devices) -> dict:
    """Disaggregated serving (scripts/bench_disagg.py): the same
    request mix through monolithic serve_paged and split serve_disagg
    (prefill worker over loopback), pricing tokens/sec and TTFT
    against the KV bytes shipped per request — lossless vs int8
    transfer. The split/monolithic ratio and the wire bytes are the
    headline; off-TPU the absolute throughput is noise."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "scripts",
        "bench_disagg.py",
    )
    spec = importlib.util.spec_from_file_location("bench_disagg", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.run_microbench(devices)
    log(f"disaggregated serving: {rec}")
    return rec


def bench_fleet(devices) -> dict:
    """Fleet serving (scripts/bench_fleet.py): a bursty, prefix-shared
    request mix over N replica paged servers under prefix-aware vs
    round-robin routing, plus an overload flood against a tight SLO.
    Headlines: the radix hit-rate gap between the two policies (the
    value of routing on cache locality) and shed rate with bounded
    queue-wait p99 under overload (graceful degradation)."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "scripts",
        "bench_fleet.py",
    )
    spec = importlib.util.spec_from_file_location("bench_fleet", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.run_microbench(devices)
    log(f"fleet serving: {rec}")
    return rec


def bench_bert(devices) -> dict:
    """Single-chip SPMD BERT-base forward throughput + MFU."""
    import jax
    import jax.numpy as jnp

    from defer_tpu.models.bert import SpmdBert
    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.parallel.transformer_stack import TransformerConfig
    from defer_tpu.utils.flops import peak_flops, transformer_flops

    cfg = TransformerConfig(
        num_layers=12,
        dim=768,
        num_heads=12,
        ffn_dim=3072,
        vocab_size=30522,
        max_len=512,
    )
    mesh = make_mesh({"stage": 1}, devices[:1])
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.bfloat16)
    params = sb.init(jax.random.key(0))
    batch, seq, num_mb = 16, 128, 8
    ids = jax.random.randint(
        jax.random.key(1), (num_mb, batch, seq), 0, cfg.vocab_size
    )
    step = sb.make_step()
    step(params, ids).block_until_ready()  # compile
    t0 = time.perf_counter()
    iters = 10
    out = None
    for _ in range(iters):
        out = step(params, ids)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    tokens_per_sec = iters * num_mb * batch * seq / dt
    flops = transformer_flops(
        num_layers=cfg.num_layers,
        dim=cfg.dim,
        ffn_dim=cfg.ffn_dim,
        seq_len=seq,
        batch=1,
    ) / seq  # per token
    peak = peak_flops(devices[0].device_kind)
    mfu = tokens_per_sec * flops / peak if peak else None
    rec = {
        "tokens_per_sec": round(tokens_per_sec, 1),
        "seq_len": seq,
        "batch": batch,
        "mfu": round(mfu, 4) if mfu is not None else None,
    }
    log(f"bert-base spmd single-chip: {rec}")
    return rec


def bench_pallas_attention(devices) -> dict:
    """Pallas flash attention vs the XLA attention path, long-sequence
    causal self-attention. OPT-IN (DEFER_TPU_PALLAS=1): on this site's
    tunneled axon backend a Mosaic compile hangs the transport, so the
    kernel is gated off by default (ops/attention.py _pallas_available)
    and this section only runs where the operator has declared the TPU
    direct-attached. The supervisor's snapshots protect every earlier
    section if the compile wedges anyway."""
    import jax
    import jax.numpy as jnp

    from defer_tpu.ops.attention import multi_head_attention

    b, s, h, dh = 4, 2048, 16, 64
    keys = jax.random.split(jax.random.key(0), 3)
    q, k, v = (
        jax.random.normal(kk, (b, s, h * dh), jnp.bfloat16) for kk in keys
    )

    def timed(use_pallas: bool) -> float:
        fn = jax.jit(
            lambda q, k, v: multi_head_attention(
                q, k, v, num_heads=h, causal=True, use_pallas=use_pallas
            )
        )
        fn(q, k, v).block_until_ready()  # compile
        iters = 20
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn(q, k, v)
        out.block_until_ready()
        return (time.perf_counter() - t0) / iters

    t_pallas = timed(True)
    t_xla = timed(False)
    rec = {
        "batch": b,
        "seq_len": s,
        "heads": h,
        "pallas_ms": round(t_pallas * 1e3, 3),
        "xla_ms": round(t_xla * 1e3, 3),
        "speedup": round(t_xla / t_pallas, 3),
    }
    log(f"pallas flash attention: {rec}")
    return rec


def run_bench() -> dict:
    import jax

    from defer_tpu.utils.platform import honor_env_platform

    honor_env_platform()
    import jax.numpy as jnp

    from defer_tpu.config import DeferConfig
    from defer_tpu.graph.partition import partition
    from defer_tpu.models import get_model
    from defer_tpu.parallel.mesh import describe_topology, pipeline_devices
    from defer_tpu.parallel.pipeline import Pipeline
    from defer_tpu.utils.flops import graph_flops, peak_flops

    devices = init_backend_with_retry()
    topo = describe_topology()
    log(f"topology: {topo}")

    model = get_model("resnet50")
    params = model.init(jax.random.key(0))
    n_dev = topo["num_devices"]
    n_stages = max(n_dev, 1)
    cuts = model.default_cuts(n_stages)
    stages = partition(model.graph, cuts) if cuts else [model.graph]
    pipe = Pipeline(
        stages,
        params,
        pipeline_devices(n_stages),
        DeferConfig(compute_dtype=jnp.bfloat16, max_inflight=128),
    )
    log(f"pipeline: {n_stages} stage(s) over {n_dev} device(s), cuts={cuts}")

    from defer_tpu.utils.profiling import TRACE_ENV, trace

    if os.environ.get(TRACE_ENV):
        log(f"device tracing enabled -> {os.environ[TRACE_ENV]}")

    flops_per_image = graph_flops(model.graph, params, (1, 224, 224, 3))
    chip_peak = peak_flops(topo["device_kind"])
    try:
        # Analytic roofline triage (host-side only, no device work):
        # says WHY the MFU number is what it is. Byte accounting must
        # match the pipeline's actual dtypes (bf16 activations AND
        # params) or intensity is off 2x against the bf16 peak.
        from defer_tpu.parallel.pipeline import cast_params_to_storage
        from defer_tpu.utils.roofline import format_report, roofline_report

        log(
            format_report(
                roofline_report(
                    model.graph,
                    cast_params_to_storage(
                        params, DeferConfig(compute_dtype=jnp.bfloat16)
                    ),
                    (128, 224, 224, 3),
                    topo["device_kind"],
                    input_dtype=jnp.bfloat16,
                    top=4,
                )
            )
        )
    except Exception as e:  # noqa: BLE001 — diagnostics only
        log(f"roofline report failed ({type(e).__name__}: {e})")
    # The pipeline spans every device — achieved FLOP/s is aggregate,
    # so MFU divides by the aggregate peak.
    peak = chip_peak * max(n_dev, 1) if chip_peak else None
    log(
        f"resnet50 analytic fwd FLOPs/image: {flops_per_image / 1e9:.2f} G; "
        f"peak[{topo['device_kind']} x {n_dev}]: "
        + (f"{peak / 1e12:.0f} TFLOP/s" if peak else "unknown")
    )

    # DEFER_BENCH_FAST=1: bounded-time mode for the CPU-fallback path
    # (a full 256-batch sweep on CPU would blow any driver timeout).
    fast = os.environ.get("DEFER_BENCH_FAST") == "1"
    best_ips = 0.0
    best_batch = None
    for batch in (1, 8, 32) if fast else (1, 8, 32, 64, 128, 256):
        try:
            stats = _measure(pipe, batch)
        except Exception as e:  # noqa: BLE001 — keep the best-so-far
            log(f"batch {batch} failed ({type(e).__name__}: {e}); "
                "keeping best so far")
            break
        mfu = stats["items_per_sec"] * flops_per_image / peak if peak else None
        log(
            f"batch {batch}: {stats['items_per_sec']:.1f} images/sec "
            f"({stats['microbatches']} microbatches in "
            f"{stats['seconds']:.2f}s)"
            + (f", mfu {mfu:.3f}" if mfu is not None else "")
        )
        if stats["items_per_sec"] > best_ips:
            best_ips = stats["items_per_sec"]
            best_batch = batch
        elif stats["items_per_sec"] < 0.9 * best_ips:
            log("throughput declining; stopping sweep")
            break
        if fast and best_batch is not None:
            # CPU-fallback insurance: a provisional headline after
            # every measured batch, so a deadline kill mid-sweep still
            # leaves a numeric value for the supervisor to salvage
            # (BENCH_r05: rounds used to end with value=null whenever
            # the fallback child outlived its leftover budget).
            snapshot(
                {
                    "metric": (
                        f"resnet50_images_per_sec_pipeline_{n_stages}"
                        f"stage_batch{best_batch}"
                    ),
                    "value": round(best_ips, 2),
                    "unit": "images/sec",
                    "vs_baseline": None,
                    "platform": topo["backend"],
                    "provisional": "mid-sweep snapshot (fast mode)",
                }
            )
    if best_batch is None:
        raise RuntimeError("no batch size measured successfully")

    # Headline is in hand — snapshot it before the optional sections so
    # a wedge in any of them can't cost the round its number.
    # chip_seconds_per_1k_images is the TPU-native stand-in for the
    # paper's per-node energy claim (reference README.md:12, -63%/node):
    # total chip time burned per 1000 images, lower is better.
    result = {
        "metric": (
            f"resnet50_images_per_sec_pipeline_{n_stages}stage"
            f"_batch{best_batch}"
        ),
        "value": round(best_ips, 2),
        "unit": "images/sec",
        "vs_baseline": None,
        "mfu": round(best_ips * flops_per_image / peak, 4) if peak else None,
        "chip_seconds_per_1k_images": round(n_dev * 1000.0 / best_ips, 2),
        "platform": topo["backend"],
        "multistage": None,
        "data_parallel": None,
        "stage_mfu": None,
        "bert_base": None,
        "vit_s16": None,
        "gpt_decode": None,
        "llama_decode": None,
        "decode_server": None,
        "paged_server": None,
        "paged_attention": None,
        "decode_window": None,
        "mixed_serving": None,
        "speculative": None,
        "tp_serving": None,
        "pp_serving": None,
        "disagg": None,
        "pallas_attention": None,
    }
    snapshot(result)

    # The pipeline sweep's own result, before any other strategy can
    # take over the headline — the multistage datapoint below must
    # report THIS, not whichever strategy won.
    pipe_ips = best_ips
    pipe_batch = best_batch

    # Multi-chip: batch-sharded SPMD data parallelism (the idiomatic
    # TPU strategy when the model fits one chip) usually beats an
    # n-device pipeline for raw throughput — measure it and let the
    # best strategy carry the headline.
    if n_dev > 1:
        try:
            from defer_tpu.parallel.data_parallel import ShardedInference

            dp = ShardedInference(
                model.graph,
                params,
                devices,
                DeferConfig(compute_dtype=jnp.bfloat16, max_inflight=128),
            )
            dp_batch = best_batch * n_dev
            stats = _measure(dp, dp_batch)
            dp_ips = stats["items_per_sec"]
            result["data_parallel"] = {
                "shards": n_dev,
                "images_per_sec": round(dp_ips, 1),
                "batch": dp_batch,
                "mfu": round(dp_ips * flops_per_image / peak, 4)
                if peak
                else None,
            }
            log(f"data-parallel: {result['data_parallel']}")
            if dp_ips > best_ips:
                result["metric"] = (
                    f"resnet50_images_per_sec_dp{n_dev}shard_batch{dp_batch}"
                )
                result["value"] = round(dp_ips, 2)
                result["mfu"] = result["data_parallel"]["mfu"]
                result["chip_seconds_per_1k_images"] = round(
                    n_dev * 1000.0 / dp_ips, 2
                )
                best_ips = dp_ips
        except Exception as e:  # noqa: BLE001 — extra datapoint only
            log(f"data-parallel probe failed ({type(e).__name__}: {e})")
        snapshot(result)

    # Per-stage latency probe, under a device trace when requested
    # ($DEFER_TPU_TRACE=dir captures a TensorBoard profile of it).
    # amortized_s leads: it is the pipeline-relevant per-call cost;
    # p50 includes a host sync round trip per call, which on tunneled
    # transports dwarfs the stage compute itself.
    try:
        from defer_tpu.utils.flops import flops_by_node

        per_node = flops_by_node(
            model.graph, params, (best_batch, 224, 224, 3)
        )
        stage_fl = [
            sum(per_node[n.name] for n in s.nodes if n.op != "input")
            for s in stages
        ]
        with trace():
            lat = pipe.probe_stage_latencies(
                jnp.ones((best_batch, 224, 224, 3), jnp.bfloat16), iters=20
            )
        stage_recs = []
        for r, fl in zip(lat, stage_fl):
            stage_mfu = (
                fl / r["amortized_s"] / chip_peak if chip_peak else None
            )
            stage_recs.append(
                {
                    "stage": r["stage"],
                    "amortized_ms": round(r["amortized_s"] * 1e3, 3),
                    "mfu": round(stage_mfu, 4)
                    if stage_mfu is not None
                    else None,
                }
            )
            log(
                f"stage {r['stage']} amortized "
                f"{r['amortized_s'] * 1e3:.2f} ms"
                + (f" (mfu {stage_mfu:.3f})" if stage_mfu is not None else "")
                + f" (sync p50 {r['p50_s'] * 1e3:.2f} ms "
                f"max {r['max_s'] * 1e3:.2f} ms) on {r['device']}"
            )
        result["stage_mfu"] = stage_recs
        snapshot(result)
    except Exception as e:  # noqa: BLE001 — diagnostics only
        log(f"stage latency probe failed ({type(e).__name__}: {e})")

    # The pipelined measurement the reference headlines (multi-stage
    # chain, reference src/test.py:30-41): round-robin the stages over
    # the available chips to quantify multi-stage dispatch overhead
    # even on a 1-chip host.
    if n_dev == 1 and not fast:
        try:
            ms_stages = 4
            ms_cuts = model.default_cuts(ms_stages)
            ms_pipe = Pipeline(
                partition(model.graph, ms_cuts),
                params,
                pipeline_devices(ms_stages),
                DeferConfig(compute_dtype=jnp.bfloat16, max_inflight=128),
            )
            stats = _measure(ms_pipe, best_batch)
            result["multistage"] = {
                "stages": ms_stages,
                "images_per_sec": round(stats["items_per_sec"], 1),
                "batch": best_batch,
            }
            log(f"multi-stage pipeline: {result['multistage']}")
        except Exception as e:  # noqa: BLE001 — extra datapoint only
            log(f"multi-stage probe failed ({type(e).__name__}: {e})")
    elif n_stages > 1:
        # The pipeline sweep itself was the multi-stage measurement.
        result["multistage"] = {
            "stages": n_stages,
            "images_per_sec": round(pipe_ips, 1),
            "batch": pipe_batch,
        }
    snapshot(result)

    if fast:
        # The baseline is a second full compile+measure subprocess;
        # in the deadline-bounded CPU-fallback run it costs minutes
        # and informs nothing (the headline already IS a CPU number).
        log("fast mode: skipping the single-CPU-device baseline")
    else:
        log("measuring single-CPU-device baseline (subprocess)...")
        cpu_ips = cpu_baseline_subprocess()
        log(f"cpu single-device: {cpu_ips:.2f} images/sec")
        north_star = 8.0 * cpu_ips if cpu_ips == cpu_ips else float("nan")
        if north_star == north_star:
            result["vs_baseline"] = round(best_ips / north_star, 3)
    snapshot(result)

    # Attention-era extras LAST (newest sections; the supervisor's
    # snapshots protect everything above if one wedges).
    if not fast:
        sections = [
            ("vit_s16", bench_vit),
            ("gpt_decode", bench_gpt_decode),
            ("llama_decode", bench_llama_decode),
            ("decode_server", bench_decode_server),
            ("paged_server", bench_paged_server),
            ("paged_attention", bench_paged_attention),
            ("decode_window", bench_decode_window),
            ("mixed_serving", bench_mixed_serving),
            ("speculative", bench_speculative),
            ("tp_serving", bench_tp_serving),
            ("pp_serving", bench_pp_serving),
            ("kv_quant", bench_kv_quant),
            ("constrain", bench_constrain),
            ("disagg", bench_disagg),
            ("fleet", bench_fleet),
            ("bert_base", bench_bert),
        ]
        # Mosaic-kernel section last. It runs wherever the pallas gate
        # answers yes: automatically on a direct-attached TPU, or
        # forced by DEFER_TPU_PALLAS=1 — note that forcing ALSO flips
        # the earlier transformer sections' use_pallas='auto' to the
        # pallas kernels, so on a tunneled backend the env var risks
        # every transformer number, not just this section; the
        # supervisor's per-section snapshots are the containment.
        from defer_tpu.ops.attention import _pallas_available

        if _pallas_available():
            sections.append(("pallas_attention", bench_pallas_attention))
        # Every section's JSON records where it ran: device kind from
        # the live topology, mesh shape when the section itself swept
        # one (tp_serving), else explicit null — so a perf number can
        # never be read without its hardware context.
        from defer_tpu.parallel.mesh import describe_topology

        section_topo = describe_topology()
        for key, fn in sections:
            try:
                rec = fn(devices)
                if isinstance(rec, dict):
                    rec.setdefault(
                        "device_kind", section_topo["device_kind"]
                    )
                    rec.setdefault("mesh_shape", None)
                result[key] = rec
            except Exception as e:  # noqa: BLE001 — extra datapoint only
                log(f"{key} probe failed ({type(e).__name__}: {e})")
            snapshot(result)

    # Static self-check rides along so the artifact records lint drift
    # next to the perf numbers (also published on the obs registry as
    # defer_analysis_findings_total{rule=...}). Sub-second, pure AST.
    # The perf-contract budgets cross-check against THIS round's
    # numbers (the in-memory result dict), so a regression the bench
    # just measured is flagged in the same artifact that measured it.
    try:
        from defer_tpu.analysis import analyze_paths
        from defer_tpu.analysis.runner import record_findings

        root = os.path.dirname(os.path.abspath(__file__))
        pkg = os.path.join(root, "defer_tpu")
        budgets = os.path.join(root, "budgets.toml")
        rep = analyze_paths(
            [pkg],
            strict=True,
            budget=budgets if os.path.exists(budgets) else None,
            bench=result,
        )
        record_findings(rep)
        result["analysis"] = {
            "findings": len(rep.findings),
            "suppressed": len(rep.suppressed),
            "counts": rep.counts,
            "suppressed_by_rule": rep.suppressed_by_rule,
        }
        if rep.budget is not None:
            result["analysis"]["budget"] = {
                c["contract"]: {
                    "status": c["status"], "value": c["value"],
                }
                for c in rep.budget["contracts"]
            }
    except Exception as e:  # noqa: BLE001 — extra datapoint only
        log(f"analysis probe failed ({type(e).__name__}: {e})")
    snapshot(result)

    return result


def cpu_fallback(err: str, timeout_s: float = 1200.0) -> dict | None:
    """When the TPU is unreachable, measure on CPU in a fresh bounded
    subprocess (this process's backend state may be wedged) so the
    round still records a real number — clearly marked platform=cpu
    with the TPU error attached — instead of nothing.

    The fallback child gets its OWN snapshot file and a reserved
    minimum deadline: fast mode snapshots a provisional headline after
    every measured batch, so even when the TPU attempts drained the
    round budget and the deadline kills the child mid-run, the salvage
    still yields a numeric value. (BENCH_r05: the old run()-based path
    popped the snapshot env and inherited whatever budget scraps were
    left, so a TimeoutExpired meant value=null for the whole round.)
    """
    import tempfile

    log("TPU unavailable; falling back to a bounded CPU measurement")
    fd, snap_path = tempfile.mkstemp(
        prefix="defer_bench_cpu_", suffix=".jsonl"
    )
    os.close(fd)
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", DEFER_BENCH_FAST="1",
        DEFER_BENCH_NO_FALLBACK="1",
    )
    env[CHILD_ENV] = "1"  # run the measurement directly; deadline below
    env[SNAPSHOT_ENV] = snap_path
    deadline = max(240.0, timeout_s)
    # Own process group, like supervise(): the deadline kill must also
    # take down measurement grandchildren or they hold the stdout pipe
    # open and the communicate() below never returns.
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE,
        stderr=None,
        text=True,
        env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        start_new_session=True,
    )
    result = None
    try:
        out, _ = proc.communicate(timeout=deadline)
        result = json.loads(out.strip().splitlines()[-1])
        if result.get("value") is None:
            result = None  # child's own error JSON; try the snapshot
    except Exception as e:  # noqa: BLE001 — salvage the snapshot below
        log(f"cpu fallback child failed ({e!r}); salvaging its snapshot")
        _kill_tree(proc)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            log("cpu fallback child unreaped after SIGKILL; abandoning")
    if result is None:
        snap = read_snapshot(snap_path)
        if snap is not None and snap.get("value") is not None:
            snap["truncated"] = (
                f"cpu fallback hit its {deadline:.0f}s deadline; "
                "reporting the last snapshot"
            )
            log("cpu fallback: using the child's last snapshot")
            result = snap
    try:
        os.unlink(snap_path)
    except OSError:
        pass
    if result is None:
        log("cpu fallback failed too: no snapshot carried a value")
        return None
    result["tpu_error"] = err
    return result


def supervise(
    cmd: list[str] | None = None,
    total_s: float | None = None,
) -> tuple[dict | None, str | None]:
    """Run the measurement in a child process under two deadlines.

    Returns (result, error): result is the child's final JSON on clean
    exit, else its last snapshot (with a `truncated` note) if that
    already carries a headline number; error describes what went wrong
    (None on clean success). `cmd` overrides the child command (tests);
    `total_s` overrides this attempt's wall-clock deadline (main()'s
    TPU-reacquisition loop shrinks it as the round budget drains).
    """
    import tempfile

    if total_s is None:
        total_s = float(os.environ.get("DEFER_BENCH_DEADLINE_S", "1500"))
    stall_s = float(os.environ.get("DEFER_BENCH_STALL_S", "660"))
    fd, snap_path = tempfile.mkstemp(prefix="defer_bench_", suffix=".jsonl")
    os.close(fd)
    env = dict(os.environ)
    env[CHILD_ENV] = "1"
    env[SNAPSHOT_ENV] = snap_path
    # Own process group: a deadline kill must take down measurement
    # grandchildren too (e.g. the CPU-baseline subprocess), or they
    # keep saturating cores under whatever measurement runs next.
    proc = subprocess.Popen(
        cmd or [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE,
        stderr=None,  # child diagnostics flow through to our stderr
        text=True,
        env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        start_new_session=True,
    )
    try:
        return _wait_supervised(proc, snap_path, total_s, stall_s)
    finally:
        try:
            os.unlink(snap_path)
        except OSError:
            pass


def _kill_tree(proc: subprocess.Popen) -> None:
    import signal

    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (OSError, ProcessLookupError):
        proc.kill()


def _wait_supervised(
    proc: subprocess.Popen, snap_path: str, total_s: float, stall_s: float
) -> tuple[dict | None, str | None]:
    t0 = time.monotonic()
    last_size = 0
    last_progress = t0
    error = None
    while True:
        try:
            proc.wait(timeout=5.0)
            break
        except subprocess.TimeoutExpired:
            pass
        now = time.monotonic()
        try:
            size = os.path.getsize(snap_path)
        except OSError:
            size = last_size
        if size != last_size:
            last_size = size
            last_progress = now
        if now - t0 > total_s:
            error = f"bench exceeded total deadline ({total_s:.0f}s)"
        elif last_size > 0 and now - last_progress > stall_s:
            # The stall clock only runs once the first snapshot exists:
            # before that, backend-init retries plus the first XLA
            # compiles can legitimately take many minutes on a slow
            # tunneled TPU, and killing a healthy child there would
            # trade a real TPU headline for a CPU fallback. Until the
            # first snapshot, only the total deadline applies.
            error = (
                f"bench made no section progress for {stall_s:.0f}s "
                "(wedged device transport?)"
            )
        if error:
            log(f"supervisor: {error}; killing measurement child")
            _kill_tree(proc)
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                # Uninterruptible child (D-state on a dead transport):
                # abandon it and salvage the snapshot — emitting the
                # headline matters more than reaping the corpse.
                log("supervisor: child unreaped after SIGKILL; abandoning")
            break
    if proc.returncode is None:
        # Unreaped child still holds the pipe's write end — a read
        # would block until its (possibly never-coming) EOF, which is
        # the exact no-JSON-line hang this supervisor exists to stop.
        out = ""
    else:
        try:
            out = proc.stdout.read() if proc.stdout else ""
        except OSError:
            out = ""
    if error is None and proc.returncode == 0:
        try:
            return json.loads(out.strip().splitlines()[-1]), None
        except (IndexError, json.JSONDecodeError):
            error = "child emitted no parseable JSON line"
    if error is None:
        error = f"measurement child exited rc={proc.returncode}"
        # The child prints an error-JSON line before dying on its own
        # exceptions — prefer its self-description.
        try:
            child_line = json.loads(out.strip().splitlines()[-1])
            if child_line.get("error"):
                error = child_line["error"]
        except (IndexError, json.JSONDecodeError):
            pass
    snap = read_snapshot(snap_path)
    if snap is not None and snap.get("value") is not None:
        snap["truncated"] = error
        log(f"supervisor: using last snapshot despite: {error}")
        return snap, None
    return None, error


def main() -> None:
    if os.environ.get(CHILD_ENV) == "1":
        # Measurement process: run directly; one JSON line on stdout.
        try:
            result = run_bench()
        except Exception as e:  # noqa: BLE001
            log(traceback.format_exc())
            print(
                json.dumps(
                    {
                        "metric": "resnet50_images_per_sec",
                        "value": None,
                        "unit": "images/sec",
                        "vs_baseline": None,
                        "error": f"{type(e).__name__}: {e}",
                    }
                ),
                flush=True,
            )
            sys.exit(1)
        print(json.dumps(result), flush=True)
        return

    # TPU-reacquisition loop: a wedged backend init is IN-PROCESS-fatal
    # only — a fresh measurement child can retry safely. Spend the
    # round's budget on fresh attempts (each burns up to ~180s probing
    # init) and only then fall back to CPU, keeping enough in reserve
    # for the fallback measurement itself.
    t0 = time.monotonic()
    budget_s = float(os.environ.get("DEFER_BENCH_DEADLINE_S", "1500"))
    # Reserve budget for the CPU fallback only when that fallback can
    # actually run — otherwise the measurement attempt gets every
    # second of the deadline, as before.
    can_fall_back = (
        os.environ.get("DEFER_BENCH_NO_FALLBACK") != "1" and not _want_cpu()
    )
    reserve_s = (
        float(os.environ.get("DEFER_BENCH_CPU_RESERVE_S", "250"))
        if can_fall_back
        else 0.0
    )
    attempt = 0
    result = err = None
    while True:
        attempt += 1
        remaining = budget_s - (time.monotonic() - t0)
        if attempt > 1 and remaining < reserve_s + 210.0:
            log(
                f"supervisor: only {remaining:.0f}s of budget left; "
                "stopping TPU attempts"
            )
            break
        result, err = supervise(total_s=max(60.0, remaining - reserve_s))
        if result is not None or _want_cpu() or not _is_init_error(err):
            break
        pause = min(30.0, 5.0 * attempt)
        log(
            f"supervisor: attempt {attempt} lost to backend init / "
            f"TPU transport ({err}); retrying in a fresh subprocess "
            f"in {pause:.0f}s"
        )
        time.sleep(pause)
    if result is None:
        if can_fall_back:
            remaining = budget_s - (time.monotonic() - t0)
            result = cpu_fallback(err or "unknown failure", remaining)
        if result is None:
            result = {
                "metric": "resnet50_images_per_sec",
                "value": None,
                "unit": "images/sec",
                "vs_baseline": None,
                "error": err,
            }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
