#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line on stdout; diagnostics on
stderr.

Protocol (mirrors the reference's measurement design, reference
src/test.py:30-41 and src/local_infer.py:16-23, adapted to TPU):

  * headline metric: ResNet50 images/sec streamed through the DEFER
    pipeline across every visible TPU device (one stage per device;
    on a 1-chip host that is a single stage).
  * baseline: the paper's comparison point is an 8-node CPU chain that
    beat one CPU device by +53% (reference README.md:12). We measure a
    single-CPU-device ResNet50 loop with this same framework in a
    subprocess, and BASELINE.json's north star is >= 8x that.
    vs_baseline = ours / (8 x single-CPU images/sec), so >= 1.0 beats
    the north star.
  * microbatch size is a tunable of our pipeline (the reference streams
    batch-1 frames); we sweep and report the best, with the sweep on
    stderr.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def cpu_baseline_subprocess(duration_s: float = 6.0) -> float:
    """Single-CPU-device ResNet50 images/sec, measured in a fresh
    process (this process owns the TPU backend)."""
    code = (
        "import jax, json;"
        "jax.config.update('jax_platforms','cpu');"
        "from defer_tpu.api import run_local_inference;"
        "from defer_tpu.models import get_model;"
        f"r = run_local_inference(get_model('resnet50'), duration_s={duration_s});"
        "print(json.dumps(r))"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        timeout=600,
    )
    if out.returncode != 0:
        log(f"cpu baseline failed:\n{out.stderr[-2000:]}")
        return float("nan")
    return json.loads(out.stdout.strip().splitlines()[-1])["items_per_sec"]


def main() -> None:
    import jax

    # Honor an explicit platform choice. The env default alone is not
    # enough here: this machine's site customization pre-imports jax
    # and forces its platform via config.update, which overrides the
    # env-derived default — so we override back, before first backend
    # use. (Verified empirically: without this, JAX_PLATFORMS=cpu runs
    # still initialized the site platform.)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    from defer_tpu.config import DeferConfig
    from defer_tpu.graph.partition import partition
    from defer_tpu.models import get_model
    from defer_tpu.parallel.mesh import describe_topology, pipeline_devices
    from defer_tpu.parallel.pipeline import Pipeline

    topo = describe_topology()
    log(f"topology: {topo}")

    model = get_model("resnet50")
    params = model.init(jax.random.key(0))
    n_dev = topo["num_devices"]
    n_stages = max(n_dev, 1)
    cuts = model.default_cuts(n_stages)
    stages = partition(model.graph, cuts) if cuts else [model.graph]
    pipe = Pipeline(
        stages,
        params,
        pipeline_devices(n_stages),
        DeferConfig(compute_dtype=jnp.bfloat16),
    )
    log(f"pipeline: {n_stages} stage(s) over {n_dev} device(s), cuts={cuts}")

    from defer_tpu.utils.profiling import TRACE_ENV, trace

    if os.environ.get(TRACE_ENV):
        log(f"device tracing enabled -> {os.environ[TRACE_ENV]}")

    best_ips = 0.0
    best_batch = None
    for batch in (1, 8, 32, 64):
        x = jnp.ones((batch, 224, 224, 3), jnp.float32)
        # Time ~4s worth of microbatches, at least 32 (throughput()
        # warms up / compiles internally).
        probe = pipe.throughput(x, num_microbatches=32)
        num_mb = max(32, int(32 * 4.0 / max(probe["seconds"], 1e-6)))
        stats = (
            probe
            if num_mb <= 32
            else pipe.throughput(x, num_microbatches=num_mb)
        )
        log(
            f"batch {batch}: {stats['items_per_sec']:.1f} images/sec "
            f"({stats['microbatches']} microbatches in "
            f"{stats['seconds']:.2f}s)"
        )
        if stats["items_per_sec"] > best_ips:
            best_ips = stats["items_per_sec"]
            best_batch = batch

    # Per-stage latency probe, under a device trace when requested
    # ($DEFER_TPU_TRACE=dir captures a TensorBoard profile of it).
    with trace():
        lat = pipe.probe_stage_latencies(
            jnp.ones((best_batch, 224, 224, 3), jnp.float32), iters=10
        )
    for r in lat:
        log(
            f"stage {r['stage']} p50 {r['p50_s'] * 1e3:.2f} ms "
            f"p99 {r['p99_s'] * 1e3:.2f} ms "
            f"amortized {r['amortized_s'] * 1e3:.2f} ms on {r['device']}"
        )

    log("measuring single-CPU-device baseline (subprocess)...")
    cpu_ips = cpu_baseline_subprocess()
    log(f"cpu single-device: {cpu_ips:.2f} images/sec")
    north_star = 8.0 * cpu_ips if cpu_ips == cpu_ips else float("nan")

    result = {
        "metric": f"resnet50_images_per_sec_pipeline_{n_stages}stage_batch{best_batch}",
        "value": round(best_ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(best_ips / north_star, 3)
        if north_star == north_star
        else None,
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
