"""Test harness: run everything on an 8-device CPU-emulated mesh.

The reference has no tests at all (SURVEY.md §4); multi-node behavior
was only ever exercised on physical hosts at hard-coded IPs (reference
src/test.py:20). Here CI needs no hardware: XLA's host platform is
forced to expose 8 virtual devices, so partitioning, device-pinned
pipelines, and shard_map collectives all run for real.

Must run before the first `import jax` anywhere in the test process.
"""

import os

# Force CPU even when the environment pre-selects a TPU platform (the
# benchmark harness uses the real chip; tests never should).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# jax may already be imported (site customization registers a TPU PJRT
# plugin in every process), so the env var alone is too late — override
# the live config before any backend initializes.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture
def trace_sanitizer():
    """The analysis subsystem's no-retrace guard
    (defer_tpu/analysis/sanitizer.py): wrap a warmed hot loop and the
    test fails with RetraceError if any watched jitted callable
    compiles a new variant inside the block."""
    from defer_tpu.analysis.sanitizer import trace_sanitizer as ts

    return ts


FLAKY = {"failures": 0}


def register_flaky_op() -> None:
    """Idempotently register the 'flaky' fault-injection op: raises
    while FLAKY['failures'] > 0 (decrementing), else identity. Shared
    by the elastic-recovery tests so both exercise the same fault."""
    from defer_tpu.ops.registry import op_names, register_op

    if "flaky" in op_names():
        return

    @register_op("flaky")
    def flaky_apply(params, inputs, attrs):
        if FLAKY["failures"] > 0:
            FLAKY["failures"] -= 1
            raise RuntimeError("transient stage failure")
        return inputs[0]


def write_keras_h5(path: str, weights: dict) -> None:
    """Write `{layer: [arrays]}` in the classic Keras save_weights h5
    layout (layer_names/weight_names attrs) for transplant tests."""
    import h5py

    with h5py.File(path, "w") as f:
        f.attrs["layer_names"] = [n.encode() for n in weights]
        for lname, arrays in weights.items():
            g = f.create_group(lname)
            wnames = [f"{lname}/w{i}".encode() for i in range(len(arrays))]
            g.attrs["weight_names"] = wnames
            for wn, a in zip(wnames, arrays):
                g.create_dataset(wn.decode(), data=a)
