"""Data-parallel inference on the 8-device CPU mesh.

The reference's only scaling axis is pipeline depth; these cover the
TPU-native alternative (batch sharding over a "data" mesh axis) and its
composition with the heterogeneous pipeline (replicas x stages).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.config import DeferConfig
from defer_tpu.graph.partition import partition
from defer_tpu.parallel.data_parallel import (
    ReplicatedPipeline,
    ShardedInference,
)
from defer_tpu.parallel.mesh import make_mesh
from tests.test_partition import residual_chain

F32 = DeferConfig(compute_dtype=jnp.float32)


def test_sharded_inference_matches_single_device(devices):
    g = residual_chain()
    params = g.init(jax.random.key(0), (8, 8))
    x = jax.random.normal(jax.random.key(1), (8, 8))
    want = g.apply(params, x)
    dp = ShardedInference(g, params, devices, config=F32)
    assert dp.num_shards == 8
    got = dp.warmup(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)
    # The batch really is sharded: each shard holds 1/8 of dim 0.
    shard_shapes = {s.data.shape for s in got.addressable_shards}
    assert shard_shapes == {(1, *want.shape[1:])}
    # Params really are replicated on all 8 devices.
    leaf = jax.tree_util.tree_leaves(dp.params)[0]
    assert leaf.sharding.device_set == set(devices)


def test_sharded_inference_rejects_ragged_batch(devices):
    g = residual_chain()
    params = g.init(jax.random.key(0), (8, 8))
    dp = ShardedInference(g, params, devices, config=F32)
    with pytest.raises(ValueError, match="not divisible"):
        dp(jnp.ones((6, 8)))


def test_sharded_inference_existing_mesh_axis(devices):
    """A caller-built mesh (e.g. shared with other jobs) works too."""
    g = residual_chain()
    params = g.init(jax.random.key(0), (4, 8))
    mesh = make_mesh({"data": 4}, devices[:4])
    dp = ShardedInference(g, params, mesh, config=F32)
    x = jax.random.normal(jax.random.key(1), (4, 8))
    np.testing.assert_allclose(
        np.asarray(dp.warmup(x)),
        np.asarray(g.apply(params, x)),
        rtol=1e-5,
    )


def test_sharded_inference_stream_order(devices):
    g = residual_chain()
    params = g.init(jax.random.key(0), (8, 8))
    dp = ShardedInference(g, params, devices, config=F32)
    xs = [jnp.full((8, 8), float(i)) for i in range(12)]
    outs = list(dp.stream(iter(xs), max_inflight=3))
    assert len(outs) == 12
    for x, out in zip(xs, outs):
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(g.apply(params, x)), rtol=1e-5
        )


def test_replicated_pipeline_matches_and_places(devices):
    g = residual_chain()
    params = g.init(jax.random.key(0), (2, 8))
    stages = partition(g, ["add_1"])  # 2 stages
    rp = ReplicatedPipeline(stages, params, devices, config=F32)
    assert rp.num_replicas == 4  # 8 devices // 2 stages
    assert rp.num_stages == 2
    x = jax.random.normal(jax.random.key(1), (2, 8))
    want = g.apply(params, x)
    np.testing.assert_allclose(
        np.asarray(rp.warmup(x)), np.asarray(want), rtol=1e-5
    )
    # Replicas occupy disjoint device pairs covering all 8.
    seen = set()
    for pipe in rp.pipes:
        for d in pipe.devices:
            assert d not in seen
            seen.add(d)
    assert seen == set(devices)


def test_replicated_pipeline_stream_order(devices):
    """Round-robin fan-out must not reorder the stream, including when
    the input count isn't a multiple of the replica count."""
    g = residual_chain()
    params = g.init(jax.random.key(0), (1, 8))
    stages = partition(g, ["add_1"])
    rp = ReplicatedPipeline(
        stages, params, devices[:6], config=F32, num_replicas=3
    )
    xs = [jnp.full((1, 8), float(i)) for i in range(17)]
    outs = list(rp.stream(iter(xs), max_inflight=2))
    assert len(outs) == 17
    for x, out in zip(xs, outs):
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(g.apply(params, x)), rtol=1e-5
        )


def test_run_defer_with_replicas(devices):
    """The reference-shaped API with the data-parallel axis: replicas=2
    over a 2-stage cut uses 4 devices and keeps the queue contract,
    output order, and values."""
    import queue
    import threading

    from defer_tpu.api import DEFER

    g = residual_chain()
    params = g.init(jax.random.key(0), (1, 8))
    defer = DEFER(config=F32)
    inq: "queue.Queue" = queue.Queue(10)
    outq: "queue.Queue" = queue.Queue()
    t = threading.Thread(
        target=defer.run_defer,
        args=(g, ["add_1"], inq, outq),
        kwargs={"params": params, "replicas": 2},
        daemon=True,
    )
    t.start()
    xs = [jnp.full((1, 8), float(i)) for i in range(9)]
    for x in xs:
        inq.put(x)
    inq.put(None)
    outs = [outq.get(timeout=120) for _ in range(9)]
    t.join(timeout=120)
    assert not t.is_alive()
    assert defer.last_pipeline.num_replicas == 2
    for x, out in zip(xs, outs):
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(g.apply(params, x)), rtol=1e-5
        )


def test_replica_retirer_orders_and_isolates(devices):
    """ReplicaRetirer: global order restored across interleaved
    replicas; each replica's barrier only ever syncs its own items (a
    wedged sibling can't have its unfinished work retired — the sync
    callback records which items it was asked to fetch)."""
    from defer_tpu.parallel.data_parallel import ReplicaRetirer
    from defer_tpu.utils.sync import hard_sync

    rr = ReplicaRetirer(2, depth=4, sync=hard_sync)
    items = [jnp.full((2,), float(i)) for i in range(10)]
    out = []
    for it in items:
        out.extend(rr.add(it))
    out.extend(rr.flush())
    assert [int(np.asarray(o[0])) for o in out] == list(range(10))
    # Isolation: replica r's Retirer must only ever hold r's items, so
    # a barrier taken on one replica cannot retire a sibling's work.
    owner = {}
    rr2 = ReplicaRetirer(2, depth=2, sync=lambda a: None)
    for i in range(6):
        arr = jnp.full((1,), float(i))
        owner[id(arr)] = i % 2
        rr2.add(arr)
    # Internal wiring: replica r's Retirer only ever holds r's items.
    for r, ret in enumerate(rr2.retirers):
        for item in ret.pending:
            assert owner[id(item)] == r


def test_replica_retirer_discard_realigns(devices):
    from defer_tpu.parallel.data_parallel import ReplicaRetirer

    rr = ReplicaRetirer(3, depth=30)
    for i in range(4):
        rr.add(jnp.full((1,), float(i)))
    lost = rr.discard()
    assert lost >= 0
    assert len(rr) == 0 and rr.ready_count() == 0
    # After a discard the rotation restarts at replica 0 — a fresh
    # submit rotation (new pipeline post-redispatch) stays aligned.
    out = []
    for i in range(6):
        out.extend(rr.add(jnp.full((1,), float(10 + i))))
    out.extend(rr.flush())
    assert [int(np.asarray(o[0])) for o in out] == list(range(10, 16))


def test_replicated_pipeline_device_budget_checked(devices):
    g = residual_chain()
    params = g.init(jax.random.key(0), (1, 8))
    stages = partition(g, ["add_1"])
    with pytest.raises(ValueError, match="needs"):
        ReplicatedPipeline(
            stages, params, devices[:3], config=F32, num_replicas=2
        )


def test_replicated_run_defer_redispatches_and_recovers(devices):
    """Elastic recovery composes with replicas: a transient failure
    rebuilds the REPLICATED pipeline (same replica count) and the
    stream completes in order."""
    import queue
    import threading

    from defer_tpu.api import DEFER
    from tests.conftest import FLAKY, register_flaky_op

    register_flaky_op()
    FLAKY["failures"] = 1

    from defer_tpu.graph.ir import GraphBuilder

    b = GraphBuilder("flaky_rp")
    x = b.input()
    h = b.add("dense", x, name="s0", features=4)
    h = b.add("flaky", h, name="wobble")
    g = b.build(h)
    params = {
        "input": {}, "wobble": {},
        "s0": {"kernel": jnp.ones((8, 4)), "bias": jnp.zeros(4)},
    }

    defer = DEFER(devices[:4], config=F32)
    inq: "queue.Queue" = queue.Queue()
    outq: "queue.Queue" = queue.Queue()
    xs = [jnp.full((2, 8), float(i)) for i in range(6)]
    for v in xs:
        inq.put(v)
    inq.put(None)
    t = threading.Thread(
        target=defer.run_defer, args=(g, ["s0"], inq, outq),
        kwargs={"params": params, "replicas": 2}, daemon=True,
    )
    t.start()
    outs = [outq.get(timeout=120) for _ in range(6)]
    t.join(timeout=60)
    assert not t.is_alive()
    assert FLAKY["failures"] == 0
    assert defer.last_pipeline.num_replicas == 2  # rebuilt, same shape
    for v, got in zip(xs, outs):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(g.apply(params, v)), rtol=1e-6
        )
