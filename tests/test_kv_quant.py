"""Quantized paged KV pool (`kv_dtype="int8"`) + host-RAM spill tier.

The contracts pinned here, in order:

  * the shared symmetric-int8 convention (models/quant.py) degrades
    safely on zero/subnormal tensors and the codec's non-finite guard
    still fires — one helper, three consumers (weight leaves, wire
    frames, the pool);
  * pool bytes: int8 stores exactly fp_bytes/itemsize + the scale
    tensors — the residency win is arithmetic, not approximate;
  * accuracy: teacher-forced along the fp greedy trajectory, int8
    logits stay within a small fraction of the logit scale at EVERY
    decode step, for every attention mode × prefix_cache × tp — the
    bounded-logit-error contract (outputs are NOT bit-identical; the
    pool is lossy by design);
  * composition: decode_window and spec_k are exact rearrangements of
    the same tick math WITHIN a pool dtype, so int8+window and
    int8+spec must be token-identical to plain int8;
  * `kv_dtype="fp"` stays bit-identical to solo generate (the default
    cannot move);
  * `defer_kv_rows_read_total` counts rows, not bytes — identical for
    fp and int8 pools;
  * spill tier: an evicted prefix block revived from host RAM is
    token-identical to a resident radix hit, for both pool dtypes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu import obs
from defer_tpu.models.gpt import tiny_gpt
from defer_tpu.models.quant import (
    dequantize_symmetric,
    quantize_symmetric,
)
from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.runtime.codec import encode
from defer_tpu.runtime.paged import PagedDecodeServer, serve_paged


@pytest.fixture(scope="module")
def model():
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    return dec, params


def _requests(vocab):
    """Shared prefix on the first two (radix hits under prefix_cache)
    plus one longer independent prompt — the test_paged_tp.py mix."""
    rng = np.random.default_rng(3)
    base = jnp.asarray(rng.integers(1, vocab, size=(1, 6)), jnp.int32)
    ext = jnp.asarray(rng.integers(1, vocab, size=(1, 4)), jnp.int32)
    return [
        (base, 7),
        (jnp.concatenate([base, ext], axis=1), 5),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 11)), jnp.int32), 6),
    ]


# -- the shared int8 convention -------------------------------------------


def test_quantize_symmetric_degenerate_and_bounds():
    """Zero and subnormal tensors clamp the scale to 1.0 (quantize to
    zeros, not clipped ±127 garbage); normal tensors round-trip within
    the per-axis amax/254 bound the scale granularity implies."""
    q, s = quantize_symmetric(np.zeros((3, 4), np.float32), axis=None, xp=np)
    assert q.dtype == np.int8 and not q.any()
    assert float(s) == 1.0
    # Smallest fp32 subnormal: amax/127 underflows to exactly 0, the
    # degenerate-scale clamp's other trigger besides the zero tensor.
    tiny = np.full((2, 2), np.float32(1.4e-45), np.float32)
    assert tiny.any()
    q, s = quantize_symmetric(tiny, axis=None, xp=np)
    assert not q.any() and float(s) == 1.0

    rng = np.random.default_rng(0)
    x = rng.standard_normal((5, 8, 16)).astype(np.float32)
    q, s = quantize_symmetric(x, axis=(-2, -1), keepdims=True, xp=np)
    back = dequantize_symmetric(q, s, np.float32, xp=np)
    amax = np.abs(x).max(axis=(-2, -1), keepdims=True)
    # Half a quantization step per element, per (leading-axis) scale.
    assert (np.abs(back - x) <= amax / 254 + 1e-7).all()


def test_codec_nonfinite_guard_matches_helper_consumers():
    """The codec refuses non-finite tensors BEFORE quantize_symmetric
    sees them (one NaN would corrupt the whole frame); the jitted pool
    writes rely on the same caller-side contract."""
    bad = np.array([1.0, np.nan], np.float32)
    with pytest.raises(ValueError, match="finite"):
        encode(bad, quantize="int8")
    with pytest.raises(ValueError, match="finite"):
        encode(np.array([np.inf], np.float64), quantize="int8")


# -- pool bytes -----------------------------------------------------------


def test_int8_pool_bytes_pinned(model):
    """The residency claim as arithmetic: the int8 pool is exactly
    fp_bytes/itemsize for the block data plus the two fp32 scale
    tensors — and the stats surface both dtype and bytes."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    kw = dict(num_blocks=16, block_size=4, max_batch=2)
    _, st_fp = serve_paged(dec, params, list(reqs), **kw)
    _, st_q8 = serve_paged(dec, params, list(reqs), kv_dtype="int8", **kw)
    assert st_fp["kv_dtype"] == "fp" and st_q8["kv_dtype"] == "int8"
    cfg = dec.cfg
    elems = (
        cfg.num_layers * 16 * cfg.kv_heads * 4 * (cfg.dim // cfg.num_heads)
    )
    itemsize = jnp.dtype(dec.compute_dtype).itemsize
    scales = cfg.num_layers * 16 * cfg.kv_heads * 4  # fp32, k and v
    assert st_fp["pool_bytes"] == 2 * elems * itemsize
    assert st_q8["pool_bytes"] == 2 * elems + 2 * scales
    assert st_q8["pool_bytes"] < st_fp["pool_bytes"] / itemsize + 2 * scales + 1


# -- accuracy: the bounded-logit-error parity matrix ----------------------


def _forced_trace(dec, params, prompt, steps, forced=None, **srv_kw):
    """Drive one request tick by tick, recording each step's logits
    row; with `forced`, override the greedy feed with a reference
    trajectory so fp and int8 runs score the SAME token sequence —
    after the first divergence, free-running logits are incomparable."""
    srv = PagedDecodeServer(
        dec, params, num_blocks=16, block_size=4, max_batch=1, **srv_kw
    )
    srv.submit(prompt, steps)
    srv._admit()
    srv._build()
    orig = srv._step

    rec = []

    def spy(*args):
        logits, pk, pv = orig(*args)
        rec.append(np.asarray(logits[:, -1, :]))
        return logits, pk, pv

    srv._step = spy
    toks = [int(np.asarray(srv._feed)[0, 0])]
    t = 0
    while any(s is not None for s in srv.slots):
        srv._tick()
        toks.append(int(np.asarray(srv._feed)[0, 0]))
        if forced is not None and t + 1 < len(forced):
            srv._feed = jnp.asarray([[forced[t + 1]]], jnp.int32)
        t += 1
    return toks, rec


MATRIX = [
    ("gathered", False, 0),
    ("gathered", True, 0),
    ("blockwise", False, 0),
    ("blockwise", True, 0),
    ("pallas", False, 0),
    ("pallas", True, 0),
    ("gathered", False, 2),
    ("blockwise", True, 2),
    ("pallas", False, 2),
]


@pytest.mark.parametrize("attention,prefix_cache,tp", MATRIX)
def test_int8_logit_error_bounded(model, attention, prefix_cache, tp):
    """Teacher-forced along the fp greedy trajectory, every decode
    step's int8 logits stay within 5% of the fp logit scale — the
    accuracy contract of per-(layer, block, head) scales — and the
    error is nonzero (the quantized path actually ran)."""
    dec, params = model
    rng = np.random.default_rng(3)
    prompt = jnp.asarray(
        rng.integers(1, dec.cfg.vocab_size, size=(1, 11)), jnp.int32
    )
    kw = dict(attention=attention, prefix_cache=prefix_cache)
    if tp:
        kw["mesh"] = make_mesh({"model": tp}, jax.devices()[:tp])
    ftoks, flog = _forced_trace(dec, params, prompt, 8, **kw)
    _, qlog = _forced_trace(
        dec, params, prompt, 8, forced=ftoks, kv_dtype="int8", **kw
    )
    assert len(flog) == len(qlog) > 0
    scale = max(float(np.max(np.abs(a))) for a in flog)
    err = max(
        float(np.max(np.abs(a - b))) for a, b in zip(flog, qlog)
    )
    assert 0 < err < 0.05 * scale, (
        f"attention={attention} tp={tp}: max|Δlogit|={err} "
        f"vs logit scale {scale}"
    )


def test_int8_window_and_spec_token_identical_to_plain_int8(model):
    """decode_window and spec verify are exact rearrangements of the
    same tick math WITHIN a pool dtype: the fused window's per-column
    writes and the verify forward's row scatters requantize blocks in
    the same order the K=1 tick would, so int8 outputs cannot move."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    kw = dict(
        num_blocks=16, block_size=4, max_batch=2, kv_dtype="int8"
    )
    for attention in ("gathered", "blockwise", "pallas"):
        plain, _ = serve_paged(
            dec, params, list(reqs), attention=attention, **kw
        )
        windowed, _ = serve_paged(
            dec, params, list(reqs), attention=attention,
            decode_window=8, **kw,
        )
        for a, b in zip(plain, windowed):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"decode_window=8 moved int8 {attention} output",
            )
    plain, _ = serve_paged(
        dec, params, list(reqs), attention="gathered", **kw
    )
    spec, st = serve_paged(
        dec, params, list(reqs), attention="gathered",
        spec_draft=dec, spec_params=params, spec_k=4, **kw,
    )
    assert st["spec_acceptance"] > 0.5  # self-draft: verify rows real
    for a, b in zip(plain, spec):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg="spec_k=4 moved int8 output",
        )


def test_fp_default_still_bit_identical(model):
    """The default pool is untouched: fp greedy outputs equal solo
    dec.generate exactly, with the quantization machinery imported and
    live in the same process."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    outs, stats = serve_paged(
        dec, params, list(reqs), num_blocks=16, block_size=4, max_batch=2
    )
    assert stats["kv_dtype"] == "fp"
    for (prompt, steps), got in zip(reqs, outs):
        want = dec.generate(params, prompt, steps)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_kv_rows_counter_is_dtype_agnostic(model):
    """`defer_kv_rows_read_total` means ROWS: an int8 pool reads the
    same row count as fp (the bytes halve, the counter must not)."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    kw = dict(
        num_blocks=16, block_size=4, max_batch=2, attention="blockwise"
    )
    with obs.counter_deltas() as d_fp:
        serve_paged(dec, params, list(reqs), **kw)
    with obs.counter_deltas() as d_q8:
        serve_paged(dec, params, list(reqs), kv_dtype="int8", **kw)
    key = 'defer_kv_rows_read_total{server="paged"}'
    assert d_fp[key] == d_q8[key] > 0


# -- host-RAM spill tier --------------------------------------------------


def _spill_workload(vocab):
    rng = np.random.default_rng(5)
    prefix = jnp.asarray(rng.integers(1, vocab, size=(1, 8)), jnp.int32)
    tails = [
        jnp.asarray(rng.integers(1, vocab, size=(1, n)), jnp.int32)
        for n in (3, 2)
    ]
    fillers = [
        jnp.asarray(rng.integers(1, vocab, size=(1, 9)), jnp.int32)
        for _ in range(3)
    ]
    return prefix, tails, fillers


def _run_phases(dec, params, *, num_blocks, spill_bytes, kv_dtype):
    """prefix warm-up -> pool-thrashing fillers -> same prefix again.
    With a big pool the second prefix request is a resident radix hit;
    with a tiny pool + spill tier it must come back via revival."""
    prefix, (ta, tb), fillers = _spill_workload(dec.cfg.vocab_size)
    srv = PagedDecodeServer(
        dec, params, num_blocks=num_blocks, block_size=4, max_batch=1,
        prefix_cache=True, kv_dtype=kv_dtype, spill_bytes=spill_bytes,
    )
    rid = srv.submit(jnp.concatenate([prefix, ta], axis=1), 4)
    srv.run()
    for f in fillers:
        srv.submit(f, 6)
        srv.run()
    if srv._spill is not None:
        srv._spill.flush()
    rid = srv.submit(jnp.concatenate([prefix, tb], axis=1), 5)
    out = np.asarray(srv.run()[rid])
    return out, srv


@pytest.mark.parametrize("kv_dtype", ["fp", "int8"])
def test_spill_revival_token_identical_to_resident_hit(model, kv_dtype):
    """An evicted prefix block revived from the host store produces
    the SAME tokens as the resident-hit run: revival re-uploads the
    stored bytes verbatim (no requantize round trip), so the pool
    state a revived chain presents is bit-identical to never having
    been evicted."""
    dec, params = model
    resident, srv_r = _run_phases(
        dec, params, num_blocks=64, spill_bytes=0, kv_dtype=kv_dtype
    )
    assert srv_r.spill_hits_n == 0
    revived, srv_s = _run_phases(
        dec, params, num_blocks=10, spill_bytes=1 << 20, kv_dtype=kv_dtype
    )
    assert srv_s.spill_hits_n > 0
    assert srv_s._spill.stored_blocks > 0
    # Revival saved the same prefill work a resident hit saves.
    assert srv_s.prefill_tokens_saved == srv_r.prefill_tokens_saved > 0
    np.testing.assert_array_equal(revived, resident)


def test_spill_counters_and_stats_surface(model):
    """Spill motion shows up in obs: blocks spilled and revived count
    on the server-labeled counters, occupancy lands in the gauge, and
    serve_paged's ServerStats carry the same numbers."""
    dec, params = model
    prefix, (ta, tb), fillers = _spill_workload(dec.cfg.vocab_size)
    reqs = (
        [(jnp.concatenate([prefix, ta], axis=1), 4)]
        + [(f, 6) for f in fillers]
        + [(jnp.concatenate([prefix, tb], axis=1), 5)]
    )
    with obs.counter_deltas() as d:
        _, st = serve_paged(
            dec, params, reqs, num_blocks=10, block_size=4, max_batch=1,
            prefix_cache=True, kv_dtype="int8", spill_bytes=1 << 20,
        )
    assert d['defer_prefix_spilled_total{server="paged"}'] > 0
    assert d['defer_prefix_spill_hits_total{server="paged"}'] > 0
    assert st["spill_hits"] > 0
    assert st["spilled_blocks"] > 0
    assert st["spill_stored_bytes"] > 0


def test_spill_requires_prefix_cache(model):
    dec, params = model
    with pytest.raises(ValueError, match="prefix_cache"):
        PagedDecodeServer(
            dec, params, num_blocks=8, block_size=4, max_batch=1,
            spill_bytes=1 << 20,
        )
    with pytest.raises(ValueError, match="kv_dtype"):
        PagedDecodeServer(
            dec, params, num_blocks=8, block_size=4, max_batch=1,
            kv_dtype="int4",
        )
