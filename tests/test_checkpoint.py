"""Checkpoint save/resume over the native codec."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models import get_model
from defer_tpu.runtime.checkpoint import load_checkpoint, save_checkpoint


def test_graphparams_round_trip(tmp_path):
    model = get_model("vgg16")
    params = model.graph.init(jax.random.key(0), (1, 224, 224, 3))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)
    back = load_checkpoint(path)
    flat_a = jax.tree_util.tree_leaves_with_path(dict(params))
    flat_b = jax.tree_util.tree_leaves_with_path(back)
    assert len(flat_a) == len(flat_b)
    for (ka, va), (kb, vb) in zip(flat_a, flat_b):
        assert ka == kb
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def test_bfloat16_round_trip(tmp_path):
    params = {
        "layer": {
            "w": jnp.asarray(
                np.random.default_rng(0).standard_normal((16, 8)), jnp.bfloat16
            ),
            "b": jnp.zeros((8,), jnp.float32),
        }
    }
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)
    back = load_checkpoint(path)
    assert back["layer"]["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(back["layer"]["w"]).view(np.uint16),
        np.asarray(params["layer"]["w"]).view(np.uint16),
    )


def test_resume_gives_identical_forward(tmp_path):
    """The checkpoint/resume contract: a forward pass from restored
    params is bit-identical."""
    model = get_model("mobilenetv2")
    shape = (1, 96, 96, 3)
    params = model.graph.init(jax.random.key(1), shape)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)
    restored = load_checkpoint(path)
    x = jax.random.normal(jax.random.key(2), shape)
    np.testing.assert_array_equal(
        np.asarray(model.graph.apply(params, x)),
        np.asarray(model.graph.apply(restored, x)),
    )


def test_bad_file_raises(tmp_path):
    p = tmp_path / "junk"
    p.write_bytes(b"not a checkpoint")
    with pytest.raises(ValueError, match="not a defer_tpu checkpoint"):
        load_checkpoint(str(p))


def test_key_with_separator_rejected(tmp_path):
    with pytest.raises(ValueError, match="may not contain"):
        save_checkpoint(str(tmp_path / "c"), {"a/b": jnp.zeros(3)})
