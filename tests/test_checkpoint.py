"""Checkpoint save/resume over the native codec."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models import get_model
from defer_tpu.runtime.checkpoint import load_checkpoint, save_checkpoint


def test_graphparams_round_trip(tmp_path):
    model = get_model("vgg16")
    params = model.graph.init(jax.random.key(0), (1, 224, 224, 3))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)
    back = load_checkpoint(path)
    flat_a = jax.tree_util.tree_leaves_with_path(dict(params))
    flat_b = jax.tree_util.tree_leaves_with_path(back)
    assert len(flat_a) == len(flat_b)
    for (ka, va), (kb, vb) in zip(flat_a, flat_b):
        assert ka == kb
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def test_bfloat16_round_trip(tmp_path):
    params = {
        "layer": {
            "w": jnp.asarray(
                np.random.default_rng(0).standard_normal((16, 8)), jnp.bfloat16
            ),
            "b": jnp.zeros((8,), jnp.float32),
        }
    }
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)
    back = load_checkpoint(path)
    assert back["layer"]["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(back["layer"]["w"]).view(np.uint16),
        np.asarray(params["layer"]["w"]).view(np.uint16),
    )


def test_resume_gives_identical_forward(tmp_path):
    """The checkpoint/resume contract: a forward pass from restored
    params is bit-identical."""
    model = get_model("mobilenetv2")
    shape = (1, 96, 96, 3)
    params = model.graph.init(jax.random.key(1), shape)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)
    restored = load_checkpoint(path)
    x = jax.random.normal(jax.random.key(2), shape)
    np.testing.assert_array_equal(
        np.asarray(model.graph.apply(params, x)),
        np.asarray(model.graph.apply(restored, x)),
    )


def test_bad_file_raises(tmp_path):
    p = tmp_path / "junk"
    p.write_bytes(b"not a checkpoint")
    with pytest.raises(ValueError, match="not a defer_tpu checkpoint"):
        load_checkpoint(str(p))


def test_key_with_separator_rejected(tmp_path):
    with pytest.raises(ValueError, match="may not contain"):
        save_checkpoint(str(tmp_path / "c"), {"a/b": jnp.zeros(3)})


def test_train_state_resume(devices):
    """Full training resume: save mid-run, restore into a fresh state,
    and require identical subsequent losses."""
    import optax

    from defer_tpu.models.bert import SpmdBert
    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.parallel.train import make_train_step
    from defer_tpu.parallel.transformer_stack import TransformerConfig
    from defer_tpu.runtime.checkpoint import load_pytree, save_pytree
    import tempfile

    mesh = make_mesh({"stage": 2}, devices[:2])
    cfg = TransformerConfig(
        num_layers=2, dim=32, num_heads=2, ffn_dim=64, vocab_size=64,
        max_len=16,
    )
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    init_state, train_step = make_train_step(sb, optax.adam(1e-2), num_classes=3)
    state = init_state(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (3, 2, 8), 0, cfg.vocab_size)
    labels = jax.random.randint(jax.random.key(2), (3, 2), 0, 3)
    state, _ = train_step(state, ids, labels)

    with tempfile.TemporaryDirectory() as td:
        save_pytree(f"{td}/state", state)
        template = init_state(jax.random.key(9))  # different values
        restored = load_pytree(f"{td}/state", template)

    # Branch A: continue from live state; branch B: from restored.
    _, loss_a = train_step(state, ids, labels)
    _, loss_b = train_step(restored, ids, labels)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)


def test_load_pytree_leaf_count_mismatch(tmp_path):
    from defer_tpu.runtime.checkpoint import load_pytree, save_pytree

    save_pytree(str(tmp_path / "t"), {"a": jnp.zeros(3)})
    with pytest.raises(ValueError, match="leaves"):
        load_pytree(str(tmp_path / "t"), {"a": jnp.zeros(3), "b": jnp.zeros(2)})


def test_sharded_save_restore_round_trip(tmp_path, devices):
    """Distributed checkpoint: shards written without gathering, each
    replicated value stored once, restore reassembles the exact
    distributed arrays (values AND shardings)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.runtime.checkpoint import restore_sharded, save_sharded

    mesh = make_mesh({"data": 2, "model": 2}, devices[:4])
    tree = {
        "w": jax.device_put(
            jnp.arange(32.0).reshape(8, 4),
            NamedSharding(mesh, P("data", "model")),
        ),
        "rows": jax.device_put(
            jnp.arange(8.0), NamedSharding(mesh, P("data"))
        ),
        "rep": jax.device_put(
            jnp.arange(6, dtype=jnp.bfloat16), NamedSharding(mesh, P())
        ),
        "nested": {"step": jnp.asarray(7)},
    }
    d = str(tmp_path / "ckpt")
    save_sharded(d, tree)

    like = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=a.sharding
        ),
        tree,
    )
    got = restore_sharded(d, like)
    for k in ("w", "rows", "rep"):
        assert got[k].sharding == tree[k].sharding, k
        np.testing.assert_array_equal(
            np.asarray(got[k]).astype(np.float32),
            np.asarray(tree[k]).astype(np.float32),
        )
    np.testing.assert_array_equal(
        np.asarray(got["nested"]["step"]), np.asarray(tree["nested"]["step"])
    )


def test_sharded_restore_missing_leaf_errors(tmp_path, devices):
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.runtime.checkpoint import restore_sharded, save_sharded

    mesh = make_mesh({"data": 2}, devices[:2])
    tree = {"w": jax.device_put(jnp.ones(4), NamedSharding(mesh, P("data")))}
    d = str(tmp_path / "ckpt")
    save_sharded(d, tree)
    like = {
        "w": tree["w"],
        "extra": jax.device_put(jnp.ones(2), NamedSharding(mesh, P())),
    }
    with pytest.raises(KeyError, match="extra"):
        restore_sharded(d, like)


def test_sharded_restore_rejects_mixed_shard_sets(tmp_path, devices):
    """Stale shard files from an earlier save with a different job size
    must be a clean error, not silently blended checkpoints."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.runtime.checkpoint import restore_sharded, save_sharded

    import os

    mesh = make_mesh({"data": 2}, devices[:2])
    tree = {"w": jax.device_put(jnp.ones(4), NamedSharding(mesh, P("data")))}
    d = str(tmp_path / "ckpt")
    save_sharded(d, tree)
    # Simulate a leftover shard from a 4-process save.
    stale = os.path.join(d, "shards-00003-of-00004.defer")
    with open(stale, "wb") as f:
        f.write(b"junk")
    with pytest.raises(ValueError, match="mixed or incomplete"):
        restore_sharded(d, tree)


def test_sharded_restored_train_state_is_jit_compatible(tmp_path, devices):
    """Cross-process resume: a train step whose FIRST compile sees the
    restored state must accept it — committed single-device scalars
    next to 8-device params would be rejected by jit (regression)."""
    import optax

    from defer_tpu.models.bert import SpmdBert
    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.parallel.train import make_train_step
    from defer_tpu.parallel.transformer_stack import TransformerConfig
    from defer_tpu.runtime.checkpoint import restore_sharded, save_sharded

    mesh = make_mesh({"data": 2, "stage": 2, "model": 2}, devices)
    cfg = TransformerConfig(
        num_layers=4, dim=32, num_heads=4, ffn_dim=64, vocab_size=64,
        max_len=16,
    )
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    init_state, train_step = make_train_step(sb, optax.adam(1e-3),
                                             num_classes=4)
    state = init_state(jax.random.key(0))
    d = str(tmp_path / "ck")
    save_sharded(d, state)
    restored = restore_sharded(d, state)
    ids = jax.random.randint(jax.random.key(1), (3, 4, 8), 0, 64)
    labels = jax.random.randint(jax.random.key(2), (3, 4), 0, 4)
    # First (and only) compile of this train_step sees the restored
    # state — the failing case before the uncommitted-scalar fix.
    _, loss = train_step(restored, ids, labels)
    assert jnp.isfinite(loss)


def test_orbax_round_trip(tmp_path):
    """Orbax interop: save via orbax, restore with a template — values
    and dtypes (incl. bfloat16) survive."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest

    pytest.importorskip("orbax.checkpoint")
    from defer_tpu.runtime.checkpoint import load_orbax, save_orbax

    tree = {
        "w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
        "nested": {"b": jnp.ones((5,), jnp.bfloat16) * 1.5},
        "step": jnp.int32(7),
    }
    path = str(tmp_path / "orbax_ckpt")
    save_orbax(path, tree)
    template = jax.tree_util.tree_map(jnp.zeros_like, tree)
    back = load_orbax(path, template)
    assert back["nested"]["b"].dtype == jnp.bfloat16
    for a, b in zip(
        jax.tree_util.tree_leaves(tree), jax.tree_util.tree_leaves(back)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_orbax_overwrite_and_abstract_template(tmp_path):
    """Repeated saves to one path overwrite (native semantics), and an
    abstract (ShapeDtypeStruct) template restores without materializing
    zeros first."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest

    pytest.importorskip("orbax.checkpoint")
    from defer_tpu.runtime.checkpoint import load_orbax, save_orbax

    path = str(tmp_path / "ck")
    save_orbax(path, {"w": jnp.zeros((2, 2))})
    tree = {"w": jnp.full((2, 2), 3.0)}
    save_orbax(path, tree)  # must not raise 'already exists'
    abstract = {"w": jax.ShapeDtypeStruct((2, 2), jnp.float32)}
    back = load_orbax(path, abstract)
    np.testing.assert_array_equal(np.asarray(back["w"]), np.asarray(tree["w"]))


def test_orbax_mixed_tree_scalars_restore_jit_compatible(
    tmp_path, devices
):
    """Same jit-compatibility contract as restore_sharded: when the
    tree mixes multi-device params with default-device scalars, the
    scalars must come back UNCOMMITTED, or the next jit rejects them
    alongside the sharded params ('incompatible devices')."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    pytest.importorskip("orbax.checkpoint")
    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.runtime.checkpoint import load_orbax, save_orbax

    mesh = make_mesh({"data": 2}, devices[:2])
    tree = {
        "step": jnp.int32(3),
        "w": jax.device_put(
            jnp.arange(4.0), NamedSharding(mesh, P("data"))
        ),
    }
    path = str(tmp_path / "ck")
    save_orbax(path, tree)
    back = load_orbax(path, tree)
    assert not back["step"]._committed
    assert back["w"].sharding == tree["w"].sharding
    # The restored mix must be jit-consumable in one computation.
    out = jax.jit(lambda s, w: w.sum() + s)(back["step"], back["w"])
    np.testing.assert_allclose(float(out), 9.0)


def test_llama_decoder_params_round_trip(tmp_path):
    """The llama pytree (conditional keys: no biases, rms scales only,
    swiglu w3, no pos table) survives the checkpoint format and decodes
    to identical tokens."""
    from defer_tpu.models.llama import tiny_llama

    dec = tiny_llama()
    params = dec.init(jax.random.key(0))
    path = str(tmp_path / "llama.ckpt")
    save_checkpoint(path, params)
    restored = load_checkpoint(path)
    assert set(restored["stack"]) == set(params["stack"])
    prompt = jnp.zeros((2, 3), jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(dec.generate(restored, prompt, 4)),
        np.asarray(dec.generate(params, prompt, 4)),
    )
