"""Retirer / hard_sync_timeout unit tests (no device dependencies —
fake futures exercise the windowed-retire logic directly)."""

import threading
import time

import pytest

from defer_tpu.utils.sync import Retirer, hard_sync_timeout


class FakeFuture:
    def __init__(self, ready=False):
        self._ready = ready

    def is_ready(self):
        return self._ready


def test_retirer_emits_ready_prefix_in_order():
    done = [FakeFuture(True), FakeFuture(True), FakeFuture(False)]
    r = Retirer(depth=10, sync=lambda a: None)
    out = []
    for f in done:
        out.extend(r.add(f))
    assert out == done[:2]
    assert list(r.pending) == [done[2]]


def test_retirer_pressure_retires_through_synced_item():
    synced = []
    r = Retirer(depth=4, sync=synced.append)
    futs = [FakeFuture(False) for _ in range(4)]
    out = []
    for f in futs:
        out.extend(r.add(f))
    # At depth, one barrier on the middle of the window retires the
    # prefix through the synced item — no index math on a mutated queue.
    assert synced == [futs[2]]
    assert out == futs[:3]
    assert list(r.pending) == [futs[3]]


def test_retirer_survives_sync_that_marks_items_ready():
    # The regression from the review: a sync callback that causes items
    # to become ready (as the watchdog barrier does while waiting) must
    # not over-retire or raise.
    r = Retirer(depth=2, sync=lambda a: None)
    a, b = FakeFuture(False), FakeFuture(False)

    def sync(target):
        a._ready = b._ready = True

    r.sync = sync
    out = r.add(a)
    out += r.add(b)
    assert out == [a, b]
    assert not r.pending


def test_retirer_flush_returns_everything():
    r = Retirer(depth=100, sync=lambda a: None)
    futs = [FakeFuture(False) for _ in range(5)]
    for f in futs:
        r.add(f)
    assert r.flush() == futs
    assert r.flush() == []


def test_hard_sync_timeout_dedups_inflight_fetches():
    # A slow array: repeated timed-out calls must share one fetch
    # thread, and the fetch must resolve once the array completes.
    release = threading.Event()

    class SlowArray:
        ndim = 0

        def __array__(self, dtype=None, copy=None):
            release.wait(5)
            import numpy as np

            return np.zeros((), np.float32)

    arr = SlowArray()
    n0 = threading.active_count()
    assert hard_sync_timeout(arr, 0.05) is False
    assert hard_sync_timeout(arr, 0.05) is False
    assert hard_sync_timeout(arr, 0.05) is False
    # One helper thread, not three.
    assert threading.active_count() <= n0 + 1
    release.set()
    assert hard_sync_timeout(arr, 5.0) is True


def test_hard_sync_timeout_propagates_fetch_errors():
    class BrokenArray:
        ndim = 0

        def __array__(self, dtype=None, copy=None):
            raise RuntimeError("xla runtime failure")

    with pytest.raises(RuntimeError, match="xla runtime failure"):
        hard_sync_timeout(BrokenArray(), 5.0)
        # The fetch thread may need a beat to surface the error.
        time.sleep(0.1)
        hard_sync_timeout(BrokenArray(), 5.0)
