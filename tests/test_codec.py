"""Transfer codec: native build, round trips, cross-backend decode."""

import numpy as np
import pytest

from defer_tpu.runtime import codec


@pytest.fixture(scope="module")
def native():
    lib = codec.load_native()
    if lib is None:
        pytest.skip("native codec unavailable (g++/zstd missing)")
    return lib


@pytest.mark.parametrize(
    "dtype", [np.float32, np.float16, np.int32, np.uint8, np.float64]
)
def test_round_trip_dtypes(native, dtype):
    rng = np.random.default_rng(0)
    arr = (rng.standard_normal((7, 33, 5)) * 10).astype(dtype)
    out = codec.decode(codec.encode(arr))
    np.testing.assert_array_equal(out, arr)
    assert out.dtype == arr.dtype


def test_round_trip_shapes(native):
    for shape in [(), (1,), (0,), (3, 0, 2), (1024,), (2, 3, 4, 5, 6)]:
        arr = np.arange(int(np.prod(shape)), dtype=np.float32).reshape(shape)
        out = codec.decode(codec.encode(arr))
        np.testing.assert_array_equal(out, arr)


def test_compresses_smooth_data(native):
    """Smooth float fields (the activations the reference ships) must
    compress well — the point of byteshuffle before entropy coding."""
    x = np.linspace(0, 1, 1 << 16, dtype=np.float32).reshape(256, 256)
    frame = codec.encode(x)
    assert len(frame) < x.nbytes / 4, (len(frame), x.nbytes)


def test_fallback_round_trip(monkeypatch):
    """zlib fallback must round-trip when the native lib is absent."""
    monkeypatch.setattr(codec, "load_native", lambda: None)
    arr = np.random.default_rng(1).standard_normal((17, 9)).astype(np.float32)
    frame = codec.encode(arr)
    out = codec.decode(frame)
    np.testing.assert_array_equal(out, arr)


def test_native_decodes_fallback_frames(native, monkeypatch):
    """Wire format is backend-agnostic: a zlib frame decodes on a host
    that has the native codec."""
    arr = np.random.default_rng(2).standard_normal((5, 5)).astype(np.float64)
    monkeypatch.setattr(codec, "load_native", lambda: None)
    frame = codec.encode(arr)
    monkeypatch.undo()
    out = codec.decode(frame)
    np.testing.assert_array_equal(out, arr)


def test_bad_frames_raise(native):
    with pytest.raises(ValueError, match="not a defer_tpu codec frame"):
        codec.decode(b"XXnope")
    arr = np.ones((4, 4), np.float32)
    frame = bytearray(codec.encode(arr))
    frame[-1] ^= 0xFF
    with pytest.raises(ValueError, match="corrupt"):
        codec.decode(bytes(frame))


def test_bfloat16_via_view(native):
    """bfloat16 (the TPU compute dtype) ships as a uint16 view."""
    import jax.numpy as jnp

    x = jnp.asarray(np.random.default_rng(3).standard_normal((8, 8)), jnp.bfloat16)
    view = np.asarray(x).view(np.uint16)
    out = codec.decode(codec.encode(view)).view(jnp.bfloat16.dtype)
    np.testing.assert_array_equal(out, np.asarray(x).view(np.uint16).view(jnp.bfloat16.dtype))


def test_q8_quantized_round_trip_error_bound(native):
    """Lossy int8 quantize-for-transfer: ~4x smaller payload, max abs
    error bounded by amax/127 (half a quantization step would be
    amax/254; rounding gives amax/127 worst case)."""
    rng = np.random.default_rng(1)
    arr = (rng.standard_normal((16, 128)) * 3).astype(np.float32)
    frame = codec.encode(arr, quantize="int8")
    lossless = codec.encode(arr)
    assert len(frame) < 0.5 * len(lossless)
    out = codec.decode(frame)
    assert out.dtype == np.float32 and out.shape == arr.shape
    step = float(np.abs(arr).max()) / 127.0
    assert float(np.abs(out - arr).max()) <= step * (0.5 + 1e-6)


def test_q8_edge_cases(native):
    # All-zero input: scale falls back to 1.0, exact round trip.
    z = np.zeros((4, 4), np.float32)
    np.testing.assert_array_equal(codec.decode(codec.encode(z, quantize="int8")), z)
    # Empty input.
    e = np.zeros((0, 3), np.float32)
    out = codec.decode(codec.encode(e, quantize="int8"))
    assert out.shape == (0, 3) and out.dtype == np.float32
    # Non-float input refused; unknown mode refused.
    with pytest.raises(ValueError, match="floating"):
        codec.encode(np.arange(4), quantize="int8")
    with pytest.raises(ValueError, match="unknown quantize"):
        codec.encode(z, quantize="fp4")


def test_q8_decodes_across_backends(native, monkeypatch):
    """A Q8 frame whose inner payload was zlib-encoded (fallback
    backend) must decode on a native host and vice versa."""
    arr = np.linspace(-2, 2, 64, dtype=np.float32).reshape(8, 8)
    native_frame = codec.encode(arr, quantize="int8")
    monkeypatch.setattr(codec, "_lib", None)
    monkeypatch.setattr(codec, "_lib_tried", True)
    fallback_frame = codec.encode(arr, quantize="int8")
    out_fb = codec.decode(fallback_frame)  # fallback decodes fallback
    monkeypatch.setattr(codec, "_lib_tried", False)
    monkeypatch.setattr(codec, "_lib", None)
    out_n1 = codec.decode(fallback_frame)  # native decodes fallback
    out_n2 = codec.decode(native_frame)
    np.testing.assert_array_equal(out_fb, out_n1)
    np.testing.assert_allclose(out_n1, out_n2, atol=1e-7)


def test_transport_quantize_mode(native):
    """ArraySender(quantize='int8'): float arrays arrive quantized,
    integer arrays arrive bit-exact."""
    import threading

    from defer_tpu.runtime.transport import ArrayReceiver, ArraySender

    recv = ArrayReceiver(port=0)
    got = []

    def drain():
        got.extend(recv)

    t = threading.Thread(target=drain, daemon=True)
    t.start()
    snd = ArraySender("127.0.0.1", recv.port, quantize="int8")
    f = np.linspace(-1, 1, 32, dtype=np.float32)
    i = np.arange(32, dtype=np.int32)
    snd.send(f)
    snd.send(i)
    snd.close()
    t.join(timeout=30)
    assert not t.is_alive() and len(got) == 2
    assert got[0].dtype == np.float32
    assert float(np.abs(got[0] - f).max()) <= 1.0 / 127.0
    np.testing.assert_array_equal(got[1], i)


def test_q8_rejects_non_finite_and_bad_sender_mode(native):
    bad = np.array([1.0, np.inf], np.float32)
    with pytest.raises(ValueError, match="finite"):
        codec.encode(bad, quantize="int8")
    with pytest.raises(ValueError, match="finite"):
        codec.encode(np.array([np.nan], np.float32), quantize="int8")
    from defer_tpu.runtime.transport import ArraySender

    with pytest.raises(ValueError, match="unknown quantize"):
        ArraySender("127.0.0.1", 1, quantize="int4")


def test_q8_fuzz_kv_shaped_round_trip():
    """Round-trip fuzz for the int8 path on KV-block-shaped tensors
    (the disagg transfer payload, [L, n_blocks, Hkv, bs, Dh]): odd
    block tails (zero-padded rows), empty stacks, both float dtypes,
    and tiny-magnitude tensors whose amax/127 would underflow to a
    zero scale without the encoder's guard. Runs on whichever backend
    is available — the scheme is backend-agnostic."""
    rng = np.random.default_rng(42)
    shapes = [
        (2, 1, 1, 4, 8),    # single block
        (2, 3, 2, 4, 8),    # odd block count
        (4, 2, 1, 16, 4),   # serving-default block_size
        (2, 0, 2, 4, 8),    # empty stack (zero blocks)
    ]
    for shape in shapes:
        for dtype in (np.float32, np.float16):
            arr = (rng.standard_normal(shape) * 2.5).astype(dtype)
            if arr.size:
                # zero-pad a tail block's later rows, like a prompt
                # that does not fill its last block
                arr[:, -1:, :, 2:, :] = 0
            out = codec.decode(codec.encode(arr, quantize="int8"))
            assert out.dtype == dtype and out.shape == arr.shape
            if arr.size == 0:
                continue
            step = float(np.abs(arr.astype(np.float64)).max()) / 127.0
            err = float(
                np.abs(out.astype(np.float64) - arr.astype(np.float64)).max()
            )
            # float16 re-rounds the dequantized value onto its own
            # grid: allow an extra half-ulp of the largest magnitude.
            slack = (
                step * 0.5 + np.spacing(np.float16(np.abs(arr).max()))
                if dtype == np.float16
                else step * 0.5
            )
            assert err <= slack * (1 + 1e-6), (shape, dtype, err, slack)
            # exact-zero rows stay exactly zero (0 / scale rounds to 0)
            np.testing.assert_array_equal(
                out[:, -1:, :, 2:, :], np.zeros_like(out[:, -1:, :, 2:, :])
            )


def test_q8_subnormal_scale_guard():
    """amax small enough that amax/127 underflows to 0.0 must not
    divide by zero into clipped +/-127 garbage — values this small
    round to zero at int8 precision."""
    tiny = np.full((3, 3), 4e-324, np.float64)  # smallest subnormal
    out = codec.decode(codec.encode(tiny, quantize="int8"))
    assert np.all(np.isfinite(out))
    assert float(np.abs(out).max()) <= 4e-324
