"""Transfer codec: native build, round trips, cross-backend decode."""

import numpy as np
import pytest

from defer_tpu.runtime import codec


@pytest.fixture(scope="module")
def native():
    lib = codec.load_native()
    if lib is None:
        pytest.skip("native codec unavailable (g++/zstd missing)")
    return lib


@pytest.mark.parametrize(
    "dtype", [np.float32, np.float16, np.int32, np.uint8, np.float64]
)
def test_round_trip_dtypes(native, dtype):
    rng = np.random.default_rng(0)
    arr = (rng.standard_normal((7, 33, 5)) * 10).astype(dtype)
    out = codec.decode(codec.encode(arr))
    np.testing.assert_array_equal(out, arr)
    assert out.dtype == arr.dtype


def test_round_trip_shapes(native):
    for shape in [(), (1,), (0,), (3, 0, 2), (1024,), (2, 3, 4, 5, 6)]:
        arr = np.arange(int(np.prod(shape)), dtype=np.float32).reshape(shape)
        out = codec.decode(codec.encode(arr))
        np.testing.assert_array_equal(out, arr)


def test_compresses_smooth_data(native):
    """Smooth float fields (the activations the reference ships) must
    compress well — the point of byteshuffle before entropy coding."""
    x = np.linspace(0, 1, 1 << 16, dtype=np.float32).reshape(256, 256)
    frame = codec.encode(x)
    assert len(frame) < x.nbytes / 4, (len(frame), x.nbytes)


def test_fallback_round_trip(monkeypatch):
    """zlib fallback must round-trip when the native lib is absent."""
    monkeypatch.setattr(codec, "load_native", lambda: None)
    arr = np.random.default_rng(1).standard_normal((17, 9)).astype(np.float32)
    frame = codec.encode(arr)
    out = codec.decode(frame)
    np.testing.assert_array_equal(out, arr)


def test_native_decodes_fallback_frames(native, monkeypatch):
    """Wire format is backend-agnostic: a zlib frame decodes on a host
    that has the native codec."""
    arr = np.random.default_rng(2).standard_normal((5, 5)).astype(np.float64)
    monkeypatch.setattr(codec, "load_native", lambda: None)
    frame = codec.encode(arr)
    monkeypatch.undo()
    out = codec.decode(frame)
    np.testing.assert_array_equal(out, arr)


def test_bad_frames_raise(native):
    with pytest.raises(ValueError, match="not a defer_tpu codec frame"):
        codec.decode(b"XXnope")
    arr = np.ones((4, 4), np.float32)
    frame = bytearray(codec.encode(arr))
    frame[-1] ^= 0xFF
    with pytest.raises(ValueError, match="corrupt"):
        codec.decode(bytes(frame))


def test_bfloat16_via_view(native):
    """bfloat16 (the TPU compute dtype) ships as a uint16 view."""
    import jax.numpy as jnp

    x = jnp.asarray(np.random.default_rng(3).standard_normal((8, 8)), jnp.bfloat16)
    view = np.asarray(x).view(np.uint16)
    out = codec.decode(codec.encode(view)).view(jnp.bfloat16.dtype)
    np.testing.assert_array_equal(out, np.asarray(x).view(np.uint16).view(jnp.bfloat16.dtype))
