"""T5 encoder-decoder: relative-position-bias attention, cross-
attention with precomputed K/V, KV-cached incremental decode — cross-
validated against HuggingFace transformers' T5ForConditionalGeneration
(the seq2seq analogue of the Keras CNN parity suite, reference
src/node.py:38-45)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models.t5 import (
    T5,
    T5Config,
    from_hf_state_dict,
    relative_position_bucket,
    t5_config,
    tiny_t5,
)


def test_config_validation():
    with pytest.raises(ValueError, match="ffn_style"):
        T5Config(ffn_style="swiglu")
    with pytest.raises(ValueError, match="rel_buckets"):
        T5Config(rel_buckets=7)
    # max_distance inside the exact-bucket range would make the causal
    # log-bucket denominator zero -> NaN bucket indices.
    with pytest.raises(ValueError, match="rel_max_distance"):
        T5Config(rel_buckets=32, rel_max_distance=16)
    assert t5_config("base").dim == 768
    with pytest.raises(KeyError):
        t5_config("xxl-imagined")


def test_prefill_guards_cache_overflow():
    """dynamic_update_slice clamps out-of-range starts, so the guarded
    prefill must refuse a write past max_len instead of silently
    corrupting live cache rows."""
    m = tiny_t5()
    params = m.init(jax.random.key(0))
    enc_out = m.encode(params, jnp.zeros((1, 4), jnp.int32))
    cache = m.start_cache(params, enc_out)
    _, cache = m.prefill(
        params, cache, jnp.zeros((1, m.cfg.max_len - 2), jnp.int32)
    )
    with pytest.raises(ValueError, match="max_len"):
        m.prefill(params, cache, jnp.zeros((1, 3), jnp.int32))


def test_bucket_properties():
    """Sanity on the bucketing itself: zero distance is bucket 0,
    buckets are monotone in |distance| per direction, range is valid,
    and the two directions use disjoint halves in bidirectional mode."""
    rel = jnp.arange(-40, 41)
    b_bi = relative_position_bucket(
        rel, bidirectional=True, num_buckets=32, max_distance=128
    )
    b_ca = relative_position_bucket(
        rel, bidirectional=False, num_buckets=32, max_distance=128
    )
    assert int(b_bi[40]) == 0 and int(b_ca[40]) == 0  # rel == 0
    assert (np.asarray(b_bi) < 32).all() and (np.asarray(b_bi) >= 0).all()
    assert (np.asarray(b_ca) < 32).all() and (np.asarray(b_ca) >= 0).all()
    neg = np.asarray(b_bi[:40])  # rel < 0 (past)
    pos = np.asarray(b_bi[41:])  # rel > 0 (future)
    assert set(neg).isdisjoint(set(pos))
    # Causal mode: future positions all collapse to bucket 0.
    assert (np.asarray(b_ca[41:]) == 0).all()
    # Monotone non-increasing as rel goes from -40 toward 0.
    assert (np.diff(neg) <= 0).all()


def test_forward_shapes_and_finiteness():
    m = tiny_t5()
    params = m.init(jax.random.key(0))
    enc_ids = jax.random.randint(jax.random.key(1), (2, 7), 0, 96)
    dec_ids = jax.random.randint(jax.random.key(2), (2, 5), 0, 96)
    logits = m.forward(params, enc_ids, dec_ids)
    assert logits.shape == (2, 5, 96)
    assert bool(jnp.isfinite(logits).all())


def test_incremental_decode_matches_teacher_forcing():
    """The cached step (static buffers, position masks, precomputed
    cross K/V, unscaled logits + relative bias) must reproduce the
    full teacher-forced decoder position by position."""
    m = tiny_t5()
    params = m.init(jax.random.key(0))
    enc_ids = jax.random.randint(jax.random.key(1), (2, 7), 0, 96)
    dec_ids = jax.random.randint(jax.random.key(2), (2, 9), 0, 96)
    enc_out = m.encode(params, enc_ids)
    want = m.decode_logits(params, enc_out, dec_ids)

    step = m.make_step(donate=False)
    cache = m.start_cache(params, enc_out)
    logits, cache = step(params, cache, dec_ids[:, :4])  # prefill
    outs = [logits]
    for t in range(4, 9):
        logits, cache = step(params, cache, dec_ids[:, t : t + 1])
        outs.append(logits)
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate(outs, axis=1)),
        np.asarray(want),
        rtol=2e-4,
        atol=2e-5,
    )


def test_incremental_decode_gated_untied():
    """Same oracle for the v1.1 shape (gated-gelu FFN, untied head)."""
    m = tiny_t5(ffn_style="gated-gelu", tie_word_embeddings=False)
    params = m.init(jax.random.key(0))
    assert "lm_head" in params and "w3" in params["dec_stack"]
    enc_ids = jax.random.randint(jax.random.key(1), (1, 6), 0, 96)
    dec_ids = jax.random.randint(jax.random.key(2), (1, 6), 0, 96)
    enc_out = m.encode(params, enc_ids)
    want = m.decode_logits(params, enc_out, dec_ids)
    step = m.make_step(donate=False)
    cache = m.start_cache(params, enc_out)
    outs = []
    for t in range(6):
        logits, cache = step(params, cache, dec_ids[:, t : t + 1])
        outs.append(logits)
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate(outs, axis=1)),
        np.asarray(want),
        rtol=2e-4,
        atol=2e-5,
    )


def test_generate_shapes_and_determinism():
    m = tiny_t5()
    params = m.init(jax.random.key(0))
    enc_ids = jnp.zeros((2, 5), jnp.int32)
    a = m.generate(params, enc_ids, 6)
    b = m.generate(params, enc_ids, 6)
    assert a.shape == (2, 7)  # start token + 6 generated
    assert int(a[0, 0]) == m.cfg.decoder_start_token_id
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError, match="max_len"):
        m.generate(params, enc_ids, m.cfg.max_len)


def test_generate_stops_at_eos():
    """eos_id pins finished rows to eos and keeps the output shape."""
    m = tiny_t5()
    params = m.init(jax.random.key(0))
    enc = jax.random.randint(jax.random.key(1), (2, 6), 1, m.cfg.vocab_size)
    free = np.asarray(m.generate(params, enc, 8))
    eos = int(free[0, 1 + 2])  # row 0's third generated token
    out = np.asarray(m.generate(params, enc, 8, eos_id=eos))
    assert out.shape == free.shape
    for b in range(2):
        gen_free = free[b, 1:]
        hits = np.where(gen_free == eos)[0]
        cut = hits[0] if len(hits) else len(gen_free) - 1
        np.testing.assert_array_equal(
            out[b, 1 : 1 + cut + 1], gen_free[: cut + 1]
        )
        if len(hits):
            assert (out[b, 1 + cut :] == eos).all()


def test_cross_kv_precomputed_once():
    """start_cache materializes per-layer cross K/V from the encoder
    output; the step never touches ck/cv again (so a zeroed-out ck in
    params must not change step outputs once the cache exists)."""
    m = tiny_t5()
    params = m.init(jax.random.key(0))
    enc_ids = jax.random.randint(jax.random.key(1), (1, 5), 0, 96)
    enc_out = m.encode(params, enc_ids)
    cache = m.start_cache(params, enc_out)
    assert cache["cross_k"].shape == (
        m.cfg.dec_layers, 1, m.cfg.num_heads, 5, m.cfg.head_dim,
    )
    step = m.make_step(donate=False)
    ids = jnp.zeros((1, 1), jnp.int32)
    want, _ = step(params, cache, ids)
    broken = {
        **params,
        "dec_stack": {
            **params["dec_stack"],
            "ck": jnp.zeros_like(params["dec_stack"]["ck"]),
        },
    }
    got, _ = step(broken, cache, ids)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tp_generate_matches_single_device(devices):
    """tp=2 sharded T5 (head-group-sharded caches + head-sliced rel
    bias + vocab-sharded embedding/head) produces the single-device
    tokens; vocab 97 exercises the pad-to-tp path."""
    from defer_tpu.models.t5 import spmd_t5
    from defer_tpu.parallel.mesh import make_mesh

    single = tiny_t5(vocab_size=97)
    params = single.init(jax.random.key(0))
    enc_ids = jax.random.randint(jax.random.key(1), (2, 6), 0, 97)
    want = single.generate(params, enc_ids, 5)

    mesh = make_mesh({"model": 2}, devices[:2])
    tp = spmd_t5(mesh, single.cfg, compute_dtype=jnp.float32)
    got = tp.generate(tp.shard_params(params), enc_ids, 5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tp_logits_match_single_device(devices):
    """tp=4 sharded incremental step reproduces single-device logits
    (not just argmax tokens) for the v1.1 gated/untied shape."""
    from defer_tpu.models.t5 import spmd_t5
    from defer_tpu.parallel.mesh import make_mesh

    single = tiny_t5(ffn_style="gated-gelu", tie_word_embeddings=False)
    params = single.init(jax.random.key(0))
    enc_ids = jax.random.randint(jax.random.key(1), (1, 5), 0, 96)
    dec_ids = jax.random.randint(jax.random.key(2), (1, 4), 0, 96)

    enc_out = single.encode(params, enc_ids)
    cache = single.start_cache(params, enc_out)
    want, _ = single.make_step(donate=False)(params, cache, dec_ids)

    mesh = make_mesh({"model": 4}, devices[:4])
    tp = spmd_t5(mesh, single.cfg, compute_dtype=jnp.float32)
    sp = tp.shard_params(params)
    ones = jnp.ones(enc_ids.shape, jnp.int32)
    _, tcache = tp.make_encode()(sp, enc_ids, ones)
    got, _ = tp.make_step(donate=False)(sp, tcache, dec_ids)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
    )


def test_tp_teacher_forced_forward_matches(devices):
    """SpmdT5.make_forward (the tp training/eval path) reproduces the
    single-device teacher-forced logits, masked ragged batch included."""
    from defer_tpu.models.t5 import spmd_t5
    from defer_tpu.parallel.mesh import make_mesh

    single = tiny_t5(vocab_size=97)
    params = single.init(jax.random.key(0))
    enc_ids = jax.random.randint(jax.random.key(1), (2, 6), 1, 97)
    dec_ids = jax.random.randint(jax.random.key(2), (2, 4), 0, 97)
    mask = jnp.asarray([[1, 1, 1, 1, 0, 0], [1, 1, 1, 1, 1, 1]], jnp.int32)
    want = single.forward(params, enc_ids, dec_ids, enc_mask=mask)

    mesh = make_mesh({"model": 2}, devices[:2])
    tp = spmd_t5(mesh, single.cfg, compute_dtype=jnp.float32)
    got = tp.make_forward()(tp.shard_params(params), enc_ids, dec_ids, mask)
    assert got.shape == (2, 4, 97)  # pad vocab rows sliced off
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
    )


def test_tp_direct_forward_slices_pad_vocab(devices):
    """Calling the inherited training forward directly on shard_params
    output (GSPMD, no shard_map) must also hide the tp vocab padding:
    [B, T, 97], not [B, T, 98], and match the single-device logits."""
    from defer_tpu.models.t5 import spmd_t5
    from defer_tpu.parallel.mesh import make_mesh

    single = tiny_t5(vocab_size=97)
    params = single.init(jax.random.key(0))
    enc_ids = jax.random.randint(jax.random.key(1), (2, 6), 1, 97)
    dec_ids = jax.random.randint(jax.random.key(2), (2, 4), 0, 97)
    want = single.forward(params, enc_ids, dec_ids)

    mesh = make_mesh({"model": 2}, devices[:2])
    tp = spmd_t5(mesh, single.cfg, compute_dtype=jnp.float32)
    got = tp.forward(tp.shard_params(params), enc_ids, dec_ids)
    assert got.shape == (2, 4, 97)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
    )


def test_all_pad_row_stays_finite():
    """A zero-length input (all-pad mask row) must not poison the
    batch with NaN — the finite mask constant keeps its logits
    garbage-but-finite and other rows exact."""
    m = tiny_t5()
    params = m.init(jax.random.key(0))
    enc_ids = jax.random.randint(jax.random.key(1), (2, 5), 1, 96)
    dec = jnp.zeros((2, 3), jnp.int32)
    mask = jnp.asarray([[0, 0, 0, 0, 0], [1, 1, 1, 0, 0]], jnp.int32)
    logits = m.forward(params, enc_ids, dec, enc_mask=mask)
    assert bool(jnp.isfinite(logits).all())
    # The healthy row is unaffected by its all-pad neighbour.
    want = m.forward(
        params, enc_ids[1:], dec[1:], enc_mask=mask[1:]
    )
    np.testing.assert_allclose(
        np.asarray(logits[1:]), np.asarray(want), rtol=2e-4, atol=2e-5
    )


def test_enc_mask_matches_unpadded_run():
    """A padded batch with enc_mask must generate the same tokens as
    the unpadded sequence — pad keys excluded from encoder self-
    attention and from every cached cross-attention step."""
    m = tiny_t5()
    params = m.init(jax.random.key(0))
    real = jax.random.randint(jax.random.key(1), (1, 5), 1, 96)
    want = m.generate(params, real, 6)

    padded = jnp.concatenate(
        [real, jnp.zeros((1, 4), real.dtype)], axis=1
    )
    mask = jnp.concatenate(
        [jnp.ones((1, 5), jnp.int32), jnp.zeros((1, 4), jnp.int32)], axis=1
    )
    got = m.generate(params, padded, 6, enc_mask=mask)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # ... and the mask genuinely matters: without it the pad keys leak
    # into attention and perturb the logits.
    dec = jnp.zeros((1, 3), jnp.int32)
    with_mask = m.forward(params, padded, dec, enc_mask=mask)
    without = m.forward(params, padded, dec)
    assert not np.allclose(
        np.asarray(with_mask), np.asarray(without), atol=1e-5
    )


def test_spmd_t5_validates_mesh_and_divisibility(devices):
    from defer_tpu.models.t5 import SpmdT5, spmd_t5
    from defer_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match="mesh"):
        SpmdT5(tiny_t5().cfg, mesh=None)
    mesh = make_mesh({"model": 8}, devices)
    with pytest.raises(ValueError, match="divide"):
        spmd_t5(mesh, tiny_t5().cfg)  # 4 heads cannot shard over tp=8


@pytest.mark.slow
def test_hf_t5_bucket_parity():
    """Bucketing vs transformers' T5Attention._relative_position_bucket
    over a wide relative-position range, both directions."""
    pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    import torch

    from transformers.models.t5.modeling_t5 import T5Attention

    rel = np.arange(-300, 301).reshape(1, -1)
    for bidirectional in (True, False):
        want = T5Attention._relative_position_bucket(
            torch.from_numpy(rel),
            bidirectional=bidirectional,
            num_buckets=32,
            max_distance=128,
        ).numpy()
        got = np.asarray(
            relative_position_bucket(
                jnp.asarray(rel),
                bidirectional=bidirectional,
                num_buckets=32,
                max_distance=128,
            )
        )
        np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_hf_t5_parity():
    """Transplant a transformers T5ForConditionalGeneration state_dict
    and require encoder-output AND logits parity with HF's forward —
    proving the relative bias, UNSCALED attention logits, RMSNorm
    placement and tied-head scaling all match the ecosystem."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.T5Config(
        vocab_size=96,
        d_model=32,
        d_kv=8,
        d_ff=64,
        num_layers=2,
        num_heads=4,
        relative_attention_num_buckets=8,
        relative_attention_max_distance=20,
        dropout_rate=0.0,
        feed_forward_proj="relu",
        tie_word_embeddings=True,
        decoder_start_token_id=0,
    )
    torch.manual_seed(0)
    hf = transformers.T5ForConditionalGeneration(hf_cfg).eval()

    m = tiny_t5()
    params = from_hf_state_dict(m.cfg, hf.state_dict())
    assert "lm_head" not in params  # tied

    rs = np.random.RandomState(0)
    enc_np = rs.randint(0, 96, size=(2, 7))
    dec_np = rs.randint(0, 96, size=(2, 5))
    with torch.no_grad():
        enc_want = (
            hf.encoder(input_ids=torch.from_numpy(enc_np))
            .last_hidden_state.numpy()
        )
        want = hf(
            input_ids=torch.from_numpy(enc_np),
            decoder_input_ids=torch.from_numpy(dec_np),
        ).logits.numpy()
    enc_got = np.asarray(m.encode(params, jnp.asarray(enc_np)))
    np.testing.assert_allclose(enc_got, enc_want, rtol=2e-3, atol=2e-4)
    got = np.asarray(
        m.forward(params, jnp.asarray(enc_np), jnp.asarray(dec_np))
    )
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)


@pytest.mark.slow
def test_hf_transplant_tie_mismatch_is_loud():
    """A checkpoint whose head tying disagrees with the config must
    raise — _head applies the tied-only dim**-0.5 scaling, so a silent
    mismatch would put every logit off by sqrt(dim)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.T5Config(
        vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=20, dropout_rate=0.0,
        feed_forward_proj="relu", tie_word_embeddings=False,
        decoder_start_token_id=0,
    )
    torch.manual_seed(3)
    hf = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    with pytest.raises(ValueError, match="tie_word_embeddings"):
        from_hf_state_dict(tiny_t5().cfg, hf.state_dict())  # cfg ties

    tied = transformers.T5ForConditionalGeneration(
        transformers.T5Config(
            **{**hf_cfg.to_dict(), "tie_word_embeddings": True}
        )
    ).eval()
    untied_cfg = tiny_t5(tie_word_embeddings=False).cfg
    with pytest.raises(ValueError, match="tie_word_embeddings"):
        from_hf_state_dict(untied_cfg, tied.state_dict())


@pytest.mark.slow
def test_hf_t5_masked_parity():
    """Padded batch + attention_mask: logits parity with HF at every
    REAL decoder position (HF masks with a large-negative constant
    rather than -inf, so only real-token logits are comparable)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.T5Config(
        vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=20, dropout_rate=0.0,
        feed_forward_proj="relu", tie_word_embeddings=True,
        decoder_start_token_id=0,
    )
    torch.manual_seed(2)
    hf = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    m = tiny_t5()
    params = from_hf_state_dict(m.cfg, hf.state_dict())

    rs = np.random.RandomState(3)
    enc_np = rs.randint(1, 96, size=(2, 8))
    enc_np[0, 5:] = 0  # row 0 padded from length 5
    mask_np = np.ones((2, 8), np.int64)
    mask_np[0, 5:] = 0
    dec_np = rs.randint(0, 96, size=(2, 4))
    with torch.no_grad():
        want = hf(
            input_ids=torch.from_numpy(enc_np),
            attention_mask=torch.from_numpy(mask_np),
            decoder_input_ids=torch.from_numpy(dec_np),
        ).logits.numpy()
    got = np.asarray(
        m.forward(
            params,
            jnp.asarray(enc_np),
            jnp.asarray(dec_np),
            enc_mask=jnp.asarray(mask_np),
        )
    )
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)


@pytest.mark.slow
def test_hf_t5_v11_parity():
    """The v1.1 shape: gated-gelu FFN + untied lm_head (no output
    scaling) against HF."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.T5Config(
        vocab_size=96,
        d_model=32,
        d_kv=8,
        d_ff=64,
        num_layers=2,
        num_heads=4,
        relative_attention_num_buckets=8,
        relative_attention_max_distance=20,
        dropout_rate=0.0,
        feed_forward_proj="gated-gelu",
        tie_word_embeddings=False,
        decoder_start_token_id=0,
    )
    torch.manual_seed(1)
    hf = transformers.T5ForConditionalGeneration(hf_cfg).eval()

    m = tiny_t5(ffn_style="gated-gelu", tie_word_embeddings=False)
    params = from_hf_state_dict(m.cfg, hf.state_dict())
    assert "lm_head" in params

    rs = np.random.RandomState(1)
    enc_np = rs.randint(0, 96, size=(2, 6))
    dec_np = rs.randint(0, 96, size=(2, 4))
    with torch.no_grad():
        want = hf(
            input_ids=torch.from_numpy(enc_np),
            decoder_input_ids=torch.from_numpy(dec_np),
        ).logits.numpy()
    got = np.asarray(
        m.forward(params, jnp.asarray(enc_np), jnp.asarray(dec_np))
    )
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)
