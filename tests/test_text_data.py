"""Packed token pipeline: stream layout, determinism, and the LM
train-step contract."""

import numpy as np
import pytest

from defer_tpu.runtime.text_data import (
    lm_batches,
    pack_documents,
    token_count,
)

EOS = 99


def test_pack_stream_layout():
    """Documents concatenate with eos separators; windows tile the
    stream exactly, in order, with no token lost before the tail."""
    docs = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
    rows = list(pack_documents(docs, 4, eos_id=EOS))
    stream = [1, 2, 3, EOS, 4, 5, EOS, 6, 7, 8, 9, EOS]
    assert [r.tolist() for r in rows] == [stream[0:4], stream[4:8], stream[8:12]]
    assert token_count(docs) == 12


def test_pack_tail_handling():
    docs = [[1, 2, 3, 4, 5]]  # stream of 6 with eos
    rows = list(pack_documents(docs, 4, eos_id=EOS))
    assert len(rows) == 1  # ragged tail dropped by default
    rows = list(pack_documents(docs, 4, eos_id=EOS, drop_remainder=False))
    assert len(rows) == 2
    assert rows[1].tolist() == [5, EOS, EOS, EOS]  # eos-padded tail


def test_pack_validates():
    with pytest.raises(ValueError, match="seq_len"):
        list(pack_documents([[1]], 1, eos_id=EOS))
    with pytest.raises(ValueError, match="1-D"):
        list(pack_documents([np.zeros((2, 2))], 4, eos_id=EOS))


def test_lm_batches_shape_and_determinism():
    rng = np.random.default_rng(0)
    docs = [rng.integers(0, 90, size=rng.integers(3, 30)).tolist()
            for _ in range(40)]
    a = list(lm_batches(docs, seq_len=16, batch=2, num_microbatches=3,
                        eos_id=EOS, seed=7))
    b = list(lm_batches(docs, seq_len=16, batch=2, num_microbatches=3,
                        eos_id=EOS, seed=7))
    c = list(lm_batches(docs, seq_len=16, batch=2, num_microbatches=3,
                        eos_id=EOS, seed=8))
    assert a and all(x.shape == (3, 2, 16) and x.dtype == np.int32 for x in a)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))
    # Every document token appears somewhere (full blocks only).
    total = token_count(docs)
    produced = sum(x.size for x in a)
    assert produced <= total and produced >= total - 3 * 2 * 16


def test_lm_batches_rejects_too_small_corpus():
    """A corpus that cannot fill one block must fail loudly, not yield
    nothing (a training loop would 'complete' with zero steps)."""
    with pytest.raises(ValueError, match="add documents"):
        list(lm_batches([[1, 2, 3]], seq_len=16, batch=4,
                        num_microbatches=4, eos_id=EOS))


def test_lm_batches_feed_train_step(devices):
    """The pipeline's blocks drive make_lm_train_step directly and the
    model learns a memorizable corpus."""
    import jax
    import jax.numpy as jnp
    import optax

    from defer_tpu.models.bert import SpmdBert
    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.parallel.train import make_lm_train_step
    from defer_tpu.parallel.transformer_stack import TransformerConfig

    docs = [[1, 2, 3, 4, 5, 6, 7] for _ in range(64)]  # memorizable
    cfg = TransformerConfig(
        num_layers=2, dim=32, num_heads=4, ffn_dim=64, vocab_size=100,
        max_len=16, norm_style="pre", causal=True,
    )
    mesh = make_mesh({"data": 2, "stage": 2}, devices[:4])
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    init_state, step = make_lm_train_step(sb, optax.adam(1e-2))
    state = init_state(jax.random.key(0))
    losses = []
    for block in lm_batches(
        docs, seq_len=16, batch=2, num_microbatches=2, eos_id=EOS,
        seed=0, epochs=8,
    ):
        state, loss = step(state, jnp.asarray(block))
        losses.append(float(loss))
    assert len(losses) >= 8
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])