"""Multi-LoRA serving: one batch, per-slot adapters, each request's
output matching a solo decode of that adapter merged into the base."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models.gpt import GptDecoder
from defer_tpu.parallel.lora import merge_lora, stack_adapters
from defer_tpu.parallel.transformer_stack import (
    TransformerConfig,
    init_stack,
)
from defer_tpu.runtime.decode_server import DecodeServer

BASE_CFG = dict(
    num_layers=2, dim=32, num_heads=4, ffn_dim=64, vocab_size=64,
    max_len=32, norm_style="pre", causal=True,
)


def _adapter_tree(seed, lora_cfg):
    """A fat-fingered fine-tune: random a AND b factors (flat [L, ...]
    stack layout, the decoder's)."""
    full = init_stack(jax.random.key(seed), lora_cfg)
    tree = {"stack": {}}
    for k, v in full.items():
        if k.endswith(":a"):
            tree["stack"][k] = v
        elif k.endswith(":b"):
            tree["stack"][k] = (
                jax.random.normal(jax.random.fold_in(jax.random.key(seed), 1),
                                  v.shape) * 0.3
            )
    return tree


def _setup():
    lora_cfg = TransformerConfig(
        **BASE_CFG, lora_rank=4, lora_alpha=8.0,
        lora_targets=("wq", "wv", "w1", "w2"),
    )
    dec = GptDecoder(TransformerConfig(**BASE_CFG), compute_dtype=jnp.float32)
    base = dec.init(jax.random.key(0))
    trees = [_adapter_tree(s, lora_cfg) for s in (11, 22)]
    return dec, base, trees, lora_cfg


def test_multilora_batch_matches_per_adapter_merge():
    """Requests on adapters 1, 2, and 0 (base) served in ONE batch
    each reproduce the solo greedy decode of that adapter merged into
    the weights (id 0 = the plain base model)."""
    dec, base, trees, lora_cfg = _setup()
    params = stack_adapters(base, trees, lora_cfg)
    assert params["stack"]["wq:a"].shape[1] == 3  # zero + 2 tenants

    reqs = [
        (jnp.asarray([[3, 9, 27]], jnp.int32), 6, 1),
        (jnp.asarray([[5, 1]], jnp.int32), 5, 2),
        (jnp.asarray([[11, 2, 8]], jnp.int32), 4, 0),
    ]
    srv = DecodeServer(dec, params, max_batch=2)
    assert srv.multi_lora and srv.num_adapters == 3
    rids = [
        srv.submit(p, s, adapter_id=a) for p, s, a in reqs
    ]
    done = srv.run()

    for (p, s, a), rid in zip(reqs, rids):
        if a == 0:
            solo_params = base
        else:
            tree = trees[a - 1]
            solo_params = merge_lora(
                {**base, "stack": {**base["stack"], **tree["stack"]}},
                lora_cfg,
            )
        want = dec.generate(solo_params, p, s)
        np.testing.assert_array_equal(
            np.asarray(done[rid]), np.asarray(want),
            err_msg=f"adapter {a}",
        )


def test_adapter_zero_is_exact_base():
    """The reserved zero adapter changes NOTHING: a multi-LoRA server
    with every request on id 0 equals the plain server bit for bit."""
    dec, base, trees, lora_cfg = _setup()
    params = stack_adapters(base, trees, lora_cfg)
    p = jnp.asarray([[7, 3, 1]], jnp.int32)
    srv = DecodeServer(dec, params, max_batch=1)
    rid = srv.submit(p, 6)
    got = srv.run()[rid]
    want = dec.generate(base, p, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_stack_adapters_validation_and_submit_guards():
    dec, base, trees, lora_cfg = _setup()
    with pytest.raises(ValueError, match="no adapter trees"):
        stack_adapters(base, [], lora_cfg)
    broken = {"stack": {k: v for k, v in trees[0]["stack"].items()
                        if not k.startswith("w1")}}
    with pytest.raises(ValueError, match="disagree"):
        stack_adapters(base, [trees[0], broken], lora_cfg)

    params = stack_adapters(base, trees, lora_cfg)
    srv = DecodeServer(dec, params, max_batch=1)
    with pytest.raises(ValueError, match="out of range"):
        srv.submit(jnp.asarray([[1]], jnp.int32), 2, adapter_id=9)
    plain = DecodeServer(dec, base, max_batch=1)
    with pytest.raises(ValueError, match="no adapter banks"):
        plain.submit(jnp.asarray([[1]], jnp.int32), 2, adapter_id=1)
    with pytest.raises(ValueError, match="multi-LoRA"):
        DecodeServer(
            dec, params, max_batch=1,
            prefix_ids=jnp.asarray([[1, 2]], jnp.int32),
        )
    # An unmerged single-LoRA training tree (3-D factors) is rejected
    # loudly, not mistaken for a stacked bank — by both servers.
    unmerged = {
        **base,
        "stack": {**base["stack"], **trees[0]["stack"]},
    }
    with pytest.raises(ValueError, match="unmerged"):
        DecodeServer(dec, unmerged, max_batch=1)
    from defer_tpu.runtime.paged import PagedDecodeServer

    with pytest.raises(ValueError, match="unmerged"):
        PagedDecodeServer(dec, unmerged, num_blocks=4, block_size=8)


def test_paged_multilora_matches_per_adapter_merge():
    """The paged server serves tenants too: block-pool cache + per-slot
    adapter banks, each output equal to its merged solo decode."""
    from defer_tpu.runtime.paged import serve_paged

    dec, base, trees, lora_cfg = _setup()
    params = stack_adapters(base, trees, lora_cfg)
    reqs = [
        (jnp.asarray([[3, 9, 27]], jnp.int32), 6),
        (jnp.asarray([[5, 1]], jnp.int32), 5),
        (jnp.asarray([[11, 2, 8]], jnp.int32), 4),
    ]
    aids = [1, 2, 0]
    outs, _ = serve_paged(
        dec, params, reqs, num_blocks=10, block_size=8, max_batch=2,
        adapter_ids=aids,
    )
    for (p, s), a, got in zip(reqs, aids, outs):
        if a == 0:
            solo = base
        else:
            solo = merge_lora(
                {**base, "stack": {**base["stack"], **trees[a - 1]["stack"]}},
                lora_cfg,
            )
        want = dec.generate(solo, p, s)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want), err_msg=f"adapter {a}"
        )
