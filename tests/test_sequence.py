"""Ring / Ulysses sequence parallelism vs single-device attention, on
the 8-virtual-device CPU mesh (SURVEY.md §4 test strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from defer_tpu.ops.attention import attention_reference
from defer_tpu.parallel.sequence import make_sharded_attention


def _qkv(shape, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def _mesh(n, axis="seq"):
    return Mesh(np.array(jax.devices()[:n]), (axis,))


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_dev", [4, 8])
def test_sequence_attention_matches_reference(strategy, causal, n_dev):
    b, h, s, d = 2, 8, 64, 16
    q, k, v = _qkv((b, h, s, d))
    mesh = _mesh(n_dev)
    attn = make_sharded_attention(
        mesh, strategy=strategy, causal=causal
    )
    got = attn(q, k, v)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_ring_attention_long_sequence_memory_shape():
    # The point of ring attention: S_global larger than any single
    # device would want to hold scores for. Just check correctness on a
    # longer sequence with a small head count.
    b, h, s, d = 1, 2, 512, 8
    q, k, v = _qkv((b, h, s, d), seed=1)
    attn = make_sharded_attention(_mesh(8), strategy="ring")
    got = attn(q, k, v)
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_ulysses_rejects_indivisible_heads():
    b, h, s, d = 1, 2, 32, 8  # 2 heads over 4 devices
    q, k, v = _qkv((b, h, s, d))
    attn = make_sharded_attention(_mesh(4), strategy="ulysses")
    with pytest.raises(ValueError, match="must divide"):
        attn(q, k, v)


def test_ring_attention_differentiable():
    b, h, s, d = 1, 2, 32, 8
    q, k, v = _qkv((b, h, s, d), seed=2)
    mesh = _mesh(4)
    attn = make_sharded_attention(mesh, strategy="ring", causal=True)

    g_ring = jax.grad(lambda q, k, v: attn(q, k, v).sum(), argnums=(0, 1, 2))(
        q, k, v
    )
    g_ref = jax.grad(
        lambda q, k, v: attention_reference(q, k, v, causal=True).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(a, b_, atol=1e-5, rtol=1e-5)


def test_llama_stack_sequence_parallel(devices):
    """Rope positions under sequence parallelism come from the shard's
    axis_index offset — a llama stack on a seq-sharded mesh must equal
    its unsharded reference (GQA repeat happens before the ring)."""
    import jax.numpy as jnp

    from defer_tpu.models.bert import SpmdBert
    from defer_tpu.models.llama import llama_config
    from defer_tpu.parallel.mesh import make_mesh

    cfg = llama_config(
        num_layers=2,
        dim=64,
        num_heads=4,
        num_kv_heads=2,
        ffn_dim=128,
        vocab_size=64,
        max_len=32,
    )
    mesh = make_mesh({"stage": 1, "seq": 2}, devices[:2])
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    params = sb.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 2, 16), 0, 64)
    got = sb.make_step()(params, ids)
    want = sb.reference_apply(params, ids)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
    )
