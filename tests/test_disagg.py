"""Disaggregated prefill/decode serving: the split must be invisible.

`serve_disagg` ships prefill to a worker and streams KV blocks back
over loopback sockets; greedy outputs must be TOKEN-IDENTICAL to
monolithic `serve_paged` across the attention-mode x prefix-cache
matrix (the wire format and the external-admission seam may not perturb
a single token), the retry path must survive a worker dying
mid-stream, and ingested blocks must seed the LOCAL radix cache
(cross-host prefix sharing)."""

import queue as queue_mod
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.disagg import (
    KVBlockIngest,
    prefill_schedule,
    serve_disagg,
    serve_prefill,
)
from defer_tpu.disagg import wire
from defer_tpu.models.gpt import SamplingParams, tiny_gpt
from defer_tpu.runtime.paged import PagedDecodeServer, serve_paged
from defer_tpu.runtime.transport import (
    ArrayReceiver,
    ArraySender,
    TransportError,
)


@pytest.fixture(scope="module")
def model():
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    return dec, params


def _requests(vocab):
    return [
        (jnp.asarray([[3, 9, 27, 1, 4, 4, 2, 8]], jnp.int32) % vocab, 7),
        (jnp.asarray([[5, 1]], jnp.int32), 4),
        (jnp.asarray([[11, 2, 8, 1, 6]], jnp.int32) % vocab, 6),
    ]


# -- wire format unit tests ------------------------------------------------


def test_prefill_schedule():
    assert prefill_schedule(7, None) == [7]
    assert prefill_schedule(7, 16) == [7]
    assert prefill_schedule(8, 4) == [4, 4]
    assert prefill_schedule(9, 4) == [4, 4, 1]
    assert prefill_schedule(1, 4) == [1]
    with pytest.raises(ValueError):
        prefill_schedule(0, None)
    with pytest.raises(ValueError):
        prefill_schedule(5, 0)


def test_bf16_wire_view_round_trip():
    import ml_dtypes

    a = np.arange(12, dtype=np.float32).astype(ml_dtypes.bfloat16)
    wired, token = wire.to_wire_array(a)
    assert wired.dtype == np.uint16 and token == "bfloat16"
    back = wire.from_wire_array(wired, token)
    np.testing.assert_array_equal(back, a)
    # dtype skew between declaration and frame is loud, not silent
    with pytest.raises(TransportError, match="dtype"):
        wire.from_wire_array(np.zeros(3, np.float32), "float64")


def test_params_flatten_round_trip():
    tree = {
        "emb": np.arange(6, dtype=np.float32).reshape(2, 3),
        "stack": {
            "w": np.ones((2, 2), np.float16),
            "inner": {"b": np.zeros(4, np.int32)},
        },
    }
    pairs = wire.flatten_params(tree)
    assert [p for p, _ in pairs] == ["emb", "stack/inner/b", "stack/w"]
    back = wire.unflatten_params(pairs)
    np.testing.assert_array_equal(back["stack"]["inner"]["b"], tree["stack"]["inner"]["b"])
    with pytest.raises(ValueError, match="separator"):
        wire.flatten_params({"a/b": np.zeros(1)})


def test_decoder_wire_round_trip(model):
    dec, _ = model
    body = wire.decoder_to_wire(dec)
    dec2 = wire.decoder_from_wire(body)
    assert dec2.cfg == dec.cfg
    assert dec2.compute_dtype == dec.compute_dtype


def test_kv_payload_loopback_round_trip(model):
    """One payload through real sockets: meta, logits, and every
    per-layer K/V frame survive framing + codec bit-exactly."""
    dec, _ = model
    L, hkv = dec.cfg.num_layers, dec.cfg.kv_heads
    dh = dec.cfg.dim // dec.cfg.num_heads
    rng = np.random.default_rng(7)
    pay = wire.KVPayload(
        rid=3,
        t0=6,
        k=rng.standard_normal((L, 2, hkv, 4, dh)).astype(np.float32),
        v=rng.standard_normal((L, 2, hkv, 4, dh)).astype(np.float32),
        logits=rng.standard_normal((1, dec.cfg.vocab_size)).astype(
            np.float32
        ),
    )
    recv = ArrayReceiver(0, host="127.0.0.1", accept_timeout_s=10.0)
    got = []

    def drain():
        got.extend(wire.iter_kv_payloads(recv))

    t = threading.Thread(target=drain)
    t.start()
    send = ArraySender("127.0.0.1", recv.port)
    n = wire.send_kv_payload(send, pay)
    send.close()
    t.join(timeout=10)
    recv.close()
    assert len(got) == 1
    out = got[0]
    assert (out.rid, out.t0) == (3, 6)
    np.testing.assert_array_equal(out.k, pay.k)
    np.testing.assert_array_equal(out.v, pay.v)
    np.testing.assert_array_equal(out.logits, pay.logits)
    # sender-side wire accounting == receiver-side
    assert out.wire_bytes == n == recv.rx_frame_bytes


# -- end-to-end parity -----------------------------------------------------


@pytest.mark.parametrize("attention", ["gathered", "blockwise"])
@pytest.mark.parametrize("prefix_cache", [False, True])
def test_disagg_token_identical_to_monolithic(
    model, attention, prefix_cache
):
    """The acceptance bar: greedy outputs equal serve_paged's across
    the attention x prefix_cache matrix."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    kw = dict(
        num_blocks=16, block_size=4, max_batch=2,
        prefix_cache=prefix_cache, attention=attention,
    )
    mono, _ = serve_paged(dec, params, reqs, **kw)
    outs, stats = serve_disagg(dec, params, reqs, **kw)
    for i, (a, b) in enumerate(zip(mono, outs)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"attention={attention} prefix_cache={prefix_cache} "
                    f"request {i}",
        )
    assert stats["disagg"] is True
    assert stats["kv_bytes_recv"] > 0
    assert stats["worker_restarts"] == 0


def test_disagg_chunked_prefill_parity(model):
    """chunk_len splits the worker's prefill into fixed-size chunks;
    the cache rows (and therefore every decoded token) must not
    move."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    mono, _ = serve_paged(
        dec, params, reqs, num_blocks=16, block_size=4, max_batch=2
    )
    outs, _ = serve_disagg(
        dec, params, reqs, num_blocks=16, block_size=4, max_batch=2,
        chunk_len=3,  # odd: exercises full chunks + a padded tail
    )
    for i, (a, b) in enumerate(zip(mono, outs)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"request {i}"
        )


def test_disagg_sampled_request_parity(model):
    """Seeded sampling draws from the SHIPPED logits row — the first
    token and the whole stream must match monolithic serving."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    samps = [
        SamplingParams(temperature=0.8, top_k=8, seed=11),
        None,
        SamplingParams(temperature=1.1, top_p=0.9, seed=3),
    ]
    mono, _ = serve_paged(
        dec, params, reqs, num_blocks=16, block_size=4, max_batch=2,
        sampling=samps,
    )
    outs, _ = serve_disagg(
        dec, params, reqs, num_blocks=16, block_size=4, max_batch=2,
        sampling=samps,
    )
    for i, (a, b) in enumerate(zip(mono, outs)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"request {i}"
        )


def test_disagg_int8_transfer_completes(model):
    """quantize='int8' is the lossy KV transfer mode: outputs may
    drift from lossless (the point of keeping it opt-in), but the
    stream must stay well-formed and ship fewer bytes."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    outs_l, st_l = serve_disagg(
        dec, params, reqs, num_blocks=16, block_size=4, max_batch=2,
        compress=False,
    )
    outs_q, st_q = serve_disagg(
        dec, params, reqs, num_blocks=16, block_size=4, max_batch=2,
        compress=False, quantize="int8",
    )
    for (prompt, steps), got in zip(reqs, outs_q):
        assert np.asarray(got).shape == (1, prompt.shape[1] + steps)
    assert st_q["quantize"] == "int8"
    # int8 KV frames ~1/4 of float32; the stream total (meta blobs +
    # fp32 logits rows ride along) must still shrink decisively.
    assert st_q["kv_bytes_recv"] < 0.6 * st_l["kv_bytes_recv"]


# -- failure handling ------------------------------------------------------


def test_worker_drop_mid_stream_retries(model):
    """First worker dies after one payload without a STOP; the
    orchestrator must re-dispatch the undelivered tail to a fresh
    worker and produce token-identical outputs."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    mono, _ = serve_paged(
        dec, params, reqs, num_blocks=16, block_size=4, max_batch=2
    )
    spawned = []

    def spawn():
        ports: "queue_mod.Queue[int]" = queue_mod.Queue()
        fail = 1 if not spawned else None
        t = threading.Thread(
            target=serve_prefill,
            kwargs=dict(
                listen_port=0, announce=ports.put,
                fail_after_requests=fail,
            ),
            daemon=True,
        )
        t.start()
        spawned.append(t)
        return "127.0.0.1", ports.get(timeout=30)

    outs, stats = serve_disagg(
        dec, params, reqs, num_blocks=16, block_size=4, max_batch=2,
        spawn_worker=spawn, worker_retries=2,
    )
    for i, (a, b) in enumerate(zip(mono, outs)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"request {i}"
        )
    assert stats["worker_restarts"] == 1
    assert len(spawned) == 2


def test_worker_drop_exhausts_retries(model):
    """Every worker dies: after worker_retries replacements the error
    surfaces instead of looping forever."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)[:2]

    def spawn():
        ports: "queue_mod.Queue[int]" = queue_mod.Queue()
        t = threading.Thread(
            target=serve_prefill,
            kwargs=dict(
                listen_port=0, announce=ports.put,
                fail_after_requests=1,
            ),
            daemon=True,
        )
        t.start()
        return "127.0.0.1", ports.get(timeout=30)

    with pytest.raises(TransportError, match="restart"):
        serve_disagg(
            dec, params, reqs, num_blocks=16, block_size=4,
            max_batch=2, spawn_worker=spawn, worker_retries=1,
        )


def test_deliver_kv_rejects_geometry_skew(model):
    """A payload whose block geometry disagrees with the server is a
    config skew, refused loudly before it can corrupt the pool."""
    dec, params = model
    srv = PagedDecodeServer(
        dec, params, num_blocks=8, block_size=4, max_batch=2
    )
    rid = srv.submit_prefilled(
        jnp.asarray([[1, 2, 3]], jnp.int32), 4
    )
    L, hkv = dec.cfg.num_layers, dec.cfg.kv_heads
    dh = dec.cfg.dim // dec.cfg.num_heads
    good_k = np.zeros((L, 1, hkv, 4, dh), np.float32)
    with pytest.raises(ValueError, match="shape"):
        srv.deliver_kv(
            rid, good_k[:, :, :, :2, :], good_k[:, :, :, :2, :],
            np.zeros((1, dec.cfg.vocab_size), np.float32),
        )
    with pytest.raises(ValueError, match="first_logits"):
        srv.deliver_kv(
            rid, good_k, good_k, np.zeros((1, 3), np.float32)
        )
    with pytest.raises(KeyError):
        srv.deliver_kv(
            999, good_k, good_k,
            np.zeros((1, dec.cfg.vocab_size), np.float32),
        )


def test_submit_prefilled_rejects_unsupported_modes(model):
    dec, params = model
    srv = PagedDecodeServer(
        dec, params, num_blocks=8, block_size=4, max_batch=2,
        prefix_ids=jnp.asarray([[1, 2, 3, 4]], jnp.int32),
    )
    with pytest.raises(ValueError, match="prefix_cache"):
        srv.submit_prefilled(jnp.asarray([[1, 2]], jnp.int32), 2)


# -- cross-host prefix sharing ---------------------------------------------


def test_ingested_blocks_revive_through_prefix_cache(model):
    """Blocks prefilled on the WORKER must park in the decode host's
    radix cache at finish, so a later LOCAL request with the same
    prefix skips its prefill — cross-host prefix sharing, the
    parking/revival acceptance criterion."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)[:1]
    mono, _ = serve_paged(
        dec, params, reqs, num_blocks=24, block_size=4, max_batch=2
    )
    srv = PagedDecodeServer(
        dec, params, num_blocks=24, block_size=4, max_batch=2,
        prefix_cache=True,
    )
    outs, _ = serve_disagg(
        dec, params, reqs, num_blocks=24, block_size=4, max_batch=2,
        server=srv,
    )
    np.testing.assert_array_equal(
        np.asarray(outs[0]), np.asarray(mono[0])
    )
    # the 8-token prompt's two full blocks are parked, not freed
    assert srv.radix.cached_blocks >= 2
    assert srv.prefill_tokens_saved == 0
    rid = srv.submit(reqs[0][0], reqs[0][1])
    out2 = srv.run()[rid]
    np.testing.assert_array_equal(
        np.asarray(out2), np.asarray(mono[0])
    )
    # the local admission walked onto the ingested blocks
    assert srv.prefill_tokens_saved > 0


# -- ingest drain unit behavior --------------------------------------------


def test_ingest_clean_eof_sets_flag(model):
    dec, params = model
    srv = PagedDecodeServer(
        dec, params, num_blocks=8, block_size=4, max_batch=2
    )
    recv = ArrayReceiver(0, host="127.0.0.1", accept_timeout_s=10.0)
    ingest = KVBlockIngest(srv, recv)
    ingest.start()
    send = ArraySender("127.0.0.1", recv.port)
    send.close()  # STOP with no payloads
    assert ingest.eof.wait(timeout=10)
    assert not ingest.failed.is_set()
    assert ingest.pump() == 0
    ingest.close()
    recv.close()
