"""defer_tpu.analysis: static rules against the fixture corpus, the
strict pass over the shipped tree (tier-1 enforcement), and the
runtime trace sanitizer — including the paged server's post-warmup
trace stability."""

import json
import pathlib
import textwrap

import jax
import jax.numpy as jnp
import pytest

from defer_tpu.analysis import (
    RetraceError,
    analyze_paths,
    trace_sanitizer as sanitize,
)
from defer_tpu.analysis.budget import BudgetError
from defer_tpu.analysis.runner import main, record_findings
from defer_tpu.obs.metrics import MetricsRegistry

HERE = pathlib.Path(__file__).resolve().parent
FIXTURES = HERE / "analysis_fixtures"
REPO = HERE.parent

# (rule, fixture stem, expected positive-finding count) — keep in sync
# with tests/analysis_fixtures/ (see its README).
CASES = [
    ("host-sync-in-hot-loop", "host_sync", 2),
    ("host-sync-in-hot-loop", "window_scan", 2),
    ("host-sync-in-hot-loop", "spec_accept", 2),
    ("host-sync-in-hot-loop", "spec_window", 2),
    ("host-sync-in-hot-loop", "shard_map", 2),
    ("host-sync-in-hot-loop", "kv_spill", 2),
    ("host-sync-in-hot-loop", "constrain", 2),
    ("host-sync-in-hot-loop", "mixed_tick", 2),
    ("fresh-closure-jit", "fresh_closure", 2),
    ("prng-key-reuse", "prng_reuse", 1),
    ("lock-discipline", "lock_discipline", 2),
    ("lock-discipline", "advert_lock", 2),
    ("lock-discipline", "lock_helper", 1),
    ("obs-name-drift", "obs_drift", 3),
    ("cross-domain-write", "domain_race", 2),
    ("host-sync-in-hot-loop", "pp_handoff", 1),
    ("shard-spec", "shard_spec", 3),
    ("shard-spec", "psum_mirror", 1),
]


def _run(path, rule):
    return analyze_paths([str(path)], rules=[rule])


# -- static rules over the fixture corpus ------------------------------


@pytest.mark.parametrize("rule,stem,n", CASES)
def test_rule_catches_positive_fixture(rule, stem, n):
    rep = _run(FIXTURES / f"{stem}_pos.py", rule)
    assert len(rep.findings) == n, [f.format() for f in rep.findings]
    assert all(f.rule == rule for f in rep.findings)


@pytest.mark.parametrize("rule,stem,n", CASES)
def test_rule_passes_negative_fixture(rule, stem, n):
    rep = _run(FIXTURES / f"{stem}_neg.py", rule)
    assert rep.findings == [], [f.format() for f in rep.findings]


def test_shipped_tree_is_strict_clean():
    """The tier-1 gate: every rule over defer_tpu/ is clean or carries
    a justified ignore. A failure here means a new hazard landed
    without a reason next to it."""
    rep = analyze_paths([str(REPO / "defer_tpu")], strict=True)
    assert rep.findings == [], "\n".join(f.format() for f in rep.findings)
    # The 20 deliberate sites (hard_sync itself, the serving syncs,
    # per-stage construction jits, framing locks) stay suppressed.
    assert len(rep.suppressed) >= 15


def test_seeded_violation_is_caught(tmp_path):
    """Acceptance check: a .item() seeded into a _tick is flagged."""
    bad = tmp_path / "seeded.py"
    bad.write_text(
        textwrap.dedent(
            """
            class PagedDecodeServer:
                def _tick(self):
                    tok = self.nxt.item()
                    return tok
            """
        )
    )
    rep = analyze_paths([str(bad)])
    assert [f.rule for f in rep.findings] == ["host-sync-in-hot-loop"]


# -- ignore mechanics --------------------------------------------------


def _ticky(marker):
    return textwrap.dedent(
        f"""
        import numpy as np


        class S:
            def _tick(self):
                {marker}
                h = np.asarray(self.nxt)
                return h
        """
    )


def test_ignore_with_reason_suppresses(tmp_path):
    p = tmp_path / "ok.py"
    p.write_text(
        _ticky("# analysis: ignore[host-sync-in-hot-loop] one batched "
               "transfer per tick by design")
    )
    rep = analyze_paths([str(p)], strict=True)
    assert rep.findings == []
    assert len(rep.suppressed) == 1


def test_strict_flags_reasonless_ignore(tmp_path):
    p = tmp_path / "bare.py"
    p.write_text(_ticky("# analysis: ignore[host-sync-in-hot-loop]"))
    lax = analyze_paths([str(p)])
    assert lax.findings == []  # non-strict: suppression holds
    strict = analyze_paths([str(p)], strict=True)
    assert [f.rule for f in strict.findings] == ["ignore-without-reason"]


def test_unknown_rule_rejected():
    with pytest.raises(ValueError, match="unknown rules"):
        analyze_paths([str(FIXTURES)], rules=["no-such-rule"])


def test_parse_error_is_a_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    rep = analyze_paths([str(p)])
    assert [f.rule for f in rep.findings] == ["parse-error"]


# -- CLI and obs wiring ------------------------------------------------


def test_cli_exit_codes_and_json(capsys):
    pos = str(FIXTURES / "prng_reuse_pos.py")
    assert main([pos, "--rules", "prng-key-reuse", "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["counts"] == {"prng-key-reuse": 1}
    neg = str(FIXTURES / "prng_reuse_neg.py")
    assert main([neg, "--rules", "prng-key-reuse"]) == 0
    assert main(["--list-rules"]) == 0
    assert main([pos, "--rules", "bogus"]) == 2


def test_findings_metric_recorded():
    rep = analyze_paths(
        [str(FIXTURES / "obs_drift_pos.py")], rules=["obs-name-drift"]
    )
    reg = MetricsRegistry()
    record_findings(rep, registry=reg)
    assert reg.value(
        "defer_analysis_findings_total", rule="obs-name-drift"
    ) == 3
    # Clean rules are published as explicit zeros, not absent.
    assert reg.value(
        "defer_analysis_findings_total", rule="prng-key-reuse"
    ) == 0


# -- perf-contract budget gate -----------------------------------------

BUDGET = FIXTURES / "budget"


def test_budget_static_and_bench_pass():
    """Healthy tree + healthy numbers: both halves green."""
    rep = analyze_paths(
        [str(BUDGET / "hot.py")],
        budget=str(BUDGET / "budgets.toml"),
        bench=str(BUDGET / "bench_ok.json"),
    )
    assert rep.findings == [], [f.format() for f in rep.findings]
    statuses = {
        c["contract"]: c["status"] for c in rep.budget["contracts"]
    }
    assert statuses == {
        "dispatches_per_token_w8": "pass",
        "kv_rows_per_shard_tp2": "pass",
        "window_drain_b_k": "pass",
    }


def test_budget_bench_violation_fails_cli(capsys):
    """Acceptance check: a violated dispatches-per-token /
    kv-rows-read bound exits non-zero with per-contract verdicts in
    the JSON payload."""
    rc = main([
        str(BUDGET / "hot.py"),
        "--budget", str(BUDGET / "budgets.toml"),
        "--bench", str(BUDGET / "bench_bad.json"),
        "--json",
    ])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert out["counts"] == {"perf-contract": 3}
    statuses = {
        c["contract"]: c["status"] for c in out["budget"]["contracts"]
    }
    assert set(statuses.values()) == {"fail"}


def test_budget_static_violation_needs_no_bench():
    """cold.py registers the metrics but its _tick feeds none of them:
    every contract fails statically even with green bench numbers."""
    rep = analyze_paths(
        [str(BUDGET / "cold.py")],
        budget=str(BUDGET / "budgets.toml"),
        bench=str(BUDGET / "bench_ok.json"),
    )
    assert [f.rule for f in rep.findings] == ["perf-contract"] * 3
    assert all("nothing reachable" in f.message for f in rep.findings)


def test_budget_missing_sections_are_no_data_not_fail():
    """A bench round that never ran a section must not fail its
    contract — only present-and-violated bounds do."""
    rep = analyze_paths(
        [str(BUDGET / "hot.py")],
        budget=str(BUDGET / "budgets.toml"),
        bench={"parsed": {"decode_window": {}}},
    )
    assert rep.findings == []
    assert {c["status"] for c in rep.budget["contracts"]} == {"no-data"}
    assert rep.budget["bench"] == "<in-memory bench result>"


def test_budget_malformed_toml_rejected(tmp_path, capsys):
    bad = tmp_path / "budgets.toml"
    bad.write_text('[contract.x]\ncounter = 5\nfunctions = ["_tick"]\n')
    with pytest.raises(BudgetError, match="counter"):
        analyze_paths([str(BUDGET / "hot.py")], budget=str(bad))
    assert main([str(BUDGET / "hot.py"), "--budget", str(bad)]) == 2
    assert "counter" in capsys.readouterr().err


def test_repo_budget_gate_and_suppression_ledger(capsys):
    """The shipped gate: --strict --budget over defer_tpu/ stays green
    (static half holds; measured half is pass or no-data, never fail
    on committed artifacts), and the JSON payload carries the per-rule
    suppression ledger."""
    rc = main([
        str(REPO / "defer_tpu"), "--strict", "--json",
        "--budget", str(REPO / "budgets.toml"),
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["findings"] == []
    ledger = out["suppressed_by_rule"]
    assert ledger.get("host-sync-in-hot-loop", 0) >= 15
    assert sum(ledger.values()) == out["suppressed"]
    verdicts = {
        c["contract"]: c["status"] for c in out["budget"]["contracts"]
    }
    assert set(verdicts) == {
        "dispatches_per_token_w8",
        "kv_rows_per_shard_tp2",
        "mixed",
        "pp",
        "window_drain_b_k",
    }
    assert all(s in ("pass", "no-data") for s in verdicts.values())


# -- trace sanitizer ---------------------------------------------------


def test_sanitizer_detects_retrace():
    f = jax.jit(lambda x: x + 1)
    f(jnp.zeros((2,)))  # warmup
    with pytest.raises(RetraceError, match="1 retrace"):
        with sanitize(f):
            f(jnp.zeros((3,)))  # new shape -> new trace


def test_sanitizer_clean_block_and_report():
    f = jax.jit(lambda x: x * 2)
    f(jnp.zeros((2,)))
    with sanitize(f) as rep:
        for _ in range(3):
            f(jnp.ones((2,)))
    assert rep.retraces == 0
    assert len(rep.watched) == 1


def test_sanitizer_allow_budget():
    f = jax.jit(lambda x: x - 1)
    f(jnp.zeros((2,)))
    with sanitize(f, allow=1):
        f(jnp.zeros((3,)))  # exactly one retrace, inside budget


def test_sanitizer_refuses_empty_watch():
    with pytest.raises(ValueError, match="no jitted callables"):
        with sanitize(object()):
            pass


def test_sanitizer_does_not_mask_block_errors():
    f = jax.jit(lambda x: x + 1)
    f(jnp.zeros((2,)))
    with pytest.raises(RuntimeError, match="boom"):
        with sanitize(f):
            f(jnp.zeros((3,)))  # retraces, but the block's own error wins
            raise RuntimeError("boom")


def test_conftest_fixture_wraps_sanitizer(trace_sanitizer):
    f = jax.jit(lambda x: x + 3)
    f(jnp.zeros((2,)))
    with trace_sanitizer(f) as rep:
        f(jnp.zeros((2,)))
    assert rep.retraces == 0


def test_jit_cached_is_trace_stable():
    """utils/memo.jit_cached: same static key -> the same jitted
    callable, so re-building the closure per call costs no retrace —
    the migration target for fresh-closure-jit findings."""
    from defer_tpu.utils.memo import jit_cached

    def make(scale):
        def f(x):
            return x * scale

        return f

    a = jit_cached(make(2.0), ("test_analysis", "stable"))
    b = jit_cached(make(2.0), ("test_analysis", "stable"))
    assert a is b
    a(jnp.zeros((2,)))
    with sanitize(a) as rep:
        b(jnp.zeros((2,)))
    assert rep.retraces == 0
    # Distinct jit options are distinct cache entries.
    c = jit_cached(make(2.0), ("test_analysis", "stable"), static_argnums=())
    assert c is not a


def test_paged_tick_trace_stable_after_warmup():
    """The enforcement form of the paged server's design contract: a
    warmed `_tick` loop lowers nothing new — 3 post-warmup ticks, zero
    retraces across every jitted callable the server holds."""
    from defer_tpu.models.gpt import tiny_gpt
    from defer_tpu.runtime.paged import PagedDecodeServer

    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    srv = PagedDecodeServer(
        dec, params, num_blocks=12, block_size=4, max_batch=2
    )
    srv.submit(jnp.asarray([[3, 9, 27]], jnp.int32), 10)
    srv.submit(jnp.asarray([[5, 1]], jnp.int32), 9)
    srv._admit()
    for _ in range(2):  # warmup: first tick compiles the step
        srv._tick()
    with sanitize(srv, dec) as rep:
        for _ in range(3):
            srv._tick()
    assert rep.retraces == 0
    assert rep.watched  # the step/insert callables were actually seen
