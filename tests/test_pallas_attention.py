"""Pallas flash attention vs the XLA reference, in interpreter mode.

The kernel itself targets TPU; `interpret=True` runs the exact same
Pallas program on the CPU test mesh so CI needs no hardware (SURVEY.md
§4's test strategy).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.ops.attention import attention_reference, multi_head_attention
from defer_tpu.ops.pallas_attention import flash_attention


def _qkv(shape, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "shape",
    [
        (1, 2, 128, 64),   # one k block
        (2, 4, 512, 64),   # multiple k blocks
        (1, 2, 384, 32),   # non-power-of-two seq -> odd block split
    ],
)
def test_flash_matches_reference(shape, causal):
    q, k, v = _qkv(shape)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_flash_bf16_inputs():
    q, k, v = _qkv((1, 2, 256, 64), dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, interpret=True)
    want = attention_reference(q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        got.astype(np.float32), want.astype(np.float32), atol=2e-2
    )


def test_flash_grad_matches_reference():
    q, k, v = _qkv((1, 2, 128, 32), seed=3)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, interpret=True).sum()

    def loss_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5)


def test_flash_rejects_short_sequences():
    q, k, v = _qkv((1, 1, 4, 16))
    with pytest.raises(ValueError):
        flash_attention(q, k, v, interpret=True)


def _decode_reference(q, k, v, pos, window=None):
    """Masked decode attention on [B, Hq, Dh] vs [B, Hkv, S, Dh]:
    GQA expand, mask j <= pos[b] (and the sliding window), fp32
    softmax — mirrors GptDecoder._block's einsum math."""
    b, hq, d = q.shape
    hkv, s = k.shape[1], k.shape[2]
    g = hq // hkv
    kx = jnp.repeat(k, g, axis=1)
    vx = jnp.repeat(v, g, axis=1)
    logits = jnp.einsum(
        "bhd,bhsd->bhs", q.astype(jnp.float32), kx.astype(jnp.float32)
    ) * (d**-0.5)
    j = jnp.arange(s)
    mask = j[None, None, :] <= pos[:, None, None]
    if window is not None:
        mask &= j[None, None, :] > pos[:, None, None] - window
    logits = jnp.where(mask, logits, -jnp.inf)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhs,bhsd->bhd", w, vx.astype(jnp.float32)).astype(
        q.dtype
    )


@pytest.mark.parametrize(
    "hq,hkv,s,pos,window",
    [
        (8, 8, 64, [63, 10], None),     # MHA, full + short slots
        (8, 2, 64, [31, 32], None),     # GQA g=4 (padded group rows)
        (16, 2, 128, [5, 100], None),   # block-boundary positions
        (8, 2, 64, [40, 63], 16),       # sliding window
        (32, 4, 64, [0, 63], None),     # g=8, no pad; pos extremes
    ],
)
def test_flash_decode_matches_reference(hq, hkv, s, pos, window):
    from defer_tpu.ops.pallas_attention import flash_decode

    d = 16
    b = len(pos)
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (b, hq, d))
    k = jax.random.normal(ks[1], (b, hkv, s, d))
    v = jax.random.normal(ks[2], (b, hkv, s, d))
    posv = jnp.asarray(pos, jnp.int32)
    got = flash_decode(
        q, k, v, posv, window=window, interpret=True, block_k=32
    )
    want = _decode_reference(q, k, v, posv, window=window)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_flash_decode_scalar_pos_and_validation():
    from defer_tpu.ops.pallas_attention import flash_decode

    ks = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(ks[0], (2, 4, 16))
    k = jax.random.normal(ks[1], (2, 2, 32, 16))
    v = jax.random.normal(ks[2], (2, 2, 32, 16))
    got = flash_decode(q, k, v, jnp.asarray(7), interpret=True, block_k=8)
    want = _decode_reference(q, k, v, jnp.full((2,), 7, jnp.int32))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
    k3 = jax.random.normal(ks[1], (2, 3, 32, 16))
    with pytest.raises(ValueError, match="multiple"):
        flash_decode(q, k3, k3, jnp.asarray(7), interpret=True)


def test_decode_step_through_kernel_matches_einsum(monkeypatch):
    """DEFER_TPU_PALLAS_INTERPRET=1 routes GptDecoder's T=1 decode
    through the flash-decode kernel (interpreter): generation must
    match the einsum path token for token — GQA + rotary included."""
    from defer_tpu.models.llama import tiny_llama

    dec = tiny_llama(64)
    params = dec.init(jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 0, 64)
    want = dec.generate(params, prompt, 8)

    monkeypatch.setenv("DEFER_TPU_PALLAS_INTERPRET", "1")
    dec2 = tiny_llama(64)  # fresh decoder -> fresh compiled steps
    got = dec2.generate(params, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_mha_auto_falls_back_off_tpu():
    # On the CPU test platform "auto" must take the XLA path and agree
    # with the reference exactly.
    b, s, d, h = 2, 64, 32, 4
    q, k, v = _qkv((b, s, d), seed=5)
    out = multi_head_attention(q, k, v, num_heads=h)
    assert out.shape == (b, s, d)


def test_pallas_availability_detection(monkeypatch):
    """The 'auto' gate: pallas only on a DIRECTLY-attached TPU backend.
    Tunneled plugins register under their own factory name while the
    client claims platform 'tpu' — that mismatch must disable pallas
    (a Mosaic compile on such transports hangs, not errors)."""
    from types import SimpleNamespace

    import jax

    from defer_tpu.ops import attention

    monkeypatch.delenv("DEFER_TPU_PALLAS", raising=False)
    fake = SimpleNamespace(platform="tpu")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        jax.extend.backend, "get_backend", lambda: fake
    )
    from jax._src import xla_bridge as xb

    # Registered under its own plugin name (e.g. 'axon') -> tunneled.
    monkeypatch.setattr(xb, "_backends", {"axon": fake})
    assert attention._pallas_available() is False
    # Registered under the platform it claims -> direct TPU.
    monkeypatch.setattr(xb, "_backends", {"tpu": fake})
    assert attention._pallas_available() is True
    # Env force wins in both directions.
    monkeypatch.setenv("DEFER_TPU_PALLAS", "1")
    monkeypatch.setattr(xb, "_backends", {"axon": fake})
    assert attention._pallas_available() is True
    monkeypatch.setenv("DEFER_TPU_PALLAS", "0")
    monkeypatch.setattr(xb, "_backends", {"tpu": fake})
    assert attention._pallas_available() is False


def test_pallas_availability_fails_closed(monkeypatch):
    """A broken probe (jax internals moved) must pick the XLA path —
    wrongly enabling pallas on a tunneled backend hangs the transport."""
    import warnings

    import jax

    from defer_tpu.ops import attention

    monkeypatch.delenv("DEFER_TPU_PALLAS", raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    def boom():
        raise AttributeError("get_backend moved")

    monkeypatch.setattr(jax.extend.backend, "get_backend", boom)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert attention._pallas_available() is False
    assert any("probe failed" in str(x.message) for x in w)
