"""Fleet serving: routing over replicas must be invisible to outputs.

`serve_fleet` places each request on one of N paged replicas by cache
locality; per-slot decode independence means placement (and admission
timing) may not perturb a single greedy token — n_replicas=1 AND
n_replicas=2 must be TOKEN-IDENTICAL to `serve_paged`. Around that
contract: the router's decision ladder is deterministic (equal load
breaks ties by index, every run), replica death re-routes queued work
and fails in-flight work loudly, shedding is a synchronous typed
rejection (never a hang), and prefix migration moves real KV blocks
without changing tokens."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.disagg import wire
from defer_tpu.fleet import (
    AdmissionController,
    AdvertisementBoard,
    FleetFrontend,
    PrefixRouter,
    ReplicaDeadError,
    ShedError,
    chain_digests,
    serve_fleet,
)
from defer_tpu.models.gpt import SamplingParams, tiny_gpt
from defer_tpu.obs import FleetMetrics
from defer_tpu.runtime.paged import PagedDecodeServer, serve_paged
from defer_tpu.runtime.transport import ArrayReceiver, ArraySender


@pytest.fixture(scope="module")
def model():
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    return dec, params


def _requests(vocab):
    return [
        (jnp.asarray([[3, 9, 27, 1, 4, 4, 2, 8]], jnp.int32) % vocab, 7),
        (jnp.asarray([[5, 1]], jnp.int32), 4),
        (jnp.asarray([[11, 2, 8, 1, 6]], jnp.int32) % vocab, 6),
        (jnp.asarray([[3, 9, 27, 1, 4, 4, 2, 8]], jnp.int32) % vocab, 5),
    ]


def _fresh_obs(n: int) -> FleetMetrics:
    """FleetMetrics over the process-global registry with the load
    gauges zeroed — unit tests must not inherit a previous test's
    parting gauge values (the same reset FleetFrontend does)."""
    obs = FleetMetrics(n)
    for i in range(n):
        obs.queue_depth[i].set(0)
        obs.inflight[i].set(0)
        obs.pool_free[i].set(0)
    return obs


def _hold_all(fe):
    """Set hold_admissions on every replica AND outwait the idle
    blocking pop: a replica already parked inside its 1ms
    `try_pop(timeout=...)` when the flag flips can still take one item
    submitted into that window — settle past it so 'held' means held."""
    for r in fe.replicas:
        r.hold_admissions = True
    time.sleep(0.05)


def _wait_until(pred, timeout=10.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {msg}")


# -- token-identity with serve_paged ----------------------------------


@pytest.mark.parametrize("prefix_cache", [False, True])
@pytest.mark.parametrize("n_replicas", [1, 2])
def test_fleet_token_identical_to_serve_paged(
    model, n_replicas, prefix_cache
):
    """The acceptance bar: greedy outputs equal serve_paged's at one
    replica (same class, nothing to route) AND at two (placement may
    not perturb a token — per-slot decode independence)."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    kw = dict(
        num_blocks=16, block_size=4, max_batch=2,
        prefix_cache=prefix_cache,
    )
    mono, _ = serve_paged(dec, params, reqs, **kw)
    outs, stats = serve_fleet(
        dec, params, reqs, n_replicas=n_replicas, **kw
    )
    for i, (a, b) in enumerate(zip(mono, outs)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"n_replicas={n_replicas} "
                    f"prefix_cache={prefix_cache} request {i}",
        )
    assert stats["n_replicas"] == n_replicas
    assert sum(stats["routed"].values()) == len(reqs)
    assert stats["shed"] == {"queue_full": 0, "slo": 0}
    assert len(stats["replicas"]) == n_replicas
    assert all(r["dead"] is None for r in stats["replicas"])


def test_fleet_sampled_request_parity(model):
    """Seeded sampling rides the routed request; streams must match
    monolithic serving per request."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    samps = [
        SamplingParams(temperature=0.8, top_k=8, seed=11),
        None,
        SamplingParams(temperature=1.1, top_p=0.9, seed=3),
        None,
    ]
    kw = dict(num_blocks=16, block_size=4, max_batch=2)
    mono, _ = serve_paged(dec, params, reqs, sampling=samps, **kw)
    outs, _ = serve_fleet(
        dec, params, reqs, n_replicas=2, sampling=samps, **kw
    )
    for i, (a, b) in enumerate(zip(mono, outs)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"request {i}"
        )


# -- digest advertisement seam (runtime/paged.py satellite) -----------


def test_resident_digests_generation_and_keys(model):
    """`resident_digests` snapshots exactly the radix key set, and the
    generation moves only when the resident KEY SET changes — the one
    int the replica's advertisement fast path compares."""
    dec, params = model
    srv = PagedDecodeServer(
        dec, params, num_blocks=16, block_size=4, max_batch=2,
        prefix_cache=True,
    )
    gen0, d0 = srv.resident_digests()
    assert d0 == frozenset()
    prompt = jnp.asarray([[3, 9, 27, 1, 4, 4, 2, 8]], jnp.int32)
    rid = srv.submit(prompt, 3)
    while rid not in srv.done:
        srv._admit()
        srv._tick()
    gen1, d1 = srv.resident_digests()
    assert gen1 > gen0
    # The prompt's two full blocks are keyed by the router's own
    # chaining — bit-for-bit, or every fleet lookup would miss.
    assert set(chain_digests(prompt, 2, 4)) <= d1
    evicted = srv.radix.evict(1)
    assert evicted
    gen2, d2 = srv.resident_digests()
    assert gen2 > gen1 and len(d2) == len(d1) - 1


def test_resident_digests_without_radix(model):
    dec, params = model
    srv = PagedDecodeServer(
        dec, params, num_blocks=8, block_size=4, max_batch=1
    )
    assert srv.resident_digests() == (0, frozenset())


# -- router decision ladder -------------------------------------------


def _router(n=2, **kw):
    obs = _fresh_obs(n)
    board = AdvertisementBoard(n)
    return PrefixRouter(board, obs, **kw), board, obs


def _toks(n_tokens=8):
    return np.arange(n_tokens, dtype=np.int64).reshape(1, -1)


def test_router_tie_break_is_deterministic():
    """Equal depth + equal load must pick the SAME replica every call
    (lower index) — reproducible placement under a balanced fleet."""
    router, board, _ = _router()
    keys = chain_digests(_toks(), 2, 4)
    board.publish(0, 1, frozenset(keys))
    board.publish(1, 1, frozenset(keys))
    for _ in range(5):
        d = router.route(_toks(), 2, 4, [True, True])
        assert (d.replica, d.reason, d.depth) == (0, "prefix", 2)
        assert d.keys == keys


def test_router_routes_least_loaded_when_no_prefix():
    router, _, obs = _router()
    d = router.route(_toks(), 2, 4, [True, True])
    assert (d.replica, d.reason) == (0, "load")  # tie -> lower index
    obs.queue_depth[0].set(3)
    d = router.route(_toks(), 2, 4, [True, True])
    assert (d.replica, d.reason) == (1, "load")


def test_router_dead_holder_is_fallback_not_load():
    router, board, _ = _router()
    board.publish(0, 1, frozenset(chain_digests(_toks(), 2, 4)))
    d = router.route(_toks(), 2, 4, [False, True])
    assert (d.replica, d.reason, d.depth) == (1, "fallback", 2)


def test_router_migrates_off_overloaded_holder():
    router, board, obs = _router(migrate_gap=4)
    keys = chain_digests(_toks(), 2, 4)
    board.publish(0, 1, frozenset(keys))
    obs.queue_depth[0].set(10)
    d = router.route(_toks(), 2, 4, [True, True])
    assert (d.replica, d.reason, d.source) == (1, "migrate", 0)
    assert d.keys == keys
    # Below the gap the holder keeps the request.
    obs.queue_depth[0].set(3)
    d = router.route(_toks(), 2, 4, [True, True])
    assert (d.replica, d.reason) == (0, "prefix")


def test_router_migrate_disabled_falls_back():
    router, board, obs = _router(migrate=False)
    board.publish(0, 1, frozenset(chain_digests(_toks(), 2, 4)))
    obs.queue_depth[0].set(10)
    d = router.route(_toks(), 2, 4, [True, True])
    assert (d.replica, d.reason) == (1, "fallback")


def test_router_round_robin_rotates_over_live():
    router, _, _ = _router(policy="round_robin")
    seq = [
        router.route(_toks(), 2, 4, [True, True]).replica
        for _ in range(4)
    ]
    assert seq == [0, 1, 0, 1]
    assert router.route(_toks(), 2, 4, [False, True]).replica == 1


def test_router_rejects_bad_policy_and_empty_fleet():
    with pytest.raises(ValueError, match="policy"):
        _router(policy="random")
    router, _, _ = _router()
    with pytest.raises(RuntimeError, match="no live replicas"):
        router.route(_toks(), 2, 4, [False, False])


# -- admission + shedding ---------------------------------------------


def test_admission_rolling_p99_and_pop():
    ctl = AdmissionController(1, _fresh_obs(1), slo_s=None)
    assert ctl.wait_p99(0) == 0.0
    assert ctl.try_pop(0) is None
    ctl.admit(0, "a")
    assert ctl.depth(0) == 1
    assert ctl.try_pop(0) == "a"
    assert ctl.depth(0) == 0
    ctl2 = AdmissionController(1, _fresh_obs(1))
    for w in [0.01] * 99 + [5.0]:
        ctl2.record_wait(0, w)
    assert ctl2.wait_p99(0) == 5.0  # the tail sample IS the p99


def test_shed_on_slo_is_synchronous(model):
    """Once the rolling queue-wait p99 exceeds the SLO, submit()
    raises a typed ShedError immediately — and the shed request can
    never be waited on into a hang."""
    dec, params = model
    fe = FleetFrontend(
        dec, params, n_replicas=2, num_blocks=16, block_size=4,
        max_batch=2, slo_s=0.01,
    )
    try:
        for i in range(2):
            fe.controller.record_wait(i, 0.5)
        t0 = time.monotonic()
        with pytest.raises(ShedError) as ei:
            fe.submit(jnp.asarray([[5, 1]], jnp.int32), 4)
        assert time.monotonic() - t0 < 1.0
        assert ei.value.reason == "slo"
        assert ei.value.wait_p99_s == pytest.approx(0.5)
        assert fe.stats()["shed"]["slo"] == 1
        with pytest.raises(KeyError):
            fe.result(0)  # the shed request's future was torn down
    finally:
        fe.close()


def test_shed_on_full_queue_never_hangs(model):
    """Held replicas + bounded queues: the overflow submit is rejected
    within the enqueue deadline, and the admitted backlog still drains
    once the replicas resume."""
    dec, params = model
    fe = FleetFrontend(
        dec, params, n_replicas=2, num_blocks=16, block_size=4,
        max_batch=2, max_queue=1, enqueue_wait_s=0.05,
    )
    try:
        _hold_all(fe)
        reqs = _requests(dec.cfg.vocab_size)
        g0 = fe.submit(*reqs[0])
        g1 = fe.submit(*reqs[1])
        t0 = time.monotonic()
        with pytest.raises(ShedError) as ei:
            fe.submit(*reqs[2])
        assert time.monotonic() - t0 < 5.0
        assert ei.value.reason == "queue_full"
        for r in fe.replicas:
            r.hold_admissions = False
        mono, _ = serve_paged(
            dec, params, reqs[:2], num_blocks=16, block_size=4,
            max_batch=2,
        )
        np.testing.assert_array_equal(
            np.asarray(fe.result(g0, timeout=60)), np.asarray(mono[0])
        )
        np.testing.assert_array_equal(
            np.asarray(fe.result(g1, timeout=60)), np.asarray(mono[1])
        )
    finally:
        fe.close()


# -- replica death ----------------------------------------------------


def test_replica_death_reroutes_queued_requests(model):
    """Requests still parked in a dead replica's admission queue were
    never touched — they must re-route and complete with the exact
    tokens a healthy fleet produces."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    fe = FleetFrontend(
        dec, params, n_replicas=2, num_blocks=16, block_size=4,
        max_batch=2,
    )
    try:
        _hold_all(fe)
        gid = fe.submit(*reqs[0])
        victim = next(
            i for i in range(2) if fe.controller.depth(i) == 1
        )
        survivor = 1 - victim
        fe.replicas[victim].inject_failure(RuntimeError("boom"))
        _wait_until(
            lambda: fe.replicas[victim].dead is not None,
            msg="replica death",
        )
        assert not fe.alive[victim]
        fe.replicas[survivor].hold_admissions = False
        mono, _ = serve_paged(
            dec, params, reqs[:1], num_blocks=16, block_size=4,
            max_batch=2,
        )
        np.testing.assert_array_equal(
            np.asarray(fe.result(gid, timeout=60)), np.asarray(mono[0])
        )
        # The fleet keeps serving minus the dead replica ...
        g2 = fe.submit(*reqs[1])
        fe.result(g2, timeout=60)
        stats = fe.stats()
        assert stats["replicas"][victim]["dead"] is not None
        assert stats["replicas"][survivor]["dead"] is None
        # ... and a cross-thread op against the corpse is loud.
        with pytest.raises(ReplicaDeadError):
            fe.replicas[victim].call(lambda srv: srv.ticks)
    finally:
        fe.close()


def test_replica_death_fails_inflight_requests(model):
    """In-flight requests died with the server's pool — they surface
    as ReplicaDeadError from result(), never a silent retry."""
    dec, params = model
    fe = FleetFrontend(
        dec, params, n_replicas=2, num_blocks=32, block_size=4,
        max_batch=2,
    )
    try:
        gid = fe.submit(jnp.asarray([[5, 1, 7, 2]], jnp.int32), 50)
        victim = None

        def seated():
            nonlocal victim
            for i, r in enumerate(fe.replicas):
                if r.inflight_gids:
                    victim = i
                    return True
            return False

        _wait_until(seated, msg="request in flight")
        fe.replicas[victim].inject_failure(RuntimeError("pool gone"))
        with pytest.raises(ReplicaDeadError, match="pool gone"):
            fe.result(gid, timeout=60)
    finally:
        fe.close()


def test_last_replica_death_fails_queued_requests(model):
    """With no survivors, re-routing has nowhere to go: queued
    requests fail typed instead of waiting forever."""
    dec, params = model
    fe = FleetFrontend(
        dec, params, n_replicas=1, num_blocks=16, block_size=4,
        max_batch=2,
    )
    try:
        _hold_all(fe)
        gid = fe.submit(jnp.asarray([[5, 1]], jnp.int32), 4)
        fe.replicas[0].inject_failure(RuntimeError("boom"))
        with pytest.raises((RuntimeError, ReplicaDeadError)):
            fe.result(gid, timeout=60)
    finally:
        fe.close()


# -- prefix routing + migration end to end ----------------------------


def _holder(fe, timeout=10.0):
    """Index of the replica whose advertisement is non-empty."""
    box = {}

    def some():
        for i, (_, dig, _) in enumerate(fe.board.snapshot()):
            if dig:
                box["idx"] = i
                return True
        return False

    _wait_until(some, timeout, "a digest advertisement")
    return box["idx"]


def test_prefix_routing_follows_the_cache(model):
    """After one request seeds a replica's radix cache and the advert
    lands, a same-prefix request routes to the holder by reason
    'prefix' — the routing signal the whole subsystem exists for."""
    dec, params = model
    fe = FleetFrontend(
        dec, params, n_replicas=2, num_blocks=16, block_size=4,
        max_batch=2, prefix_cache=True,
    )
    shared = jnp.asarray([[3, 9, 27, 1, 4, 4, 2, 8]], jnp.int32)
    try:
        fe.result(fe.submit(shared, 5), timeout=60)
        holder = _holder(fe)
        saved0 = fe.replicas[holder].srv.prefill_tokens_saved
        p2 = jnp.concatenate(
            [shared, jnp.asarray([[7, 7]], jnp.int32)], axis=1
        )
        fe.result(fe.submit(p2, 4), timeout=60)
        assert fe.routed["prefix"] == 1
        # The routed request actually reused the resident blocks.
        assert fe.replicas[holder].srv.prefill_tokens_saved > saved0
    finally:
        fe.close()


def test_migration_moves_blocks_and_keeps_tokens(model):
    """An overloaded holder's prefix chain ships to the least-loaded
    replica (disagg wire payload, real pool writes on both ends) and
    the rerouted request's tokens are unchanged."""
    dec, params = model
    shared = jnp.asarray([[3, 9, 27, 1, 4, 4, 2, 8]], jnp.int32)
    p2 = jnp.concatenate(
        [shared, jnp.asarray([[7, 7]], jnp.int32)], axis=1
    )
    ref, _ = serve_paged(
        dec, params, [(p2, 4)], num_blocks=16, block_size=4,
        max_batch=2, prefix_cache=True,
    )
    fe = FleetFrontend(
        dec, params, n_replicas=2, num_blocks=16, block_size=4,
        max_batch=2, prefix_cache=True, migrate_gap=4,
    )
    try:
        fe.result(fe.submit(shared, 5), timeout=60)
        holder = _holder(fe)
        # Fake a deep backlog on the holder: the queue_depth gauge is
        # admission-owned, so the replica loop won't overwrite it.
        fe.obs.queue_depth[holder].set(10)
        out = fe.result(fe.submit(p2, 4), timeout=60)
        assert fe.routed["migrate"] == 1
        assert fe.migrated_blocks == 2  # the prompt's two full blocks
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(ref[0])
        )
        # The chain is now resident on BOTH replicas.
        gen, dig = fe.replicas[1 - holder].srv.resident_digests()
        assert set(chain_digests(shared, 2, 4)) <= dig
    finally:
        fe.close()


# -- prefix payload wire format ---------------------------------------


def test_prefix_payload_loopback_round_trip():
    """Token bytes and lossless K/V block stacks survive real sockets
    bit-exactly (a migrated block becomes shared cache state — lossy
    transport would skew every future sharer)."""
    rng = np.random.default_rng(5)
    toks = [
        np.arange(4, dtype=np.int64).tobytes(),
        np.arange(4, 8, dtype=np.int64).tobytes(),
    ]
    pay = wire.PrefixPayload(
        toks=toks,
        k=rng.standard_normal((3, 2, 2, 4, 8)).astype(np.float32),
        v=rng.standard_normal((3, 2, 2, 4, 8)).astype(np.float32),
    )
    recv = ArrayReceiver(0, host="127.0.0.1", accept_timeout_s=10.0)
    got = []
    import threading

    def drain():
        it = iter(recv)
        got.append(wire.read_prefix_payload(it, recv))

    t = threading.Thread(target=drain)
    t.start()
    send = ArraySender("127.0.0.1", recv.port)
    n = wire.send_prefix_payload(send, pay)
    send.close()
    t.join(timeout=10)
    recv.close()
    out = got[0]
    assert out.toks == toks
    np.testing.assert_array_equal(out.k, pay.k)
    np.testing.assert_array_equal(out.v, pay.v)
    assert out.wire_bytes == n == recv.rx_frame_bytes


def test_prefix_payload_toks_shape_mismatch_is_loud():
    pay = wire.PrefixPayload(
        toks=[b"x"],
        k=np.zeros((1, 2, 1, 4, 2), np.float32),
        v=np.zeros((1, 2, 1, 4, 2), np.float32),
    )
    with pytest.raises(ValueError, match="token blobs"):
        wire.send_prefix_payload(object(), pay)
