"""Roofline analyzer: byte accounting, classification, report shape."""

import jax
import numpy as np

from defer_tpu.models import get_model
from defer_tpu.utils.flops import flops_by_node
from defer_tpu.utils.roofline import (
    bytes_by_node,
    format_report,
    peak_bandwidth,
    roofline_report,
)


def test_peak_bandwidth_table():
    assert peak_bandwidth("TPU v5 lite") == 819e9
    assert peak_bandwidth("TPU v4") == 1228e9
    assert peak_bandwidth("TFRT_CPU") is None


def test_bytes_by_node_dense():
    from tests.test_partition import residual_chain

    g = residual_chain()
    params = g.init(jax.random.key(0), (4, 8))
    b = bytes_by_node(g, params, (4, 8))
    # dense h0: read (4,8) in + (8,8) kernel + (8,) bias, write (4,8),
    # all fp32.
    d0 = next(n for n in g.nodes if n.op == "dense").name
    want = 4 * (4 * 8 + 8 * 8 + 8 + 4 * 8)
    assert b[d0] == want


def test_resnet50_classification_large_batch():
    """At batch 128 the big convs are compute-bound on v5e, the
    elementwise/BN tail is memory-bound, and the aggregate report
    carries both shares."""
    model = get_model("resnet50")
    params = model.graph.init(jax.random.key(0), (1, 64, 64, 3))
    rep = roofline_report(
        model.graph, params, (128, 64, 64, 3), "TPU v5 lite"
    )
    assert rep["ridge_intensity"] == round(197e12 / 819e9, 1)
    assert all("bound" in e for e in rep["top_nodes"])
    # Both regimes present: heavy convs contribute compute time, the
    # elementwise/BN tail contributes memory time.
    assert 0.0 < rep["time_share"]["compute"] < 1.0
    assert 0.0 < rep["time_share"]["memory"] < 1.0
    assert rep["items_per_sec_at_bound"] > 0
    # Totals agree with the flops module.
    f = flops_by_node(model.graph, params, (128, 64, 64, 3))
    assert rep["total_flops"] == sum(f.values())


def test_relu_is_memory_bound():
    """An elementwise op can never beat the ridge point."""
    from defer_tpu.graph.ir import GraphBuilder

    b = GraphBuilder("ew")
    x = b.input()
    g = b.build(b.add("relu", x, name="r"))
    params = g.init(jax.random.key(0), (1024, 1024))
    rep = roofline_report(g, params, (1024, 1024), "TPU v5 lite")
    (entry,) = rep["top_nodes"]
    assert entry["bound"] == "memory"
    assert entry["intensity"] < rep["ridge_intensity"]


def test_format_report_runs():
    model = get_model("vit_tiny")
    params = model.graph.init(jax.random.key(0), (1, 32, 32, 3))
    rep = roofline_report(
        model.graph, params, (8, 32, 32, 3), "TPU v5 lite", top=4
    )
    text = format_report(rep)
    assert "roofline[TPU v5 lite]" in text and "bound:" in text
    # Unknown device: no ridge, still produces a report.
    rep2 = roofline_report(
        model.graph, params, (8, 32, 32, 3), "TFRT_CPU", top=4
    )
    assert rep2["ridge_intensity"] is None
    assert "top_nodes" in rep2 and format_report(rep2)


def test_fusion_folds_elementwise_tail():
    """conv -> bn -> relu: with fusion the bn/relu cost ~param bytes
    only, and total bytes drop well below the unfused accounting."""
    from defer_tpu.graph.ir import GraphBuilder

    b = GraphBuilder("cbr")
    x = b.input()
    h = b.add("conv", x, name="c", features=64, kernel_size=(3, 3))
    h = b.add("batch_norm", h, name="bn")
    g = b.build(b.add("relu", h, name="r"))
    params = g.init(jax.random.key(0), (8, 32, 32, 16))
    fused = bytes_by_node(g, params, (8, 32, 32, 16))
    unfused = bytes_by_node(
        g, params, (8, 32, 32, 16), assume_fusion=False
    )
    act = 8 * 32 * 32 * 64 * 4
    # bn: no activation read (registers), no write (relu consumes it
    # fused), only its 4 per-channel param vectors.
    assert fused["bn"] == 4 * 64 * 4
    # relu is the graph output: write only.
    assert fused["r"] == act
    assert sum(fused.values()) < 0.5 * sum(unfused.values())
