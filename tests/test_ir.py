"""Graph IR: construction, validation, execution, memoization."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.graph.ir import Graph, GraphBuilder, GraphError, OpNode
from defer_tpu.ops import get_op, op_names, register_op


def tiny_residual_graph():
    """input -> dense -> relu -> [dense branch] -> add -> dense_out."""
    b = GraphBuilder("tiny")
    x = b.input()
    h = b.add("dense", x, name="d1", features=8)
    h = b.add("relu", h, name="r1")
    br = b.add("dense", h, name="d2", features=8)
    s = b.add("add", h, br, name="add_1")
    out = b.add("dense", s, name="d3", features=4)
    return b.build(out)


def test_builder_and_topology():
    g = tiny_residual_graph()
    assert g.input_name == "input"
    assert g.output_name == "d3"
    assert [n.name for n in g.nodes] == [
        "input", "d1", "r1", "d2", "add_1", "d3",
    ]


def test_builder_rejects_unknown_input():
    b = GraphBuilder("bad")
    b.input()
    with pytest.raises(GraphError):
        b.add("dense", "nope", features=4)


def test_graph_rejects_non_topological_order():
    with pytest.raises(GraphError):
        Graph(
            name="bad",
            nodes=(
                OpNode("a", "relu", ("b",)),
                OpNode("b", "input", ()),
            ),
            input_name="b",
            output_name="a",
        )


def test_graph_rejects_duplicate_names():
    with pytest.raises(GraphError):
        Graph(
            name="bad",
            nodes=(OpNode("a", "input", ()), OpNode("a", "relu", ("a",))),
            input_name="a",
            output_name="a",
        )


def test_init_and_apply_shapes():
    g = tiny_residual_graph()
    params = g.init(jax.random.key(0), (2, 16))
    x = jnp.ones((2, 16))
    y = g.apply(params, x)
    assert y.shape == (2, 4)
    spec = g.output_spec(params, (2, 16))
    assert spec.shape == (2, 4)


def test_apply_matches_manual_computation():
    g = tiny_residual_graph()
    params = g.init(jax.random.key(0), (3, 16))
    x = jax.random.normal(jax.random.key(1), (3, 16))
    h = x @ params["d1"]["kernel"] + params["d1"]["bias"]
    h = np.maximum(h, 0)
    br = h @ params["d2"]["kernel"] + params["d2"]["bias"]
    s = h + br
    want = s @ params["d3"]["kernel"] + params["d3"]["bias"]
    got = g.apply(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def test_multipath_node_evaluated_once():
    """The reference re-executes ops reachable along multiple paths
    (reference src/dag_util.py:18-19); the IR must not."""
    calls = {"n": 0}

    if "counting_op" not in op_names():

        @register_op("counting_op")
        def counting_apply(params, inputs, attrs):  # noqa: ANN001
            calls["n"] += 1
            return inputs[0] * 2.0

    b = GraphBuilder("diamond")
    x = b.input()
    shared = b.add("counting_op", x, name="shared")
    l = b.add("relu", shared, name="left")
    r = b.add("tanh", shared, name="right")
    out = b.add("add", l, r, name="join")
    g = b.build(out)
    params = g.init(jax.random.key(0), (1, 4))
    calls["n"] = 0
    g.apply(params, jnp.ones((1, 4)))
    assert calls["n"] == 1


def test_infer_shapes_covers_all_nodes():
    g = tiny_residual_graph()
    params = g.init(jax.random.key(0), (2, 16))
    specs = g.infer_shapes(params, (2, 16))
    assert set(specs) == {n.name for n in g.nodes}
    assert specs["add_1"].shape == (2, 8)


def test_op_registry_unknown_op():
    with pytest.raises(KeyError):
        get_op("definitely_not_an_op")
