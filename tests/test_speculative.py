"""Speculative decoding: greedy output must be BIT-IDENTICAL to the
target's own greedy decode, with fewer target forwards."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models.gpt import GptDecoder
from defer_tpu.models.llama import llama_config, tiny_llama
from defer_tpu.models.speculative import speculative_generate
from defer_tpu.parallel.transformer_stack import TransformerConfig


def _target():
    return GptDecoder(
        TransformerConfig(
            num_layers=3,
            dim=64,
            num_heads=4,
            ffn_dim=128,
            vocab_size=96,
            max_len=64,
            norm_style="pre",
        ),
        compute_dtype=jnp.float32,
    )


def _draft():
    # Smaller, independently initialized — realistic low-agreement
    # draft with the same vocabulary.
    return GptDecoder(
        TransformerConfig(
            num_layers=1,
            dim=32,
            num_heads=2,
            ffn_dim=64,
            vocab_size=96,
            max_len=64,
            norm_style="pre",
        ),
        compute_dtype=jnp.float32,
    )


@pytest.mark.parametrize("k", [1, 3, 4])
def test_speculative_equals_target_greedy(k):
    target, draft = _target(), _draft()
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    prompt = jnp.asarray([[5, 17, 3]], jnp.int32)
    steps = 12
    want = target.generate(tp, prompt, steps)
    got, stats = speculative_generate(target, tp, draft, dp, prompt, steps, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert got.shape == (1, 3 + steps)
    assert stats["rounds"] >= 1


def test_perfect_draft_amortizes_target_reads():
    """With draft == target every proposal is accepted: k tokens per
    target forward, so target_steps collapses to ~steps/k."""
    target = _target()
    tp = target.init(jax.random.key(0))
    prompt = jnp.asarray([[1, 2]], jnp.int32)
    steps, k = 12, 4
    want = target.generate(tp, prompt, steps)
    got, stats = speculative_generate(
        target, tp, target, tp, prompt, steps, k=k
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert stats["acceptance"] == 1.0
    # ceil(12/4)=3 verify rounds + 1 prefill.
    assert stats["target_steps"] == 4
    assert stats["target_steps"] < stats["plain_steps"]


def test_speculative_llama_target():
    """Cross-family: llama target (rope/GQA) with a gpt draft."""
    target = tiny_llama(64)
    draft = _draft()
    draft = dataclasses.replace(
        draft,
        cfg=dataclasses.replace(draft.cfg, vocab_size=target.cfg.vocab_size),
    )
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    prompt = jnp.asarray([[7, 2, 9, 4]], jnp.int32)
    steps = 10
    want = target.generate(tp, prompt, steps)
    got, _ = speculative_generate(target, tp, draft, dp, prompt, steps, k=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_sampled_speculative_deterministic_and_in_vocab():
    """temperature > 0: reproducible under a fixed rng, divergent
    under different rngs, tokens in vocab, sane stats."""
    target, draft = _target(), _draft()
    tp, dp = target.init(jax.random.key(0)), draft.init(jax.random.key(1))
    prompt = jax.random.randint(jax.random.key(2), (1, 4), 0, 96)
    a, sa = speculative_generate(
        target, tp, draft, dp, prompt, 10, k=3,
        temperature=0.9, top_p=0.95, rng=jax.random.key(7),
    )
    b, _ = speculative_generate(
        target, tp, draft, dp, prompt, 10, k=3,
        temperature=0.9, top_p=0.95, rng=jax.random.key(7),
    )
    c, _ = speculative_generate(
        target, tp, draft, dp, prompt, 10, k=3,
        temperature=0.9, top_p=0.95, rng=jax.random.key(8),
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    assert a.shape == (1, 14)
    toks = np.asarray(a)
    assert toks.min() >= 0 and toks.max() < 96
    assert 0.0 <= sa["acceptance"] <= 1.0


@pytest.mark.slow
def test_sampled_speculative_preserves_target_distribution():
    """The distribution-preservation theorem, empirically: the first
    token from speculative sampling is distributed as the TARGET's own
    filtered softmax — total-variation distance to the exact p stays
    at the sampling-noise floor. (A broken accept rule — e.g. taking
    q or a p/q mixture — shifts TV by the draft/target disagreement,
    an order of magnitude above this tolerance.)"""
    import collections

    from defer_tpu.models.gpt import truncate_logits

    vocab = 16
    cfg = dict(
        num_layers=1, dim=32, num_heads=2, ffn_dim=64,
        vocab_size=vocab, max_len=16, norm_style="pre",
    )
    target = GptDecoder(TransformerConfig(**cfg), compute_dtype=jnp.float32)
    draft = GptDecoder(TransformerConfig(**cfg), compute_dtype=jnp.float32)
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(5))  # different weights: q != p
    prompt = jnp.asarray([[3, 7, 1]], jnp.int32)
    temp = 1.2

    # Exact target distribution for the first generated token.
    last, _ = target.prefill(tp, target.init_cache(1), prompt)
    p = np.asarray(
        jax.nn.softmax(
            truncate_logits(last.astype(jnp.float32) / temp), axis=-1
        )
    )[0]

    n = 1500
    counts = collections.Counter()
    for i in range(n):
        ids, _ = speculative_generate(
            target, tp, draft, dp, prompt, 1, k=2,
            temperature=temp, rng=jax.random.key(100 + i),
        )
        counts[int(np.asarray(ids)[0, 3])] += 1
    freq = np.asarray([counts[t] / n for t in range(vocab)])
    tv = 0.5 * np.abs(freq - p).sum()
    assert tv < 0.08, (tv, freq, p)


def test_speculative_input_validation():
    target, draft = _target(), _draft()
    tp = target.init(jax.random.key(0))
    dp = draft.init(jax.random.key(1))
    with pytest.raises(ValueError, match="batch-1"):
        speculative_generate(
            target, tp, draft, dp, jnp.zeros((2, 3), jnp.int32), 4
        )
    with pytest.raises(ValueError, match="k=0"):
        speculative_generate(
            target, tp, draft, dp, jnp.zeros((1, 3), jnp.int32), 4, k=0
        )
    with pytest.raises(ValueError, match="max_len"):
        speculative_generate(
            target, tp, draft, dp, jnp.zeros((1, 3), jnp.int32), 500
        )


def test_speculative_with_int8_target():
    """Speculative decoding composes with weight-only int8: quantized
    target params still yield bit-exact agreement with the target's
    own (quantized) greedy decode."""
    from defer_tpu.models.quant import quantize_decoder_params

    target, draft = _target(), _draft()
    tp = quantize_decoder_params(target.init(jax.random.key(0)))
    dp = draft.init(jax.random.key(1))
    prompt = jnp.asarray([[5, 17, 3]], jnp.int32)
    want = target.generate(tp, prompt, 10)
    got, _ = speculative_generate(target, tp, draft, dp, prompt, 10, k=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
