"""Training through the SPMD pipeline (dp/pp/tp/sp/ep) and the
expert-parallel MoE block, on the 8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from defer_tpu.models.bert import SpmdBert
from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.parallel.train import make_train_step
from defer_tpu.parallel.transformer_stack import TransformerConfig


def _cfg(**kw):
    base = dict(
        num_layers=4, dim=32, num_heads=4, ffn_dim=64, vocab_size=64,
        max_len=32,
    )
    base.update(kw)
    return TransformerConfig(**base)


def test_moe_expert_parallel_matches_reference(devices):
    """Top-1 MoE with experts split over the expert axis == the same
    model computed unsharded."""
    cfg = _cfg(num_experts=4)
    mesh = make_mesh({"stage": 2, "expert": 4}, devices)
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    params = sb.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (3, 2, 8), 0, cfg.vocab_size)
    got = sb.make_step()(params, ids)
    want = sb.reference_apply(params, ids)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6
    )


def test_moe_rejects_mismatched_expert_axis(devices):
    cfg = _cfg(num_experts=3)
    mesh = make_mesh({"stage": 1, "expert": 2}, devices[:2])
    with pytest.raises(ValueError, match="not divisible"):
        SpmdBert(mesh, cfg)


def _run_training(mesh, cfg, steps=12, num_mb=4, batch=2, seq=8):
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    init_state, train_step = make_train_step(
        sb, optax.adam(1e-2), num_classes=4
    )
    state = init_state(jax.random.key(0))
    ids = jax.random.randint(
        jax.random.key(1), (num_mb, batch, seq), 0, cfg.vocab_size
    )
    labels = jax.random.randint(jax.random.key(2), (num_mb, batch), 0, 4)
    losses = []
    for _ in range(steps):
        state, loss = train_step(state, ids, labels)
        losses.append(float(loss))
    return losses


def test_train_step_dp_pp_tp(devices):
    mesh = make_mesh({"data": 2, "stage": 2, "model": 2}, devices)
    losses = _run_training(mesh, _cfg())
    assert np.isfinite(losses).all()
    # Overfitting one tiny fixed batch with Adam must drive loss down.
    assert losses[-1] < losses[0] * 0.7, losses


def test_train_step_pp_sp_ep(devices):
    """Pipeline x ring-attention sequence parallel x expert parallel."""
    mesh = make_mesh({"stage": 2, "seq": 2, "expert": 2}, devices)
    losses = _run_training(mesh, _cfg(num_experts=2))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses


def test_train_loss_matches_reference_forward(devices):
    """The pipelined training loss equals the loss computed from the
    unpipelined reference forward on the same params."""
    mesh = make_mesh({"stage": 4}, devices[:4])
    cfg = _cfg()
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    init_state, train_step = make_train_step(
        sb, optax.sgd(0.0), num_classes=4
    )
    state = init_state(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (5, 2, 8), 0, cfg.vocab_size)
    labels = jax.random.randint(jax.random.key(2), (5, 2), 0, 4)
    # train_step donates its input state, so take the reference forward
    # (which needs the pre-update params the loss was computed at) first.
    pooled = sb.reference_apply(state.params, ids)
    logits = (
        pooled.astype(jnp.float32) @ state.params["cls_w"]
        + state.params["cls_b"]
    )
    _, loss = train_step(state, ids, labels)
    want = optax.softmax_cross_entropy_with_integer_labels(
        logits, labels
    ).mean()
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)


def test_moe_a2a_dispatch_matches_dense(devices):
    """The all-to-all capacity dispatch with a no-drop capacity factor
    must equal the dense masked dispatch exactly — same router, same
    top-1, same gates; only the movement differs (one all_to_all out,
    expert-local compute, one all_to_all back)."""
    import dataclasses

    cfg_a2a = _cfg(
        num_experts=4,
        moe_dispatch="a2a",
        # Local tokens per device = 2*8 = 16; cap = ceil(8*16/4) = 32:
        # nothing can drop, so equality with dense is exact.
        capacity_factor=8.0,
    )
    mesh = make_mesh({"stage": 2, "expert": 4}, devices)
    sb = SpmdBert(mesh, cfg_a2a, compute_dtype=jnp.float32)
    params = sb.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (3, 2, 8), 0, 64)
    got = sb.make_step()(params, ids)

    cfg_dense = dataclasses.replace(cfg_a2a, moe_dispatch="dense")
    sb_dense = SpmdBert(mesh, cfg_dense, compute_dtype=jnp.float32)
    want = sb_dense.make_step()(params, ids)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6
    )


def test_moe_a2a_capacity_drops_are_bounded(devices):
    """With capacity 1 most tokens fall through on the residual path:
    the output must stay finite and differ from the no-drop result
    (drops really happened) without blowing up."""
    cfg = _cfg(num_experts=4, moe_dispatch="a2a", capacity_factor=0.01)
    mesh = make_mesh({"stage": 2, "expert": 4}, devices)
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    params = sb.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (3, 2, 8), 0, 64)
    out = sb.make_step()(params, ids)
    assert bool(jnp.isfinite(out).all())
    import dataclasses

    full = SpmdBert(
        mesh,
        dataclasses.replace(cfg, capacity_factor=8.0),
        compute_dtype=jnp.float32,
    ).make_step()(params, ids)
    assert not np.allclose(np.asarray(out), np.asarray(full))


def test_moe_a2a_trains(devices):
    """Gradients flow through both all_to_alls: one jitted train step
    on the a2a dispatch produces a finite loss."""
    cfg = _cfg(num_experts=2, moe_dispatch="a2a", capacity_factor=2.0)
    mesh = make_mesh({"stage": 2, "expert": 2, "data": 2}, devices)
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    init_state, train_step = make_train_step(
        sb, optax.adam(1e-3), num_classes=4
    )
    state = init_state(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (3, 2, 8), 0, 64)
    labels = jax.random.randint(jax.random.key(2), (3, 2), 0, 4)
    state, loss = train_step(state, ids, labels)
    assert jnp.isfinite(loss)


def test_capacity_factor_validated():
    with pytest.raises(ValueError, match="capacity_factor"):
        _cfg(num_experts=2, moe_dispatch="a2a", capacity_factor=0.0)


def test_remat_train_step_matches_exact(devices):
    """cfg.remat recomputes block internals on the backward pass —
    same math, less activation memory. The SECOND step's loss depends
    on the first step's gradients, so agreement across two steps
    proves the remat'd backward, not just the shared forward."""
    import dataclasses

    cfg = _cfg()
    mesh = make_mesh({"stage": 2, "model": 2}, devices[:4])
    ids = jax.random.randint(jax.random.key(1), (3, 2, 8), 0, 64)
    labels = jax.random.randint(jax.random.key(2), (3, 2), 0, 4)

    traces = []
    for c in (cfg, dataclasses.replace(cfg, remat=True)):
        sb = SpmdBert(mesh, c, compute_dtype=jnp.float32)
        init_state, train_step = make_train_step(
            sb, optax.adam(1e-3), num_classes=4
        )
        state = init_state(jax.random.key(0))
        state, loss1 = train_step(state, ids, labels)
        _, loss2 = train_step(state, ids, labels)
        traces.append((float(loss1), float(loss2)))
    # Different compiled graphs may round differently in the last ulp;
    # everything beyond that means wrong gradients.
    np.testing.assert_allclose(traces[0], traces[1], rtol=1e-6)
    assert traces[0][1] != traces[0][0]  # step 2 really used the grads


def test_top2_moe_dense_equals_a2a(devices):
    """Mixtral-style top-2 routing: the dense and a2a dispatches must
    still agree exactly at no-drop capacity (each token now claims two
    expert slots with renormalized weights)."""
    import dataclasses

    cfg = _cfg(
        num_experts=4,
        moe_top_k=2,
        moe_dispatch="a2a",
        capacity_factor=8.0,
    )
    mesh = make_mesh({"stage": 2, "expert": 4}, devices)
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    params = sb.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (3, 2, 8), 0, 64)
    got = sb.make_step()(params, ids)

    sb_dense = SpmdBert(
        mesh,
        dataclasses.replace(cfg, moe_dispatch="dense"),
        compute_dtype=jnp.float32,
    )
    want = sb_dense.make_step()(params, ids)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6
    )

    # Top-2 really engages a second expert: output differs from top-1
    # on the same params.
    sb_top1 = SpmdBert(
        mesh,
        dataclasses.replace(cfg, moe_top_k=1, moe_dispatch="dense"),
        compute_dtype=jnp.float32,
    )
    top1 = sb_top1.make_step()(params, ids)
    assert not np.allclose(np.asarray(want), np.asarray(top1))


def test_top2_moe_trains(devices):
    cfg = _cfg(num_experts=2, moe_top_k=2, moe_dispatch="a2a",
               capacity_factor=4.0)
    mesh = make_mesh({"stage": 2, "expert": 2, "data": 2}, devices)
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    init_state, train_step = make_train_step(
        sb, optax.adam(1e-3), num_classes=4
    )
    state = init_state(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (3, 2, 8), 0, 64)
    labels = jax.random.randint(jax.random.key(2), (3, 2), 0, 4)
    _, loss = train_step(state, ids, labels)
    assert jnp.isfinite(loss)


def test_moe_top_k_validated():
    with pytest.raises(ValueError, match="moe_top_k"):
        _cfg(num_experts=2, moe_top_k=3)


def test_lm_train_then_serve_on_decoder(devices):
    """Next-token LM training through the pipeline, then the SAME
    trained tree (stack flattened from [Stages, L/S, ...] to [L, ...])
    serves on the KV-cache decoder: the decoder's full-sequence logits
    assign the training corpus a much better loss than at init, and
    pipeline-side logits equal decoder-side logits."""
    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.parallel.train import make_lm_train_step
    from defer_tpu.parallel.transformer_stack import _layer_norm

    cfg = TransformerConfig(
        num_layers=4, dim=32, num_heads=4, ffn_dim=64,
        vocab_size=64, max_len=16, norm_style="pre", causal=True,
    )
    mesh = make_mesh({"data": 2, "stage": 2}, devices[:4])
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    init_state, step = make_lm_train_step(sb, optax.adam(5e-3))
    state = init_state(jax.random.key(0))
    # One fixed corpus, memorized.
    ids = jax.random.randint(jax.random.key(1), (2, 4, 12), 0, 64)
    losses = []
    for _ in range(30):
        state, loss = step(state, ids)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0], losses

    def decoder_loss(dparams):
        dec = GptDecoder(cfg, compute_dtype=jnp.float32)
        flat_ids = np.asarray(ids).reshape(-1, 12)
        logits = dec.reference_logits(dparams, jnp.asarray(flat_ids))
        return float(
            optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1, :], jnp.asarray(flat_ids)[:, 1:]
            ).mean()
        )

    def flatten(tree):
        out = {k: v for k, v in tree.items() if k != "stack"}
        out["stack"] = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a).reshape(-1, *a.shape[2:]),
            tree["stack"],
        )
        return out

    trained = decoder_loss(flatten(state.params))
    fresh = decoder_loss(flatten(init_state(jax.random.key(0)).params))
    assert trained < 0.5 * fresh, (trained, fresh)
    # Train/serve logits parity at one position.
    dec = GptDecoder(cfg, compute_dtype=jnp.float32)
    dparams = flatten(state.params)
    want = dec.reference_logits(dparams, ids[0])[:, -1, :]
    h = sb.make_hidden_step()(state.params, ids)[0].astype(jnp.float32)
    h = _layer_norm(
        h,
        state.params["final_ln_scale"],
        state.params["final_ln_bias"],
        cfg.layer_norm_eps,
    )
    got = (h @ state.params["token_embedding"].T)[:, -1, :]
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_dpo_learns_preferences(devices):
    """DPO through the pipeline: a fixed preference set (chosen vs
    rejected completions of shared prompts) drives loss below log(2)
    and pair accuracy to 1.0, while the frozen reference params never
    change."""
    from defer_tpu.parallel.train import (
        make_dpo_train_step,
        sequence_logprobs,
    )

    cfg = TransformerConfig(
        num_layers=4, dim=32, num_heads=4, ffn_dim=64,
        vocab_size=64, max_len=16, norm_style="pre", causal=True,
    )
    mesh = make_mesh({"data": 2, "stage": 2}, devices[:4])
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    init_state, step = make_dpo_train_step(sb, optax.adam(5e-3), beta=0.5)
    state = init_state(jax.random.key(0))
    ref = jax.tree_util.tree_map(jnp.array, state.params)
    ref_before = jax.tree_util.tree_map(np.asarray, ref)

    # Shared 4-token prompts; completions differ in the last 8 tokens.
    m, b = 2, 4
    prompt = jax.random.randint(jax.random.key(1), (m, b, 4), 0, 64)
    win = jax.random.randint(jax.random.key(2), (m, b, 8), 0, 64)
    lose = jax.random.randint(jax.random.key(3), (m, b, 8), 0, 64)
    chosen = jnp.concatenate([prompt, win], axis=-1)
    rejected = jnp.concatenate([prompt, lose], axis=-1)
    mask = jnp.concatenate(
        [jnp.zeros((m, b, 4), jnp.int32), jnp.ones((m, b, 8), jnp.int32)],
        axis=-1,
    )

    losses, accs = [], []
    for _ in range(25):
        state, (loss, acc) = step(
            state, ref, chosen, rejected, mask, mask
        )
        losses.append(float(loss))
        accs.append(float(acc))
    assert losses[-1] < float(np.log(2.0)) < losses[0] + 0.2, losses
    assert accs[-1] == 1.0, accs
    jax.tree_util.tree_map(
        lambda a, b_: np.testing.assert_array_equal(np.asarray(a), b_),
        ref,
        ref_before,
    )
    # The policy now scores chosen completions above rejected ones.
    pi_c = sequence_logprobs(sb, state.params, chosen, mask)
    pi_r = sequence_logprobs(sb, state.params, rejected, mask)
    assert float((pi_c > pi_r).mean()) == 1.0


def test_dpo_requires_pre_ln_causal(devices):
    from defer_tpu.parallel.train import make_dpo_train_step

    mesh = make_mesh({"stage": 2}, devices[:2])
    sb = SpmdBert(mesh, _cfg(), compute_dtype=jnp.float32)
    with pytest.raises(ValueError, match="causal"):
        make_dpo_train_step(sb, optax.adam(1e-3))
    sb_post = SpmdBert(
        mesh, _cfg(causal=True), compute_dtype=jnp.float32
    )
    with pytest.raises(ValueError, match="pre"):
        make_dpo_train_step(sb_post, optax.adam(1e-3))


def test_lm_train_requires_causal(devices):
    from defer_tpu.parallel.train import make_lm_train_step

    mesh = make_mesh({"stage": 2}, devices[:2])
    sb = SpmdBert(
        mesh, _cfg(norm_style="pre"), compute_dtype=jnp.float32
    )
    with pytest.raises(ValueError, match="causal"):
        make_lm_train_step(sb, optax.adam(1e-3))
    # Post-norm causal trains fine as a classifier but cannot serve on
    # the pre-LN decoder — reject before the training run, not after.
    sb_post = SpmdBert(
        mesh, _cfg(causal=True), compute_dtype=jnp.float32
    )
    with pytest.raises(ValueError, match="pre"):
        make_lm_train_step(sb_post, optax.adam(1e-3))


def test_zero1_matches_replicated_and_shards_moments(devices):
    """ZeRO-1 is a layout change, not a numerics change: losses match
    the replicated-optimizer run step for step, and the Adam moments
    really are sharded over the data axis (and stay sharded after
    updates)."""
    cfg = _cfg()
    ids = jax.random.randint(jax.random.key(1), (3, 4, 16), 0, 64)
    labels = jax.random.randint(jax.random.key(2), (3, 4), 0, 4)

    def run(zero1):
        mesh = make_mesh({"data": 2, "stage": 2, "model": 2}, devices)
        sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
        init_state, train_step = make_train_step(
            sb, optax.adam(1e-3), num_classes=4, zero1=zero1
        )
        state = init_state(jax.random.key(0))
        losses = []
        for _ in range(4):
            state, loss = train_step(state, ids, labels)
            losses.append(float(loss))
        return losses, state

    losses_rep, _ = run(zero1=False)
    losses_z1, state = run(zero1=True)
    np.testing.assert_allclose(losses_z1, losses_rep, rtol=1e-5)

    # After 4 donated updates the moments must still carry the data
    # axis — XLA resolving them back to replicated would silently give
    # the memory saving back.
    def spec_axes(spec):
        out = set()
        for e in spec:
            if isinstance(e, tuple):
                out |= set(e)
            elif e is not None:
                out.add(e)
        return out

    mu = state.opt_state[0].mu
    dp_sharded = [
        leaf
        for leaf in jax.tree_util.tree_leaves(mu)
        if "data" in spec_axes(leaf.sharding.spec)
    ]
    assert dp_sharded, "no Adam moment is sharded over the data axis"
    # The big stack matrices in particular must be dp-sharded.
    assert any(leaf.ndim >= 3 for leaf in dp_sharded)


def test_fsdp_matches_replicated(devices):
    """FSDP (weights sharded over the data axis, all-gathered just in
    time per block) is a layout change: forward and training losses
    equal the replicated-weight run exactly, while every planned stack
    leaf rests at 1/dp per chip."""
    import math

    cfg = _cfg()
    mesh = make_mesh({"data": 2, "stage": 2, "model": 2}, devices)
    ids = jax.random.randint(jax.random.key(1), (3, 4, 16), 0, 64)
    labels = jax.random.randint(jax.random.key(2), (3, 4), 0, 4)

    def run(fsdp):
        sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32, fsdp=fsdp)
        init_state, step = make_train_step(
            sb, optax.adam(1e-3), num_classes=4
        )
        state = init_state(jax.random.key(0))
        losses = []
        for _ in range(4):
            state, loss = step(state, ids, labels)
            losses.append(float(loss))
        return losses, state

    losses_rep, _ = run(False)
    losses_fsdp, state = run(True)
    np.testing.assert_allclose(losses_fsdp, losses_rep, rtol=1e-6)

    w1 = state.params["stack"]["w1"]
    assert "data" in tuple(w1.sharding.spec)
    local = w1.addressable_shards[0].data.size
    # stage x data x model all shard w1: local = global / 8.
    assert local == math.prod(w1.shape) // 8


def test_fsdp_with_remat_and_lora(devices):
    """FSDP composes with rematerialization (re-gather on backward)
    and LoRA (adapter factors get planned too)."""
    import dataclasses as dc

    cfg = dc.replace(_cfg(), remat=True, lora_rank=4)
    mesh = make_mesh({"data": 2, "stage": 2}, devices[:4])
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32, fsdp=True)
    assert "wq:a" in sb._fsdp_plan
    init_state, step = make_train_step(sb, optax.adam(1e-3), num_classes=4)
    state = init_state(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (3, 2, 16), 0, 64)
    labels = jax.random.randint(jax.random.key(2), (3, 2), 0, 4)
    _, loss = step(state, ids, labels)
    assert jnp.isfinite(loss)


def test_fsdp_composes_with_zero1(devices):
    """fsdp=True + zero1=True must not double-apply the data axis:
    FSDP-sharded params are already 1/dp, so their moments inherit
    that layout, and leaves FSDP skipped still get ZeRO's sharding."""
    cfg = _cfg()
    mesh = make_mesh({"data": 2, "stage": 2}, devices[:4])
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32, fsdp=True)
    init_state, step = make_train_step(
        sb, optax.adam(1e-3), num_classes=4, zero1=True
    )
    state = init_state(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (3, 2, 16), 0, 64)
    labels = jax.random.randint(jax.random.key(2), (3, 2), 0, 4)
    for _ in range(2):
        state, loss = step(state, ids, labels)
    assert jnp.isfinite(loss)
    mu = state.opt_state[0].mu
    # FSDP stack moment: data axis present exactly once (inherited).
    w1_spec = [e for e in mu["stack"]["w1"].sharding.spec if e is not None]
    assert w1_spec.count("data") == 1
    # Replicated embedding: ZeRO-1 still shards its moment.
    emb_spec = tuple(mu["token_embedding"].sharding.spec)
    assert "data" in emb_spec


def test_fsdp_requires_data_axis(devices):
    mesh = make_mesh({"stage": 2}, devices[:2])
    with pytest.raises(ValueError, match="data"):
        SpmdBert(mesh, _cfg(), fsdp=True)


def test_zero1_without_data_axis_is_a_noop(devices):
    """zero1=True on a mesh with no 'data' axis must degrade to the
    replicated layout, not crash trying to use a missing axis."""
    mesh = make_mesh({"stage": 2}, devices[:2])
    sb = SpmdBert(mesh, _cfg(), compute_dtype=jnp.float32)
    init_state, train_step = make_train_step(
        sb, optax.adam(1e-3), num_classes=4, zero1=True
    )
    state = init_state(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (3, 2, 16), 0, 64)
    labels = jax.random.randint(jax.random.key(2), (3, 2), 0, 4)
    _, loss = train_step(state, ids, labels)
    assert jnp.isfinite(loss)
