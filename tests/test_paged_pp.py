"""Pipeline-parallel paged serving: `PagedDecodeServer(pp_stages=S)`
splits the layer stack into S contiguous stages — each owning ONLY its
layers' slice of the paged KV pool — and decodes through a round-major
pipelined window, and nothing the user can observe moves: greedy
outputs are token-identical to pp_stages=1 across attention modes,
prefix cache, decode windows, chunked prefill, explicit/probed cuts,
the joint pp x tp mesh, and the framed-transport stage placement
(runtime/remote_stage.py serve_pp_stage).

Schedule contract (the perf claim in miniature, pinned here because a
parity test alone can't see it): per-stage pool bytes scale as 1/S
while their sum equals the monolithic pool, every stage's labeled
dispatch counter advances equally (each microbatch round visits every
stage exactly once), and the measured bubble fraction is the realized
dispatch-slot accounting, not an assumed closed form. Runs on forced
host devices (conftest.py), so the same code path lights up on real
chips.
"""

import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu import obs
from defer_tpu.models.gpt import tiny_gpt
from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.runtime.paged import PagedDecodeServer, serve_paged


@pytest.fixture(scope="module")
def model():
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    return dec, params


def _requests(vocab):
    """Shared prefix on the first two (radix hits under prefix_cache),
    one prompt long enough that prefill_chunk=8 actually splits it."""
    rng = np.random.default_rng(3)
    base = jnp.asarray(rng.integers(1, vocab, size=(1, 6)), jnp.int32)
    ext = jnp.asarray(rng.integers(1, vocab, size=(1, 4)), jnp.int32)
    return [
        (base, 7),
        (jnp.concatenate([base, ext], axis=1), 5),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 11)), jnp.int32), 6),
    ]


@pytest.fixture(scope="module")
def solo(model):
    """Greedy references: every pp config below must reproduce the
    plain decoder's own tokens, not merely agree with pp_stages=1."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    return reqs, [dec.generate(params, p, s) for p, s in reqs]


# Curated cut of the (attention x prefix_cache x window x chunk x S)
# space — both attention tick bodies, both window shapes, the radix
# path, and chunked prefill each cross a stage boundary at least once,
# at S=2 and an S=4 point, without compiling the full product. The
# tier-1 suite sits against its wall clock cap, so all but the two
# cheapest points ride in the slow tier (full-run only).
MATRIX = [
    pytest.param("gathered", False, 1, None, 2, marks=pytest.mark.slow),
    ("blockwise", True, 8, None, 2),
    pytest.param("gathered", True, 8, None, 4, marks=pytest.mark.slow),
    pytest.param("gathered", False, 1, 8, 2, marks=pytest.mark.slow),
]


@pytest.mark.parametrize("attention,prefix_cache,window,chunk,s", MATRIX)
def test_pp_token_identical(
    model, solo, attention, prefix_cache, window, chunk, s
):
    dec, params = model
    reqs, want = solo
    outs, stats = serve_paged(
        dec, params, reqs, num_blocks=16, block_size=4, max_batch=2,
        attention=attention, prefix_cache=prefix_cache,
        decode_window=window, prefill_chunk=chunk, pp_stages=s,
    )
    for i, (got, ref) in enumerate(zip(outs, want)):
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(ref),
            err_msg=f"request {i} attention={attention} pp={s}",
        )
    assert stats["pp_stages"] == s
    assert 0.0 <= stats["pp_bubble_fraction"] < 1.0


@pytest.mark.slow
def test_pp_tp_joint_mesh(model, solo):
    """pp x tp: the joint mesh carries the stage axis OUTERMOST around
    the model axis; each stage is a tp submesh and tokens still match
    the plain decoder."""
    dec, params = model
    reqs, want = solo
    mesh = make_mesh({"stage": 2, "model": 2}, jax.devices()[:4])
    outs, st = serve_paged(
        dec, params, reqs, num_blocks=16, block_size=4, max_batch=2,
        pp_stages=2, mesh=mesh,
    )
    for got, ref in zip(outs, want):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert st["pp_stages"] == 2 and st["tp_psums"] > 0


def test_pool_slices_and_stage_counters(model, solo):
    """The capacity + schedule pin: each stage owns a 1/S slice of the
    pool (their sum IS the monolithic pool's bytes), every stage's
    labeled dispatch counter advances by the same amount, and the
    per-stage occupancy vector matches the bubble the server reports."""
    dec, params = model
    reqs, _ = solo
    kw = dict(num_blocks=16, block_size=4, max_batch=2, decode_window=8)
    _, st1 = serve_paged(dec, params, reqs, **kw)
    with obs.counter_deltas() as d:
        _, st2 = serve_paged(dec, params, reqs, pp_stages=2, **kw)
    assert st1["pp_stages"] == 1 and st1["pp_stage_pool_bytes"] == []
    bytes2 = st2["pp_stage_pool_bytes"]
    assert len(bytes2) == 2 and bytes2[0] == bytes2[1]
    assert sum(bytes2) == st1["pool_bytes"]
    disp = st2["pp_stage_dispatches"]
    assert len(disp) == 2 and disp[0] == disp[1] > 0
    for s in range(2):
        assert d[f'defer_pp_stage_dispatches_total{{stage="{s}"}}'] == disp[s]
    occ = st2["pp_stage_occupancy"]
    assert len(occ) == 2 and all(0.0 < o <= 1.0 for o in occ)
    assert st2["pp_bubble_fraction"] == pytest.approx(
        1.0 - sum(occ) / len(occ)
    )


@pytest.mark.slow
def test_explicit_cuts_and_probe_balance(model, solo):
    """Stage splits: explicit pp_cuts are honored verbatim (a skewed
    3+1 split still decodes token-identical), and pp_balance='probe'
    picks cuts via the measured per-layer step cost."""
    dec, params = model
    reqs, want = solo
    kw = dict(num_blocks=16, block_size=4, max_batch=2)
    outs, st = serve_paged(
        dec, params, reqs, pp_stages=2, pp_cuts=[0, 3], **kw
    )
    for got, ref in zip(outs, want):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert st["pp_cut_starts"] == [0, 3]
    outs, st = serve_paged(
        dec, params, reqs, pp_stages=2, pp_balance="probe", **kw
    )
    for got, ref in zip(outs, want):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    starts = st["pp_cut_starts"]
    assert starts[0] == 0 and len(starts) == 2
    assert 0 < starts[1] < dec.cfg.num_layers


def test_balance_cuts_on_skewed_stack():
    """The min-max DP behind pp_balance='probe' splits a SKEWED stack
    by cost, not layer count: one fat layer up front pulls the cut
    left of the equal-count split."""
    from defer_tpu.parallel.pipeline import balance_stage_cuts

    assert balance_stage_cuts([1.0] * 4, 2) == [0, 2]
    assert balance_stage_cuts([4.0, 1.0, 1.0, 1.0], 2) == [0, 1]
    assert balance_stage_cuts([1.0, 1.0, 1.0, 4.0], 2) == [0, 3]
    assert balance_stage_cuts([3.0, 1.0, 1.0, 1.0, 1.0, 3.0], 3) == [
        0, 1, 4,
    ]


def test_transport_stage_parity(model, solo):
    """Framed-transport placement: stage 1 lives behind a
    serve_pp_stage worker reached over the wire, controller keeps
    stage 0 in-process — tokens must not move, and the worker must
    exit on the STOP frame."""
    from defer_tpu.runtime.remote_stage import serve_pp_stage
    from defer_tpu.runtime.transport import ArrayReceiver

    dec, params = model
    reqs, want = solo
    results = ArrayReceiver(0, host="127.0.0.1", accept_timeout_s=60.0)
    ports: queue.Queue = queue.Queue()
    worker = threading.Thread(
        target=serve_pp_stage,
        args=(dec, params, 2, 4),
        kwargs=dict(
            num_blocks=16, block_size=4, attention="gathered",
            listen_port=0, listen_host="127.0.0.1",
            result_host="127.0.0.1", result_port=results.port,
            accept_timeout_s=60.0, announce=ports.put,
        ),
        daemon=True,
    )
    worker.start()
    try:
        port = ports.get(timeout=30)
        outs, st = serve_paged(
            dec, params, reqs, num_blocks=16, block_size=4,
            max_batch=2, pp_stages=2, pp_cuts=[0, 2],
            pp_remote={1: ("127.0.0.1", port, results)},
        )
        for got, ref in zip(outs, want):
            np.testing.assert_array_equal(
                np.asarray(got), np.asarray(ref)
            )
        assert st["pp_stage_dispatches"][1] > 0
        worker.join(timeout=30)
        assert not worker.is_alive(), "worker did not exit on STOP"
    finally:
        results.close()


def test_pp_ctor_validation(model):
    """Every bad composition is caught at construction with the fix
    spelled out, before any compile."""
    dec, params = model
    kw = dict(num_blocks=8, block_size=4, max_batch=2)
    with pytest.raises(ValueError, match="only apply with pp_stages > 1"):
        PagedDecodeServer(dec, params, pp_cuts=[0, 2], **kw)
    with pytest.raises(ValueError, match="exceeds num_layers"):
        PagedDecodeServer(dec, params, pp_stages=8, **kw)
    with pytest.raises(ValueError, match="spec_k > 0 does not compose"):
        PagedDecodeServer(
            dec, params, pp_stages=2, spec_draft=dec,
            spec_params=params, spec_k=2, **kw,
        )
    with pytest.raises(ValueError, match="does not divide into"):
        PagedDecodeServer(
            dec, params, num_blocks=8, block_size=4, max_batch=3,
            pp_stages=2, pp_inflight=2,
        )
    with pytest.raises(ValueError, match="pins ONE device"):
        PagedDecodeServer(
            dec, params, pp_stages=2, device=jax.devices()[0], **kw
        )
    srv = PagedDecodeServer(dec, params, pp_stages=2, **kw)
    with pytest.raises(ValueError, match="disagg ingest"):
        srv.submit_prefilled(jnp.asarray([[1, 2, 3]], jnp.int32), 4)
