"""Model zoo: build, shape-check, and validate default cut points."""

import jax
import pytest

from defer_tpu.graph.partition import validate_cut_points
from defer_tpu.models import get_model, model_names

pytestmark = pytest.mark.slow


def test_model_registry_lists_models():
    names = model_names()
    assert "resnet50" in names
    assert "vgg19" in names


@pytest.mark.parametrize("name", ["resnet50", "vgg16", "vgg19"])
def test_cnn_builds_and_has_valid_cuts(name):
    model = get_model(name)
    assert model.input_shape == (224, 224, 3)
    for n in (2, 4, 8):
        cuts = model.default_cuts(n)
        assert len(cuts) == n - 1
        validate_cut_points(model.graph, cuts)


def test_resnet50_output_shape():
    model = get_model("resnet50")
    params = model.graph.init(jax.random.key(0), (2, 64, 64, 3))
    spec = model.graph.output_spec(params, (2, 64, 64, 3))
    assert spec.shape == (2, 1000)


def test_default_cuts_exact_count_at_limit():
    """num_stages == len(candidates)+1 must not silently collapse cuts."""
    model = get_model("resnet50")
    cuts = model.default_cuts(17)
    assert len(cuts) == 16 and len(set(cuts)) == 16
    with pytest.raises(ValueError, match="cannot make 18"):
        model.default_cuts(18)


def test_resnet50_has_16_adds():
    model = get_model("resnet50")
    assert model.cut_candidates == tuple(f"add_{i}" for i in range(1, 17))


@pytest.mark.parametrize(
    "name,res,feat",
    [
        ("mobilenetv2", 96, 1280),
        ("efficientnet_b0", 96, 1280),
        ("inceptionv3", 96, 2048),
        ("inception_resnet_v2", 96, 1536),
        ("nasnet_mobile", 96, 1056),
        ("xception", 96, 2048),
    ],
)
def test_new_zoo_builds_with_expected_head(name, res, feat):
    """Shape-infer each zoo model (GAP heads are resolution-flexible, so
    a small input keeps eval_shape cheap) and check the penultimate
    feature width matches the published architecture."""
    model = get_model(name)
    params = model.graph.init(jax.random.key(0), (1, res, res, 3))
    spec = model.graph.output_spec(params, (1, res, res, 3))
    assert spec.shape == (1, 1000)
    head = params["predictions_dense"]["kernel"]
    assert head.shape == (feat, 1000)


@pytest.mark.parametrize(
    "name", ["mobilenetv2", "efficientnet_b0", "inceptionv3",
             "inception_resnet_v2", "xception"]
)
def test_new_zoo_cuts_are_valid(name):
    model = get_model(name)
    for n in (2, 4, 8):
        cuts = model.default_cuts(n)
        assert len(cuts) == n - 1
        validate_cut_points(model.graph, cuts)


def test_nasnet_pipelinable_via_multi_tensor_bundles():
    """NASNet's p-skip makes cell boundaries non-articulation points;
    the (cell_i, cell_i-1) bundles make every boundary cuttable."""
    model = get_model("nasnet_mobile")
    # 4 + 3*num_blocks cells -> one boundary per cell (last is single).
    assert len(model.cut_candidates) == 2 + 15
    for n in (2, 8, len(model.cut_candidates) + 1):
        validate_cut_points(model.graph, model.default_cuts(n))
    # A bare cell output mid-chain is still NOT valid on its own.
    from defer_tpu.graph.partition import PartitionError
    with pytest.raises(PartitionError):
        validate_cut_points(model.graph, ["cell_2"])


def test_nasnet_multi_cut_partition_composes():
    """Composed bundle stages must equal the unpartitioned forward."""
    import jax.numpy as jnp

    from defer_tpu.graph.partition import partition, stage_params

    model = get_model("nasnet_mobile")
    shape = (1, 64, 64, 3)
    params = model.graph.init(jax.random.key(4), shape)
    x = jax.random.normal(jax.random.key(5), shape)
    full = model.graph.apply(params, x)
    stages = partition(model.graph, model.default_cuts(4))
    y = x
    for st in stages:
        y = st.apply(stage_params(params, st), y)
    assert jnp.allclose(full, y, atol=1e-5), float(jnp.max(jnp.abs(full - y)))


def test_mobilenetv2_partition_composes():
    """Composed pipeline stages must equal the unpartitioned forward
    (the invariant the reference never checks, SURVEY.md §3.4)."""
    import jax.numpy as jnp

    from defer_tpu.graph.partition import partition, stage_params

    model = get_model("mobilenetv2")
    shape = (1, 96, 96, 3)
    params = model.graph.init(jax.random.key(1), shape)
    x = jax.random.normal(jax.random.key(2), shape)
    full = model.graph.apply(params, x)
    stages = partition(model.graph, model.default_cuts(3))
    y = x
    for st in stages:
        y = st.apply(stage_params(params, st), y)
    assert jnp.allclose(full, y, atol=1e-5)


def test_vgg19_output_shape():
    model = get_model("vgg19")
    # VGG's flatten->dense head fixes the input resolution at 224.
    params = model.graph.init(jax.random.key(0), (1, 224, 224, 3))
    spec = model.graph.output_spec(params, (1, 224, 224, 3))
    assert spec.shape == (1, 1000)
