"""Model zoo: build, shape-check, and validate default cut points."""

import jax
import pytest

from defer_tpu.graph.partition import validate_cut_points
from defer_tpu.models import get_model, model_names


def test_model_registry_lists_models():
    names = model_names()
    assert "resnet50" in names
    assert "vgg19" in names


@pytest.mark.parametrize("name", ["resnet50", "vgg16", "vgg19"])
def test_cnn_builds_and_has_valid_cuts(name):
    model = get_model(name)
    assert model.input_shape == (224, 224, 3)
    for n in (2, 4, 8):
        cuts = model.default_cuts(n)
        assert len(cuts) == n - 1
        validate_cut_points(model.graph, cuts)


def test_resnet50_output_shape():
    model = get_model("resnet50")
    params = model.graph.init(jax.random.key(0), (2, 64, 64, 3))
    spec = model.graph.output_spec(params, (2, 64, 64, 3))
    assert spec.shape == (2, 1000)


def test_default_cuts_exact_count_at_limit():
    """num_stages == len(candidates)+1 must not silently collapse cuts."""
    model = get_model("resnet50")
    cuts = model.default_cuts(17)
    assert len(cuts) == 16 and len(set(cuts)) == 16
    with pytest.raises(ValueError, match="cannot make 18"):
        model.default_cuts(18)


def test_resnet50_has_16_adds():
    model = get_model("resnet50")
    assert model.cut_candidates == tuple(f"add_{i}" for i in range(1, 17))


def test_vgg19_output_shape():
    model = get_model("vgg19")
    # VGG's flatten->dense head fixes the input resolution at 224.
    params = model.graph.init(jax.random.key(0), (1, 224, 224, 3))
    spec = model.graph.output_spec(params, (1, 224, 224, 3))
    assert spec.shape == (1, 1000)
