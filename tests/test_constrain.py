"""On-device constrained decoding (defer_tpu/constrain/, ISSUE 17).

Three contracts. (1) COMPILER: `compile_regex` lowers a regex against
the token-string vocabulary into a dead-end-free TokenDFA (token
lift: a multi-char token is admissible iff the char DFA accepts its
whole spelling from the current state), and `schema_to_regex` lowers
the JSON-schema subset into a pattern that is simultaneously valid
for dfa.py and Python `re` — so every constrained output below is
re-validated with `re.fullmatch` (and `json.loads` for schemas).
(2) PARITY: constrained greedy output is TOKEN-IDENTICAL across
decode_window {1, 8} x spec_k {0, 4} x attention {gathered,
blockwise} x tensor parallelism, with free riders in the same batch
bit-identical to an unconstrained server. (3) FAILURE: a hand-built
DFA that dead-ends surfaces as a clean per-request error (the forced
eos never enters the output), never a hang; `constraints=None`
serving is bit-identical and retrace-free — the subsystem costs
nothing when off."""

import json
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.analysis import trace_sanitizer as sanitize
from defer_tpu.constrain import (
    ConstraintError,
    TokenDFA,
    compile_json_schema,
    compile_regex,
    schema_to_regex,
)
from defer_tpu.models.gpt import SamplingParams, tiny_gpt
from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.runtime.decode_server import DecodeServer, serve_greedy
from defer_tpu.runtime.paged import PagedDecodeServer, serve_paged

# Synthetic 128-string vocabulary for tiny_gpt (vocab_size 128):
# id 0 is the empty string and doubles as eos, then single chars,
# a few multi-char tokens (the token-lift cases), then filler.
_CHARS = list("0123456789abcdefghijklmnopqrstuvwxyz{}[]\",:.- eE+")
VOCAB = [""] + _CHARS + ["ab", "12", '":', "},"]
VOCAB += [f"<u{i}>" for i in range(128 - len(VOCAB))]

DIGITS = "[0-9]+"
SCHEMA = {"type": "object", "properties": {"ok": {"type": "boolean"}}}
EOS = 0


def detok(ids):
    """ids -> text; id 0 ("") contributes nothing, so trailing eos
    and padding vanish without special-casing."""
    return "".join(VOCAB[int(t)] for t in np.asarray(ids).ravel())


def tid(s):
    return VOCAB.index(s)


@pytest.fixture(scope="module")
def model():
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    return dec, params


@pytest.fixture(scope="module")
def cons():
    return {
        "digits": compile_regex(DIGITS, VOCAB),
        "obj": compile_json_schema(SCHEMA, VOCAB),
    }


def _trap():
    """Hand-built 2-state DFA: state 0 admits exactly one token into
    a non-accepting trap that admits nothing — the dead-end case
    compiled DFAs can never produce (prune_dead_states)."""
    tr = np.full((2, 128), -1, np.int32)
    tr[0, 5] = 1
    return TokenDFA(
        transitions=tr,
        accepting=np.array([False, False]),
        pattern="<trap>",
    )


def _requests():
    rng = np.random.default_rng(11)
    mk = lambda n: jnp.asarray(
        rng.integers(1, 128, size=(1, n)), jnp.int32
    )
    return [(mk(3), 8), (mk(4), 16), (mk(2), 8)]


# -- compiler ----------------------------------------------------------


def test_compile_regex_walk_and_admissible():
    dfa = compile_regex(DIGITS, VOCAB)
    assert dfa.vocab_size == 128
    s = dfa.walk([tid("1"), tid("2")])
    assert s >= 0 and dfa.accepting[s]
    assert dfa.walk([tid("a")]) == -1
    # Start state admits exactly the ten digits plus the "12" lift.
    adm = set(np.flatnonzero(dfa.admissible(dfa.start)).tolist())
    assert adm == {tid(c) for c in "0123456789"} | {tid("12")}


def test_token_lift_multichar_spelling():
    # "[0-9]" (exactly one digit) must NOT admit the 2-char "12"
    # token; "12+" must admit it from start (spelling "1","2").
    one = compile_regex("[0-9]", VOCAB)
    assert not one.admissible(one.start)[tid("12")]
    rep = compile_regex("12+", VOCAB)
    assert rep.admissible(rep.start)[tid("12")]
    s = rep.step(rep.start, tid("12"))
    assert s >= 0 and rep.accepting[s]


def test_compiled_dfas_are_dead_end_free():
    for pat in (DIGITS, "(ab|a)c*", schema_to_regex(SCHEMA)):
        dfa = compile_regex(pat, VOCAB)
        for s in range(dfa.num_states):
            assert dfa.accepting[s] or dfa.admissible(s).any(), (
                pat, s,
            )


def test_unsatisfiable_pattern_raises():
    with pytest.raises(ConstraintError, match="unsatisfiable"):
        compile_regex("[0-9]#", VOCAB)  # '#' not in any token


def test_schema_regex_is_re_compatible_and_json_valid():
    pat = schema_to_regex(SCHEMA)
    for text in ('{"ok":true}', '{"ok":false}'):
        assert re.fullmatch(pat, text)
        assert json.loads(text) in ({"ok": True}, {"ok": False})
    assert not re.fullmatch(pat, '{"ok":1}')
    enum = schema_to_regex({"enum": ["a", "b"]})
    assert re.fullmatch(enum, '"a"') and not re.fullmatch(enum, '"c"')
    arr = schema_to_regex(
        {"type": "array", "items": {"type": "integer"},
         "minItems": 1, "maxItems": 2}
    )
    assert re.fullmatch(arr, "[1,23]") and not re.fullmatch(arr, "[]")
    with pytest.raises(ConstraintError, match="unsupported"):
        schema_to_regex({"type": "tuple"})


# -- submit-time validation --------------------------------------------


def test_constraints_require_eos(model, cons):
    dec, params = model
    with pytest.raises(ValueError, match="eos_id"):
        PagedDecodeServer(
            dec, params, num_blocks=12, block_size=4, max_batch=2,
            constraints=cons,
        )
    with pytest.raises(ValueError, match="eos_id"):
        DecodeServer(dec, params, max_batch=2, constraints=cons)


def test_unknown_and_unregistered_constraint_rejected(model, cons):
    dec, params = model
    p = jnp.asarray([[3, 9]], jnp.int32)
    srv = PagedDecodeServer(
        dec, params, num_blocks=12, block_size=4, max_batch=2,
        eos_id=EOS, constraints=cons,
    )
    with pytest.raises(ValueError, match="unknown constraint"):
        srv.submit(p, 4, sampling=SamplingParams(constraint="nope"))
    bare = PagedDecodeServer(
        dec, params, num_blocks=12, block_size=4, max_batch=2,
        eos_id=EOS,
    )
    with pytest.raises(ValueError, match="without constraints"):
        bare.submit(p, 4, sampling=SamplingParams(constraint="digits"))


def test_dead_start_state_rejected_at_submit(model):
    dec, params = model
    tr = np.full((1, 128), -1, np.int32)
    stuck = TokenDFA(
        transitions=tr, accepting=np.array([False]), pattern="<stuck>"
    )
    srv = PagedDecodeServer(
        dec, params, num_blocks=12, block_size=4, max_batch=2,
        eos_id=EOS, constraints={"stuck": stuck},
    )
    with pytest.raises(ValueError, match="no first token"):
        srv.submit(
            jnp.asarray([[3]], jnp.int32), 4,
            sampling=SamplingParams(constraint="stuck"),
        )


# -- parity matrix ------------------------------------------------------


def _serve(model, cons, *, window=1, spec=0, attention="gathered",
           mesh=None):
    dec, params = model
    reqs = _requests()
    kw = dict(
        num_blocks=24, block_size=4, max_batch=4, eos_id=EOS,
        decode_window=window, attention=attention,
        constraints=cons,
        sampling=[
            SamplingParams(constraint="digits"),
            SamplingParams(constraint="obj"),
            None,  # free rider in the same batch
        ],
    )
    if spec:
        kw.update(spec_draft=dec, spec_params=params, spec_k=spec)
    if mesh is not None:
        kw.update(mesh=mesh)
    return serve_paged(dec, params, list(reqs), **kw), reqs


def _validate(outs, reqs):
    dig = detok(outs[0][0, reqs[0][0].shape[1]:])
    assert re.fullmatch(DIGITS, dig), dig
    obj = detok(outs[1][0, reqs[1][0].shape[1]:])
    assert re.fullmatch(schema_to_regex(SCHEMA), obj), obj
    assert json.loads(obj) in ({"ok": True}, {"ok": False})


@pytest.fixture(scope="module")
def cref(model, cons):
    """Reference: window 1, spec 0, gathered, no mesh — validated
    once; every matrix point must reproduce it token for token."""
    (outs, stats), reqs = _serve(model, cons)
    outs = [np.asarray(o) for o in outs]
    _validate(outs, reqs)
    assert stats["constrained_tokens"] > 0
    assert stats["constraint_dead_ends"] == 0
    return outs


@pytest.mark.parametrize("attention", ["gathered", "blockwise"])
@pytest.mark.parametrize("spec", [0, 4])
@pytest.mark.parametrize("window", [1, 8])
def test_constrained_token_identical_matrix(
    model, cons, cref, window, spec, attention
):
    if (window, spec, attention) == (1, 0, "gathered"):
        pytest.skip("the reference point itself")
    (outs, stats), reqs = _serve(
        model, cons, window=window, spec=spec, attention=attention
    )
    _validate([np.asarray(o) for o in outs], reqs)
    for got, want in zip(outs, cref):
        np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["constrained_tokens"] > 0


@pytest.mark.parametrize("spec", [0, 4])
def test_constrained_token_identical_tp2(model, cons, cref, spec):
    mesh = make_mesh({"model": 2}, jax.devices()[:2])
    (outs, stats), reqs = _serve(
        model, cons, window=8, spec=spec, mesh=mesh
    )
    _validate([np.asarray(o) for o in outs], reqs)
    for got, want in zip(outs, cref):
        np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["mesh_shape"] == "model=2"


def test_flat_server_matches_paged(model, cons, cref):
    """The flat DecodeServer runs the same DFA runtime over its dense
    cache: same tokens as the paged reference, window 1 and 8."""
    dec, params = model
    reqs = _requests()
    for window in (1, 8):
        outs, stats = serve_greedy(
            dec, params, list(reqs), max_batch=4, eos_id=EOS,
            decode_window=window, constraints=cons,
            sampling=[
                SamplingParams(constraint="digits"),
                SamplingParams(constraint="obj"),
                None,
            ],
        )
        for got, want in zip(outs, cref):
            np.testing.assert_array_equal(np.asarray(got), want)
        assert stats["constrained_tokens"] > 0


def test_constrained_sampling_stays_in_grammar(model, cons):
    """Temperature > 0 composes with the mask: every sampled token is
    grammar-admissible (the draw sees folded logits), across plain,
    windowed and speculative serving."""
    dec, params = model
    p = jnp.asarray([[7, 21]], jnp.int32)
    for kw in (
        {},
        {"decode_window": 8},
        {"spec_draft": dec, "spec_params": params, "spec_k": 3},
    ):
        outs, _ = serve_paged(
            dec, params, [(p, 10)], num_blocks=16, block_size=4,
            max_batch=2, eos_id=EOS, constraints=cons,
            sampling=[
                SamplingParams(temperature=0.9, seed=3,
                               constraint="digits")
            ],
            **kw,
        )
        text = detok(np.asarray(outs[0])[0, 2:])
        assert re.fullmatch(DIGITS, text), (kw, text)


# -- dead ends and mid-window eos --------------------------------------


@pytest.mark.parametrize("spec", [0, 4])
@pytest.mark.parametrize("window", [1, 8])
def test_dead_end_is_clean_error_not_hang(model, window, spec):
    """A hand-built trap DFA: one admissible token, then a state that
    admits nothing and does not accept. The request finishes with a
    per-request error, output ends at the last admissible token (the
    device-forced eos is dropped), and the free rider in the same
    batch is untouched."""
    dec, params = model
    kw = dict(
        num_blocks=24, block_size=4, max_batch=2, eos_id=EOS,
        decode_window=window, constraints={"trap": _trap()},
    )
    if spec:
        kw.update(spec_draft=dec, spec_params=params, spec_k=spec)
    srv = PagedDecodeServer(dec, params, **kw)
    p = jnp.asarray([[3, 9, 27]], jnp.int32)
    free_p = jnp.asarray([[5]], jnp.int32)
    r1 = srv.submit(p, 8, sampling=SamplingParams(constraint="trap"))
    r2 = srv.submit(free_p, 6)
    done = srv.run()
    out = np.asarray(done[r1])[0]
    assert list(out[3:]) == [5], out
    assert "dead end" in srv.errors[r1]
    assert srv.constraint_dead_ends_n == 1
    np.testing.assert_array_equal(
        np.asarray(done[r2]),
        np.asarray(dec.generate(params, free_p, 6)),
    )


def test_mid_window_satisfied_constraint_stops_at_eos(model, cons):
    """A satisfied schema emits eos (admitted only in accepting
    states) mid-window: generation must stop there, well short of the
    step budget, and the tail must not leak."""
    dec, params = model
    p = jnp.asarray([[7, 21]], jnp.int32)
    outs, _ = serve_paged(
        dec, params, [(p, 40)], num_blocks=24, block_size=4,
        max_batch=2, eos_id=EOS, decode_window=8, constraints=cons,
        sampling=[SamplingParams(constraint="obj")],
    )
    out = np.asarray(outs[0])[0]
    text = detok(out[2:])
    assert json.loads(text) in ({"ok": True}, {"ok": False})
    # eos fired mid-window: well under the 40-step budget.
    assert out.shape[0] - 2 < 20


# -- release / re-admission (satellite: full policy-row reset) ---------


def test_slot_release_resets_all_policy_rows(model, cons):
    """A slot that served a constrained request, then a heavily
    filtered sampled request, must serve a plain greedy request
    EXACTLY like a fresh server — release() clears constraint rows
    AND every filter row (temp/topk/topp/minp), so nothing leaks
    into the re-admitted stream."""
    dec, params = model
    p3 = jnp.asarray([[4, 8, 15]], jnp.int32)
    srv = PagedDecodeServer(
        dec, params, num_blocks=16, block_size=4, max_batch=1,
        eos_id=EOS, constraints=cons,
    )
    srv.submit(
        jnp.asarray([[3, 9]], jnp.int32), 5,
        sampling=SamplingParams(constraint="digits"),
    )
    srv.run()
    srv.submit(
        jnp.asarray([[6]], jnp.int32), 5,
        sampling=SamplingParams(
            temperature=0.8, top_k=5, top_p=0.6, min_p=0.2, seed=9
        ),
    )
    srv.run()
    r3 = srv.submit(p3, 6)
    got = np.asarray(srv.run()[r3])
    fresh = PagedDecodeServer(
        dec, params, num_blocks=16, block_size=4, max_batch=1,
        eos_id=EOS,
    )
    rf = fresh.submit(p3, 6)
    np.testing.assert_array_equal(got, np.asarray(fresh.run()[rf]))


# -- constraints=None costs nothing ------------------------------------


def test_constraints_off_bit_identical_and_trace_stable(model, cons):
    """Satellite contract: with no constrained row live, the server
    dispatches the PRE-CONSTRAINT programs — outputs bit-identical
    between constraints=None and constraints-registered-but-unused
    servers, and a warmed tick loop lowers nothing new (zero
    post-warmup retraces)."""
    dec, params = model
    reqs = [
        (jnp.asarray([[3, 9, 27]], jnp.int32), 10),
        (jnp.asarray([[5, 1]], jnp.int32), 9),
    ]
    outs = []
    for constraints in (None, cons):
        srv = PagedDecodeServer(
            dec, params, num_blocks=12, block_size=4, max_batch=2,
            eos_id=EOS, constraints=constraints,
        )
        rids = [srv.submit(p, s) for p, s in reqs]
        srv._admit()
        for _ in range(2):  # warmup: first ticks compile the step
            srv._tick()
        with sanitize(srv, dec) as rep:
            for _ in range(3):
                srv._tick()
        assert rep.retraces == 0
        done = srv.run()
        outs.append([np.asarray(done[r]) for r in rids])
    for a, b in zip(*outs):
        np.testing.assert_array_equal(a, b)


# -- obs ---------------------------------------------------------------


def test_constrain_metrics_surface(model, cons):
    from defer_tpu.obs import get_registry
    from defer_tpu.obs import reset as obs_reset

    obs_reset()
    (outs, stats), _ = _serve(model, cons)
    reg = get_registry()
    lab = {"server": "paged"}
    ct = reg.value("defer_constrained_tokens_total", **lab)
    assert ct == stats["constrained_tokens"] > 0
    frac = reg.value("defer_constrain_masked_frac", **lab)
    assert frac["count"] == ct  # one observation per constrained token
    assert reg.value("defer_constrain_dead_ends_total", **lab) == 0
    # The snapshot inside stats carries the same series.
    key = 'defer_constrained_tokens_total{server="paged"}'
    assert stats["metrics"]["counters"][key] == ct
