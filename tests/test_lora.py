"""LoRA adapters over the SPMD stack: identity at init, merge/unmerged
equivalence, frozen-base training, and tensor-parallel sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from defer_tpu.models.bert import SpmdBert
from defer_tpu.parallel.lora import (
    combine_lora,
    make_lora_train_step,
    merge_lora,
    split_lora,
)
from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.parallel.transformer_stack import TransformerConfig


def _cfg(**kw):
    base = dict(
        num_layers=2, dim=32, num_heads=4, ffn_dim=64, vocab_size=64,
        max_len=32,
    )
    base.update(kw)
    return TransformerConfig(**base)


def _randomize_b(params, rng, scale=0.3):
    """Give the zero-init b factors real values so adapters do work."""
    stack = dict(params["stack"])
    for i, k in enumerate(sorted(stack)):
        if k.endswith(":b"):
            stack[k] = (
                jax.random.normal(
                    jax.random.fold_in(rng, i), stack[k].shape
                )
                * scale
            )
    return {**params, "stack": stack}


def test_config_validates_targets():
    with pytest.raises(ValueError, match="not adaptable"):
        _cfg(lora_rank=4, lora_targets=("wq", "w3"))  # w3 is swiglu-only
    with pytest.raises(ValueError, match="not adaptable"):
        _cfg(lora_rank=4, lora_targets=("w1",), num_experts=2)
    with pytest.raises(ValueError, match="empty"):
        _cfg(lora_rank=4, lora_targets=())
    cfg = _cfg(lora_rank=4, lora_alpha=8.0)
    assert cfg.lora_scale == 2.0
    assert _cfg().lora_scale == 0.0


def test_fresh_adapter_is_identity(devices):
    """b = 0 at init: a lora-enabled stack computes exactly what the
    base stack computes from the same rng."""
    mesh = make_mesh({"stage": 1}, devices[:1])
    cfg_l = _cfg(lora_rank=4, lora_targets=("wq", "wv", "w1", "w2"))
    sb_l = SpmdBert(mesh, cfg_l, compute_dtype=jnp.float32)
    sb_0 = SpmdBert(mesh, _cfg(), compute_dtype=jnp.float32)
    p_l = sb_l.init(jax.random.key(0))
    p_0 = sb_0.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 2, 16), 0, 64)
    out_l = sb_l.make_step()(p_l, ids)
    out_0 = sb_0.make_step()(p_0, ids)
    np.testing.assert_allclose(
        np.asarray(out_l), np.asarray(out_0), rtol=1e-5, atol=1e-5
    )


def test_merge_matches_unmerged(devices):
    """Folding w + scale * a @ b into the base weights reproduces the
    unmerged adapter forward, and drops every factor key."""
    mesh = make_mesh({"stage": 1}, devices[:1])
    cfg = _cfg(
        lora_rank=4,
        lora_alpha=8.0,
        lora_targets=("wq", "wk", "wv", "wo", "w1", "w2"),
    )
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    params = _randomize_b(sb.init(jax.random.key(0)), jax.random.key(2))
    ids = jax.random.randint(jax.random.key(1), (1, 2, 16), 0, 64)
    want = sb.make_step()(params, ids)

    merged = merge_lora(params, cfg)
    assert not any(":" in k for k in merged["stack"])
    sb_0 = SpmdBert(mesh, _cfg(), compute_dtype=jnp.float32)
    got = sb_0.make_step()(merged, ids)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
    )


def test_split_combine_roundtrip(devices):
    mesh = make_mesh({"stage": 1}, devices[:1])
    cfg = _cfg(lora_rank=2)
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    params = sb.init(jax.random.key(0))
    base, lora = split_lora(params)
    assert set(lora["stack"]) == {"wq:a", "wq:b", "wv:a", "wv:b"}
    assert not any(":" in k for k in base["stack"])
    back = combine_lora(base, lora)
    assert set(back["stack"]) == set(params["stack"])


def test_lora_train_freezes_base(devices):
    """The LoRA step trains only adapters + head: loss drops, base
    weights are untouched, and the optimizer state is adapter-sized."""
    mesh = make_mesh({"stage": 2, "data": 2}, devices[:4])
    cfg = _cfg(lora_rank=4, lora_targets=("wq", "wv", "w1", "w2"))
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    init_state, step = make_lora_train_step(
        sb, optax.adam(5e-2), num_classes=4
    )
    state, base = init_state(jax.random.key(0))
    base_before = jax.tree_util.tree_map(lambda x: np.asarray(x), base)

    # Optimizer state covers only the trainable leaves.
    n_trainable = len(jax.tree_util.tree_leaves(state.params))
    n_opt = len(jax.tree_util.tree_leaves(state.opt_state[0].mu))
    assert n_opt == n_trainable

    ids = jax.random.randint(jax.random.key(1), (3, 4, 16), 0, 64)
    labels = jax.random.randint(jax.random.key(2), (3, 4), 0, 4)
    losses = []
    for _ in range(8):
        state, loss = step(state, base, ids, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        base,
        base_before,
    )
    assert int(state.step) == 8


def test_lora_tp_matches_single_device(devices):
    """Adapter factors shard with their base weights: a tp=2 pipeline
    forward equals the unsharded forward with the same params."""
    cfg = _cfg(
        lora_rank=4,
        lora_targets=("wq", "wo", "w1", "w2"),
        lora_alpha=4.0,
    )
    mesh_1 = make_mesh({"stage": 1}, devices[:1])
    sb_1 = SpmdBert(mesh_1, cfg, compute_dtype=jnp.float32)
    params = _randomize_b(sb_1.init(jax.random.key(0)), jax.random.key(2))
    ids = jax.random.randint(jax.random.key(1), (1, 2, 16), 0, 64)
    want = sb_1.make_step()(params, ids)

    mesh_tp = make_mesh({"stage": 2, "model": 2}, devices[:4])
    sb_tp = SpmdBert(mesh_tp, cfg, compute_dtype=jnp.float32)
    host = jax.tree_util.tree_map(np.asarray, params)
    # Re-place the single-device tree onto the tp mesh shardings by
    # initializing for structure and device_put-ing the numbers.
    template = sb_tp.init(jax.random.key(0))
    # The stage-1 tree stacks layers as [1, L, ...]; the stage-2
    # template as [2, L/2, ...] — same layer order, so a reshape
    # re-stacks losslessly.
    placed = jax.tree_util.tree_map(
        lambda t, v: jax.device_put(
            jnp.asarray(v).reshape(t.shape), t.sharding
        ),
        template,
        host,
    )
    got = sb_tp.make_step()(placed, ids)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
    )


def test_adapter_checkpoint_round_trip(tmp_path, devices):
    """The adapter-only tree (the thing a fine-tune ships) checkpoints
    and restores bit-exact through the standard machinery — keys with
    the ':a'/':b' suffixes included — and recombines with a fresh base
    to the same forward."""
    from defer_tpu.runtime.checkpoint import load_checkpoint, save_checkpoint

    mesh = make_mesh({"stage": 1}, devices[:1])
    cfg = _cfg(lora_rank=4, lora_targets=("wq", "wv", "w1", "w2"))
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    params = _randomize_b(sb.init(jax.random.key(0)), jax.random.key(2))
    base, lora = split_lora(params)
    path = str(tmp_path / "adapters.ckpt")
    save_checkpoint(path, lora)
    restored = load_checkpoint(path)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        lora,
        restored,
    )
    ids = jax.random.randint(jax.random.key(1), (1, 2, 16), 0, 64)
    want = sb.make_step()(params, ids)
    got = sb.make_step()(combine_lora(base, restored), ids)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6
    )


def test_decoder_rejects_unmerged_lora():
    from defer_tpu.models.gpt import GptDecoder

    cfg = _cfg(norm_style="pre", causal=True, lora_rank=2)
    with pytest.raises(ValueError, match="merge"):
        GptDecoder(cfg)
