"""Keras to_json ingester: fixture parsing, forward equivalence with a
hand-built IR graph, auto cut discovery, and error paths."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.graph.keras_import import (
    KerasImportError,
    from_keras_json,
    model_from_keras,
)
from defer_tpu.graph.partition import articulation_points, validate_cut_points
from defer_tpu.models import get_model


def _layer(cls, name, inbound, **config):
    config.setdefault("name", name)
    return {
        "class_name": cls,
        "name": name,
        "config": config,
        "inbound_nodes": [[[src, 0, 0, {}] for src in inbound]] if inbound else [],
    }


def _residual_json():
    """A small residual CNN in classic functional-model JSON."""
    layers = [
        _layer("InputLayer", "input_1", [], batch_input_shape=[None, 16, 16, 3]),
        _layer("ZeroPadding2D", "pad", ["input_1"], padding=[[1, 1], [1, 1]]),
        _layer(
            "Conv2D", "conv1", ["pad"], filters=8, kernel_size=[3, 3],
            strides=[1, 1], padding="valid", use_bias=False,
            activation="linear",
        ),
        _layer("BatchNormalization", "bn1", ["conv1"], axis=3, epsilon=1.1e-5),
        _layer("Activation", "act1", ["bn1"], activation="relu"),
        _layer(
            "Conv2D", "conv2", ["act1"], filters=8, kernel_size=[3, 3],
            padding="same", use_bias=True, activation="relu",
        ),
        _layer("Add", "add_1", ["conv2", "act1"]),
        _layer("MaxPooling2D", "pool", ["add_1"], pool_size=[2, 2], strides=[2, 2], padding="valid"),
        _layer("GlobalAveragePooling2D", "gap", ["pool"]),
        _layer("Dropout", "drop", ["gap"], rate=0.5),
        _layer("Dense", "fc", ["drop"], units=10, activation="softmax"),
    ]
    return json.dumps(
        {
            "class_name": "Functional",
            "config": {
                "name": "toy_resnet",
                "layers": layers,
                "input_layers": [["input_1", 0, 0]],
                "output_layers": [["fc", 0, 0]],
            },
        }
    )


def test_ingest_matches_hand_built_graph():
    graph, input_shape = from_keras_json(_residual_json())
    assert input_shape == (16, 16, 3)

    b = GraphBuilder("manual")
    x = b.input("input_1")
    x = b.add("zero_pad", x, name="pad", padding=((1, 1), (1, 1)))
    x = b.add("conv", x, name="conv1", features=8, kernel_size=(3, 3),
              strides=(1, 1), padding="VALID", use_bias=False)
    x = b.add("batch_norm", x, name="bn1", eps=1.1e-5)
    x = b.add("relu", x, name="act1")
    y = b.add("conv", x, name="conv2", features=8, kernel_size=(3, 3),
              padding="SAME", use_bias=True)
    y = b.add("relu", y, name="conv2_activation_fused")
    x = b.add("add", y, x, name="add_1")
    x = b.add("max_pool", x, name="pool", window=(2, 2), strides=(2, 2),
              padding="VALID")
    x = b.add("global_avg_pool", x, name="gap")
    x = b.add("dropout", x, name="drop")
    x = b.add("dense", x, name="fc", features=10)
    x = b.add("softmax", x, name="fc_activation_fused")
    manual = b.build(x)

    shape = (2, 16, 16, 3)
    p1 = graph.init(jax.random.key(0), shape)
    p2 = manual.init(jax.random.key(0), shape)
    xin = jax.random.normal(jax.random.key(1), shape)
    np.testing.assert_allclose(
        np.asarray(graph.apply(p1, xin)),
        np.asarray(manual.apply(p2, xin)),
        rtol=1e-6,
    )


def test_imported_model_partitions_and_runs():
    model, params = model_from_keras(_residual_json())
    assert params is None
    assert "add_1" in model.cut_candidates
    # Nodes inside the residual branch must NOT be candidates.
    assert "conv2" not in model.cut_candidates
    cuts = ["add_1"]
    validate_cut_points(model.graph, cuts)
    from defer_tpu.graph.partition import partition, stage_params

    params = model.init(jax.random.key(0))
    x = jnp.ones((1, 16, 16, 3))
    full = model.graph.apply(params, x)
    y = x
    for st in partition(model.graph, cuts):
        y = st.apply(stage_params(params, st), y)
    np.testing.assert_allclose(np.asarray(full), np.asarray(y), rtol=1e-6)


def test_articulation_points_superset_of_resnet_adds():
    model = get_model("resnet50")
    pts = set(articulation_points(model.graph))
    assert set(model.cut_candidates) <= pts
    assert "res2a_b_relu" not in pts  # inside a residual branch


def test_articulation_points_match_naive_definition():
    """The O(V+E) sweep must agree with the ancestors-based definition
    node for node."""
    for make in (
        lambda: get_model("mobilenetv2").graph,
        lambda: from_keras_json(_residual_json())[0],
    ):
        graph = make()
        fast = set(articulation_points(graph))
        edges = [(i, n.name) for n in graph.nodes for i in n.inputs]
        live = graph.ancestors(graph.output_name)
        naive = set()
        for node in graph.nodes:
            if node.name in (graph.input_name, graph.output_name):
                continue
            # Candidates are restricted to ancestors of the output —
            # partition() cannot chain stages through a dead node.
            if node.name not in live:
                continue
            anc = graph.ancestors(node.name)
            if all(
                u == node.name or u not in anc or v in anc for u, v in edges
            ):
                naive.add(node.name)
        assert fast == naive


def test_channels_first_rejected():
    bad = json.loads(_residual_json())
    bad["config"]["layers"][2]["config"]["data_format"] = "channels_first"
    with pytest.raises(KerasImportError, match="channels_first"):
        from_keras_json(bad)


def test_variable_input_dims_rejected():
    bad = json.loads(_residual_json())
    bad["config"]["layers"][0]["config"]["batch_input_shape"] = [
        None, None, None, 3,
    ]
    with pytest.raises(KerasImportError, match="static shapes"):
        from_keras_json(bad)


def test_unsupported_layer_raises():
    bad = json.loads(_residual_json())
    bad["config"]["layers"][2]["class_name"] = "LocallyConnected2D"
    with pytest.raises(KerasImportError, match="LocallyConnected2D"):
        from_keras_json(bad)


def test_multi_output_rejected():
    spec = json.loads(_residual_json())
    spec["config"]["output_layers"].append(["gap", 0, 0])
    with pytest.raises(KerasImportError, match="single-input single-output"):
        from_keras_json(spec)


def test_sequential_well_formed_converts_and_runs():
    """A valid Sequential JSON (no explicit InputLayer — first layer
    carries batch_input_shape) converts to a runnable graph."""
    spec = {
        "class_name": "Sequential",
        "config": {
            "name": "seq_mlp",
            "layers": [
                {
                    "class_name": "Dense",
                    "config": {
                        "name": "d1",
                        "units": 8,
                        "activation": "relu",
                        "batch_input_shape": [None, 4],
                    },
                },
                {
                    "class_name": "Dense",
                    "config": {"name": "d2", "units": 3,
                               "activation": "softmax"},
                },
            ],
        },
    }
    graph, input_shape = from_keras_json(json.dumps(spec))
    assert input_shape == (4,)
    params = graph.init(jax.random.key(0), (2, 4))
    out = graph.apply(params, jnp.ones((2, 4)))
    assert out.shape == (2, 3)
    np.testing.assert_allclose(np.asarray(out.sum(-1)), 1.0, rtol=1e-5)


def test_sequential_malformed_config_rejected_with_clear_error():
    """Sequential is supported, but a config without a layers list must
    surface as KerasImportError, not a bare KeyError (reference would
    crash deep inside keras deserialization instead)."""
    with pytest.raises(KerasImportError, match="layers"):
        from_keras_json(json.dumps({"class_name": "Sequential", "config": {}}))
    with pytest.raises(KerasImportError, match="layers"):
        from_keras_json(json.dumps({"class_name": "Sequential"}))
    with pytest.raises(KerasImportError, match="malformed"):
        from_keras_json(
            json.dumps({"class_name": "Sequential", "config": {"layers": [42]}})
        )
    with pytest.raises(KerasImportError, match="config"):
        from_keras_json(
            json.dumps(
                {
                    "class_name": "Sequential",
                    "config": {"layers": [{"class_name": "Dense"}]},
                }
            )
        )


def test_h5_weights_path(tmp_path):
    """JSON + h5 weights -> running model with transplanted params."""
    from conftest import write_keras_h5

    from defer_tpu.models.transplant import export_keras_weights

    model, _ = model_from_keras(_residual_json())
    params = model.init(jax.random.key(3))
    kw = export_keras_weights(model.graph, params)
    path = str(tmp_path / "w.h5")
    write_keras_h5(path, kw)

    model2, loaded = model_from_keras(_residual_json(), weights_h5=path)
    x = jnp.ones((1, 16, 16, 3))
    np.testing.assert_array_equal(
        np.asarray(model.graph.apply(params, x)),
        np.asarray(model2.graph.apply(loaded, x)),
    )
