"""The bench supervisor: a hang in any measurement section must cost a
bounded wait, not the round's headline artifact.

Round-2 history: the driver's bench once timed out with NO JSON line
because one (new, optional) section wedged the device transport — a
failure class that can't be caught in-process since a hung XLA/Mosaic
compile never returns to Python. bench.py therefore runs measurement in
a killable child that snapshots its result-so-far after every section;
these tests drive the supervisor with fake children.
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys
import textwrap
import time

_BENCH_PATH = os.path.join(os.path.dirname(__file__), "..", "bench.py")
_spec = importlib.util.spec_from_file_location("defer_bench", _BENCH_PATH)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _child(tmp_path, body: str) -> list[str]:
    """Write a fake measurement child; it sees the supervisor's env
    (DEFER_BENCH_SNAPSHOT et al) like the real one."""
    path = tmp_path / "fake_child.py"
    path.write_text(
        textwrap.dedent(
            """
            import json, os, sys, time

            def snapshot(result):
                with open(os.environ["DEFER_BENCH_SNAPSHOT"], "a") as f:
                    f.write(json.dumps(result) + "\\n")
                    f.flush()
                    os.fsync(f.fileno())
            """
        )
        + textwrap.dedent(body)
    )
    return [sys.executable, str(path)]


def test_clean_child_result_passes_through(tmp_path, monkeypatch):
    monkeypatch.setenv("DEFER_BENCH_DEADLINE_S", "60")
    monkeypatch.setenv("DEFER_BENCH_STALL_S", "60")
    cmd = _child(
        tmp_path,
        """
        snapshot({"metric": "m", "value": 1.0})
        print(json.dumps({"metric": "m", "value": 2.0, "unit": "x"}))
        """,
    )
    result, err = bench.supervise(cmd)
    assert err is None
    assert result == {"metric": "m", "value": 2.0, "unit": "x"}


def test_hung_child_is_killed_and_snapshot_survives(tmp_path, monkeypatch):
    monkeypatch.setenv("DEFER_BENCH_DEADLINE_S", "60")
    monkeypatch.setenv("DEFER_BENCH_STALL_S", "3")
    cmd = _child(
        tmp_path,
        """
        snapshot({"metric": "m", "value": 13075.9, "unit": "images/sec"})
        time.sleep(600)   # a wedged section: never returns
        """,
    )
    result, err = bench.supervise(cmd)
    assert err is None
    assert result["value"] == 13075.9
    assert "truncated" in result  # the kill is recorded, not hidden


def test_hang_before_any_headline_reports_error(tmp_path, monkeypatch):
    # Before the first snapshot exists only the TOTAL deadline applies
    # (backend init + first compiles are legitimately slow); the stall
    # clock must not kill a child that hasn't had a chance to measure.
    monkeypatch.setenv("DEFER_BENCH_DEADLINE_S", "8")
    monkeypatch.setenv("DEFER_BENCH_STALL_S", "3")
    cmd = _child(tmp_path, "time.sleep(600)\n")
    t0 = time.monotonic()
    result, err = bench.supervise(cmd)
    assert time.monotonic() - t0 > 6  # stall_s alone must NOT fire
    assert result is None
    assert "total deadline" in err


def test_crashing_child_error_json_is_surfaced(tmp_path, monkeypatch):
    monkeypatch.setenv("DEFER_BENCH_DEADLINE_S", "60")
    monkeypatch.setenv("DEFER_BENCH_STALL_S", "60")
    cmd = _child(
        tmp_path,
        """
        print(json.dumps({"metric": "m", "value": None,
                          "error": "RuntimeError: no devices"}))
        sys.exit(1)
        """,
    )
    result, err = bench.supervise(cmd)
    assert result is None
    assert err == "RuntimeError: no devices"


def test_read_snapshot_skips_torn_tail(tmp_path):
    p = tmp_path / "snap.jsonl"
    p.write_text('{"value": 1}\n{"value": 2}\n{"val')  # torn final write
    assert bench.read_snapshot(str(p)) == {"value": 2}
    assert bench.read_snapshot(str(tmp_path / "missing.jsonl")) is None


def test_is_init_error_classification():
    """The TPU-reacquisition loop must retry on backend-init failures
    AND on tunneled-transport deaths (remote-compile endpoint refusing
    connections mid-run), but never on ordinary measurement bugs."""
    assert bench._is_init_error("BackendInitHang: devices() exceeded 180s")
    assert bench._is_init_error(
        "JaxRuntimeError: UNAVAILABLE: http://127.0.0.1:8083/"
        "remote_compile: Connection Failed: Connection refused (os error 111)"
    )
    assert bench._is_init_error(
        "RuntimeError: requested platform 'tpu' but got CPU devices"
    )
    assert not bench._is_init_error("ValueError: no batch size measured")
    assert not bench._is_init_error(None)
    assert not bench._is_init_error("")
