"""SPMD circular pipeline (shard_map + ppermute) on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from defer_tpu.models.bert import SpmdBert
from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.parallel.spmd_pipeline import (
    make_spmd_pipeline,
    stack_for_stages,
    staged_specs,
)
from defer_tpu.parallel.transformer_stack import TransformerConfig


def test_pipeline_equals_sequential(devices):
    """4-stage ppermute pipeline == applying the 4 stage fns in order."""
    mesh = make_mesh({"stage": 4}, devices[:4])
    # Each stage: x -> x * w + b with per-stage scalar params.
    params = {
        "w": jnp.arange(1.0, 5.0).reshape(4, 1),
        "b": jnp.arange(0.0, 4.0).reshape(4, 1),
    }

    def stage_fn(p, x):
        return x * p["w"] + p["b"]

    specs = {"w": P("stage"), "b": P("stage")}
    run = make_spmd_pipeline(mesh, stage_fn, specs, stage_axis="stage")
    xs = jnp.arange(6.0).reshape(6, 1, 1)  # [M=6, B=1, 1]
    ys = jax.jit(run)(params, xs)
    assert ys.shape == xs.shape

    want = xs
    for s in range(4):
        want = want * params["w"][s, 0] + params["b"][s, 0]
    np.testing.assert_allclose(np.asarray(ys), np.asarray(want), rtol=1e-6)


def test_pipeline_output_buffer_is_microbatch_sized(devices):
    """The pipeline's global output buffer must be [M, B, ...] — not the
    [S, M+S-1, B, ...] per-stage materialization (every stage's per-step
    emissions are masked and reduced away inside the shard_map)."""
    S, M, B, D = 4, 6, 2, 8
    mesh = make_mesh({"stage": S}, devices[:S])
    params = {"w": jnp.ones((S, D))}
    specs = {"w": P("stage")}

    def stage_fn(p, x):
        return x * p["w"]

    run = make_spmd_pipeline(mesh, stage_fn, specs, stage_axis="stage")
    out = jax.eval_shape(run, params, jnp.zeros((M, B, D)))
    # `run` IS the shard_map-ed function now — its output spec is the
    # global buffer; no host-side slicing of a larger array happens.
    assert out.shape == (M, B, D)


def _bert_check(mesh, devices, batch=4, num_mb=5):
    cfg = TransformerConfig(
        num_layers=4, dim=32, num_heads=4, ffn_dim=64, vocab_size=64,
        max_len=32,
    )
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    params = sb.init(jax.random.key(0))
    ids = jax.random.randint(
        jax.random.key(1), (num_mb, batch, 8), 0, cfg.vocab_size
    )
    step = sb.make_step()
    got = step(params, ids)
    want = sb.reference_apply(params, ids)
    assert got.shape == (num_mb, batch, cfg.dim)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6
    )


def test_spmd_bert_stage_only(devices):
    _bert_check(make_mesh({"stage": 4}, devices[:4]), devices)


def test_spmd_bert_dp_pp_tp(devices):
    """The full 3-axis composition: 2-way data x 2-stage pipeline x
    2-way tensor parallel on 8 devices."""
    _bert_check(
        make_mesh({"data": 2, "stage": 2, "model": 2}, devices), devices
    )


def test_spmd_bert_tp_only(devices):
    _bert_check(make_mesh({"stage": 1, "model": 4}, devices[:4]), devices)


def test_spmd_bert_sp_ring(devices):
    """Sequence parallelism: ring attention over a 4-way seq axis."""
    _bert_check(make_mesh({"stage": 1, "seq": 4}, devices[:4]), devices)


def test_spmd_bert_pp_tp_sp(devices):
    """pp x tp x sp composed: 2-stage pipeline, 2-way tensor parallel,
    2-way ring-attention sequence parallel on 8 devices."""
    _bert_check(
        make_mesh({"stage": 2, "model": 2, "seq": 2}, devices), devices
    )


def test_spmd_bert_sp_ulysses(devices):
    cfg = TransformerConfig(
        num_layers=2, dim=32, num_heads=4, ffn_dim=64, vocab_size=64,
        max_len=32,
    )
    mesh = make_mesh({"stage": 1, "seq": 2}, jax.devices()[:2])
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32, sp_strategy="ulysses")
    params = sb.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (3, 2, 8), 0, cfg.vocab_size)
    got = sb.make_step()(params, ids)
    want = sb.reference_apply(params, ids)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6
    )


def test_llama_stack_pipeline_equals_reference(devices):
    """A llama-configured SpmdBert (rope + rms + GQA + swiglu) on the
    dp x pp x tp mesh must equal its unpipelined reference — rope
    offsets, GQA grouping and the biasless spec set all have to agree
    across the shard_map boundary."""
    from defer_tpu.models.llama import llama_config

    mesh = make_mesh(
        {"data": 2, "stage": 2, "model": 2}, devices[:8]
    )
    cfg = llama_config(
        num_layers=4,
        dim=64,
        num_heads=4,
        num_kv_heads=2,
        ffn_dim=128,
        vocab_size=64,
        max_len=32,
    )
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    params = sb.init(jax.random.key(0))
    assert "pos_embedding" not in params  # rope: no learned table
    ids = jax.random.randint(jax.random.key(1), (4, 4, 16), 0, 64)
    got = sb.make_step()(params, ids)
    want = sb.reference_apply(params, ids)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
    )


def test_llama_stack_trains(devices):
    """One full jitted train step (loss + grads through the pipeline +
    optax update) on the llama-style stack."""
    import optax

    from defer_tpu.models.llama import llama_config
    from defer_tpu.parallel.train import make_train_step

    mesh = make_mesh({"stage": 2, "model": 2}, devices[:4])
    cfg = llama_config(
        num_layers=2,
        dim=64,
        num_heads=4,
        num_kv_heads=2,
        ffn_dim=128,
        vocab_size=64,
        max_len=32,
    )
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    init_state, train_step = make_train_step(
        sb, optax.adam(1e-3), num_classes=4
    )
    state = init_state(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (3, 2, 16), 0, 64)
    labels = jax.random.randint(jax.random.key(2), (3, 2), 0, 4)
    state, loss = train_step(state, ids, labels)
    assert jnp.isfinite(loss)
