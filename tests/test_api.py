"""DEFER facade: the reference's queue-driven contract
(reference src/test.py:44-50)."""

import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu import DEFER, DeferConfig, run_local_inference
from defer_tpu.models import get_model


def test_run_defer_queue_contract(devices):
    """Mirrors the reference driver: run_defer in a daemon thread, feed
    an input queue, drain an output queue (reference src/test.py:44-54)."""
    model = get_model("resnet50")
    params = model.graph.init(jax.random.key(0), (1, 32, 32, 3))
    x = jnp.ones((1, 32, 32, 3))
    want = model.graph.apply(params, x)

    defer = DEFER(config=DeferConfig(compute_dtype=jnp.float32))
    input_q: "queue.Queue" = queue.Queue(10)
    output_q: "queue.Queue" = queue.Queue(10)
    t = threading.Thread(
        target=defer.run_defer,
        args=(model, ["add_4", "add_8"], input_q, output_q),
        kwargs={"params": params},
        daemon=True,
    )
    t.start()
    n = 6
    for _ in range(n):
        input_q.put(x)
    input_q.put(None)  # end-of-stream sentinel
    outs = [output_q.get(timeout=120) for _ in range(n)]
    t.join(timeout=120)
    assert not t.is_alive()
    for out in outs:
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(want), rtol=1e-4, atol=1e-6
        )


def test_stop_unblocks_run_defer(devices):
    model = get_model("resnet50")
    params = model.graph.init(jax.random.key(0), (1, 32, 32, 3))
    defer = DEFER(config=DeferConfig(compute_dtype=jnp.float32))
    input_q: "queue.Queue" = queue.Queue()
    output_q: "queue.Queue" = queue.Queue()
    t = threading.Thread(
        target=defer.run_defer,
        args=(model, ["add_8"], input_q, output_q),
        kwargs={"params": params},
        daemon=True,
    )
    t.start()
    input_q.put(jnp.ones((1, 32, 32, 3)))
    output_q.get(timeout=120)
    defer.stop()
    t.join(timeout=30)
    assert not t.is_alive()


def test_run_local_inference_smoke():
    model = get_model("resnet50")
    params = model.graph.init(jax.random.key(0), (1, 32, 32, 3))
    # Tiny duration; we only care that it runs and reports sane numbers.
    res = run_local_inference(_Tiny(model), duration_s=0.5, params=params)
    assert res["count"] >= 1
    assert res["items_per_sec"] > 0


class _Tiny:
    """Wrap a model but shrink its example input for CPU test speed."""

    def __init__(self, model):
        self.graph = model.graph
        self._model = model

    def example_input(self, batch_size=1, dtype=None):
        return jnp.ones((batch_size, 32, 32, 3))

    def init(self, rng, **kw):
        return self._model.init(rng, **kw)


def test_stage_failure_surfaces_cleanly(devices):
    """Fault injection: a stage whose op raises must propagate an
    exception out of run_defer instead of hanging (the reference hangs
    forever on node death, reference src/node.py:102-103). Run in a
    thread with a deadline so a regression fails rather than hanging
    the suite."""
    from defer_tpu.graph.ir import GraphBuilder
    from defer_tpu.ops.registry import op_names, register_op

    if "explode" not in op_names():
        @register_op("explode")
        def explode_apply(params, inputs, attrs):
            # Stands in for any stage-side failure (bad op config,
            # shape bug, OOM).
            raise RuntimeError("injected stage failure")

    b = GraphBuilder("faulty")
    x = b.input()
    h = b.add("dense", x, name="s0", features=4)
    h = b.add("explode", h, name="boom")
    g = b.build(h)

    defer = DEFER(devices[:2])
    inq, outq = queue.Queue(), queue.Queue()
    inq.put(jnp.ones((2, 8)))
    errors = []

    def run():
        try:
            defer.run_defer(
                g, ["s0"], inq, outq,
                params={"input": {}, "boom": {},
                        "s0": {"kernel": jnp.ones((8, 4)),
                               "bias": jnp.zeros(4)}},
            )
        except Exception as e:  # noqa: BLE001 — the assertion target
            errors.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=120)
    assert not t.is_alive(), "run_defer hung on an injected stage failure"
    assert errors and "injected stage failure" in str(errors[0])


def test_stage_failure_redispatches_and_recovers(devices):
    """Elastic recovery: a transiently failing stage triggers a health
    probe + pipeline rebuild and the failed microbatch is retried —
    the reference hangs forever on any node death (reference
    src/node.py:102-103); fail-fast (redispatch_attempts=0) is the
    other mode, covered by test_stage_failure_surfaces_cleanly."""
    import numpy as np

    from defer_tpu.graph.ir import GraphBuilder
    from tests.conftest import FLAKY, register_flaky_op

    register_flaky_op()
    FLAKY["failures"] = 1  # first build fails, rebuild heals

    b = GraphBuilder("flaky_model")
    x = b.input()
    h = b.add("dense", x, name="s0", features=4)
    h = b.add("flaky", h, name="wobble")
    g = b.build(h)
    params = {
        "input": {}, "wobble": {},
        "s0": {"kernel": jnp.ones((8, 4)), "bias": jnp.zeros(4)},
    }

    defer = DEFER(devices[:2], config=DeferConfig(compute_dtype=jnp.float32))
    inq, outq = queue.Queue(), queue.Queue()
    xin = jnp.ones((2, 8))
    inq.put(xin)
    inq.put(xin)
    inq.put(None)

    t = threading.Thread(
        target=defer.run_defer, args=(g, ["s0"], inq, outq),
        kwargs={"params": params}, daemon=True,
    )
    t.start()
    outs = [outq.get(timeout=120), outq.get(timeout=120)]
    t.join(timeout=60)
    assert not t.is_alive()
    assert FLAKY["failures"] == 0
    want = np.asarray(g.apply(params, xin))
    for got in outs:
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_auto_cuts_builds_balanced_pipeline(devices):
    """partition_layers="auto": FLOPs-balanced boundaries, one stage per
    device — the cut list the reference makes the user find by hand
    (reference src/test.py:24-28)."""
    import numpy as np

    from defer_tpu.models import get_model

    model = get_model("mobilenetv2")
    defer = DEFER(devices[:4], config=DeferConfig(compute_dtype=jnp.float32))
    params = model.init(jax.random.key(0))
    pipe, example = defer.build_pipeline(model, "auto", params=params)
    assert pipe.num_stages == 4
    got = np.asarray(pipe.warmup(example))
    want = np.asarray(model.graph.apply(params, example))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
