"""Smoke-test the driver entry `dryrun_multichip` exactly the way the
driver invokes it: a fresh interpreter, a hard external timeout, and
only stdout to judge by. Guards against the default tier regressing
past the driver's budget (VERDICT r03: rc=124 three rounds running).
"""

import os
import subprocess
import sys
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_default_tier_under_driver_budget():
    env = dict(os.environ)
    env.pop("DEFER_DRYRUN_FULL", None)
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import sys; sys.path.insert(0, %r); "
            "import __graft_entry__ as g; g.dryrun_multichip(8)" % REPO,
        ],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=150,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    # Per-section progress lines must reach stdout (a driver timeout
    # still leaves evidence of how far the run got).
    for section in (
        "spmd",
        "train-dp-pp-tp",
        "hetero-pipeline",
        "data-parallel",
        "tp-decode",
        "bundle",
    ):
        assert f"[dryrun] {section} ok" in proc.stdout, proc.stdout
    assert "dryrun_multichip OK" in proc.stdout, proc.stdout
