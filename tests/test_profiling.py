"""Profiling seam: trace capture and annotations must work (and be
no-ops when disabled)."""

import os

import jax.numpy as jnp
import pytest

from defer_tpu.utils import profiling


def test_trace_noop_when_unconfigured(monkeypatch):
    monkeypatch.delenv(profiling.TRACE_ENV, raising=False)
    with profiling.trace() as t:
        assert t is None


def test_annotate_is_reentrant():
    with profiling.annotate("outer"):
        with profiling.annotate("inner"):
            x = jnp.ones((4, 4)) @ jnp.ones((4, 4))
    assert float(x[0, 0]) == 4.0


def test_trace_captures_profile(tmp_path):
    target = str(tmp_path / "trace")
    with profiling.trace(target):
        (jnp.ones((32, 32)) @ jnp.ones((32, 32))).block_until_ready()
    # jax writes plugins/profile/<ts>/*.xplane.pb under the target dir.
    found = [
        f
        for root, _, files in os.walk(target)
        for f in files
        if f.endswith(".xplane.pb") or f.endswith(".trace.json.gz")
    ]
    assert found, f"no trace artifacts under {target}"


def test_window_trace_bounds_capture(tmp_path):
    """WindowTrace stops after `limit` ticks even if the loop goes on."""
    target = str(tmp_path / "wt")
    wt = profiling.WindowTrace(limit=3, trace_dir=target)
    for _ in range(10):
        wt.tick()
        (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
    assert wt._done and not wt._active
    wt.close()  # idempotent
    found = [
        f for root, _, files in os.walk(target) for f in files
        if f.endswith(".xplane.pb") or f.endswith(".trace.json.gz")
    ]
    assert found


def test_window_trace_inert_without_target(monkeypatch):
    monkeypatch.delenv(profiling.TRACE_ENV, raising=False)
    wt = profiling.WindowTrace(limit=2)
    wt.tick()
    wt.tick()
    wt.close()
    assert not wt._active


def test_pipeline_runs_with_annotations():
    """The annotated hot path still composes correctly."""
    import jax

    from defer_tpu.config import DeferConfig
    from defer_tpu.graph.partition import partition
    from defer_tpu.models import get_model
    from defer_tpu.parallel.mesh import pipeline_devices
    from defer_tpu.parallel.pipeline import Pipeline

    model = get_model("vgg16")
    params = model.graph.init(jax.random.key(0), (1, 224, 224, 3))
    stages = partition(model.graph, model.default_cuts(2))
    pipe = Pipeline(
        stages, params, pipeline_devices(2),
        DeferConfig(compute_dtype=jnp.float32),
    )
    out = pipe.warmup(jnp.ones((1, 224, 224, 3)))
    assert out.shape == (1, 1000)
