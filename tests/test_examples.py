"""Smoke the example drivers the way a user runs them: fresh
interpreters, tiny configs, real argv — catches example bit-rot that
library tests can't see."""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    XLA_FLAGS=(
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip(),
)


def _run(args, timeout=420):
    proc = subprocess.run(
        [sys.executable, *args],
        cwd=REPO,
        env=ENV,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    return proc.stdout


def test_generate_example_llama_speculative():
    out = _run(
        [
            "examples/generate.py", "--family", "llama", "--layers", "2",
            "--dim", "64", "--heads", "4", "--kv-heads", "2",
            "--ffn", "128", "--vocab", "96", "--max-len", "64",
            "--prompt-len", "8", "--steps", "4", "--speculate", "2",
        ]
    )
    assert "steady decode" in out and "speculative" in out


@pytest.mark.parametrize("prefix,adapters", [(0, 0), (6, 0), (0, 2)])
def test_serve_decode_example_checked(prefix, adapters):
    args = [
        "examples/serve_decode.py", "--layers", "2", "--dim", "64",
        "--heads", "4", "--ffn", "128", "--vocab", "96",
        "--max-len", "128", "--requests", "4", "--slots", "2",
        "--check",
    ]
    if prefix:
        args += ["--prefix", str(prefix)]
    if adapters:
        args += ["--adapters", str(adapters)]
    else:
        args += ["--stop-demo"]
    out = _run(args)
    assert "valid greedy choices" in out
    if not adapters:
        assert "terminated request 0" in out
    if prefix:
        assert "prefill tokens reused" in out
    else:
        assert "prefill tokens reused" not in out


def test_finetune_lora_example():
    out = _run(
        [
            "examples/finetune_lora.py", "--layers", "2", "--dim", "32",
            "--heads", "4", "--ffn", "64", "--vocab", "96",
            "--rank", "4", "--steps", "15",
        ]
    )
    assert "finetune_lora OK" in out


def test_pretrained_example_skips_cleanly_offline():
    # No network, no cache, no --weights file: the documented SKIP
    # contract (exit 0, SKIP line) must hold.
    out = _run(
        ["examples/pretrained_infer.py", "--weights", "/nonexistent.h5"]
    )
    assert "SKIP" in out
