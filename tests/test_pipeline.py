"""Device-pinned pipeline on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from defer_tpu.config import DeferConfig
from defer_tpu.graph.partition import partition
from defer_tpu.models import get_model
from defer_tpu.parallel.mesh import make_mesh, pipeline_devices
from defer_tpu.parallel.pipeline import Pipeline
from tests.test_partition import residual_chain


F32 = DeferConfig(compute_dtype=jnp.float32)


def test_pipeline_matches_single_device(devices):
    g = residual_chain()
    params = g.init(jax.random.key(0), (4, 8))
    x = jax.random.normal(jax.random.key(1), (4, 8))
    want = g.apply(params, x)
    stages = partition(g, ["add_1", "add_2"])
    pipe = Pipeline(stages, params, devices[:3], config=F32)
    got = pipe.warmup(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)
    # Params really live on distinct devices.
    assert {
        d
        for p in pipe.stage_params
        for a in jax.tree_util.tree_leaves(p)
        for d in a.sharding.device_set
    } == set(devices[:3])


def test_stream_preserves_order(devices):
    g = residual_chain()
    params = g.init(jax.random.key(0), (1, 8))
    stages = partition(g, ["add_1"])
    pipe = Pipeline(stages, params, devices[:2], config=F32)
    xs = [jnp.full((1, 8), float(i)) for i in range(20)]
    outs = list(pipe.stream(iter(xs), max_inflight=4))
    assert len(outs) == 20
    for x, out in zip(xs, outs):
        want = g.apply(params, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(want), rtol=1e-5
        )


def test_resnet50_8stage_pipeline(devices):
    """The headline configuration: ResNet50 cut 8 ways over 8 devices
    (reference src/test.py:27 documents this cut list)."""
    model = get_model("resnet50")
    params = model.graph.init(jax.random.key(0), (1, 64, 64, 3))
    x = jax.random.normal(jax.random.key(1), (1, 64, 64, 3))
    want = jax.jit(model.graph.apply)(params, x)
    cuts = ["add_2", "add_4", "add_6", "add_8", "add_10", "add_12", "add_14"]
    stages = partition(model.graph, cuts)
    pipe = Pipeline(stages, params, pipeline_devices(8, devices), config=F32)
    outs = list(pipe.stream(iter([x] * 4)))
    assert len(outs) == 4
    for out in outs:
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(want), rtol=2e-4, atol=2e-6
        )


def test_probe_and_throughput_run(devices):
    g = residual_chain()
    params = g.init(jax.random.key(0), (1, 8))
    stages = partition(g, ["add_1"])
    pipe = Pipeline(stages, params, devices[:2], config=F32)
    x = jnp.ones((1, 8))
    lat = pipe.probe_stage_latencies(x, iters=3)
    assert len(lat) == 2
    assert all(r["p50_s"] > 0 for r in lat)
    stats = pipe.throughput(x, num_microbatches=8)
    assert stats["microbatches"] == 8
    assert stats["items_per_sec"] > 0


def test_make_mesh(devices):
    mesh = make_mesh({"data": 2, "stage": 4}, devices)
    assert mesh.shape == {"data": 2, "stage": 4}
