"""Continuous-batching decode server: per-request outputs must be
BIT-IDENTICAL to solo greedy decodes while decode ticks are shared."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models.gpt import tiny_gpt
from defer_tpu.models.llama import tiny_llama
from defer_tpu.runtime.decode_server import DecodeServer, serve_greedy


def _requests(vocab, dtype=jnp.int32):
    return [
        (jnp.asarray([[3, 9, 27]], dtype) % vocab, 7),
        (jnp.asarray([[5]], dtype) % vocab, 4),
        (jnp.asarray([[11, 2, 8, 1, 6]], dtype) % vocab, 9),
        (jnp.asarray([[4, 4]], dtype) % vocab, 2),
        (jnp.asarray([[1, 7, 7, 2]], dtype) % vocab, 1),
    ]


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_server_matches_solo_generate(family):
    """Five requests of different prompt lengths and step counts
    through 2 slots: every output equals that request's solo
    dec.generate — per-slot positions (learned table for gpt, rotary
    for llama + GQA cache), slot admission mid-flight, and stale-row
    masking all have to agree for this to hold."""
    dec = tiny_gpt(64) if family == "gpt" else tiny_llama(64)
    params = dec.init(jax.random.key(0))
    reqs = _requests(dec.cfg.vocab_size)
    outs, stats = serve_greedy(dec, params, reqs, max_batch=2)
    for (prompt, steps), got in zip(reqs, outs):
        want = dec.generate(params, prompt, steps)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want),
            err_msg=f"{family} prompt={np.asarray(prompt)} steps={steps}",
        )
    assert stats["ticks"] > 0


def test_batched_ticks_are_shared():
    """Concurrent slots share weight reads: serving two identical
    12-step requests in one 2-slot server takes ~12 ticks, not 24."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    reqs = [
        (jnp.asarray([[3, 1]], jnp.int32), 12),
        (jnp.asarray([[9, 5]], jnp.int32), 12),
    ]
    _, stats = serve_greedy(dec, params, reqs, max_batch=2)
    assert stats["solo_steps"] == 24
    assert stats["ticks"] <= 12  # admission yields token 1 per request


def test_submit_validation():
    dec = tiny_gpt(32)
    srv = DecodeServer(dec, dec.init(jax.random.key(0)), max_batch=2)
    with pytest.raises(ValueError, match="one request"):
        srv.submit(jnp.zeros((2, 3), jnp.int32), 2)
    with pytest.raises(ValueError, match="at least one token"):
        srv.submit(jnp.zeros((1, 0), jnp.int32), 2)
    with pytest.raises(ValueError, match="max_len"):
        srv.submit(jnp.zeros((1, 3), jnp.int32), 64)
    with pytest.raises(ValueError, match="num_steps"):
        srv.submit(jnp.zeros((1, 3), jnp.int32), 0)


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_prefix_cached_serving_matches_solo(family):
    """With a shared system prefix, every served suffix+generation is
    bit-identical to solo-decoding the CONCATENATED prompt — the
    copied prefix K/V lane, offset suffix prefill, and per-slot
    positions must all agree (learned positions for gpt, rotary for
    llama)."""
    dec = tiny_gpt(64) if family == "gpt" else tiny_llama(64)
    params = dec.init(jax.random.key(0))
    prefix = jnp.asarray([[7, 3, 1, 12, 9, 2]], jnp.int32)
    reqs = _requests(dec.cfg.vocab_size)
    outs, stats = serve_greedy(
        dec, params, reqs, max_batch=2, prefix_ids=prefix
    )
    P = prefix.shape[1]
    for (suffix, steps), got in zip(reqs, outs):
        full = jnp.concatenate([prefix, suffix], axis=1)
        want = dec.generate(params, full, steps)[:, P:]
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want),
            err_msg=f"{family} suffix={np.asarray(suffix)} steps={steps}",
        )
    assert stats["saved_prefill_tokens"] == P * len(reqs)


def test_eos_frees_slots_early():
    """With a stop token, a request finishing early releases its slot
    (fewer ticks than the full budget) and each output equals the
    solo eos-stopped decode trimmed at its first eos."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    reqs = _requests(dec.cfg.vocab_size)[:4]
    # Choose an eos that actually occurs: the token request 0 emits
    # at its second step in a free-running decode.
    free = dec.generate(params, reqs[0][0], reqs[0][1])
    eos = int(np.asarray(free)[0, reqs[0][0].shape[1] + 1])
    _, stats_free = serve_greedy(dec, params, reqs, max_batch=2)
    outs, stats = serve_greedy(
        dec, params, reqs, max_batch=2, eos_id=eos
    )
    # The economics, not just the trimming: early slot release must
    # save batched ticks vs the same workload without a stop token.
    assert stats["ticks"] < stats_free["ticks"]
    stopped_early = False
    for (p, s), got in zip(reqs, outs):
        want = np.asarray(dec.generate(params, p, s, eos_id=eos))
        got = np.asarray(got)
        assert got.shape[1] <= want.shape[1]
        np.testing.assert_array_equal(got[0], want[0, : got.shape[1]])
        if got.shape[1] < want.shape[1]:
            assert got[0, -1] == eos
            stopped_early = True
    assert stopped_early  # the chosen eos fired for at least one req


def test_rolling_cache_server_matches_solo():
    """Sliding-window (Mistral-family) serving: per-slot rolling
    caches — each slot's write recycles ITS OWN window — match solo
    rolling decodes exactly, for prompts shorter AND longer than the
    window and generation that crosses the window boundary."""
    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.llama import mistral_config

    cfg = mistral_config(
        num_layers=2, dim=32, num_heads=4, num_kv_heads=2,
        ffn_dim=64, vocab_size=64, max_len=64, window=8,
    )
    dec = GptDecoder(cfg, rolling_cache=True, compute_dtype=jnp.float32)
    params = dec.init(jax.random.key(0))
    reqs = [
        (jnp.asarray([[3, 9, 27]], jnp.int32), 12),  # crosses window
        (jnp.asarray([[5]], jnp.int32), 4),
        # Prompt longer than the window: chunked rolling prefill.
        (
            jax.random.randint(jax.random.key(1), (1, 13), 0, 64),
            6,
        ),
        (jnp.asarray([[4, 4]], jnp.int32), 9),
    ]
    outs, stats = serve_greedy(dec, params, reqs, max_batch=2)
    for (p, s), got in zip(reqs, outs):
        want = dec.generate(params, p, s)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want),
            err_msg=f"prompt len {p.shape[1]} steps {s}",
        )
    assert stats["ticks"] > 0


def test_streaming_callback_matches_outputs():
    """on_token streams every generated token in order, with done=True
    exactly once per request, and the streamed sequence equals the
    generated tail of the final output."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    reqs = _requests(dec.cfg.vocab_size)[:4]
    streamed: dict[int, list[int]] = {}
    finals: list[int] = []

    def on_token(rid, tok, done):
        streamed.setdefault(rid, []).append(tok)
        if done:
            finals.append(rid)

    srv = DecodeServer(dec, params, max_batch=2, on_token=on_token)
    rids = [srv.submit(p, s) for p, s in reqs]
    done = srv.run()
    assert sorted(finals) == sorted(rids) and len(finals) == len(set(finals))
    for (p, s), rid in zip(reqs, rids):
        gen = np.asarray(done[rid])[0, p.shape[1]:]
        assert streamed[rid] == gen.tolist()
        assert len(streamed[rid]) == s


def test_prefix_validation():
    dec = tiny_gpt(32)
    params = dec.init(jax.random.key(0))
    with pytest.raises(ValueError, match=r"\[1, P\]"):
        DecodeServer(dec, params, prefix_ids=jnp.zeros((3,), jnp.int32))
    with pytest.raises(ValueError, match="no room"):
        DecodeServer(dec, params, prefix_ids=jnp.zeros((1, 32), jnp.int32))
    srv = DecodeServer(
        dec, params, max_batch=2, prefix_ids=jnp.zeros((1, 10), jnp.int32)
    )
    with pytest.raises(ValueError, match="prefix 10"):
        srv.submit(jnp.zeros((1, 4), jnp.int32), 19)  # 10+4+19 > 32

    from defer_tpu.models.llama import mistral_config
    from defer_tpu.models.gpt import GptDecoder

    rolling = GptDecoder(
        mistral_config(
            num_layers=2, dim=32, num_heads=4, num_kv_heads=2,
            ffn_dim=64, vocab_size=64, max_len=32, window=8,
        ),
        rolling_cache=True,
    )
    with pytest.raises(ValueError, match="rolling"):
        DecodeServer(
            rolling,
            rolling.init(jax.random.key(0)),
            prefix_ids=jnp.zeros((1, 4), jnp.int32),
        )


def test_server_composes_with_tensor_parallel(devices):
    """Continuous batching over a tp=2 SpmdGptDecoder: head-sharded
    caches + per-slot positions, token-exact vs the single-device
    reference decoder."""
    from defer_tpu.models.gpt import SpmdGptDecoder
    from defer_tpu.parallel.mesh import make_mesh

    ref = tiny_gpt(64)
    params = ref.init(jax.random.key(0))
    mesh = make_mesh({"model": 2}, devices[:2])
    tp = SpmdGptDecoder(ref.cfg, compute_dtype=jnp.float32, mesh=mesh)
    tparams = tp.shard_params(params)
    reqs = _requests(ref.cfg.vocab_size)[:3]
    outs, _ = serve_greedy(tp, tparams, reqs, max_batch=2)
    for (p, s), got in zip(reqs, outs):
        want = ref.generate(params, p, s)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_server_serves_int8_params():
    """Continuous batching composes with weight-only int8: quantized
    param trees flow through per-slot ticks unchanged."""
    from defer_tpu.models.quant import quantize_decoder_params

    dec = tiny_llama(64)
    params = quantize_decoder_params(dec.init(jax.random.key(0)))
    reqs = _requests(dec.cfg.vocab_size)[:3]
    outs, _ = serve_greedy(dec, params, reqs, max_batch=2)
    for (prompt, steps), got in zip(reqs, outs):
        want = dec.generate(params, prompt, steps)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def _sampling_cases():
    from defer_tpu.models.gpt import SamplingParams

    return [
        SamplingParams(temperature=0.8, top_k=20, seed=7),
        None,  # greedy slot sharing ticks with sampled neighbors
        SamplingParams(temperature=1.3, top_p=0.9, min_p=0.05, seed=42),
        SamplingParams(temperature=0.6, top_k=8, top_p=0.95, seed=3),
        SamplingParams(temperature=1.0, seed=0),
    ]


def _solo_reference(dec, params, prompt, steps, sp):
    if sp is None:
        return dec.generate(params, prompt, steps)
    return dec.generate(
        params, prompt, steps,
        temperature=sp.temperature, top_k=sp.top_k, top_p=sp.top_p,
        min_p=sp.min_p, rng=jax.random.key(sp.seed),
    )


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_per_request_sampling_matches_solo(family):
    """Each sampled slot must reproduce solo
    `generate(..., rng=jax.random.key(seed))` BIT-FOR-BIT while
    sharing batched ticks with slots running other policies (and a
    greedy slot): per-slot key streams split exactly once per emitted
    token, and the batched truncate reproduces each row's static
    filters."""
    dec = tiny_gpt(64) if family == "gpt" else tiny_llama(64)
    params = dec.init(jax.random.key(0))
    reqs = _requests(dec.cfg.vocab_size)
    samps = _sampling_cases()
    outs, _ = serve_greedy(
        dec, params, reqs, max_batch=2, sampling=samps
    )
    for (prompt, steps), sp, got in zip(reqs, samps, outs):
        want = _solo_reference(dec, params, prompt, steps, sp)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want),
            err_msg=f"{family} sampling={sp}",
        )


def test_sampling_slot_reuse_resets_policy():
    """A greedy request admitted into the slot a sampled request
    vacated must not inherit the stale temperature row."""
    from defer_tpu.models.gpt import SamplingParams

    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    reqs = _requests(dec.cfg.vocab_size)[:2]
    srv = DecodeServer(dec, params, max_batch=1)
    r1 = srv.submit(
        reqs[0][0], reqs[0][1],
        sampling=SamplingParams(temperature=1.5, seed=1),
    )
    r2 = srv.submit(reqs[1][0], reqs[1][1])  # greedy, same slot later
    done = srv.run()
    np.testing.assert_array_equal(
        np.asarray(done[r2]),
        np.asarray(dec.generate(params, reqs[1][0], reqs[1][1])),
    )
    np.testing.assert_array_equal(
        np.asarray(done[r1]),
        np.asarray(
            _solo_reference(
                dec, params, reqs[0][0], reqs[0][1],
                SamplingParams(temperature=1.5, seed=1),
            )
        ),
    )


def test_sampling_validation():
    from defer_tpu.models.gpt import SamplingParams

    dec = tiny_gpt(32)
    srv = DecodeServer(dec, dec.init(jax.random.key(0)), max_batch=1)
    prompt = jnp.asarray([[1, 2]], jnp.int32)
    with pytest.raises(ValueError, match="temperature"):
        srv.submit(
            prompt, 2, sampling=SamplingParams(temperature=-1.0)
        )
    with pytest.raises(ValueError, match="top_p"):
        srv.submit(
            prompt, 2,
            sampling=SamplingParams(temperature=1.0, top_p=0.0),
        )


def test_truncate_logits_batched_matches_static():
    """Row-by-row bit-equality of the batched filter against the
    static-parameter truncate_logits across the policy grid (incl.
    disabled filters reducing to neutral thresholds)."""
    from defer_tpu.models.gpt import (
        truncate_logits,
        truncate_logits_batched,
    )

    cases = [
        (0, 1.0, 0.0),
        (5, 1.0, 0.0),
        (0, 0.7, 0.0),
        (0, 1.0, 0.2),
        (12, 0.85, 0.05),
        (1, 0.5, 0.5),
    ]
    logits = jax.random.normal(
        jax.random.key(11), (len(cases), 33)
    ) * 3.0
    got = truncate_logits_batched(
        logits,
        jnp.asarray([c[0] for c in cases], jnp.int32),
        jnp.asarray([c[1] for c in cases], jnp.float32),
        jnp.asarray([c[2] for c in cases], jnp.float32),
    )
    for r, (k, p, mp) in enumerate(cases):
        want = truncate_logits(
            logits[r:r + 1], top_k=k, top_p=p, min_p=mp
        )
        np.testing.assert_array_equal(
            np.asarray(got[r]), np.asarray(want[0]),
            err_msg=f"row {r}: top_k={k} top_p={p} min_p={mp}",
        )


def test_stop_sequence_finishes_request_mid_budget():
    """A request whose generated tail completes a 2-token stop
    sequence must finish right there — its output ends with the stop
    sequence, short of its step budget — and the vacated slot serves
    the queue; an identical request without the stop runs out its
    full budget."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    prompt = jnp.asarray([[3, 9, 27]], jnp.int32)
    full = np.asarray(dec.generate(params, prompt, 12))[0]
    gen = full[3:]
    stop = [int(gen[5]), int(gen[6])]
    srv = DecodeServer(dec, params, max_batch=2)
    r_stop = srv.submit(prompt, 12, stop=[stop])
    r_free = srv.submit(prompt, 12)
    done = srv.run()
    got = np.asarray(done[r_stop])[0]
    assert len(got) == 3 + 7, got  # mid-budget: 7 of 12 steps
    assert list(got[-2:]) == stop
    np.testing.assert_array_equal(got, full[: len(got)])
    np.testing.assert_array_equal(np.asarray(done[r_free])[0], full)


def test_stop_sequence_composes_with_sampling():
    """Stop matching runs on the sampled stream: serve once sampled to
    learn its tokens, then re-serve with a 2-token stop drawn from
    that stream — the output must be the same stream truncated at the
    stop."""
    from defer_tpu.models.gpt import SamplingParams

    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    prompt = jnp.asarray([[11, 2, 8]], jnp.int32)
    sp = SamplingParams(temperature=1.1, top_k=30, seed=9)
    base = np.asarray(
        dec.generate(
            params, prompt, 12, temperature=sp.temperature,
            top_k=sp.top_k, rng=jax.random.key(sp.seed),
        )
    )[0]
    gen = base[3:]
    stop = [int(gen[4]), int(gen[5])]
    # The pair could occur earlier in the stream; find its FIRST
    # occurrence to predict the cut point.
    first_end = next(
        j
        for j in range(1, len(gen))
        if [int(gen[j - 1]), int(gen[j])] == stop
    )
    srv = DecodeServer(dec, params, max_batch=2)
    r = srv.submit(prompt, 12, sampling=sp, stop=[stop])
    got = np.asarray(srv.run()[r])[0]
    assert len(got) == 3 + first_end + 1, (got, base, stop)
    np.testing.assert_array_equal(got, base[: len(got)])


def test_sample_token_batched_nosort_bit_identical():
    """The sort-free sampler must be BITWISE equal to the general one
    whenever top-k/top-p are disabled on every row — tokens and the
    advanced key state both, so a server can switch variants
    tick-by-tick (greedy rows, temperature spread, min_p floors)."""
    from defer_tpu.models.gpt import (
        sample_token_batched,
        sample_token_batched_nosort,
    )

    B, V = 5, 97
    logits = jax.random.normal(jax.random.key(3), (B, V)) * 4.0
    keys = jax.random.split(jax.random.key(17), B)
    temp = jnp.asarray([0.0, 0.7, 1.3, 1.0, 0.0], jnp.float32)
    minp = jnp.asarray([0.0, 0.05, 0.0, 0.2, 0.1], jnp.float32)
    zero_k = jnp.zeros((B,), jnp.int32)
    one_p = jnp.ones((B,), jnp.float32)
    want_t, want_k = sample_token_batched(
        logits, keys, temp, zero_k, one_p, minp
    )
    got_t, got_k = sample_token_batched_nosort(logits, keys, temp, minp)
    np.testing.assert_array_equal(np.asarray(got_t), np.asarray(want_t))
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(got_k)),
        np.asarray(jax.random.key_data(want_k)),
    )


def test_nosort_dispatch_preserves_solo_parity():
    """End-to-end: a server whose active slots all sample WITHOUT
    top-k/top-p takes the sort-free draw every tick (row_sort stays
    all-False), and each output still equals the solo reference
    bit-for-bit; a top-k admission flips its slot's row_sort."""
    from defer_tpu.models.gpt import SamplingParams

    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    reqs = _requests(dec.cfg.vocab_size)[:3]
    samps = [
        SamplingParams(temperature=0.9, seed=11),
        None,  # greedy neighbor shares ticks with the sampled rows
        SamplingParams(temperature=1.2, min_p=0.1, seed=4),
    ]
    srv = DecodeServer(dec, params, max_batch=2)
    rids = [
        srv.submit(p, s, sampling=sp)
        for (p, s), sp in zip(reqs, samps)
    ]
    done = srv.run()
    assert not any(srv._sampler.row_sort)
    for (p, s), sp, r in zip(reqs, samps, rids):
        want = _solo_reference(dec, params, p, s, sp)
        np.testing.assert_array_equal(
            np.asarray(done[r]), np.asarray(want)
        )

    srv2 = DecodeServer(dec, params, max_batch=2)
    r_sorted = srv2.submit(
        reqs[0][0], 3,
        sampling=SamplingParams(temperature=1.0, top_k=5, seed=1),
    )
    # release() clears row_sort the moment a slot finishes, so observe
    # the flag at release time: it must have been True while the top-k
    # slot was live, and all-False again once the run drains.
    sorted_at_release = []
    orig_release = srv2._sampler.release

    def _spy(i):
        sorted_at_release.append(srv2._sampler.row_sort[i])
        orig_release(i)

    srv2._sampler.release = _spy
    done2 = srv2.run()
    assert any(sorted_at_release)
    assert not any(srv2._sampler.row_sort)
    np.testing.assert_array_equal(
        np.asarray(done2[r_sorted]),
        np.asarray(
            _solo_reference(
                dec, params, reqs[0][0], 3,
                SamplingParams(temperature=1.0, top_k=5, seed=1),
            )
        ),
    )
