"""GPT decoder + KV cache: incremental decode must equal the full
causal forward, and generation must be deterministic/cache-correct."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models.gpt import GptDecoder, tiny_gpt
from defer_tpu.parallel.transformer_stack import TransformerConfig


def test_incremental_decode_matches_full_forward():
    """Teacher forcing: feeding tokens one at a time through the cache
    reproduces the full-sequence causal logits at every position."""
    dec = tiny_gpt()
    params = dec.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 10), 0, 128)

    want = dec.reference_logits(params, ids)  # [B, T, V]

    step = dec.make_step(donate=False)
    cache = dec.init_cache(2)
    got = []
    for t in range(10):
        logits, cache = step(params, cache, ids[:, t : t + 1])
        got.append(logits[:, 0, :])
    got = jnp.stack(got, axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_prefill_then_decode_matches():
    """Prompt prefill (T=6 in one step) then per-token decode continues
    the same distribution as pure per-token decoding."""
    dec = tiny_gpt()
    params = dec.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 9), 0, 128)

    step = dec.make_step(donate=False)
    c1 = dec.init_cache(1)
    l1, c1 = step(params, c1, ids[:, :6])  # prefill
    l1b, c1 = step(params, c1, ids[:, 6:7])
    l1c, c1 = step(params, c1, ids[:, 7:8])

    want = dec.reference_logits(params, ids)
    np.testing.assert_allclose(
        np.asarray(l1[:, -1]), np.asarray(want[:, 5]), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(l1b[:, 0]), np.asarray(want[:, 6]), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(l1c[:, 0]), np.asarray(want[:, 7]), rtol=2e-4, atol=2e-4
    )


def test_generate_greedy_deterministic_and_bounded():
    dec = tiny_gpt()
    params = dec.init(jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, 128)
    out1 = dec.generate(params, prompt, 8)
    out2 = dec.generate(params, prompt, 8)
    assert out1.shape == (2, 12)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    np.testing.assert_array_equal(
        np.asarray(out1[:, :4]), np.asarray(prompt)
    )
    # Greedy continuation must equal argmax over the reference logits
    # at each position (teacher-forced on its own output).
    ref = dec.reference_logits(params, out1[:, :-1])
    for t in range(4, 12):
        np.testing.assert_array_equal(
            np.asarray(out1[:, t]),
            np.asarray(jnp.argmax(ref[:, t - 1, :], axis=-1)),
        )


def test_generate_budget_checked():
    dec = tiny_gpt(seq_len=16)
    params = dec.init(jax.random.key(0))
    prompt = jnp.zeros((1, 10), jnp.int32)
    with pytest.raises(ValueError, match="exceeds max_len"):
        dec.generate(params, prompt, 7)


def test_decoder_validates_config():
    with pytest.raises(ValueError, match="pre"):
        GptDecoder(
            TransformerConfig(
                num_layers=2, dim=32, num_heads=2, ffn_dim=64,
                vocab_size=64, max_len=16, norm_style="post",
            )
        )


def test_sampled_generation_respects_temperature():
    """Temperature>0 with a fixed rng is reproducible; different rngs
    diverge (i.e. sampling actually happens)."""
    dec = tiny_gpt()
    params = dec.init(jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (1, 4), 0, 128)
    a = dec.generate(
        params, prompt, 10, temperature=1.0, rng=jax.random.key(7)
    )
    b = dec.generate(
        params, prompt, 10, temperature=1.0, rng=jax.random.key(7)
    )
    c = dec.generate(
        params, prompt, 10, temperature=1.0, rng=jax.random.key(8)
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_truncate_logits_top_k():
    """top_k keeps exactly the k largest logits; the rest drop to the
    dtype floor so categorical can never pick them."""
    from defer_tpu.models.gpt import truncate_logits

    logits = jnp.array([[0.0, 3.0, 1.0, 2.0, -1.0]])
    out = np.asarray(truncate_logits(logits, top_k=2))
    neg = np.finfo(np.float32).min
    np.testing.assert_allclose(out[0], [neg, 3.0, neg, 2.0, neg])
    # k >= vocab is a no-op
    np.testing.assert_allclose(
        np.asarray(truncate_logits(logits, top_k=5)), np.asarray(logits)
    )


def test_truncate_logits_top_p():
    """Nucleus: tokens are kept in descending-probability order until
    the cumulative mass first reaches top_p; the top token always
    survives even for tiny top_p."""
    from defer_tpu.models.gpt import truncate_logits

    # softmax of these is ~[0.474, 0.474, 0.047, 0.005]
    logits = jnp.log(jnp.array([[10.0, 10.0, 1.0, 0.1]]))
    neg = np.finfo(np.float32).min
    out = np.asarray(truncate_logits(logits, top_p=0.9))
    # 0.474 + 0.474 = 0.948 >= 0.9 -> first two survive, rest masked
    assert out[0, 0] > neg / 2 and out[0, 1] > neg / 2
    assert out[0, 2] == neg and out[0, 3] == neg

    tiny = np.asarray(truncate_logits(logits, top_p=1e-6))
    # only the argmax-tied top tokens survive
    assert (tiny[0, :2] > neg / 2).any()
    assert tiny[0, 2] == neg and tiny[0, 3] == neg

    # Degenerate top_p=0 still keeps the top token instead of masking
    # everything (which would silently sample uniformly).
    zero = np.asarray(truncate_logits(jnp.array([[0.0, 3.0, 1.0]]), top_p=0.0))
    assert zero[0, 1] > neg / 2
    assert zero[0, 0] == neg and zero[0, 2] == neg


def test_min_p_filters_by_confidence():
    """min_p keeps tokens whose probability clears min_p x the top
    probability — a peaked distribution keeps few, a flat one many."""
    from defer_tpu.models.gpt import truncate_logits

    neg = np.finfo(np.float32).min
    # probs ~ [0.64, 0.23, 0.09, 0.03]: with min_p=0.2 only the top
    # two clear 0.2 * 0.64 = 0.128.
    peaked = jnp.log(jnp.array([[20.0, 7.3, 2.7, 1.0]]))
    out = np.asarray(truncate_logits(peaked, min_p=0.2))
    assert out[0, 0] > neg / 2 and out[0, 1] > neg / 2
    assert out[0, 2] == neg and out[0, 3] == neg
    # A uniform distribution keeps everything at the same min_p.
    flat = jnp.zeros((1, 4))
    np.testing.assert_allclose(
        np.asarray(truncate_logits(flat, min_p=0.2)), np.asarray(flat)
    )


def test_repetition_penalty_discourages_seen_tokens():
    """HF semantics: seen tokens' positive logits divide by the
    penalty, negative ones multiply; unseen logits are untouched —
    and a greedy decode with a high penalty avoids immediate loops."""
    from defer_tpu.models.gpt import repetition_penalty

    logits = jnp.array([[2.0, -1.0, 3.0, 0.5]])
    ids = jnp.array([[0, 1]])  # tokens 0 and 1 already emitted
    out = np.asarray(repetition_penalty(logits, ids, 2.0))
    np.testing.assert_allclose(out[0], [1.0, -2.0, 3.0, 0.5])
    # penalty 1.0 is the identity
    np.testing.assert_allclose(
        np.asarray(repetition_penalty(logits, ids, 1.0)),
        np.asarray(logits),
    )

    dec = tiny_gpt()
    params = dec.init(jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (1, 4), 0, 128)
    out = dec.generate(params, prompt, 12, rep_penalty=1e6)
    gen = np.asarray(out)[0, 4:]
    # An absurd penalty forbids ever repeating a token.
    assert len(set(gen.tolist())) == len(gen)


def test_sample_token_top_k_restricts_support():
    """Sampling with top_k=2 at high temperature only ever emits the
    two highest-logit ids; top_k=1 is exactly greedy."""
    from defer_tpu.models.gpt import sample_token

    logits = jnp.array([[0.0, 5.0, 4.9, 1.0, 2.0]])
    rng = jax.random.key(0)
    seen = set()
    for _ in range(64):
        tok, rng = sample_token(logits, rng, 5.0, top_k=2)
        seen.add(int(tok[0]))
    assert seen <= {1, 2} and len(seen) == 2

    tok, _ = sample_token(logits, jax.random.key(3), 5.0, top_k=1)
    assert int(tok[0]) == 1


def test_generate_with_nucleus_sampling():
    """End-to-end: generate with temperature + top_k + top_p is
    reproducible under a fixed rng and stays in-vocab."""
    dec = tiny_gpt()
    params = dec.init(jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, 128)
    a = dec.generate(
        params, prompt, 8, temperature=0.8, top_k=40, top_p=0.95,
        rng=jax.random.key(7),
    )
    b = dec.generate(
        params, prompt, 8, temperature=0.8, top_k=40, top_p=0.95,
        rng=jax.random.key(7),
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (2, 12)
    toks = np.asarray(a)
    assert toks.min() >= 0 and toks.max() < 128


def test_generate_stops_at_eos():
    """eos_id: generation matches the unstopped run up to the first
    eos emission, pins everything after to eos, keeps the [B, T0+N]
    shape, and the host loop provably stopped early (same prefix)."""
    dec = tiny_gpt()
    params = dec.init(jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, 128)
    free = np.asarray(dec.generate(params, prompt, 10))
    # Force a stop: use the token row 0 emits at step 3 as "eos".
    eos = int(free[0, 4 + 3])
    out = np.asarray(dec.generate(params, prompt, 10, eos_id=eos))
    assert out.shape == free.shape
    for b in range(2):
        gen_free = free[b, 4:]
        hits = np.where(gen_free == eos)[0]
        cut = hits[0] if len(hits) else 10 - 1
        # identical up to and including the first eos (or the end)
        np.testing.assert_array_equal(out[b, 4 : 4 + cut + 1],
                                      gen_free[: cut + 1])
        assert (out[b, 4 + cut :] == eos).all() or len(hits) == 0


def test_generate_stops_at_stop_sequence():
    """Multi-token stop sequences (runtime/stopping.py): each row
    matches the unstopped run up to and including the first completion
    of a 2-token stop in its GENERATED tail, pins later positions to
    pad_id, keeps the [B, T0+N] shape, and a batch with per-row match
    points stops each row independently."""
    dec = tiny_gpt()
    params = dec.init(jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, 128)
    free = np.asarray(dec.generate(params, prompt, 10))
    # Use the 2-token window row 0 emits at generated steps 4-5.
    stop = [int(free[0, 4 + 4]), int(free[0, 4 + 5])]
    out = np.asarray(
        dec.generate(params, prompt, 10, stop_sequences=[stop])
    )
    assert out.shape == free.shape
    for b in range(2):
        gen_free = free[b, 4:]
        cut = None  # index of the last token of the first match
        for j in range(1, len(gen_free)):
            if [int(gen_free[j - 1]), int(gen_free[j])] == stop:
                cut = j
                break
        if cut is None:
            np.testing.assert_array_equal(out[b], free[b])
        else:
            np.testing.assert_array_equal(
                out[b, 4 : 4 + cut + 1], gen_free[: cut + 1]
            )
            assert (out[b, 4 + cut + 1 :] == 0).all()
    # Row 0 stops mid-budget by construction.
    assert (out[0, 4 + 6 :] == 0).all()


def test_stop_sequences_ignore_eos_padding():
    """eos + stop together: an eos-finished row's pinned padding is
    NOT generated content, so it must never complete a stop sequence
    — even one made of eos tokens — and the output must equal the
    eos-only run whenever no stop matches real tokens."""
    dec = tiny_gpt()
    params = dec.init(jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, 128)
    free = np.asarray(dec.generate(params, prompt, 10))
    eos = int(free[0, 4 + 3])
    out_eos = np.asarray(dec.generate(params, prompt, 10, eos_id=eos))
    for stop in ([99999, 99998], [eos, eos]):
        out = np.asarray(
            dec.generate(
                params, prompt, 10, eos_id=eos, stop_sequences=[stop]
            )
        )
        np.testing.assert_array_equal(out_eos, out, err_msg=f"{stop}")


def test_tp_sharded_decode_matches_single_device(devices):
    """SpmdGptDecoder over model=2: head-sharded caches + Megatron
    projections reproduce the single-device decoder exactly, through
    prefill, incremental decode, and generate."""
    from defer_tpu.models.gpt import SpmdGptDecoder
    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.parallel.transformer_stack import TransformerConfig

    cfg = TransformerConfig(
        num_layers=3, dim=64, num_heads=4, ffn_dim=128,
        vocab_size=96, max_len=24, norm_style="pre",
    )
    ref = GptDecoder(cfg, compute_dtype=jnp.float32)
    params = ref.init(jax.random.key(0))

    mesh = make_mesh({"model": 2}, devices[:2])
    tp = SpmdGptDecoder(
        cfg, compute_dtype=jnp.float32, mesh=mesh, tp_axis="model"
    )
    tparams = tp.shard_params(params)
    # The stack really is sharded over the model axis.
    wq = tparams["stack"]["wq"]
    assert {s.data.shape for s in wq.addressable_shards} == {(3, 64, 32)}
    # ... and so is the vocab matrix (Megatron embedding sharding).
    emb = tparams["token_embedding"]
    assert {s.data.shape for s in emb.addressable_shards} == {(48, 64)}

    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, 96)
    want = ref.reference_logits(params, ids)

    step = tp.make_step(donate=False)
    cache = tp.init_cache(2)
    logits, cache = step(tparams, cache, ids[:, :5])  # prefill
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(want[:, :5]), rtol=2e-4, atol=2e-4
    )
    for t in range(5, 8):
        logits, cache = step(tparams, cache, ids[:, t : t + 1])
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]),
            np.asarray(want[:, t]),
            rtol=2e-4,
            atol=2e-4,
        )

    out_ref = ref.generate(params, ids[:, :4], 6)
    out_tp = tp.generate(tparams, ids[:, :4], 6)
    np.testing.assert_array_equal(np.asarray(out_ref), np.asarray(out_tp))


def test_spmd_decoder_validates_mesh_and_divisibility(devices):
    from defer_tpu.models.gpt import SpmdGptDecoder
    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.parallel.transformer_stack import TransformerConfig

    cfg = TransformerConfig(
        num_layers=2, dim=64, num_heads=4, ffn_dim=128,
        vocab_size=64, max_len=16, norm_style="pre",
    )
    with pytest.raises(ValueError, match="mesh"):
        SpmdGptDecoder(cfg, mesh=None)
    mesh3 = make_mesh({"model": 3}, devices[:3])
    with pytest.raises(ValueError, match="divide"):
        SpmdGptDecoder(cfg, mesh=mesh3)


def test_tp_decode_with_non_divisible_vocab(devices):
    """Vocab 49 on tp=2 pads to 50 internally; outputs stay [.., 49]
    and token-exact vs the single-device decoder (pad rows must never
    win an argmax)."""
    from defer_tpu.models.gpt import SpmdGptDecoder
    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.parallel.transformer_stack import TransformerConfig

    cfg = TransformerConfig(
        num_layers=2, dim=32, num_heads=4, ffn_dim=64,
        vocab_size=49, max_len=16, norm_style="pre",
    )
    ref = GptDecoder(cfg, compute_dtype=jnp.float32)
    params = ref.init(jax.random.key(0))
    mesh = make_mesh({"model": 2}, devices[:2])
    tp = SpmdGptDecoder(cfg, compute_dtype=jnp.float32, mesh=mesh)
    tparams = tp.shard_params(params)
    assert tparams["token_embedding"].shape == (50, 32)  # padded

    ids = jax.random.randint(jax.random.key(1), (1, 6), 0, 49)
    want = ref.reference_logits(params, ids)
    step = tp.make_step(donate=False)
    logits, _ = step(tparams, tp.init_cache(1), ids)
    assert logits.shape == (1, 6, 49)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(want), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_array_equal(
        np.asarray(ref.generate(params, ids[:, :3], 5)),
        np.asarray(tp.generate(tparams, ids[:, :3], 5)),
    )


def test_causal_stack_matches_decoder_blocks():
    """TransformerConfig(causal=True, norm_style='pre') makes
    layers_apply (the trainable SPMD stack) produce the decoder's
    block outputs exactly — the same params train and serve."""
    from defer_tpu.parallel.transformer_stack import layers_apply

    dec = tiny_gpt()
    import dataclasses

    cfg_causal = dataclasses.replace(dec.cfg, causal=True)
    params = dec.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, 128)

    # Decoder path: embed -> cached blocks (fresh cache, full seq).
    want = dec.reference_logits(params, ids)

    # Stack path: same embed, causal layers_apply, same final LN/head.
    emb = jnp.take(params["token_embedding"], ids, axis=0)
    emb = emb + params["pos_embedding"][: ids.shape[1]]
    x = layers_apply(params["stack"], emb.astype(jnp.float32), cfg_causal)
    from defer_tpu.parallel.transformer_stack import _layer_norm

    x = _layer_norm(
        x.astype(jnp.float32),
        params["final_ln_scale"],
        params["final_ln_bias"],
        dec.cfg.layer_norm_eps,
    )
    got = x @ params["token_embedding"].T
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_causal_gpt_trains_through_spmd_pipeline(devices):
    """End-to-end decoder training: SpmdBert machinery with
    causal+pre-LN config, dp x pp mesh, loss decreases."""
    import optax

    from defer_tpu.models.bert import SpmdBert
    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.parallel.train import make_train_step

    cfg = TransformerConfig(
        num_layers=4, dim=32, num_heads=4, ffn_dim=64,
        vocab_size=64, max_len=16, norm_style="pre", causal=True,
    )
    mesh = make_mesh({"data": 2, "stage": 2}, devices[:4])
    sb = SpmdBert(mesh, cfg, compute_dtype=jnp.float32)
    init_state, train_step = make_train_step(
        sb, optax.adam(1e-2), num_classes=4
    )
    state = init_state(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (3, 4, 8), 0, 64)
    labels = jax.random.randint(jax.random.key(2), (3, 4), 0, 4)
    state, loss0 = train_step(state, ids, labels)
    for _ in range(5):
        state, loss = train_step(state, ids, labels)
    assert float(loss) < float(loss0)
    # Mask sensitivity: the flag must actually reach the attention op —
    # with identical params, causal and bidirectional pooled outputs
    # differ (token 0 sees everything bidirectionally, only itself
    # causally).
    import dataclasses

    sb_bidir = SpmdBert(
        mesh, dataclasses.replace(cfg, causal=False),
        compute_dtype=jnp.float32,
    )
    out_causal = sb.make_step()(state.params, ids)
    out_bidir = sb_bidir.make_step()(state.params, ids)
    assert not np.allclose(
        np.asarray(out_causal), np.asarray(out_bidir)
    )


def test_dp_tp_decode_matches_single_device(devices):
    """dp x tp serving mesh (data=2, model=2): batch-sharded cache +
    head-sharded projections, token-exact vs the single-device
    decoder."""
    from defer_tpu.models.gpt import SpmdGptDecoder
    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.parallel.transformer_stack import TransformerConfig

    cfg = TransformerConfig(
        num_layers=2, dim=32, num_heads=4, ffn_dim=64,
        vocab_size=64, max_len=16, norm_style="pre",
    )
    ref = GptDecoder(cfg, compute_dtype=jnp.float32)
    params = ref.init(jax.random.key(0))
    mesh = make_mesh({"data": 2, "model": 2}, devices[:4])
    dec = SpmdGptDecoder(
        cfg, compute_dtype=jnp.float32, mesh=mesh, dp_axis="data"
    )
    tparams = dec.shard_params(params)
    cache = dec.init_cache(4)  # batch 4 -> 2 per dp shard
    assert {
        s.data.shape for s in cache["k"].addressable_shards
    } == {(2, 2, 2, 16, 8)}

    ids = jax.random.randint(jax.random.key(1), (4, 6), 0, 64)
    want = ref.reference_logits(params, ids)
    step = dec.make_step(donate=False)
    logits, cache = step(tparams, cache, ids[:, :4])
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(want[:, :4]), rtol=2e-4, atol=2e-4
    )
    logits, cache = step(tparams, cache, ids[:, 4:5])
    np.testing.assert_allclose(
        np.asarray(logits[:, 0]), np.asarray(want[:, 4]),
        rtol=2e-4, atol=2e-4,
    )
    np.testing.assert_array_equal(
        np.asarray(ref.generate(params, ids[:, :3], 4)),
        np.asarray(dec.generate(tparams, ids[:, :3], 4)),
    )


def test_dp_axis_validated(devices):
    from defer_tpu.models.gpt import SpmdGptDecoder
    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.parallel.transformer_stack import TransformerConfig

    cfg = TransformerConfig(
        num_layers=2, dim=32, num_heads=4, ffn_dim=64,
        vocab_size=64, max_len=16, norm_style="pre",
    )
    mesh = make_mesh({"model": 2}, devices[:2])
    with pytest.raises(ValueError, match="not a mesh axis"):
        SpmdGptDecoder(cfg, mesh=mesh, dp_axis="data")


def test_dp_equals_tp_axis_rejected(devices):
    from defer_tpu.models.gpt import SpmdGptDecoder
    from defer_tpu.parallel.mesh import make_mesh
    from defer_tpu.parallel.transformer_stack import TransformerConfig

    cfg = TransformerConfig(
        num_layers=2, dim=32, num_heads=4, ffn_dim=64,
        vocab_size=64, max_len=16, norm_style="pre",
    )
    mesh = make_mesh({"model": 2}, devices[:2])
    with pytest.raises(ValueError, match="must differ"):
        SpmdGptDecoder(cfg, mesh=mesh, dp_axis="model")


def test_cast_params_decode_matches_fp32_tokens():
    """bf16-stored params (the serving configuration, cast_params) must
    produce the same greedy tokens as fp32 storage — the cast changes
    HBM traffic, not the sampled path, on these scales."""
    from defer_tpu.models.gpt import tiny_gpt

    dec = tiny_gpt()
    params = dec.init(jax.random.key(0))
    prompt = jnp.zeros((2, 3), jnp.int32)
    want = dec.generate(params, prompt, 5)
    got = dec.generate(dec.cast_params(params), prompt, 5)
    # compute_dtype is fp32 for tiny_gpt, so the cast is exact there;
    # exercise a real bf16 cast too and require identical argmax paths.
    import dataclasses

    dec16 = dataclasses.replace(dec, compute_dtype=jnp.bfloat16)
    got16 = dec16.generate(dec16.cast_params(params), prompt, 5)
    # bf16 COMPUTE with fp32 storage is the reference: the step casts
    # per use, so bf16 storage must yield the exact same token path.
    want16 = dec16.generate(params, prompt, 5)
    np.testing.assert_array_equal(np.asarray(got16), np.asarray(want16))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_chunked_prefill_matches_full():
    """Fixed-chunk prefill (incl. a zero-padded tail piece + position
    rewind) must reproduce the one-shot prefill logits and the whole
    greedy generation, for both position styles."""
    from defer_tpu.models.gpt import tiny_gpt
    from defer_tpu.models.llama import tiny_llama

    for dec in (tiny_gpt(64), tiny_llama(64)):
        params = dec.init(jax.random.key(0))
        ids = jax.random.randint(
            jax.random.key(1), (2, 11), 0, dec.cfg.vocab_size
        )
        full_last, _ = dec.prefill(params, dec.init_cache(2), ids)
        for chunk in (1, 4, 16):
            last, cache = dec.prefill(
                params, dec.init_cache(2), ids, chunk=chunk
            )
            assert int(jax.device_get(cache["pos"])) == 11
            np.testing.assert_allclose(
                np.asarray(last),
                np.asarray(full_last),
                rtol=2e-4,
                atol=2e-5,
                err_msg=f"chunk={chunk}",
            )
        want = dec.generate(params, ids, 5)
        got = dec.generate(params, ids, 5, prefill_chunk=4)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_chunked_prefill_at_max_len_boundary():
    """The padded tail must never clamp-write over earlier cache rows:
    with max_len=12, t0=11, chunk=5 the tail is fed unpadded, and the
    generation equals the unchunked one exactly."""
    from defer_tpu.models.gpt import tiny_gpt

    dec = tiny_gpt(seq_len=12)
    params = dec.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 11), 0, 128)
    want = dec.generate(params, ids, 1)
    got = dec.generate(params, ids, 1, prefill_chunk=5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    with pytest.raises(ValueError, match="exceeds max_len"):
        dec.prefill(
            params, dec.init_cache(1), jnp.zeros((1, 13), jnp.int32)
        )


def test_chunked_prefill_on_warm_cache():
    """prefill bounds come from the cache's real write head: a warm
    cache near max_len must reject overflow and never clamp-write, and
    a valid warm continuation must match the one-shot equivalent."""
    from defer_tpu.models.gpt import tiny_gpt

    dec = tiny_gpt(seq_len=32)
    params = dec.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 30), 0, 128)

    # One-shot over the full 30 tokens is the oracle.
    want, _ = dec.prefill(params, dec.init_cache(1), ids)

    # Warm path: 26 tokens in, then a 4-token chunked continuation
    # whose padded piece would cross max_len=32 (26+4+... the guard
    # must feed it unpadded).
    _, cache = dec.prefill(params, dec.init_cache(1), ids[:, :26])
    got, cache = dec.prefill(params, cache, ids[:, 26:], chunk=3)
    assert int(jax.device_get(cache["pos"])) == 30
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
    )

    with pytest.raises(ValueError, match="cache position"):
        dec.prefill(params, cache, jnp.zeros((1, 5), jnp.int32))
