"""Paged KV cache: block-pool serving must be bit-identical to solo
decodes while using less memory than max_batch x max_len lanes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models.gpt import tiny_gpt
from defer_tpu.models.llama import tiny_llama
from defer_tpu.runtime.paged import PagedDecodeServer, serve_paged


def _requests(vocab):
    return [
        (jnp.asarray([[3, 9, 27]], jnp.int32) % vocab, 7),
        (jnp.asarray([[5]], jnp.int32) % vocab, 4),
        (jnp.asarray([[11, 2, 8, 1, 6]], jnp.int32) % vocab, 9),
        (jnp.asarray([[4, 4]], jnp.int32) % vocab, 2),
        (jnp.asarray([[1, 7, 7, 2]], jnp.int32) % vocab, 1),
    ]


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_paged_matches_solo_generate(family):
    """Every output equals the request's solo dec.generate — the
    gathered-page attention runs the flat decoder's own block math, so
    paging must be invisible (learned positions for gpt, rotary+GQA
    for llama)."""
    dec = tiny_gpt(64) if family == "gpt" else tiny_llama(64)
    params = dec.init(jax.random.key(0))
    reqs = _requests(dec.cfg.vocab_size)
    outs, stats = serve_paged(
        dec, params, reqs, num_blocks=12, block_size=8, max_batch=2
    )
    for (prompt, steps), got in zip(reqs, outs):
        want = dec.generate(params, prompt, steps)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want),
            err_msg=f"{family} prompt={np.asarray(prompt)} steps={steps}",
        )
    assert stats["ticks"] > 0


def test_pool_smaller_than_flat_lanes():
    """The whole point: a pool far smaller than max_batch x max_len
    rows serves the workload, and peak usage reflects actual request
    budgets."""
    dec = tiny_gpt(64)  # max_len 64
    params = dec.init(jax.random.key(0))
    reqs = _requests(dec.cfg.vocab_size)
    # Flat server equivalent: 4 slots x 64 rows = 256 rows. Pool: 11
    # usable blocks x 4 rows = 44 rows.
    outs, stats = serve_paged(
        dec, params, reqs, num_blocks=12, block_size=4, max_batch=4
    )
    for (prompt, steps), got in zip(reqs, outs):
        want = dec.generate(params, prompt, steps)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    rows = stats["pool_blocks"] * stats["block_size"]
    assert rows < stats["flat_equivalent_rows"] // 5
    assert 0 < stats["peak_blocks"] <= stats["pool_blocks"]


def test_pool_exhaustion_defers_admission():
    """When the pool cannot hold another request, admission waits for
    a finisher instead of corrupting memory — and still completes."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    # Each request needs ceil((2+6)/4) = 2 blocks; pool has 3 usable,
    # so only one request fits at a time despite 4 slots.
    reqs = [
        (jnp.asarray([[i + 1, i + 2]], jnp.int32), 6) for i in range(3)
    ]
    outs, stats = serve_paged(
        dec, params, reqs, num_blocks=4, block_size=4, max_batch=4
    )
    for (prompt, steps), got in zip(reqs, outs):
        want = dec.generate(params, prompt, steps)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert stats["peak_blocks"] <= 3


def test_eos_frees_blocks_early():
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    reqs = _requests(dec.cfg.vocab_size)[:3]
    free0 = dec.generate(params, reqs[0][0], reqs[0][1])
    eos = int(np.asarray(free0)[0, reqs[0][0].shape[1] + 1])
    outs, stats = serve_paged(
        dec, params, reqs, num_blocks=12, block_size=4, max_batch=2,
        eos_id=eos,
    )
    for (p, s), got in zip(reqs, outs):
        want = np.asarray(dec.generate(params, p, s, eos_id=eos))
        got = np.asarray(got)
        np.testing.assert_array_equal(got[0], want[0, : got.shape[1]])


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_shared_prefix_paging_matches_solo(family):
    """TRUE prefix sharing: the system prompt's blocks exist once in
    the pool and every table points at them — each served suffix +
    generation equals solo-decoding the concatenated ids, for both
    position styles."""
    dec = tiny_gpt(64) if family == "gpt" else tiny_llama(64)
    params = dec.init(jax.random.key(0))
    prefix = jax.random.randint(jax.random.key(9), (1, 8), 0, 64)
    reqs = _requests(dec.cfg.vocab_size)[:4]
    outs, stats = serve_paged(
        dec, params, reqs, num_blocks=14, block_size=4, max_batch=2,
        prefix_ids=prefix,
    )
    assert stats["shared_prefix_blocks"] == 2  # 8 tokens / 4-row blocks
    for (sfx, steps), got in zip(reqs, outs):
        full = jnp.concatenate([prefix, sfx], axis=1)
        want = dec.generate(params, full, steps)[:, prefix.shape[1]:]
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want),
            err_msg=f"{family} suffix={np.asarray(sfx)} steps={steps}",
        )


def test_shared_prefix_blocks_are_never_rewritten():
    """The shared blocks' contents are bit-identical before and after
    serving a full workload — admissions write only owned blocks."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    prefix = jax.random.randint(jax.random.key(9), (1, 8), 0, 64)
    srv = PagedDecodeServer(
        dec, params, num_blocks=14, block_size=4, max_batch=2,
        prefix_ids=prefix,
    )
    shared = list(srv.shared_blocks)
    before_k = np.asarray(srv.pool_k[:, shared])
    for p, s in _requests(64)[:3]:
        srv.submit(p, s)
    srv.run()
    np.testing.assert_array_equal(
        np.asarray(srv.pool_k[:, shared]), before_k
    )


def test_shared_prefix_validation():
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    with pytest.raises(ValueError, match="multiple"):
        PagedDecodeServer(
            dec, params, num_blocks=8, block_size=4,
            prefix_ids=jnp.zeros((1, 6), jnp.int32),  # 6 % 4 != 0
        )
    srv = PagedDecodeServer(
        dec, params, num_blocks=8, block_size=4,
        prefix_ids=jnp.zeros((1, 8), jnp.int32),
    )
    with pytest.raises(ValueError, match="max_len"):
        srv.submit(jnp.zeros((1, 30), jnp.int32), 30)  # 8+30+30 > 64


def test_paged_streaming_callback():
    """on_token streams every generated token in order with done=True
    exactly once per request — same contract as the flat server."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    reqs = _requests(dec.cfg.vocab_size)[:3]
    streamed: dict[int, list[int]] = {}
    finals: list[int] = []

    srv = PagedDecodeServer(
        dec, params, num_blocks=12, block_size=8, max_batch=2,
        on_token=lambda rid, tok, done: (
            streamed.setdefault(rid, []).append(tok),
            finals.append(rid) if done else None,
        ),
    )
    rids = [srv.submit(p, s) for p, s in reqs]
    done = srv.run()
    assert sorted(finals) == sorted(rids)
    for (p, s), rid in zip(reqs, rids):
        gen = np.asarray(done[rid])[0, p.shape[1]:]
        assert streamed[rid] == gen.tolist() and len(streamed[rid]) == s


def test_paged_validation():
    dec = tiny_gpt(32)
    params = dec.init(jax.random.key(0))
    srv = PagedDecodeServer(
        dec, params, num_blocks=4, block_size=4, max_batch=2
    )
    with pytest.raises(ValueError, match="one request"):
        srv.submit(jnp.zeros((2, 3), jnp.int32), 2)
    with pytest.raises(ValueError, match="max_len"):
        srv.submit(jnp.zeros((1, 30), jnp.int32), 10)
    with pytest.raises(ValueError, match="pool has"):
        # needs ceil(24/4)=6 blocks > 3 usable: would deadlock
        srv.submit(jnp.zeros((1, 12), jnp.int32), 12)

    from defer_tpu.models.llama import mistral_config
    from defer_tpu.models.gpt import GptDecoder

    rolling = GptDecoder(
        mistral_config(
            num_layers=2, dim=32, num_heads=4, num_kv_heads=2,
            ffn_dim=64, vocab_size=64, max_len=32, window=8,
        ),
        rolling_cache=True,
    )
    with pytest.raises(ValueError, match="rolling"):
        PagedDecodeServer(
            rolling, rolling.init(jax.random.key(1)),
            num_blocks=4, block_size=4,
        )


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_paged_per_request_sampling_matches_solo(family):
    """Per-request sampling over the paged pool: every slot's output
    must be bit-identical to solo generate with the same seed while
    sharing ticks with other policies and a greedy neighbor."""
    from defer_tpu.models.gpt import SamplingParams

    dec = tiny_gpt(64) if family == "gpt" else tiny_llama(64)
    params = dec.init(jax.random.key(0))
    reqs = _requests(dec.cfg.vocab_size)[:4]
    samps = [
        SamplingParams(temperature=0.8, top_k=20, seed=7),
        None,
        SamplingParams(temperature=1.3, top_p=0.9, min_p=0.05, seed=42),
        SamplingParams(temperature=1.0, seed=5),
    ]
    outs, _ = serve_paged(
        dec, params, reqs, num_blocks=40, block_size=8,
        max_batch=2, sampling=samps,
    )
    for (prompt, steps), sp, got in zip(reqs, samps, outs):
        if sp is None:
            want = dec.generate(params, prompt, steps)
        else:
            want = dec.generate(
                params, prompt, steps, temperature=sp.temperature,
                top_k=sp.top_k, top_p=sp.top_p, min_p=sp.min_p,
                rng=jax.random.key(sp.seed),
            )
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want),
            err_msg=f"{family} sampling={sp}",
        )


def test_paged_stop_sequence_frees_blocks_mid_budget():
    """The paged server's stop-sequence path: the request terminates
    the moment its tail matches, its blocks return to the pool, and
    the output equals the unstopped stream truncated at the match."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    prompt = jnp.asarray([[3, 9, 27]], jnp.int32)
    full = np.asarray(dec.generate(params, prompt, 12))[0]
    stop = [int(full[3 + 5]), int(full[3 + 6])]
    srv = PagedDecodeServer(
        dec, params, num_blocks=20, block_size=8, max_batch=2
    )
    r = srv.submit(prompt, 12, stop=[stop])
    done = srv.run()
    got = np.asarray(done[r])[0]
    assert len(got) == 3 + 7, got
    assert list(got[-2:]) == stop
    np.testing.assert_array_equal(got, full[: len(got)])
    assert srv.blocks_in_use == 0 and len(srv.free) == 19


def test_radix_prefix_cache_shares_common_blocks():
    """VERDICT r4 #6 done-criterion: two concurrently-active requests
    with a common 2-block prefix occupy common + own blocks (peak 5,
    not 7, here), refcounts park the shared blocks at 0 when both
    finish, and outputs stay bit-identical to solo."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    bs = 8
    common = jax.random.randint(jax.random.key(2), (1, 16), 0, 128)
    pA = jnp.concatenate(
        [common, jnp.asarray([[7, 3]], jnp.int32)], axis=1
    )
    pB = jnp.concatenate(
        [common, jnp.asarray([[9, 1, 4]], jnp.int32)], axis=1
    )
    srv = PagedDecodeServer(
        dec, params, num_blocks=20, block_size=bs, max_batch=2,
        prefix_cache=True,
    )
    rA = srv.submit(pA, 6)
    rB = srv.submit(pB, 6)
    done = srv.run()
    for p, r in ((pA, rA), (pB, rB)):
        np.testing.assert_array_equal(
            np.asarray(done[r]), np.asarray(dec.generate(params, p, 6))
        )
    # A: ceil(24/8)=3 blocks, B: ceil(25/8)=4, sharing the 2 common.
    assert srv.blocks_peak == 5
    # B's admission skipped the 2 hit blocks' prefill.
    assert srv.prefill_tokens_saved == 16
    # Refcounts drained: nothing held, both shared blocks parked.
    assert srv.blocks_in_use == 0
    assert srv.radix.cached_blocks == 2 and len(srv.radix.lru) == 2


def test_radix_parked_blocks_revive_for_later_requests():
    """Finished requests' shared blocks persist at refcount 0 and are
    revived by a later request with the same prefix — cross-request
    (not just concurrent) prefix caching."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    common = jax.random.randint(jax.random.key(2), (1, 16), 0, 128)
    srv = PagedDecodeServer(
        dec, params, num_blocks=20, block_size=8, max_batch=2,
        prefix_cache=True,
    )
    p1 = jnp.concatenate(
        [common, jnp.asarray([[7, 3]], jnp.int32)], axis=1
    )
    r1 = srv.submit(p1, 6)
    srv.run()
    saved_before = srv.prefill_tokens_saved
    p2 = jnp.concatenate(
        [common, jnp.asarray([[5]], jnp.int32)], axis=1
    )
    r2 = srv.submit(p2, 4)
    done = srv.run()
    np.testing.assert_array_equal(
        np.asarray(done[r2]),
        np.asarray(dec.generate(params, p2, 4)),
    )
    assert srv.prefill_tokens_saved == saved_before + 16


def test_radix_eviction_under_pool_pressure():
    """Parked refcount-0 blocks are reclaimed (LRU) only when the
    free list cannot cover an admission; outputs stay exact through
    eviction and re-registration."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    srv = PagedDecodeServer(
        dec, params, num_blocks=8, block_size=8, max_batch=1,
        prefix_cache=True,
    )
    q1 = jax.random.randint(jax.random.key(5), (1, 24), 0, 128)
    q2 = jax.random.randint(jax.random.key(6), (1, 24), 0, 128)
    for q in (q1, q2):
        r = srv.submit(q, 8)
        out = srv.run()[r]
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(dec.generate(params, q, 8))
        )
    assert srv.radix.cached_blocks == 6  # 3 full prompt blocks each
    # Needs 4 blocks, 2 hits on q1's parked prefix, 1 free -> evicts.
    r3 = srv.submit(q1[:, :20], 12)
    out3 = srv.run()[r3]
    np.testing.assert_array_equal(
        np.asarray(out3),
        np.asarray(dec.generate(params, q1[:, :20], 12)),
    )
    assert srv.radix.cached_blocks <= 6


def test_radix_composes_with_sampling_and_stop():
    """Radix sharing must not disturb per-request sampling streams or
    stop matching: a sampled request over a cached prefix reproduces
    its solo stream exactly."""
    from defer_tpu.models.gpt import SamplingParams

    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    common = jax.random.randint(jax.random.key(3), (1, 8), 0, 128)
    prompt = jnp.concatenate(
        [common, jnp.asarray([[4, 4]], jnp.int32)], axis=1
    )
    sp = SamplingParams(temperature=1.1, top_k=30, seed=9)
    srv = PagedDecodeServer(
        dec, params, num_blocks=20, block_size=8, max_batch=2,
        prefix_cache=True,
    )
    warm = srv.submit(common, 4)  # parks the common block
    srv.run()
    r = srv.submit(prompt, 8, sampling=sp)
    got = srv.run()[r]
    want = dec.generate(
        params, prompt, 8, temperature=sp.temperature, top_k=sp.top_k,
        rng=jax.random.key(sp.seed),
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert srv.prefill_tokens_saved >= 8


def test_radix_validation():
    dec = tiny_gpt(32)
    params = dec.init(jax.random.key(0))
    with pytest.raises(ValueError, match="subsumes"):
        PagedDecodeServer(
            dec, params, num_blocks=8, block_size=4,
            prefix_cache=True,
            prefix_ids=jnp.zeros((1, 4), jnp.int32),
        )


# -- PrefixBlockCache unit semantics (chained keys, invariants) -------


def test_prefix_cache_register_refuses_live_displacement():
    """Displacing a block that still has live references is an
    invariant violation (any active holder of the deeper chain should
    have made the key a hit) — register must raise, not corrupt the
    maps; at refcount 0 the displacement succeeds and hands the old
    block back for the free list."""
    from defer_tpu.runtime.paged import PrefixBlockCache

    c = PrefixBlockCache()
    bb = np.arange(4, dtype=np.int64).tobytes()
    key = PrefixBlockCache._hash(b"", bb)
    c.register(key, bb, 5)  # refcount 1, held by the registrant
    with pytest.raises(RuntimeError, match="live reference"):
        c.register(key, bb, 7)
    c.release(5)  # parks block 5 at refcount 0
    assert c.register(key, bb, 7) == 5
    assert c.by_key[key] == 7 and c.ref[7] == 1 and 5 not in c.ref


def test_prefix_cache_collision_guard(monkeypatch):
    """Force every chained digest to collide: a walk over DIFFERENT
    tokens must still miss (the own-block byte compare), and the
    genuine tokens must still hit."""
    from defer_tpu.runtime.paged import PrefixBlockCache

    monkeypatch.setattr(
        PrefixBlockCache, "_hash", staticmethod(lambda prev, bb: b"X")
    )
    c = PrefixBlockCache()
    t1 = np.asarray([1, 2, 3, 4], np.int64)
    t2 = np.asarray([9, 9, 9, 9], np.int64)
    hits, keys, toks = c.walk(t1, 1, 4)
    assert hits == []
    c.register(keys[0], toks[0], 3)
    assert c.walk(t2, 1, 4)[0] == []  # digest equal, bytes differ
    c.release(3)
    assert c.walk(t1, 1, 4)[0] == [3]  # true match hits (and revives)


def test_prefix_cache_keys_encode_ancestry():
    """Chained keys depend on the whole ancestry, not just the
    block's own tokens: block 1 of one prompt never aliases block 0
    of another even with identical own-token bytes, while a shared
    leading block keys identically from either prompt."""
    from defer_tpu.runtime.paged import PrefixBlockCache

    c = PrefixBlockCache()
    a = np.asarray([1, 2, 3, 4, 5, 6, 7, 8], np.int64)
    b = np.asarray([5, 6, 7, 8], np.int64)  # == a's second block
    _, ka, ta = c.walk(a, 2, 4)
    _, kb, tb = c.walk(b, 1, 4)
    assert ta[1] == tb[0]  # same own bytes ...
    assert ka[1] != kb[0]  # ... different ancestry, different key
    assert ka[0] == c.walk(a[:4], 1, 4)[1][0]  # prefix-stable
