"""Package CLI surface (`python -m defer_tpu`)."""

import json

import pytest

from defer_tpu.__main__ import main


def test_info(capsys):
    main(["info"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["topology"]["num_devices"] >= 1
    assert "resnet50" in doc["models"] and "vit_b16" in doc["models"]
    assert doc["num_ops"] > 20


def test_partition_auto(capsys):
    main(["partition", "resnet50", "--stages", "4", "--auto"])
    out = capsys.readouterr().out
    assert "4 stages" in out and "stage 3" in out
    # FLOPs-balanced: no stage above 35% of the model.
    shares = [
        float(line.rsplit("(", 1)[1].rstrip("%)\n"))
        for line in out.splitlines()
        if line.strip().startswith("stage")
    ]
    assert len(shares) == 4 and max(shares) < 35.0


def test_roofline_cli(capsys):
    main(
        [
            "roofline",
            "vit_tiny",
            "--batch",
            "8",
            "--device-kind",
            "TPU v5 lite",
            "--top",
            "2",
        ]
    )
    out = capsys.readouterr().out
    # vit_tiny at batch 8 is tiny — top nodes are its dense layers.
    assert "roofline[TPU v5 lite]" in out and "bound:" in out


def test_unknown_command_rejected():
    with pytest.raises(SystemExit):
        main(["frobnicate"])
