"""ViT family: build, correctness through the pipeline runtimes, and
parameter-count sanity (beyond-reference zoo entry — the reference zoo
is CNN-only, reference src/test.py:23)."""

import jax
import jax.numpy as jnp
import numpy as np

from defer_tpu.config import DeferConfig
from defer_tpu.graph.partition import partition, validate_cut_points
from defer_tpu.models import get_model
from defer_tpu.parallel.pipeline import Pipeline

F32 = DeferConfig(compute_dtype=jnp.float32)


def test_vit_b16_builds_with_expected_shapes():
    model = get_model("vit_b16")
    assert model.input_shape == (224, 224, 3)
    params = model.graph.init(jax.random.key(0), (1, 224, 224, 3))
    spec = model.graph.output_spec(params, (1, 224, 224, 3))
    assert spec.shape == (1, 1000)
    # Published ViT-B/16 size: ~86M params.
    n = sum(
        a.size
        for node in params.values()
        for a in jax.tree_util.tree_leaves(node)
    )
    assert 85e6 < n < 88e6, f"ViT-B/16 param count {n / 1e6:.1f}M"
    # Patch embedding really is a 16x16/s16 conv onto 768 channels.
    assert params["patch_embed"]["kernel"].shape == (16, 16, 3, 768)
    for k in (2, 4, 6):
        cuts = model.default_cuts(k)
        assert len(cuts) == k - 1
        validate_cut_points(model.graph, cuts)


def test_vit_tiny_forward_and_cls_token():
    model = get_model("vit_tiny")
    params = model.graph.init(jax.random.key(0), (2, 32, 32, 3))
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    out = model.graph.apply(params, x)
    assert out.shape == (2, 10)
    # 4x4 grid of 8x8 patches + [class] token = 17 tokens.
    assert params["position_embedding"]["table"].shape[0] == 17
    # The class token actually participates: zeroing it changes the
    # head output (it is the only token the head reads).
    params2 = {
        k: (
            {"token": jnp.zeros_like(v["token"])}
            if k == "class_token"
            else v
        )
        for k, v in params.items()
    }
    out2 = model.graph.apply(params2, x)
    assert not np.allclose(np.asarray(out), np.asarray(out2))


def test_vit_pipeline_composes_across_devices(devices):
    """Block-boundary cuts through the heterogeneous pipeline: composed
    stages == single jit, with attention inside the stages."""
    model = get_model("vit_tiny")
    params = model.graph.init(jax.random.key(0), (2, 32, 32, 3))
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    want = jax.jit(model.graph.apply)(params, x)
    cuts = model.default_cuts(4)
    stages = partition(model.graph, cuts)
    pipe = Pipeline(stages, params, devices[:4], config=F32)
    got = pipe.warmup(x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5
    )


def test_vit_auto_partition_balances():
    """partition_layers='auto' path: FLOPs-balanced cuts from the block
    candidates (uniform blocks -> roughly uniform stages)."""
    from defer_tpu.utils.flops import balanced_cuts, flops_by_node

    model = get_model("vit_tiny")
    params = model.graph.init(jax.random.key(0), (1, 32, 32, 3))
    cuts = balanced_cuts(
        model.graph, params, (1, 32, 32, 3), 2, model.cut_candidates
    )
    assert len(cuts) == 1
    per = flops_by_node(model.graph, params, (1, 32, 32, 3))
    stages = partition(model.graph, cuts)
    loads = [
        sum(per[n.name] for n in s.nodes if n.op != "input") for s in stages
    ]
    assert max(loads) / max(min(loads), 1.0) < 1.6


def test_vit_mha_flops_counted():
    """mha nodes must contribute their matmul FLOPs, not 1/elem."""
    from defer_tpu.utils.flops import flops_by_node

    model = get_model("vit_tiny")
    params = model.graph.init(jax.random.key(0), (1, 32, 32, 3))
    per = flops_by_node(model.graph, params, (1, 32, 32, 3))
    s, d = 17, 64
    want = 8 * s * d * d + 4 * s * s * d
    assert per["block_0_mha"] == want
