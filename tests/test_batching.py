"""Dynamic batching: the batch-1 queue contract on top of real device
batches (reference streams single frames, reference src/test.py:52-54;
the TPU wants batch 256)."""

import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.api import DEFER
from defer_tpu.config import DeferConfig
from defer_tpu.runtime.batching import BatchGatherer, split_output
from defer_tpu.runtime.host_io import STOP
from tests.test_partition import residual_chain


def test_gatherer_fills_a_batch():
    q: "queue.Queue" = queue.Queue()
    for i in range(4):
        q.put(jnp.full((1, 8), float(i)))
    g = BatchGatherer(batch_size=4, max_wait_s=5.0)
    batch, sizes, eos = g.gather(q)
    assert batch.shape == (4, 8) and sizes == [1, 1, 1, 1] and not eos
    assert [float(batch[i, 0]) for i in range(4)] == [0.0, 1.0, 2.0, 3.0]


def test_gatherer_slo_flushes_partial_batch():
    q: "queue.Queue" = queue.Queue()
    q.put(jnp.ones((2, 8)))
    g = BatchGatherer(batch_size=64, max_wait_s=0.05)
    batch, sizes, eos = g.gather(q)
    assert batch.shape == (2, 8) and sizes == [2] and not eos


def test_gatherer_idle_and_sentinel():
    q: "queue.Queue" = queue.Queue()
    g = BatchGatherer(batch_size=4, max_wait_s=0.01)
    assert g.gather(q, poll_s=0.01) == (None, None, False)
    q.put(STOP)
    assert g.gather(q) == (None, None, True)
    q.put(None)
    assert g.gather(q) == (None, None, True)


def test_gatherer_sentinel_mid_batch_flushes():
    q: "queue.Queue" = queue.Queue()
    q.put(jnp.ones((1, 8)))
    q.put(jnp.ones((1, 8)) * 2)
    q.put(None)
    g = BatchGatherer(batch_size=8, max_wait_s=5.0)
    batch, sizes, eos = g.gather(q)
    assert batch.shape == (2, 8) and sizes == [1, 1] and eos


def test_gatherer_mismatch_carries():
    q: "queue.Queue" = queue.Queue()
    q.put(jnp.ones((1, 8)))
    q.put(jnp.ones((1, 16)))  # different trailing shape
    g = BatchGatherer(batch_size=4, max_wait_s=0.2)
    b1, s1, _ = g.gather(q)
    assert b1.shape == (1, 8) and s1 == [1]
    assert g.pending()
    b2, s2, _ = g.gather(q)
    assert b2.shape == (1, 16) and s2 == [1]
    assert not g.pending()


def test_gatherer_varying_item_batch_dims():
    q: "queue.Queue" = queue.Queue()
    q.put(jnp.ones((2, 8)))
    q.put(jnp.full((3, 8), 2.0))
    g = BatchGatherer(batch_size=8, max_wait_s=0.2)
    batch, sizes, _ = g.gather(q)
    # total 5 pads up to the 8 bucket; sizes still sum to the real 5.
    assert batch.shape == (8, 8) and sizes == [2, 3]
    parts = split_output(batch, sizes)
    assert parts[0].shape == (2, 8) and parts[1].shape == (3, 8)
    assert float(parts[1][0, 0]) == 2.0


def test_gatherer_rejects_degenerate_size():
    with pytest.raises(ValueError, match="batch_size >= 2"):
        BatchGatherer(batch_size=1, max_wait_s=0.1)


def test_run_defer_dynamic_batching_end_to_end(devices, monkeypatch):
    """20 batch-1 items through run_defer with dynamic_batch_size=4:
    per-item outputs in order with correct values, and the device saw
    FEWER dispatches than items (batching actually happened)."""
    from defer_tpu.parallel.pipeline import Pipeline

    dispatch_batches = []
    orig_submit = Pipeline.submit

    def counting_submit(self, x):
        dispatch_batches.append(int(x.shape[0]))
        return orig_submit(self, x)

    monkeypatch.setattr(Pipeline, "submit", counting_submit)

    g = residual_chain()
    params = g.init(jax.random.key(0), (1, 8))
    cfg = DeferConfig(
        compute_dtype=jnp.float32, dynamic_batch_size=4, batch_wait_s=0.2
    )
    defer = DEFER(config=cfg)
    inq: "queue.Queue" = queue.Queue()
    outq: "queue.Queue" = queue.Queue()
    xs = [jnp.full((1, 8), float(i)) for i in range(20)]
    # Pre-fill before starting so the gatherer sees full batches.
    for x in xs:
        inq.put(x)
    inq.put(None)
    t = threading.Thread(
        target=defer.run_defer,
        args=(g, ["add_1"], inq, outq),
        kwargs={"params": params},
        daemon=True,
    )
    t.start()
    outs = [outq.get(timeout=120) for _ in range(20)]
    t.join(timeout=120)
    assert not t.is_alive()
    for x, out in zip(xs, outs):
        assert out.shape == (1, g.apply(params, x).shape[-1])
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(g.apply(params, x)), rtol=1e-5
        )
    assert len(dispatch_batches) < 20, dispatch_batches
    assert max(dispatch_batches) == 4, dispatch_batches


def test_gatherer_pads_partial_batches_to_buckets():
    """Bursty partial flushes must land on power-of-two buckets so the
    jitted stages see a bounded set of leading dims (each distinct size
    is a full recompile)."""
    q: "queue.Queue" = queue.Queue()
    for _ in range(3):
        q.put(jnp.ones((1, 8)))
    g = BatchGatherer(batch_size=64, max_wait_s=0.05)
    batch, sizes, _ = g.gather(q)
    assert sizes == [1, 1, 1]
    assert batch.shape == (4, 8)  # padded 3 -> 4
    parts = split_output(batch, sizes)
    assert len(parts) == 3 and all(p.shape == (1, 8) for p in parts)
    # A full batch is not padded.
    for _ in range(4):
        q.put(jnp.ones((16, 8)))
    g2 = BatchGatherer(batch_size=64, max_wait_s=1.0)
    b2, s2, _ = g2.gather(q)
    assert b2.shape == (64, 8) and s2 == [16, 16, 16, 16]


def test_gatherer_rejects_scalar_items():
    q: "queue.Queue" = queue.Queue()
    q.put(jnp.float32(3.0))
    g = BatchGatherer(batch_size=4, max_wait_s=0.01)
    with pytest.raises(ValueError, match="leading"):
        g.gather(q)


def test_transport_quantize_non_finite_falls_back_lossless():
    import numpy as onp

    from defer_tpu.runtime.transport import ArrayReceiver, ArraySender

    recv = ArrayReceiver(port=0)
    got = []
    t = threading.Thread(target=lambda: got.extend(recv), daemon=True)
    t.start()
    snd = ArraySender("127.0.0.1", recv.port, quantize="int8")
    bad = onp.array([1.0, onp.inf, onp.nan], onp.float32)
    snd.send(bad)
    snd.close()
    t.join(timeout=30)
    assert not t.is_alive() and len(got) == 1
    onp.testing.assert_array_equal(got[0], bad)  # lossless, NaN/Inf kept


def test_single_padded_item_does_not_leak_pad_rows():
    """A lone (3, C) item padded to the 4-bucket must come back as
    (3, C) — pad rows are garbage, not results."""
    q: "queue.Queue" = queue.Queue()
    q.put(jnp.ones((3, 8)))
    g = BatchGatherer(batch_size=64, max_wait_s=0.05)
    batch, sizes, _ = g.gather(q)
    assert batch.shape == (4, 8) and sizes == [3]
    parts = split_output(batch, sizes)
    assert len(parts) == 1 and parts[0].shape == (3, 8)


def test_gather_bounds_rows_not_item_count():
    """batch_size caps device ROWS: (3, C) items with batch_size=8 stop
    at 2 items (6 rows; a third would overflow) and the overflow item
    carries to the next batch."""
    q: "queue.Queue" = queue.Queue()
    for _ in range(3):
        q.put(jnp.ones((3, 8)))
    g = BatchGatherer(batch_size=8, max_wait_s=1.0)
    b1, s1, _ = g.gather(q)
    assert s1 == [3, 3]
    assert b1.shape == (8, 8)  # 6 rows padded to the 8 bucket
    assert g.pending()
    b2, s2, _ = g.gather(q)
    assert s2 == [3] and b2.shape == (4, 8)


def test_deadline_budget_machinery():
    """Deadline is the shared monotonic budget both the gatherer's
    flush SLO and fleet admission's enqueue wait run on: remaining
    shrinks, elapsed grows, expiry is a one-way door."""
    import time

    from defer_tpu.runtime.batching import Deadline

    dl = Deadline(0.05)
    assert not dl.expired()
    r0 = dl.remaining()
    assert 0 < r0 <= 0.05
    time.sleep(0.06)
    assert dl.expired()
    assert dl.remaining() <= 0
    assert dl.elapsed() >= 0.06


def test_poisson_arrivals_deterministic_open_loop():
    """The open-loop arrival trace the mixed-serving bench replays
    (scripts/bench_paged.py --mixed-sweep): seeded, non-decreasing,
    anchored at t=0, mean gap ~ 1/rate — and the same (n, rate, seed)
    is bit-identical on every call, so a sweep's budgets all face the
    SAME offered load."""
    from defer_tpu.runtime.batching import poisson_arrivals

    a = poisson_arrivals(500, rate=20.0, seed=3)
    b = poisson_arrivals(500, rate=20.0, seed=3)
    assert np.array_equal(a, b)
    assert a[0] == 0.0
    assert np.all(np.diff(a) >= 0)
    gaps = np.diff(a)
    assert 0.5 / 20.0 < gaps.mean() < 2.0 / 20.0  # ~1/rate
    assert not np.array_equal(a, poisson_arrivals(500, 20.0, seed=4))
    with pytest.raises(ValueError, match="rate"):
        poisson_arrivals(5, rate=0.0)
    with pytest.raises(ValueError, match="arrivals"):
        poisson_arrivals(0, rate=1.0)
