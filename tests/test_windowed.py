"""Fused multi-token decode windows (`decode_window=K`): K>1 must be
token-identical to the K=1 tick-per-token loop in BOTH servers, across
attention paths, prefix caching, mixed greedy+sampled slots, eos
mid-window, stop sequences, and streaming — while issuing ~1/K the
host dispatches. Plus the trace-stability contract: a warmed windowed
`_tick` lowers nothing new.

Parity argument being pinned (runtime/decode_server.py /
runtime/paged.py `_build_window`): the window scans the SAME raw step
body the K=1 tick jits, pins positions with the same sub-step-start
active mask, and draws from the same per-slot key schedule — so every
accepted token is the token K=1 would have produced, and overshoot
past eos/budget/stop is discarded before it can reach outputs or the
stop-match history.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu import obs
from defer_tpu.models.gpt import SamplingParams, tiny_gpt
from defer_tpu.models.llama import tiny_llama
from defer_tpu.runtime.decode_server import DecodeServer, serve_greedy
from defer_tpu.runtime.paged import PagedDecodeServer, serve_paged


def _mixed_requests(vocab, rng_seed=5):
    """Same shape as test_paged_attention's mix: shared 16-token
    prefix on the first two (prefix_cache shares blocks), lengths
    straddling block boundaries, 5 requests through 2 slots so
    finish/re-admit happens mid-run — at K>1, at window boundaries."""
    rng = np.random.default_rng(rng_seed)
    base = jnp.asarray(
        rng.integers(1, vocab, size=(1, 18)), jnp.int32
    )
    ext = jnp.asarray(rng.integers(1, vocab, size=(1, 5)), jnp.int32)
    return [
        (base, 6),
        (jnp.concatenate([base, ext], axis=1), 5),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 3)), jnp.int32), 7),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 9)), jnp.int32), 4),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 2)), jnp.int32), 3),
    ]


_MIXED_SAMPLING = [
    None,
    SamplingParams(temperature=0.9, seed=3),
    SamplingParams(temperature=1.2, top_k=5, seed=11),
    None,
    SamplingParams(temperature=1.0, top_p=0.9, seed=2),
]


@pytest.fixture(scope="module")
def llama():
    dec = tiny_llama(64)
    return dec, dec.init(jax.random.key(0))


@pytest.fixture(scope="module")
def gpt():
    dec = tiny_gpt(64)
    return dec, dec.init(jax.random.key(0))


def _serve(dec, params, reqs, **kw):
    outs, stats = serve_paged(
        dec, params, reqs,
        num_blocks=18, block_size=4, max_batch=2,
        sampling=_MIXED_SAMPLING, **kw,
    )
    return [np.asarray(o) for o in outs], stats


# -- paged parity matrix ----------------------------------------------


@pytest.mark.parametrize("attention", ["gathered", "blockwise"])
@pytest.mark.parametrize("prefix_cache", [False, True])
@pytest.mark.parametrize("K", [4, 8])
def test_paged_window_parity_matrix(llama, attention, prefix_cache, K):
    """decode_window=K is token-identical to K=1 across attention
    paths x prefix-cache on/off, with mixed greedy+sampled slots and
    mid-run finish/re-admit, at ~1/K the host dispatches."""
    dec, params = llama
    reqs = _mixed_requests(dec.cfg.vocab_size)
    want, base = _serve(
        dec, params, reqs,
        attention=attention, prefix_cache=prefix_cache,
    )
    got, stats = _serve(
        dec, params, reqs,
        attention=attention, prefix_cache=prefix_cache,
        decode_window=K,
    )
    for i, (w, g) in enumerate(zip(want, got)):
        assert w.shape == g.shape, f"req {i}: {w.shape} vs {g.shape}"
        assert (w == g).all(), f"req {i} diverged at K={K}"
    assert stats["decode_window"] == K
    assert stats["host_dispatches"] < base["host_dispatches"]
    # Each dispatch must be accepting multiple tokens on average.
    assert stats["tokens_per_dispatch"] > base["tokens_per_dispatch"]


# -- flat server -------------------------------------------------------


@pytest.mark.parametrize("K", [4, 8])
def test_flat_window_parity(gpt, K):
    """Flat-server twin of the paged matrix: mixed greedy+sampled
    requests, bit-identical outputs, fewer dispatches."""
    dec, params = gpt
    reqs = _mixed_requests(dec.cfg.vocab_size)
    want, base = serve_greedy(
        dec, params, reqs, max_batch=2, sampling=_MIXED_SAMPLING,
    )
    got, stats = serve_greedy(
        dec, params, reqs, max_batch=2, sampling=_MIXED_SAMPLING,
        decode_window=K,
    )
    for w, g in zip(want, got):
        assert w.shape == g.shape
        assert (np.asarray(w) == np.asarray(g)).all()
    assert stats["host_dispatches"] < base["host_dispatches"]


def test_flat_window_prefix_cache_parity(gpt):
    """Windowed decode composes with the flat server's shared-prefix
    cache (suffix-only admissions feed the same window step)."""
    dec, params = gpt
    prefix = jnp.asarray([[9, 4, 2, 6, 1, 3, 8, 5]], jnp.int32)
    reqs = _mixed_requests(dec.cfg.vocab_size)[:3]
    want, _ = serve_greedy(
        dec, params, reqs, max_batch=2, prefix_ids=prefix,
    )
    got, _ = serve_greedy(
        dec, params, reqs, max_batch=2, prefix_ids=prefix,
        decode_window=4,
    )
    for w, g in zip(want, got):
        assert (np.asarray(w) == np.asarray(g)).all()


def test_decode_window_validation(gpt):
    dec, params = gpt
    with pytest.raises(ValueError, match="decode_window"):
        DecodeServer(dec, params, decode_window=0)
    with pytest.raises(ValueError, match="decode_window"):
        PagedDecodeServer(
            dec, params, num_blocks=12, block_size=4,
            decode_window=-1,
        )


# -- eos mid-window ----------------------------------------------------


def _harvest_eos(outs, reqs, gen_index=2):
    """A token some request actually generates mid-stream, to use as
    eos: re-serving with it forces a mid-window finish (deterministic
    — same seeds, same tokens)."""
    for (prompt, steps), o in zip(reqs, outs):
        t0 = prompt.shape[1]
        gen = np.asarray(o)[0, t0:]
        if len(gen) > gen_index:
            return int(gen[gen_index])
    raise AssertionError("no request generated enough tokens")


@pytest.mark.parametrize("server", ["flat", "paged"])
def test_eos_mid_window_truncates(gpt, server):
    """A request hitting eos mid-window freezes on device: outputs
    end with the eos exactly as at K=1 (overshoot discarded), and the
    truncation counter records the cut windows."""
    dec, params = gpt
    reqs = _mixed_requests(dec.cfg.vocab_size)

    def run(**kw):
        if server == "flat":
            return serve_greedy(dec, params, reqs, max_batch=2, **kw)
        return serve_paged(
            dec, params, reqs,
            num_blocks=18, block_size=4, max_batch=2, **kw,
        )

    plain, _ = run()
    eos = _harvest_eos(plain, reqs)
    want, _ = run(eos_id=eos)
    with obs.counter_deltas() as d:
        got, stats = run(eos_id=eos, decode_window=4)
    for w, g in zip(want, got):
        assert w.shape == g.shape
        assert (np.asarray(w) == np.asarray(g)).all()
    lab = f'server="{server}"'
    assert d.get(f"defer_window_truncated_total{{{lab}}}", 0) > 0


# -- stop sequences across windows ------------------------------------


@pytest.mark.parametrize("server", ["flat", "paged"])
def test_stop_sequence_window_parity(gpt, server):
    """Stop matching stays host-side: the window overshoots past the
    match, the drain truncates at it, and discarded overshoot never
    enters the match history — outputs identical to K=1."""
    dec, params = gpt
    reqs = _mixed_requests(dec.cfg.vocab_size)

    def run(stop, K):
        outs = []
        if server == "flat":
            srv = DecodeServer(
                dec, params, max_batch=2, decode_window=K,
            )
        else:
            srv = PagedDecodeServer(
                dec, params, num_blocks=18, block_size=4,
                max_batch=2, decode_window=K,
            )
        rids = [
            srv.submit(p, s, stop=stop) for p, s in reqs
        ]
        done = srv.run()
        return [np.asarray(done[r]) for r in rids]

    plain = run(None, 1)
    # A 2-token subsequence one request actually generates — every
    # run sharing it must stop there, mid-budget, whatever K is.
    p0, _ = reqs[0]
    gen = plain[0][0, p0.shape[1]:]
    assert len(gen) >= 3
    stop = [[int(gen[1]), int(gen[2])]]
    want = run(stop, 1)
    got = run(stop, 4)
    for w, g in zip(want, got):
        assert w.shape == g.shape
        assert (w == g).all()


# -- streaming ---------------------------------------------------------


def test_streaming_per_request_order_preserved(gpt):
    """on_token consumers see each request's tokens in order with
    done on the last — and within a window, tick-major interleaving
    (all slots' sub-step t before any slot's t+1), the K=1 order."""
    dec, params = gpt
    reqs = _mixed_requests(dec.cfg.vocab_size)

    def run(K):
        events = []
        srv = DecodeServer(
            dec, params, max_batch=2, decode_window=K,
            on_token=lambda rid, tok, done: events.append(
                (rid, tok, done)
            ),
        )
        rids = [srv.submit(p, s) for p, s in reqs]
        done = srv.run()
        return events, rids, done

    ev1, rids1, _ = run(1)
    evK, ridsK, doneK = run(4)

    def per_rid(events, rids):
        out = {r: [] for r in rids}
        for rid, tok, done in events:
            out[rid].append((tok, done))
        return out

    m1, mK = per_rid(ev1, rids1), per_rid(evK, ridsK)
    for r1, rK in zip(rids1, ridsK):
        assert m1[r1] == mK[rK]
        assert mK[rK][-1][1] is True  # done fires on the last token
    # Streamed tokens match the returned arrays (generated region).
    for (prompt, _), rK in zip(reqs, ridsK):
        t0 = prompt.shape[1]
        streamed = [t for t, _ in mK[rK]]
        assert streamed == np.asarray(doneK[rK])[0, t0:].tolist()


# -- trace stability ---------------------------------------------------


def test_windowed_tick_trace_stable_after_warmup(gpt):
    """The windowed `_tick` keeps the paged server's trace-stability
    contract: 3 post-warmup windows lower nothing new in any jitted
    callable the server or decoder holds (the window program is
    memoized on the decoder, where the sanitizer auto-watches it)."""
    from defer_tpu.analysis import trace_sanitizer as sanitize

    dec, params = gpt
    srv = PagedDecodeServer(
        dec, params, num_blocks=16, block_size=4, max_batch=2,
        decode_window=4,
    )
    srv.submit(jnp.asarray([[3, 9, 27]], jnp.int32), 25)
    srv.submit(jnp.asarray([[5, 1]], jnp.int32), 24)
    srv._admit()
    for _ in range(2):  # warmup: first window compiles the scan
        srv._tick()
    with sanitize(srv, dec) as rep:
        for _ in range(3):
            srv._tick()
    assert rep.retraces == 0
    assert rep.watched
