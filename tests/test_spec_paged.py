"""Paged-native speculative decoding + pool-native chunked prefill.

Correctness contract (runtime/paged.py `spec_k` docstring): greedy
output is BIT-IDENTICAL to `serve_paged` at spec_k=0 — the verify
forward's row 0 re-derives the target's own argmax chain, proposals
only ever shorten the number of forwards, never change a token.
Sampled slots ride the verify forward's first row through the same
SlotSampler key stream as spec_k=0, so sampled streams match too.
The chunked pool-native prefill path (`prefill_chunk`) must likewise
be invisible in the tokens while its `defer_kv_rows_*` accounting
scales with the prompt's live blocks, never with pool size.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu import obs
from defer_tpu.models.gpt import SamplingParams, tiny_gpt
from defer_tpu.runtime.paged import PagedDecodeServer, serve_paged


@pytest.fixture(scope="module")
def model():
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    return dec, params


@pytest.fixture(scope="module")
def divergent_draft():
    """Same architecture, different weights: proposals disagree with
    the target almost immediately, driving acceptance toward 0 — the
    rejection/rewrite path gets exercised every round."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(7))
    return dec, params


def _mixed_requests(vocab):
    """Shared 8-token prefix on the first two (radix hits when
    prefix_cache=True), lengths straddling block boundaries, one
    single-token prompt."""
    rng = np.random.default_rng(11)
    base = jnp.asarray(rng.integers(1, vocab, size=(1, 8)), jnp.int32)
    ext = jnp.asarray(rng.integers(1, vocab, size=(1, 3)), jnp.int32)
    return [
        (base, 6),
        (jnp.concatenate([base, ext], axis=1), 5),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 1)), jnp.int32), 7),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 5)), jnp.int32), 4),
    ]


def _mixed_sampling():
    """Two greedy slots, two sampled — speculative rounds must carry
    both kinds at once (sampled rows keep only verify row 0)."""
    return [
        None,
        SamplingParams(temperature=0.9, seed=13),
        None,
        SamplingParams(temperature=1.0, top_k=8, seed=5),
    ]


@pytest.fixture(scope="module")
def baseline(model):
    """spec_k=0 reference outputs, one per prefix_cache setting."""
    dec, params = model
    reqs = _mixed_requests(dec.cfg.vocab_size)
    out = {}
    for pc in (False, True):
        outs, _ = serve_paged(
            dec, params, reqs, num_blocks=24, block_size=8,
            max_batch=2, sampling=_mixed_sampling(), prefix_cache=pc,
        )
        out[pc] = outs
    return out


@pytest.mark.parametrize("prefix_cache", [False, True])
@pytest.mark.parametrize(
    "attention", ["gathered", "blockwise", "pallas"]
)
@pytest.mark.parametrize("k", [2, 4])
def test_spec_parity_matrix(model, baseline, k, attention, prefix_cache):
    """The acceptance criterion: every k/attention/prefix_cache combo,
    with greedy and sampled slots mixed in one batch, emits exactly
    the spec_k=0 token streams (self-draft, so full-accept rounds and
    the bonus-row path dominate)."""
    dec, params = model
    reqs = _mixed_requests(dec.cfg.vocab_size)
    outs, stats = serve_paged(
        dec, params, reqs, num_blocks=24, block_size=8, max_batch=2,
        sampling=_mixed_sampling(), prefix_cache=prefix_cache,
        attention=attention,
        spec_draft=dec, spec_params=params, spec_k=k,
    )
    for want, got, (p, _) in zip(baseline[prefix_cache], outs, reqs):
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want),
            err_msg=(
                f"k={k} attention={attention} prefix_cache="
                f"{prefix_cache} prompt={np.asarray(p)}"
            ),
        )
    assert stats["spec_k"] == k
    assert stats["spec_rounds"] > 0


def test_spec_rejections_still_match(model, divergent_draft, baseline):
    """A draft that disagrees with the target (acceptance ~0) changes
    only the round count, never a token: every rejected row is
    replaced by the target's own choice."""
    dec, params = model
    draft, dparams = divergent_draft
    reqs = _mixed_requests(dec.cfg.vocab_size)
    outs, stats = serve_paged(
        dec, params, reqs, num_blocks=24, block_size=8, max_batch=2,
        sampling=_mixed_sampling(),
        spec_draft=draft, spec_params=dparams, spec_k=3,
    )
    for want, got in zip(baseline[False], outs):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # Greedy slots proposed every round; a divergent tiny model
    # rarely guesses the target's argmax, so acceptance sits low.
    assert stats["spec_proposed"] > 0
    assert stats["spec_acceptance"] < 0.5


def test_spec_acceptance_stats_and_dispatch_amortization(model):
    """Self-draft: every proposal accepted (acceptance == 1.0), so
    each two-dispatch round commits k+1 tokens per greedy slot —
    strictly fewer host dispatches than one-per-token serving. The
    defer_spec_* counters must agree with the stats fields."""
    dec, params = model
    reqs = [(jnp.asarray([[3, 9, 27]], jnp.int32), 9)]
    with obs.counter_deltas() as d:
        outs, stats = serve_paged(
            dec, params, reqs, num_blocks=16, block_size=8,
            max_batch=2, spec_draft=dec, spec_params=params, spec_k=4,
        )
    assert stats["spec_acceptance"] == 1.0
    assert stats["spec_accepted"] == stats["spec_proposed"] > 0
    # 9 generated tokens: 1 at admission + 8 from ceil(8/5)=2 rounds.
    assert stats["spec_rounds"] == 2
    assert stats["host_dispatches"] == 2 * stats["spec_rounds"]
    assert stats["host_dispatches"] < 8  # beats one dispatch/token
    assert (
        d.get('defer_spec_rounds_total{server="paged"}', 0)
        == stats["spec_rounds"]
    )
    assert (
        d.get('defer_spec_proposed_total{server="paged"}', 0)
        == stats["spec_proposed"]
    )
    assert (
        d.get('defer_spec_accepted_total{server="paged"}', 0)
        == stats["spec_accepted"]
    )


def test_spec_eos_and_stop_mid_round(model):
    """A terminator inside a speculative window truncates exactly
    where the sequential loop stops: eos ends the output WITH the eos
    token; a stop sequence ends it at the sequence's last token."""
    dec, params = model
    req = (jnp.asarray([[11, 2, 8, 1, 6]], jnp.int32), 9)
    base, _ = serve_paged(
        dec, params, [req], num_blocks=16, block_size=8, max_batch=1
    )
    toks = np.asarray(base[0])[0]
    t0 = req[0].shape[1]
    eos = int(toks[t0 + 3])  # 4th generated token
    for kwargs in (
        {"eos_id": eos},
        {"stop": [[int(toks[t0 + 2]), int(toks[t0 + 3])]]},
    ):
        stop = kwargs.pop("stop", None)
        srv_args = dict(
            num_blocks=16, block_size=8, max_batch=1, **kwargs
        )
        want_srv = PagedDecodeServer(dec, params, **srv_args)
        want_srv.submit(req[0], req[1], stop=stop)
        want = list(want_srv.run().values())[0]
        got_srv = PagedDecodeServer(
            dec, params, spec_draft=dec, spec_params=params, spec_k=4,
            **srv_args,
        )
        got_srv.submit(req[0], req[1], stop=stop)
        got = list(got_srv.run().values())[0]
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert np.asarray(got).shape[1] < t0 + 9  # actually truncated


def test_spec_constructor_and_submit_validation(model):
    dec, params = model
    base = dict(num_blocks=16, block_size=8, max_batch=2)
    with pytest.raises(ValueError, match="spec_k must be >= 0"):
        PagedDecodeServer(dec, params, spec_k=-1, **base)
    with pytest.raises(ValueError, match="spec_k >= 1"):
        PagedDecodeServer(dec, params, spec_draft=dec, **base)
    with pytest.raises(ValueError, match="spec_draft and spec_params"):
        PagedDecodeServer(dec, params, spec_k=2, **base)
    with pytest.raises(ValueError, match="prefix_ids"):
        PagedDecodeServer(
            dec, params, spec_draft=dec, spec_params=params, spec_k=2,
            prefix_ids=jnp.zeros((1, 8), jnp.int32), **base,
        )
    small = tiny_gpt(32)
    with pytest.raises(ValueError, match="max_len"):
        PagedDecodeServer(
            dec, params, spec_draft=small,
            spec_params=small.init(jax.random.key(1)), spec_k=2, **base,
        )
    with pytest.raises(ValueError, match="prefill_chunk"):
        PagedDecodeServer(dec, params, prefill_chunk=0, **base)
    srv = PagedDecodeServer(
        dec, params, spec_draft=dec, spec_params=params, spec_k=4,
        **base,
    )
    # Verify headroom: prompt + steps + spec_k must fit max_len —
    # on BOTH admission paths (a disagg decode worker speculates over
    # ingested KV, so submit_prefilled takes the same check).
    with pytest.raises(ValueError, match="spec_k"):
        srv.submit(jnp.zeros((1, 8), jnp.int32), 56)
    with pytest.raises(ValueError, match="spec_k"):
        srv.submit_prefilled(jnp.ones((1, 8), jnp.int32), 56)
    # Lifted composition limits: spec x decode_window (fused rounds)
    # and spec on prefilled admissions both construct/enqueue now.
    PagedDecodeServer(
        dec, params, spec_draft=dec, spec_params=params, spec_k=2,
        decode_window=4, **base,
    )
    assert srv.submit_prefilled(jnp.ones((1, 8), jnp.int32), 4) >= 0


@pytest.mark.parametrize(
    "attention", ["gathered", "blockwise", "pallas"]
)
def test_chunked_prefill_parity(model, baseline, attention):
    """prefill_chunk changes where prefill K/V is computed (straight
    into pool blocks, chunk by chunk), not a single output token —
    including radix-hit admissions that resume mid-prompt."""
    dec, params = model
    reqs = _mixed_requests(dec.cfg.vocab_size)
    for pc in (False, True):
        outs, stats = serve_paged(
            dec, params, reqs, num_blocks=24, block_size=8,
            max_batch=2, sampling=_mixed_sampling(), prefix_cache=pc,
            attention=attention, prefill_chunk=3,
        )
        for want, got in zip(baseline[pc], outs):
            np.testing.assert_array_equal(
                np.asarray(got), np.asarray(want),
                err_msg=f"attention={attention} prefix_cache={pc}",
            )
        assert stats["prefill_chunk"] == 3


def test_chunked_prefill_rows_scale_with_blocks_not_pool(model):
    """The prefill acceptance criterion on the obs counters: with
    block-native attention, rows read during a chunked prefill derive
    from the prompt's position span — growing the pool must not change
    them, and they must undercut the gathered baseline. steps=1
    requests finish at admission, so the deltas are pure prefill."""
    dec, params = model
    reqs = [
        (jnp.asarray([[3, 9, 27, 4, 1, 8, 2, 6, 5, 7]], jnp.int32), 1),
        (jnp.asarray([[5, 1, 2, 9]], jnp.int32), 1),
    ]

    def rows(attention, num_blocks):
        with obs.counter_deltas() as d:
            _, stats = serve_paged(
                dec, params, reqs, num_blocks=num_blocks, block_size=4,
                max_batch=2, attention=attention, prefill_chunk=4,
            )
        assert stats["ticks"] == 0  # admission-only: pure prefill
        return (
            d.get('defer_kv_rows_read_total{server="paged"}', 0),
            d.get(
                'defer_kv_rows_gathered_baseline_total{server="paged"}',
                0,
            ),
        )

    for attention in ("blockwise", "pallas"):
        read_small, base_small = rows(attention, 18)
        assert 0 < read_small < base_small
        read_big, base_big = rows(attention, 40)
        assert read_big == read_small  # pool size is invisible
        assert base_big == base_small


@pytest.mark.slow
def test_paged_prefill_kernel_matches_blockwise_reference():
    """Interpret-mode paged_flash_prefill vs the pure-XLA multi-token
    fold on random pools and ragged start positions — same masking,
    same block-table indirection, bitwise-comparable fp32 outputs
    within kernel tolerance."""
    from defer_tpu.ops.pallas_attention import paged_flash_prefill
    from defer_tpu.runtime.paged import _blockwise_attend_mt

    rng = np.random.default_rng(3)
    B, Hq, Hkv, T, Dh, bs, MB, NB = 2, 4, 2, 5, 16, 8, 6, 11
    q = jnp.asarray(
        rng.standard_normal((B, Hq, T, Dh)), jnp.float32
    )
    pk = jnp.asarray(
        rng.standard_normal((NB, Hkv, bs, Dh)), jnp.float32
    )
    pv = jnp.asarray(
        rng.standard_normal((NB, Hkv, bs, Dh)), jnp.float32
    )
    tables = jnp.asarray(
        rng.integers(1, NB, size=(B, MB)), jnp.int32
    )
    for start in ([0, 9], [3, 17], [26, 1]):
        pos = jnp.asarray(start, jnp.int32)
        got = paged_flash_prefill(
            q, pk, pv, tables, pos, interpret=True
        )  # [B, Hq, T, Dh]
        want = _blockwise_attend_mt(
            q, pk, pv, tables, pos, bs, MB, None
        )  # [B, T, Hq*Dh]
        got_flat = got.transpose(0, 2, 1, 3).reshape(B, T, Hq * Dh)
        np.testing.assert_allclose(
            np.asarray(got_flat), np.asarray(want),
            rtol=2e-5, atol=2e-5, err_msg=f"start={start}",
        )
