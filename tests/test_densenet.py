"""DenseNet: dense-connectivity stress case for the partitioner (only
block concat outputs and transition layers are valid cuts — never a
dense layer's internal branch) + real tf.keras numerical parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.config import DeferConfig
from defer_tpu.graph.partition import (
    PartitionError,
    partition,
    validate_cut_points,
)
from defer_tpu.models import get_model
from defer_tpu.parallel.pipeline import Pipeline

pytestmark = pytest.mark.slow

F32 = DeferConfig(compute_dtype=jnp.float32)


def test_densenet121_builds_with_expected_head():
    model = get_model("densenet121")
    params = model.graph.init(jax.random.key(0), (1, 64, 64, 3))
    spec = model.graph.output_spec(params, (1, 64, 64, 3))
    assert spec.shape == (1, 1000)
    # DenseNet-121 final feature width: 1024.
    assert params["predictions"]["kernel"].shape == (1024, 1000)
    # Every block concat + 3 transitions are valid cuts: 58+3.
    assert len(model.cut_candidates) == 6 + 12 + 24 + 16 + 3
    validate_cut_points(model.graph, model.cut_candidates)


def test_densenet169_builds_with_expected_head():
    model = get_model("densenet169")
    params = model.graph.init(jax.random.key(0), (1, 64, 64, 3))
    spec = model.graph.output_spec(params, (1, 64, 64, 3))
    assert spec.shape == (1, 1000)
    # DenseNet-169 final feature width: 1664.
    assert params["predictions"]["kernel"].shape == (1664, 1000)
    assert len(model.cut_candidates) == 6 + 12 + 32 + 32 + 3
    validate_cut_points(model.graph, model.cut_candidates)


def test_densenet_intra_layer_cut_rejected():
    """The BN-ReLU-conv branch inside a dense layer runs parallel to
    the concat skip, so a cut through it must be refused (the reference
    would silently miscompile it, reference src/dag_util.py:11-27) —
    while the concat output itself is a valid cut."""
    model = get_model("densenet121")
    with pytest.raises(PartitionError, match="crosses"):
        partition(model.graph, ["conv3_block2_1_relu"])
    partition(model.graph, ["conv3_block2_concat"])  # valid


def test_densenet_pipeline_composes(devices):
    model = get_model("densenet121")
    params = model.graph.init(jax.random.key(0), (1, 64, 64, 3))
    x = jax.random.normal(jax.random.key(1), (1, 64, 64, 3))
    want = jax.jit(model.graph.apply)(params, x)
    stages = partition(model.graph, model.default_cuts(4))
    pipe = Pipeline(stages, params, devices[:4], config=F32)
    got = pipe.warmup(x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5
    )


def test_densenet121_keras_parity():
    """Numerical parity with the real tf.keras DenseNet121 (random
    weights, no network) through the transplant path — node names match
    real Keras layer names identically, so no name_map is needed."""
    tf = pytest.importorskip("tensorflow")

    from defer_tpu.models.transplant import KerasWeights, transplant

    keras_model = tf.keras.applications.DenseNet121(
        weights=None, input_shape=(224, 224, 3)
    )
    model = get_model("densenet121")
    params = model.init(jax.random.key(0))
    weights = {
        l.name: l.get_weights() for l in keras_model.layers if l.get_weights()
    }
    params2 = transplant(
        model.graph, params, KerasWeights(weights), strict=True
    )

    x = np.random.default_rng(0).standard_normal((1, 224, 224, 3)).astype(
        np.float32
    )
    want = keras_model(x, training=False).numpy()
    got = np.asarray(jax.jit(model.graph.apply)(params2, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-5)
