"""Llama family: RMSNorm + RoPE + GQA + SwiGLU on the shared stack,
served by the same KV-cache decoder as GPT, cross-validated against
HuggingFace transformers' LlamaForCausalLM (the LLM analogue of the
Keras CNN parity suite, reference src/node.py:38-45)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.models.gpt import GptDecoder
from defer_tpu.models.llama import (
    from_hf_state_dict,
    llama_config,
    spmd_llama,
    tiny_llama,
)


def test_gqa_cache_is_kv_heads_sized():
    dec = tiny_llama()
    cache = dec.init_cache(batch=2)
    cfg = dec.cfg
    dh = cfg.dim // cfg.num_heads
    # The architecture's point: the cache holds KV heads, not Q heads.
    assert cache["k"].shape == (
        cfg.num_layers, 2, cfg.num_kv_heads, cfg.max_len, dh,
    )
    assert cfg.num_kv_heads < cfg.num_heads


def test_incremental_decode_matches_full_forward():
    """Token-by-token decoding with the GQA cache must equal the full
    causal forward — RoPE by absolute position, cache masking, and the
    grouped attention all have to line up for this to hold."""
    dec = tiny_llama()
    params = dec.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 9), 0, dec.cfg.vocab_size)
    full = dec.reference_logits(params, ids)

    step = dec.make_step(donate=False)
    cache = dec.init_cache(2)
    logits, cache = step(params, cache, ids[:, :4])  # prefill
    outs = [logits]
    for tpos in range(4, 9):
        logits, cache = step(params, cache, ids[:, tpos : tpos + 1])
        outs.append(logits)
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate(outs, axis=1)),
        np.asarray(full),
        rtol=2e-4,
        atol=2e-5,
    )


def test_generate_shapes_and_determinism():
    dec = tiny_llama()
    params = dec.init(jax.random.key(0))
    prompt = jnp.zeros((2, 3), jnp.int32)
    a = dec.generate(params, prompt, 5)
    b = dec.generate(params, prompt, 5)
    assert a.shape == (2, 8)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_tp_decode_matches_single_device(devices):
    """tp=2 sharded llama decode (head-group-sharded GQA cache, vocab-
    sharded tied head) produces the single-device tokens."""
    from defer_tpu.parallel.mesh import make_mesh

    cfg = llama_config(
        num_layers=2,
        dim=64,
        num_heads=4,
        num_kv_heads=2,
        ffn_dim=128,
        vocab_size=97,  # odd on purpose: exercises the pad-to-tp path
        max_len=16,
    )
    single = GptDecoder(cfg, compute_dtype=jnp.float32)
    params = single.init(jax.random.key(0))
    prompt = jnp.zeros((1, 3), jnp.int32)
    want = single.generate(params, prompt, 4)

    mesh = make_mesh({"model": 2}, devices[:2])
    dec = spmd_llama(mesh, cfg, compute_dtype=jnp.float32)
    got = dec.generate(dec.shard_params(params), prompt, 4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_kv_heads_must_divide_tp(devices):
    from defer_tpu.parallel.mesh import make_mesh

    cfg = llama_config(
        num_layers=2,
        dim=64,
        num_heads=4,
        num_kv_heads=1,  # 1 kv head cannot shard over tp=2
        ffn_dim=128,
        vocab_size=64,
        max_len=16,
    )
    mesh = make_mesh({"model": 2}, devices[:2])
    with pytest.raises(ValueError, match="kv"):
        spmd_llama(mesh, cfg, compute_dtype=jnp.float32)


@pytest.mark.slow
def test_hf_llama_parity():
    """Transplant a real transformers LlamaForCausalLM state_dict and
    require logits parity with HF's own forward — proving RMSNorm,
    RoPE (rotate-half convention), GQA grouping and SwiGLU all match
    the ecosystem's implementation, not just our own reference path."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.LlamaConfig(
        vocab_size=96,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=32,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        attention_bias=False,
        tie_word_embeddings=True,
    )
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()

    cfg = llama_config(
        num_layers=2,
        dim=64,
        num_heads=4,
        num_kv_heads=2,
        ffn_dim=128,
        vocab_size=96,
        max_len=32,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.float32)
    params = from_hf_state_dict(cfg, hf.state_dict())

    ids_np = np.random.RandomState(0).randint(0, 96, size=(2, 11))
    with torch.no_grad():
        want = hf(torch.from_numpy(ids_np)).logits.numpy()
    got = np.asarray(dec.reference_logits(params, jnp.asarray(ids_np)))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)

    # Untied head (tie_word_embeddings=False — the real Llama-2/3
    # release shape): the distinct lm_head must be transplanted and
    # used, not silently replaced by the tied embedding.
    hf_cfg_untied = transformers.LlamaConfig(
        **{**hf_cfg.to_dict(), "tie_word_embeddings": False}
    )
    torch.manual_seed(1)
    hf2 = transformers.LlamaForCausalLM(hf_cfg_untied).eval()
    params2 = from_hf_state_dict(cfg, hf2.state_dict())
    assert "lm_head" in params2
    with torch.no_grad():
        want2 = hf2(torch.from_numpy(ids_np)).logits.numpy()
    got2 = np.asarray(dec.reference_logits(params2, jnp.asarray(ids_np)))
    np.testing.assert_allclose(got2, want2, rtol=2e-3, atol=2e-4)


# -- sliding-window attention (Mistral family) -------------------------


def _tiny_mistral(window):
    from defer_tpu.models.llama import mistral_config

    return GptDecoder(
        mistral_config(
            num_layers=1,
            dim=64,
            num_heads=4,
            num_kv_heads=2,
            ffn_dim=128,
            vocab_size=96,
            max_len=32,
            window=window,
        ),
        compute_dtype=jnp.float32,
    )


def test_sliding_window_suffix_equivalence():
    """RoPE scores depend only on RELATIVE positions, so a 1-layer
    windowed decoder's last-token logits must equal running just the
    last `window` tokens — the independent oracle for the mask."""
    import dataclasses

    W = 5
    dec = _tiny_mistral(W)
    params = dec.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 13), 0, 96)
    full = dec.reference_logits(params, ids)[:, -1, :]
    suffix = dec.reference_logits(params, ids[:, -W:])[:, -1, :]
    np.testing.assert_allclose(
        np.asarray(full), np.asarray(suffix), rtol=2e-4, atol=2e-5
    )
    # ... and the window genuinely matters at this length: the same
    # params under FULL causal attention give different logits.
    far = GptDecoder(
        dataclasses.replace(dec.cfg, window=None), compute_dtype=jnp.float32
    )
    full_causal = far.reference_logits(params, ids)[:, -1, :]
    assert not np.allclose(np.asarray(full), np.asarray(full_causal))


def test_sliding_window_incremental_decode_matches():
    """Cache-masked decode and the full windowed forward agree."""
    dec = _tiny_mistral(4)
    params = dec.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 10), 0, 96)
    full = dec.reference_logits(params, ids)
    step = dec.make_step(donate=False)
    cache = dec.init_cache(1)
    logits, cache = step(params, cache, ids[:, :6])
    outs = [logits]
    for t in range(6, 10):
        logits, cache = step(params, cache, ids[:, t : t + 1])
        outs.append(logits)
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate(outs, axis=1)),
        np.asarray(full),
        rtol=2e-4,
        atol=2e-5,
    )


def test_window_config_validated():
    from defer_tpu.parallel.transformer_stack import TransformerConfig

    with pytest.raises(ValueError, match="window"):
        TransformerConfig(
            num_layers=2, dim=32, num_heads=4, ffn_dim=64,
            vocab_size=64, max_len=16, window=4,  # causal=False
        )


@pytest.mark.slow
def test_hf_mistral_parity():
    """Logits parity with transformers' MistralForCausalLM at a
    sequence longer than the sliding window — proving the window mask
    matches the ecosystem, not just our own suffix oracle."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    W = 4
    hf_cfg = transformers.MistralConfig(
        vocab_size=96,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=32,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        sliding_window=W,
        tie_word_embeddings=True,
    )
    torch.manual_seed(0)
    hf = transformers.MistralForCausalLM(hf_cfg).eval()

    from defer_tpu.models.llama import mistral_config

    cfg = mistral_config(
        num_layers=2,
        dim=64,
        num_heads=4,
        num_kv_heads=2,
        ffn_dim=128,
        vocab_size=96,
        max_len=32,
        window=W,
    )
    dec = GptDecoder(cfg, compute_dtype=jnp.float32)
    params = from_hf_state_dict(cfg, hf.state_dict())

    ids_np = np.random.RandomState(0).randint(0, 96, size=(2, 12))
    with torch.no_grad():
        want = hf(torch.from_numpy(ids_np)).logits.numpy()
    got = np.asarray(dec.reference_logits(params, jnp.asarray(ids_np)))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)


def test_rolling_cache_matches_windowed_decoder():
    """A rolling cache (window slots, scatter writes, explicit slot
    positions) must reproduce the plain windowed decoder exactly —
    incremental decode, generation, and a prompt longer than the
    window (auto-chunked prefill)."""
    W = 5
    flat = _tiny_mistral(W)
    roll = GptDecoder(
        flat.cfg, compute_dtype=jnp.float32, rolling_cache=True
    )
    params = flat.init(jax.random.key(0))
    cache = roll.init_cache(2)
    assert cache["k"].shape[3] == W  # slots = window, not max_len

    ids = jax.random.randint(jax.random.key(1), (2, 13), 0, 96)
    want = flat.reference_logits(params, ids)
    step = roll.make_step(donate=False)
    c = roll.init_cache(2)
    logits, c = step(params, c, ids[:, :4])
    outs = [logits]
    for t in range(4, 13):
        logits, c = step(params, c, ids[:, t : t + 1])
        outs.append(logits)
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate(outs, axis=1)),
        np.asarray(want),
        rtol=2e-4,
        atol=2e-5,
    )

    prompt = ids[:, :9]  # longer than W -> chunked rolling prefill
    np.testing.assert_array_equal(
        np.asarray(roll.generate(params, prompt, 6)),
        np.asarray(flat.generate(params, prompt, 6)),
    )


def test_rolling_cache_generates_past_max_len():
    """The point of the rolling cache: generation length is no longer
    bounded by max_len (positions are unbounded, slots recycle)."""
    W = 5
    flat = _tiny_mistral(W)  # max_len 32
    roll = GptDecoder(
        flat.cfg, compute_dtype=jnp.float32, rolling_cache=True
    )
    params = flat.init(jax.random.key(0))
    prompt = jnp.zeros((1, 3), jnp.int32)
    out = roll.generate(params, prompt, 60)  # 63 > max_len 32
    assert out.shape == (1, 63)
    assert (np.asarray(out) >= 0).all()
    # The first in-bounds stretch agrees with the flat decoder.
    want = flat.generate(params, prompt, 20)
    np.testing.assert_array_equal(
        np.asarray(out[:, :23]), np.asarray(want)
    )


def test_rolling_cache_requires_window_and_rope():
    from defer_tpu.models.gpt import tiny_gpt

    with pytest.raises(ValueError, match="rolling_cache"):
        GptDecoder(
            tiny_gpt().cfg, compute_dtype=jnp.float32, rolling_cache=True
        )


def test_rolling_reference_logits_streams_long_sequences():
    """The oracle itself works past the window for rolling decoders,
    matching the flat windowed oracle position by position."""
    W = 5
    flat = _tiny_mistral(W)
    roll = GptDecoder(
        flat.cfg, compute_dtype=jnp.float32, rolling_cache=True
    )
    params = flat.init(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 17), 0, 96)
    np.testing.assert_allclose(
        np.asarray(roll.reference_logits(params, ids)),
        np.asarray(flat.reference_logits(params, ids)),
        rtol=2e-4,
        atol=2e-5,
    )


def test_speculative_rejects_rolling_cache():
    from defer_tpu.models.speculative import speculative_generate

    W = 5
    roll = GptDecoder(
        _tiny_mistral(W).cfg, compute_dtype=jnp.float32, rolling_cache=True
    )
    params = roll.init(jax.random.key(0))
    with pytest.raises(ValueError, match="rolling cache"):
        speculative_generate(
            roll, params, roll, params, jnp.zeros((1, 3), jnp.int32), 4
        )
