"""Partitioner: validation + the compose(stages) == full_model property
(the test strategy SURVEY.md §4 prescribes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.graph.partition import (
    PartitionError,
    partition,
    stage_params,
    validate_cut_points,
)
from defer_tpu.models import get_model


def residual_chain():
    """Two residual blocks; adds are valid cuts, branch interiors are not."""
    b = GraphBuilder("chain")
    x = b.input()
    h = b.add("dense", x, name="stem", features=8)
    for i in (1, 2):
        br = b.add("dense", h, name=f"blk{i}_dense", features=8)
        br = b.add("relu", br, name=f"blk{i}_relu")
        h = b.add("add", h, br, name=f"add_{i}")
    out = b.add("dense", h, name="head", features=4)
    return b.build(out)


def test_valid_cuts_pass():
    g = residual_chain()
    validate_cut_points(g, ["add_1"])
    validate_cut_points(g, ["add_1", "add_2"])
    validate_cut_points(g, ["stem"])


def test_cut_inside_residual_branch_rejected():
    """The reference silently miscompiles this case (SURVEY.md §3.4)."""
    g = residual_chain()
    with pytest.raises(PartitionError, match="crosses the boundary"):
        validate_cut_points(g, ["blk1_relu"])


def test_unknown_and_duplicate_and_boundary_cuts_rejected():
    g = residual_chain()
    with pytest.raises(PartitionError, match="not a node"):
        validate_cut_points(g, ["nope"])
    # A repeated cut adds no nodes to the chain — rejected as an empty
    # stage rather than as a literal duplicate.
    with pytest.raises(PartitionError, match="adds no nodes"):
        validate_cut_points(g, ["add_1", "add_1"])
    with pytest.raises(PartitionError, match="input/output"):
        validate_cut_points(g, ["input"])
    with pytest.raises(PartitionError, match="chain order"):
        validate_cut_points(g, ["add_2", "add_1"])


def test_partition_structure():
    g = residual_chain()
    stages = partition(g, ["add_1"])
    assert len(stages) == 2
    s0, s1 = stages
    assert s0.output_name == "add_1"
    assert s1.input_name == "add_1"
    assert s1.output_name == "head"
    names0 = {n.name for n in s0.nodes}
    names1 = {n.name for n in s1.nodes}
    # Each compute op lives in exactly one stage; only the cut node name
    # appears on both sides (as output / as input placeholder).
    assert names0 & names1 == {"add_1"}
    all_names = {n.name for n in g.nodes}
    assert names0 | names1 == all_names


def compose(stages, params, x):
    h = x
    for s in stages:
        h = s.apply(stage_params(params, s), h)
    return h


def test_compose_equals_full_small():
    g = residual_chain()
    params = g.init(jax.random.key(0), (4, 8))
    x = jax.random.normal(jax.random.key(1), (4, 8))
    full = g.apply(params, x)
    for cuts in (["add_1"], ["add_1", "add_2"], ["stem", "add_2"]):
        stages = partition(g, cuts)
        got = compose(stages, params, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(full), rtol=1e-5
        )


def test_compose_equals_full_resnet50():
    """End-to-end on the real headline model at a reduced resolution,
    cut at the reference's documented 8-way list (reference
    src/test.py:27)."""
    model = get_model("resnet50")
    params = model.graph.init(jax.random.key(0), (1, 64, 64, 3))
    x = jax.random.normal(jax.random.key(1), (1, 64, 64, 3))
    full = jax.jit(model.graph.apply)(params, x)
    cuts = ["add_2", "add_4", "add_6", "add_8", "add_10", "add_12", "add_14"]
    stages = partition(model.graph, cuts)
    assert len(stages) == 8
    got = compose(stages, params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full), rtol=2e-4, atol=2e-6
    )


def test_stage_params_partition_params_exactly():
    g = residual_chain()
    params = g.init(jax.random.key(0), (4, 8))
    stages = partition(g, ["add_1"])
    p0 = stage_params(params, stages[0])
    p1 = stage_params(params, stages[1])
    parameterized = {k for k, v in params.items() if v}
    assert set(p0) | set(p1) == parameterized
    assert not set(p0) & set(p1)


# -- multi-tensor boundaries ------------------------------------------------


def skip_chain():
    """NASNet-shaped skeleton: block k consumes outputs k-1 AND k-2, so
    no single tensor separates the chain but (h_k, h_{k-1}) does."""
    b = GraphBuilder("skip")
    x = b.input()
    h_prev = b.add("dense", x, name="h0", features=8)
    h = b.add("dense", h_prev, name="h1", features=8)
    for i in range(2, 5):
        nxt = b.add("add", h, h_prev, name=f"mix{i}")
        nxt = b.add("dense", nxt, name=f"h{i}", features=8)
        h_prev, h = h, nxt
    out = b.add("dense", h, name="head", features=4)
    return b.build(out)


def test_single_cut_on_skip_chain_rejected():
    g = skip_chain()
    with pytest.raises(PartitionError, match="crosses the boundary"):
        validate_cut_points(g, ["h2"])


def test_bundle_cut_on_skip_chain_validates_and_composes():
    g = skip_chain()
    cuts = [("h2", "h1"), ("h4", "h3")]
    validate_cut_points(g, cuts)
    stages = partition(g, cuts)
    assert len(stages) == 3
    params = g.init(jax.random.key(0), (2, 8))
    x = jax.random.normal(jax.random.key(1), (2, 8))
    full = g.apply(params, x)
    y = x
    for st in stages:
        y = st.apply(stage_params(params, st), y)
    np.testing.assert_allclose(np.asarray(y), np.asarray(full), rtol=1e-6)


def test_bundle_passthrough_across_boundaries():
    """A tensor consumed two boundaries later rides through the middle
    stage as an input that is also an output."""
    b = GraphBuilder("pass")
    x = b.input()
    a = b.add("dense", x, name="a", features=8)
    m = b.add("dense", a, name="mid", features=8)
    m2 = b.add("dense", m, name="mid2", features=8)
    out = b.add("add", m2, a, name="join")
    g = b.build(b.add("dense", out, name="head", features=4))
    cuts = [("mid", "a"), ("mid2", "a")]
    validate_cut_points(g, cuts)
    stages = partition(g, cuts)
    params = g.init(jax.random.key(2), (3, 8))
    x_in = jax.random.normal(jax.random.key(3), (3, 8))
    full = g.apply(params, x_in)
    y = x_in
    for st in stages:
        y = st.apply(stage_params(params, st), y)
    np.testing.assert_allclose(np.asarray(y), np.asarray(full), rtol=1e-6)


def test_bundle_missing_member_rejected_with_hint():
    g = skip_chain()
    with pytest.raises(PartitionError, match="Add .* to the bundle"):
        validate_cut_points(g, [("h2",)])


def test_empty_and_degenerate_bundles_rejected():
    g = skip_chain()
    with pytest.raises(PartitionError, match="empty cut bundle"):
        validate_cut_points(g, [()])
    with pytest.raises(PartitionError, match="duplicate node"):
        validate_cut_points(g, [("h2", "h2")])
    with pytest.raises(PartitionError, match="adds no nodes"):
        validate_cut_points(g, [("h2", "h1"), ("h2", "h1")])


def test_bundle_pipeline_on_devices(devices):
    """Bundle boundaries flow as tuples through the device-pinned
    pipeline (device_put/donation/sync on pytrees)."""
    from defer_tpu.config import DeferConfig
    from defer_tpu.parallel.pipeline import Pipeline

    g = skip_chain()
    cuts = [("h2", "h1"), ("h4", "h3")]
    stages = partition(g, cuts)
    params = g.init(jax.random.key(4), (2, 8))
    pipe = Pipeline(
        stages, params, devices[:3], DeferConfig(compute_dtype=jnp.float32)
    )
    x = jax.random.normal(jax.random.key(5), (2, 8))
    out = pipe.warmup(x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(g.apply(params, x)), rtol=1e-6
    )
    outs = list(pipe.stream([x, x, x]))
    assert len(outs) == 3


def test_bundle_stage_params_stay_disjoint():
    """Cut-node weights belong only to the producing stage, even though
    the consuming stage names the cut node as its input placeholder."""
    g = skip_chain()
    stages = partition(g, [("h2", "h1")])
    params = g.init(jax.random.key(6), (2, 8))
    slices = [stage_params(params, st) for st in stages]
    for a in range(len(slices)):
        for b in range(a + 1, len(slices)):
            overlap = set(slices[a]) & set(slices[b])
            assert not overlap, overlap
    # Every param-bearing node lands in exactly one slice.
    owned = set().union(*(set(s) for s in slices))
    assert owned == {k for k, v in params.items() if v}


def test_bundle_member_not_carried_forward_rejected():
    """A later bundle may not name a tensor the previous boundary
    didn't relay (it was computed upstream and is unavailable)."""
    b = GraphBuilder("lin")
    x = b.input()
    a = b.add("dense", x, name="a", features=8)
    bb = b.add("dense", a, name="b", features=8)
    c = b.add("dense", bb, name="c", features=8)
    g = b.build(b.add("dense", c, name="head", features=4))
    with pytest.raises(PartitionError, match="not carried across"):
        validate_cut_points(g, [("b",), ("c", "a")])


def test_fuzz_random_dags_partition_composes():
    """Randomized DAGs: every discovered articulation point (and some
    random bundle boundaries) must validate and compose exactly."""
    from defer_tpu.graph.partition import articulation_points

    rng = np.random.default_rng(7)
    for trial in range(12):
        b = GraphBuilder(f"fuzz{trial}")
        nodes = [b.input()]
        for i in range(rng.integers(4, 14)):
            k = int(rng.integers(1, min(3, len(nodes)) + 1))
            srcs = list(
                np.array(nodes)[rng.choice(len(nodes), k, replace=False)]
            )
            if k == 1:
                n = b.add("dense", srcs[0], name=f"n{i}", features=6)
            else:
                # align feature dims: adds need equal shapes -> project
                projected = [
                    b.add("dense", s, name=f"n{i}p{j}", features=6)
                    for j, s in enumerate(srcs)
                ]
                n = b.add("add", *projected, name=f"n{i}")
            nodes.append(n)
        g = b.build(b.add("dense", nodes[-1], name="out", features=2))

        params = g.init(jax.random.key(trial), (2, 6))
        x = jax.random.normal(jax.random.key(100 + trial), (2, 6))
        full = g.apply(params, x)

        pts = articulation_points(g)
        for cut in pts:
            stages = partition(g, [cut])
            got = compose(stages, params, x)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(full), rtol=1e-4,
                err_msg=f"trial {trial} cut {cut}",
            )
        if len(pts) >= 2:
            stages = partition(g, [pts[0], pts[-1]])
            got = compose(stages, params, x)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(full), rtol=1e-4
            )


def test_chain_boundaries_discovers_bundles():
    """A NASNet-shaped skip chain has no single-tensor cut inside the
    cell run, but chain_boundaries finds the (cell_i, cell_i-1)
    frontiers — and every discovered boundary sequence partitions to
    the same outputs as the full graph."""
    import itertools

    from defer_tpu.graph.partition import chain_boundaries

    b = GraphBuilder("skips")
    v = b.input()
    h_prev = b.add("dense", v, name="h0", features=8)
    h = b.add("dense", h_prev, name="h1", features=8)
    for i in range(2, 6):
        nxt = b.add("add", h, h_prev, name=f"mix{i}")
        nxt = b.add("dense", nxt, name=f"h{i}", features=8)
        h_prev, h = h, nxt
    g = b.build(b.add("dense", h, name="head", features=3))

    cands = chain_boundaries(g, max_width=2)
    # The pairwise frontiers exist...
    assert ("h1", "h2") in cands or ("h2", "h1") in cands
    assert ("h3", "h4") in cands or ("h4", "h3") in cands
    # ...and the trailing single-tensor cut (h5 feeds only the head
    # once mix-chains end) appears as a plain name.
    assert "h5" in cands

    params = g.init(jax.random.key(0), (2, 8))
    x = jax.random.normal(jax.random.key(1), (2, 8))
    want = np.asarray(g.apply(params, x))
    # Every increasing subsequence of discovered boundaries is a valid
    # chain (spot-check all pairs + the full list).
    picks = [list(p) for p in itertools.combinations(cands, 2)]
    picks.append(list(cands))
    for cuts in picks:
        stages = partition(g, cuts)
        h = x
        for s in stages:
            h = s.apply(stage_params(params, s), h)
        np.testing.assert_allclose(np.asarray(h), want, rtol=1e-5)


def test_chain_boundaries_agrees_with_articulation_points():
    """Width-1 discoveries are exactly the articulation points, on a
    branchy model (ResNet50)."""
    from defer_tpu.graph.partition import (
        articulation_points,
        chain_boundaries,
    )
    from defer_tpu.models import get_model

    model = get_model("resnet50")
    singles = [
        c for c in chain_boundaries(model.graph, max_width=1)
        if isinstance(c, str)
    ]
    assert singles == articulation_points(model.graph)


def test_balanced_cuts_evens_stage_flops():
    """FLOPs-balanced picks beat index-even picks on VGG16 (whose conv
    blocks are very uneven) and stay valid boundaries."""
    from defer_tpu.models import get_model
    from defer_tpu.utils.flops import balanced_cuts, node_flops

    m = get_model("vgg16")
    p = m.init(jax.random.key(0))
    shape = (1, *m.input_shape)
    specs = m.graph.infer_shapes(p, shape)

    def imbalance(cuts):
        per_stage = [
            sum(
                node_flops(n.op, p.get(n.name, {}), specs[n.name].shape)
                for n in s.nodes
                if n.op != "input"
            )
            for s in partition(m.graph, cuts)
        ]
        return max(per_stage) / min(per_stage)

    naive = imbalance(m.default_cuts(4))
    bal_cuts = balanced_cuts(m.graph, p, shape, 4, m.cut_candidates)
    validate_cut_points(m.graph, bal_cuts)
    assert imbalance(bal_cuts) < naive
