"""Partitioner: validation + the compose(stages) == full_model property
(the test strategy SURVEY.md §4 prescribes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.graph.partition import (
    PartitionError,
    partition,
    stage_params,
    validate_cut_points,
)
from defer_tpu.models import get_model


def residual_chain():
    """Two residual blocks; adds are valid cuts, branch interiors are not."""
    b = GraphBuilder("chain")
    x = b.input()
    h = b.add("dense", x, name="stem", features=8)
    for i in (1, 2):
        br = b.add("dense", h, name=f"blk{i}_dense", features=8)
        br = b.add("relu", br, name=f"blk{i}_relu")
        h = b.add("add", h, br, name=f"add_{i}")
    out = b.add("dense", h, name="head", features=4)
    return b.build(out)


def test_valid_cuts_pass():
    g = residual_chain()
    validate_cut_points(g, ["add_1"])
    validate_cut_points(g, ["add_1", "add_2"])
    validate_cut_points(g, ["stem"])


def test_cut_inside_residual_branch_rejected():
    """The reference silently miscompiles this case (SURVEY.md §3.4)."""
    g = residual_chain()
    with pytest.raises(PartitionError, match="articulation"):
        validate_cut_points(g, ["blk1_relu"])


def test_unknown_and_duplicate_and_boundary_cuts_rejected():
    g = residual_chain()
    with pytest.raises(PartitionError, match="not a node"):
        validate_cut_points(g, ["nope"])
    with pytest.raises(PartitionError, match="duplicate"):
        validate_cut_points(g, ["add_1", "add_1"])
    with pytest.raises(PartitionError, match="input/output"):
        validate_cut_points(g, ["input"])
    with pytest.raises(PartitionError, match="chain order"):
        validate_cut_points(g, ["add_2", "add_1"])


def test_partition_structure():
    g = residual_chain()
    stages = partition(g, ["add_1"])
    assert len(stages) == 2
    s0, s1 = stages
    assert s0.output_name == "add_1"
    assert s1.input_name == "add_1"
    assert s1.output_name == "head"
    names0 = {n.name for n in s0.nodes}
    names1 = {n.name for n in s1.nodes}
    # Each compute op lives in exactly one stage; only the cut node name
    # appears on both sides (as output / as input placeholder).
    assert names0 & names1 == {"add_1"}
    all_names = {n.name for n in g.nodes}
    assert names0 | names1 == all_names


def compose(stages, params, x):
    h = x
    for s in stages:
        h = s.apply(stage_params(params, s), h)
    return h


def test_compose_equals_full_small():
    g = residual_chain()
    params = g.init(jax.random.key(0), (4, 8))
    x = jax.random.normal(jax.random.key(1), (4, 8))
    full = g.apply(params, x)
    for cuts in (["add_1"], ["add_1", "add_2"], ["stem", "add_2"]):
        stages = partition(g, cuts)
        got = compose(stages, params, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(full), rtol=1e-5
        )


def test_compose_equals_full_resnet50():
    """End-to-end on the real headline model at a reduced resolution,
    cut at the reference's documented 8-way list (reference
    src/test.py:27)."""
    model = get_model("resnet50")
    params = model.graph.init(jax.random.key(0), (1, 64, 64, 3))
    x = jax.random.normal(jax.random.key(1), (1, 64, 64, 3))
    full = jax.jit(model.graph.apply)(params, x)
    cuts = ["add_2", "add_4", "add_6", "add_8", "add_10", "add_12", "add_14"]
    stages = partition(model.graph, cuts)
    assert len(stages) == 8
    got = compose(stages, params, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full), rtol=2e-4, atol=2e-6
    )


def test_stage_params_partition_params_exactly():
    g = residual_chain()
    params = g.init(jax.random.key(0), (4, 8))
    stages = partition(g, ["add_1"])
    p0 = stage_params(params, stages[0])
    p1 = stage_params(params, stages[1])
    parameterized = {k for k, v in params.items() if v}
    assert set(p0) | set(p1) == parameterized
    assert not set(p0) & set(p1)
