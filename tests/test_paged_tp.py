"""Tensor-parallel paged serving: `PagedDecodeServer(mesh=...)` runs
the tick machinery over a model mesh axis, and nothing the user can
observe moves — greedy outputs are token-identical to `mesh=None`
across attention modes, windows, speculation, and chunked prefill
(runtime/paged.py module docstring has the sharding layout).

Counter contract (the perf claim in miniature, pinned here because a
parity test alone can't see it): per-shard `defer_kv_rows_read_total`
scales as 1/TP — each shard reads only its kv_heads/TP slice of the
pool — while `defer_host_dispatches_total` is unchanged, because the
host loop samples replicated post-psum logits and never dispatches
per shard. Runs on forced host devices (conftest.py sets
XLA_FLAGS=--xla_force_host_platform_device_count=8), so everything
here is CPU-testable and the same code path lights up on real chips.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu import obs
from defer_tpu.models.gpt import tiny_gpt
from defer_tpu.models.llama import tiny_llama
from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.runtime.paged import PagedDecodeServer, serve_paged


@pytest.fixture(scope="module")
def model():
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(0))
    return dec, params


@pytest.fixture(scope="module")
def draft():
    """Same architecture, different weights — rejections every round
    (the test_spec_paged.py divergent-draft idiom)."""
    dec = tiny_gpt(64)
    params = dec.init(jax.random.key(7))
    return dec, params


def _requests(vocab):
    """Shared prefix on the first two (radix hits under prefix_cache),
    one prompt long enough that prefill_chunk=8 actually splits it."""
    rng = np.random.default_rng(3)
    base = jnp.asarray(rng.integers(1, vocab, size=(1, 6)), jnp.int32)
    ext = jnp.asarray(rng.integers(1, vocab, size=(1, 4)), jnp.int32)
    return [
        (base, 7),
        (jnp.concatenate([base, ext], axis=1), 5),
        (jnp.asarray(rng.integers(1, vocab, size=(1, 11)), jnp.int32), 6),
    ]


@pytest.fixture(scope="module")
def solo(model):
    """Greedy references: every TP config below must reproduce the
    plain decoder's own tokens, not merely agree with mesh=None."""
    dec, params = model
    reqs = _requests(dec.cfg.vocab_size)
    return reqs, [dec.generate(params, p, s) for p, s in reqs]


def _mesh(tp):
    return make_mesh({"model": tp}, jax.devices()[:tp])


# Curated cut of the (attention x prefix_cache x window x spec x
# chunked) space — every sharded tick body appears at least once, at
# tp=2 and two tp=4 points, without compiling the full product.
MATRIX = [
    ("gathered", False, 1, 0, None, 2),
    ("blockwise", True, 1, 0, None, 2),
    ("pallas", False, 1, 0, None, 2),
    ("gathered", False, 8, 0, None, 2),
    ("blockwise", False, 1, 4, None, 2),
    ("gathered", True, 1, 0, 8, 2),
    ("gathered", False, 8, 0, None, 4),
    ("blockwise", False, 1, 0, None, 4),
]


@pytest.mark.parametrize(
    "attention,prefix_cache,window,spec_k,chunk,tp", MATRIX
)
def test_tp_token_identical(
    model, draft, solo, attention, prefix_cache, window, spec_k, chunk, tp
):
    dec, params = model
    reqs, want = solo
    spec = (
        dict(spec_draft=draft[0], spec_params=draft[1], spec_k=spec_k)
        if spec_k
        else {}
    )
    outs, stats = serve_paged(
        dec, params, reqs, num_blocks=16, block_size=4, max_batch=2,
        attention=attention, prefix_cache=prefix_cache,
        decode_window=window, prefill_chunk=chunk, mesh=_mesh(tp),
        **spec,
    )
    for i, (got, ref) in enumerate(zip(outs, want)):
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(ref),
            err_msg=f"request {i} attention={attention} tp={tp}",
        )
    assert stats["mesh_shape"] == f"model={tp}"
    assert stats["tp_psums"] > 0


def test_size1_mesh_matches_mesh_none(model, solo):
    """A 1-device mesh runs the shard_map path end to end; tokens must
    match mesh=None exactly (the degenerate-mesh contract)."""
    dec, params = model
    reqs, _ = solo
    outs0, st0 = serve_paged(
        dec, params, reqs, num_blocks=16, block_size=4, max_batch=2
    )
    outs1, st1 = serve_paged(
        dec, params, reqs, num_blocks=16, block_size=4, max_batch=2,
        mesh=_mesh(1),
    )
    for a, b in zip(outs0, outs1):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert st0["mesh_shape"] is None and st0["tp_psums"] == 0
    assert st1["mesh_shape"] == "model=1" and st1["tp_psums"] > 0


def test_kv_rows_scale_dispatches_do_not(model, solo):
    """The counter pin: per-shard KV reads halve at tp=2, host
    dispatches per token do not move, and the collective count matches
    the server's own host-side mirror."""
    dec, params = model
    reqs, _ = solo
    kw = dict(
        num_blocks=16, block_size=4, max_batch=2, attention="blockwise"
    )
    with obs.counter_deltas() as d0:
        serve_paged(dec, params, reqs, **kw)
    with obs.counter_deltas() as d2:
        _, st2 = serve_paged(dec, params, reqs, mesh=_mesh(2), **kw)
    rows0 = d0['defer_kv_rows_read_total{server="paged"}']
    rows2 = d2['defer_kv_rows_read_total{mesh="model=2",server="paged"}']
    assert rows0 > 0 and rows2 * 2 == rows0
    disp0 = d0['defer_host_dispatches_total{server="paged"}']
    disp2 = d2['defer_host_dispatches_total{mesh="model=2",server="paged"}']
    assert disp0 == disp2 > 0
    psums = d2['defer_tp_psum_total{mesh="model=2",server="paged"}']
    assert psums == st2["tp_psums"] > 0
    assert d0.get('defer_tp_psum_total{server="paged"}', 0) == 0


def test_kv_head_shard_errors():
    """Satellite contract: both indivisibility failures are caught at
    construction with the fix spelled out, before any compile."""
    dec = tiny_llama(32)  # num_kv_heads=2
    params = dec.init(jax.random.key(0))
    with pytest.raises(ValueError, match="num_kv_heads=2 is smaller"):
        PagedDecodeServer(
            dec, params, num_blocks=8, block_size=4, max_batch=2,
            mesh=_mesh(4),
        )
    dec4 = tiny_gpt(32)  # 4 heads, MHA: kv_heads=4
    params4 = dec4.init(jax.random.key(0))
    with pytest.raises(ValueError, match="does not divide"):
        PagedDecodeServer(
            dec4, params4, num_blocks=8, block_size=4, max_batch=2,
            mesh=_mesh(3),
        )


def test_fleet_replicas_get_meshes(model, solo):
    """`model_axis_size=` turns every fleet replica into an N-chip
    mesh via the same ctor path; outputs stay token-identical and the
    per-replica stats carry the mesh shape. Default placement (no
    model_axis_size) spreads replicas over distinct single devices."""
    from defer_tpu.fleet.api import serve_fleet

    dec, params = model
    reqs, want = solo
    kw = dict(n_replicas=2, num_blocks=16, block_size=4, max_batch=2)
    outs, st = serve_fleet(dec, params, reqs, model_axis_size=2, **kw)
    for got, ref in zip(outs, want):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert [r["mesh_shape"] for r in st["replicas"]] == ["model=2"] * 2
    outs1, st1 = serve_fleet(dec, params, reqs, **kw)
    for got, ref in zip(outs1, want):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert all(r["mesh_shape"] is None for r in st1["replicas"])


def test_disagg_ingest_scatters_into_shards(model):
    """Disagg wire blobs are full-head (format unchanged); a meshed
    decode server splits them on the head axis at ingest. Delivering a
    real prefill worker blob must finish token-identical to the
    unmeshed server fed the same blob."""
    from defer_tpu.disagg.prefill_worker import run_prefill

    dec, params = model
    prompt = jnp.asarray([[3, 9, 27, 5, 11]], jnp.int32)
    k, v, lg = run_prefill(
        dec, params, np.asarray(prompt), block_size=4
    )
    outs = []
    for mesh in (None, _mesh(2)):
        srv = PagedDecodeServer(
            dec, params, num_blocks=16, block_size=4, max_batch=2,
            mesh=mesh,
        )
        rid = srv.submit_prefilled(prompt, 6)
        srv.deliver_kv(rid, k, v, lg)
        outs.append(srv.run()[rid])
    np.testing.assert_array_equal(
        np.asarray(outs[0]), np.asarray(outs[1])
    )
    want = dec.generate(params, prompt, 6)
    np.testing.assert_array_equal(np.asarray(outs[0]), np.asarray(want))
